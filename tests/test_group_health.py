"""Serve-group failure detection (VERDICT r3 item 3): heartbeat
monitor, step watchdog, frontend drain-on-degraded, ServeGroupDegraded
condition driving whole-slice replacement, and the kill-a-follower e2e
on the 2-process CPU harness.

Reference invariant being extended to the serve layer: unhealthy
multi-host groups are repaired WHOLE, never partially
(raycluster_controller.go:1269-1289)."""

import os
import signal
import socket
import subprocess
import sys
import time

import pytest

from kuberay_tpu.serve.group_health import (
    GroupMonitor,
    start_heartbeat,
)


def wait_for(fn, timeout=10.0, poll=0.05):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if fn():
            return True
        time.sleep(poll)
    return False


class FakeClock:
    """Deterministic monotonic clock for the monitor's timeout math —
    the unit tests below advance it explicitly instead of sleeping, so
    a loaded CI box can't stretch a sleep past a deadline and flake."""

    def __init__(self, t: float = 100.0):
        self.t = t

    def now(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


# ---------------------------------------------------------------------------
# monitor unit behavior (fake-clocked: no wall-time dependence)


def test_monitor_detects_missed_heartbeats():
    clk = FakeClock()
    m = GroupMonitor(expected=[1, 2], miss_timeout=0.3, grace=0.0,
                     clock=clk)
    m.beat(1)
    m.beat(2)
    assert m.check() is None
    m.beat(1)
    clk.advance(0.5)
    m.beat(1)                      # 1 keeps beating, 2 went silent
    reason = m.check()
    assert reason and "[2]" in reason
    # Sticky: later beats do not resurrect the group.
    m.beat(2)
    assert m.check() == reason


def test_monitor_step_watchdog():
    clk = FakeClock()
    m = GroupMonitor(expected=[], miss_timeout=30.0, step_timeout=0.2,
                     clock=clk)
    m.step_begin()
    assert m.check() is None
    clk.advance(0.4)
    assert "stuck" in m.check()
    # step_end clears the clock for healthy groups.
    m2 = GroupMonitor(expected=[], miss_timeout=30.0, step_timeout=0.2,
                      clock=clk)
    m2.step_begin()
    m2.step_end()
    clk.advance(0.4)
    assert m2.check() is None


def test_monitor_ignores_stray_worker_ids():
    """A beat from an unexpected id (misconfigured worker, stale prior
    incarnation, random writer on the open port) must not create a
    tracked entry that later goes stale and degrades a healthy group."""
    clk = FakeClock()
    m = GroupMonitor(expected=[1], miss_timeout=0.3, grace=0.0, clock=clk)
    m.beat(1)
    m.beat(7)                      # stray
    clk.advance(0.4)
    m.beat(1)
    assert m.check() is None
    assert set(m.status()["beat_age_seconds"]) == {"1"}


def test_monitor_grace_defers_first_beat_deadline():
    clk = FakeClock()
    m = GroupMonitor(expected=[1], miss_timeout=0.2, grace=5.0, clock=clk)
    clk.advance(0.4)               # past miss_timeout, inside grace
    assert m.check() is None
    # Past grace + miss_timeout with no beat ever: degraded.
    clk.advance(5.0)
    assert m.check() and "missed heartbeats" in m.check()


def test_monitor_on_degraded_fires_once():
    fired = []
    clk = FakeClock()
    m = GroupMonitor(expected=[1], miss_timeout=0.1, grace=0.0,
                     on_degraded=fired.append, clock=clk)
    clk.advance(0.2)
    m.check()
    m.check()
    assert len(fired) == 1


def test_heartbeat_wire_protocol():
    m = GroupMonitor(expected=[1], miss_timeout=1.0, grace=10.0)
    port = m.listen(host="127.0.0.1", port=0)
    stop = start_heartbeat("127.0.0.1", port, 1, interval=0.1)
    try:
        assert wait_for(
            lambda: m.status()["beat_age_seconds"]["1"] < 0.5)
        # Beats keep the group healthy past the grace-less deadline.
        time.sleep(1.2)
        assert m.check() is None
        # Stop beating -> degradation within miss_timeout.
        stop.set()
        assert wait_for(lambda: m.check() is not None, timeout=5)
        assert "missed heartbeats" in m.check()
    finally:
        stop.set()
        m.close()


# ---------------------------------------------------------------------------
# frontend drain semantics (single-process: monitor injected directly)


def test_frontend_fails_pending_and_rejects_on_degraded():
    import jax

    from kuberay_tpu.models import llama
    from kuberay_tpu.serve.engine import ServeEngine
    from kuberay_tpu.serve.server import ServeFrontend

    cfg = llama.CONFIGS["llama_tiny"]
    eng = ServeEngine(cfg, llama.init_params(cfg, jax.random.PRNGKey(0)),
                      max_slots=2, max_len=64)
    reasons = []
    fe = ServeFrontend(eng, on_degraded=reasons.append)
    import threading
    out = []
    t = threading.Thread(
        target=lambda: out.append(fe.submit([1, 2, 3], max_tokens=8,
                                            timeout=30)),
        daemon=True)
    # Park the loop BEFORE the request is admitted so the waiter is
    # pending when degradation hits.
    fe._handle_degraded("test: follower lost")
    t.start()
    t.join(timeout=5)
    assert not t.is_alive()
    assert out == [None]
    assert fe.degraded == "test: follower lost"
    assert reasons == ["test: follower lost"]
    assert fe.stats()["degraded"] == "test: follower lost"
    # drain() reports failure instead of waiting out its timeout.
    t0 = time.time()
    assert fe.drain(timeout=30) is False
    assert time.time() - t0 < 1
    fe.close()


def test_frontend_degrades_on_engine_exception():
    import jax

    from kuberay_tpu.models import llama
    from kuberay_tpu.serve.engine import ServeEngine
    from kuberay_tpu.serve.server import ServeFrontend

    cfg = llama.CONFIGS["llama_tiny"]
    eng = ServeEngine(cfg, llama.init_params(cfg, jax.random.PRNGKey(0)),
                      max_slots=2, max_len=64)

    def boom():
        raise RuntimeError("collective aborted: peer disconnected")

    eng.step = boom
    fe = ServeFrontend(eng)
    assert fe.submit([1, 2, 3], max_tokens=4, timeout=10) is None
    assert "collective aborted" in (fe.degraded or "")
    fe.close()


# ---------------------------------------------------------------------------
# controller: DEGRADED app -> condition + immediate slice replacement


def test_service_controller_replaces_on_degraded_app():
    """A DEGRADED serve app (dead follower) sets ServeGroupDegraded and
    triggers whole-cluster replacement IMMEDIATELY — no threshold wait —
    through the full controller stack (cluster controller + kubelet)."""
    from kuberay_tpu.api.tpuservice import (
        ServiceConditionType,
        ServiceStatusName,
    )
    from tests.test_service_controller import (
        ServiceHarness,
        make_service,
    )

    h = ServiceHarness()
    svc = make_service()
    # Hour-long thresholds prove DEGRADED bypasses them.
    svc.spec.serviceUnhealthySecondThreshold = 3600
    svc.spec.deploymentUnhealthySecondThreshold = 3600
    h.store.create(svc.to_dict())
    h.settle()
    s = h.svc()
    active = s.status.activeServiceStatus.clusterName
    conds = {c.type: c for c in s.status.conditions}
    assert conds[ServiceConditionType.SERVE_GROUP_DEGRADED].status == \
        "False"

    # Follower dies: the serve server posts DEGRADED to the coordinator.
    h.clients[active].set_serve_app(
        "llm", ServiceStatusName.DEGRADED,
        "follower(s) [1] missed heartbeats for >10s")
    # One reconcile pass: condition up + replacement prepared, BEFORE
    # the recovery machinery has had time to promote anything.
    h.svc_ctrl.reconcile("svc", "default")
    s = h.svc()
    conds = {c.type: c for c in s.status.conditions}
    cond = conds[ServiceConditionType.SERVE_GROUP_DEGRADED]
    assert cond.status == "True"
    assert "missed heartbeats" in cond.message
    # Replacement cluster exists (prepared despite the 3600 s threshold).
    assert any(c["metadata"]["name"] != active
               for c in h.store.list("TpuCluster", "default"))

    # Replacement comes up, takes over, condition clears.
    h.settle(rounds=16)
    s = h.svc()
    assert s.status.activeServiceStatus.clusterName != active
    assert s.status.serviceStatus == "Running"
    conds = {c.type: c for c in s.status.conditions}
    assert conds[ServiceConditionType.SERVE_GROUP_DEGRADED].status == \
        "False"


# ---------------------------------------------------------------------------
# e2e: kill a follower mid-decode on the 2-process CPU harness


@pytest.mark.timeout(420)
def test_kill_follower_no_hang_and_degraded(tmp_path):
    """SIGKILL the follower while host 0 is mid-decode: host 0 must
    detect (heartbeat loss), fail the in-flight request fast, 503 its
    health probe, reject new work, and exit cleanly — no hang."""
    script = os.path.join(os.path.dirname(__file__), "helpers",
                          "degraded_serve_worker.py")
    sock = socket.socket()
    sock.bind(("127.0.0.1", 0))
    hb_port = sock.getsockname()[1]
    sock.close()
    ready_file = str(tmp_path / "ready")

    def spawn(worker_id):
        env = dict(os.environ)
        env.update({
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
            "TPU_WORKER_HOSTNAMES": "localhost,localhost",
            "TPU_NUM_PROCESSES": "2",
            "TPU_WORKER_ID": str(worker_id),
            "TPU_GROUP_HEALTH_PORT": str(hb_port),
            "READY_FILE": ready_file,
        })
        return subprocess.Popen([sys.executable, script], env=env,
                                stdout=subprocess.PIPE,
                                stderr=subprocess.STDOUT, text=True)

    host0, follower = spawn(0), spawn(1)
    try:
        # Fail fast if a worker dies during bootstrap: burning the full
        # readiness timeout on an already-dead subprocess tells us
        # nothing the traceback doesn't.
        wait_for(lambda: os.path.exists(ready_file) or
                 host0.poll() is not None or follower.poll() is not None,
                 timeout=300, poll=0.2)
        if not os.path.exists(ready_file):
            dead = host0 if host0.poll() is not None else follower
            out, _ = dead.communicate(timeout=30)
            pytest.fail("serving never reached in-flight state; worker "
                        f"exited rc={dead.returncode}:\n{out[-3000:]}")
        follower.send_signal(signal.SIGKILL)
        follower.wait(timeout=30)
        out, _ = host0.communicate(timeout=120)
    finally:
        for p in (host0, follower):
            if p.poll() is None:
                p.kill()
    assert host0.returncode == 0, out[-3000:]
    # Either detection path is correct — whichever wins the race: the
    # collective erroring on the scheduling thread (gloo notices the
    # closed TCP pair instantly) or the heartbeat monitor (covered in
    # isolation by test_heartbeat_wire_protocol).
    assert "DEGRADED " in out
    assert ("missed heartbeats" in out or "engine step failed" in out)
    assert "SUBMIT_FAILED_FAST joined=True none=True" in out
    assert "HEALTHZ_503 code=503" in out
    assert "NEW_SUBMIT_REJECTED none=True" in out
    # Rejection was immediate, not a 30 s timeout burn.
    rej = next(ln for ln in out.splitlines()
               if ln.startswith("NEW_SUBMIT_REJECTED"))
    assert float(rej.split("secs=")[1]) < 2.0
    assert "CLEAN_EXIT" in out


# ---------------------------------------------------------------------------
# adaptive step budgets (VERDICT r4 item 9: no static constant on the
# hot path — the budget derives from observed step-time distribution)


def test_adaptive_budget_tracks_observed_steps():
    """Cold start uses the static default; after MIN_SAMPLES completed
    steps the budget becomes multiplier x rolling p99, floored at the
    miss timeout."""
    clk = FakeClock()
    m = GroupMonitor(expected=[], miss_timeout=0.5, step_timeout=60.0,
                     budget_multiplier=20.0, clock=clk)
    assert m.current_step_budget() == 60.0          # cold start
    # Observe fast steps (5 ms): budget drops to the miss-timeout
    # floor — far quicker hang detection than the 60 s constant.
    for _ in range(m.MIN_SAMPLES):
        m.step_begin()
        clk.advance(0.005)
        m.step_end()
    fast = m.current_step_budget()
    assert fast == pytest.approx(0.5, abs=0.01), fast    # floor
    # A workload shift to slow steps RAISES the budget: p99 follows.
    for _ in range(30):
        m._durations.append(0.2)          # 200 ms steps, 20x -> 4 s
    slow = m.current_step_budget()
    assert slow == pytest.approx(4.0, rel=0.1), slow
    assert "step_budget_seconds" in m.status()


def test_slow_but_alive_group_never_degrades():
    """Steps 10x slower than the historical p99 but inside the adaptive
    budget must NOT degrade the group (the false-DEGRADED this feature
    exists to prevent: a legit long chunked-prefill batch on a big
    model would otherwise trip a whole-slice replacement)."""
    clk = FakeClock()
    m = GroupMonitor(expected=[], miss_timeout=0.05, step_timeout=0.1,
                     budget_multiplier=20.0, clock=clk)
    # History: 10 ms steps -> p99 10 ms -> budget max(0.05, 0.2)=0.2 s.
    for _ in range(m.MIN_SAMPLES):
        m.step_begin()
        clk.advance(0.01)
        m.step_end()
    budget = m.current_step_budget()
    assert budget >= 0.15, budget
    # A 0.12 s step (longer than the 0.1 s static default!) survives.
    m.step_begin()
    clk.advance(0.12)
    assert m.check() is None, m.check()
    m.step_end()
    assert m.degraded is None
    # A genuinely stuck step still trips once the budget is exceeded.
    m.step_begin()
    clk.advance(budget + 0.1)
    assert m.check() and "stuck" in m.check()


def test_compile_steps_stay_out_of_distribution():
    """A compile-flagged step must use the compile budget and must NOT
    inflate the rolling p99 for subsequent steps."""
    clk = FakeClock()
    m = GroupMonitor(expected=[], miss_timeout=0.5, step_timeout=60.0,
                     compile_timeout=300.0, budget_multiplier=20.0,
                     clock=clk)
    m.step_begin(compiling=True)
    assert m._step_budget == 300.0
    clk.advance(0.2)                      # a "long compile"
    m.step_end()
    assert m._durations == []             # not recorded
    for _ in range(m.MIN_SAMPLES):
        m.step_begin()
        clk.advance(0.002)
        m.step_end()
    # Budget reflects the fast steady state, not the compile outlier.
    assert m.current_step_budget() == pytest.approx(0.5, abs=0.01)
