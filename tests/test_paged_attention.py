"""Block-table-native paged decode kernel vs the gather+XLA reference."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kuberay_tpu.ops.paged_attention import (
    gather_view,
    paged_decode_attention_pallas,
    paged_decode_attention_xla,
)


def make(S=3, Hq=4, Hkv=2, D=16, num_blocks=8, bs=8, nblk=4, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 4)
    q = jax.random.normal(ks[0], (S, Hq, D))
    pk = jax.random.normal(ks[1], (Hkv, num_blocks * bs, D))
    pv = jax.random.normal(ks[2], (Hkv, num_blocks * bs, D))
    # Scrambled, request-disjoint physical pages (the realistic shape).
    perm = jax.random.permutation(ks[3], num_blocks)[:S * nblk]
    tables = perm.reshape(S, nblk).astype(jnp.int32) \
        if S * nblk <= num_blocks else \
        jax.random.randint(ks[3], (S, nblk), 0, num_blocks, jnp.int32)
    return q, pk, pv, tables


@pytest.mark.parametrize("gqa", [1, 2, 4])
def test_paged_kernel_matches_gather_xla(gqa):
    q, pk, pv, tables = make(S=2, Hq=4, Hkv=4 // gqa, num_blocks=16, nblk=4)
    lens = jnp.array([5, 29], jnp.int32)
    ref = paged_decode_attention_xla(q, pk, pv, lens, tables, block_size=8)
    got = paged_decode_attention_pallas(q, pk, pv, lens, tables,
                                        block_size=8, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-3, atol=2e-3)


def test_ragged_lengths_and_block_boundaries():
    q, pk, pv, tables = make(S=4, num_blocks=32, nblk=6)
    lens = jnp.array([1, 8, 9, 48], jnp.int32)
    ref = paged_decode_attention_xla(q, pk, pv, lens, tables, block_size=8)
    got = paged_decode_attention_pallas(q, pk, pv, lens, tables,
                                        block_size=8, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-3, atol=2e-3)


def test_dead_pages_never_influence_output():
    """Entries past the live length may be ANY page id (the engine zeroes
    them): poisoning the dead region of the pool must not change the
    result — the index clamp + compute skip make it unreachable."""
    q, pk, pv, tables = make(S=2, num_blocks=16, nblk=4)
    lens = jnp.array([10, 16], jnp.int32)
    base = paged_decode_attention_pallas(q, pk, pv, lens, tables,
                                         block_size=8, interpret=True)
    # Poison every page NOT referenced by a live table entry.
    live = set()
    for s in range(2):
        for j in range((int(lens[s]) + 7) // 8):
            live.add(int(tables[s, j]))
    mask = np.ones(16, bool)
    mask[list(live)] = False
    pk2 = np.asarray(pk).reshape(pk.shape[0], 16, 8, -1).copy()
    pv2 = np.asarray(pv).reshape(pv.shape[0], 16, 8, -1).copy()
    pk2[:, mask] = 1e9
    pv2[:, mask] = -1e9
    got = paged_decode_attention_pallas(
        q, jnp.asarray(pk2.reshape(pk.shape)),
        jnp.asarray(pv2.reshape(pv.shape)), lens, tables,
        block_size=8, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(base),
                               rtol=1e-5, atol=1e-5)


def test_gather_view_resolves_tables():
    """gather_view is the ground-truth indirection: logical position p of
    request b reads pool page tables[b, p // bs] at offset p % bs."""
    Hkv, nb, bs, D = 2, 6, 4, 8
    pool = jnp.arange(Hkv * nb * bs * D, dtype=jnp.float32).reshape(
        Hkv, nb * bs, D)
    tables = jnp.asarray([[3, 0, 5]], jnp.int32)
    view = gather_view(pool, tables, bs)         # [1, 12, Hkv, D]
    for p in range(12):
        phys = int(tables[0, p // bs]) * bs + p % bs
        np.testing.assert_array_equal(np.asarray(view[0, p]),
                                      np.asarray(pool[:, phys]))


def test_paged_engine_native_kernel_parity():
    """The engine generates identical tokens whether decode attention
    runs the gather+XLA fallback or the block-table-native kernel
    (interpret mode on CPU)."""
    from kuberay_tpu.models import llama
    from kuberay_tpu.serve.engine import Request
    from kuberay_tpu.serve.paged_engine import PagedServeEngine

    cfg = llama.CONFIGS["llama_tiny"]
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    prompts = [[5, 17, 42, 7], [9, 9, 1, 30, 2, 8, 4]]

    outs = {}
    for impl in ("xla", "pallas_interpret"):
        eng = PagedServeEngine(cfg, params, max_slots=2, max_len=64,
                               block_size=8, decode_impl=impl)
        for i, p in enumerate(prompts):
            eng.add_request(Request(f"r{i}", list(p), max_new_tokens=5))
        outs[impl] = {r.request_id: r.tokens for r in eng.run()}
    assert outs["xla"] == outs["pallas_interpret"]
