"""Goodput/badput ledger: per-job wall-clock attribution (ISSUE 4).

The acceptance gates:

1. **Sim-gated exactness** — under the virtual clock, seeded fault
   schedules (kill-a-follower + slow recovery, slow-start bootstrap)
   produce exact-second attribution: intervals partition the run (no
   gaps, no overlaps, sum(phases) == elapsed) and interrupted+recovery
   equals the fault window the schedule implies, to the second.
2. **Replay invariance** — the journal hash of a chaos run is
   byte-identical with the ledger on or off.
3. **Post-mortem survival** — a deleted cluster's goodput doc survives
   via the history archive and `HistoryServer` GET returns the same
   rollup.
4. The live `/debug/goodput` + `/debug/autoscaler` operator surface.
"""

from __future__ import annotations

import json
import time
import urllib.request

import pytest

from kuberay_tpu.controlplane.store import ObjectStore
from kuberay_tpu.history.server import HistoryCollector, HistoryServer
from kuberay_tpu.history.storage import LocalStorage
from kuberay_tpu.obs import GoodputLedger, TransitionRecorder
from kuberay_tpu.obs.goodput import PHASES
from kuberay_tpu.sim.faults import FaultPlan
from kuberay_tpu.sim.harness import SimHarness
from kuberay_tpu.sim.scenarios import get_scenario, make_cluster_obj
from kuberay_tpu.utils import constants as C

QUIET = {f: 0.0 for f in FaultPlan(0).profile}


def _assert_partition(intervals, now, total_expected=None):
    """Intervals must partition [start, end]: contiguous (each end IS
    the next start), monotonic, no gaps, no overlaps."""
    assert intervals, "empty ledger"
    prev_end = intervals[0]["start"]
    for iv in intervals:
        assert iv["start"] == prev_end, \
            f"gap/overlap at {iv}: start != previous end {prev_end}"
        end = iv["end"] if iv["end"] is not None else now
        assert end >= iv["start"]
        prev_end = iv["end"] if iv["end"] is not None else now
    if total_expected is not None:
        assert prev_end - intervals[0]["start"] == \
            pytest.approx(total_expected, abs=1e-6)


def _assert_rollup_exact(roll):
    """The exclusivity/exhaustiveness contract: every phase key
    present, sum(phases) == total exactly."""
    assert set(roll["phases"]) == set(PHASES)
    assert sum(roll["phases"].values()) == pytest.approx(
        roll["total"], abs=1e-6)
    assert 0.0 <= roll["goodput_ratio"] <= 1.0


# ---------------------------------------------------------------------------
# sim-gated exactness
# ---------------------------------------------------------------------------

@pytest.mark.timeout(120)
def test_kill_a_follower_exact_second_attribution():
    """The seeded schedule: kill a follower at t=X, the controller
    reacts at X+5 (slice deleted + recreated), replacements slow-start
    +40s.  The ledger must attribute exactly 5s interrupted + 40s
    recovery — the fault window — and partition the whole run."""
    with SimHarness(0, fault_profile=QUIET, goodput=True) as h:
        t0 = h.clock.now()
        h.store.create(make_cluster_obj("demo", topology="2x2x2",
                                        replicas=1))
        h.settle()
        roll = h.goodput.rollup("TpuCluster", "default", "demo")
        assert roll["current_phase"] == "productive"

        h.clock.advance(30.0)              # 30 productive seconds
        t_kill = h.clock.now()
        workers = sorted(
            p["metadata"]["name"] for p in h.store.list("Pod")
            if p["metadata"]["labels"].get(C.LABEL_GROUP) == "workers")
        assert len(workers) == 2           # 2x2x2 v5p = 2 hosts
        h.kubelet.fail_pod(workers[1])     # the follower dies at t=X

        h.clock.advance(5.0)               # detection -> reaction delay
        h.manager.run_until_idle()         # slice deleted + recreated
        pending = [p["metadata"]["name"] for p in h.store.list("Pod")
                   if p.get("status", {}).get("phase",
                                              "Pending") == "Pending"]
        assert pending                     # replacements exist, not up
        for name in pending:               # slow-start +40s
            h.kubelet.hold_pod(name, until=h.clock.now() + 40.0)
        h.settle(horizon=120.0)

        now = h.clock.now()
        roll = h.goodput.rollup("TpuCluster", "default", "demo", now=now)
        intervals = h.goodput.intervals("TpuCluster", "default", "demo")

    _assert_partition(intervals, now, total_expected=now - t0)
    _assert_rollup_exact(roll)
    assert roll["total"] == pytest.approx(now - t0, abs=1e-6)
    # Exact-second attribution of the schedule: 30s productive before
    # the kill, 5s interrupted (kill -> reaction), 40s recovery
    # (slow-start hold), productive again after.
    assert roll["phases"]["interrupted"] == pytest.approx(5.0, abs=1e-3)
    assert roll["phases"]["recovery"] == pytest.approx(40.0, abs=1e-3)
    fault_window = roll["phases"]["interrupted"] + roll["phases"]["recovery"]
    assert fault_window == pytest.approx(45.0, abs=1e-3)
    assert roll["phases"]["productive"] == pytest.approx(
        roll["total"] - fault_window, abs=1e-3)
    assert roll["current_phase"] == "productive"
    # The phase sequence tells the story in order.
    seq = [iv["phase"] for iv in intervals]
    assert seq == ["queued", "provisioning", "bootstrap", "productive",
                   "interrupted", "recovery", "productive"]


@pytest.mark.timeout(120)
def test_slow_start_bootstrap_attribution():
    """Slow-start +40s on one host of a fresh slice: the whole 40s is
    bootstrap (multi-host bring-up gated on the slowest TPU_WORKER_ID),
    and the run still partitions exactly."""
    with SimHarness(0, fault_profile=QUIET, goodput=True) as h:
        t0 = h.clock.now()
        h.store.create(make_cluster_obj("demo", topology="2x2x2",
                                        replicas=1))
        h.manager.run_until_idle()         # pods created, none running
        workers = sorted(
            p["metadata"]["name"] for p in h.store.list("Pod")
            if p["metadata"]["labels"].get(C.LABEL_GROUP) == "workers")
        h.kubelet.hold_pod(workers[0], until=h.clock.now() + 40.0)
        h.settle(horizon=120.0)

        now = h.clock.now()
        roll = h.goodput.rollup("TpuCluster", "default", "demo", now=now)
        intervals = h.goodput.intervals("TpuCluster", "default", "demo")

        _assert_partition(intervals, now, total_expected=now - t0)
        _assert_rollup_exact(roll)
        assert roll["phases"]["bootstrap"] == pytest.approx(40.0, abs=1e-3)
        assert roll["phases"]["interrupted"] == 0.0
        assert roll["phases"]["recovery"] == 0.0
        assert roll["current_phase"] == "productive"

        # Deletion freezes the ledger: teardown closes, the rollup stops
        # extending with the clock.
        h.store.delete("TpuCluster", "demo")
        h.settle()
        end = h.clock.now()
        roll = h.goodput.rollup("TpuCluster", "default", "demo")
        assert roll["closed"] and roll["current_phase"] == "teardown"
        h.clock.advance(1000.0)
        assert h.goodput.rollup("TpuCluster", "default",
                                "demo")["total"] == roll["total"]
        assert roll["end"] <= end


@pytest.mark.timeout(300)
def test_journal_hash_invariant_with_ledger_on_or_off():
    """The replay contract: rolling-upgrade seed 0 produces a
    byte-identical journal hash with the goodput ledger on and off —
    the ledger is purely observational."""
    with SimHarness(0, scenario=get_scenario("rolling-upgrade"),
                    goodput=True) as h:
        with_ledger = h.run(2)
        export = h.export_trace()
    with SimHarness(0, scenario=get_scenario("rolling-upgrade")) as h:
        without = h.run(2)
    assert with_ledger.ok and without.ok
    assert with_ledger.journal_hash == without.journal_hash
    assert with_ledger.journal_len == without.journal_len
    # The export artifact carries the ledger snapshot, JSON-ready.
    assert export["goodput"]
    json.dumps(export)


# ---------------------------------------------------------------------------
# post-mortem: the history archive round-trip
# ---------------------------------------------------------------------------

def _pod(name, cluster, phase="Pending"):
    return {"apiVersion": "v1", "kind": "Pod",
            "metadata": {"name": name, "namespace": "default",
                         "labels": {C.LABEL_CLUSTER: cluster}},
            "spec": {}, "status": {"phase": phase}}


@pytest.mark.timeout(60)
def test_deleted_cluster_goodput_survives_history_archive(tmp_path):
    """Archive -> HistoryServer GET -> same rollup: the time-loss
    breakdown outlives the cluster."""
    store = ObjectStore()
    ledger = GoodputLedger()
    cancel = store.watch(ledger.observe_event)
    storage = LocalStorage(str(tmp_path / "arch"))
    collector = HistoryCollector(store, storage, goodput=ledger)
    try:
        store.create(make_cluster_obj("demo", accelerator="v5e",
                                      topology="2x2", replicas=1))
        # 2x2 v5e = 1 host -> expected pods = head + 1 worker.
        for name in ("demo-head", "demo-workers-0-0"):
            store.create(_pod(name, "demo"))
            pod = store.get("Pod", name)
            pod["status"] = {"phase": "Running"}
            store.update_status(pod)
        roll_live = ledger.rollup("TpuCluster", "default", "demo")
        assert roll_live["current_phase"] == "productive"
        store.delete("TpuCluster", "demo")
    finally:
        collector.close()          # drains the archive queue
        cancel()

    frozen = ledger.rollup("TpuCluster", "default", "demo")
    assert frozen["closed"]

    hs = HistoryServer(storage)
    code, body, is_text = hs.route("/api/history/goodput/default/demo")
    assert code == 200 and not is_text
    assert body["kind"] == "TpuCluster"
    # Same rollup as the (closed, frozen) in-memory ledger.
    assert body["rollup"]["phases"] == frozen["phases"]
    assert body["rollup"]["total"] == frozen["total"]
    assert body["rollup"]["closed"]
    seq = [iv["phase"] for iv in body["intervals"]]
    assert seq[0] == "queued" and seq[-1] == "teardown"
    # Also reachable through the generic meta listing.
    code, meta, _ = hs.route("/api/history/meta/default/demo")
    assert code == 200 and "goodput.json" in meta

    # Unknown cluster -> 404, not a crash.
    code, _, _ = hs.route("/api/history/goodput/default/nope")
    assert code == 404


# ---------------------------------------------------------------------------
# live operator surface
# ---------------------------------------------------------------------------

@pytest.mark.timeout(120)
def test_operator_debug_goodput_and_autoscaler_endpoints():
    from kuberay_tpu.operator import Operator

    op = Operator(fake_kubelet=True)
    url = op.start(api_port=0)
    try:
        op.store.create(make_cluster_obj("smoke", topology="2x2x2",
                                         replicas=1))
        for _ in range(6):
            op.run_until_idle()
        assert op.store.get("TpuCluster", "smoke")["status"]["state"] == \
            "ready"
        with urllib.request.urlopen(f"{url}/debug/goodput") as r:
            listing = json.load(r)
        rows = {(o["kind"], o["name"]): o for o in listing["objects"]}
        assert rows[("TpuCluster", "smoke")]["current_phase"] == "productive"
        with urllib.request.urlopen(
                f"{url}/debug/goodput/TpuCluster/default/smoke") as r:
            doc = json.load(r)
        _assert_rollup_exact(doc["rollup"])
        _assert_partition(doc["intervals"], time.time())
        with urllib.request.urlopen(f"{url}/debug/autoscaler") as r:
            audit = json.load(r)
        assert "decisions" in audit
        # The metric catalog carries the new series.
        with urllib.request.urlopen(f"{url}/metrics") as r:
            text = r.read().decode()
        assert "tpu_goodput_seconds_total" in text
        assert 'tpu_goodput_ratio{kind="TpuCluster"' in text
        # Unknown object -> 404.
        try:
            urllib.request.urlopen(
                f"{url}/debug/goodput/TpuCluster/default/nope")
            assert False, "expected 404"
        except urllib.error.HTTPError as e:
            assert e.code == 404
    finally:
        op.stop()


# ---------------------------------------------------------------------------
# coordinator feed: server-side timestamps only
# ---------------------------------------------------------------------------

def test_coordinator_goodput_feed_ignores_client_clocks():
    from kuberay_tpu.runtime.coordinator_server import (CoordinatorServer,
                                                        MemoryBackend)

    ledger = GoodputLedger()
    coord = CoordinatorServer(state=MemoryBackend(), spawn_jobs=False,
                              goodput=ledger)
    t0 = time.time()
    coord.submit("j1", "echo hi")
    # Client clocks are wildly skewed (past AND future): attribution
    # must come from the server's receive time regardless.
    coord.record_events({"job_id": "j1", "name": "job_started",
                         "ts": 17.0})
    coord.record_events({"job_id": "j1", "name": "job_finished",
                         "ts": t0 + 9e9})
    roll = ledger.rollup("CoordinatorJob", "head", "j1")
    assert roll["closed"]
    seq = [iv["phase"] for iv in ledger.intervals("CoordinatorJob",
                                                  "head", "j1")]
    assert seq == ["queued", "productive", "teardown"]
    # Interval stamps are server wall-clock, not the client's 17.0 /
    # far-future lies.
    assert t0 - 5 <= roll["start"] <= time.time() + 5
    assert t0 - 5 <= roll["end"] <= time.time() + 5
    _assert_rollup_exact(roll)


def test_transition_recorder_feeds_ledger_and_flight():
    from kuberay_tpu.obs import FlightRecorder

    ledger = GoodputLedger()
    flight = FlightRecorder()
    rec = TransitionRecorder(flight=flight, ledger=ledger)
    rec.record("TpuJob", "default", "train", "Initializing",
               old_state="New")
    rec.record("TpuJob", "default", "train", "Running",
               old_state="Initializing")
    seq = [iv["phase"] for iv in ledger.intervals("TpuJob", "default",
                                                  "train")]
    assert seq == ["provisioning", "productive"]
    records = flight.timeline("TpuJob", "default", "train")
    assert [r["detail"] for r in records if r["type"] == "state"] == \
        ["New -> Initializing", "Initializing -> Running"]
    assert all(r.get("source") == "controller" for r in records
               if r["type"] == "state")
