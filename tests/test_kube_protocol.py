"""K8s API-server protocol conformance: streaming watch (resourceVersion
resume, bookmarks, 410 Gone + relist), bearer auth, TLS — the seam that
lets the operator run against a real kube-apiserver
(ref ray-operator/test/e2e + envtest suite_test.go roles).
"""

import json
import subprocess
import threading
import time
import urllib.error
import urllib.request

import pytest

from kuberay_tpu.api.config import OperatorConfiguration
from kuberay_tpu.apiserver.server import serve_background
from kuberay_tpu.controlplane.fake_kubelet import FakeKubelet
from kuberay_tpu.controlplane.rest_store import RestObjectStore
from kuberay_tpu.controlplane.store import ObjectStore
from kuberay_tpu.operator import Operator
from kuberay_tpu.runtime.coordinator_client import FakeCoordinatorClient
from kuberay_tpu.utils import constants as C
from tests.test_api_types import make_cluster


def wait_for(fn, timeout=30.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if fn():
            return True
        time.sleep(0.05)
    return False


def mkpod(name, ns="default", labels=None):
    # Pod watch streams are scoped to operator-created pods
    # (managercache analogue) — stamp the label unless the test
    # overrides it.
    base = {C.LABEL_CREATED_BY: C.CREATED_BY_OPERATOR}
    return {"apiVersion": "v1", "kind": "Pod",
            "metadata": {"name": name, "namespace": ns,
                         "labels": {**base, **(labels or {})}},
            "spec": {}, "status": {}}


@pytest.fixture
def remote():
    backing = ObjectStore()
    srv, url = serve_background(backing)
    yield backing, url
    srv.shutdown()


# ---------------------------------------------------------------------------
# raw protocol
# ---------------------------------------------------------------------------

def test_streaming_watch_raw_protocol(remote):
    """?watch=true streams ADDED/MODIFIED/DELETED lines from the given
    resourceVersion, then ends cleanly at timeoutSeconds."""
    backing, url = remote
    backing.create(mkpod("seed"))        # rv=0 means "from now" (K8s
    rv0 = backing.resource_version()     # semantics); resume needs rv>0
    backing.create(mkpod("w1"))
    p = backing.get("Pod", "w1")
    p["status"] = {"phase": "Running"}
    backing.update_status(p)
    backing.delete("Pod", "w1")

    resp = urllib.request.urlopen(
        f"{url}/api/v1/namespaces/default/pods"
        f"?watch=true&resourceVersion={rv0}&timeoutSeconds=2")
    lines = [json.loads(ln) for ln in resp if ln.strip()]
    types = [(e["type"], e["object"]["metadata"]["name"]) for e in lines]
    assert ("ADDED", "w1") in types
    assert ("MODIFIED", "w1") in types
    assert ("DELETED", "w1") in types


def test_watch_bookmarks_advance_rv(remote):
    """allowWatchBookmarks: idle stream still carries the latest rv so a
    reconnect never resumes from an expired point."""
    backing, url = remote
    rv0 = backing.resource_version()
    # Traffic on a DIFFERENT kind: the pod watch sees no events, only
    # bookmarks — which must still advance past the foreign-kind span.
    for i in range(3):
        backing.create({"apiVersion": "v1", "kind": "Service",
                        "metadata": {"name": f"s{i}",
                                     "namespace": "default"},
                        "spec": {}})
    resp = urllib.request.urlopen(
        f"{url}/api/v1/namespaces/default/pods"
        f"?watch=true&resourceVersion={rv0}&timeoutSeconds=1"
        f"&allowWatchBookmarks=true")
    lines = [json.loads(ln) for ln in resp if ln.strip()]
    bookmarks = [e for e in lines if e["type"] == "BOOKMARK"]
    assert bookmarks, "idle watch sent no bookmark"
    assert int(bookmarks[-1]["object"]["metadata"]["resourceVersion"]) \
        >= rv0 + 3


def test_watch_410_on_expired_rv(remote):
    """A resume point older than the event backlog must 410 (client
    relists), never silently skip the missed span."""
    backing, url = remote
    backing._backlog_max = 5
    backing.create(mkpod("seed"))
    for i in range(20):
        backing.create(mkpod(f"flood-{i}"))
    with pytest.raises(urllib.error.HTTPError) as exc:
        urllib.request.urlopen(
            f"{url}/api/v1/namespaces/default/pods"
            "?watch=true&resourceVersion=1&timeoutSeconds=1")
    assert exc.value.code == 410
    body = json.loads(exc.value.read())
    assert body["reason"] == "Expired"


def test_watch_410_on_future_rv(remote):
    """A resume point AHEAD of the store (apiserver restarted, rv counter
    reset) must 410 so the client relists — not silently filter every
    event below the stale rv forever."""
    backing, url = remote
    backing.create(mkpod("now"))
    with pytest.raises(urllib.error.HTTPError) as exc:
        urllib.request.urlopen(
            f"{url}/api/v1/namespaces/default/pods"
            "?watch=true&resourceVersion=999999&timeoutSeconds=1")
    assert exc.value.code == 410


def test_list_carries_k8s_metadata_rv(remote):
    backing, url = remote
    backing.create(mkpod("lp"))
    out = json.load(urllib.request.urlopen(
        f"{url}/api/v1/namespaces/default/pods"))
    assert int(out["metadata"]["resourceVersion"]) >= 1
    assert out["items"]


# ---------------------------------------------------------------------------
# RestObjectStore consumption
# ---------------------------------------------------------------------------

def test_client_prefers_k8s_watch_mode(remote):
    backing, url = remote
    store = RestObjectStore(url)
    assert store._detect_watch_mode() == ("k8s", True)
    got = []
    store.watch(lambda ev: got.append((ev.type, ev.kind,
                                       ev.obj["metadata"]["name"])))
    time.sleep(0.5)          # per-kind streams connect
    backing.create(mkpod("fast"))
    assert wait_for(lambda: ("ADDED", "Pod", "fast") in got, 5.0), got
    p = backing.get("Pod", "fast")
    p["metadata"]["labels"]["x"] = "1"
    backing.update(p)
    assert wait_for(lambda: ("MODIFIED", "Pod", "fast") in got, 5.0), got
    backing.delete("Pod", "fast")
    assert wait_for(lambda: ("DELETED", "Pod", "fast") in got, 5.0), got
    store.close()


def test_client_stream_expired_rv_triggers_relist(remote):
    """Protocol unit: _stream_kind returns None on 410 (the relist
    signal); _kind_loop then relists and emits the missed diff."""
    backing, url = remote
    backing._backlog_max = 5
    store = RestObjectStore(url)
    for i in range(12):
        backing.create(mkpod(f"p{i}"))
    assert store._stream_kind("Pod", "1", threading.Event()) is None
    store.close()


def test_client_converges_through_backlog_overflow(remote):
    """End-to-end 410 recovery: a flood larger than the server backlog
    must still leave the client's view complete (relist + rediff)."""
    backing, url = remote
    backing._backlog_max = 8
    store = RestObjectStore(url)
    seen = set()
    store.watch(lambda ev: seen.add((ev.type,
                                     ev.obj["metadata"]["name"])))
    time.sleep(0.5)
    for i in range(40):
        backing.create(mkpod(f"burst-{i}"))
    ok = wait_for(
        lambda: all(("ADDED", f"burst-{i}") in seen for i in range(40)),
        20.0)
    store.close()
    missing = [i for i in range(40) if ("ADDED", f"burst-{i}") not in seen]
    assert ok, f"never saw ADDED for: {missing}"


# ---------------------------------------------------------------------------
# auth + TLS
# ---------------------------------------------------------------------------

def test_bearer_auth_enforced_and_watch_authed():
    backing = ObjectStore()
    srv, url = serve_background(backing, token="sekrit")
    try:
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(f"{url}/api/v1/namespaces/default/pods")
        assert exc.value.code == 401
        # healthz stays open for probes.
        assert urllib.request.urlopen(f"{url}/healthz").status == 200

        store = RestObjectStore(url, token="sekrit")
        store.create(mkpod("authed"))
        assert store.get("Pod", "authed")["metadata"]["name"] == "authed"
        got = []
        store.watch(lambda ev: got.append(ev.obj["metadata"]["name"]))
        time.sleep(0.5)
        backing.create(mkpod("w2"))
        assert wait_for(lambda: "w2" in got, 5.0)
        store.close()

        bad = RestObjectStore(url, token="wrong")
        from kuberay_tpu.controlplane.store import StoreError
        with pytest.raises(StoreError):
            bad.get("Pod", "authed")
    finally:
        srv.shutdown()


@pytest.fixture
def tls_material(tmp_path):
    key = tmp_path / "tls.key"
    crt = tmp_path / "tls.crt"
    subprocess.run(
        ["openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
         "-keyout", str(key), "-out", str(crt), "-days", "1",
         "-subj", "/CN=localhost",
         "-addext", "subjectAltName=DNS:localhost,IP:127.0.0.1"],
        check=True, capture_output=True)
    return str(crt), str(key)


def test_tls_with_bearer_token(tls_material):
    """kubeconfig-style client credentials: https + CA bundle + token."""
    crt, key = tls_material
    backing = ObjectStore()
    srv, url = serve_background(backing, token="tok",
                                certfile=crt, keyfile=key)
    assert url.startswith("https://")
    try:
        store = RestObjectStore(url, token="tok", ca_cert=crt)
        store.create(mkpod("secure"))
        assert store.get("Pod", "secure")["metadata"]["name"] == "secure"
        got = []
        store.watch(lambda ev: got.append(ev.obj["metadata"]["name"]))
        time.sleep(0.5)
        backing.create(mkpod("tls-watched"))
        assert wait_for(lambda: "tls-watched" in got, 5.0)
        store.close()
    finally:
        srv.shutdown()


# ---------------------------------------------------------------------------
# operator e2e over the authenticated protocol
# ---------------------------------------------------------------------------

def test_operator_reconciles_over_authed_k8s_protocol(tls_material):
    """The 'real kube-apiserver seam' e2e: operator + RestObjectStore
    with kubeconfig-style credentials (https + CA bundle + bearer) over
    the K8s watch protocol, create -> slices ready -> scale -> delete."""
    crt, key = tls_material
    backing = ObjectStore()
    srv, url = serve_background(backing, token="op-token",
                                certfile=crt, keyfile=key)
    kubelet = FakeKubelet(backing)
    stop = threading.Event()

    def kubelet_loop():
        while not stop.is_set():
            kubelet.step()
            stop.wait(0.05)

    threading.Thread(target=kubelet_loop, daemon=True).start()

    rest = RestObjectStore(url, token="op-token", ca_cert=crt,
                           poll_interval=0.1)
    op = Operator(OperatorConfiguration(reconcileConcurrency=2),
                  store=rest, client_provider=lambda s: FakeCoordinatorClient())
    op.start(api_port=0)
    try:
        rest.create(make_cluster(name="sealed", accelerator="v5p",
                                 topology="2x2x2", replicas=1).to_dict())
        assert wait_for(lambda: rest.get(C.KIND_CLUSTER, "sealed")
                        .get("status", {}).get("state") == "ready"), \
            "cluster never ready over authed protocol"
        assert len(backing.list("Pod")) == 3       # head + 2-host slice

        # Scale to 2 slices through the API.
        cur = rest.get(C.KIND_CLUSTER, "sealed")
        cur["spec"]["workerGroupSpecs"][0]["replicas"] = 2
        rest.update(cur)
        assert wait_for(lambda: len(backing.list("Pod")) == 5)

        rest.delete(C.KIND_CLUSTER, "sealed")
        assert wait_for(lambda: backing.list("Pod") == [])
    finally:
        op.stop()
        rest.close()
        stop.set()
        srv.shutdown()
