"""Stateful session serving gate (gateway + kv_tiers, docs/kv-tiers.md).

The resume contract through the gateway: a request carrying a
``session`` id sticks to its last backend and resumes its KV chain from
the tier hierarchy instead of re-prefilling; when the chain lives on a
peer, the fleet index directs a fleet fetch; when the owning replica
evicted it, the advert channel UNLEARNS the index so a stale entry can
never direct a fetch at a dead block (the PR's regression gate); and
the whole resume decomposes into session-lookup / (fleet-fetch) /
tier-fetch / decode spans under one serve-request root at
/debug/traces?tree=1.
"""

import json
import time
import urllib.request

import jax
import pytest

from kuberay_tpu.controlplane.store import ObjectStore
from kuberay_tpu.models import llama
from kuberay_tpu.obs import Tracer, span_tree
from kuberay_tpu.serve.gateway import GatewayConfig, WeightedGateway
from kuberay_tpu.serve.paged_engine import PagedServeEngine
from kuberay_tpu.serve.prefix import block_hashes
from kuberay_tpu.serve.server import ServeFrontend
from kuberay_tpu.utils.metrics import MetricsRegistry

CFG = llama.CONFIGS["llama_tiny"]
BS = 8
PROMPT = list(range(1, 25))                      # 3 full blocks, in-vocab


@pytest.fixture(scope="module")
def params():
    return llama.init_params(CFG, jax.random.PRNGKey(0))


def _route(store, weights, name="sess-route"):
    store.create({
        "apiVersion": "tpu.dev/v1", "kind": "TrafficRoute",
        "metadata": {"name": name, "namespace": "default"},
        "spec": {"backends": [
            {"service": svc, "weight": w} for svc, w in weights.items()]},
        "status": {},
    })


def _set_weights(store, weights, name="sess-route"):
    obj = store.get("TrafficRoute", name)
    obj["spec"]["backends"] = [
        {"service": svc, "weight": w} for svc, w in weights.items()]
    store.update(obj)
    time.sleep(0.25)                             # route watch refresh


class _Fleet:
    """N tiered replicas behind one gateway, all sharing one tracer."""

    def __init__(self, params, services, tracer=None, metrics=None,
                 host_blocks=64, weights=None):
        self.tracer = tracer
        self.engines, self.frontends, self.servers, self.urls = {}, {}, {}, {}
        for svc in services:
            eng = PagedServeEngine(CFG, params, max_slots=2, max_len=64,
                                   block_size=BS, host_blocks=host_blocks,
                                   tracer=tracer)
            fe = ServeFrontend(eng, max_queue=8)
            srv, url = fe.serve_background()
            self.engines[svc], self.frontends[svc] = eng, fe
            self.servers[svc], self.urls[svc] = srv, url
        self.store = ObjectStore()
        _route(self.store, weights or {svc: 1 for svc in services})
        self.gateway = WeightedGateway(
            self.store, "sess-route", resolver=lambda s: self.urls[s],
            poll_interval=0.05, tracer=tracer, metrics=metrics,
            config=GatewayConfig(block_size=BS))
        time.sleep(0.1)                          # first route poll

    def turn(self, prompt, sid, max_tokens=4):
        body = json.dumps({"prompt_tokens": list(prompt),
                           "max_tokens": max_tokens, "temperature": 0.0,
                           "session": sid}).encode()
        code, payload, headers = self.gateway.forward_ex(
            "/v1/completions", body, 120.0)
        return code, json.loads(payload), headers

    def drain_pump(self, svc):
        self.frontends[svc].call_engine(
            lambda e: e._pump_demotions(limit=1 << 20))

    def evict_device(self, svc):
        """Cannibalize every cached device block with in-vocab junk
        posted straight to the replica (the gateway never sees it)."""
        eng = self.engines[svc]
        plen = (eng.max_blocks - 1) * BS
        rounds = eng.num_blocks // (eng.max_blocks - 1) + 1
        for j in range(rounds):
            toks = [(30 + j * plen + i) % 231 + 25 for i in range(plen)]
            req = urllib.request.Request(
                self.urls[svc] + "/v1/completions",
                data=json.dumps({"prompt_tokens": toks,
                                 "max_tokens": 1}).encode(),
                headers={"Content-Type": "application/json"})
            urllib.request.urlopen(req, timeout=60).read()

    def prefill_tokens(self, svc):
        st = self.frontends[svc].call_engine(lambda e: dict(e.stats))
        return st["prefix_query_tokens"] - st["prefix_hit_tokens"]

    def close(self):
        self.gateway.close()
        for svc in self.servers:
            self.servers[svc].shutdown()
            self.frontends[svc].close()


# ---------------------------------------------------------------------------
# resume + stickiness
# ---------------------------------------------------------------------------

@pytest.mark.timeout(300)
def test_session_resume_sticks_and_skips_prefill(params):
    fleet = _Fleet(params, ["replica-0"])
    try:
        code, doc, _ = fleet.turn(PROMPT, "s1")
        assert code == 200
        stats = fleet.gateway.session_stats()
        assert stats["sessions"] == 1 and stats["session_resumes"] == 0
        fleet.drain_pump("replica-0")

        turn2 = PROMPT + doc["tokens"] + list(range(30, 38))
        p0 = fleet.prefill_tokens("replica-0")
        code, _, _ = fleet.turn(turn2, "s1")
        assert code == 200
        stats = fleet.gateway.session_stats()
        assert stats["session_resumes"] == 1
        # The chain covers prompt + response: turn 2 re-prefilled only
        # the unseen tail, never the whole conversation.
        assert fleet.prefill_tokens("replica-0") - p0 < len(turn2) - BS
    finally:
        fleet.close()


@pytest.mark.timeout(300)
def test_session_resume_promotes_from_host_tier(params):
    """Device eviction between turns: the resume is served by host-tier
    promotion (tier_fetch_blocks moves), not a full re-prefill."""
    fleet = _Fleet(params, ["replica-0"])
    try:
        code, doc, _ = fleet.turn(PROMPT, "s1")
        assert code == 200
        fleet.drain_pump("replica-0")
        fleet.evict_device("replica-0")
        eng = fleet.engines["replica-0"]
        assert fleet.frontends["replica-0"].call_engine(
            lambda e: e.resident_prefix_blocks(PROMPT)) == 0

        fetched0 = fleet.frontends["replica-0"].call_engine(
            lambda e: e.tier_fetch_blocks)
        turn2 = PROMPT + doc["tokens"] + list(range(30, 38))
        code, _, _ = fleet.turn(turn2, "s1")
        assert code == 200
        fetched = fleet.frontends["replica-0"].call_engine(
            lambda e: e.tier_fetch_blocks)
        assert fetched - fetched0 >= 3
    finally:
        fleet.close()


# ---------------------------------------------------------------------------
# fleet fetch from a peer
# ---------------------------------------------------------------------------

@pytest.mark.timeout(300)
def test_session_fleet_fetch_from_peer(params):
    """The session's backend drains out of the route: the resume lands
    on the peer, which fleet-fetches the chain from the replica the
    residency index names instead of recomputing it."""
    tracer = Tracer(max_spans=8192)
    metrics = MetricsRegistry()
    fleet = _Fleet(params, ["replica-a", "replica-b"], tracer=tracer,
                   metrics=metrics, weights={"replica-a": 1,
                                             "replica-b": 0})
    try:
        code, doc, _ = fleet.turn(PROMPT, "s1")
        assert code == 200
        fleet.drain_pump("replica-a")
        # One more request so the gateway observes replica-a's advert
        # cursor and syncs the fleet index.
        assert fleet.turn([1, 2, 3], "warm")[0] == 200

        _set_weights(fleet.store, {"replica-a": 0, "replica-b": 1})
        turn2 = PROMPT + doc["tokens"] + list(range(30, 38))
        p0 = fleet.prefill_tokens("replica-b")
        code, _, hdrs = fleet.turn(turn2, "s1")
        assert code == 200
        trace_id = hdrs["traceparent"].split("-")[1]
        spans = {s["name"]: s for s in tracer.export(trace_id)}
        ff = spans.get("fleet-fetch")
        assert ff is not None and ff["attrs"]["blocks_sent"] >= 3
        assert ff["attrs"]["src"] == "replica-a"
        assert ff["attrs"]["dst"] == "replica-b"
        # The shipped chain covered the conversation so far; only the
        # unseen tail prefilled on the peer.
        assert fleet.prefill_tokens("replica-b") - p0 < len(turn2) - BS
        assert "tpu_kv_fleet_fetch_blocks_total" in metrics.render()
    finally:
        fleet.close()


# ---------------------------------------------------------------------------
# the regression gate: eviction unlearns the index, no stale fleet fetch
# ---------------------------------------------------------------------------

@pytest.mark.timeout(300)
def test_evicted_blocks_cannot_attract_a_fleet_fetch(params):
    """Satellite #1: once the owning replica evicts a chain from every
    tier and adverts the deletions, the fleet index forgets it — a
    resume elsewhere recomputes (no fleet-fetch span, no transfer
    attempt at dead blocks) and still succeeds."""
    tracer = Tracer(max_spans=8192)
    metrics = MetricsRegistry()
    # Host tier sized below the junk working set, so the junk fill
    # naturally evicts the session chain from host as well as device.
    fleet = _Fleet(params, ["replica-a", "replica-b"], tracer=tracer,
                   metrics=metrics, host_blocks=8,
                   weights={"replica-a": 1, "replica-b": 0})
    try:
        code, doc, _ = fleet.turn(PROMPT, "s1")
        assert code == 200
        fleet.drain_pump("replica-a")
        assert fleet.turn([1, 2, 3], "warm")[0] == 200   # index learns a

        chain = block_hashes(PROMPT + doc["tokens"], BS)
        # The fill evicts the chain from device AND pressures it out of
        # the 8-block host tier; the pump demotes junk over it.
        fleet.evict_device("replica-a")
        fleet.drain_pump("replica-a")
        resident = fleet.frontends["replica-a"].call_engine(
            lambda e: [e.tiers.tier_of(h) for h in chain])
        assert set(resident) == {None}, resident
        # Another request to replica-a relays the advert deltas: the
        # deletions UNLEARN the fleet index (and the affinity shadow).
        assert fleet.turn([1, 2, 3, 4], "warm2")[0] == 200

        _set_weights(fleet.store, {"replica-a": 0, "replica-b": 1})
        turn2 = PROMPT + doc["tokens"] + list(range(30, 38))
        code, _, hdrs = fleet.turn(turn2, "s1")
        assert code == 200                       # resume still works...
        trace_id = hdrs["traceparent"].split("-")[1]
        names = {s["name"] for s in tracer.export(trace_id)}
        assert "fleet-fetch" not in names, (
            "stale index entry directed a fleet fetch at evicted blocks")
        assert "tpu_kv_index_invalidations_total" in metrics.render()
    finally:
        fleet.close()


# ---------------------------------------------------------------------------
# acceptance: the resume trace decomposes under one root
# ---------------------------------------------------------------------------

@pytest.mark.timeout(300)
def test_resume_trace_tree_at_debug_endpoint(params):
    """One trace id on the resume response resolves, at
    /debug/traces?tree=1, to a single serve-request root whose children
    decompose the resume: session-lookup, the forward hop, and the
    engine-side tier-fetch + decode spans."""
    from kuberay_tpu.apiserver.server import serve_background

    tracer = Tracer(max_spans=8192)
    fleet = _Fleet(params, ["replica-0"], tracer=tracer)
    api_srv = api_url = None
    try:
        code, doc, _ = fleet.turn(PROMPT, "s1")
        assert code == 200
        fleet.drain_pump("replica-0")
        fleet.evict_device("replica-0")

        turn2 = PROMPT + doc["tokens"] + list(range(30, 38))
        code, _, hdrs = fleet.turn(turn2, "s1")
        assert code == 200
        trace_id = hdrs["traceparent"].split("-")[1]

        api_srv, api_url = serve_background(ObjectStore(), tracer=tracer)
        with urllib.request.urlopen(
                f"{api_url}/debug/traces?tree=1&trace_id={trace_id}",
                timeout=30) as resp:
            trees = json.load(resp)["traces"]
        assert len(trees) == 1
        root = trees[0]
        assert root["name"] == "serve-request"
        children = {c["name"] for c in root["children"]}
        assert {"session-lookup", "forward", "tier-fetch",
                "prefill", "decode"} <= children, sorted(children)
        # Every span of the resume lives under the one root.
        assert all(not c["children"] for c in root["children"])
    finally:
        if api_srv is not None:
            api_srv.shutdown()
        fleet.close()
