"""Speculative decoding (prompt-lookup drafts, greedy acceptance)."""

import jax
import numpy as np
import pytest

from kuberay_tpu.models.llama import CONFIGS, init_params
from kuberay_tpu.serve.engine import (
    Request,
    ServeEngine,
    prompt_lookup_draft,
)

CFG = CONFIGS["llama_tiny"]
PARAMS = init_params(CFG, jax.random.PRNGKey(0))


def make_engine(**kw):
    kw.setdefault("max_slots", 4)
    kw.setdefault("max_len", 128)
    return ServeEngine(CFG, PARAMS, **kw)


# -- drafting ---------------------------------------------------------------

def test_prompt_lookup_finds_repeats():
    hist = [1, 2, 3, 9, 9, 1, 2, 3]
    assert prompt_lookup_draft(hist, 3) == [9, 9, 1]


def test_prompt_lookup_prefers_longer_ngram_and_latest_match():
    hist = [5, 1, 2, 7, 7, 1, 2, 8, 8, 1, 2]
    # Trigram [8, 1, 2] has no earlier occurrence; bigram [1, 2] matches
    # latest at index 5 -> continuation [8, 8, 1].
    assert prompt_lookup_draft(hist, 3) == [8, 8, 1]


def test_prompt_lookup_no_match():
    assert prompt_lookup_draft([1, 2, 3, 4], 3) == []
    assert prompt_lookup_draft([1], 3) == []


def test_ngram_index_matches_reference_scan():
    """The incremental index must produce the same draft as the O(L)
    reference scan at every history length, including across incremental
    extends."""
    from kuberay_tpu.serve.engine import NgramIndex
    rng = np.random.default_rng(11)
    hist = rng.integers(1, 6, size=200).tolist()   # small alphabet: repeats
    idx = NgramIndex()
    for upto in range(2, len(hist) + 1):
        h = hist[:upto]
        idx.extend(h)
        assert idx.draft(h, 4) == prompt_lookup_draft(h, 4), upto


# -- exactness --------------------------------------------------------------

def repetitive_prompts():
    """Prompts with internal repeats (drafts will hit) + random ones."""
    rng = np.random.default_rng(7)
    rep = ([3, 4, 5, 6] * 6)[:20]
    rnd = rng.integers(1, CFG.vocab_size, size=15).tolist()
    return [rep, rnd, rep[::-1] + rep, [9, 9, 9, 9, 9, 9]]


def run_all(engine, temp=0.0, n=24):
    for i, p in enumerate(repetitive_prompts()):
        engine.add_request(Request(f"r{i}", p, max_new_tokens=n,
                                   temperature=temp))
    out = engine.run()
    return {r.request_id: (r.tokens, r.finish_reason) for r in out}


def test_speculative_outputs_exactly_match_sequential():
    want = run_all(make_engine())
    got = run_all(make_engine(speculative=4))
    assert got == want


def test_speculation_actually_accepts():
    eng = make_engine(speculative=4)
    run_all(eng)
    assert eng.spec_stats["verify_steps"] > 0
    assert eng.spec_stats["accepted"] > 0
    # Fewer engine iterations than emitted tokens proves multi-emit.
    total = eng.spec_stats["accepted"] + eng.spec_stats["drafted"]
    assert eng.spec_stats["accepted"] <= eng.spec_stats["drafted"] <= total


def test_sampling_slots_never_draft():
    eng = make_engine(speculative=4)
    run_all(eng, temp=0.9)
    assert eng.spec_stats["drafted"] == 0


def test_eos_respected_mid_acceptance():
    """An eos token inside an accepted draft must end the request there,
    exactly as sequential decode would."""
    eng_seq = make_engine()
    eng_spec = make_engine(speculative=4)
    prompt = [3, 4, 5, 6] * 5
    # Use whatever sequential decode emits 3rd as the eos token.
    probe = make_engine()
    probe.add_request(Request("p", list(prompt), max_new_tokens=10))
    third = probe.run()[0].tokens[2]
    outs = {}
    for name, eng in (("seq", eng_seq), ("spec", eng_spec)):
        eng.add_request(Request("x", list(prompt), max_new_tokens=10,
                                eos_token=int(third)))
        outs[name] = [(r.tokens, r.finish_reason) for r in eng.run()]
    assert outs["seq"] == outs["spec"]


def test_speculative_with_chunked_prefill_compose():
    want = run_all(make_engine())
    got = run_all(make_engine(speculative=4, prefill_chunk=8))
    assert got == want


def test_fewer_device_steps_with_speculation():
    """On a pathologically repetitive prompt, speculation must finish in
    materially fewer engine steps than sequential decode."""
    def count_steps(engine):
        engine.add_request(Request("r", [5, 6] * 8, max_new_tokens=32))
        steps = 0
        while engine.has_work():
            engine.step()
            steps += 1
        return steps
    seq = count_steps(make_engine())
    spec = count_steps(make_engine(speculative=4))
    assert spec < seq


def test_ngram_index_prunes_out_of_window_entries():
    """Index memory is bounded by the lookup window, not the full
    history (ADVICE r2): out-of-window entries are evicted on the
    amortized prune pass, and drafting semantics are unchanged."""
    from kuberay_tpu.serve.engine import NgramIndex, prompt_lookup_draft

    idx = NgramIndex(ngram=3, window=256)
    hist = [(i * 7 + i // 5) % 50 for i in range(4096)]   # varied tokens
    idx.extend(hist)
    for n, m in idx.maps.items():
        stale = [k for k in m.values() if k < len(hist) - 256 - 1024]
        # Everything older than window + one prune period is gone.
        assert not stale, (n, len(stale))
    assert idx.draft(hist, 4) == prompt_lookup_draft(hist, 4, window=256)


def test_verify_gated_on_drafting_fraction():
    """One repetitive request among many must not route the whole batch
    through the (γ+1)-token verify forward (ADVICE r2 batch-level
    amplification): below SPEC_MIN_DRAFT_FRACTION the engine decodes
    normally and the drafts are discarded."""
    cfg = CONFIGS["llama_tiny"]
    params = init_params(cfg, jax.random.PRNGKey(0))

    def run_with_drafts(drafting_slots):
        eng = ServeEngine(cfg, params, max_slots=5, max_len=256,
                          speculative=4)
        for i in range(5):
            eng.add_request(Request(f"r{i}", list(range(3 + i, 13 + i)),
                                    max_new_tokens=4))
        # Deterministic drafts (real drafting depends on the random
        # model's repetition): the named slots always draft, others never.
        eng._build_drafts = lambda: [
            [1, 2] if i in drafting_slots else [] for i in range(5)]
        eng.run()
        return eng.spec_stats["verify_steps"]

    # 1/5 = 0.2 < 0.25: gated — normal decode, drafts discarded.
    assert run_with_drafts({0}) == 0
    # 2/5 = 0.4 >= 0.25: verify path runs.
    assert run_with_drafts({0, 3}) > 0


def test_paged_speculative_exact_and_capacity_capped():
    """Speculative decoding over the block-table path: outputs exactly
    match sequential paged decoding; drafts never write past a slot's
    allocated blocks (a position beyond the table tail would alias
    another request's physical block), and a near-full pool only shrinks
    drafts, never corrupts."""
    from kuberay_tpu.serve.paged_engine import PagedServeEngine

    cfg = CONFIGS["llama_tiny"]
    params = init_params(cfg, jax.random.PRNGKey(0))
    prompts = [[7, 8, 9] * 12, [4, 5] * 10, list(range(20))]

    def run(**kw):
        eng = PagedServeEngine(cfg, params, max_slots=3, max_len=128,
                               block_size=8, **kw)
        for i, p in enumerate(prompts):
            eng.add_request(Request(f"r{i}", p, max_new_tokens=16))
        return {r.request_id: r.tokens for r in eng.run()}, eng

    base, _ = run()
    spec, eng = run(speculative=4)
    assert base == spec
    assert eng.spec_stats["verify_steps"] > 0
    assert eng.spec_stats["accepted"] > 0
    # Pool sized with no draft headroom: capacity cap shrinks drafts
    # instead of corrupting shared blocks; outputs stay exact.
    tiny, _ = run(num_blocks=18, speculative=4)
    tiny_base, _ = run(num_blocks=18)
    assert tiny == tiny_base


def test_draft_headroom_released_when_slot_backs_off():
    """A slot that becomes draft-ineligible (spec-miss backoff or
    sampling) must return its idle draft-headroom blocks to the pool
    instead of hoarding them until it finishes."""
    from kuberay_tpu.serve.paged_engine import PagedServeEngine

    cfg = CONFIGS["llama_tiny"]
    params = init_params(cfg, jax.random.PRNGKey(0))
    eng = PagedServeEngine(cfg, params, max_slots=2, max_len=128,
                           block_size=8, speculative=4, num_blocks=32)
    eng.add_request(Request("r0", list(range(20)), max_new_tokens=32))
    # One step admits the request and grows best-effort draft headroom.
    eng.step()
    headroom = len(eng.owned[0]) * eng.block_size - int(eng.lens[0]) - 1
    assert headroom >= 1, "precondition: slot acquired draft headroom"
    free_before = eng.allocator.num_free
    # Force the slot into spec-miss backoff; the next pass must shed the
    # now-idle headroom blocks.
    eng._spec_miss[0] = eng.SPEC_MISS_LIMIT
    eng._decode_all()
    assert len(eng.owned[0]) == eng._blocks_needed(int(eng.lens[0]) + 1)
    assert eng.allocator.num_free > free_before
    # Table tail cleared for the dropped blocks.
    assert all(eng.tables[0, len(eng.owned[0]):] == 0)
