"""Kernel correctness: Pallas (interpret mode on CPU) vs XLA reference."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kuberay_tpu.ops.attention import attention_xla, flash_attention
from kuberay_tpu.ops.rmsnorm import rmsnorm, rmsnorm_xla
from kuberay_tpu.ops.rope import apply_rope, rope_frequencies


def test_rmsnorm_matches_reference():
    x = jax.random.normal(jax.random.PRNGKey(0), (4, 32, 128))
    w = jax.random.normal(jax.random.PRNGKey(1), (128,)) * 0.1 + 1.0
    np.testing.assert_allclose(rmsnorm(x, w), rmsnorm_xla(x, w), rtol=1e-5)


def test_rmsnorm_grad():
    x = jax.random.normal(jax.random.PRNGKey(0), (8, 64))
    w = jnp.ones((64,))
    g1 = jax.grad(lambda x: rmsnorm(x, w).sum())(x)
    g2 = jax.grad(lambda x: rmsnorm_xla(x, w).sum())(x)
    np.testing.assert_allclose(g1, g2, rtol=1e-5, atol=1e-6)


def test_rope_rotation_preserves_norm():
    cos, sin = rope_frequencies(64, 128)
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 16, 4, 64))
    y = apply_rope(x, cos, sin)
    # Rotation preserves the norm of each (x1[i], x2[i]) pair.
    x1, x2 = jnp.split(x, 2, -1)
    y1, y2 = jnp.split(y, 2, -1)
    np.testing.assert_allclose(
        jnp.sqrt(x1 ** 2 + x2 ** 2), jnp.sqrt(y1 ** 2 + y2 ** 2),
        rtol=1e-4, atol=1e-5)


def test_rope_position_zero_is_identity():
    cos, sin = rope_frequencies(32, 8)
    x = jax.random.normal(jax.random.PRNGKey(0), (1, 8, 2, 32))
    y = apply_rope(x, cos, sin)
    np.testing.assert_allclose(y[:, 0], x[:, 0], rtol=1e-6)


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("gqa", [1, 2])
def test_flash_attention_forward(causal, gqa):
    key = jax.random.PRNGKey(0)
    B, S, H, D = 2, 64, 4, 32
    q = jax.random.normal(key, (B, S, H, D), dtype=jnp.float32)
    k = jax.random.normal(jax.random.PRNGKey(1), (B, S, H // gqa, D))
    v = jax.random.normal(jax.random.PRNGKey(2), (B, S, H // gqa, D))
    ref = attention_xla(q, k, v, causal=causal)
    got = flash_attention(q, k, v, causal=causal, impl="pallas_interpret")
    np.testing.assert_allclose(got, ref, rtol=2e-3, atol=2e-3)


@pytest.mark.parametrize("gqa", [1, 2])
def test_flash_attention_backward(gqa):
    key = jax.random.PRNGKey(3)
    B, S, H, D = 1, 32, 2, 16
    q = jax.random.normal(key, (B, S, H, D))
    k = jax.random.normal(jax.random.PRNGKey(4), (B, S, H // gqa, D))
    v = jax.random.normal(jax.random.PRNGKey(5), (B, S, H // gqa, D))

    def f_ref(q, k, v):
        return (attention_xla(q, k, v, causal=True) ** 2).sum()

    def f_pallas(q, k, v):
        return (flash_attention(q, k, v, causal=True,
                                impl="pallas_interpret") ** 2).sum()

    g_ref = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    g_pal = jax.grad(f_pallas, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_pal, g_ref):
        np.testing.assert_allclose(a, b, rtol=5e-3, atol=5e-3)


def test_flash_attention_kv_cache_offset():
    """Decode-style cross-length attention: Sq < Skv, causal offset."""
    B, Sq, Skv, H, D = 1, 8, 32, 2, 16
    q = jax.random.normal(jax.random.PRNGKey(0), (B, Sq, H, D))
    k = jax.random.normal(jax.random.PRNGKey(1), (B, Skv, H, D))
    v = jax.random.normal(jax.random.PRNGKey(2), (B, Skv, H, D))
    ref = attention_xla(q, k, v, causal=True)
    got = flash_attention(q, k, v, causal=True, impl="pallas_interpret")
    np.testing.assert_allclose(got, ref, rtol=2e-3, atol=2e-3)


def test_flash_attention_ragged_seq_falls_back():
    """Non-tiling lengths must produce correct output (XLA fallback), not
    silently-unwritten rows."""
    B, S, H, D = 1, 17, 2, 16
    q = jax.random.normal(jax.random.PRNGKey(0), (B, S, H, D))
    k = jax.random.normal(jax.random.PRNGKey(1), (B, S, H, D))
    v = jax.random.normal(jax.random.PRNGKey(2), (B, S, H, D))
    ref = attention_xla(q, k, v, causal=True)
    got = flash_attention(q, k, v, causal=True, impl="pallas_interpret")
    np.testing.assert_allclose(got, ref, rtol=2e-3, atol=2e-3)


def test_flash_attention_bad_gqa():
    q = jnp.zeros((1, 8, 3, 16))
    k = jnp.zeros((1, 8, 2, 16))
    with pytest.raises(ValueError):
        flash_attention(q, k, q, impl="xla")
