"""Warm slice pools + admission webhooks."""

import json
import urllib.request

import pytest

from kuberay_tpu.controlplane.fake_kubelet import FakeKubelet
from kuberay_tpu.controlplane.store import ObjectStore
from kuberay_tpu.controlplane.warmpool_controller import (
    KIND_WARM_POOL,
    LABEL_WARM_CLAIMED,
    LABEL_WARM_POOL,
    WarmSlicePoolController,
)
from kuberay_tpu.controlplane.webhooks import (
    WebhookServer,
    review_response,
    validate_admission,
)
from kuberay_tpu.utils import constants as C
from kuberay_tpu.utils import features
from tests.test_api_types import make_cluster


@pytest.fixture(autouse=True)
def gates():
    features.reset()
    features.set_gates({"WarmSlicePools": True})
    yield
    features.reset()


def make_pool(store, size=2):
    store.create({
        "apiVersion": C.API_VERSION, "kind": KIND_WARM_POOL,
        "metadata": {"name": "pool1", "namespace": "default"},
        "spec": {"accelerator": "v5p", "topology": "2x2x2",
                 "poolSize": size,
                 "template": {"spec": {"containers": [
                     {"name": "w", "image": "rt:warm"}]}}},
    })


def test_pool_maintains_warm_slices():
    store = ObjectStore()
    kubelet = FakeKubelet(store)
    ctrl = WarmSlicePoolController(store)
    make_pool(store, size=2)
    ctrl.reconcile("pool1")
    pods = store.list("Pod", labels={LABEL_WARM_POOL: "pool1"})
    assert len(pods) == 4   # 2 slices x 2 hosts
    kubelet.step()
    ctrl.reconcile("pool1")
    st = store.get(KIND_WARM_POOL, "pool1")["status"]
    assert st == {"warmSlices": 2, "readySlices": 2, "hostsPerSlice": 2}
    # Warm pods carry full TPU env but no cluster identity.
    env = {e["name"]: e.get("value", "")
           for e in pods[0]["spec"]["containers"][0]["env"]}
    assert env[C.ENV_TPU_TOPOLOGY] == "2x2x2"
    assert C.LABEL_CLUSTER not in pods[0]["metadata"]["labels"]


def test_pool_replaces_failed_slice():
    store = ObjectStore()
    kubelet = FakeKubelet(store)
    ctrl = WarmSlicePoolController(store)
    make_pool(store, size=1)
    ctrl.reconcile("pool1")
    kubelet.step()
    victim = store.list("Pod", labels={LABEL_WARM_POOL: "pool1"})[0]
    kubelet.fail_pod(victim["metadata"]["name"])
    ctrl.reconcile("pool1")      # deletes the bad slice
    ctrl.reconcile("pool1")      # re-provisions
    kubelet.step()
    ctrl.reconcile("pool1")
    st = store.get(KIND_WARM_POOL, "pool1")["status"]
    assert st["readySlices"] == 1


def test_pool_claim_releases_slice():
    store = ObjectStore()
    kubelet = FakeKubelet(store)
    ctrl = WarmSlicePoolController(store)
    make_pool(store, size=2)
    ctrl.reconcile("pool1")
    kubelet.step()
    names = ctrl.claim("pool1")
    assert names and len(names) == 2
    claimed = store.get("Pod", names[0])
    assert claimed["metadata"]["labels"][LABEL_WARM_CLAIMED] == "true"
    # Pool backfills to poolSize on next pass.
    ctrl.reconcile("pool1")
    unclaimed = [p for p in store.list("Pod", labels={LABEL_WARM_POOL: "pool1"})
                 if not p["metadata"]["labels"].get(LABEL_WARM_CLAIMED)]
    assert len(unclaimed) == 4


def test_concurrent_claims_resolve_to_one_winner():
    """Two claimants racing for a pool of ONE slice (two preemption
    drains firing together) must serialize: exactly one wins the warm
    slice, the loser gets None and cold-provisions."""
    import threading

    store = ObjectStore()
    kubelet = FakeKubelet(store)
    ctrl = WarmSlicePoolController(store)
    make_pool(store, size=1)
    ctrl.reconcile("pool1")
    kubelet.step()
    barrier = threading.Barrier(2)
    results = []

    def grab():
        barrier.wait()
        results.append(ctrl.claim("pool1"))

    threads = [threading.Thread(target=grab) for _ in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wins = [r for r in results if r]
    assert len(wins) == 1
    assert len([r for r in results if r is None]) == 1
    # Every pod of the slice is claimed exactly once.
    claimed = [p for p in store.list("Pod", labels={LABEL_WARM_POOL: "pool1"})
               if p["metadata"]["labels"].get(LABEL_WARM_CLAIMED)]
    assert sorted(p["metadata"]["name"] for p in claimed) == sorted(wins[0])


def test_simultaneous_notices_serialize_on_pool_of_one():
    """End to end: BOTH slices of a cluster get a preemption notice in
    the same instant against a warm pool of one.  The controller must
    adopt the single warm slice for one replacement, cold-provision the
    other, and leave warm-pool accounting (and every other invariant)
    clean after the kills land."""
    from kuberay_tpu.sim.harness import SimHarness
    from kuberay_tpu.sim.scenarios import make_cluster_obj

    with SimHarness(0, fault_profile={}) as h:
        h.store.create(make_cluster_obj(
            "drill", accelerator="v5e", topology="4x4",
            replicas=2, max_replicas=4))
        h.store.create({
            "apiVersion": C.API_VERSION, "kind": KIND_WARM_POOL,
            "metadata": {"name": "reserve", "namespace": "default"},
            "spec": {"accelerator": "v5e", "topology": "4x4",
                     "poolSize": 1},
            "status": {},
        })
        h.settle()
        snames = sorted({
            p["metadata"]["labels"][C.LABEL_SLICE_NAME]
            for p in h.store.list("Pod",
                                  labels={C.LABEL_CLUSTER: "drill"})
            if C.LABEL_SLICE_NAME in p["metadata"]["labels"]})
        assert len(snames) == 2
        for sname in snames:
            h.inject_preemption_notice("default", sname, 40.0)
        h.settle()
        text = h.metrics.registry.render()
        assert 'tpu_warmpool_claims_total{reason="preemption"} 1' in text
        # Past the kills and through recovery: back to strength, clean.
        h.clock.advance_to(h.clock.now() + 200.0)
        h.settle()
        violations = h.check()
        assert violations == [], [str(v) for v in violations]


def test_pool_gate_off():
    features.reset()
    store = ObjectStore()
    ctrl = WarmSlicePoolController(store)
    make_pool(store)
    ctrl.reconcile("pool1")
    assert store.list("Pod") == []


def test_warmpool_wired_into_operator():
    """Gate on -> the live operator provisions warm slices end-to-end."""
    from kuberay_tpu.api.config import OperatorConfiguration
    from kuberay_tpu.operator import Operator
    op = Operator(OperatorConfiguration(
        featureGates={"WarmSlicePools": True}), fake_kubelet=True)
    try:
        make_pool(op.store, size=1)
        for _ in range(6):
            op.run_until_idle()
        st = op.store.get(KIND_WARM_POOL, "pool1").get("status", {})
        assert st.get("readySlices") == 1
    finally:
        op.stop()


def test_apiserver_update_enforces_immutability():
    """The embedded API path enforces the same rules as the webhook."""
    from kuberay_tpu.apiserver.server import serve_background
    from kuberay_tpu.cli.client import ApiClient, ApiError
    from kuberay_tpu.controlplane.store import ObjectStore
    store = ObjectStore()
    srv, url = serve_background(store)
    try:
        client = ApiClient(url)
        client.create(make_cluster().to_dict())
        obj = client.get("TpuCluster", "demo")
        obj["spec"]["workerGroupSpecs"][0]["groupName"] = "renamed"
        with pytest.raises(ApiError) as exc:
            client.update(obj)
        assert exc.value.code == 422
        assert "renamed" in str(exc.value)
    finally:
        srv.shutdown()


def test_admission_update_immutability():
    old = make_cluster().to_dict()
    new = make_cluster().to_dict()
    assert validate_admission(new, old) == []
    renamed = make_cluster().to_dict()
    renamed["spec"]["workerGroupSpecs"][0]["groupName"] = "renamed"
    errs = validate_admission(renamed, old)
    assert any("cannot be removed or renamed" in e for e in errs)


def test_webhook_server_admission_review():
    srv, url = WebhookServer().serve_background()
    try:
        review = {"request": {"uid": "u1",
                              "object": make_cluster().to_dict()}}
        req = urllib.request.Request(
            f"{url}/validate", data=json.dumps(review).encode(),
            headers={"Content-Type": "application/json"})
        out = json.load(urllib.request.urlopen(req))
        assert out["response"]["allowed"] is True
        assert out["response"]["uid"] == "u1"
        bad = {"request": {"uid": "u2",
                           "object": make_cluster(topology="9x9").to_dict()}}
        req = urllib.request.Request(
            f"{url}/validate", data=json.dumps(bad).encode(),
            headers={"Content-Type": "application/json"})
        out = json.load(urllib.request.urlopen(req))
        assert out["response"]["allowed"] is False
        assert out["response"]["status"]["code"] == 422
    finally:
        srv.shutdown()
