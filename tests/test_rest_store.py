"""The real-cluster seam: a full operator running against a REMOTE API
server over REST (RestObjectStore), no in-memory sharing — controllers,
expectations, and watches all flow through HTTP exactly as they would
against a kube-apiserver fronting the tpu.dev CRDs."""

import threading
import time

import pytest

from kuberay_tpu.api.config import OperatorConfiguration
from kuberay_tpu.apiserver.server import serve_background
from kuberay_tpu.cli.client import ApiClient
from kuberay_tpu.controlplane.fake_kubelet import FakeKubelet
from kuberay_tpu.controlplane.rest_store import RestObjectStore
from kuberay_tpu.controlplane.store import AlreadyExists, Conflict, NotFound, ObjectStore
from kuberay_tpu.operator import Operator
from kuberay_tpu.runtime.coordinator_client import FakeCoordinatorClient
from kuberay_tpu.utils import constants as C
from tests.test_api_types import make_cluster


@pytest.fixture
def remote():
    """The 'cluster side': API server + kubelet over a private store."""
    backing = ObjectStore()
    srv, url = serve_background(backing)
    kubelet = FakeKubelet(backing)
    stop = threading.Event()

    def kubelet_loop():
        while not stop.is_set():
            kubelet.step()
            stop.wait(0.05)

    t = threading.Thread(target=kubelet_loop, daemon=True)
    t.start()
    yield backing, url
    stop.set()
    srv.shutdown()


def wait_for(fn, timeout=30.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if fn():
            return True
        time.sleep(0.1)
    return False


def test_rest_store_verbs(remote):
    backing, url = remote
    store = RestObjectStore(url)
    c = make_cluster(name="verbs").to_dict()
    created = store.create(c)
    assert created["metadata"]["uid"]
    with pytest.raises(AlreadyExists):
        store.create(c)
    got = store.get(C.KIND_CLUSTER, "verbs")
    got["spec"]["workerGroupSpecs"][0]["replicas"] = 0
    store.update(got)
    # Stale update conflicts.
    with pytest.raises(Conflict):
        store.update(got)
    store.patch_labels(C.KIND_CLUSTER, "verbs", "default", {"team": "x"})
    assert store.list(C.KIND_CLUSTER, labels={"team": "x"})
    store.add_finalizer(C.KIND_CLUSTER, "verbs", "default", "t/fin")
    store.delete(C.KIND_CLUSTER, "verbs")
    assert store.get(C.KIND_CLUSTER, "verbs")["metadata"]["deletionTimestamp"]
    store.remove_finalizer(C.KIND_CLUSTER, "verbs", "default", "t/fin")
    assert store.try_get(C.KIND_CLUSTER, "verbs") is None


def test_operator_over_rest_end_to_end(remote):
    backing, url = remote
    coord = FakeCoordinatorClient()
    rest = RestObjectStore(url, poll_interval=0.1)
    op = Operator(OperatorConfiguration(reconcileConcurrency=2),
                  store=rest,
                  client_provider=lambda s: coord)
    op.start(api_port=0)
    try:
        # Create through the REMOTE api server (like any external client).
        remote_client = ApiClient(url)
        remote_client.create(make_cluster(
            name="restful", accelerator="v5p", topology="2x2x2",
            replicas=1).to_dict())
        assert wait_for(lambda: remote_client.get(
            C.KIND_CLUSTER, "restful").get("status", {}).get("state")
            == "ready"), "cluster never became ready over REST"
        pods = backing.list("Pod")
        assert len(pods) == 3      # head + 2-host slice, created via REST
        env = {e["name"]: e.get("value", "")
               for e in pods[1]["spec"]["containers"][0]["env"]
               if "value" in e}
        assert env.get(C.ENV_TPU_TOPOLOGY) == "2x2x2"
        # Slice repair across the wire: fail a host on the REMOTE side.
        workers = [p for p in pods if p["metadata"]["labels"].get(
            C.LABEL_NODE_TYPE) == "worker"]
        victim = workers[0]["metadata"]["name"]
        pod = backing.get("Pod", victim)
        pod["status"] = {"phase": "Failed"}
        backing.update_status(pod)
        assert wait_for(lambda: all(
            p.get("status", {}).get("phase") == "Running"
            for p in backing.list("Pod", labels={
                C.LABEL_NODE_TYPE: "worker"})) and len(
            backing.list("Pod", labels={C.LABEL_NODE_TYPE: "worker"})) == 2)
        # Deletion cascades server-side.
        remote_client.delete(C.KIND_CLUSTER, "restful")
        assert wait_for(lambda: backing.list("Pod") == [])
    finally:
        op.stop()
        rest.close()


def test_streaming_watch_endpoint(remote):
    """/watch long-poll: immediate event delivery with rv resume."""
    import json
    import urllib.request
    backing, url = remote
    rv0 = json.load(urllib.request.urlopen(
        f"{url}/watch?sinceRv=999999999&timeoutSeconds=0"))["resourceVersion"]
    backing.create({"apiVersion": "v1", "kind": "Pod",
                    "metadata": {"name": "w1", "namespace": "default"},
                    "spec": {}, "status": {}})
    out = json.load(urllib.request.urlopen(
        f"{url}/watch?sinceRv={rv0}&timeoutSeconds=5&kinds=Pod"))
    types = [(e["type"], e["object"]["metadata"]["name"])
             for e in out["events"]]
    assert ("ADDED", "w1") in types
    # Resume from the returned rv: nothing new -> empty after timeout 0.
    out2 = json.load(urllib.request.urlopen(
        f"{url}/watch?sinceRv={out['resourceVersion']}&timeoutSeconds=0"))
    assert out2["events"] == []


def test_rest_store_uses_streaming_watch(remote):
    """The client consumes /watch (no interval latency): events arrive
    well under the polling interval."""
    import time
    backing, url = remote
    store = RestObjectStore(url, poll_interval=5.0)   # polling would be slow
    got = []
    store.watch(lambda ev: got.append((ev.type, ev.kind,
                                       ev.obj["metadata"]["name"])))
    time.sleep(0.3)
    backing.create({"apiVersion": "v1", "kind": "Pod",
                    "metadata": {"name": "fast", "namespace": "default",
                                 # Pod watches are scoped to
                                 # operator-created pods (managercache).
                                 "labels": {C.LABEL_CREATED_BY:
                                            C.CREATED_BY_OPERATOR}},
                    "spec": {}, "status": {}})
    deadline = time.time() + 3.0     # << poll_interval: must be streamed
    while time.time() < deadline:
        if ("ADDED", "Pod", "fast") in got:
            break
        time.sleep(0.05)
    store.close()
    assert ("ADDED", "Pod", "fast") in got


def test_watch_scope_bounds_pod_streams():
    """Scoped informers (ref internal/managercache/cache.go:18): only
    operator-created Pods enter the watch cache — a cluster full of
    foreign workloads must not inflate the operator's memory.  Jobs are
    deliberately unscoped (few, and pre-label Jobs must stay visible);
    explicit list() calls stay unscoped."""
    from kuberay_tpu.apiserver.server import serve_background
    from kuberay_tpu.controlplane.store import ObjectStore
    from kuberay_tpu.utils import constants as C

    store = ObjectStore()
    srv, url = serve_background(store)
    try:
        rs = RestObjectStore(url, watched_kinds=("Pod", "Job"),
                             poll_interval=0.05)
        seen = []
        rs.watch(lambda ev: seen.append(
            (ev.kind, ev.obj["metadata"]["name"])))
        mine = {"kind": "Pod", "metadata": {
            "name": "ours", "namespace": "default",
            "labels": {C.LABEL_CREATED_BY: C.CREATED_BY_OPERATOR}},
            "spec": {}}
        foreign = {"kind": "Pod", "metadata": {
            "name": "theirs", "namespace": "default",
            "labels": {"app": "someone-else"}}, "spec": {}}
        store.create(mine)
        store.create(foreign)
        store.create({"kind": "Job", "metadata": {
            "name": "their-job", "namespace": "default"}, "spec": {}})
        # (Jobs unscoped by design: their-job WILL be seen below.)
        deadline = time.time() + 10
        while time.time() < deadline and ("Pod", "ours") not in seen:
            time.sleep(0.05)
        time.sleep(0.5)          # window for any foreign event to leak
        assert ("Pod", "ours") in seen, seen
        assert ("Pod", "theirs") not in seen, seen
        assert ("Job", "their-job") in seen, seen
        # Direct list() is NOT scoped (controllers pass their own labels).
        assert {p["metadata"]["name"] for p in rs.list("Pod")} == \
            {"ours", "theirs"}
        # Leaving the scope (label stripped) surfaces as DELETED — the
        # kube contract for selector-scoped watches; the cache must not
        # keep a phantom entry.
        before = len(seen)
        store.patch("Pod", "ours", "default",
                    {"metadata": {"labels": {C.LABEL_CREATED_BY: None}}})
        deadline = time.time() + 10
        while time.time() < deadline and len(seen) == before:
            time.sleep(0.05)
        assert seen[before:] == [("Pod", "ours")]
        assert ("Pod", "ours") not in [
            (k[0], k[2]) for k in rs._known], "phantom cache entry"
        # Opt-out restores full streams.
        rs.close()
        rs2 = RestObjectStore(url, watched_kinds=("Pod",),
                              poll_interval=0.05, watch_scope={})
        seen2 = []
        rs2.watch(lambda ev: seen2.append(ev.obj["metadata"]["name"]))
        store.create({"kind": "Pod", "metadata": {
            "name": "theirs-2", "namespace": "default",
            "labels": {"app": "x"}}, "spec": {}})
        deadline = time.time() + 10
        while time.time() < deadline and "theirs-2" not in seen2:
            time.sleep(0.05)
        assert "theirs-2" in seen2
        rs2.close()
    finally:
        srv.shutdown()
