"""Ecosystem: apiserver REST, CLI, operator wiring, metrics, data loader."""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

from kuberay_tpu.api.config import OperatorConfiguration
from kuberay_tpu.cli.client import ApiClient, ApiError
from kuberay_tpu.operator import Operator
from kuberay_tpu.runtime.coordinator_client import FakeCoordinatorClient
from kuberay_tpu.train.data import TokenShardLoader, native_available, synthetic_shard
from kuberay_tpu.utils import constants as C
from kuberay_tpu.utils.metrics import ControlPlaneMetrics
from tests.test_api_types import make_cluster


@pytest.fixture
def op():
    coord = FakeCoordinatorClient()
    operator = Operator(OperatorConfiguration(reconcileConcurrency=2),
                        client_provider=lambda status: coord,
                        fake_kubelet=True)
    operator.coordinator = coord
    url = operator.start(api_port=0)
    yield operator
    operator.stop()


def wait_for(fn, timeout=15.0, interval=0.1):
    deadline = time.time() + timeout
    while time.time() < deadline:
        v = fn()
        if v:
            return v
        time.sleep(interval)
    raise TimeoutError("condition not met")


def test_rest_crud_and_reconcile(op):
    client = ApiClient(op.api_url)
    assert client.healthy()
    manifest = make_cluster(accelerator="v5p", topology="2x2x2",
                            replicas=1).to_dict()
    created = client.create(manifest)
    assert created["metadata"]["uid"]
    # The live operator (threaded) provisions it.
    wait_for(lambda: client.get(C.KIND_CLUSTER, "demo").get(
        "status", {}).get("state") == "ready")
    pods = client.list("Pod")
    assert len(pods) == 3
    # Invalid manifest rejected with 422.
    bad = make_cluster(name="bad", topology="9x9").to_dict()
    with pytest.raises(ApiError) as exc:
        client.create(bad)
    assert exc.value.code == 422
    # Deletion cascades.
    client.delete(C.KIND_CLUSTER, "demo")
    wait_for(lambda: client.list("Pod") == [])


def test_rest_label_selector_and_conflicts(op):
    client = ApiClient(op.api_url)
    c = make_cluster(name="sel")
    c.metadata.labels = {"team": "a"}
    client.create(c.to_dict())
    assert client.list(C.KIND_CLUSTER, label_selector="team=a")
    assert client.list(C.KIND_CLUSTER, label_selector="team=b") == []
    with pytest.raises(ApiError) as exc:
        client.create(c.to_dict())
    assert exc.value.code == 409


def test_metrics_endpoint(op):
    client = ApiClient(op.api_url)
    client.create(make_cluster(name="m1").to_dict())
    wait_for(lambda: client.get(C.KIND_CLUSTER, "m1").get(
        "status", {}).get("state") == "ready")
    import urllib.request
    text = urllib.request.urlopen(op.api_url + "/metrics").read().decode()
    assert "tpu_reconcile_total" in text
    assert "tpu_cluster_provisioned_duration_seconds" in text


def run_cli(op, *argv):
    from kuberay_tpu.cli.__main__ import main
    import io
    from contextlib import redirect_stdout
    buf = io.StringIO()
    with redirect_stdout(buf):
        rc = main(["--server", op.api_url, *argv])
    return rc, buf.getvalue()


def test_cli_create_get_scale_delete(op):
    rc, out = run_cli(op, "create", "cluster", "c1", "--tpu", "v5p",
                      "--topology", "2x2x2", "--slices", "1")
    assert rc == 0 and "created" in out
    wait_for(lambda: ApiClient(op.api_url).get(C.KIND_CLUSTER, "c1").get(
        "status", {}).get("state") == "ready")
    rc, out = run_cli(op, "get", "clusters")
    assert rc == 0 and "c1" in out and "ready" in out
    rc, out = run_cli(op, "get", "slices")
    assert "c1-workers-0" in out and "2/2" in out
    rc, out = run_cli(op, "scale", "c1", "--replicas", "2")
    assert rc == 0
    wait_for(lambda: ApiClient(op.api_url).get(C.KIND_CLUSTER, "c1").get(
        "status", {}).get("readySlices") == 2)
    rc, out = run_cli(op, "delete", "cluster", "c1")
    assert rc == 0


def test_cli_submit_and_wait(op):
    # Job completes when the fake coordinator reports SUCCEEDED.
    def finisher():
        try:
            wait_for(lambda: op.coordinator.jobs, timeout=20)
            for jid in list(op.coordinator.jobs):
                op.coordinator.set_job_status(jid, "SUCCEEDED")
        except TimeoutError:
            pass
    import threading
    t = threading.Thread(target=finisher, daemon=True)
    t.start()
    rc, out = run_cli(op, "submit", "train1", "--tpu", "v5e", "--topology",
                      "2x2", "--mode", "HTTPMode", "--shutdown-after-finish",
                      "--wait", "--", "python", "-m", "kuberay_tpu.train")
    assert rc == 0, out
    assert "Complete" in out


def test_cli_bad_topology_fails_cleanly(op):
    rc, _ = run_cli(op, "create", "cluster", "x", "--tpu", "v5e",
                    "--topology", "3x3")
    assert rc == 1
    # Nothing was created.
    assert ApiClient(op.api_url).list(C.KIND_CLUSTER,
                                      label_selector="") == [] or all(
        i["metadata"]["name"] != "x"
        for i in ApiClient(op.api_url).list(C.KIND_CLUSTER))


def test_invalid_path_404(op):
    import urllib.request, urllib.error
    with pytest.raises(urllib.error.HTTPError) as e:
        urllib.request.urlopen(op.api_url + "/apis/tpu.dev/v1/namespaces/d/nope")
    assert e.value.code == 404


def test_metrics_render_format():
    m = ControlPlaneMetrics()
    m.observe_provisioned("c1", 12.5)
    m.observe_job_duration("j1", "SUCCEEDED", 100.0)
    m.set_cluster_state("c1", "ready")
    text = m.render()
    assert '# TYPE tpu_cluster_provisioned_duration_seconds histogram' in text
    assert 'tpu_cluster_state{cluster="c1",state="ready"} 1.0' in text
    assert 'le="+Inf"' in text
    m.forget_cluster("c1")
    assert 'cluster="c1"' not in m.render()


def test_token_shard_loader(tmp_path):
    shard = tmp_path / "shard.bin"
    synthetic_shard(str(shard), n_tokens=10_000, vocab=1000, seed=7)
    loader = TokenShardLoader(str(shard), seq_len=64, batch=4, seed=1)
    b = loader.next()
    assert b["tokens"].shape == (4, 64)
    assert b["targets"].shape == (4, 64)
    # Next-token alignment.
    np.testing.assert_array_equal(b["tokens"][:, 1:], b["targets"][:, :-1])
    assert loader.num_windows == 10_000 // 65
    loader.close()


def test_native_loader_matches_numpy(tmp_path):
    if not native_available():
        pytest.skip("no C++ toolchain")
    shard = tmp_path / "shard.bin"
    synthetic_shard(str(shard), n_tokens=5_000, vocab=500, seed=3)
    nat = TokenShardLoader(str(shard), seq_len=32, batch=2, seed=9,
                           prefer_native=True, n_threads=1)
    py = TokenShardLoader(str(shard), seq_len=32, batch=2, seed=9,
                          prefer_native=False)
    assert nat.backend == "native" and py.backend == "numpy"
    for _ in range(5):
        np.testing.assert_array_equal(nat.next()["tokens"],
                                      py.next()["tokens"])
    nat.close()


def test_histogram_invariants():
    """Prometheus contract: le="+Inf" cumulative count == _count."""
    m = ControlPlaneMetrics()
    for v in (0.3, 0.3, 7.0, 1000.0):
        m.observe_provisioned("c", v)
    text = m.render()
    inf_line = next(l for l in text.splitlines()
                    if "tpu_cluster_provisioned_duration_seconds_bucket" in l
                    and 'le="+Inf"' in l)
    count_line = next(l for l in text.splitlines()
                      if l.startswith("tpu_cluster_provisioned_duration_seconds_count"))
    assert inf_line.rsplit(" ", 1)[1] == "4"
    assert count_line.rsplit(" ", 1)[1] == "4"
    # le=0.5 bucket holds exactly the two 0.3s.
    half = next(l for l in text.splitlines() if 'le="0.5"' in l)
    assert half.rsplit(" ", 1)[1] == "2"


def test_cli_create_workergroup(op):
    """`tpuctl create workergroup` extends an existing cluster (ref
    `kubectl ray create workergroup`); the controller then provisions
    the new group's slices; `get workergroups` lists both."""
    rc, out = run_cli(op, "create", "cluster", "wg1", "--tpu", "v5p",
                      "--topology", "2x2x2", "--slices", "1")
    assert rc == 0
    wait_for(lambda: ApiClient(op.api_url).get(C.KIND_CLUSTER, "wg1").get(
        "status", {}).get("state") == "ready")
    rc, out = run_cli(op, "create", "workergroup", "inference",
                      "--cluster", "wg1", "--tpu", "v5e",
                      "--topology", "2x2", "--slices", "2")
    assert rc == 0 and "added" in out
    wait_for(lambda: ApiClient(op.api_url).get(C.KIND_CLUSTER, "wg1").get(
        "status", {}).get("readySlices") == 3)
    rc, out = run_cli(op, "get", "workergroups")
    assert rc == 0 and "inference" in out and "workers" in out
    assert "2x2x2" in out and "v5e" in out   # both groups' rows render
    # Duplicate group name refused.
    rc, out = run_cli(op, "create", "workergroup", "inference",
                      "--cluster", "wg1", "--tpu", "v5e",
                      "--topology", "2x2")
    assert rc == 1
    run_cli(op, "delete", "cluster", "wg1")


def test_grafana_dashboards_reference_real_metrics():
    """The canned Grafana dashboards (ref config/grafana/*.json in the
    reference) must only query metric names the code actually exposes —
    a renamed metric must break this test, not the dashboard."""
    import json
    import pathlib
    import re

    import jax

    from kuberay_tpu.models import llama
    from kuberay_tpu.serve.paged_engine import PagedServeEngine
    from kuberay_tpu.serve.server import ServeFrontend

    root = pathlib.Path(__file__).resolve().parent.parent

    # Exposed serve metric names: render /metrics off a live frontend
    # (paged + speculative so pool/spec counters exist).
    cfg = llama.CONFIGS["llama_tiny"]
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    eng = PagedServeEngine(cfg, params, max_slots=2, max_len=64,
                           block_size=8, speculative=2)
    fe = ServeFrontend(eng)
    serve_names = {f"tpu_serve_{k}" for k, v in fe.stats().items()
                   if isinstance(v, (int, float)) and not isinstance(v, bool)}
    fe.close()

    train_names = {"tpu_train_step", "tpu_train_loss",
                   "tpu_train_tokens_per_sec", "tpu_train_step_seconds",
                   "tpu_train_mfu"}   # set in train/launcher.py
    operator_names_src = (root / "kuberay_tpu/utils/metrics.py").read_text()

    for fname, allowed in (
            ("serve_grafana_dashboard.json", serve_names),
            ("train_grafana_dashboard.json", train_names)):
        doc = json.loads((root / "config/grafana" / fname).read_text())
        assert doc["panels"], fname
        for p in doc["panels"]:
            for t in p["targets"]:
                for m in re.findall(r"tpu_[a-z_]+", t["expr"]):
                    base = re.sub(r"_(bucket|sum|count)$", "", m)
                    assert base in allowed, (fname, p["title"], m)

    # Operator dashboard names must appear in the metrics module.
    doc = json.loads(
        (root / "config/grafana/operator-dashboard.json").read_text())
    for p in doc["panels"]:
        for t in p["targets"]:
            for m in re.findall(r"tpu_[a-z_]+", t["expr"]):
                base = re.sub(r"_(bucket|sum|count)$", "", m)
                assert base in operator_names_src, (p["title"], m)


def test_docs_tree_consistent_with_cli_and_nav():
    """Docs drift guards: mkdocs nav entries exist, cross-links resolve,
    and the tpuctl reference documents every real subcommand."""
    import pathlib
    import re

    import yaml

    root = pathlib.Path(__file__).resolve().parent.parent
    nav = yaml.safe_load((root / "mkdocs.yml").read_text())

    def nav_files(node):
        if isinstance(node, str):
            yield node
        elif isinstance(node, list):
            for item in node:
                yield from nav_files(item)
        elif isinstance(node, dict):
            for v in node.values():
                yield from nav_files(v)

    for f in nav_files(nav["nav"]):
        assert (root / "docs" / f).exists(), f"nav entry missing: {f}"

    for doc in (root / "docs").glob("*.md"):
        for target in re.findall(r"\]\(([A-Za-z0-9_.\-]+\.md)(?:#[^)]*)?\)",
                                 doc.read_text()):
            assert (root / "docs" / target).exists(), (doc.name, target)

    # Every CLI subcommand appears in the tpuctl reference.
    import kuberay_tpu.cli.__main__ as cli_main
    src = pathlib.Path(cli_main.__file__).read_text()
    subcommands = set(re.findall(r'add_parser\(\s*"([a-z-]+)"', src))
    # Dynamically registered verbs (for name in (...): add_parser(name)).
    for tup in re.findall(r'for name in \(([^)]*)\):\s*\n\s*'
                          r'sp = sub\.add_parser\(name\)', src):
        subcommands |= set(re.findall(r'"([a-z-]+)"', tup))
    assert {"suspend", "resume"} <= subcommands, subcommands
    ref = (root / "docs/tpuctl.md").read_text()
    for cmd in subcommands:
        assert f"tpuctl {cmd}" in ref or f"`{cmd}`" in ref, \
            f"tpuctl.md does not document {cmd!r}"


def test_tpuctl_create_service():
    """tpuctl create service: one command yields a valid TpuService with
    the serveConfig-to-engine wire prewired (worker pods read engine
    settings from the coordinator)."""
    from kuberay_tpu.apiserver.server import serve_background
    from kuberay_tpu.cli.__main__ import main as tpuctl
    from kuberay_tpu.controlplane.store import ObjectStore
    from kuberay_tpu.utils.validation import validate_service
    from kuberay_tpu.api.tpuservice import TpuService

    store = ObjectStore()
    srv, url = serve_background(store)
    try:
        rc = tpuctl(["--server", url, "create", "service", "chat",
                     "--tpu", "v5e", "--topology", "4x4", "--slices", "1",
                     "--model", "llama3_8b", "--paged",
                     "--checkpoint-dir", "/ckpt"])
        assert rc == 0
        obj = store.get("TpuService", "chat")
        assert validate_service(TpuService.from_dict(obj)) == []
        app = obj["spec"]["serveConfig"]["applications"][0]
        assert app == {"name": "llm", "model": "llama3_8b",
                       "max_len": 2048, "paged": True,
                       "checkpoint_dir": "/ckpt"}
        worker = obj["spec"]["clusterSpec"]["workerGroupSpecs"][0][
            "template"]["spec"]["containers"][0]
        assert "--config-from-coordinator" in worker["args"]
        assert worker["command"][-1] == "kuberay_tpu.serve.server"
    finally:
        srv.shutdown()
