"""TpuCronJob controller tests (ref e2eraycronjob specs)."""

import time

import pytest

from kuberay_tpu.api.common import ObjectMeta
from kuberay_tpu.api.tpucronjob import ConcurrencyPolicy, TpuCronJob, TpuCronJobSpec
from kuberay_tpu.controlplane.cronjob_controller import TpuCronJobController
from kuberay_tpu.controlplane.store import ObjectStore
from kuberay_tpu.utils import constants as C
from kuberay_tpu.utils import features
from tests.test_job_controller import make_job


@pytest.fixture(autouse=True)
def gate():
    features.reset()
    features.set_gates({"TpuCronJob": True})
    yield
    features.reset()


def make_cron(name="nightly", schedule="* * * * *", **kw):
    spec = TpuCronJobSpec(schedule=schedule, jobTemplate=make_job().spec)
    for k, v in kw.items():
        setattr(spec, k, v)
    return TpuCronJob(metadata=ObjectMeta(name=name), spec=spec)


def test_launches_due_job():
    store = ObjectStore()
    ctrl = TpuCronJobController(store)
    cron = make_cron()
    obj = cron.to_dict()
    # Created 2 minutes ago -> at least one run due.
    obj["metadata"]["creationTimestamp"] = time.time() - 120
    store.create(obj)
    requeue = ctrl.reconcile("nightly")
    jobs = store.list(C.KIND_JOB)
    assert len(jobs) == 1
    assert jobs[0]["metadata"]["labels"][C.LABEL_ORIGINATED_FROM_CRD] == \
        C.KIND_CRONJOB
    st = store.get(C.KIND_CRONJOB, "nightly")["status"]
    assert st["lastScheduleTime"] > 0
    assert requeue and requeue <= 61


def test_catchup_runs_only_latest():
    store = ObjectStore()
    ctrl = TpuCronJobController(store)
    obj = make_cron().to_dict()
    obj["metadata"]["creationTimestamp"] = time.time() - 600  # 10 missed
    store.create(obj)
    ctrl.reconcile("nightly")
    assert len(store.list(C.KIND_JOB)) == 1  # only the latest
    events = [e for e in store.list("Event") if e["reason"] == "MissedRuns"]
    assert events


def test_forbid_concurrency():
    store = ObjectStore()
    ctrl = TpuCronJobController(store)
    obj = make_cron(concurrencyPolicy=ConcurrencyPolicy.FORBID).to_dict()
    obj["metadata"]["creationTimestamp"] = time.time() - 120
    store.create(obj)
    ctrl.reconcile("nightly")
    assert len(store.list(C.KIND_JOB)) == 1
    # Next tick with the first job still active: no second job.
    st = store.get(C.KIND_CRONJOB, "nightly")
    st["status"]["lastScheduleTime"] = time.time() - 120
    store.update_status(st)
    ctrl.reconcile("nightly")
    assert len(store.list(C.KIND_JOB)) == 1


def test_replace_concurrency():
    store = ObjectStore()
    ctrl = TpuCronJobController(store)
    obj = make_cron(concurrencyPolicy=ConcurrencyPolicy.REPLACE).to_dict()
    obj["metadata"]["creationTimestamp"] = time.time() - 120
    store.create(obj)
    ctrl.reconcile("nightly")
    first = store.list(C.KIND_JOB)[0]["metadata"]
    st = store.get(C.KIND_CRONJOB, "nightly")
    st["status"]["lastScheduleTime"] = time.time() - 120
    store.update_status(st)
    ctrl.reconcile("nightly")
    jobs = store.list(C.KIND_JOB)
    # Replace: the active job was deleted and a fresh one launched (the
    # deterministic name may repeat for the same minute; uid proves it).
    assert len(jobs) == 1
    assert jobs[0]["metadata"]["uid"] != first["uid"]


def test_suspend_skips_launch():
    store = ObjectStore()
    ctrl = TpuCronJobController(store)
    obj = make_cron(suspend=True).to_dict()
    obj["metadata"]["creationTimestamp"] = time.time() - 120
    store.create(obj)
    ctrl.reconcile("nightly")
    assert store.list(C.KIND_JOB) == []


def test_history_pruning():
    store = ObjectStore()
    ctrl = TpuCronJobController(store)
    obj = make_cron(successfulJobsHistoryLimit=1).to_dict()
    store.create(obj)
    cron_uid = store.get(C.KIND_CRONJOB, "nightly")["metadata"]["uid"]
    # Three finished children.
    for i in range(3):
        store.create({
            "apiVersion": C.API_VERSION, "kind": C.KIND_JOB,
            "metadata": {"name": f"nightly-old{i}", "namespace": "default",
                         "labels": {C.LABEL_ORIGINATED_FROM_CR_NAME: "nightly",
                                    C.LABEL_ORIGINATED_FROM_CRD: C.KIND_CRONJOB}},
            "spec": {"entrypoint": "x"},
            "status": {"jobDeploymentStatus": "Complete", "endTime": 1000.0 + i},
        })
    ctrl.reconcile("nightly")
    names = {j["metadata"]["name"] for j in store.list(C.KIND_JOB)
             if j["metadata"]["name"].startswith("nightly-old")}
    assert names == {"nightly-old2"}  # newest kept


def test_gate_off_noop():
    features.reset()
    store = ObjectStore()
    ctrl = TpuCronJobController(store)
    obj = make_cron().to_dict()
    obj["metadata"]["creationTimestamp"] = time.time() - 120
    store.create(obj)
    assert ctrl.reconcile("nightly") is None
    assert store.list(C.KIND_JOB) == []