"""Serving HTTP front end: concurrent requests through the real socket."""

import json
import threading
import urllib.error
import urllib.request

import jax
import time

import pytest

from kuberay_tpu.models import llama
from kuberay_tpu.runtime.coordinator_server import CoordinatorServer, MemoryBackend
from kuberay_tpu.serve.engine import ServeEngine
from kuberay_tpu.serve.server import ServeFrontend, register_with_coordinator

CFG = llama.CONFIGS["llama_tiny"]


@pytest.fixture(scope="module")
def frontend():
    params = llama.init_params(CFG, jax.random.PRNGKey(0))
    engine = ServeEngine(CFG, params, max_slots=2, max_len=64)
    fe = ServeFrontend(engine)
    srv, url = fe.serve_background()
    yield fe, url
    srv.shutdown()
    fe.close()


def post(url, body, timeout=60):
    req = urllib.request.Request(
        f"{url}/v1/completions", data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"})
    return json.load(urllib.request.urlopen(req, timeout=timeout))


def test_completion_roundtrip(frontend):
    fe, url = frontend
    out = post(url, {"prompt_tokens": [5, 6, 7], "max_tokens": 4})
    assert len(out["tokens"]) == 4
    assert out["finish_reason"] == "length"
    assert all(isinstance(t, int) for t in out["tokens"])
    # Exact enqueue->first-token latency rides every completion (the
    # gateway traffic bench's TTFT source).
    assert isinstance(out["ttft_ms"], float) and out["ttft_ms"] > 0


def test_completion_reports_load_headers(frontend):
    """Continuous-batching feedback: engine queue depth rides completion
    responses so the gateway can fold backend load into its routing
    score without a second round trip."""
    fe, url = frontend
    req = urllib.request.Request(
        f"{url}/v1/completions",
        data=json.dumps({"prompt_tokens": [1, 2], "max_tokens": 2}).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=60) as resp:
        assert resp.status == 200
        assert resp.headers["X-TPU-Queue-Depth"].isdigit()
        assert resp.headers["X-TPU-Active-Slots"].isdigit()
        json.load(resp)
    # Dense engines report scheduling state only; paged engines add the
    # KV pool occupancy (covered in test_serve_config_from_coordinator_e2e).
    st = fe.engine.stats
    assert st["queue_depth"] == 0 and st["active_slots"] == 0


def test_concurrent_requests_batched(frontend):
    fe, url = frontend
    results = {}
    errs = []

    def worker(i):
        try:
            results[i] = post(url, {"prompt_tokens": [10 + i, 20 + i],
                                    "max_tokens": 3})
        except Exception as e:
            errs.append(e)

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(5)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs
    assert len(results) == 5
    assert all(len(r["tokens"]) == 3 for r in results.values())
    stats = json.load(urllib.request.urlopen(f"{url}/stats"))
    assert stats["completed"] >= 5   # this test's own requests


def test_greedy_is_deterministic(frontend):
    fe, url = frontend
    a = post(url, {"prompt_tokens": [1, 2, 3], "max_tokens": 5})
    b = post(url, {"prompt_tokens": [1, 2, 3], "max_tokens": 5})
    assert a["tokens"] == b["tokens"]


def test_bad_request_rejected(frontend):
    fe, url = frontend
    for body in ({}, {"prompt_tokens": []}, {"prompt_tokens": "abc"},
                 {"prompt_tokens": [1.5]}):
        with pytest.raises(urllib.error.HTTPError) as e:
            post(url, body)
        assert e.value.code == 400


def test_register_with_coordinator(frontend):
    coord = CoordinatorServer(state=MemoryBackend(), spawn_jobs=False)
    srv, curl = coord.serve_background()
    try:
        coord.put_serve_config({"applications": [{"name": "llm"}]})
        assert coord.serve_apps["llm"]["status"] == "DEPLOYING"
        assert register_with_coordinator("llm", curl)
        assert coord.serve_apps["llm"]["status"] == "RUNNING"
    finally:
        srv.shutdown()


def test_metrics_endpoint():
    """Prometheus text exposition over the serve HTTP server."""
    import urllib.request

    import jax

    from kuberay_tpu.models import llama
    from kuberay_tpu.serve.engine import ServeEngine
    from kuberay_tpu.serve.server import ServeFrontend

    cfg = llama.CONFIGS["llama_tiny"]
    eng = ServeEngine(cfg, llama.init_params(cfg, jax.random.PRNGKey(0)),
                      max_slots=2, max_len=64)
    fe = ServeFrontend(eng)
    srv, url = fe.serve_background()
    try:
        resp = fe.submit([1, 2, 3], max_tokens=3, timeout=120)
        assert resp is not None
        text = urllib.request.urlopen(f"{url}/metrics").read().decode()
        assert "# TYPE tpu_serve_requests counter" in text
        assert "tpu_serve_completed 1" in text
        assert "tpu_serve_tokens_out 3" in text
    finally:
        fe.close()
        srv.shutdown()


def test_frontend_drain_completes_inflight():
    """drain() lets an in-flight request finish with a REAL response
    (the TpuService-roll SIGTERM path must not drop work)."""
    import threading

    import jax

    from kuberay_tpu.models import llama
    from kuberay_tpu.serve.engine import ServeEngine
    from kuberay_tpu.serve.server import ServeFrontend

    cfg = llama.CONFIGS["llama_tiny"]
    eng = ServeEngine(cfg, llama.init_params(cfg, jax.random.PRNGKey(0)),
                      max_slots=2, max_len=64)
    fe = ServeFrontend(eng)
    results = {}

    def client():
        results["r"] = fe.submit([1, 2, 3], max_tokens=10, timeout=120)

    t = threading.Thread(target=client)
    t.start()
    try:
        deadline = time.time() + 30
        while time.time() < deadline and not eng.has_work():
            time.sleep(0.01)
        assert fe.drain(timeout=120)
        t.join(30)
        assert results["r"] is not None
        assert len(results["r"].tokens) == 10
    finally:
        fe.close()


@pytest.mark.timeout(240)
def test_server_sigterm_drains_then_exits():
    """SIGTERM mid-request: the server stops accepting, finishes the
    in-flight completion, reports drained, and exits cleanly."""
    import json
    import os
    import signal
    import subprocess
    import sys
    import threading
    import urllib.request

    env = dict(os.environ)
    env.update({"JAX_PLATFORMS": "cpu"})
    srv = subprocess.Popen(
        [sys.executable, "-m", "kuberay_tpu.serve.server", "--model",
         "llama_tiny", "--port", "0", "--host", "127.0.0.1",
         "--max-slots", "2", "--max-len", "64"],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True, bufsize=1)
    try:
        # Ephemeral port: parse the actual bound port from the banner.
        port = None
        deadline = time.time() + 120
        while time.time() < deadline and port is None:
            line = srv.stdout.readline()
            if not line:
                break
            if "serving llama_tiny" in line:
                port = int(line.split(" on ", 1)[1].split(" ")[0]
                           .rsplit(":", 1)[1])
        assert port, "server never printed its banner"
        result = {}

        def request():
            req = json.dumps({"prompt_tokens": [1, 2, 3],
                              "max_tokens": 12}).encode()
            r = urllib.request.urlopen(urllib.request.Request(
                f"http://127.0.0.1:{port}/v1/completions", data=req,
                headers={"Content-Type": "application/json"}), timeout=150)
            result.update(json.loads(r.read()))

        t = threading.Thread(target=request)
        t.start()
        time.sleep(0.5)                      # request in flight
        srv.send_signal(signal.SIGTERM)
        t.join(timeout=180)
        out, _ = srv.communicate(timeout=120)
        out = out or ""
        assert srv.returncode == 0, out[-2000:]
        assert "draining" in out and "drained=True" in out, out[-2000:]
        assert len(result.get("tokens", [])) == 12, (result, out[-1000:])
    finally:
        srv.kill()


def test_streaming_matches_blocking_and_is_incremental():
    """submit_stream yields exactly the tokens the blocking API returns,
    and yields them BEFORE completion (true streaming, not a buffered
    replay)."""
    import jax

    from kuberay_tpu.models import llama
    from kuberay_tpu.serve.engine import ServeEngine
    from kuberay_tpu.serve.server import ServeFrontend

    cfg = llama.CONFIGS["llama_tiny"]
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    eng = ServeEngine(cfg, params, max_slots=2, max_len=64)
    fe = ServeFrontend(eng)
    try:
        want = fe.submit([1, 2, 3, 4], max_tokens=10, timeout=60)
        assert want is not None
        batches, final = [], None
        for item in fe.submit_stream([1, 2, 3, 4], max_tokens=10,
                                     timeout=60):
            if isinstance(item, list):
                batches.append(item)
            else:
                final = item
        streamed = [t for b in batches for t in b]
        assert streamed == want.tokens
        assert final is not None and final.tokens == want.tokens
        assert final.finish_reason == want.finish_reason
        # Incremental: more than one emission for a 10-token generation.
        assert len(batches) >= 2, batches
    finally:
        fe.close()


def test_streaming_speculative_runs_arrive_in_batches():
    import jax

    from kuberay_tpu.models import llama
    from kuberay_tpu.serve.engine import ServeEngine
    from kuberay_tpu.serve.server import ServeFrontend

    cfg = llama.CONFIGS["llama_tiny"]
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    eng = ServeEngine(cfg, params, max_slots=2, max_len=128,
                      speculative=4)
    fe = ServeFrontend(eng)
    try:
        # Repetitive prompt: prompt-lookup drafts will hit.
        prompt = [7, 8, 9] * 8
        want = fe.submit(list(prompt), max_tokens=16, timeout=120)
        batches = [b for b in fe.submit_stream(list(prompt),
                                               max_tokens=16, timeout=120)
                   if isinstance(b, list)]
        assert [t for b in batches for t in b] == want.tokens
        assert eng.spec_stats["accepted"] > 0
        assert any(len(b) > 1 for b in batches), \
            "accepted speculative runs should stream as multi-token batches"
    finally:
        fe.close()


def test_streaming_http_ndjson():
    """POST /v1/completions {"stream": true} answers chunked NDJSON:
    token lines then a finish line; body matches the blocking call."""
    import json as _json
    import urllib.request

    import jax

    from kuberay_tpu.models import llama
    from kuberay_tpu.serve.engine import ServeEngine
    from kuberay_tpu.serve.server import ServeFrontend

    cfg = llama.CONFIGS["llama_tiny"]
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    fe = ServeFrontend(ServeEngine(cfg, params, max_slots=2, max_len=64))
    srv, url = fe.serve_background()
    try:
        blocking = _json.load(urllib.request.urlopen(urllib.request.Request(
            f"{url}/v1/completions",
            data=_json.dumps({"prompt_tokens": [5, 6, 7],
                              "max_tokens": 8}).encode(),
            headers={"Content-Type": "application/json"}), timeout=60))
        req = urllib.request.Request(
            f"{url}/v1/completions",
            data=_json.dumps({"prompt_tokens": [5, 6, 7], "max_tokens": 8,
                              "stream": True}).encode(),
            headers={"Content-Type": "application/json"})
        lines = []
        with urllib.request.urlopen(req, timeout=60) as resp:
            assert resp.headers["Content-Type"] == "application/x-ndjson"
            for line in resp:
                lines.append(_json.loads(line))
        toks = [t for ln in lines if "tokens" in ln for t in ln["tokens"]]
        assert toks == blocking["tokens"]
        assert lines[-1]["finish_reason"] == blocking["finish_reason"]
        assert lines[-1]["num_tokens"] == len(blocking["tokens"])
    finally:
        srv.shutdown()
        fe.close()


def test_streaming_fails_fast_on_degraded():
    import threading

    import jax

    from kuberay_tpu.models import llama
    from kuberay_tpu.serve.engine import ServeEngine
    from kuberay_tpu.serve.server import ServeFrontend

    cfg = llama.CONFIGS["llama_tiny"]
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    fe = ServeFrontend(ServeEngine(cfg, params, max_slots=2, max_len=64))
    try:
        out = []

        def consume():
            for item in fe.submit_stream([1, 2, 3], max_tokens=500,
                                         timeout=60):
                out.append(item)

        t = threading.Thread(target=consume, daemon=True)
        t.start()
        import time as _t
        _t.sleep(0.3)
        fe._handle_degraded("test: follower lost")
        t.join(timeout=10)
        assert not t.is_alive(), "stream must terminate on degradation"
        assert out and out[-1] is None     # terminal failure marker
        # New streams reject immediately.
        assert list(fe.submit_stream([1], max_tokens=2,
                                     timeout=5)) == [None]
    finally:
        fe.close()


def test_streaming_http_rejection_is_503():
    """A degraded/overloaded streamed request must answer 503 like the
    blocking path — never 200-with-error-line (load balancers key on
    the status)."""
    import json as _json
    import urllib.error
    import urllib.request

    import jax

    from kuberay_tpu.models import llama
    from kuberay_tpu.serve.engine import ServeEngine
    from kuberay_tpu.serve.server import ServeFrontend

    cfg = llama.CONFIGS["llama_tiny"]
    fe = ServeFrontend(ServeEngine(cfg, llama.init_params(
        cfg, jax.random.PRNGKey(0)), max_slots=2, max_len=64))
    srv, url = fe.serve_background()
    try:
        fe._handle_degraded("test: follower lost")
        req = urllib.request.Request(
            f"{url}/v1/completions",
            data=_json.dumps({"prompt_tokens": [1, 2], "max_tokens": 4,
                              "stream": True}).encode(),
            headers={"Content-Type": "application/json"})
        try:
            urllib.request.urlopen(req, timeout=10)
            raise AssertionError("expected 503")
        except urllib.error.HTTPError as e:
            assert e.code == 503
    finally:
        srv.shutdown()
        fe.close()


def test_serve_config_from_coordinator_e2e():
    """The serveConfig-to-engine wire: the TpuService controller PUTs a
    serve config to the coordinator; a serve pod started with
    --config-from-coordinator reads its app block and boots the engine
    accordingly (paged pool visible in /stats)."""
    import json as _json
    import os
    import signal
    import socket
    import subprocess
    import sys
    import time
    import urllib.request

    from kuberay_tpu.runtime.coordinator_client import CoordinatorClient
    from kuberay_tpu.runtime.coordinator_server import CoordinatorServer

    coord_srv, coord_url = CoordinatorServer().serve_background()
    s = socket.socket(); s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]; s.close()
    proc = None
    try:
        # Controller side: PUT the serve config (late, like a real roll).
        CoordinatorClient(coord_url).update_serve_apps({
            "applications": [{
                "name": "llm", "model": "llama_tiny", "paged": True,
                "block_size": 8, "max_slots": 2, "max_len": 64,
                "speculative": 2}]})
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        proc = subprocess.Popen(
            [sys.executable, "-m", "kuberay_tpu.serve.server",
             "--model", "llama_1b",          # overridden by the config
             "--host", "127.0.0.1", "--port", str(port),
             "--app-name", "llm", "--coordinator", coord_url,
             "--config-from-coordinator"],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True)
        deadline = time.time() + 240
        stats = None
        while time.time() < deadline:
            assert proc.poll() is None, proc.communicate()[0][-2000:]
            try:
                stats = _json.load(urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/stats", timeout=2))
                break
            except OSError:
                time.sleep(0.5)
        assert stats is not None, "server never came up"
        # Paged engine booted (pool counters exist) with the config's
        # tiny model — llama_1b would still be compiling/oom'ing.
        assert "free_blocks" in stats, stats
        # App registered RUNNING with the coordinator.
        apps = CoordinatorClient(coord_url).get_serve_apps()
        assert apps.get("llm", {}).get("status") == "RUNNING", apps
        # And it actually serves.
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/v1/completions",
            data=_json.dumps({"prompt_tokens": [1, 2, 3],
                              "max_tokens": 4}).encode(),
            headers={"Content-Type": "application/json"})
        out = _json.load(urllib.request.urlopen(req, timeout=120))
        assert len(out["tokens"]) == 4
    finally:
        if proc is not None and proc.poll() is None:
            proc.send_signal(signal.SIGTERM)
            try:
                proc.wait(timeout=30)
            except subprocess.TimeoutExpired:
                proc.kill()
        coord_srv.shutdown()
