"""The quota fairness regression curve (benchmark/quota_bench.py).

``benchmark/results/quota_r15.json`` is the committed evidence that the
hierarchical ledger keeps its three promises under a 1k-job contention
storm: a tenant inside its guarantee never queues behind borrowers
(prod's waits stay an order of magnitude under the starvation bound,
with zero escalations), borrowers are served fairly (the zero-guarantee
tenant still moves a healthy share of chips), and nobody starves past
the bound-plus-service tail.  The whole pipeline runs on a fake clock
and a seeded schedule, so the gate both (a) asserts the curve's shape
from the committed file and (b) recomputes the storm and pins it to the
committed numbers — a behavior change in the admission/reclaim/
starvation machinery shows up here as a diff, not silently.
"""

from __future__ import annotations

import importlib.util
import json
import os

import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ARTIFACT = os.path.join(REPO_ROOT, "benchmark", "results", "quota_r15.json")
_BENCH = os.path.join(REPO_ROOT, "benchmark", "quota_bench.py")


def _load_bench():
    spec = importlib.util.spec_from_file_location("quota_bench", _BENCH)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def artifact():
    with open(ARTIFACT) as fh:
        return json.load(fh)


def test_artifact_shape(artifact):
    assert artifact["schema"] == "tpu-quota-bench/v1"
    assert artifact["seeds"] == [0, 1, 2, 3, 4]
    assert artifact["jobs"] == 1000
    assert set(artifact["curve"]) == {"prod", "batch", "free"}
    assert len(artifact["runs"]) == 5
    for r in artifact["runs"]:
        # Every job completes (the backlog always drains) and no tick
        # ever violated conservation, gang atomicity, or the
        # escalation deadline.
        assert r["completed"] == artifact["jobs"], r["seed"]
        assert r["violations"] == [], r["seed"]


def test_guaranteed_tenant_never_queues_behind_borrowers(artifact):
    """The headline: prod's offered load sits inside its guarantee, so
    its admission is a pre-sold contract — short waits, no starvation
    escalation, (almost) no reclaim ever pointed at it."""
    bound = artifact["pool"]["starvationBoundSeconds"]
    for r in artifact["runs"]:
        prod = r["tenants"]["prod"]
        assert prod["starvation_escalations"] == 0, r["seed"]
        assert prod["preemptions"] <= 1, r["seed"]
        assert prod["p95_wait_s"] < bound / 2, r["seed"]
        for other in ("batch", "free"):
            assert prod["p95_wait_s"] < \
                r["tenants"][other]["p95_wait_s"], (r["seed"], other)


def test_borrowers_starve_no_longer_than_the_bound_tail(artifact):
    """Bounded starvation: even the zero-guarantee tenant's worst wait
    stays within 2x the escalation bound (bound + reclaim notice +
    service), and the guard actually fires for the borrowers."""
    bound = artifact["pool"]["starvationBoundSeconds"]
    for r in artifact["runs"]:
        escalations = 0
        for name, t in r["tenants"].items():
            assert t["max_wait_s"] <= 2 * bound, (r["seed"], name)
            escalations += t["starvation_escalations"]
        assert escalations > 0, r["seed"]


def test_fairness_curve_shape(artifact):
    """While backlogged, a guaranteed borrower still averages at least
    its guarantee; the zero-guarantee tenant still moves a real share
    of the pool's chips (its ~0.3 offered share, served late but
    served)."""
    for r in artifact["runs"]:
        batch = r["tenants"]["batch"]
        assert batch["avg_backlogged_chips"] >= \
            batch["guaranteed_chips"], r["seed"]
        assert r["tenants"]["free"]["goodput_share"] > 0.2, r["seed"]


def test_recomputed_curve_matches_committed(artifact):
    """Full deterministic replay: rerunning the storm in-process must
    reproduce the committed artifact exactly (fake clock + seeded
    schedule; no wall time enters the numbers)."""
    bench = _load_bench()
    doc = bench.run_curve(artifact["seeds"])
    assert doc["curve"] == artifact["curve"]
    assert doc["runs"] == artifact["runs"]
