"""Paged KV cache: block allocator, prefix caching, engine parity.

The paged path must be bit-compatible with the dense cache (same
attention math, different memory layout), so every behavioral test
compares against the dense engine or the full forward as ground truth.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kuberay_tpu.models import llama
from kuberay_tpu.serve.engine import Request, ServeEngine
from kuberay_tpu.serve.paged_engine import PagedServeEngine
from kuberay_tpu.serve.paged_kv import (
    BlockAllocator,
    init_paged_cache,
    make_paged_forward,
)

CFG = llama.CONFIGS["llama_tiny"]
BS = 8      # block size for tests


@pytest.fixture(scope="module")
def params():
    return llama.init_params(CFG, jax.random.PRNGKey(0))


# ---------------------------------------------------------------------------
# allocator
# ---------------------------------------------------------------------------

def test_allocator_refcount_and_free():
    a = BlockAllocator(num_blocks=4, block_size=BS)
    b1, b2 = a.allocate(), a.allocate()
    assert a.num_free == 2 and {b1, b2} == {0, 1}
    a.free(b1)
    assert a.num_free == 3
    with pytest.raises(AssertionError):
        a.free(b1)                      # double free


def test_allocator_exhaustion():
    a = BlockAllocator(num_blocks=2, block_size=BS)
    assert a.allocate() is not None and a.allocate() is not None
    assert a.allocate() is None


def test_prefix_match_and_cannibalize():
    a = BlockAllocator(num_blocks=3, block_size=4)
    toks = [1, 2, 3, 4, 5, 6, 7, 8]
    ids = [a.allocate(), a.allocate()]
    a.register_prefix(toks, ids)
    for b in ids:
        a.free(b)                       # refcount 0, still cached
    got = a.match_prefix(toks + [9])    # both full blocks hit
    assert got == ids
    for b in got:
        a.free(b)
    # Demanding all 3 blocks forces cannibalizing cached ones; after
    # that the prefix no longer matches.
    taken = [a.allocate() for _ in range(3)]
    assert None not in taken
    for b in taken:
        a.free(b)
    assert a.match_prefix(toks) == []


# ---------------------------------------------------------------------------
# paged forward parity
# ---------------------------------------------------------------------------

def test_paged_forward_matches_full(params):
    """Prefill+decode through the paged cache == one-shot full forward,
    with a deliberately scrambled (non-identity) block table."""
    fwd = make_paged_forward(BS)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (1, 12), 0,
                                CFG.vocab_size)
    full = llama.forward(CFG, params, tokens)

    cache = init_paged_cache(CFG, num_blocks=8, block_size=BS)
    table = jnp.asarray([[5, 2, 7, 0]], jnp.int32)   # scrambled physical ids
    logits_p, cache = fwd(CFG, params, tokens[:, :8], cache, table,
                          jnp.zeros(1, jnp.int32))
    np.testing.assert_allclose(np.asarray(logits_p),
                               np.asarray(full[:, :8]), rtol=2e-3, atol=2e-3)
    for t in range(8, 12):
        logits_t, cache = fwd(CFG, params, tokens[:, t:t + 1], cache, table,
                              jnp.array([t], jnp.int32))
        np.testing.assert_allclose(np.asarray(logits_t[:, 0]),
                                   np.asarray(full[:, t]),
                                   rtol=2e-3, atol=2e-3)


# ---------------------------------------------------------------------------
# engine parity + behavior
# ---------------------------------------------------------------------------

def test_paged_engine_matches_dense(params):
    prompts = [[5, 17, 42, 7], [9, 9, 1, 30, 2, 8, 4], [3]]
    reqs = [Request(f"r{i}", p, max_new_tokens=6)
            for i, p in enumerate(prompts)]

    dense = ServeEngine(CFG, params, max_slots=2, max_len=64)
    paged = PagedServeEngine(CFG, params, max_slots=2, max_len=64,
                             block_size=BS)
    for r in reqs:
        dense.add_request(Request(r.request_id, list(r.prompt_tokens),
                                  max_new_tokens=r.max_new_tokens))
        paged.add_request(r)
    d = {r.request_id: r.tokens for r in dense.run()}
    p = {r.request_id: r.tokens for r in paged.run()}
    assert d == p
    # All blocks returned to the pool once everything finished.
    assert paged.allocator.num_free == paged.num_blocks


def test_prefix_cache_reuse(params):
    """Second request sharing a long prefix: blocks are reused (stats
    show hits) and the output is unchanged vs a cold engine."""
    shared = list(range(1, 17))                  # 16 tokens = 2 full blocks
    p1 = shared + [21, 22]
    p2 = shared + [31]

    cold = PagedServeEngine(CFG, params, max_slots=1, max_len=64,
                            block_size=BS)
    cold.add_request(Request("x", list(p2), max_new_tokens=4))
    expected = cold.run()[0].tokens

    eng = PagedServeEngine(CFG, params, max_slots=1, max_len=64,
                           block_size=BS)
    eng.add_request(Request("a", list(p1), max_new_tokens=4))
    eng.run()
    assert eng.stats["prefix_hit_tokens"] == 0   # cold cache
    eng.add_request(Request("b", list(p2), max_new_tokens=4))
    out = eng.run()
    assert out[0].tokens == expected             # reuse changed nothing
    assert eng.stats["prefix_hit_tokens"] == 2 * BS


def test_admission_waits_for_memory(params):
    """A pool too small for two prompts admits them one after another
    (memory-based admission), still finishing both correctly."""
    eng = PagedServeEngine(CFG, params, max_slots=2, max_len=64,
                           block_size=BS, num_blocks=3)   # 24 token budget
    eng.add_request(Request("a", [1] * 10, max_new_tokens=3))
    eng.add_request(Request("b", [2] * 10, max_new_tokens=3))
    out = eng.step()                    # only "a" fits (2 blocks + head)
    assert eng.num_active == 1 and not out
    out = eng.run()
    ids = sorted(r.request_id for r in out)
    assert ids == ["a", "b"]
    assert all(r.finish_reason == "length" and len(r.tokens) == 3
               for r in out)


def test_preemption_on_pool_exhaustion(params):
    """Decode that outgrows the pool preempts rather than corrupting."""
    eng = PagedServeEngine(CFG, params, max_slots=1, max_len=256,
                           block_size=BS, num_blocks=2)   # 16 token budget
    eng.add_request(Request("a", [1] * 12, max_new_tokens=50))
    out = eng.run()
    assert out[0].finish_reason == "preempted"
    assert 0 < len(out[0].tokens) < 50
    assert eng.allocator.num_free == eng.num_blocks


def test_unservable_prompt_cancelled_not_livelocked(params):
    """A prompt larger than the whole pool is rejected immediately;
    requests behind it still run (review regression: requeue-forever)."""
    eng = PagedServeEngine(CFG, params, max_slots=1, max_len=256,
                           block_size=BS, num_blocks=2)   # 16-token pool
    eng.add_request(Request("big", [1] * 40, max_new_tokens=4))
    eng.add_request(Request("ok", [2] * 6, max_new_tokens=3))
    out = eng.run(max_steps=50)
    by_id = {r.request_id: r for r in out}
    assert by_id["big"].finish_reason == "cancelled"
    assert by_id["ok"].finish_reason == "length" and len(by_id["ok"].tokens) == 3


def test_headroom_reserved_no_instant_preemption(params):
    """Block-aligned prompts admitted together must not steal each
    other's first-decode block (review regression: checked-not-reserved
    headroom preempted a request after one token)."""
    eng = PagedServeEngine(CFG, params, max_slots=2, max_len=64,
                           block_size=BS, num_blocks=5)
    eng.add_request(Request("a", list(range(1, 17)), max_new_tokens=3))
    eng.add_request(Request("b", list(range(21, 37)), max_new_tokens=3))
    out = eng.run(max_steps=200)
    assert sorted(r.request_id for r in out) == ["a", "b"]
    assert all(r.finish_reason == "length" and len(r.tokens) == 3
               for r in out)


def test_hash_collision_degrades_to_miss():
    """A chained-hash collision must MISS (token verification), never
    serve another prompt's blocks."""
    a = BlockAllocator(num_blocks=4, block_size=4)
    a._chain = lambda parent, toks: 42          # force universal collisions
    toks1, toks2 = [1, 2, 3, 4], [5, 6, 7, 8]
    b1 = a.allocate()
    a.register_prefix(toks1, [b1])
    assert a.match_prefix(toks2) == []          # collision -> miss
    got = a.match_prefix(toks1)                 # exact tokens still hit
    assert got == [b1]


def test_paged_mixtral_matches_dense(params):
    """MoE serving through the paged cache == the dense engine (the
    kv_update strategy is orthogonal to the FFN)."""
    from kuberay_tpu.models import mixtral
    mcfg = mixtral.CONFIGS["mixtral_tiny"]
    mparams = mixtral.init_params(mcfg, jax.random.PRNGKey(3))
    reqs = [([5, 17, 42, 7, 11], 5), ([9, 1, 30], 4)]

    dense = ServeEngine(mcfg, mparams, max_slots=2, max_len=64)
    paged = PagedServeEngine(mcfg, mparams, max_slots=2, max_len=64,
                             block_size=BS)
    for i, (p, n) in enumerate(reqs):
        dense.add_request(Request(f"r{i}", list(p), max_new_tokens=n))
        paged.add_request(Request(f"r{i}", list(p), max_new_tokens=n))
    d = {r.request_id: r.tokens for r in dense.run()}
    p = {r.request_id: r.tokens for r in paged.run()}
    assert d == p


def test_paged_mixtral_warm_cache_invariant(params):
    """MoE outputs must not depend on cache warmth: serving prefill
    routes droplessly (per-token), so prefix sharing is safe for MoE —
    a repeat prompt reuses cached blocks AND produces exactly the
    cold-engine tokens."""
    from kuberay_tpu.models import mixtral
    mcfg = mixtral.CONFIGS["mixtral_tiny"]
    mparams = mixtral.init_params(mcfg, jax.random.PRNGKey(3))
    prompt = list(range(1, 20))                 # > 2 full blocks

    cold = PagedServeEngine(mcfg, mparams, max_slots=1, max_len=64,
                            block_size=BS)
    cold.add_request(Request("x", list(prompt), max_new_tokens=4))
    expected = cold.run()[0].tokens

    eng = PagedServeEngine(mcfg, mparams, max_slots=1, max_len=64,
                           block_size=BS)
    eng.add_request(Request("warm", list(prompt), max_new_tokens=4))
    eng.run()
    eng.add_request(Request("again", list(prompt), max_new_tokens=4))
    out = eng.run()
    assert out[0].tokens == expected
    assert eng.stats["prefix_hit_tokens"] > 0    # sharing now on for MoE


def test_int8_paged_pool_matrix():
    """int8 paged pool (quantize-on-write scatter + gathered int8 views
    into the dense quant attention): half the pool bytes at rest, and
    every composition stays exact against its own int8 twin — TP,
    chunked prefill, speculative."""
    import jax

    from kuberay_tpu.models import llama
    from kuberay_tpu.serve.engine import Request
    from kuberay_tpu.serve.paged_engine import PagedServeEngine
    from kuberay_tpu.serve.sharding import serve_mesh

    cfg = llama.CONFIGS["llama_tiny"]
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    prompts = [[1, 2, 3, 4, 5], [9, 8, 7], [1, 2, 3, 4, 5, 6, 7],
               list(range(24))]

    def run(**kw):
        eng = PagedServeEngine(cfg, params, max_slots=3, max_len=64,
                               block_size=8, kv_quant="int8",
                               decode_impl="xla", **kw)
        for i, p in enumerate(prompts):
            eng.add_request(Request(f"r{i}", p, max_new_tokens=6))
        return {r.request_id: r.tokens for r in eng.run()}, eng

    base, eng = run()
    assert eng.cache["k"]["q"].dtype.name == "int8"
    tp, _ = run(mesh=serve_mesh(2))
    assert base == tp
    ck, _ = run(prefill_chunk=16)
    ctp, _ = run(prefill_chunk=16, mesh=serve_mesh(2))
    assert ck == ctp
    spec, seng = run(speculative=4)
    assert spec == base                    # greedy spec is exact
    spec_tp, _ = run(speculative=4, mesh=serve_mesh(2))
    assert spec_tp == base
    spec_ck, _ = run(speculative=4, prefill_chunk=16)
    ck_base, _ = run(prefill_chunk=16)
    assert spec_ck == ck_base              # spec+chunk vs chunk twin


def test_int8_paged_mixtral():
    """MoE + paged + int8: the quant pool is orthogonal to the FFN (both
    route through forward_with_cache's strategy seams)."""
    import jax

    from kuberay_tpu.models import mixtral
    from kuberay_tpu.serve.engine import Request
    from kuberay_tpu.serve.paged_engine import PagedServeEngine

    cfg = mixtral.CONFIGS["mixtral_tiny"]
    params = mixtral.init_params(cfg, jax.random.PRNGKey(0))

    def run(**kw):
        eng = PagedServeEngine(cfg, params, max_slots=2, max_len=64,
                               block_size=8, **kw)
        for i, p in enumerate([[1, 2, 3, 4, 5], [9, 8, 7]]):
            eng.add_request(Request(f"r{i}", p, max_new_tokens=5))
        return {r.request_id: r.tokens for r in eng.run()}

    out = run(kv_quant="int8", decode_impl="xla")
    assert all(len(t) == 5 for t in out.values())
    # int8 twin is deterministic.
    assert out == run(kv_quant="int8", decode_impl="xla")


# ---------------------------------------------------------------------------
# KV-block transfer (disaggregated prefill/decode, docs/serving.md)
# ---------------------------------------------------------------------------

def test_allocator_import_block_lifecycle():
    """import_block publishes an externally produced block refcount-1;
    after the caller frees it, it serves match_prefix like a locally
    prefilled block and stays LRU-evictable."""
    a = BlockAllocator(num_blocks=3, block_size=4)
    toks = [1, 2, 3, 4, 5, 6, 7, 8]
    h0, h1 = a.block_hashes(toks)
    b0 = a.import_block(h0, toks[:4])
    b1 = a.import_block(h1, toks[4:8])
    assert b0 is not None and b1 is not None
    assert a.refcount[b0] == 1 and a.refcount[b1] == 1
    assert a.import_block(h0, toks[:4]) is None   # already resident
    assert a.lookup_block(h0) == (b0, tuple(toks[:4]))
    # While refcount-1 (content being written) the blocks cannot be
    # cannibalized: only the one never-imported block is allocatable.
    assert a.allocate() is not None and a.allocate() is None
    a.free(b0), a.free(b1)
    assert a.match_prefix(toks) == [b0, b1]       # now a normal cache hit
    for b in (b0, b1):
        a.free(b)


def test_allocator_import_block_pool_exhausted():
    a = BlockAllocator(num_blocks=1, block_size=4)
    keep = a.allocate()
    assert a.import_block(12345, [1, 2, 3, 4]) is None
    a.free(keep)


def test_allocator_resident_probe_is_pure():
    """resident_prefix_blocks never increfs (the delta probe runs on the
    engine loop against in-flight state) and token-verifies each block."""
    a = BlockAllocator(num_blocks=2, block_size=4)
    toks = [1, 2, 3, 4, 5, 6, 7, 8]
    ids = [a.allocate(), a.allocate()]
    a.register_prefix(toks, ids)
    for b in ids:
        a.free(b)
    before = list(a.refcount)
    assert a.resident_prefix_blocks(toks) == 2
    assert a.resident_prefix_blocks(toks[:4]) == 1
    assert a.resident_prefix_blocks([9, 9, 9, 9]) == 0
    # A mid-chain token mismatch stops the walk (collision reads as
    # non-resident).
    assert a.resident_prefix_blocks(toks[:4] + [0, 0, 0, 0]) == 1
    assert list(a.refcount) == before


def test_engine_kv_export_import_roundtrip(params):
    """Prefill on one engine, ship the blocks, decode on another: the
    importer's output is bit-identical to a cold engine that prefilled
    the prompt itself, and a second transfer is all-skip (delta-only)."""
    prompt = list(range(1, 25))                  # 3 full blocks
    cold = PagedServeEngine(CFG, params, max_slots=1, max_len=64,
                            block_size=BS)
    cold.add_request(Request("c", list(prompt), max_new_tokens=6))
    expected = cold.run()[0].tokens

    pf = PagedServeEngine(CFG, params, max_slots=1, max_len=64,
                          block_size=BS)
    pf.add_request(Request("p", list(prompt), max_new_tokens=1))
    pf.run()
    assert pf.resident_prefix_blocks(prompt) == 3
    blocks = pf.export_kv_blocks(prompt)
    assert [b["index"] for b in blocks] == [0, 1, 2]
    assert blocks[0]["hash"] == pf.allocator.block_hashes(prompt)[0]

    de = PagedServeEngine(CFG, params, max_slots=1, max_len=64,
                          block_size=BS)
    assert de.import_kv_blocks(prompt, blocks) == \
        {"imported": 3, "skipped": 0}
    # Re-import is pure skip — the wire carries nothing twice.
    assert de.import_kv_blocks(prompt, blocks) == \
        {"imported": 0, "skipped": 3}
    de.add_request(Request("d", list(prompt), max_new_tokens=6))
    out = de.run()
    assert out[0].tokens == expected             # transferred KV == local
    # 2 of 3 blocks served from the transfer: the engine always
    # recomputes the prompt's final block so prefill emits real logits.
    assert de.stats["prefix_hit_tokens"] == 2 * BS
    # Export honors skip_blocks (the resident-probe delta).
    assert [b["index"] for b in pf.export_kv_blocks(prompt, skip_blocks=2)] \
        == [2]
    assert pf.export_kv_blocks(prompt, skip_blocks=3) == []
    # max_blocks budgets the transfer but keeps the shipped records a
    # contiguous resident prefix (the importer recomputes the rest).
    assert [b["index"] for b in pf.export_kv_blocks(prompt, max_blocks=2)] \
        == [0, 1]
    assert [b["index"] for b in pf.export_kv_blocks(prompt, skip_blocks=1,
                                                    max_blocks=1)] == [1]
    assert [b["index"] for b in pf.export_kv_blocks(prompt, max_blocks=0)] \
        == [0, 1, 2]


def test_engine_kv_import_rejects_malformed_and_gapped(params):
    prompt = list(range(1, 17))                  # 2 full blocks
    pf = PagedServeEngine(CFG, params, max_slots=1, max_len=64,
                          block_size=BS)
    pf.add_request(Request("p", list(prompt), max_new_tokens=1))
    pf.run()
    blocks = pf.export_kv_blocks(prompt)

    # Tampered hash: the chain walk stops before the bad record.
    de = PagedServeEngine(CFG, params, max_slots=1, max_len=64,
                          block_size=BS)
    bad = [dict(blocks[0], hash=blocks[0]["hash"] + 1), blocks[1]]
    assert de.import_kv_blocks(prompt, bad) == {"imported": 0, "skipped": 0}
    # Gap (block 0 missing): a non-contiguous suffix is unusable.
    assert de.import_kv_blocks(prompt, [blocks[1]]) == \
        {"imported": 0, "skipped": 0}
    # Truncated payload: stop clean, nothing adopted.
    trunc = [dict(blocks[0], k=blocks[0]["k"][:8])]
    assert de.import_kv_blocks(prompt, trunc) == \
        {"imported": 0, "skipped": 0}
    assert de.allocator.num_free == de.num_blocks


def test_engine_kv_transfer_requires_unquantized_pool(params):
    eng = PagedServeEngine(CFG, params, max_slots=1, max_len=64,
                           block_size=BS, kv_quant="int8",
                           decode_impl="xla")
    with pytest.raises(NotImplementedError):
        eng.export_kv_blocks(list(range(1, 9)))
    with pytest.raises(NotImplementedError):
        eng.import_kv_blocks(list(range(1, 9)), [])


def test_kv_http_endpoints(params):
    """/v1/kv/{resident,export,import} over real HTTP: probe, delta
    export, import, and validation errors — serialized with the engine
    loop via call_engine."""
    import json as _json
    import urllib.error
    import urllib.request

    from kuberay_tpu.serve.server import ServeFrontend

    prompt = list(range(1, 17))                  # 2 full blocks

    def post(url, path, doc, code=200):
        req = urllib.request.Request(
            url + path, data=_json.dumps(doc).encode(),
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=30) as r:
                return r.status, _json.loads(r.read())
        except urllib.error.HTTPError as e:
            return e.code, _json.loads(e.read())

    pf_fe = ServeFrontend(PagedServeEngine(CFG, params, max_slots=1,
                                           max_len=64, block_size=BS))
    de_fe = ServeFrontend(PagedServeEngine(CFG, params, max_slots=1,
                                           max_len=64, block_size=BS))
    pf_srv, pf_url = pf_fe.serve_background()
    de_srv, de_url = de_fe.serve_background()
    try:
        code, doc = post(pf_url, "/v1/completions",
                         {"prompt_tokens": prompt, "max_tokens": 1})
        assert code == 200 and len(doc["tokens"]) == 1

        code, doc = post(pf_url, "/v1/kv/resident",
                         {"prompt_tokens": prompt})
        assert (code, doc["resident_blocks"]) == (200, 2)
        code, doc = post(de_url, "/v1/kv/resident",
                         {"prompt_tokens": prompt})
        assert (code, doc["resident_blocks"]) == (200, 0)

        code, doc = post(pf_url, "/v1/kv/export",
                         {"prompt_tokens": prompt, "skip_blocks": 1})
        assert code == 200 and doc["block_size"] == BS
        assert [b["index"] for b in doc["blocks"]] == [1]
        code, full = post(pf_url, "/v1/kv/export",
                          {"prompt_tokens": prompt})
        assert code == 200 and len(full["blocks"]) == 2

        code, doc = post(de_url, "/v1/kv/import",
                         {"prompt_tokens": prompt,
                          "blocks": full["blocks"]})
        assert (code, doc) == (200, {"imported": 2, "skipped": 0})
        code, doc = post(de_url, "/v1/kv/resident",
                         {"prompt_tokens": prompt})
        assert doc["resident_blocks"] == 2

        # Validation: bad prompt_tokens / blocks shape -> 400.
        assert post(de_url, "/v1/kv/resident",
                    {"prompt_tokens": []})[0] == 400
        assert post(de_url, "/v1/kv/import",
                    {"prompt_tokens": prompt, "blocks": "nope"})[0] == 400
        assert post(pf_url, "/v1/kv/export",
                    {"prompt_tokens": prompt,
                     "skip_blocks": "x"})[0] == 400
    finally:
        for srv, fe in ((pf_srv, pf_fe), (de_srv, de_fe)):
            srv.shutdown()
            fe.close()


def test_kv_http_501_for_non_paged_engine(params):
    """A dense (non-paged) replica advertises the seam as unimplemented,
    not as an error the gateway would retry."""
    import json as _json
    import urllib.error
    import urllib.request

    from kuberay_tpu.serve.server import ServeFrontend

    fe = ServeFrontend(ServeEngine(CFG, params, max_slots=1, max_len=64))
    srv, url = fe.serve_background()
    try:
        req = urllib.request.Request(
            url + "/v1/kv/resident",
            data=_json.dumps({"prompt_tokens": [1, 2, 3]}).encode(),
            headers={"Content-Type": "application/json"})
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=30)
        assert ei.value.code == 501
    finally:
        srv.shutdown()
        fe.close()
