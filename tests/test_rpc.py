"""gRPC V1 surface (ref proto/*.proto + apiserver/cmd/main.go:97-147):
contract drift, dict<->message fidelity, five services round-tripping
over a real grpc server, error-code mapping, auth, pagination, and the
RPC front door driving the real operator."""

import pathlib

import pytest

from kuberay_tpu.controlplane.store import (AlreadyExists, Conflict,
                                            Invalid, NotFound, ObjectStore,
                                            StoreError)
from kuberay_tpu.rpc import schema
from kuberay_tpu.rpc.client import RpcClient
from kuberay_tpu.rpc.server import serve_background
from kuberay_tpu.utils import constants as C
from tests.test_api_types import make_cluster

REPO = pathlib.Path(__file__).resolve().parent.parent


@pytest.fixture(scope="module")
def stack():
    from kuberay_tpu.utils import features
    features.reset()
    features.set_gates({"TpuCronJob": True})
    store = ObjectStore()
    server, addr = serve_background(store, token="tok")
    rpc = RpcClient(addr, token="tok")
    yield store, rpc, addr
    rpc.close()
    server.stop(None)
    features.reset()


# ---------------------------------------------------------------------------
# contract
# ---------------------------------------------------------------------------

def test_proto_contract_in_sync():
    """The checked-in IDL must match what the api dataclasses generate —
    message schema and CRD surface cannot diverge — and the serialized
    descriptor set must match the IDL (a stale schema.binpb would make
    the runtime speak an old contract while the text check stays
    green)."""
    import subprocess
    import sys
    out = subprocess.run(
        [sys.executable, str(REPO / "scripts" / "gen_proto.py"),
         "--check"], capture_output=True, text=True)
    assert out.returncode == 0, out.stdout + out.stderr


def test_descriptor_set_loads_all_services():
    for name in ("TpuClusterService", "TpuJobService", "TpuServeService",
                 "TpuCronJobService", "ComputeTemplateService"):
        sd = schema.service_descriptor(name)
        assert len(sd.methods) >= 5, name


def test_dict_message_round_trip_all_kinds():
    from kuberay_tpu.api.tpucluster import TpuCluster
    samples = {
        "TpuCluster": make_cluster("rt").to_dict(),
        "TpuJob": {"kind": "TpuJob", "metadata": {"name": "j"},
                   "spec": {"entrypoint": "python x.py",
                            "runtimeEnv": {"K": "v"},
                            "clusterSelector": {"a": "b"},
                            "backoffLimit": 3}},
        "TpuService": {"kind": "TpuService", "metadata": {"name": "s"},
                       "spec": {"serveConfig": {
                           "applications": [{
                               "name": "a", "route_prefix": "/",
                               "deployments": [{"name": "d",
                                                "num_replicas": 2}]}]}}},
        "TpuCronJob": {"kind": "TpuCronJob", "metadata": {"name": "c"},
                       "spec": {"schedule": "*/5 * * * *",
                                "concurrencyPolicy": "Forbid"}},
        "ComputeTemplate": {
            "kind": "ComputeTemplate", "metadata": {"name": "t"},
            "spec": {"accelerator": "v5p", "topology": "4x4x4",
                     "tolerations": [{"key": "tpu", "value": 1}]}},
    }
    for msg_name, d in samples.items():
        msg = schema.dict_to_message(d, msg_name)
        back = schema.message_to_dict(msg)
        for section in ("spec", "metadata"):
            for k, v in d.get(section, {}).items():
                assert back[section][k] == v, (msg_name, section, k)
    # full typed-layer equivalence on the richest kind
    d = make_cluster("rt").to_dict()
    back = schema.message_to_dict(schema.dict_to_message(d, "TpuCluster"))
    assert TpuCluster.from_dict(back).to_dict() == \
        TpuCluster.from_dict(d).to_dict()


def test_unknown_field_rejected_not_dropped():
    with pytest.raises(ValueError, match="numSlicez"):
        schema.dict_to_message(
            {"spec": {"workerGroupSpecs": [{"numSlicez": 2}]}},
            "TpuCluster")


# ---------------------------------------------------------------------------
# services over the wire
# ---------------------------------------------------------------------------

def test_cluster_crud_round_trip(stack):
    store, rpc, _ = stack
    created = rpc.clusters.create(make_cluster("crud").to_dict())
    assert created["metadata"]["uid"]
    assert store.try_get(C.KIND_CLUSTER, "crud") is not None
    got = rpc.clusters.get("crud")
    assert got["metadata"]["uid"] == created["metadata"]["uid"]
    got["spec"]["suspend"] = True
    updated = rpc.clusters.update(got)
    assert updated["spec"]["suspend"] is True
    assert updated["metadata"]["generation"] > got["metadata"]["generation"]
    assert rpc.clusters.delete("crud") is True
    with pytest.raises(NotFound):
        rpc.clusters.get("crud")


def test_all_kind_services(stack):
    _, rpc, _ = stack
    job = {"kind": "TpuJob", "metadata": {"name": "rpc-job"},
           "spec": {"entrypoint": "python t.py",
                    "clusterSpec": make_cluster("x").to_dict()["spec"]}}
    assert rpc.jobs.create(job)["metadata"]["name"] == "rpc-job"
    svc = {"kind": "TpuService", "metadata": {"name": "rpc-svc"},
           "spec": {"clusterSpec": make_cluster("x").to_dict()["spec"],
                    "serveConfig": {"applications": [
                        {"name": "app", "route_prefix": "/"}]}}}
    assert rpc.services.create(svc)["metadata"]["name"] == "rpc-svc"
    cron = {"kind": "TpuCronJob", "metadata": {"name": "rpc-cron"},
            "spec": {"schedule": "0 * * * *",
                     "jobTemplate": job["spec"]}}
    assert rpc.cronjobs.create(cron)["metadata"]["name"] == "rpc-cron"
    tmpl = {"kind": "ComputeTemplate", "metadata": {"name": "rpc-tmpl"},
            "spec": {"accelerator": "v5e", "topology": "2x2"}}
    assert rpc.compute_templates.create(tmpl)["metadata"]["name"] == \
        "rpc-tmpl"
    for kc, name in ((rpc.jobs, "rpc-job"), (rpc.services, "rpc-svc"),
                     (rpc.cronjobs, "rpc-cron"),
                     (rpc.compute_templates, "rpc-tmpl")):
        assert kc.get(name)["metadata"]["name"] == name
        assert kc.delete(name) is True


def test_admission_validation_on_create_and_update(stack):
    _, rpc, _ = stack
    bad = make_cluster("Bad_Name!").to_dict()
    with pytest.raises(Invalid, match="DNS-1123"):
        rpc.clusters.create(bad)
    ok = rpc.clusters.create(make_cluster("adm").to_dict())
    ok["spec"]["workerGroupSpecs"] = []     # group removal is immutable
    # removing a worker group in place is refused by update admission
    with pytest.raises(Invalid, match="cannot be removed"):
        rpc.clusters.update(ok)
    rpc.clusters.delete("adm")


def test_noop_update_does_not_bump_generation(stack):
    """A get->update round trip with no changes must be a true no-op:
    the proto round trip may add/drop default-valued keys, but the
    server canonicalizes through the typed layer so the store's spec
    comparison sees identical dicts."""
    store, rpc, _ = stack
    rpc.clusters.create(make_cluster("noop").to_dict())
    got = rpc.clusters.get("noop")
    gen_before = got["metadata"]["generation"]
    updated = rpc.clusters.update(got)
    assert updated["metadata"]["generation"] == gen_before
    rpc.clusters.delete("noop")


def test_ssa_managed_object_readable_over_rpc(stack):
    """Store objects carry metadata the contract does not model (SSA
    managedFields); reads must skip it, not 500."""
    store, rpc, _ = stack
    rpc.clusters.create(make_cluster("ssa").to_dict())
    store.patch(C.KIND_CLUSTER, "ssa", "default",
                {"apiVersion": "tpu.dev/v1", "kind": "TpuCluster",
                 "metadata": {"name": "ssa", "labels": {"own": "er"}}},
                patch_type="apply", field_manager="kubectl")
    got = rpc.clusters.get("ssa")
    assert got["metadata"]["labels"]["own"] == "er"
    assert "managedFields" not in got["metadata"]
    rpc.clusters.delete("ssa")


def test_pagination_rejects_negative_inputs(stack):
    _, rpc, _ = stack
    with pytest.raises(Invalid, match="limit"):
        rpc.clusters.list(limit=-1)
    with pytest.raises(Invalid, match="continue_token"):
        rpc.clusters.list(limit=2, continue_token="-3")
    with pytest.raises(StoreError):
        rpc.compute_templates.update({"metadata": {"name": "x"}})


def test_error_mapping(stack):
    _, rpc, _ = stack
    rpc.clusters.create(make_cluster("dup").to_dict())
    with pytest.raises(AlreadyExists):
        rpc.clusters.create(make_cluster("dup").to_dict())
    stale = rpc.clusters.get("dup")
    fresh = rpc.clusters.get("dup")
    fresh["spec"]["suspend"] = True
    rpc.clusters.update(fresh)
    stale["spec"]["suspend"] = False        # write with the stale rv
    with pytest.raises(Conflict):
        rpc.clusters.update(stale)
    rpc.clusters.delete("dup")


def test_auth_required(stack):
    _, _, addr = stack
    anon = RpcClient(addr)
    with pytest.raises(StoreError, match="UNAUTHENTICATED"):
        anon.clusters.list()
    anon.close()


def test_pagination(stack):
    store, rpc, _ = stack
    for i in range(7):
        rpc.clusters.create(make_cluster(f"pg-{i}").to_dict())
    items, tok = rpc.clusters.list(limit=3)
    assert [i["metadata"]["name"] for i in items] == \
        ["pg-0", "pg-1", "pg-2"]
    assert tok
    items2, tok2 = rpc.clusters.list(limit=3, continue_token=tok)
    assert [i["metadata"]["name"] for i in items2] == \
        ["pg-3", "pg-4", "pg-5"]
    every = rpc.clusters.list_all_pages(page_size=2)
    assert len(every) == 7
    # ListAll spans namespaces
    other = make_cluster("pg-other").to_dict()
    other["metadata"]["namespace"] = "blue"
    rpc.clusters.create(other)
    all_ns = rpc.clusters.list_all_pages(all_namespaces=True)
    assert len(all_ns) == 8
    for o in all_ns:
        rpc.clusters.delete(o["metadata"]["name"],
                            o["metadata"].get("namespace", "default"))


def test_rpc_front_door_drives_operator(stack):
    """A cluster created over gRPC reconciles through the REAL
    controller; its status is visible back through gRPC — the typed
    surface and the operator share one resource layer."""
    from kuberay_tpu.controlplane.cluster_controller import (
        TpuClusterController,
    )
    from kuberay_tpu.controlplane.fake_kubelet import FakeKubelet
    from kuberay_tpu.controlplane.manager import Manager, owned_pod_mapper

    store, rpc, _ = stack
    mgr = Manager(store)
    ctrl = TpuClusterController(store, expectations=mgr.expectations)
    mgr.register(C.KIND_CLUSTER, ctrl.reconcile)
    mgr.map_owned(owned_pod_mapper)
    kubelet = FakeKubelet(store)
    rpc.clusters.create(make_cluster("via-rpc").to_dict())
    for _ in range(5):
        mgr.flush_delayed()
        mgr.run_until_idle()
        kubelet.step()
    mgr.flush_delayed()
    mgr.run_until_idle()
    got = rpc.clusters.get("via-rpc")
    assert got["status"]["state"] == "ready"
    assert got["status"]["readySlices"] >= 1
    rpc.clusters.delete("via-rpc")
