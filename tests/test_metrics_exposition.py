"""Prometheus text exposition gate for utils/metrics.py.

Golden-output coverage of ``MetricsRegistry.render()`` — counter /
gauge / histogram ordering, HELP/TYPE headers, cumulative ``le``
buckets — plus the label-value escaping the text-format spec requires
(a value containing ``"``, ``\\`` or a newline previously corrupted the
whole scrape) and ``drop_labeled`` removing all three series types.
"""

from kuberay_tpu.utils.metrics import ControlPlaneMetrics, MetricsRegistry


def test_render_golden_output():
    r = MetricsRegistry()
    r.describe("tpu_test_requests_total", "Requests served")
    r.describe("tpu_test_queue_depth", "Current queue depth")
    r.describe("tpu_test_latency_seconds", "Request latency")
    r.inc("tpu_test_requests_total", {"code": "200"}, value=3)
    r.inc("tpu_test_requests_total", {"code": "500"})
    r.set_gauge("tpu_test_queue_depth", 7, {"shard": "a"})
    # Two observations into the first bucket, one into the second:
    # cumulative le counts must be 2, 3, 3, ... and +Inf == count.
    r.observe("tpu_test_latency_seconds", 0.2)
    r.observe("tpu_test_latency_seconds", 0.3)
    r.observe("tpu_test_latency_seconds", 0.7)
    text = r.render()
    lines = text.splitlines()

    assert lines[0] == "# HELP tpu_test_requests_total Requests served"
    assert lines[1] == "# TYPE tpu_test_requests_total counter"
    assert lines[2] == 'tpu_test_requests_total{code="200"} 3.0'
    assert lines[3] == 'tpu_test_requests_total{code="500"} 1.0'
    assert lines[4] == "# HELP tpu_test_queue_depth Current queue depth"
    assert lines[5] == "# TYPE tpu_test_queue_depth gauge"
    assert lines[6] == 'tpu_test_queue_depth{shard="a"} 7'
    assert lines[7] == "# HELP tpu_test_latency_seconds Request latency"
    assert lines[8] == "# TYPE tpu_test_latency_seconds histogram"
    assert lines[9] == 'tpu_test_latency_seconds_bucket{le="0.5"} 2'
    assert lines[10] == 'tpu_test_latency_seconds_bucket{le="1"} 3'
    # Every later bucket stays cumulative, +Inf equals the count.
    assert 'tpu_test_latency_seconds_bucket{le="+Inf"} 3' in lines
    assert "tpu_test_latency_seconds_sum 1.2" in text
    assert "tpu_test_latency_seconds_count 3" in text
    # Histograms render after counters and gauges; each family gets its
    # TYPE header exactly once.
    assert text.count("# TYPE tpu_test_latency_seconds histogram") == 1
    assert text.endswith("\n")


def test_label_value_escaping_per_text_format_spec():
    r = MetricsRegistry()
    r.inc("tpu_test_total", {"path": 'a\\b"c\nd'})
    text = r.render()
    # Escape order matters: backslash first, then quote, then newline.
    assert 'tpu_test_total{path="a\\\\b\\"c\\nd"} 1.0' in text
    # The exposition stays one-sample-per-line (no raw newline leaked).
    for line in text.splitlines():
        assert line.startswith(("#", "tpu_test_total"))


def test_label_escaping_applies_to_histogram_series_too():
    r = MetricsRegistry()
    r.observe("tpu_test_seconds", 0.1, {"q": 'say "hi"'})
    text = r.render()
    assert 'q="say \\"hi\\""' in text
    # The synthetic le label composes with escaped user labels.
    assert 'tpu_test_seconds_bucket{q="say \\"hi\\"",le="0.5"} 1' in text


def test_help_text_escaping():
    r = MetricsRegistry()
    r.describe("tpu_test_total", "line one\nline two \\ backslash")
    r.inc("tpu_test_total")
    text = r.render()
    assert "# HELP tpu_test_total line one\\nline two \\\\ backslash" in text


def test_drop_labeled_removes_counters_gauges_and_histograms():
    r = MetricsRegistry()
    for cluster in ("keep", "gone"):
        labels = {"cluster": cluster}
        r.inc("tpu_test_total", labels)
        r.set_gauge("tpu_test_state", 1.0, labels)
        r.observe("tpu_test_seconds", 1.0, labels)
    r.drop_labeled("cluster", "gone")
    text = r.render()
    assert 'cluster="gone"' not in text
    assert 'tpu_test_total{cluster="keep"}' in text
    assert 'tpu_test_state{cluster="keep"}' in text
    assert 'tpu_test_seconds_count{cluster="keep"}' in text


def test_goodput_and_autoscaler_catalog_renders():
    """Golden exposition for the goodput/autoscaler series: counter +
    gauge families, sorted labels, HELP/TYPE headers exactly once."""
    m = ControlPlaneMetrics()
    m.goodput_seconds("TpuCluster", "productive", 12.5)
    m.goodput_seconds("TpuCluster", "interrupted", 2.5)
    m.set_goodput_ratio("TpuCluster", "default", "demo", 0.75)
    m.autoscaler_decision("TpuCluster", "up")
    m.autoscaler_decision("TpuCluster", "up")
    m.autoscaler_decision("TpuCluster", "down")
    text = m.render()
    assert "# TYPE tpu_goodput_seconds_total counter" in text
    assert ('tpu_goodput_seconds_total{kind="TpuCluster",'
            'phase="productive"} 12.5') in text
    assert ('tpu_goodput_seconds_total{kind="TpuCluster",'
            'phase="interrupted"} 2.5') in text
    assert "# TYPE tpu_goodput_ratio gauge" in text
    # Labels render sorted: kind, name, namespace.
    assert ('tpu_goodput_ratio{kind="TpuCluster",name="demo",'
            'namespace="default"} 0.75') in text
    assert "# TYPE tpu_autoscaler_decisions_total counter" in text
    assert ('tpu_autoscaler_decisions_total{direction="up",'
            'kind="TpuCluster"} 2.0') in text
    assert ('tpu_autoscaler_decisions_total{direction="down",'
            'kind="TpuCluster"} 1.0') in text
    for family in ("tpu_goodput_seconds_total", "tpu_goodput_ratio",
                   "tpu_autoscaler_decisions_total"):
        assert text.count(f"# TYPE {family} ") == 1
        assert f"# HELP {family} " in text


def test_gateway_counter_families_render_golden():
    """Golden exposition for the PR-7 gateway families: requests_total
    grew a ``backend`` label, plus the prefix-cache-hit and shed counter
    families — HELP/TYPE once each, labels sorted, values cumulative."""
    from kuberay_tpu.controlplane.store import ObjectStore
    from kuberay_tpu.serve.gateway import WeightedGateway

    r = MetricsRegistry()
    # The gateway's constructor owns the describes (HELP text is product
    # code, not test fixture); an empty route keeps it inert.
    gw = WeightedGateway(ObjectStore(), "no-route", metrics=r,
                         poll_interval=30.0)
    try:
        code, _ = gw.forward("/v1/completions", b"{}")
        assert code == 503
    finally:
        gw.stop()
    r.inc("tpu_gateway_requests_total", {"backend": "svc-a", "code": "200"},
          value=4)
    r.inc("tpu_gateway_prefix_cache_hits_total", {"backend": "svc-a"},
          value=3)
    r.inc("tpu_gateway_shed_total", {"reason": "queue_full"})
    r.inc("tpu_gateway_shed_total", {"reason": "deadline"}, value=2)
    text = r.render()
    assert ("# HELP tpu_gateway_requests_total Requests forwarded by the "
            "serve gateway, by backend service and HTTP status code") in text
    assert 'tpu_gateway_requests_total{backend="none",code="503"} 1.0' in text
    assert ('tpu_gateway_requests_total{backend="svc-a",code="200"} 4.0'
            in text)
    assert "# TYPE tpu_gateway_prefix_cache_hits_total counter" in text
    assert ('tpu_gateway_prefix_cache_hits_total{backend="svc-a"} 3.0'
            in text)
    assert "# TYPE tpu_gateway_shed_total counter" in text
    assert 'tpu_gateway_shed_total{reason="deadline"} 2.0' in text
    assert 'tpu_gateway_shed_total{reason="queue_full"} 1.0' in text
    for family in ("tpu_gateway_requests_total",
                   "tpu_gateway_prefix_cache_hits_total",
                   "tpu_gateway_shed_total"):
        assert text.count(f"# TYPE {family} ") == 1
        assert f"# HELP {family} " in text


def test_exemplar_on_landing_bucket_golden():
    """OpenMetrics exemplar: rides the cumulative le-line of exactly the
    bucket the observation landed in, as ``# {trace_id="..."} value ts``
    — so a p99 bucket links to one inspectable trace at
    /debug/traces?trace_id=."""
    r = MetricsRegistry()
    r.observe("tpu_test_latency_seconds", 0.2)
    r.observe("tpu_test_latency_seconds", 0.7, exemplar="t000042",
              exemplar_ts=123.5)
    lines = r.render().splitlines()
    # The 0.2 observation carried no exemplar: its bucket renders plain.
    assert 'tpu_test_latency_seconds_bucket{le="0.5"} 1' in lines
    # The 0.7 observation landed in le="1"; the exemplar rides that line
    # (raw observed value + timestamp, not the cumulative count).
    assert ('tpu_test_latency_seconds_bucket{le="1"} 2 '
            '# {trace_id="t000042"} 0.7 123.5') in lines
    # Later cumulative buckets count it but do NOT repeat the exemplar.
    assert 'tpu_test_latency_seconds_bucket{le="+Inf"} 2' in lines
    assert sum(1 for ln in lines if "# {trace_id=" in ln) == 1


def test_exemplar_trace_id_escaped_like_label_values():
    r = MetricsRegistry()
    r.observe("tpu_test_seconds", 0.1, exemplar='t"1\\2', exemplar_ts=1.0)
    text = r.render()
    # Same escaping contract as label values: backslash first, then quote.
    assert '# {trace_id="t\\"1\\\\2"} 0.1 1.0' in text


def test_exemplar_latest_observation_wins_per_bucket():
    r = MetricsRegistry()
    r.observe("tpu_test_seconds", 0.1, exemplar="t000001", exemplar_ts=1.0)
    r.observe("tpu_test_seconds", 0.2, exemplar="t000002", exemplar_ts=2.0)
    text = r.render()
    assert "t000001" not in text
    assert '# {trace_id="t000002"} 0.2 2.0' in text
    # An exemplar-less observation into the same bucket keeps the stored
    # exemplar (untraced traffic must not blank the trace link).
    r.observe("tpu_test_seconds", 0.3)
    assert '# {trace_id="t000002"} 0.2 2.0' in r.render()


def test_plain_render_unchanged_without_exemplars():
    """A registry that never receives an exemplar renders classic
    Prometheus text — no OpenMetrics suffix on any sample line, so
    pre-exemplar scrapers parse it untouched."""
    r = MetricsRegistry()
    r.inc("tpu_test_total", {"code": "200"})
    r.set_gauge("tpu_test_depth", 3)
    r.observe("tpu_test_seconds", 0.2)
    r.observe("tpu_test_seconds", 0.7)
    text = r.render()
    assert "# {" not in text
    for line in text.splitlines():
        if not line.startswith("#"):
            assert " # " not in line, line


def test_histogram_snapshot_reads_one_series():
    from kuberay_tpu.utils.metrics import SERVE_LATENCY_BUCKETS

    r = MetricsRegistry()
    assert r.histogram_snapshot("tpu_test_seconds") is None
    r.observe("tpu_test_seconds", 0.03, {"phase": "ttft"},
              buckets=SERVE_LATENCY_BUCKETS)
    r.observe("tpu_test_seconds", 0.03, {"phase": "ttft"},
              buckets=SERVE_LATENCY_BUCKETS)
    snap = r.histogram_snapshot("tpu_test_seconds", {"phase": "ttft"})
    assert snap["n"] == 2 and abs(snap["sum"] - 0.06) < 1e-9
    assert snap["buckets"] == list(SERVE_LATENCY_BUCKETS)
    assert sum(snap["counts"]) == 2
    # Snapshot is a copy: mutating it never corrupts the live histogram.
    snap["counts"][0] = 999
    assert sum(r.histogram_snapshot("tpu_test_seconds",
                                    {"phase": "ttft"})["counts"]) == 2


def test_controlplane_metrics_catalog_renders():
    m = ControlPlaneMetrics()
    m.observe_slice_ready("demo", "workers", 12.5)
    m.reconcile_error("TpuCluster")
    text = m.render()
    assert ("# HELP tpu_slice_ready_duration_seconds Seconds from slice "
            "creation to all hosts running (north-star metric)") in text
    assert ('tpu_slice_ready_duration_seconds_bucket{cluster="demo",'
            'group="workers",le="30"} 1') in text
    assert 'tpu_reconcile_errors_total{kind="TpuCluster"} 1.0' in text
