"""Ring attention == full attention, on a real sp-sharded mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from kuberay_tpu.ops.attention import attention_xla
from kuberay_tpu.parallel.mesh import MeshSpec
from kuberay_tpu.parallel.ring import ring_attention


def make_qkv(B=2, S=32, Hq=4, Hkv=4, D=16, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    q = jax.random.normal(ks[0], (B, S, Hq, D))
    k = jax.random.normal(ks[1], (B, S, Hkv, D))
    v = jax.random.normal(ks[2], (B, S, Hkv, D))
    return q, k, v


@pytest.mark.parametrize("causal", [True, False])
def test_ring_matches_full(causal):
    mesh = MeshSpec(dp=1, fsdp=1, tp=1, sp=4).build(jax.devices()[:4])
    q, k, v = make_qkv()
    ref = attention_xla(q, k, v, causal=causal)
    got = ring_attention(q, k, v, mesh, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-3, atol=2e-3)


def test_ring_gqa():
    mesh = MeshSpec(dp=1, fsdp=1, tp=1, sp=4).build(jax.devices()[:4])
    q, k, v = make_qkv(Hq=4, Hkv=2)
    ref = attention_xla(q, k, v, causal=True)
    got = ring_attention(q, k, v, mesh, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-3, atol=2e-3)


def test_ring_sharded_inputs_stay_sharded():
    """With inputs actually laid out over sp, the output keeps the layout
    (no implicit gather to one device)."""
    mesh = MeshSpec(dp=1, fsdp=1, tp=1, sp=8).build(jax.devices()[:8])
    q, k, v = make_qkv(S=64)
    sh = NamedSharding(mesh, P(None, "sp", None, None))
    qs, ks_, vs = (jax.device_put(x, sh) for x in (q, k, v))
    out = jax.jit(lambda a, b, c: ring_attention(a, b, c, mesh))(qs, ks_, vs)
    assert out.sharding.spec == P(None, "sp", None, None)
    ref = attention_xla(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-3, atol=2e-3)


def test_ring_gradients_flow():
    mesh = MeshSpec(dp=1, fsdp=1, tp=1, sp=4).build(jax.devices()[:4])
    q, k, v = make_qkv(S=16)

    def loss_ring(q, k, v):
        return (ring_attention(q, k, v, mesh) ** 2).sum()

    def loss_ref(q, k, v):
        return (attention_xla(q, k, v) ** 2).sum()

    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-3, atol=5e-3)


# ---------------------------------------------------------------------------
# RDMA (make_async_remote_copy) variant — parallel/ring_pallas.py


def _rand_qkv(B=2, S=256, Hq=4, Hkv=2, D=128, dtype=jnp.float32):
    ks = jax.random.split(jax.random.PRNGKey(7), 3)
    return (jax.random.normal(ks[0], (B, S, Hq, D), dtype),
            jax.random.normal(ks[1], (B, S, Hkv, D), dtype),
            jax.random.normal(ks[2], (B, S, Hkv, D), dtype))


@pytest.mark.parametrize("causal", [True, False])
def test_rdma_ring_matches_ppermute(causal):
    mesh = Mesh(np.array(jax.devices()[:4]), ("sp",))
    q, k, v = _rand_qkv()
    ref = jax.jit(lambda q, k, v: ring_attention(
        q, k, v, mesh, causal=causal))(q, k, v)
    out = jax.jit(lambda q, k, v: ring_attention(
        q, k, v, mesh, causal=causal, impl="rdma_interpret"))(q, k, v)
    assert float(jnp.max(jnp.abs(out - ref))) < 1e-5


def test_rdma_ring_gradients_match_ppermute():
    mesh = Mesh(np.array(jax.devices()[:4]), ("sp",))
    q, k, v = _rand_qkv(B=1, S=128)

    def loss(impl):
        def f(q, k, v):
            return jnp.sum(ring_attention(q, k, v, mesh, impl=impl) ** 2)
        return f

    gr = jax.jit(jax.grad(loss("rdma_interpret"), argnums=(0, 1, 2)))(q, k, v)
    gp = jax.jit(jax.grad(loss("ppermute"), argnums=(0, 1, 2)))(q, k, v)
    for a, b in zip(gr, gp):
        assert float(jnp.max(jnp.abs(a - b))) < 5e-5


def test_rdma_ring_multi_axis_mesh_falls_back_under_interpret():
    """The interpreter's remote-DMA discharge only handles single-axis
    meshes, so interpret-mode dispatch on a multi-axis mesh must fall
    back to the ppermute ring (the compiled kernel uses MESH coordinate
    dicts and handles the general case on hardware)."""
    devs = np.array(jax.devices()[:8])
    for names, shape in ((("dp", "sp"), (2, 4)), (("sp", "dp"), (4, 2))):
        mesh = Mesh(devs.reshape(shape), names)
        q, k, v = _rand_qkv(B=2, S=256)
        ref = jax.jit(lambda q, k, v: ring_attention(q, k, v, mesh))(q, k, v)
        out = jax.jit(lambda q, k, v: ring_attention(
            q, k, v, mesh, impl="rdma_interpret"))(q, k, v)
        assert float(jnp.max(jnp.abs(out - ref))) < 1e-6, names


def test_rdma_ring_vmem_fallback():
    """Oversized working sets silently fall back to the ppermute ring."""
    from kuberay_tpu.parallel import ring_pallas
    assert not ring_pallas.fits_vmem(8, 32768, 32768, 32, 8, 128)
    mesh = Mesh(np.array(jax.devices()[:4]), ("sp",))
    q, k, v = _rand_qkv(B=1, S=128)
    orig = ring_pallas.fits_vmem
    ring_pallas.fits_vmem = lambda *a, **kw: False
    try:
        out = jax.jit(lambda q, k, v: ring_attention(
            q, k, v, mesh, impl="rdma_interpret"))(q, k, v)
        ref = jax.jit(lambda q, k, v: ring_attention(q, k, v, mesh))(q, k, v)
        assert float(jnp.max(jnp.abs(out - ref))) < 1e-6
    finally:
        ring_pallas.fits_vmem = orig
