"""Ring attention == full attention, on a real sp-sharded mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from kuberay_tpu.ops.attention import attention_xla
from kuberay_tpu.parallel.mesh import MeshSpec
from kuberay_tpu.parallel.ring import ring_attention


def make_qkv(B=2, S=32, Hq=4, Hkv=4, D=16, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    q = jax.random.normal(ks[0], (B, S, Hq, D))
    k = jax.random.normal(ks[1], (B, S, Hkv, D))
    v = jax.random.normal(ks[2], (B, S, Hkv, D))
    return q, k, v


@pytest.mark.parametrize("causal", [True, False])
def test_ring_matches_full(causal):
    mesh = MeshSpec(dp=1, fsdp=1, tp=1, sp=4).build(jax.devices()[:4])
    q, k, v = make_qkv()
    ref = attention_xla(q, k, v, causal=causal)
    got = ring_attention(q, k, v, mesh, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-3, atol=2e-3)


def test_ring_gqa():
    mesh = MeshSpec(dp=1, fsdp=1, tp=1, sp=4).build(jax.devices()[:4])
    q, k, v = make_qkv(Hq=4, Hkv=2)
    ref = attention_xla(q, k, v, causal=True)
    got = ring_attention(q, k, v, mesh, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-3, atol=2e-3)


def test_ring_sharded_inputs_stay_sharded():
    """With inputs actually laid out over sp, the output keeps the layout
    (no implicit gather to one device)."""
    mesh = MeshSpec(dp=1, fsdp=1, tp=1, sp=8).build(jax.devices()[:8])
    q, k, v = make_qkv(S=64)
    sh = NamedSharding(mesh, P(None, "sp", None, None))
    qs, ks_, vs = (jax.device_put(x, sh) for x in (q, k, v))
    out = jax.jit(lambda a, b, c: ring_attention(a, b, c, mesh))(qs, ks_, vs)
    assert out.sharding.spec == P(None, "sp", None, None)
    ref = attention_xla(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-3, atol=2e-3)


def test_ring_gradients_flow():
    mesh = MeshSpec(dp=1, fsdp=1, tp=1, sp=4).build(jax.devices()[:4])
    q, k, v = make_qkv(S=16)

    def loss_ring(q, k, v):
        return (ring_attention(q, k, v, mesh) ** 2).sum()

    def loss_ref(q, k, v):
        return (attention_xla(q, k, v) ** 2).sum()

    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-3, atol=5e-3)
