"""History server, dashboard, and sample-manifest conformance."""

import json
import urllib.request

import pytest
import yaml

from kuberay_tpu.controlplane.store import ObjectStore
from kuberay_tpu.history.server import (
    HistoryCollector,
    HistoryServer,
    LocalStorage,
)
from kuberay_tpu.utils import constants as C
from kuberay_tpu.utils import features
from tests.test_api_types import make_cluster


def test_collector_archives_lifecycle(tmp_path):
    store = ObjectStore()
    storage = LocalStorage(str(tmp_path / "history"))
    collector = HistoryCollector(store, storage)

    c = make_cluster(name="archived")
    store.create(c.to_dict())
    obj = store.get(C.KIND_CLUSTER, "archived")
    obj["status"] = {"state": "ready", "readySlices": 1}
    store.update_status(obj)
    # An event about it.
    store.create({
        "apiVersion": "v1", "kind": "Event",
        "metadata": {"name": "archived.ev1", "namespace": "default"},
        "type": "Normal", "reason": "CreatedSlice", "message": "slice up",
        "involvedObject": {"kind": C.KIND_CLUSTER, "name": "archived",
                           "namespace": "default"},
        "eventTime": 1.0,
    })
    store.delete(C.KIND_CLUSTER, "archived")
    collector.close()   # drains the async archive queue

    doc = storage.get_doc(f"{C.KIND_CLUSTER}/default/archived.json")
    assert doc is not None
    assert doc["deleted"] is True
    assert doc["status"]["state"] == "ready"    # last status preserved
    assert any(e["reason"] == "CreatedSlice" for e in doc["events"])


def test_history_server_replay(tmp_path):
    storage = LocalStorage(str(tmp_path / "history"))
    storage.put_doc(f"{C.KIND_JOB}/default/old-job.json",
                    {"kind": C.KIND_JOB, "metadata": {"name": "old-job"},
                     "status": {"jobDeploymentStatus": "Complete"}})
    srv, url = HistoryServer(storage).serve_background()
    try:
        items = json.load(urllib.request.urlopen(
            f"{url}/api/history/TpuJob"))["items"]
        assert items[0]["metadata"]["name"] == "old-job"
        doc = json.load(urllib.request.urlopen(
            f"{url}/api/history/TpuJob/default/old-job"))
        assert doc["status"]["jobDeploymentStatus"] == "Complete"
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(f"{url}/api/history/TpuJob/default/nope")
    finally:
        srv.shutdown()


def test_dashboard_served():
    from kuberay_tpu.apiserver.server import serve_background
    store = ObjectStore()
    srv, url = serve_background(store)
    try:
        html = urllib.request.urlopen(f"{url}/dashboard").read().decode()
        assert "TpuClusters" in html and "tpuclusters" in html
    finally:
        srv.shutdown()


def test_apiserver_mounts_history(tmp_path):
    """The dashboard's history views read /api/history from the SAME
    apiserver endpoint (ref dashboard/src/app/history)."""
    from kuberay_tpu.apiserver.server import serve_background

    store = ObjectStore()
    storage = LocalStorage(str(tmp_path / "arch"))
    collector = HistoryCollector(store, storage)
    store.create(make_cluster(name="mounted").to_dict())
    store.delete(C.KIND_CLUSTER, "mounted")
    collector.close()

    srv, url = serve_background(store, history=HistoryServer(storage))
    try:
        rows = json.load(urllib.request.urlopen(
            f"{url}/api/history/clusters"))["items"]
        assert rows[0]["name"] == "mounted" and rows[0]["deleted"]
        doc = json.load(urllib.request.urlopen(
            f"{url}/api/history/TpuCluster/default/mounted"))
        assert doc["deleted"] is True
    finally:
        srv.shutdown()


def test_dashboard_create_job_flow():
    """POST the exact document shape the dashboard's New form builds and
    watch the operator drive it (ref dashboard/src/app/new)."""
    from kuberay_tpu.api.config import OperatorConfiguration
    from kuberay_tpu.operator import Operator

    op = Operator(OperatorConfiguration(), fake_kubelet=True)
    op.start(leader_election=False)
    try:
        doc = {
            "apiVersion": "tpu.dev/v1", "kind": "TpuJob",
            "metadata": {"name": "from-form", "namespace": "default"},
            "spec": {
                "entrypoint": "python -m kuberay_tpu.train.launcher",
                "shutdownAfterJobFinishes": True,
                "clusterSpec": {
                    "headGroupSpec": {"template": {"spec": {"containers": [
                        {"name": "head", "image": "tpu-trainer:latest"}]}}},
                    "workerGroupSpecs": [{
                        "groupName": "workers", "numSlices": 1,
                        "tpuVersion": "v5e", "topology": "2x4",
                        "template": {"spec": {"containers": [
                            {"name": "worker",
                             "image": "tpu-trainer:latest"}]}}}],
                },
            },
        }
        req = urllib.request.Request(
            f"{op.api_url}/apis/tpu.dev/v1/namespaces/default/tpujobs",
            data=json.dumps(doc).encode(),
            headers={"Content-Type": "application/json"}, method="POST")
        assert urllib.request.urlopen(req).status in (200, 201)
        for _ in range(30):
            op.run_until_idle()
        job = op.store.get(C.KIND_JOB, "from-form")
        assert job["status"].get("jobDeploymentStatus") not in (None, "New")
    finally:
        op.stop()


def test_all_samples_validate_and_provision():
    """Sample-manifest conformance (ref test/sampleyaml + SURVEY §4 tier 4):
    every cluster sample must actually reach ready under the operator."""
    import pathlib
    from kuberay_tpu.api.config import OperatorConfiguration
    from kuberay_tpu.operator import Operator

    features.set_gates({"TpuCronJob": True})
    op = Operator(OperatorConfiguration(), fake_kubelet=True)
    try:
        for path in sorted(pathlib.Path("samples").glob("*.yaml")):
            doc = yaml.safe_load(path.read_text())
            op.store.create(doc)
        for _ in range(30):
            op.run_until_idle()
        clusters = op.store.list(C.KIND_CLUSTER)
        # Direct cluster samples reach ready (autoscaled starts at 0 slices
        # but still gets a ready head; job/service samples spawn their own).
        direct = [c for c in clusters
                  if c["metadata"]["name"] in
                  ("v5e-singlehost", "v6e-16", "v6e-256", "autoscaled")]
        assert len(direct) == 4
        for c in direct:
            assert c["status"].get("state") == "ready", c["metadata"]["name"]
        # The v6e-256 sample created a full 64-host slice atomically.
        big = next(c for c in clusters if c["metadata"]["name"] == "v6e-256")
        assert big["status"]["desiredWorkerHosts"] == 64
        assert big["status"]["readyWorkerHosts"] == 64
        # Job samples progressed to cluster creation.
        jobs = {j["metadata"]["name"] for j in op.store.list(C.KIND_JOB)}
        assert "llama3-8b-pretrain" in jobs and "mixtral-ep" in jobs
    finally:
        op.stop()
        features.reset()


def test_dashboard_has_drilldown_views():
    """Job/service drill-downs shipped in the SPA (ref
    dashboard/src/app job + serve detail pages)."""
    from kuberay_tpu.apiserver.dashboard import DASHBOARD_HTML
    for marker in ("viewJob", "viewService", "Driver log (live tail)",
                   "#/job/", "#/service/", "Step events",
                   "/api/proxy/", "Traffic route", "Task events",
                   "/api/history/events/", "/api/history/timeline/"):
        assert marker in DASHBOARD_HTML, marker


@pytest.mark.timeout(60)
def test_coordinator_proxy_live_log_and_events(tmp_path):
    """The dashboard's live drill-down seam: the apiserver proxies
    whitelisted coordinator endpoints for a cluster, resolving the
    address from the cluster's status (never the request)."""
    import sys
    import time as _t

    from kuberay_tpu.apiserver.server import serve_background
    from kuberay_tpu.runtime.coordinator_client import CoordinatorClient
    from kuberay_tpu.runtime.coordinator_server import (
        CoordinatorServer,
        MemoryBackend,
    )

    coord = CoordinatorServer(state=MemoryBackend(),
                              log_dir=str(tmp_path / "logs"))
    csrv, curl = coord.serve_background()
    host, port = curl.rsplit("//", 1)[1].rsplit(":", 1)
    store = ObjectStore()
    srv, url = serve_background(store)
    try:
        client = CoordinatorClient(curl)
        client.submit_job("j-p", f"{sys.executable} -c 'print(\"hi\")'")
        deadline = _t.time() + 20
        while _t.time() < deadline and \
                client.get_job_info("j-p").status != "SUCCEEDED":
            _t.sleep(0.1)
        c = make_cluster(name="live").to_dict()
        store.create(c)
        obj = store.get(C.KIND_CLUSTER, "live")
        # Point the proxy at the live coordinator (tests run it on an
        # ephemeral port; production uses the standard dashboard port).
        obj["status"] = {"coordinatorAddress": f"{host}:{port}"}
        store.update_status(obj)
        import kuberay_tpu.utils.constants as consts
        orig = consts.PORT_DASHBOARD
        consts.PORT_DASHBOARD = int(port)
        try:
            logs = json.load(urllib.request.urlopen(
                f"{url}/api/proxy/default/live/jobs/j-p/logs"))
            assert "hi" in logs["logs"]
            evs = json.load(urllib.request.urlopen(
                f"{url}/api/proxy/default/live/events?job_id=j-p"))["events"]
            assert any(e["name"] == "job_finished" for e in evs)
        finally:
            consts.PORT_DASHBOARD = orig
        # Whitelist: arbitrary sub-paths do not proxy.
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(
                f"{url}/api/proxy/default/live/jobs/j-p/stop")
        # Unknown cluster -> 404, no outbound call.
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(
                f"{url}/api/proxy/default/nope/events")
    finally:
        srv.shutdown()
        csrv.shutdown()
