"""TpuService zero-downtime upgrade tests (modeled on
rayservice_controller_test.go + e2erayservice upgrade specs)."""

import pytest

from kuberay_tpu.api.common import ObjectMeta
from kuberay_tpu.api.tpuservice import (
    ClusterUpgradeOptions,
    ServiceUpgradeType,
    TpuService,
    TpuServiceSpec,
)
from kuberay_tpu.controlplane.cluster_controller import TpuClusterController
from kuberay_tpu.controlplane.fake_kubelet import FakeKubelet
from kuberay_tpu.controlplane.manager import (
    Manager,
    originated_from_mapper,
    owned_pod_mapper,
)
from kuberay_tpu.controlplane.service_controller import TpuServiceController
from kuberay_tpu.controlplane.store import ObjectStore
from kuberay_tpu.runtime.coordinator_client import FakeCoordinatorClient
from kuberay_tpu.utils import constants as C
from kuberay_tpu.utils import features
from tests.test_api_types import make_cluster


class ServiceHarness:
    def __init__(self):
        self.store = ObjectStore()
        self.manager = Manager(self.store)
        self.clients = {}   # cluster name -> FakeCoordinatorClient

        def provider(cluster_name, _status):
            client = self.clients.setdefault(cluster_name,
                                             FakeCoordinatorClient())
            return client

        self.cluster_ctrl = TpuClusterController(
            self.store, expectations=self.manager.expectations)
        self.svc_ctrl = TpuServiceController(self.store,
                                             client_provider=provider)
        self.manager.register(C.KIND_CLUSTER, self.cluster_ctrl.reconcile)
        self.manager.register(C.KIND_SERVICE, self.svc_ctrl.reconcile)
        self.manager.map_owned(owned_pod_mapper)
        self.manager.map_owned(originated_from_mapper(C.KIND_SERVICE))
        self.kubelet = FakeKubelet(self.store)

    def settle(self, rounds=10):
        for _ in range(rounds):
            self.manager.flush_delayed()
            self.manager.run_until_idle()
            self.kubelet.step()
            # Serve apps become RUNNING once the config lands.
            for client in self.clients.values():
                if client.serve_config is not None and not client.serve_apps:
                    client.set_serve_app("llm", "RUNNING")
        self.manager.flush_delayed()
        self.manager.run_until_idle()

    def svc(self, name="svc"):
        return TpuService.from_dict(self.store.get(C.KIND_SERVICE, name))


@pytest.fixture
def h():
    return ServiceHarness()


@pytest.fixture(autouse=True)
def reset_gates():
    features.reset()
    yield
    features.reset()


def make_service(name="svc"):
    return TpuService(
        metadata=ObjectMeta(name=name),
        spec=TpuServiceSpec(
            serveConfig={"applications": [{"name": "llm",
                                           "model": "llama3-8b"}]},
            clusterSpec=make_cluster(accelerator="v5e", topology="4x4",
                                     replicas=1).spec,
            clusterDeletionDelaySeconds=0,
        ),
    )


def test_first_rollout_promotes(h):
    h.store.create(make_service().to_dict())
    h.settle()
    s = h.svc()
    assert s.status.activeServiceStatus is not None
    assert s.status.pendingServiceStatus is None
    assert s.status.serviceStatus == "Running"
    assert s.status.numServeEndpoints > 0
    # Stable serve service points at the active cluster.
    stable = h.store.get("Service", "svc-serve-svc")
    assert stable["spec"]["selector"][C.LABEL_CLUSTER] == \
        s.status.activeServiceStatus.clusterName


def test_scale_only_change_is_in_place(h):
    h.store.create(make_service().to_dict())
    h.settle()
    active = h.svc().status.activeServiceStatus.clusterName
    obj = h.store.get(C.KIND_SERVICE, "svc")
    obj["spec"]["clusterSpec"]["workerGroupSpecs"][0]["replicas"] = 2
    obj["spec"]["clusterSpec"]["workerGroupSpecs"][0]["maxReplicas"] = 2
    h.store.update(obj)
    h.settle()
    s = h.svc()
    # Same cluster, no pending: scale flowed through in place.
    assert s.status.activeServiceStatus.clusterName == active
    assert s.status.pendingServiceStatus is None
    cluster = h.store.get(C.KIND_CLUSTER, active)
    assert cluster["spec"]["workerGroupSpecs"][0]["replicas"] == 2


def test_spec_change_rolls_new_cluster(h):
    h.store.create(make_service().to_dict())
    h.settle()
    old_active = h.svc().status.activeServiceStatus.clusterName
    # Real spec change: new image.
    obj = h.store.get(C.KIND_SERVICE, "svc")
    obj["spec"]["clusterSpec"]["workerGroupSpecs"][0]["template"]["spec"][
        "containers"][0]["image"] = "model:v2"
    h.store.update(obj)
    h.settle(rounds=14)
    s = h.svc()
    assert s.status.activeServiceStatus.clusterName != old_active
    assert s.status.pendingServiceStatus is None
    assert s.status.serviceStatus == "Running"
    # Old cluster retired (deletion delay 0).
    assert h.store.try_get(C.KIND_CLUSTER, old_active) is None
    # Stable service now selects the new cluster.
    stable = h.store.get("Service", "svc-serve-svc")
    assert stable["spec"]["selector"][C.LABEL_CLUSTER] == \
        s.status.activeServiceStatus.clusterName


def test_upgrade_strategy_none_blocks_roll(h):
    svc = make_service()
    svc.spec.upgradeStrategy = ServiceUpgradeType.NONE
    h.store.create(svc.to_dict())
    h.settle()
    active = h.svc().status.activeServiceStatus.clusterName
    obj = h.store.get(C.KIND_SERVICE, "svc")
    obj["spec"]["clusterSpec"]["workerGroupSpecs"][0]["template"]["spec"][
        "containers"][0]["image"] = "model:v2"
    h.store.update(obj)
    h.settle()
    s = h.svc()
    assert s.status.activeServiceStatus.clusterName == active
    assert s.status.pendingServiceStatus is None


def test_suspend_deletes_clusters(h):
    h.store.create(make_service().to_dict())
    h.settle()
    active = h.svc().status.activeServiceStatus.clusterName
    obj = h.store.get(C.KIND_SERVICE, "svc")
    obj["spec"]["suspend"] = True
    h.store.update(obj)
    h.settle()
    s = h.svc()
    assert s.status.serviceStatus == "Suspended"
    assert h.store.try_get(C.KIND_CLUSTER, active) is None


def test_incremental_upgrade_steps_traffic(h):
    features.set_gates({"TpuServiceIncrementalUpgrade": True})
    svc = make_service()
    svc.spec.upgradeStrategy = ServiceUpgradeType.INCREMENTAL
    svc.spec.upgradeOptions = ClusterUpgradeOptions(
        stepSizePercent=100, intervalSeconds=1)
    h.store.create(svc.to_dict())
    h.settle()
    old_active = h.svc().status.activeServiceStatus.clusterName
    seen_routes = []
    h.store.watch(lambda ev: seen_routes.append(ev)
                  if ev.kind == "TrafficRoute" else None)
    obj = h.store.get(C.KIND_SERVICE, "svc")
    obj["spec"]["clusterSpec"]["workerGroupSpecs"][0]["template"]["spec"][
        "containers"][0]["image"] = "model:v2"
    h.store.update(obj)
    h.settle(rounds=16)
    s = h.svc()
    # Rolled fully through the weighted steps.
    assert s.status.activeServiceStatus.clusterName != old_active
    # A weighted route existed during the roll and was cleaned up after.
    assert any(ev.type == "ADDED" for ev in seen_routes)
    assert h.store.list("TrafficRoute") == []


def test_active_unhealthy_triggers_self_heal(h):
    """serviceUnhealthySecondThreshold: a persistently unhealthy active
    cluster is replaced whole via the promotion path."""
    svc = make_service()
    svc.spec.serviceUnhealthySecondThreshold = 0   # heal immediately
    h.store.create(svc.to_dict())
    h.settle()
    s = h.svc()
    old_active = s.status.activeServiceStatus.clusterName
    # Break the active cluster's serve app.
    h.clients[old_active].set_serve_app("llm", "UNHEALTHY", "oom")
    h.settle(rounds=16)
    s = h.svc()
    assert s.status.activeServiceStatus.clusterName != old_active
    assert s.status.serviceStatus == "Running"
    events = [e for e in h.store.list("Event")
              if e["reason"] == "ActiveUnhealthy"]
    assert events


def test_pending_unhealthy_recreated(h):
    """deploymentUnhealthySecondThreshold: a pending cluster that never
    gets healthy is torn down and retried."""
    svc = make_service()
    svc.spec.deploymentUnhealthySecondThreshold = 0
    h.store.create(svc.to_dict())

    # Make every new cluster's app come up UNHEALTHY instead of RUNNING.
    broken = {"on": True}

    def settle_broken(rounds=4):
        # Bounded iterations: the broken phase churns (abandon/recreate by
        # design) and would otherwise spin a long time per round.
        for _ in range(rounds):
            h.manager.flush_delayed()
            h.manager.run_until_idle(max_iterations=40)
            h.kubelet.step()
            for client in h.clients.values():
                if client.serve_config is not None and not client.serve_apps:
                    client.set_serve_app(
                        "llm", "UNHEALTHY" if broken["on"] else "RUNNING")
        h.manager.flush_delayed()
        h.manager.run_until_idle(max_iterations=40)

    settle_broken()
    first_events = [e for e in h.store.list("Event")
                    if e["reason"] == "PendingUnhealthy"]
    assert first_events, "stuck pending should be recreated"
    # Heal the environment: new attempts come up RUNNING and promote.
    broken["on"] = False
    for client in h.clients.values():
        client.serve_apps.clear()
    h.settle(rounds=16)
    s = h.svc()
    assert s.status.serviceStatus == "Running"
    assert s.status.activeServiceStatus is not None


def test_head_pod_serve_label(h):
    svc = make_service()
    svc.spec.excludeHeadPodFromServe = True
    h.store.create(svc.to_dict())
    h.settle()
    s = h.svc()
    heads = h.store.list("Pod", labels={
        C.LABEL_CLUSTER: s.status.activeServiceStatus.clusterName,
        C.LABEL_NODE_TYPE: C.NODE_TYPE_HEAD})
    assert heads and all(
        p["metadata"]["labels"].get(C.LABEL_SERVE) == "false" for p in heads)
    # Excluded heads don't count as endpoints.
    workers_running = h.store.list("Pod", labels={
        C.LABEL_CLUSTER: s.status.activeServiceStatus.clusterName,
        C.LABEL_NODE_TYPE: C.NODE_TYPE_WORKER})
    assert s.status.numServeEndpoints == len(
        [p for p in workers_running
         if p["status"].get("phase") == "Running"])


def test_serve_tier_stamped_into_traffic_route(h):
    """spec.serveTier flows into every TrafficRoute backend the
    incremental upgrade writes — the gateway's two-hop scheduler keys
    off this field — and an unknown tier normalizes to mixed rather
    than poisoning routing."""
    features.set_gates({"TpuServiceIncrementalUpgrade": True})
    svc = make_service()
    svc.spec.serveTier = C.SERVE_TIER_PREFILL
    svc.spec.upgradeStrategy = ServiceUpgradeType.INCREMENTAL
    svc.spec.upgradeOptions = ClusterUpgradeOptions(
        stepSizePercent=100, intervalSeconds=1)
    h.store.create(svc.to_dict())
    h.settle()
    routes = []
    h.store.watch(lambda ev: routes.append(ev.obj)
                  if ev.kind == "TrafficRoute" and ev.type != "DELETED"
                  else None)
    obj = h.store.get(C.KIND_SERVICE, "svc")
    obj["spec"]["clusterSpec"]["workerGroupSpecs"][0]["template"]["spec"][
        "containers"][0]["image"] = "model:v2"
    h.store.update(obj)
    h.settle(rounds=16)
    backends = [b for r in routes for b in r["spec"]["backends"]]
    assert backends, "no weighted route observed during the roll"
    assert all(b["tier"] == C.SERVE_TIER_PREFILL for b in backends)


def test_unknown_serve_tier_normalizes_to_mixed(h):
    features.set_gates({"TpuServiceIncrementalUpgrade": True})
    svc = make_service()
    svc.spec.serveTier = "bogus-tier"
    svc.spec.upgradeStrategy = ServiceUpgradeType.INCREMENTAL
    svc.spec.upgradeOptions = ClusterUpgradeOptions(
        stepSizePercent=100, intervalSeconds=1)
    h.store.create(svc.to_dict())
    h.settle()
    routes = []
    h.store.watch(lambda ev: routes.append(ev.obj)
                  if ev.kind == "TrafficRoute" and ev.type != "DELETED"
                  else None)
    obj = h.store.get(C.KIND_SERVICE, "svc")
    obj["spec"]["clusterSpec"]["workerGroupSpecs"][0]["template"]["spec"][
        "containers"][0]["image"] = "model:v2"
    h.store.update(obj)
    h.settle(rounds=16)
    backends = [b for r in routes for b in r["spec"]["backends"]]
    assert backends
    assert all(b["tier"] == C.SERVE_TIER_MIXED for b in backends)


# ---------------------------------------------------------------------------
# burn-rate-gated incremental upgrades (docs/upgrades.md): rollback,
# abort latch, abandoned pending, prewarm/drain handshakes — all under a
# virtual clock and a scriptable gate
# ---------------------------------------------------------------------------

from kuberay_tpu.api.tpuservice import UpgradeState  # noqa: E402
from kuberay_tpu.sim.clock import VirtualClock  # noqa: E402
from kuberay_tpu.utils.names import serve_service_name  # noqa: E402


class FakeGate:
    """Scriptable stand-in for controlplane.upgrade.BurnRateGate."""

    def __init__(self):
        self.healthy = True
        self.alert = None
        self.forgotten = []

    def verdict(self, backend):
        if self.healthy:
            return True, None
        return False, dict(self.alert or
                           {"name": "upgrade-green-availability",
                            "window": "fast"})

    def forget(self, backend):
        self.forgotten.append(backend)


def gated_harness(**opts):
    """ServiceHarness wired for the closed-loop ramp: feature gate on, a
    FakeGate verdict source, and a virtual clock so interval/hold maths
    are exact instead of wall-time races."""
    features.set_gates({"TpuServiceIncrementalUpgrade": True})
    h = ServiceHarness()
    clock = VirtualClock(start=10_000.0)
    h.svc_ctrl._now = clock.now
    gate = FakeGate()
    h.svc_ctrl.upgrade_gate = gate
    svc = make_service()
    svc.spec.upgradeStrategy = ServiceUpgradeType.INCREMENTAL
    base = dict(stepSizePercent=50, intervalSeconds=3600,
                maxRollbacks=2, holdSeconds=60)
    base.update(opts)
    svc.spec.upgradeOptions = ClusterUpgradeOptions(**base)
    h.store.create(svc.to_dict())
    h.settle()
    return h, clock, gate


def bump_image(h, image):
    obj = h.store.get(C.KIND_SERVICE, "svc")
    obj["spec"]["clusterSpec"]["workerGroupSpecs"][0]["template"]["spec"][
        "containers"][0]["image"] = image
    h.store.update(obj)


def green_weight(h):
    cs = h.svc().status.pendingServiceStatus
    return None if cs is None else cs.trafficWeightPercent


def test_gated_rollback_snaps_weight_then_holds_then_reramps(h):
    h, clock, gate = gated_harness(prewarmPrompts=4)
    old_active = h.svc().status.activeServiceStatus.clusterName
    bump_image(h, "model:v2")
    h.settle(rounds=6)

    # Pre-warm handshake: the ramp parks at weight 0 until the gateway
    # acks the prefix replay in the route status.
    s = h.svc()
    assert s.status.upgrade.state == UpgradeState.PREWARMING
    assert green_weight(h) == 0
    green_svc = serve_service_name(s.status.pendingServiceStatus.clusterName)
    route = h.store.get("TrafficRoute", "svc-route")
    route.setdefault("status", {})["prewarmed"] = {green_svc: 4}
    h.store.update_status(route)

    # First step: interval since lastUpgradeStepTime=0 is long past.
    h.settle(rounds=2)
    assert green_weight(h) == 50
    assert h.svc().status.upgrade.state == UpgradeState.RAMPING
    # Interval gate holds the next step until the virtual clock moves.
    h.settle(rounds=2)
    assert green_weight(h) == 50

    # The green fleet burns: one decision snaps weight to 0.
    gate.healthy = False
    gate.alert = {"name": "upgrade-green-ttft", "window": "fast"}
    h.settle(rounds=2)
    s = h.svc()
    assert green_weight(h) == 0
    assert s.status.activeServiceStatus.trafficWeightPercent == 100
    assert s.status.upgrade.state == UpgradeState.ROLLED_BACK
    assert s.status.upgrade.rollbacks == 1
    assert s.status.upgrade.lastAlert["name"] == "upgrade-green-ttft"

    # Clean burn again, but holdSeconds of backoff must elapse first.
    gate.healthy = True
    h.settle(rounds=2)
    assert green_weight(h) == 0
    assert h.svc().status.upgrade.state == UpgradeState.HOLDING
    clock.advance(3600.0)                      # past hold AND interval
    h.settle(rounds=2)
    assert green_weight(h) == 50
    clock.advance(3600.0)
    h.settle(rounds=4)

    # 100% with no drain gate promotes in the same reconcile.
    s = h.svc()
    assert s.status.pendingServiceStatus is None
    assert s.status.activeServiceStatus.clusterName != old_active
    assert s.status.upgrade.state == UpgradeState.PROMOTED
    assert s.status.upgrade.rollbacks == 1     # history survives promote
    assert green_svc in gate.forgotten         # fresh windows next time
    assert h.store.list("TrafficRoute") == []


def test_gated_abort_latches_spec_hash_until_spec_changes(h):
    h, clock, gate = gated_harness(maxRollbacks=0)
    old_active = h.svc().status.activeServiceStatus.clusterName
    bump_image(h, "model:v2")
    h.settle(rounds=6)
    assert green_weight(h) == 50

    # Budget is zero: the first breach at weight > 0 aborts the upgrade.
    gate.healthy = False
    h.settle(rounds=2)
    s = h.svc()
    assert s.status.upgrade.state == UpgradeState.ABORTED
    assert s.status.upgrade.abortedSpecHash
    assert s.status.pendingServiceStatus is None
    assert s.status.activeServiceStatus.clusterName == old_active
    assert s.status.activeServiceStatus.trafficWeightPercent == 100
    assert h.store.list("TrafficRoute") == []
    aborted_hash = s.status.upgrade.abortedSpecHash

    # The latch: the same bad spec is NOT retried, even with a clean gate.
    gate.healthy = True
    h.settle(rounds=4)
    s = h.svc()
    assert s.status.pendingServiceStatus is None
    assert s.status.upgrade.state == UpgradeState.ABORTED

    # A new spec clears it — and the fresh ramp starts with fresh budgets.
    bump_image(h, "model:v3")
    h.settle(rounds=6)
    clock.advance(3600.0)
    h.settle(rounds=6)
    clock.advance(3600.0)
    h.settle(rounds=6)
    s = h.svc()
    assert s.status.activeServiceStatus.clusterName != old_active
    assert s.status.upgrade.state == UpgradeState.PROMOTED
    assert s.status.upgrade.rollbacks == 0
    assert s.status.upgrade.abortedSpecHash != aborted_hash


def test_abandoned_stale_pending_restarts_with_fresh_budgets(h):
    """Satellite: a spec change landing mid-upgrade retires the stale-hash
    pending cluster whole and the next upgrade starts cleanly."""
    h, clock, gate = gated_harness()
    old_active = h.svc().status.activeServiceStatus.clusterName
    bump_image(h, "model:v2")
    h.settle(rounds=6)
    assert green_weight(h) == 50
    stale_pending = h.svc().status.pendingServiceStatus.clusterName

    # Burn once so the in-flight ramp carries spent budget state.
    gate.healthy = False
    h.settle(rounds=2)
    assert h.svc().status.upgrade.rollbacks == 1
    gate.healthy = True

    # The operator ships v3 while v2's ramp is parked at weight 0.
    bump_image(h, "model:v3")
    h.settle(rounds=2)
    s = h.svc()
    assert s.status.pendingServiceStatus is not None
    assert s.status.pendingServiceStatus.clusterName != stale_pending
    # Stale pending cluster is gone, and the ramp state reset with it.
    assert h.store.try_get(C.KIND_CLUSTER, stale_pending) is None
    assert any(c.type == "RollingBack" and c.reason == "PendingAbandoned"
               for c in s.status.conditions)

    clock.advance(3600.0)
    h.settle(rounds=6)
    clock.advance(3600.0)
    h.settle(rounds=6)
    s = h.svc()
    assert s.status.activeServiceStatus.clusterName != old_active
    assert s.status.upgrade.state == UpgradeState.PROMOTED
    assert s.status.upgrade.rollbacks == 0     # fresh budgets, not v2's
    image = h.store.get(C.KIND_CLUSTER,
                        s.status.activeServiceStatus.clusterName)[
        "spec"]["workerGroupSpecs"][0]["template"]["spec"][
        "containers"][0]["image"]
    assert image == "model:v3"


def test_gated_promotion_waits_for_blue_drain_ack(h):
    h, clock, gate = gated_harness(stepSizePercent=100,
                                   drainTimeoutSeconds=300)
    blue = h.svc().status.activeServiceStatus.clusterName
    bump_image(h, "model:v2")
    h.settle(rounds=6)

    # Green stepped straight to 100, but blue still has admitted work:
    # promotion holds in Draining until the gateway acks.
    s = h.svc()
    assert green_weight(h) == 100
    assert s.status.upgrade.state == UpgradeState.DRAINING
    assert s.status.pendingServiceStatus is not None
    h.settle(rounds=2)
    assert h.svc().status.upgrade.state == UpgradeState.DRAINING

    route = h.store.get("TrafficRoute", "svc-route")
    route.setdefault("status", {})["drained"] = {serve_service_name(blue): True}
    h.store.update_status(route)
    h.settle(rounds=4)
    s = h.svc()
    assert s.status.pendingServiceStatus is None
    assert s.status.upgrade.state == UpgradeState.PROMOTED
    assert s.status.activeServiceStatus.clusterName != blue
