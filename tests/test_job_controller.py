"""TpuJob state-machine tests (modeled on rayjob_controller_test.go +
rayjob_controller_suspended_test.go specs)."""

import time

import pytest

from kuberay_tpu.api.common import ObjectMeta
from kuberay_tpu.api.tpujob import (
    DeletionRule,
    DeletionStrategy,
    JobDeploymentStatus,
    JobStatus,
    JobSubmissionMode,
    TpuJob,
    TpuJobSpec,
)
from kuberay_tpu.controlplane.cluster_controller import TpuClusterController
from kuberay_tpu.controlplane.fake_kubelet import FakeKubelet
from kuberay_tpu.controlplane.job_controller import TpuJobController
from kuberay_tpu.controlplane.manager import (
    Manager,
    originated_from_mapper,
    owned_pod_mapper,
)
from kuberay_tpu.controlplane.store import ObjectStore
from kuberay_tpu.runtime.coordinator_client import FakeCoordinatorClient
from kuberay_tpu.utils import constants as C
from tests.test_api_types import make_cluster


class JobHarness:
    def __init__(self):
        self.store = ObjectStore()
        self.manager = Manager(self.store)
        self.coordinator = FakeCoordinatorClient()
        self.cluster_ctrl = TpuClusterController(
            self.store, expectations=self.manager.expectations)
        self.job_ctrl = TpuJobController(
            self.store, client_provider=lambda _status: self.coordinator)
        self.manager.register(C.KIND_CLUSTER, self.cluster_ctrl.reconcile)
        self.manager.register(C.KIND_JOB, self.job_ctrl.reconcile)
        self.manager.map_owned(owned_pod_mapper)
        self.manager.map_owned(originated_from_mapper(C.KIND_JOB))
        self.kubelet = FakeKubelet(self.store)

    def settle(self, rounds=8):
        for _ in range(rounds):
            self.manager.flush_delayed()
            self.manager.run_until_idle()
            self.kubelet.step()
        self.manager.flush_delayed()
        self.manager.run_until_idle()

    def job(self, name="train"):
        return TpuJob.from_dict(self.store.get(C.KIND_JOB, name))


def make_job(name="train", **kw):
    spec = TpuJobSpec(
        entrypoint="python -m kuberay_tpu.train.launcher --model llama3_8b",
        clusterSpec=make_cluster(accelerator="v5p", topology="2x2x2",
                                 replicas=1).spec,
        submissionMode=JobSubmissionMode.HTTP,
        shutdownAfterJobFinishes=True,
    )
    for k, v in kw.items():
        setattr(spec, k, v)
    return TpuJob(metadata=ObjectMeta(name=name), spec=spec)


@pytest.fixture
def h():
    return JobHarness()


def drive_job(h, name="train"):
    """Settle until the job reaches Running (cluster comes up on the way)."""
    for _ in range(10):
        h.settle()
        j = h.job(name)
        if j.status.jobDeploymentStatus == JobDeploymentStatus.RUNNING:
            return j
    return h.job(name)


def test_job_happy_path(h):
    h.store.create(make_job().to_dict())
    j = drive_job(h)
    assert j.status.jobDeploymentStatus == JobDeploymentStatus.RUNNING
    assert j.status.clusterName
    # Cluster was created and became ready.
    cluster = h.store.get(C.KIND_CLUSTER, j.status.clusterName)
    assert cluster["status"]["state"] == "ready"
    assert h.coordinator.submit_count == 1
    # App finishes -> Complete; cluster torn down (shutdownAfterJobFinishes).
    h.coordinator.set_job_status(j.status.jobId, "SUCCEEDED")
    h.settle()
    j = h.job()
    assert j.status.jobDeploymentStatus == JobDeploymentStatus.COMPLETE
    assert j.status.jobStatus == JobStatus.SUCCEEDED
    h.settle()
    assert h.store.try_get(C.KIND_CLUSTER, j.status.clusterName) is None


def test_job_retry_with_fresh_cluster(h):
    h.store.create(make_job(backoffLimit=1).to_dict())
    j = drive_job(h)
    first_cluster = j.status.clusterName
    h.coordinator.set_job_status(j.status.jobId, "FAILED", "oom")
    h.settle()
    j = drive_job(h)
    assert int(j.status.failed) == 1
    assert j.status.clusterName != first_cluster  # fresh cluster per attempt
    assert j.status.jobDeploymentStatus == JobDeploymentStatus.RUNNING
    # Second failure exhausts the budget.
    h.coordinator.set_job_status(j.status.jobId, "FAILED", "oom again")
    h.settle()
    j = h.job()
    assert j.status.jobDeploymentStatus == JobDeploymentStatus.FAILED
    assert j.status.reason == "AppFailed"


def test_job_suspend_resume(h):
    h.store.create(make_job().to_dict())
    j = drive_job(h)
    cluster_name = j.status.clusterName
    obj = h.store.get(C.KIND_JOB, "train")
    obj["spec"]["suspend"] = True
    h.store.update(obj)
    h.settle()
    j = h.job()
    assert j.status.jobDeploymentStatus == JobDeploymentStatus.SUSPENDED
    assert h.store.try_get(C.KIND_CLUSTER, cluster_name) is None
    # Resume.
    obj = h.store.get(C.KIND_JOB, "train")
    obj["spec"]["suspend"] = False
    h.store.update(obj)
    j = drive_job(h)
    assert j.status.jobDeploymentStatus == JobDeploymentStatus.RUNNING


def test_job_active_deadline(h):
    h.store.create(make_job(activeDeadlineSeconds=1).to_dict())
    j = drive_job(h)
    time.sleep(1.1)
    h.settle()
    j = h.job()
    assert j.status.jobDeploymentStatus == JobDeploymentStatus.FAILED
    assert j.status.reason == "DeadlineExceeded"


def test_job_deletion_rules(h):
    job = make_job(
        shutdownAfterJobFinishes=False,
        deletionStrategy=DeletionStrategy(rules=[
            DeletionRule(policy="DeleteWorkers", condition="Succeeded",
                         ttlSeconds=0),
        ]))
    h.store.create(job.to_dict())
    j = drive_job(h)
    h.coordinator.set_job_status(j.status.jobId, "SUCCEEDED")
    h.settle()
    j = h.job()
    assert j.status.jobDeploymentStatus == JobDeploymentStatus.COMPLETE
    h.settle()
    # Cluster survives but workers scaled to zero; head remains.
    cluster = h.store.get(C.KIND_CLUSTER, j.status.clusterName)
    assert cluster["spec"]["workerGroupSpecs"][0]["replicas"] == 0
    pods = h.store.list("Pod", labels={C.LABEL_NODE_TYPE: "worker"})
    assert pods == [] or all(p["metadata"].get("deletionTimestamp") for p in pods)


def test_job_k8s_submitter_mode(h):
    h.store.create(make_job(submissionMode=JobSubmissionMode.K8S_JOB).to_dict())
    j = drive_job(h)
    sub = h.store.get("Job", "train-submitter")
    cmd = sub["spec"]["template"]["spec"]["containers"][0]["command"]
    assert cmd[0] == "/bin/sh" and "--job-id" in cmd[2]
    # Submitter completion marks the job complete.
    sub["status"] = {"succeeded": 1}
    h.store.update_status(sub)
    h.coordinator.set_job_status(j.status.jobId, "SUCCEEDED")
    h.settle()
    assert h.job().status.jobDeploymentStatus == JobDeploymentStatus.COMPLETE


def test_job_cluster_selector_mode(h):
    # Pre-existing shared cluster; the job must not delete it on finish.
    shared = make_cluster(name="shared", accelerator="v5e", topology="2x2",
                          replicas=1)
    shared.metadata.labels = {"team": "ml"}
    h.store.create(shared.to_dict())
    h.settle()
    job = make_job(clusterSelector={"team": "ml"})
    job.spec.clusterSpec = None
    h.store.create(job.to_dict())
    j = drive_job(h)
    assert j.status.clusterName == "shared"
    h.coordinator.set_job_status(j.status.jobId, "SUCCEEDED")
    h.settle()
    assert h.job().status.jobDeploymentStatus == JobDeploymentStatus.COMPLETE
    h.settle()
    assert h.store.try_get(C.KIND_CLUSTER, "shared") is not None


def test_job_invalid_spec_fails(h):
    job = make_job(entrypoint="")
    h.store.create(job.to_dict())
    h.settle()
    j = h.job()
    assert j.status.jobDeploymentStatus == JobDeploymentStatus.FAILED
    assert j.status.reason == "ValidationFailed"


# -- SidecarMode (ref common/job.go:95-158, e2erayjob sidecar specs) ---------

def _set_submitter_terminated(h, cluster_name, exit_code):
    from kuberay_tpu.utils.names import head_pod_name
    pod = h.store.get("Pod", head_pod_name(cluster_name))
    pod.setdefault("status", {})["containerStatuses"] = [
        {"name": C.SUBMITTER_CONTAINER_NAME,
         "state": {"terminated": {"exitCode": exit_code}}}]
    h.store.update_status(pod)


def _head_submitter(h, cluster_name):
    from kuberay_tpu.utils.names import head_pod_name
    pod = h.store.get("Pod", head_pod_name(cluster_name))
    subs = [c for c in pod["spec"]["containers"]
            if c["name"] == C.SUBMITTER_CONTAINER_NAME]
    return subs[0] if subs else None


def test_job_sidecar_mode_completes(h):
    h.store.create(make_job(
        submissionMode=JobSubmissionMode.SIDECAR).to_dict())
    j = drive_job(h)
    assert j.status.jobDeploymentStatus == JobDeploymentStatus.RUNNING
    # The submitter container rides the head pod, localhost-addressed,
    # waiting for the colocated coordinator.
    sub = _head_submitter(h, j.status.clusterName)
    assert sub is not None
    assert "--wait-for-coordinator" in sub["command"][2]
    assert "127.0.0.1" in sub["command"][2]
    # Pod-level Never (ref rayjob_controller.go:1035): the exited
    # submitter surfaces as state.terminated instead of restarting.
    from kuberay_tpu.utils.names import head_pod_name
    head = h.store.get("Pod", head_pod_name(j.status.clusterName))
    assert head["spec"].get("restartPolicy") == "Never"
    # Terminal container state drives the job outcome.
    _set_submitter_terminated(h, j.status.clusterName, 0)
    h.settle()
    j = h.job()
    assert j.status.jobDeploymentStatus == JobDeploymentStatus.COMPLETE
    assert j.status.jobStatus == JobStatus.SUCCEEDED


def test_job_sidecar_mode_fails_with_backoff(h):
    h.store.create(make_job(submissionMode=JobSubmissionMode.SIDECAR,
                            backoffLimit=1).to_dict())
    j = drive_job(h)
    first_cluster = j.status.clusterName
    _set_submitter_terminated(h, first_cluster, 1)
    h.settle()
    j = drive_job(h)
    # Retry on a fresh cluster whose head pod got a fresh submitter.
    assert int(j.status.failed) == 1
    assert j.status.clusterName != first_cluster
    assert j.status.jobDeploymentStatus == JobDeploymentStatus.RUNNING
    assert _head_submitter(h, j.status.clusterName) is not None
    _set_submitter_terminated(h, j.status.clusterName, 1)
    h.settle()
    j = h.job()
    assert j.status.jobDeploymentStatus == JobDeploymentStatus.FAILED
    assert j.status.reason == "AppFailed"


def test_job_sidecar_refuses_cluster_selector(h):
    job = make_job(submissionMode=JobSubmissionMode.SIDECAR,
                   clusterSelector={"team": "ml"})
    job.spec.clusterSpec = None
    h.store.create(job.to_dict())
    h.settle()
    j = h.job()
    assert j.status.jobDeploymentStatus == JobDeploymentStatus.FAILED
    assert j.status.reason == "ValidationFailed"
