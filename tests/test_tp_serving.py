"""Tensor-parallel serving: sharded engines must be token-identical to
single-device engines, like-for-like (same engine mode) on the virtual
8-device CPU mesh.

The serving counterpart of the reference's vLLM-TPU role (reference
``config/samples/vllm/ray-service.vllm-tpu-v6e-singlehost.yaml``): params
shard over the mesh's tp axis, the KV cache shards its kv-head axis, and
every jitted step runs SPMD (serve/sharding.py).
"""

import dataclasses

import jax
import pytest

from kuberay_tpu.models import llama
from kuberay_tpu.serve.engine import Request, ServeEngine
from kuberay_tpu.serve.sharding import (
    cache_shardings,
    serve_mesh,
    validate_tp,
)

CFG = llama.CONFIGS["llama_tiny"]
PROMPTS = [[1, 2, 3, 4, 5], [7, 8, 9], [20] * 10, list(range(30))]


@pytest.fixture(scope="module")
def params():
    return llama.init_params(CFG, jax.random.PRNGKey(0))


def run_engine(params, mesh, cfg=CFG, **kw):
    eng = ServeEngine(cfg, params, max_slots=4, max_len=128, mesh=mesh, **kw)
    for i, p in enumerate(PROMPTS):
        # One sampling slot (exercises the temperature path under SPMD);
        # the rest greedy.
        eng.add_request(Request(f"r{i}", p, max_new_tokens=12,
                                temperature=0.7 if i == 3 else 0.0))
    out = {r.request_id: r.tokens for r in eng.run()}
    assert len(out) == len(PROMPTS)
    return out


def test_tp2_token_identical(params):
    ref = run_engine(params, None)
    tp = run_engine(params, serve_mesh(2))
    assert ref == tp


def test_tp2_int8_kv_token_identical(params):
    """int8 cache quantization under tp: the shard_mapped quant decode
    kernel on local head shards must reproduce the single-device int8
    engine exactly."""
    ref = run_engine(params, None, kv_quant="int8", decode_impl="xla")
    tp = run_engine(params, serve_mesh(2), kv_quant="int8",
                    decode_impl="xla")
    assert ref == tp


def test_tp2_chunked_and_speculative(params):
    """Chunked prefill and speculative verify both run SPMD; each must
    match its own single-device twin (chunked scheduling consumes RNG
    differently from whole-prompt prefill, so cross-mode comparisons are
    not expected to hold)."""
    assert run_engine(params, None, prefill_chunk=16) == \
        run_engine(params, serve_mesh(2), prefill_chunk=16)
    assert run_engine(params, None, speculative=4) == \
        run_engine(params, serve_mesh(2), speculative=4)


def test_tp4_wider_config():
    cfg = dataclasses.replace(CFG, n_heads=8, n_kv_heads=4)
    params = llama.init_params(cfg, jax.random.PRNGKey(1))
    ref = run_engine(params, None, cfg=cfg)
    tp = run_engine(params, serve_mesh(4), cfg=cfg)
    assert ref == tp


def test_tp4_kv_replicated(params):
    """tp beyond n_kv_heads: llama_tiny has 2 kv heads, tp=4 puts the
    extra factor on the kv-replication axis (the llama3_8b-on-v5e-16
    configuration: 8 kv heads, 16 chips).  Still token-identical."""
    mesh = serve_mesh(4, n_kv_heads=CFG.n_kv_heads)
    assert dict(mesh.shape) == {"tp": 2, "tpr": 2}
    assert run_engine(params, None) == run_engine(params, mesh)


def test_validate_tp_rejects_uneven_split():
    with pytest.raises(ValueError, match="n_kv_heads"):
        validate_tp(CFG, serve_mesh(4))   # 2 kv heads, no replication ok'd
    validate_tp(CFG, serve_mesh(2))       # divides everything
    from kuberay_tpu.serve.sharding import tp_factors
    with pytest.raises(ValueError, match="not a[\\s]+multiple"):
        tp_factors(3, 2)


def test_init_sharded_params_places_shards():
    """init_sharded_params must materialize weights already split — the
    whole point is that the full model never exists on one device."""
    from kuberay_tpu.serve.sharding import init_sharded_params
    mesh = serve_mesh(2)
    p = init_sharded_params(CFG, jax.random.PRNGKey(0), mesh)
    wq = p["layers"]["wq"]           # logical axes (layers, embed, heads)
    assert not wq.sharding.is_fully_replicated
    assert wq.sharding.shard_shape(wq.shape)[-1] == wq.shape[-1] // 2


def test_cache_shardings_match_cache_tree():
    from kuberay_tpu.serve.kv_cache import init_kv_cache
    mesh = serve_mesh(2)
    for quant in ("none", "int8"):
        cache = init_kv_cache(CFG, 4, 128, quant=quant)
        sh = cache_shardings(CFG, mesh, quant)
        # Tree structures must line up leaf-for-leaf for device_put.
        jax.tree.map(lambda a, s: None, cache, sh)


@pytest.mark.timeout(300)
def test_multihost_lockstep_two_processes(params):
    """Production-shaped multi-host serving: two processes (2 virtual CPU
    devices each) join one jax.distributed group; host 0 schedules and
    broadcasts step plans, host 1 replays them (serve/multihost.py).
    Host 0's tokens must equal the single-process engine's."""
    import json
    import os
    import subprocess
    import sys

    script = os.path.join(os.path.dirname(__file__), "helpers",
                          "tp_serve_worker.py")

    def spawn(worker_id):
        env = dict(os.environ)
        env.update({
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
            "TPU_WORKER_HOSTNAMES": "localhost,localhost",
            "TPU_NUM_PROCESSES": "2",
            "TPU_WORKER_ID": str(worker_id),
        })
        return subprocess.Popen([sys.executable, script], env=env,
                                stdout=subprocess.PIPE,
                                stderr=subprocess.STDOUT, text=True)

    procs = [spawn(0), spawn(1)]
    outs = [p.communicate(timeout=280)[0] for p in procs]
    for p, out in zip(procs, outs):
        assert p.returncode == 0, out[-2000:]
    result = next(line for line in outs[0].splitlines()
                  if line.startswith("RESULT "))
    got = json.loads(result[len("RESULT "):])
    assert "replayed" in outs[1]

    # Single-process reference with the same requests/settings (the
    # worker widens llama_tiny to 4 kv heads for tp=4).
    cfg = dataclasses.replace(CFG, n_heads=8, n_kv_heads=4)
    ref_params = llama.init_params(cfg, jax.random.PRNGKey(0))
    eng = ServeEngine(cfg, ref_params, max_slots=2, max_len=64)
    from tests.helpers.tp_serve_worker import LOCKSTEP_REQUESTS
    for i, (p, kw) in enumerate(LOCKSTEP_REQUESTS):
        eng.add_request(Request(f"r{i}", p, **kw))
    want = {r.request_id: r.tokens for r in eng.run()}
    assert got == want


def test_engine_cache_stays_sharded(params):
    """The cache must round-trip sharded through a step — an accidental
    all-gather would defeat the memory split that makes >1-chip models
    servable."""
    mesh = serve_mesh(2)
    eng = ServeEngine(CFG, params, max_slots=4, max_len=128, mesh=mesh)
    eng.add_request(Request("r", [1, 2, 3], max_new_tokens=2))
    eng.step()
    k = eng.cache["k"]
    assert not k.sharding.is_fully_replicated
    # kv-head axis (index 3) is the split one.
    shard_shape = k.sharding.shard_shape(k.shape)
    assert shard_shape[3] == CFG.n_kv_heads // 2


def test_mixtral_tp2_token_identical():
    """MoE serving under TP: expert weights replicate (SERVE_RULES maps
    'expert' to None), mlp width shards over the joint tp axes, and the
    dropless decode routing partitions under SPMD unchanged."""
    from kuberay_tpu.models import mixtral

    cfg = mixtral.CONFIGS["mixtral_tiny"]
    mparams = mixtral.init_params(cfg, jax.random.PRNGKey(0))

    def run(mesh):
        eng = ServeEngine(cfg, mparams, max_slots=2, max_len=64, mesh=mesh)
        for i, p in enumerate([[1, 2, 3, 4, 5], [9, 8, 7], [11] * 8]):
            eng.add_request(Request(f"r{i}", p, max_new_tokens=6))
        return {r.request_id: r.tokens for r in eng.run()}

    assert run(None) == run(serve_mesh(2))


def test_paged_tp2_token_identical(params):
    """Paged KV pool under TP: the pool's kv-head axis shards on tp, the
    block-table-native Pallas decode runs per-shard via shard_map, and
    gathered prefill views use the stock sharded attention — token-
    identical to the single-device paged engine, prefix sharing and
    chunked prefill included."""
    from kuberay_tpu.serve.paged_engine import PagedServeEngine

    # r2 block-shares the [1..5] prompt prefix with r0 (block_size 8
    # boundary within the shared 5-token prefix is not aligned, so this
    # exercises the partial-share path too).
    prompts = [[1, 2, 3, 4, 5], [9, 8, 7], [1, 2, 3, 4, 5, 6, 7],
               list(range(30))]

    def run(mesh, **kw):
        eng = PagedServeEngine(CFG, params, max_slots=3, max_len=64,
                               block_size=8, mesh=mesh, **kw)
        for i, p in enumerate(prompts):
            eng.add_request(Request(f"r{i}", p, max_new_tokens=6))
        return {r.request_id: r.tokens for r in eng.run()}

    assert run(None) == run(serve_mesh(2))
    assert run(None, prefill_chunk=16) == \
        run(serve_mesh(2), prefill_chunk=16)
    # Pool stays sharded through steps.
    eng = PagedServeEngine(CFG, params, max_slots=2, max_len=64,
                           block_size=8, mesh=serve_mesh(2))
    eng.add_request(Request("r", [1, 2, 3], max_new_tokens=2))
    eng.step()
    k = eng.cache["k"]
    assert not k.sharding.is_fully_replicated
    assert k.sharding.shard_shape(k.shape)[1] == CFG.n_kv_heads // 2


@pytest.mark.timeout(300)
def test_multihost_paged_lockstep(params):
    """Multi-host PAGED serving: block tables ride every broadcast plan,
    so followers replay host 0's allocator decisions without running an
    allocator.  Host 0's tokens must equal the single-process paged
    engine's."""
    import json
    import os
    import subprocess
    import sys

    from kuberay_tpu.serve.paged_engine import PagedServeEngine

    script = os.path.join(os.path.dirname(__file__), "helpers",
                          "tp_serve_worker.py")

    def spawn(worker_id):
        env = dict(os.environ)
        env.update({
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
            "TPU_WORKER_HOSTNAMES": "localhost,localhost",
            "TPU_NUM_PROCESSES": "2",
            "TPU_WORKER_ID": str(worker_id),
        })
        return subprocess.Popen([sys.executable, script, "--paged"],
                                env=env, stdout=subprocess.PIPE,
                                stderr=subprocess.STDOUT, text=True)

    procs = [spawn(0), spawn(1)]
    outs = [p.communicate(timeout=280)[0] for p in procs]
    for p, out in zip(procs, outs):
        assert p.returncode == 0, out[-2000:]
    result = next(line for line in outs[0].splitlines()
                  if line.startswith("RESULT "))
    got = json.loads(result[len("RESULT "):])

    cfg = dataclasses.replace(CFG, n_heads=8, n_kv_heads=4)
    ref_params = llama.init_params(cfg, jax.random.PRNGKey(0))
    eng = PagedServeEngine(cfg, ref_params, max_slots=2, max_len=64,
                           block_size=8)
    from tests.helpers.tp_serve_worker import LOCKSTEP_REQUESTS
    for i, (p, kw) in enumerate(LOCKSTEP_REQUESTS):
        eng.add_request(Request(f"r{i}", p, **kw))
    want = {r.request_id: r.tokens for r in eng.run()}
    assert got == want
