"""APIServer V2 reverse proxy (ref apiserversdk/proxy.go:28-40): auth
injection, verb pass-through (PATCH + streaming watch included), retry
round-tripper, and route scoping."""

import json
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from kuberay_tpu.apiserver.proxy import ReverseProxy, serve_background
from kuberay_tpu.apiserver.server import (
    serve_background as api_serve_background,
)
from kuberay_tpu.controlplane.store import ObjectStore
from tests.test_api_types import make_cluster

TOKEN = "upstream-secret"
BASE = "/apis/tpu.dev/v1/namespaces/default/tpuclusters"


@pytest.fixture()
def stack():
    """Real apiserver (bearer-auth required) fronted by the proxy; the
    CLIENT sends no credentials — the proxy injects them."""
    store = ObjectStore()
    api_srv, api_url = api_serve_background(store, token=TOKEN)
    proxy = ReverseProxy(api_url, token=TOKEN)
    px_srv, px_url = serve_background(proxy)
    yield store, px_url
    px_srv.shutdown()
    api_srv.shutdown()


def _req(url, path, method="GET", body=None, ctype="application/json",
         expect=200):
    req = urllib.request.Request(
        url + path, data=json.dumps(body).encode() if body is not None
        else None, method=method, headers={"Content-Type": ctype})
    try:
        with urllib.request.urlopen(req, timeout=10) as resp:
            assert resp.status == expect, resp.status
            payload = resp.read()
            return json.loads(payload) if payload else {}
    except urllib.error.HTTPError as e:
        assert e.code == expect, (e.code, e.read()[:300])
        return json.loads(e.read() or b"{}")


def test_full_verb_passthrough_with_auth_injection(stack):
    store, px = stack
    # Direct (un-authed) access to the upstream would 401; through the
    # proxy every verb works with no client credentials.
    doc = make_cluster("via-proxy").to_dict()
    created = _req(px, BASE, "POST", doc, expect=201)
    assert created["metadata"]["name"] == "via-proxy"
    got = _req(px, BASE + "/via-proxy")
    assert got["metadata"]["uid"] == created["metadata"]["uid"]
    got["spec"]["suspend"] = True
    _req(px, BASE + "/via-proxy", "PUT", got)
    # PATCH (strategic) through the proxy.
    out = _req(px, BASE + "/via-proxy", "PATCH",
               {"spec": {"workerGroupSpecs": [
                   {"groupName": "workers", "replicas": 1}]}},
               ctype="application/strategic-merge-patch+json")
    assert out["spec"]["suspend"] is True
    lst = _req(px, BASE)
    assert [i["metadata"]["name"] for i in lst["items"]] == ["via-proxy"]
    _req(px, BASE + "/via-proxy", "DELETE")
    assert store.try_get("TpuCluster", "via-proxy") is None


def test_streaming_watch_through_proxy(stack):
    store, px = stack
    rv = store.resource_version()
    events = []

    def watch():
        req = urllib.request.Request(
            f"{px}{BASE}?watch=true&resourceVersion={rv}"
            f"&timeoutSeconds=10")
        with urllib.request.urlopen(req, timeout=15) as resp:
            for line in resp:
                events.append(json.loads(line))
                if len(events) >= 2:
                    return

    t = threading.Thread(target=watch, daemon=True)
    t.start()
    time.sleep(0.3)
    store.create(make_cluster("w1").to_dict())
    store.patch("TpuCluster", "w1", "default",
                {"metadata": {"labels": {"x": "y"}}})
    t.join(timeout=15)
    assert not t.is_alive(), "watch through proxy never delivered"
    assert [e["type"] for e in events] == ["ADDED", "MODIFIED"]
    assert events[0]["object"]["metadata"]["name"] == "w1"


def test_route_scoping(stack):
    _, px = stack
    # Non-tpu.dev paths never reach the upstream.
    body = _req(px, "/api/v1/namespaces/default/pods", expect=404)
    assert body["message"] == "path not proxied"
    _req(px, "/apis/apps/v1/namespaces/default/deployments", expect=404)
    _req(px, "/version", expect=404)


def test_events_selector_pinned():
    """The proxied events routes must carry the tpu.dev fieldSelector
    regardless of what the client asked for (withFieldSelector role) —
    with the field label each API group actually defines: core v1 Events
    support involvedObject.*, events.k8s.io/v1 Events support
    regarding.* (a regarding selector on the core path 400s against a
    real apiserver)."""
    seen = {}

    class Upstream(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_GET(self):
            seen["path"] = self.path
            data = b'{"kind":"EventList","items":[]}'
            self.send_response(200)
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

    up = ThreadingHTTPServer(("127.0.0.1", 0), Upstream)
    threading.Thread(target=up.serve_forever, daemon=True).start()
    try:
        proxy = ReverseProxy(f"http://127.0.0.1:{up.server_port}")
        srv, px = serve_background(proxy)
        _req(px, "/api/v1/namespaces/default/events"
                 "?fieldSelector=involvedObject.kind=Pod")
        assert "involvedObject.apiVersion%3Dtpu.dev%2Fv1" in seen["path"] \
            or "involvedObject.apiVersion=tpu.dev%2Fv1" in seen["path"], \
            seen
        _req(px, "/apis/events.k8s.io/v1/namespaces/default/events"
                 "?fieldSelector=regarding.kind=Pod")
        assert "regarding.apiVersion%3Dtpu.dev%2Fv1" in seen["path"] or \
            "regarding.apiVersion=tpu.dev%2Fv1" in seen["path"], seen
        srv.shutdown()
    finally:
        up.shutdown()


def test_dot_segment_traversal_refused(stack):
    """A path that normalizes OUT of the tpu.dev scope must 404 before
    touching the upstream (Go's ServeMux cleans paths; urllib does not,
    so the proxy normalizes explicitly)."""
    _, px = stack
    _req(px, "/apis/tpu.dev/v1/../../api/v1/namespaces/kube-system/"
             "secrets", expect=404)
    _req(px, "/apis/tpu.dev/v1/%2e%2e/%2e%2e/api/v1/namespaces/"
             "kube-system/secrets", expect=404)
    # Encoded slashes (and any other percent-escape, including the
    # double-encoded form) are refused outright: a decode-before-route
    # upstream would resolve %2f into a separator AFTER our prefix
    # check, reaching out-of-scope paths with injected credentials.
    _req(px, "/apis/tpu.dev/v1/..%2f..%2fapi/v1/namespaces/"
             "kube-system/secrets", expect=404)
    _req(px, "/apis/tpu.dev/v1/..%252f..%252fapi/v1/namespaces/"
             "kube-system/secrets", expect=404)
    _req(px, "/apis/tpu.dev/v1/namespaces/default/tpuclusters%2Fx",
         expect=404)
    # Normalization is not over-eager: an in-scope path with a redundant
    # dot segment still works.
    lst = _req(px, "/apis/tpu.dev/v1/namespaces/./default/tpuclusters")
    assert lst["items"] == []


def test_bodyless_status_no_chunked_framing():
    """204/304 upstream responses must pass through without a body or
    Transfer-Encoding (RFC 7230 §3.3); 200s with Content-Length keep
    plain framing."""
    class Upstream(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_DELETE(self):
            self.send_response(204)
            self.end_headers()

        def do_GET(self):
            data = b'{"kind":"TpuClusterList","items":[]}'
            self.send_response(200)
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

    up = ThreadingHTTPServer(("127.0.0.1", 0), Upstream)
    threading.Thread(target=up.serve_forever, daemon=True).start()
    try:
        proxy = ReverseProxy(f"http://127.0.0.1:{up.server_port}")
        srv, px = serve_background(proxy)
        # Raw socket so we can see the exact framing on the wire.
        import socket
        host, port = srv.server_address

        def raw(method, path):
            s = socket.create_connection((host, port), timeout=10)
            s.sendall(f"{method} {path} HTTP/1.1\r\nHost: x\r\n"
                      f"Connection: close\r\n\r\n".encode())
            out = b""
            while True:
                b = s.recv(65536)
                if not b:
                    break
                out += b
            s.close()
            return out

        resp = raw("DELETE", BASE + "/x")
        head = resp.split(b"\r\n\r\n", 1)[0].lower()
        assert b"204" in resp.split(b"\r\n", 1)[0]
        assert b"transfer-encoding" not in head, resp
        assert resp.split(b"\r\n\r\n", 1)[1] == b"", resp

        resp = raw("GET", BASE)
        head, body = resp.split(b"\r\n\r\n", 1)
        assert b"content-length" in head.lower(), resp
        assert b"transfer-encoding" not in head.lower(), resp
        assert json.loads(body)["kind"] == "TpuClusterList"
        srv.shutdown()
    finally:
        up.shutdown()


def test_retry_roundtripper_replays_body():
    """First attempts get 503; the proxy retries with the SAME body and
    succeeds — non-idempotent verbs included (the upstream refused the
    earlier attempts, so replay is safe)."""
    state = {"n": 0, "bodies": []}

    class Flaky(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_POST(self):
            state["n"] += 1
            body = self.rfile.read(
                int(self.headers.get("Content-Length", 0)))
            state["bodies"].append(body)
            if state["n"] <= 2:
                self.send_response(503)
                self.send_header("Content-Length", "0")
                self.end_headers()
                return
            self.send_response(201)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    up = ThreadingHTTPServer(("127.0.0.1", 0), Flaky)
    threading.Thread(target=up.serve_forever, daemon=True).start()
    try:
        proxy = ReverseProxy(f"http://127.0.0.1:{up.server_port}")
        srv, px = serve_background(proxy)
        out = _req(px, BASE, "POST", {"kind": "TpuCluster"}, expect=201)
        assert out == {"kind": "TpuCluster"}
        assert state["n"] == 3
        assert len(set(state["bodies"])) == 1      # body replayed intact
        srv.shutdown()
    finally:
        up.shutdown()


def test_unreachable_upstream_502():
    proxy = ReverseProxy("http://127.0.0.1:1")       # nothing listens
    srv, px = serve_background(proxy)
    try:
        body = _req(px, BASE, expect=502)
        assert "unreachable" in body["message"]
    finally:
        srv.shutdown()


def test_middleware_seam():
    """MuxConfig.Middleware analogue: wraps the forwarding function."""
    store = ObjectStore()
    api_srv, api_url = api_serve_background(store, token=TOKEN)

    def audit(next_fwd):
        calls = []

        def fwd(method, path, query, headers, body):
            calls.append((method, path))
            if method == "DELETE":
                return 403, [("Content-Type", "application/json")], iter(
                    [b'{"kind":"Status","code":403,'
                     b'"message":"deletes forbidden by middleware"}'])
            return next_fwd(method, path, query, headers, body)

        fwd.calls = calls
        return fwd

    proxy = ReverseProxy(api_url, token=TOKEN, middleware=audit)
    srv, px = serve_background(proxy)
    try:
        _req(px, BASE, "POST", make_cluster("mw").to_dict(), expect=201)
        body = _req(px, BASE + "/mw", "DELETE", expect=403)
        assert "forbidden" in body["message"]
        assert store.try_get("TpuCluster", "mw") is not None
    finally:
        srv.shutdown()
        api_srv.shutdown()
