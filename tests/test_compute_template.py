"""ComputeTemplate: named slice presets resolved server-side.

Reference capability: apiserver v1 ComputeTemplate service
(proto/config.proto; templates stored as ConfigMaps, resolved by the
resource manager when materializing clusters).  Here templates are CRs
(or builtin presets) resolved by the cluster controller at reconcile
time, so CLI/SDK/raw-YAML clients all benefit.
"""

import pytest

from kuberay_tpu.api.common import ObjectMeta
from kuberay_tpu.api.computetemplate import (
    BUILTIN_TEMPLATES,
    ComputeTemplate,
    ComputeTemplateSpec,
    builtin_template,
    validate_compute_template,
)
from kuberay_tpu.api.config import OperatorConfiguration
from kuberay_tpu.api.tpucluster import TpuCluster
from kuberay_tpu.operator import Operator
from kuberay_tpu.utils import constants as C
from kuberay_tpu.utils import features
from kuberay_tpu.utils.validation import validate_cluster


@pytest.fixture(autouse=True)
def reset_gates():
    features.reset()
    yield
    features.reset()


@pytest.fixture
def op():
    o = Operator(OperatorConfiguration(), fake_kubelet=True)
    yield o
    o.kubelet.close()


def settle(op, rounds=8):
    for _ in range(rounds):
        op.run_until_idle()


def make_templated_cluster(template_name, name="demo"):
    return {
        "apiVersion": "tpu.dev/v1", "kind": "TpuCluster",
        "metadata": {"name": name},
        "spec": {
            "headGroupSpec": {"template": {"spec": {"containers": [
                {"name": "head", "image": "img"}]}}},
            "workerGroupSpecs": [{
                "groupName": "workers",
                "computeTemplate": template_name,
                "replicas": 1, "maxReplicas": 2,
                "template": {"spec": {"containers": [
                    {"name": "worker", "image": "img"}]}},
            }],
        },
    }


def test_builtin_presets_are_valid():
    for name in BUILTIN_TEMPLATES:
        t = builtin_template(name)
        assert validate_compute_template(t) == [], name


def test_builtin_template_resolves_and_provisions(op):
    op.store.create(make_templated_cluster("tpu-medium"))
    settle(op)
    got = op.store.get(C.KIND_CLUSTER, "demo")
    assert got["status"]["state"] == "ready", got["status"]
    # v5e 4x4 = 4 hosts per slice: 1 head + 4 workers.
    workers = op.store.list(
        "Pod", labels={C.LABEL_NODE_TYPE: C.NODE_TYPE_WORKER})
    assert len(workers) == 4
    env = {e["name"]: e.get("value", "")
           for e in workers[0]["spec"]["containers"][0]["env"]}
    assert env[C.ENV_TPU_TOPOLOGY] == "4x4"
    # Template cpu/memory landed as container requests.
    res = workers[0]["spec"]["containers"][0]["resources"]["requests"]
    assert res["cpu"] == "24" and res["memory"] == "48Gi"
    # The stored CR keeps the indirection (resolution is in-memory only).
    stored_group = got["spec"]["workerGroupSpecs"][0]
    assert stored_group["computeTemplate"] == "tpu-medium"
    assert "accelerator" not in stored_group or \
        stored_group["accelerator"] == "v5e"


def test_cr_template_shadows_builtin(op):
    op.store.create(ComputeTemplate(
        metadata=ObjectMeta(name="tpu-medium"),
        spec=ComputeTemplateSpec(accelerator="v5p", topology="2x2x1",
                                 nodeSelectors={"pool": "gold"}),
    ).to_dict())
    op.store.create(make_templated_cluster("tpu-medium"))
    settle(op)
    workers = op.store.list(
        "Pod", labels={C.LABEL_NODE_TYPE: C.NODE_TYPE_WORKER})
    env = {e["name"]: e.get("value", "")
           for e in workers[0]["spec"]["containers"][0]["env"]}
    assert env[C.ENV_TPU_TOPOLOGY] == "2x2x1"
    assert workers[0]["spec"]["nodeSelector"]["pool"] == "gold"


def test_unknown_template_fails_validation(op):
    op.store.create(make_templated_cluster("no-such-preset"))
    settle(op)
    got = op.store.get(C.KIND_CLUSTER, "demo")
    assert got["status"]["state"] == "failed"
    assert "no-such-preset" in got["status"].get("reason", "")
    assert not op.store.list("Pod")


def test_cluster_self_heals_when_template_appears(op):
    """Cluster referencing a not-yet-created template fails, then recovers
    as soon as the ComputeTemplate CR lands (event-mapped resync — no
    manual touch of the cluster object)."""
    op.store.create(make_templated_cluster("late-template"))
    settle(op)
    assert op.store.get(C.KIND_CLUSTER, "demo")["status"]["state"] == "failed"
    op.store.create(ComputeTemplate(
        metadata=ObjectMeta(name="late-template"),
        spec=ComputeTemplateSpec(accelerator="v5e", topology="2x2"),
    ).to_dict())
    settle(op)
    got = op.store.get(C.KIND_CLUSTER, "demo")
    assert got["status"]["state"] == "ready", got["status"]


def test_admission_rejects_invalid_template():
    """Invalid templates are rejected at the door (shared validation
    surface), not discovered later by referencing clusters."""
    from kuberay_tpu.utils.validation import kind_validators
    v = kind_validators()["ComputeTemplate"]
    assert v({"metadata": {"name": "bad"},
              "spec": {"accelerator": "v5e", "topology": "3x5"}})
    assert v({"metadata": {"name": "ok"},
              "spec": {"accelerator": "v5e", "topology": "4x4"}}) == []


def test_sdk_create_template_payload_is_valid():
    from kuberay_tpu.client.apis import ComputeTemplateApi

    class _Capture:
        def create(self, body):
            self.body = body
            return body
    api = ComputeTemplateApi.__new__(ComputeTemplateApi)
    api.client = _Capture()
    body = api.create_template("t1", "v5p", "2x2x1", cpu="8", memory="16Gi")
    t = ComputeTemplate.from_dict(body)
    assert validate_compute_template(t) == []
    assert t.spec.cpu == "8" and t.spec.memory == "16Gi"


def test_group_explicit_fields_win_over_template_resources():
    """A group that sets its own cpu requests keeps them; the template
    only fills gaps."""
    from kuberay_tpu.api.computetemplate import resolve_group_template
    cluster = TpuCluster.from_dict(make_templated_cluster("tpu-small"))
    group = cluster.spec.workerGroupSpecs[0]
    group.template.spec.containers[0].resources.requests["cpu"] = "99"
    resolve_group_template(group, builtin_template("tpu-small"))
    res = group.template.spec.containers[0].resources
    assert res.requests["cpu"] == "99"             # explicit wins
    assert res.requests["memory"] == "16Gi"        # gap filled
    assert group.accelerator == "v5e" and group.topology == "2x2"
    assert validate_cluster(cluster) == []


def test_worker_group_alias_keys_accepted():
    """SDK/dashboard friendly keys (numSlices/tpuVersion) parse into the
    canonical fields; canonical keys win when both appear."""
    doc = make_templated_cluster("")
    g = doc["spec"]["workerGroupSpecs"][0]
    del g["computeTemplate"]
    g.update({"numSlices": 3, "tpuVersion": "v6e", "maxReplicas": 3})
    del g["replicas"]
    c = TpuCluster.from_dict(doc)
    assert c.spec.workerGroupSpecs[0].replicas == 3
    assert c.spec.workerGroupSpecs[0].accelerator == "v6e"
    g["replicas"] = 1          # canonical beats alias
    c = TpuCluster.from_dict(doc)
    assert c.spec.workerGroupSpecs[0].replicas == 1
