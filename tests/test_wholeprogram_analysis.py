"""The whole-program analyzer: graph-driven rules over the golden
fixture package, output determinism, the suppression ledger, the
``--changed-only`` restriction logic, and the docs/rule-catalog drift
gate.

The fixtures under ``tests/helpers/lint_fixtures/`` are analyzer
*inputs* (parsed, never imported): per whole-program rule a positive
multi-hop wrapper bypass the per-file rules cannot see, a
suppressed-with-reason variant, and a compliant negative.
"""

from __future__ import annotations

import os
import re
import textwrap

import pytest

from kuberay_tpu.analysis import RULES, analyze_paths
from kuberay_tpu.analysis.reporters import (render_human, render_json,
                                            render_rule_list)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO_ROOT, "tests", "helpers", "lint_fixtures")

WHOLE_PROGRAM_RULES = [
    "sim-determinism",
    "transitive-seam-bypass",
    "transitive-blocking-under-lock",
    "reconcile-exception-escape",
    "suppression-without-reason",
]

_REPORT_CACHE = {}


def _fixture_report(keep_suppressed=False):
    key = keep_suppressed
    if key not in _REPORT_CACHE:
        _REPORT_CACHE[key] = analyze_paths(
            [FIXTURES], only=WHOLE_PROGRAM_RULES,
            keep_suppressed=keep_suppressed)
    return _REPORT_CACHE[key]


def _findings(rule):
    return [f for f in _fixture_report().findings if f.rule == rule]


def _base(path):
    return os.path.basename(path)


# ---------------------------------------------------------------------------
# per-rule: positive fires with a multi-hop chain, negative stays clean
# ---------------------------------------------------------------------------

def test_sim_determinism_catches_wrapped_entropy():
    found = _findings("sim-determinism")
    files = {_base(f.path) for f in found}
    assert files == {"det_bypass.py"}
    sinks = {f.message.split("'")[1] for f in found}
    assert sinks == {"uuid.uuid4", "time.time"}
    for f in found:
        assert f.chain and len(f.chain) >= 2, f.render()
        assert "reconcile" in f.chain[0]["function"]


def test_seam_bypass_catches_all_three_seams():
    found = _findings("transitive-seam-bypass")
    by_file = {_base(f.path): f for f in found}
    assert set(by_file) == {"seam_quota.py", "seam_weight.py",
                            "seam_teardown.py"}
    assert "scheduler ask" in by_file["seam_quota.py"].message
    assert "trafficWeightPercent write" in by_file["seam_weight.py"].message
    assert "raw pod delete" in by_file["seam_teardown.py"].message
    for f in found:
        # depth >= 2: the wrapper hop is what the per-file rules miss
        assert f.chain and len(f.chain) >= 2, f.render()


def test_transitive_blocking_catches_cross_module_sleep():
    found = _findings("transitive-blocking-under-lock")
    assert len(found) == 1
    f = found[0]
    assert _base(f.path) == "lock_blocking.py"
    assert "time.sleep" in f.message
    # chain crosses into lock_helpers.py and starts at the lock holder
    assert "lock_helpers.py" in f.chain[-1]["path"]
    assert "holds the" in f.chain[0]["note"]
    assert len(f.chain) >= 3


def test_exception_escape_catches_multi_hop_raise():
    found = _findings("reconcile-exception-escape")
    assert len(found) == 1
    f = found[0]
    assert _base(f.path) == "exc_escape.py"
    assert "FixtureError" in f.message
    assert "raises FixtureError" in f.chain[-1]["note"]
    assert len(f.chain) >= 3
    # Conflict (sanctioned) and the handled controller produced nothing
    assert "Conflict" not in f.message


def test_bare_suppression_is_a_finding():
    found = _findings("suppression-without-reason")
    assert len(found) == 1
    assert _base(found[0].path) == "suppression_bare.py"


def test_chain_hops_render_clickable():
    f = _findings("transitive-blocking-under-lock")[0]
    rendered = f.render()
    for hop in f.chain:
        assert f"via {hop['path']}:{hop['line']}:" in rendered


# ---------------------------------------------------------------------------
# suppressed-with-reason variants are honored and counted
# ---------------------------------------------------------------------------

def test_suppressed_fixtures_are_silenced_and_ledgered():
    report = _fixture_report()
    counts = report.suppressed_counts
    assert counts == {"reconcile-exception-escape": 1,
                      "sim-determinism": 1,
                      "transitive-blocking-under-lock": 1,
                      "transitive-seam-bypass": 3}
    # audit mode surfaces them again
    kept = _fixture_report(keep_suppressed=True).findings
    assert len(kept) == len(report.findings) + sum(counts.values())


def test_justified_suppression_not_flagged_by_hygiene_rule():
    # suppression_bare.py has one bare and one justified suppression;
    # only the bare one is a finding.
    found = _findings("suppression-without-reason")
    assert len(found) == 1


# ---------------------------------------------------------------------------
# output determinism
# ---------------------------------------------------------------------------

def test_analyzer_output_is_order_independent():
    files = sorted(
        os.path.join(FIXTURES, n) for n in os.listdir(FIXTURES)
        if n.endswith(".py"))
    fwd = analyze_paths(files, only=WHOLE_PROGRAM_RULES)
    rev = analyze_paths(list(reversed(files)), only=WHOLE_PROGRAM_RULES)
    again = analyze_paths(files, only=WHOLE_PROGRAM_RULES)
    out_fwd = render_human(fwd.findings, fwd.suppressed_counts)
    assert out_fwd == render_human(rev.findings, rev.suppressed_counts)
    assert out_fwd == render_human(again.findings, again.suppressed_counts)
    assert render_json(fwd.findings, fwd.suppressed_counts) == \
        render_json(rev.findings, rev.suppressed_counts)


# ---------------------------------------------------------------------------
# reporters carry the ledger
# ---------------------------------------------------------------------------

def test_json_report_includes_suppressed_counts():
    import json
    report = _fixture_report()
    doc = json.loads(render_json(report.findings, report.suppressed_counts))
    assert doc["suppressed"] == report.suppressed_counts
    assert doc["suppressed_count"] == sum(report.suppressed_counts.values())
    chained = [f for f in doc["findings"] if "chain" in f]
    assert chained and all(
        {"function", "path", "line"} <= set(h) for f in chained
        for h in f["chain"])


def test_human_report_mentions_suppression_ledger():
    report = _fixture_report()
    out = render_human(report.findings, report.suppressed_counts)
    assert "suppressed with reason" in out
    assert "transitive-seam-bypass: 3" in out


# ---------------------------------------------------------------------------
# --changed-only restriction logic
# ---------------------------------------------------------------------------

def _mini_project(tmp_path):
    (tmp_path / "caller.py").write_text(textwrap.dedent("""
        from helper import greet

        def use():
            return greet()
    """))
    (tmp_path / "helper.py").write_text(textwrap.dedent("""
        def greet():
            return "hi"
    """))
    return tmp_path


def test_changed_only_restricts_to_leaf_changes(tmp_path, monkeypatch):
    import kuberay_tpu.analysis.__main__ as cli
    proj = _mini_project(tmp_path)
    caller = str(proj / "caller.py")
    monkeypatch.setattr(cli, "_git_changed_files",
                        lambda: {os.path.abspath(caller)})
    # caller.py has no callers elsewhere: restriction holds
    assert cli._changed_restriction([str(proj)]) == {caller}


def test_changed_only_widens_when_unchanged_callers_exist(tmp_path,
                                                          monkeypatch,
                                                          capsys):
    import kuberay_tpu.analysis.__main__ as cli
    proj = _mini_project(tmp_path)
    helper = str(proj / "helper.py")
    monkeypatch.setattr(cli, "_git_changed_files",
                        lambda: {os.path.abspath(helper)})
    # helper.greet is called from unchanged caller.py: whole repo
    assert cli._changed_restriction([str(proj)]) is None
    assert "callers in unchanged" in capsys.readouterr().err


def test_changed_only_empty_set_and_git_failure(tmp_path, monkeypatch):
    import kuberay_tpu.analysis.__main__ as cli
    proj = _mini_project(tmp_path)
    monkeypatch.setattr(cli, "_git_changed_files", lambda: set())
    assert cli._changed_restriction([str(proj)]) == set()
    monkeypatch.setattr(cli, "_git_changed_files", lambda: None)
    assert cli._changed_restriction([str(proj)]) is None


def test_changed_only_cli_exits_clean_on_no_changes(tmp_path, monkeypatch):
    import kuberay_tpu.analysis.__main__ as cli
    proj = _mini_project(tmp_path)
    monkeypatch.setattr(cli, "_git_changed_files", lambda: set())
    assert cli.main([str(proj), "--changed-only"]) == 0


def test_changed_only_restriction_limits_reporting(tmp_path, monkeypatch):
    # A finding in an unrestricted file is not reported, but the graph
    # still sees the whole project.
    clean = tmp_path / "clean.py"
    clean.write_text("x = 1\n")
    dirty = tmp_path / "dirty.py"
    dirty.write_text(textwrap.dedent("""
        def fanout(items):
            for item in items:
                try:
                    item()
                except Exception:
                    pass
    """))
    report = analyze_paths([str(tmp_path)], restrict_to={str(clean)})
    assert report.findings == []
    report = analyze_paths([str(tmp_path)], restrict_to={str(dirty)})
    assert {f.rule for f in report.findings} == {"exception-swallow"}


# ---------------------------------------------------------------------------
# docs drift: --list-rules vs the static-analysis.md catalog
# ---------------------------------------------------------------------------

def test_rule_catalog_matches_docs():
    """Every registered rule has a ``### `rule-id` `` heading in
    docs/static-analysis.md and vice versa (parse-error is synthetic —
    not in RULES, and must not be documented as one)."""
    doc = open(os.path.join(REPO_ROOT, "docs", "static-analysis.md"),
               encoding="utf-8").read()
    documented = set(re.findall(r"^### `([a-z0-9-]+)`", doc, re.M))
    registered = set(RULES)
    assert documented == registered, (
        f"docs missing: {sorted(registered - documented)}; "
        f"stale docs: {sorted(documented - registered)}")
    assert "parse-error" not in documented
    # --list-rules is generated from the same registry
    listed = {line.split(":", 1)[0] for line in
              render_rule_list().splitlines()
              if line and not line.startswith(" ")}
    assert listed == registered


def test_fixture_package_is_not_importable_as_tests():
    """The fixtures are analyzer inputs, not collectible test modules."""
    assert not os.path.exists(os.path.join(FIXTURES, "__init__.py"))
    assert not any(n.startswith("test_") for n in os.listdir(FIXTURES))
