"""Hot-path store contracts (ISSUE 5): indexed reads return exactly
what the old full-scan returned, copy-on-write snapshots isolate
readers without deepcopy, SSA-created objects replay deterministically,
the rv-sorted backlog bisects correctly, and async dispatch delivers
everything in commit order off the mutation lock."""

import copy
import threading

from kuberay_tpu.controlplane.snapshot import CowDict, CowList
from kuberay_tpu.controlplane.store import Event, ObjectStore


def obj(kind, name, ns="default", labels=None, owners=None, spec=None):
    md = {"name": name, "namespace": ns}
    if labels:
        md["labels"] = labels
    if owners:
        md["ownerReferences"] = owners
    return {"apiVersion": "v1", "kind": kind, "metadata": md,
            "spec": spec or {"x": 1}, "status": {}}


def make_mixed_store():
    """Mixed fixture: three kinds, two namespaces, indexed and
    unindexed labels."""
    s = ObjectStore()
    s.create(obj("Pod", "p0", labels={"tpu.dev/cluster": "c1",
                                      "role": "head"}))
    s.create(obj("Pod", "p1", labels={"tpu.dev/cluster": "c1",
                                      "role": "worker"}))
    s.create(obj("Pod", "p2", labels={"tpu.dev/cluster": "c2"}))
    s.create(obj("Pod", "p3", ns="other", labels={"tpu.dev/cluster": "c1"}))
    s.create(obj("Pod", "p4", ns="other", labels={"role": "worker"}))
    s.create(obj("TpuCluster", "c1", labels={"tier": "prod"}))
    s.create(obj("TpuCluster", "c2", ns="other"))
    s.create(obj("Service", "svc1", labels={"tpu.dev/cluster": "c1"}))
    return s


def scan_list(store, kind, namespace=None, labels=None):
    """The old implementation: full scan + deepcopy, as the parity
    oracle."""
    out = []
    with store._lock:
        for (k, _, _), o in store._objects.items():
            if k != kind or o.get("kind") != kind:
                continue
            md = o.get("metadata", {})
            if namespace is not None and md.get("namespace") != namespace:
                continue
            if labels:
                obj_labels = md.get("labels", {}) or {}
                if any(obj_labels.get(lk) != lv for lk, lv in labels.items()):
                    continue
            out.append(copy.deepcopy(o))
    out.sort(key=lambda o: (o["metadata"]["namespace"],
                            o["metadata"]["name"]))
    return out


# ---------------------------------------------------------------------------
# indexed reads
# ---------------------------------------------------------------------------

def test_indexed_list_matches_scan_on_mixed_fixture():
    s = make_mixed_store()
    cases = [
        ("Pod", None, None),
        ("Pod", "default", None),
        ("Pod", "other", None),
        ("Pod", "missing-ns", None),
        ("Pod", None, {"tpu.dev/cluster": "c1"}),
        ("Pod", "default", {"tpu.dev/cluster": "c1"}),
        ("Pod", "other", {"tpu.dev/cluster": "c1"}),
        ("Pod", None, {"role": "worker"}),                 # unindexed label
        ("Pod", None, {"tpu.dev/cluster": "c1", "role": "head"}),
        ("Service", None, {"tpu.dev/cluster": "c1"}),
        ("TpuCluster", None, None),
        ("TpuCluster", "other", None),
        ("NoSuchKind", None, None),
    ]
    for kind, ns, labels in cases:
        assert s.list(kind, ns, labels) == scan_list(s, kind, ns, labels), \
            (kind, ns, labels)


def test_indexes_track_update_delete_and_label_moves():
    s = make_mixed_store()
    # Label move: p2 migrates to c1 — both index buckets must follow.
    s.patch_labels("Pod", "p2", "default", {"tpu.dev/cluster": "c1"})
    assert [p["metadata"]["name"]
            for p in s.list("Pod", "default",
                            {"tpu.dev/cluster": "c1"})] == ["p0", "p1", "p2"]
    assert s.list("Pod", None, {"tpu.dev/cluster": "c2"}) == []
    # Delete: drops out of every bucket.
    s.delete("Pod", "p0", "default")
    assert [p["metadata"]["name"]
            for p in s.list("Pod", "default",
                            {"tpu.dev/cluster": "c1"})] == ["p1", "p2"]
    assert s.count("Pod") == 4
    assert s.kinds() == ["Pod", "Service", "TpuCluster"]
    s.delete("Service", "svc1", "default")
    assert s.kinds() == ["Pod", "TpuCluster"]


def test_cascade_delete_uses_owner_index():
    s = ObjectStore()
    owner = s.create(obj("TpuCluster", "own"))
    uid = owner["metadata"]["uid"]
    ref = [{"kind": "TpuCluster", "name": "own", "uid": uid}]
    s.create(obj("Pod", "dep-a", owners=ref))
    s.create(obj("Pod", "dep-b", owners=ref))
    # Same uid, different namespace: ownerReferences are namespace-local.
    s.create(obj("Pod", "dep-other-ns", ns="other", owners=ref))
    s.create(obj("Pod", "unrelated"))
    s.delete("TpuCluster", "own")
    names = [p["metadata"]["name"] for p in s.list("Pod")]
    assert names == ["unrelated", "dep-other-ns"]   # (ns, name) sort order
    # The owner bucket is gone with its members.
    assert uid not in s._owner_index or \
        all(k[1] == "other" for k in s._owner_index[uid])


# ---------------------------------------------------------------------------
# copy-on-write read path
# ---------------------------------------------------------------------------

def test_snapshot_mutation_never_reaches_committed_state():
    s = make_mixed_store()
    snap = s.get("Pod", "p0")
    assert isinstance(snap, CowDict)
    # Nested mutation through the wrapper: committed object untouched.
    snap["metadata"]["labels"]["role"] = "MUTATED"
    snap["spec"]["x"] = 999
    snap["status"]["phase"] = "Running"
    fresh = s.get("Pod", "p0")
    assert fresh["metadata"]["labels"]["role"] == "head"
    assert fresh["spec"]["x"] == 1
    assert fresh.get("status") == {}
    # And the mutated wrapper round-trips through update as a write.
    snap2 = s.get("Pod", "p0")
    snap2["spec"]["x"] = 2
    s.update(snap2)
    assert s.get("Pod", "p0")["spec"]["x"] == 2


def test_snapshot_list_iteration_wraps_elements():
    s = ObjectStore()
    s.create(obj("TpuCluster", "c", spec={
        "workerGroupSpecs": [{"groupName": "g0", "replicas": 1},
                             {"groupName": "g1", "replicas": 2}]}))
    snap = s.get("TpuCluster", "c")
    groups = snap["spec"]["workerGroupSpecs"]
    assert isinstance(groups, CowList)
    for g in groups:
        g["replicas"] = 99          # element wrappers, not committed dicts
    assert [g["replicas"] for g in
            s.get("TpuCluster", "c")["spec"]["workerGroupSpecs"]] == [1, 2]


def test_deep_reads_return_plain_private_dicts():
    s = make_mixed_store()
    d = s.get("Pod", "p0", deep=True)
    assert type(d) is dict and type(d["metadata"]) is dict
    for o in s.list("Pod", deep=True):
        assert type(o) is dict
    # deepcopy of a wrapper materializes to plain containers too.
    m = copy.deepcopy(s.get("Pod", "p0"))
    assert type(m) is dict and type(m["metadata"]) is dict
    assert type(m["metadata"]["labels"]) is dict


def test_watch_event_objects_are_isolated():
    s = ObjectStore()
    got = []
    s.watch(lambda ev: got.append(ev))
    s.create(obj("Pod", "p"))
    got[0].obj["metadata"]["labels"] = {"corrupted": "yes"}
    assert "labels" not in s.get("Pod", "p")["metadata"] or \
        s.get("Pod", "p")["metadata"].get("labels") != {"corrupted": "yes"}


def test_create_and_update_accept_snapshot_input():
    s = ObjectStore()
    s.create(obj("Pod", "src"))
    snap = s.get("Pod", "src")
    snap["metadata"]["name"] = "clone"
    del snap["metadata"]["uid"]
    snap["metadata"].pop("resourceVersion")
    s.create(snap)          # wrapper input materializes via entry deepcopy
    assert s.count("Pod") == 2


# ---------------------------------------------------------------------------
# SSA upsert determinism (satellite: patch() create path)
# ---------------------------------------------------------------------------

def _ssa_create(store):
    return store.patch(
        "TpuCluster", "applied", "default",
        {"spec": {"suspend": False}}, patch_type="apply",
        field_manager="kubectl")


def test_ssa_created_objects_use_uid_factory():
    counter = iter(range(1, 100))
    s = ObjectStore(uid_factory=lambda: f"det-uid-{next(counter):04d}")
    created = s.create(obj("Pod", "first"))
    applied = _ssa_create(s)
    assert created["metadata"]["uid"] == "det-uid-0001"
    assert applied["metadata"]["uid"] == "det-uid-0002", \
        "SSA upsert must mint uids through the injected factory " \
        "(deterministic replay), not uuid4"


def test_ssa_create_replays_identically():
    def run():
        counter = iter(range(1, 100))
        s = ObjectStore(uid_factory=lambda: f"sim-uid-{next(counter):06d}")
        s.create(obj("Pod", "seed"))
        out = _ssa_create(s)
        md = out["metadata"]
        return (md["uid"], md["resourceVersion"], md["generation"])

    assert run() == run()


# ---------------------------------------------------------------------------
# backlog bisect
# ---------------------------------------------------------------------------

def test_events_since_bisect_matches_full_filter():
    s = ObjectStore()
    for i in range(50):
        s.create(obj("Pod" if i % 2 else "Service", f"o{i:02d}"))
    latest = s.resource_version()
    for rv in (0, 1, 7, latest // 2, latest - 1, latest, latest + 5):
        events, got_latest, truncated = s.events_since(rv)
        with s._lock:
            expect = [(erv, ev) for erv, ev in s._backlog if erv > rv]
        assert events == expect, rv
        assert got_latest == latest
        ev_pods, _, _ = s.events_since(rv, kinds=("Pod",))
        assert ev_pods == [(erv, ev) for erv, ev in expect
                           if ev.kind == "Pod"]


def test_events_since_truncation_contract_survives():
    s = ObjectStore()
    s._backlog_max = 10
    for i in range(30):
        s.create(obj("Pod", f"p{i:02d}"))
    events, latest, truncated = s.events_since(1)
    assert truncated
    assert len(events) == 10
    events, _, truncated = s.events_since(latest - 3)
    assert not truncated and len(events) == 3


def test_wait_for_events_returns_immediately_past_rv():
    s = ObjectStore()
    s.create(obj("Pod", "p"))
    events, latest, truncated = s.wait_for_events(0, timeout=0.5)
    assert events and not truncated
    events, _, _ = s.wait_for_events(latest, timeout=0.05)
    assert events == []


# ---------------------------------------------------------------------------
# dispatch modes
# ---------------------------------------------------------------------------

def test_async_dispatch_delivers_everything_in_commit_order():
    s = ObjectStore(dispatch="async")
    try:
        got = []
        lock = threading.Lock()

        def watcher(ev):
            with lock:
                got.append((ev.type, ev.obj["metadata"]["name"],
                            ev.obj["metadata"]["resourceVersion"]))

        s.watch(watcher)
        for i in range(40):
            s.create(obj("Pod", f"p{i:02d}"))
        s.delete("Pod", "p00")
        assert s.flush_watch(timeout=10.0)
        with lock:
            rvs = [rv for _, _, rv in got]
            assert rvs == sorted(rvs), "async delivery must keep commit order"
            assert len(got) == 42    # 40 ADDED + MODIFIED(dts) + DELETED
            assert got[-1][0] == Event.DELETED
    finally:
        s.close()


def test_sync_dispatch_is_default_and_inline():
    s = ObjectStore()
    assert s._dispatch_mode == "sync"
    seen = []
    s.watch(lambda ev: seen.append(ev.type))
    s.create(obj("Pod", "p"))
    assert seen == [Event.ADDED]     # delivered before create() returned


def test_watcher_mutating_store_from_callback_does_not_deadlock():
    """A watcher that writes back into the store (the netpol-mapper
    pattern) must drain its nested events inline without deadlocking
    the sync dispatch path."""
    s = ObjectStore()
    seen = []

    def reactor(ev):
        seen.append((ev.type, ev.kind, ev.obj["metadata"]["name"]))
        if ev.kind == "TpuCluster" and ev.type == Event.ADDED:
            s.create(obj("NetworkPolicy",
                         f"np-{ev.obj['metadata']['name']}"))

    s.watch(reactor)
    s.create(obj("TpuCluster", "c1"))
    assert ("ADDED", "TpuCluster", "c1") in seen
    assert ("ADDED", "NetworkPolicy", "np-c1") in seen
    assert s.count("NetworkPolicy") == 1


def test_subscriber_queue_overflow_drops_oldest_and_counts():
    s = ObjectStore(watch_queue_max=5, dispatch="async")
    try:
        got = []
        gate = threading.Event()

        def slow_watcher(ev):
            gate.wait(5.0)
            got.append(ev.obj["metadata"]["name"])

        s.watch(slow_watcher)
        for i in range(30):
            s.create(obj("Pod", f"p{i:02d}"))
        gate.set()
        s.flush_watch(timeout=10.0)
        assert s.watch_dropped_total() > 0
        assert len(got) >= 5         # the bounded tail still lands
    finally:
        s.close()
