"""Tiered KV-cache hierarchy gate (serve/kv_tiers.py + paged engine).

Three layers:

1. the store's own contracts — content-verified checkout, LRU pressure
   demotion host->spill->gone, pin exclusion, the bounded advert log
   (delta vs reset snapshot);
2. the gateway-side session/fleet structures — TTL + capacity bounds,
   exact unlearning, deterministic best-source scoring;
3. the paged engine wired through the hierarchy — demotion pump,
   promotion back into the pool on resume (bit-identical to recompute),
   tier-served export, and an importer racing the source's eviction.
"""

import jax
import numpy as np
import pytest

from kuberay_tpu.models import llama
from kuberay_tpu.obs import Tracer
from kuberay_tpu.serve.engine import Request
from kuberay_tpu.serve.kv_tiers import (
    TIER_DEVICE,
    TIER_HOST,
    TIER_SPILL,
    FleetKvIndex,
    KvTierStore,
    SessionTable,
)
from kuberay_tpu.serve.paged_engine import PagedServeEngine
from kuberay_tpu.serve.prefix import block_hashes
from kuberay_tpu.utils.metrics import MetricsRegistry

CFG = llama.CONFIGS["llama_tiny"]
BS = 8


@pytest.fixture(scope="module")
def params():
    return llama.init_params(CFG, jax.random.PRNGKey(0))


def _blk(i):
    """A distinct full block of tokens for hash ``i``."""
    return tuple(range(i * 100, i * 100 + 4))


# ---------------------------------------------------------------------------
# KvTierStore
# ---------------------------------------------------------------------------

def test_store_admit_checkout_roundtrip():
    st = KvTierStore(host_blocks=4)
    assert st.admit(11, _blk(1), "payload-1")
    assert st.checkout(11, _blk(1)) == "payload-1"
    assert st.tier_of(11) == TIER_HOST and st.contains(11)
    assert st.checkout(99, _blk(9)) is None
    s = st.stats()
    assert (s["tier_hits_host"], s["tier_misses"]) == (1, 1)


def test_store_checkout_is_content_verified():
    """A stored entry whose tokens differ from the requested ones is a
    stale overwrite — dropped and counted, never served (the invariant
    the sim's no-stale-block checker replays)."""
    st = KvTierStore(host_blocks=4)
    st.admit(11, _blk(1), "stale")
    assert st.checkout(11, _blk(2)) is None
    assert st.stale_drops == 1
    # The poisoned entry is gone: even the original tokens now miss.
    assert st.checkout(11, _blk(1)) is None
    assert not st.contains(11)


def test_store_pressure_demotes_host_lru_then_drops_spill_lru():
    st = KvTierStore(host_blocks=2, spill_blocks=1)
    for i in (1, 2, 3):
        st.admit(i, _blk(i), f"p{i}")
    # Host LRU (1) demoted to spill; 2,3 stay host.
    assert st.tier_of(1) == TIER_SPILL
    assert st.tier_of(2) == TIER_HOST and st.tier_of(3) == TIER_HOST
    assert st.demotions == 1
    st.admit(4, _blk(4), "p4")
    # 2 demotes host->spill; spill overflows and drops its LRU (1).
    assert st.tier_of(1) is None and st.tier_of(2) == TIER_SPILL
    assert st.evictions == 1
    # Disabled spill: pressure drops straight off the hierarchy.
    flat = KvTierStore(host_blocks=1)
    flat.admit(1, _blk(1), "a")
    flat.admit(2, _blk(2), "b")
    assert flat.tier_of(1) is None and flat.evictions == 1


def test_store_spill_hit_promotes_to_host():
    st = KvTierStore(host_blocks=2, spill_blocks=2)
    for i in (1, 2, 3):
        st.admit(i, _blk(i), f"p{i}")
    assert st.tier_of(1) == TIER_SPILL
    assert st.checkout(1, _blk(1)) == "p1"
    assert st.tier_of(1) == TIER_HOST
    assert st.promotions == 1 and st.hits[TIER_SPILL] == 1


def test_store_pin_excludes_from_eviction():
    st = KvTierStore(host_blocks=1)
    st.admit(1, _blk(1), "pinned")
    st.pin(1)
    # Everything pinned: the newest admit is shed, not the pinned block.
    assert not st.admit(2, _blk(2), "shed")
    assert st.tier_of(1) == TIER_HOST and st.tier_of(2) is None
    st.unpin(1)
    assert st.admit(3, _blk(3), "p3")
    assert st.tier_of(1) is None and st.tier_of(3) == TIER_HOST


def test_store_discard_counts_tier_copies():
    st = KvTierStore(host_blocks=2, spill_blocks=2)
    for i in (1, 2, 3):
        st.admit(i, _blk(i), f"p{i}")
    assert st.discard(1) == 1          # spill copy
    assert st.discard(2) == 1          # host copy
    assert st.discard(99) == 0         # never resident
    assert not st.contains(1) and not st.contains(2)


def test_store_admit_readmit_is_content_addressed_noop():
    """Re-admitting a resident hash refreshes recency but never
    replaces content — same hash means same bytes by construction."""
    st = KvTierStore(host_blocks=2)
    st.admit(1, _blk(1), "original")
    assert st.admit(1, _blk(9), "imposter")
    assert st.checkout(1, _blk(1)) == "original"


def test_advert_delta_and_reset_snapshot():
    st = KvTierStore(host_blocks=4, spill_blocks=2, advert_capacity=16)
    st.note_device(7, True)
    st.admit(1, _blk(1), "a")
    seq = st.advert_seq
    doc = st.advert_since(0)
    # The log still reaches back to seq 0: a plain delta replays the
    # full history (reset is only for readers past the window).
    assert not doc["reset"]
    assert sorted(doc["add"]) == [[1, TIER_HOST], [7, TIER_DEVICE]]
    st.admit(2, _blk(2), "b")
    st.discard(1)
    delta = st.advert_since(seq)
    assert not delta["reset"]
    assert delta["add"] == [[2, TIER_HOST]] and delta["del"] == [1]
    assert st.advert_since(st.advert_seq) == \
        {"seq": st.advert_seq, "reset": False, "add": [], "del": []}
    # Overflow the bounded log: a laggard reader gets reset, not a
    # silently truncated delta.
    for i in range(10, 40):
        st.admit(i, _blk(i), "x")
    assert st.advert_since(seq)["reset"]


def test_store_gauges_and_counters_reach_metrics():
    m = MetricsRegistry()
    st = KvTierStore(host_blocks=1, spill_blocks=1, metrics=m)
    st.admit(1, _blk(1), "a")
    st.admit(2, _blk(2), "b")          # 1 demoted host->spill
    st.checkout(1, _blk(1))            # spill hit, promoted
    st.checkout(9, _blk(9))            # miss
    out = m.render()
    for name in ("tpu_kv_tier_blocks", "tpu_kv_tier_capacity_blocks",
                 "tpu_kv_tier_hits_total", "tpu_kv_tier_misses_total",
                 "tpu_kv_tier_demotions_total",
                 "tpu_kv_tier_promotions_total"):
        assert name in out, name


# ---------------------------------------------------------------------------
# SessionTable
# ---------------------------------------------------------------------------

def test_session_table_touch_lookup_ttl():
    now = [0.0]
    tab = SessionTable(capacity=8, ttl=10.0, clock=lambda: now[0])
    tab.touch("s1", (11, 22), 16, "replica-0")
    sess = tab.lookup("s1")
    assert sess.hashes == (11, 22) and sess.backend == "replica-0"
    assert tab.resumes == 1
    now[0] = 11.0
    assert tab.lookup("s1") is None and tab.expired == 1
    assert tab.lookup("never") is None


def test_session_table_capacity_evicts_lru_and_sweep():
    now = [0.0]
    tab = SessionTable(capacity=2, ttl=10.0, clock=lambda: now[0])
    for sid in ("a", "b", "c"):
        tab.touch(sid, (1,), 8, "r0")
    assert len(tab) == 2 and tab.evicted == 1
    assert tab.lookup("a") is None     # LRU fell off
    now[0] = 20.0
    assert tab.sweep() == 2 and len(tab) == 0


def test_session_table_forget_backend_keeps_chain():
    tab = SessionTable(capacity=8, ttl=0)
    tab.touch("s1", (11, 22), 16, "replica-0")
    assert tab.forget_backend("replica-0") == 1
    sess = tab.lookup("s1")
    # Chain survives — the blocks may be resident elsewhere in the
    # fleet — but stickiness to the dead replica is gone.
    assert sess.hashes == (11, 22) and sess.backend == ""


# ---------------------------------------------------------------------------
# FleetKvIndex
# ---------------------------------------------------------------------------

def test_fleet_index_apply_depth_and_unlearn():
    idx = FleetKvIndex()
    idx.apply("a", {"seq": 3, "reset": False,
                    "add": [[1, "host"], [2, "host"], [3, "spill"]],
                    "del": []})
    assert idx.resident_depth("a", [1, 2, 3, 4]) == 3
    # Leading-prefix semantics: a gap stops the walk even when later
    # hashes are resident.
    assert idx.resident_depth("a", [9, 2, 3]) == 0
    idx.apply("a", {"seq": 4, "reset": False, "add": [], "del": [2]})
    assert idx.resident_depth("a", [1, 2, 3]) == 1
    assert idx.seq("a") == 4
    assert idx.needs_sync("a", 5) and not idx.needs_sync("a", 4)
    idx.apply("a", {"seq": 9, "reset": True, "add": [[7, "host"]],
                    "del": []})
    assert idx.resident_depth("a", [1]) == 0 and idx.size("a") == 1


def test_fleet_index_best_source_deterministic_and_droppable():
    idx = FleetKvIndex()
    idx.apply("b", {"seq": 1, "reset": False,
                    "add": [[1, "host"], [2, "host"]], "del": []})
    idx.apply("a", {"seq": 1, "reset": False,
                    "add": [[1, "host"], [2, "host"]], "del": []})
    idx.apply("c", {"seq": 1, "reset": False, "add": [[1, "host"]],
                    "del": []})
    # Tie on depth 2 breaks lexicographically: deterministic placement.
    assert idx.best_source([1, 2, 3]) == ("a", 2)
    assert idx.best_source([1, 2, 3], exclude=("a",)) == ("b", 2)
    assert idx.best_source([9]) == (None, 0)
    assert idx.drop_backend("a") == 2
    assert idx.best_source([1, 2, 3]) == ("b", 2)


# ---------------------------------------------------------------------------
# engine integration: demote / promote / export through the hierarchy
# ---------------------------------------------------------------------------

def _engine(params, **kw):
    kw.setdefault("max_slots", 1)
    kw.setdefault("max_len", 64)
    kw.setdefault("block_size", BS)
    return PagedServeEngine(CFG, params, **kw)


def _fill_pool(eng):
    """Cannibalize every cached device block with disjoint slot-sized
    junk prompts (each fits one slot; enough of them to walk the free
    list and then the cached LRU — the blocks under test).  Tokens stay
    inside llama_tiny's 256-entry vocab: an out-of-range id poisons the
    logits and every later decode on the engine."""
    plen = (eng.max_blocks - 1) * BS             # leave the decode block
    rounds = eng.num_blocks // (eng.max_blocks - 1) + 1
    for j in range(rounds):
        start = 30 + j * plen
        toks = [(start + i) % 231 + 25 for i in range(plen)]
        eng.add_request(Request(f"junk{j}", toks, max_new_tokens=1))
        eng.run()


def test_engine_demotes_freed_blocks_and_resumes_without_prefill(params):
    """The resume contract end to end: device eviction loses nothing
    the pump saved — promotion re-imports the chain and decode is
    bit-identical to a cold engine that prefilled everything."""
    prompt = list(range(1, 25))                  # 3 full blocks
    cold = _engine(params)
    cold.add_request(Request("c", list(prompt), max_new_tokens=6))
    expected = cold.run()[0].tokens

    tracer = Tracer()
    # Host tier sized so the junk prompts' own demotions never pressure
    # out the blocks under test.
    eng = _engine(params, max_slots=2, host_blocks=64, tracer=tracer)
    eng.add_request(Request("p", list(prompt), max_new_tokens=1))
    eng.run()
    # The step pump already demoted the freed blocks host-ward (it runs
    # inside step(), bounded per step); drain any stragglers.
    eng._pump_demotions(limit=1 << 10)
    assert eng.tiers.stats()["host_blocks_used"] >= 3
    _fill_pool(eng)
    assert eng.resident_prefix_blocks(prompt) == 0   # device copy gone

    ctx = tracer.start_request("serve-request")
    eng.add_request(Request("r", list(prompt), max_new_tokens=6,
                            trace=ctx))
    out = eng.run()
    tracer.finish_request(ctx)
    assert out[0].tokens == expected
    st = eng.stats
    assert st["tier_fetch_blocks"] >= 2
    # All but the final block came back from the host tier (the engine
    # always re-runs the last block through prefill for logits).
    assert st["prefix_hit_tokens"] >= 2 * BS
    spans = {s["name"]: s for s in tracer.export(ctx.trace_id)}
    assert spans["tier-fetch"]["attrs"]["blocks"] >= 2


def test_engine_advert_covers_tiers_and_eviction(params):
    eng = _engine(params, host_blocks=16)
    prompt = list(range(1, 17))                  # 2 full blocks
    eng.add_request(Request("p", list(prompt), max_new_tokens=1))
    eng.run()
    eng._pump_demotions(limit=1 << 10)
    doc = eng.kv_advert(0)
    hashes = set(eng.allocator.block_hashes(prompt))
    advertised = {h for h, _ in doc["add"]}
    assert hashes <= advertised
    seq = doc["seq"]
    # Tier discard shows up as a delta del — the unlearning signal the
    # gateway's fleet index folds in.
    victim = eng.allocator.block_hashes(prompt)[0]
    eng.tiers.discard(victim)
    delta = eng.kv_advert(seq)
    assert victim in delta["del"] and not delta["reset"]
    # A tier-less engine adverts the empty contract, not an error.
    assert _engine(params).kv_advert(0) == \
        {"seq": 0, "reset": False, "add": [], "del": []}


def test_export_serves_from_tier_after_device_eviction(params):
    """The wire chain stays contiguous across device eviction: blocks
    the pool cannibalized are served from their host-tier copy, and the
    importer's decode matches a cold prefill bit for bit."""
    prompt = list(range(1, 25))
    cold = _engine(params)
    cold.add_request(Request("c", list(prompt), max_new_tokens=6))
    expected = cold.run()[0].tokens

    src = _engine(params, max_slots=2, host_blocks=64)
    src.add_request(Request("p", list(prompt), max_new_tokens=1))
    src.run()
    src._pump_demotions(limit=1 << 10)
    _fill_pool(src)
    assert src.resident_prefix_blocks(prompt) == 0   # device copy gone
    blocks = src.export_kv_blocks(prompt)
    assert [b["index"] for b in blocks] == [0, 1, 2]

    dst = _engine(params)
    assert dst.import_kv_blocks(prompt, blocks) == \
        {"imported": 3, "skipped": 0}
    dst.add_request(Request("d", list(prompt), max_new_tokens=6))
    assert dst.run()[0].tokens == expected


def test_import_racing_source_eviction_keeps_contiguous_prefix(params):
    """An importer whose source evicts mid-transfer (first batch
    shipped, remainder gone) ends with a usable contiguous prefix and
    recomputes the tail — same output, no torn chain."""
    prompt = list(range(1, 25))
    cold = _engine(params)
    cold.add_request(Request("c", list(prompt), max_new_tokens=6))
    expected = cold.run()[0].tokens

    src = _engine(params)                        # no tiers: eviction is
    src.add_request(Request("p", list(prompt), max_new_tokens=1))  # final
    src.run()
    first = src.export_kv_blocks(prompt, max_blocks=2)
    assert [b["index"] for b in first] == [0, 1]
    _fill_pool(src)                              # the race: source evicts
    assert src.export_kv_blocks(prompt, skip_blocks=2) == []

    dst = _engine(params)
    assert dst.import_kv_blocks(prompt, first) == \
        {"imported": 2, "skipped": 0}
    dst.add_request(Request("d", list(prompt), max_new_tokens=6))
    assert dst.run()[0].tokens == expected
    assert dst.stats["prefix_hit_tokens"] == 2 * BS


def test_engine_stats_surface_tier_counters(params):
    eng = _engine(params, host_blocks=16, spill_blocks=4)
    st = eng.stats
    for key in ("host_blocks_used", "host_blocks_total",
                "spill_blocks_total", "pending_demotions",
                "tier_fetch_blocks", "tier_demoted_blocks", "advert_seq"):
        assert key in st, key
    assert st["host_blocks_total"] == 16 and st["spill_blocks_total"] == 4
