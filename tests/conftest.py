"""Test harness: force an 8-device virtual CPU mesh before JAX initializes.

Mirrors the reference's envtest strategy (SURVEY.md §4 tier 2): multi-host
behavior is tested without real hardware — there, a real kube-apiserver with
hand-set pod phases; here, a virtual 8-device CPU platform so every sharding
and collective path compiles and executes exactly as it would on a slice.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

# The hosting site may force jax_platforms to include a hardware plugin
# whose init dials a tunnel; pin to cpu in-process so tests are hermetic.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
