"""Test harness: force an 8-device virtual CPU mesh before JAX initializes.

Mirrors the reference's envtest strategy (SURVEY.md §4 tier 2): multi-host
behavior is tested without real hardware — there, a real kube-apiserver with
hand-set pod phases; here, a virtual 8-device CPU platform so every sharding
and collective path compiles and executes exactly as it would on a slice.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

# The hosting site may force jax_platforms to include a hardware plugin
# whose init dials a tunnel; pin to cpu in-process so tests are hermetic.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


# ---------------------------------------------------------------------------
# @pytest.mark.timeout fallback: pytest-timeout is not installed in this
# image, which silently turns the marker into a no-op — a hung
# subprocess test would stall CI forever.  SIGALRM-based stand-in
# (POSIX; tests run in the main thread).

import signal  # noqa: E402

import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "timeout(seconds): fail the test if it runs longer "
        "(conftest SIGALRM fallback for the absent pytest-timeout plugin)")


@pytest.hookimpl(hookwrapper=True)
def pytest_runtest_call(item):
    marker = item.get_closest_marker("timeout")
    has_plugin = item.config.pluginmanager.hasplugin("timeout")
    if marker is None or has_plugin or not hasattr(signal, "SIGALRM"):
        yield
        return
    seconds = int(marker.args[0]) if marker.args else 60

    def _alarm(signum, frame):
        raise TimeoutError(
            f"test exceeded timeout marker ({seconds}s, conftest fallback)")

    old = signal.signal(signal.SIGALRM, _alarm)
    signal.alarm(seconds)
    try:
        yield
    finally:
        signal.alarm(0)
        signal.signal(signal.SIGALRM, old)
