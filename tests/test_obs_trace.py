"""Observability gate (kuberay_tpu.obs): tracer, flight recorder,
manager wiring, /debug endpoints, serve phase histograms, and the
sim-level acceptance contract — slice-ready durations decompose into
queue-wait + reconcile + pod-start child spans that account for the
virtual-clock total, and the replay hash is byte-identical with tracing
on and off.
"""

import json
import urllib.request

import pytest

from kuberay_tpu.controlplane.events import EventRecorder
from kuberay_tpu.controlplane.fake_kubelet import FakeKubelet
from kuberay_tpu.controlplane.manager import Manager
from kuberay_tpu.controlplane.store import Conflict, ObjectStore
from kuberay_tpu.obs import FlightRecorder, NOOP_TRACER, Tracer, span_tree
from kuberay_tpu.sim.clock import VirtualClock
from kuberay_tpu.sim.faults import FaultPlan
from kuberay_tpu.sim.harness import SimHarness
from kuberay_tpu.sim.scenarios import get_scenario, make_cluster_obj
from kuberay_tpu.utils import constants as C


# ---------------------------------------------------------------------------
# tracer core
# ---------------------------------------------------------------------------

def test_span_parenting_and_export():
    clock = VirtualClock(start=100.0)
    tracer = Tracer(clock=clock)
    key = ("TpuCluster", "default", "demo")
    tracer.queued(key, 100.0)
    clock.advance(2.0)
    tracer.dequeued(key, 102.0)
    with tracer.reconcile(key, kind="TpuCluster") as span:
        with tracer.span("store-write", obj="demo"):
            clock.advance(1.0)
        span.set(requeue_after=5.0)
    spans = tracer.export()
    by_name = {s["name"]: s for s in spans}
    root = by_name["chain:TpuCluster/default/demo"]
    qw = by_name["queue-wait"]
    rec = by_name["reconcile"]
    sw = by_name["store-write"]
    # One trace; queue-wait and reconcile hang off the chain root; the
    # store-write nests under the reconcile that issued it.
    assert {s["trace_id"] for s in spans} == {root["trace_id"]}
    assert qw["parent_id"] == root["span_id"]
    assert rec["parent_id"] == root["span_id"]
    assert sw["parent_id"] == rec["span_id"]
    assert qw["start"] == 100.0 and qw["end"] == 102.0
    assert rec["attrs"]["requeue_after"] == 5.0
    # The open root's end extended to the last finished child.
    assert root["end"] == pytest.approx(103.0)
    trees = span_tree(spans)
    assert len(trees) == 1
    assert {c["name"] for c in trees[0]["children"]} == {
        "queue-wait", "reconcile"}


def test_tracer_bounded_span_store():
    tracer = Tracer(clock=VirtualClock(), max_spans=10)
    key = ("Kind", "ns", "x")
    for _ in range(50):
        with tracer.reconcile(key):
            pass
    assert len(tracer.store) == 10
    assert tracer.store.dropped == 41     # 50 reconciles + 1 root - 10 kept


def test_record_error_marks_current_span():
    tracer = Tracer(clock=VirtualClock())
    with tracer.reconcile(("K", "ns", "n")):
        tracer.record_error("coordinator", "connection refused")
    rec = [s for s in tracer.export() if s["name"] == "reconcile"][0]
    assert rec["status"] == "error"
    assert "coordinator: connection refused" in rec["error"]
    # Outside any span the error still lands (zero-duration span).
    tracer.record_error("orphan", "boom")
    orphan = [s for s in tracer.export() if s["name"] == "error:orphan"][0]
    assert orphan["status"] == "error"


def test_noop_tracer_is_free_and_silent():
    t = NOOP_TRACER
    t.queued(("K", "ns", "n"))
    t.dequeued(("K", "ns", "n"))
    with t.reconcile(("K", "ns", "n")) as span:
        span.set(x=1)
        span.error("nope")
    t.record_error("s", "m")
    t.record_for_key(("K", "ns", "n"), "pod-start", 0.0, 1.0)
    assert t.export() == []
    assert t.current() is None


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------

def test_flight_recorder_ring_and_eviction():
    clock = VirtualClock(start=0.0)
    fr = FlightRecorder(capacity=3, max_objects=2, clock=clock)
    for i in range(5):
        fr.record("Pod", "ns", "a", "watch", f"MODIFIED-{i}")
    assert [r["detail"] for r in fr.timeline("Pod", "ns", "a")] == [
        "MODIFIED-2", "MODIFIED-3", "MODIFIED-4"]   # ring keeps the tail
    fr.record("Pod", "ns", "b", "watch", "ADDED")
    fr.record("Pod", "ns", "c", "watch", "ADDED")   # evicts LRU key 'a'
    assert fr.timeline("Pod", "ns", "a") == []
    assert len(fr.keys()) == 2


def test_flight_recorder_state_transitions_and_events():
    from kuberay_tpu.controlplane.store import Event
    fr = FlightRecorder(clock=VirtualClock())
    obj = {"kind": "TpuCluster",
           "metadata": {"name": "demo", "namespace": "ns",
                        "resourceVersion": 4},
           "status": {"state": "ready"}}
    fr.observe_event(Event(Event.MODIFIED, "TpuCluster", obj))
    fr.observe_event(Event(Event.MODIFIED, "TpuCluster", obj))  # no re-record
    tl = fr.timeline("TpuCluster", "ns", "demo")
    assert [r["type"] for r in tl] == ["watch", "state", "watch"]
    assert tl[1]["detail"] == "<none> -> ready"
    # K8s Events land on the involved object's timeline.
    ev_obj = {"kind": "Event", "metadata": {"name": "demo.evt1",
                                            "namespace": "ns"},
              "type": "Warning", "reason": "Unhealthy", "message": "bad",
              "involvedObject": {"kind": "TpuCluster", "name": "demo",
                                 "namespace": "ns"}}
    fr.observe_event(Event(Event.ADDED, "Event", ev_obj))
    tl = fr.timeline("TpuCluster", "ns", "demo")
    assert tl[-1]["type"] == "event"
    assert "Warning/Unhealthy" in tl[-1]["detail"]


# ---------------------------------------------------------------------------
# manager wiring: queue-wait + reconcile spans, conflict/requeue records
# ---------------------------------------------------------------------------

def test_manager_emits_queue_wait_and_reconcile_spans():
    clock = VirtualClock(start=1000.0)
    store = ObjectStore()
    tracer = Tracer(clock=clock)
    flight = FlightRecorder(clock=clock)
    manager = Manager(store, clock=clock, tracer=tracer, flight=flight)
    calls = {"n": 0}

    def flaky(name, ns):
        calls["n"] += 1
        if calls["n"] == 1:
            raise Conflict("lost the rv race")
        return None

    manager.register("Thing", flaky)
    manager.enqueue(("Thing", "default", "x"))
    manager.run_until_idle()                    # conflict -> requeue 0.05
    clock.advance(0.06)
    manager.run_until_idle()                    # clean pass
    spans = tracer.export()
    recs = [s for s in spans if s["name"] == "reconcile"]
    assert len(recs) == 2
    assert recs[0]["status"] == "error" and "conflict" in recs[0]["error"]
    assert recs[0]["attrs"]["requeue_after"] == 0.05
    assert recs[1]["status"] == "ok"
    # The retry's queue-wait span covers the backoff interval.
    waits = [s for s in spans if s["name"] == "queue-wait"]
    assert len(waits) == 2
    assert waits[1]["duration"] == pytest.approx(0.06)
    assert waits[1]["attrs"].get("delayed") is True
    # Flight recorder saw the conflict and the requeue.
    types = [r["type"] for r in flight.timeline("Thing", "default", "x")]
    assert "conflict" in types and "requeue" in types


def test_manager_watch_events_reach_flight_recorder():
    store = ObjectStore()
    flight = FlightRecorder()
    manager = Manager(store, flight=flight)
    manager.register("TpuCluster", lambda name, ns: None)
    store.create({"kind": "TpuCluster", "metadata": {"name": "demo"}})
    manager.run_until_idle()
    types = [r["type"] for r in
             flight.timeline("TpuCluster", "default", "demo")]
    assert "watch" in types


# ---------------------------------------------------------------------------
# kubelet pod-start spans
# ---------------------------------------------------------------------------

def test_kubelet_records_pod_start_against_owner_chain():
    clock = VirtualClock(start=0.0)
    store = ObjectStore()
    tracer = Tracer(clock=clock)
    kubelet = FakeKubelet(store, now_fn=clock.now, tracer=tracer)
    store.create({"kind": "Pod", "metadata": {
        "name": "w0", "creationTimestamp": 1.0,
        "labels": {C.LABEL_CLUSTER: "demo",
                   C.LABEL_SLICE_NAME: "demo-workers-0"}},
        "spec": {"containers": [{"name": "w"}]}})
    clock.advance(1.0)
    kubelet.hold_pod("w0", until=30.0)
    kubelet.step()
    clock.advance(30.0)
    kubelet.step()
    starts = [s for s in tracer.export() if s["name"] == "pod-start"]
    assert len(starts) == 1
    assert starts[0]["attrs"]["pod"] == "w0"
    assert starts[0]["duration"] == pytest.approx(30.0)
    # Parented on the owning cluster's chain.
    chains = [s for s in tracer.export()
              if s["name"] == "chain:TpuCluster/default/demo"]
    assert chains and starts[0]["parent_id"] == chains[0]["span_id"]
    kubelet.close()


# ---------------------------------------------------------------------------
# deterministic event emission (sim satellite)
# ---------------------------------------------------------------------------

def test_sim_event_recording_is_deterministic():
    names = []
    for _ in range(2):
        with SimHarness(3, scenario=get_scenario("scale-up-storm")) as h:
            h.run(2)
            names.append(sorted(
                (e["metadata"]["name"], e["eventTime"])
                for e in h.store.list("Event")))
    assert names[0], "scenario produced no events — determinism untested"
    assert names[0] == names[1]
    # Counter-named, not uuid-suffixed, under the harness.
    assert all(".evt" in n for n, _ in names[0])


def test_event_recorder_custom_clock_and_names():
    store = ObjectStore()
    clock = VirtualClock(start=777.0)
    rec = EventRecorder(store, clock=clock,
                        name_factory=lambda base: f"{base}.E1")
    rec.normal({"kind": "TpuCluster", "metadata": {"name": "demo"}},
               "Created", "hello")
    ev = store.list("Event")[0]
    assert ev["metadata"]["name"] == "demo.E1"
    assert ev["eventTime"] == 777.0


# ---------------------------------------------------------------------------
# /debug endpoints on the API server
# ---------------------------------------------------------------------------

def test_debug_endpoints_serve_traces_and_flight():
    from kuberay_tpu.apiserver.server import serve_background
    store = ObjectStore()
    tracer = Tracer()
    flight = FlightRecorder()
    with tracer.reconcile(("TpuCluster", "default", "demo")):
        pass
    flight.record("TpuCluster", "default", "demo", "requeue", "after=5.0")
    srv, url = serve_background(store, tracer=tracer, flight=flight)
    try:
        with urllib.request.urlopen(f"{url}/debug/traces") as resp:
            doc = json.load(resp)
        assert any(s["name"] == "reconcile" for s in doc["spans"])
        with urllib.request.urlopen(f"{url}/debug/traces?tree=1") as resp:
            tree = json.load(resp)
        assert tree["traces"][0]["children"]
        with urllib.request.urlopen(
                f"{url}/debug/flight/TpuCluster/default/demo") as resp:
            fdoc = json.load(resp)
        assert fdoc["records"][0]["type"] == "requeue"
        with urllib.request.urlopen(f"{url}/debug/flight") as resp:
            listing = json.load(resp)
        assert {"kind": "TpuCluster", "namespace": "default",
                "name": "demo"} in listing["objects"]
    finally:
        srv.shutdown()


def test_debug_endpoints_404_when_disabled():
    from kuberay_tpu.apiserver.server import serve_background
    srv, url = serve_background(ObjectStore())
    try:
        for path in ("/debug/traces", "/debug/flight/TpuCluster/d/x"):
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(f"{url}{path}")
            assert ei.value.code == 404
    finally:
        srv.shutdown()


def test_operator_exposes_debug_surface():
    from kuberay_tpu.operator import Operator
    op = Operator(fake_kubelet=True)
    url = op.start(api_port=0)
    try:
        op.store.create(make_cluster_obj("demo", topology="2x2x2",
                                         replicas=1))
        for _ in range(4):
            op.run_until_idle()
        with urllib.request.urlopen(f"{url}/debug/traces") as resp:
            doc = json.load(resp)
        names = {s["name"] for s in doc["spans"]}
        assert "reconcile" in names and "queue-wait" in names
        assert any(n.startswith("chain:TpuCluster") for n in names)
        with urllib.request.urlopen(
                f"{url}/debug/flight/TpuCluster/default/demo") as resp:
            fdoc = json.load(resp)
        assert fdoc["records"]
        # The north-star histogram now actually observes.
        with urllib.request.urlopen(f"{url}/metrics") as resp:
            text = resp.read().decode()
        assert "tpu_slice_ready_duration_seconds_count" in text
    finally:
        op.stop()


# ---------------------------------------------------------------------------
# serve engine phase histograms
# ---------------------------------------------------------------------------

@pytest.mark.timeout(300)
def test_serve_engine_request_phase_histograms():
    from kuberay_tpu.models import llama
    from kuberay_tpu.serve.engine import Request, ServeEngine
    from kuberay_tpu.utils.metrics import MetricsRegistry
    import jax
    cfg = llama.CONFIGS["llama_tiny"]
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    reg = MetricsRegistry()
    engine = ServeEngine(cfg, params, max_slots=2, max_len=64, metrics=reg)
    engine.add_request(Request("r1", [1, 2, 3], max_new_tokens=4))
    engine.run()
    text = reg.render()
    for phase in ("queue", "prefill", "decode"):
        assert (f'tpu_serve_request_duration_seconds_count'
                f'{{phase="{phase}"}} 1') in text
    assert engine._req_phase_ts == {}           # accounting fully drained


def test_gateway_observes_forward_phase():
    from kuberay_tpu.serve.gateway import WeightedGateway
    from kuberay_tpu.utils.metrics import MetricsRegistry
    store = ObjectStore()
    reg = MetricsRegistry()
    gw = WeightedGateway(store, "route", metrics=reg, poll_interval=30.0)
    try:
        code, _ = gw.forward("/v1/completions", b"{}")
        assert code == 503                       # no backends in route
        text = reg.render()
        assert ('tpu_serve_request_duration_seconds_count'
                '{phase="gateway"} 1') in text
        assert ('tpu_gateway_requests_total{backend="none",code="503"} 1.0'
                in text)
    finally:
        gw.close()


# ---------------------------------------------------------------------------
# acceptance: slice-ready decomposition + replay-hash invariance
# ---------------------------------------------------------------------------

def _union_length(intervals):
    total, cur = 0.0, None
    for a, b in sorted(i for i in intervals if i[1] > i[0]):
        if cur is None or a > cur[1]:
            if cur is not None:
                total += cur[1] - cur[0]
            cur = [a, b]
        else:
            cur[1] = max(cur[1], b)
    if cur is not None:
        total += cur[1] - cur[0]
    return total


def _assert_decomposes(spans, require_positive=False):
    slice_spans = [s for s in spans if s["name"] == "slice-ready"]
    assert slice_spans, "no slice-ready spans recorded"
    by_trace = {}
    for s in spans:
        by_trace.setdefault(s["trace_id"], []).append(s)
    for s in slice_spans:
        trace = by_trace[s["trace_id"]]
        names = {t["name"] for t in trace}
        assert {"queue-wait", "reconcile", "pod-start"} <= names, names
        total = s["end"] - s["start"]
        if require_positive:
            assert total > 0
        window = [(max(t["start"], s["start"]), min(t["end"], s["end"]))
                  for t in trace
                  if t["name"] in ("queue-wait", "reconcile", "pod-start")
                  and t["end"] is not None]
        covered = _union_length(window)
        # The children fully account for the slice-ready duration in
        # virtual time: no more than the total (they live inside the
        # window) and no unexplained gaps.
        assert covered <= total + 1e-6
        assert covered == pytest.approx(total, abs=1e-3)


@pytest.mark.timeout(120)
def test_slice_ready_decomposition_with_slow_start():
    """Deterministic decomposition: a held pod makes slice-ready take
    real virtual time, and the span tree accounts for every second."""
    quiet = {f: 0.0 for f in FaultPlan(0).profile}
    with SimHarness(0, fault_profile=quiet, trace=True) as h:
        h.store.create(make_cluster_obj("demo", topology="2x2x2",
                                        replicas=1))
        # Pods exist but have not run yet: hold one host 40 virtual
        # seconds so the slice's readiness is gated on it.
        h.manager.run_until_idle()
        pods = [p for p in h.store.list("Pod")
                if p["metadata"]["labels"].get(C.LABEL_GROUP) == "workers"]
        assert pods
        victim = sorted(p["metadata"]["name"] for p in pods)[0]
        h.kubelet.hold_pod(victim, until=h.clock.now() + 40.0)
        h.settle(horizon=120.0)
        spans = h.tracer.export()
        _assert_decomposes(spans, require_positive=True)
        slice_span = [s for s in spans if s["name"] == "slice-ready"][0]
        assert slice_span["end"] - slice_span["start"] >= 40.0
        metrics_text = h.metrics.render()
    assert "tpu_slice_ready_duration_seconds_count" in metrics_text


@pytest.mark.timeout(300)
def test_sim_trace_decomposition_and_replay_hash_invariance():
    """The ISSUE acceptance run: rolling-upgrade seed 0 with tracing
    produces a decomposing span tree, and the (scenario, seed) journal
    hash is byte-identical with tracing on and off."""
    with SimHarness(0, scenario=get_scenario("rolling-upgrade"),
                    trace=True) as h:
        traced = h.run(3)
        spans = h.tracer.export()
        export = h.export_trace()
    with SimHarness(0, scenario=get_scenario("rolling-upgrade")) as h:
        untraced = h.run(3)
    assert traced.ok and untraced.ok
    assert traced.journal_hash == untraced.journal_hash
    assert traced.journal_len == untraced.journal_len
    _assert_decomposes(spans)
    # The exported artifact carries spans + the replayable journal.
    assert export["seed"] == 0
    assert export["journal_hash"] == traced.journal_hash
    assert len(export["events"]) == traced.journal_len
    assert export["spans"] and export["flight"]
    json.dumps(export)                          # JSON-serializable


# ---------------------------------------------------------------------------
# traceparent parsing: propagation must never fail a request
# ---------------------------------------------------------------------------

def test_from_traceparent_accepts_w3c_and_counter_ids():
    from kuberay_tpu.obs.trace import TraceContext
    ctx = TraceContext.from_traceparent(
        "00-0af7651916cd43dd8448eb211c80319c-b7ad6b7169203331-01")
    assert ctx is not None
    assert ctx.trace_id == "0af7651916cd43dd8448eb211c80319c"
    assert ctx.span_id == "b7ad6b7169203331"
    # This tracer's own counter ids round-trip too.
    ctx = TraceContext.from_traceparent("00-t000001-s000002-01")
    assert (ctx.trace_id, ctx.span_id) == ("t000001", "s000002")
    # Surrounding whitespace is tolerated (proxies pad headers).
    assert TraceContext.from_traceparent("  00-t000001-s000002-01\n") \
        is not None


def test_from_traceparent_rejects_wrong_field_count():
    from kuberay_tpu.obs.trace import TraceContext
    assert TraceContext.from_traceparent("00-t000001-s000002") is None
    assert TraceContext.from_traceparent(
        "00-t000001-s000002-01-extra") is None
    assert TraceContext.from_traceparent("00") is None
    assert TraceContext.from_traceparent("") is None
    assert TraceContext.from_traceparent(None) is None


def test_from_traceparent_rejects_non_hex_ids():
    from kuberay_tpu.obs.trace import TraceContext
    for bad in ("00-TRACE001-s000002-01",      # uppercase
                "00-t00 001-s000002-01",       # embedded space
                "00-t000001-s0000;2-01",       # punctuation
                "00-träce-s000002-01",         # non-ascii
                "00--s000002-01",              # empty trace id
                "00-t000001--01"):             # empty span id
        assert TraceContext.from_traceparent(bad) is None, bad
    # Length bounds on each id: 64 ok, 65 rejected.
    assert TraceContext.from_traceparent(
        f"00-{'a' * 64}-s000002-01") is not None
    assert TraceContext.from_traceparent(
        f"00-{'a' * 65}-s000002-01") is None


def test_from_traceparent_rejects_oversized_header_and_bad_version():
    from kuberay_tpu.obs.trace import TraceContext
    assert TraceContext.from_traceparent(
        "01-t000001-s000002-01") is None     # version != 00
    assert TraceContext.from_traceparent(
        "ff-t000001-s000002-01") is None
    oversized = "00-" + "a" * 300 + "-s000002-01"
    assert len(oversized) > 200
    assert TraceContext.from_traceparent(oversized) is None


# ---------------------------------------------------------------------------
# SpanStore tail-sampling: what survives memory pressure
# ---------------------------------------------------------------------------

def _mk_span(i, *, dur=None, status="ok", name="s"):
    from kuberay_tpu.obs.trace import Span
    end = None if dur is None else float(i) + dur
    return Span(f"t{i:03d}", f"s{i:03d}", "", name, float(i), end,
                status=status)


def test_span_store_evicts_fast_ok_spans_first():
    from kuberay_tpu.obs.trace import SpanStore
    store = SpanStore(max_spans=40)
    # 30 fast ok spans, 4 slow ok spans, 3 errors, 3 still-open spans,
    # then overflow traffic that forces an eviction pass.
    for i in range(30):
        store.add(_mk_span(i, dur=0.01, name="fast"))
    for i in range(30, 34):
        store.add(_mk_span(i, dur=9.0, name="slow"))
    for i in range(34, 37):
        store.add(_mk_span(i, dur=0.01, status="error", name="err"))
    for i in range(37, 40):
        store.add(_mk_span(i, name="open"))
    assert store.dropped == 0
    for i in range(40, 50):
        store.add(_mk_span(i, dur=0.01, name="fast"))
    stats = store.stats()
    assert stats["dropped"] > 0
    assert stats["spans"] <= stats["max_spans"] == 40
    names = [s["name"] for s in store.export()]
    # The interesting tail survives: every slow span, every error, and
    # every still-open span outlive the fast-ok churn.
    assert names.count("slow") == 4
    assert names.count("err") == 3
    assert names.count("open") == 3
    # And what was dropped came from the fast-ok pool.
    assert 30 + 10 - names.count("fast") == stats["dropped"]


def test_span_store_under_extreme_pressure_keeps_open_spans_longest():
    from kuberay_tpu.obs.trace import SpanStore
    store = SpanStore(max_spans=4)
    store.add(_mk_span(0, name="open-a"))
    store.add(_mk_span(1, name="open-b"))
    store.add(_mk_span(2, dur=0.1, status="error", name="err"))
    store.add(_mk_span(3, dur=0.1, name="ok"))
    store.add(_mk_span(4, dur=0.1, name="ok2"))     # forces eviction
    names = [s["name"] for s in store.export()]
    # Open spans are the last resort; the closed-ok spans go first.
    assert "open-a" in names and "open-b" in names
    assert store.stats()["dropped"] >= 1


def test_span_store_stats_envelope_shape():
    from kuberay_tpu.obs.trace import SpanStore
    store = SpanStore(max_spans=8)
    assert store.stats() == {"spans": 0, "max_spans": 8, "dropped": 0}
    for i in range(3):
        store.add(_mk_span(i, dur=0.5))
    assert store.stats() == {"spans": 3, "max_spans": 8, "dropped": 0}
