"""Sample-YAML conformance tier.

Reference model: ``test/sampleyaml/`` + ``.github/workflows/test-sample-yamls.yml``
apply every ``config/samples/*.yaml`` and assert the CR reaches readiness.
Here every file under ``samples/`` is applied through the FULL operator
(all controllers registered, fake kubelet running pods) and must reach its
kind's ready state — so a sample that drifts from the API types or trips
validation fails CI, not a user.
"""

import glob
import os

import pytest
import yaml

from kuberay_tpu.api.config import OperatorConfiguration
from kuberay_tpu.operator import Operator
from kuberay_tpu.runtime.coordinator_client import FakeCoordinatorClient
from kuberay_tpu.utils import constants as C
from kuberay_tpu.utils import features

SAMPLES = sorted(glob.glob(
    os.path.join(os.path.dirname(__file__), "..", "samples", "*.yaml")))


def sample_id(path):
    return os.path.basename(path)


@pytest.fixture(autouse=True)
def reset_gates():
    features.reset()
    yield
    features.reset()


class SampleHarness:
    """Full operator + fake kubelet + per-cluster fake coordinators."""

    def __init__(self):
        self.clients = {}

        def provider(status):
            # Key fake coordinators by coordinator URL so each cluster
            # (active/pending pair, retry clusters...) gets its own.
            key = getattr(status, "coordinatorURL", "") or "default"
            return self.clients.setdefault(key, FakeCoordinatorClient())

        self.operator = Operator(
            OperatorConfiguration(featureGates={"TpuCronJob": True}),
            client_provider=provider, fake_kubelet=True)
        self.store = self.operator.store

    def settle(self, rounds=12):
        for _ in range(rounds):
            self.operator.run_until_idle()
            # Serve apps report RUNNING once their config lands (the same
            # seam rayservice envtest fakes: set_serve_app on submission).
            for client in self.clients.values():
                if client.serve_config is not None and not client.serve_apps:
                    for app in client.serve_config.get("applications", []):
                        client.set_serve_app(app.get("name", "app"), "RUNNING")
        self.operator.run_until_idle()

    def warning_events(self):
        return [e for e in self.store.list("Event")
                if e.get("type") == "Warning"]


@pytest.fixture
def h():
    return SampleHarness()


def load(path):
    with open(path) as f:
        return yaml.safe_load(f)


def expected_slices(cluster_spec):
    return sum(int(g.get("replicas", 0) or 0)
               for g in cluster_spec.get("workerGroupSpecs", []))


def test_all_kinds_are_covered():
    """Every sample parses and no CR kind lacks a conformance branch."""
    kinds = {load(p)["kind"] for p in SAMPLES}
    assert kinds <= {"TpuCluster", "TpuJob", "TpuService", "TpuCronJob",
                     "ComputeTemplate"}
    # The four workload kinds all have at least one sample.
    assert {"TpuCluster", "TpuJob", "TpuService", "TpuCronJob"} <= kinds


@pytest.mark.parametrize("path", SAMPLES, ids=sample_id)
def test_sample_reaches_ready(h, path):
    doc = load(path)
    kind, name = doc["kind"], doc["metadata"]["name"]
    h.store.create(doc)
    h.settle()

    if kind == "TpuCluster":
        got = h.store.get(C.KIND_CLUSTER, name)
        assert got["status"]["state"] == "ready", got["status"]
        assert got["status"]["readySlices"] == expected_slices(doc["spec"])
        # Head pod + head service always exist.
        assert h.store.try_get("Service", f"{name}-head-svc") is not None

    elif kind == "TpuJob":
        # Reaches Running with a ready backing cluster...
        got = h.store.get(C.KIND_JOB, name)
        assert got["status"]["jobDeploymentStatus"] == "Running", got["status"]
        cluster = h.store.get(C.KIND_CLUSTER, got["status"]["clusterName"])
        assert cluster["status"]["state"] == "ready"
        # ... and completes when the app succeeds (submitter + coordinator).
        for sub in h.store.list("Job"):
            sub["status"] = {"succeeded": 1}
            h.store.update_status(sub)
        for client in h.clients.values():
            for jid in list(client.jobs):
                client.set_job_status(jid, "SUCCEEDED")
        h.settle()
        got = h.store.get(C.KIND_JOB, name)
        assert got["status"]["jobDeploymentStatus"] == "Complete", got["status"]

    elif kind == "TpuService":
        got = h.store.get(C.KIND_SERVICE, name)
        assert got["status"]["serviceStatus"] == "Running", got["status"]
        active = got["status"]["activeServiceStatus"]["clusterName"]
        assert h.store.get(C.KIND_CLUSTER, active)["status"]["state"] == "ready"
        assert got["status"]["numServeEndpoints"] > 0

    elif kind == "ComputeTemplate":
        from kuberay_tpu.api.computetemplate import (
            ComputeTemplate, validate_compute_template)
        got = ComputeTemplate.from_dict(
            h.store.get("ComputeTemplate", name))
        assert validate_compute_template(got) == []

    elif kind == "TpuCronJob":
        # Nightly schedule: nothing due now — conformance is that the CR
        # reconciles cleanly and records scheduling state.
        got = h.store.get(C.KIND_CRONJOB, name)
        assert "status" in got
        # Force one due run to prove the template itself is valid.
        got["status"]["lastScheduleTime"] = 1.0  # long before now
        h.store.update_status(got)
        h.operator.manager.enqueue((C.KIND_CRONJOB, "default", name))
        h.settle()
        jobs = h.store.list(C.KIND_JOB)
        assert jobs, "cron job never materialized a TpuJob"
        assert jobs[0]["metadata"]["labels"][C.LABEL_ORIGINATED_FROM_CRD] \
            == C.KIND_CRONJOB

    # No sample may trip validation or builder warnings.
    bad = [e for e in h.warning_events()
           if "Invalid" in e.get("reason", "")]
    assert not bad, bad
