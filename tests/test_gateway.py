"""Weighted gateway: TrafficRoute-driven traffic shifting end to end."""

import json
import urllib.request

import jax
import pytest

from kuberay_tpu.controlplane.store import ObjectStore
from kuberay_tpu.models import llama
from kuberay_tpu.serve.engine import ServeEngine
from kuberay_tpu.serve.gateway import WeightedGateway
from kuberay_tpu.serve.server import ServeFrontend

CFG = llama.CONFIGS["llama_tiny"]


@pytest.fixture(scope="module")
def two_backends():
    """Two real serve frontends (old/new cluster stand-ins)."""
    params = llama.init_params(CFG, jax.random.PRNGKey(0))
    fes, urls = [], {}
    for name in ("svc-old", "svc-new"):
        fe = ServeFrontend(ServeEngine(CFG, params, max_slots=2, max_len=64))
        srv, url = fe.serve_background()
        fes.append((fe, srv))
        urls[name] = url
    yield urls
    for fe, srv in fes:
        srv.shutdown()
        fe.close()


def post(url, body, timeout=120):
    req = urllib.request.Request(
        f"{url}/v1/completions", data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"})
    return json.load(urllib.request.urlopen(req, timeout=timeout))


def make_route(store, weights):
    store.create({
        "apiVersion": "tpu.dev/v1", "kind": "TrafficRoute",
        "metadata": {"name": "svc-route", "namespace": "default"},
        "spec": {"backends": [
            {"service": name, "weight": w} for name, w in weights.items()]},
        "status": {},
    })


def test_weighted_routing_follows_route(two_backends):
    store = ObjectStore()
    make_route(store, {"svc-old": 100, "svc-new": 0})
    gw = WeightedGateway(store, "svc-route",
                         resolver=lambda svc: two_backends[svc],
                         poll_interval=0.05)
    srv, url = gw.serve_background_http()
    try:
        out = post(url, {"prompt_tokens": [1, 2, 3], "max_tokens": 2})
        assert len(out["tokens"]) == 2
        # 100/0: everything lands on old.
        for _ in range(5):
            post(url, {"prompt_tokens": [4, 5], "max_tokens": 1})
        assert gw.stats().get(two_backends["svc-new"], 0) == 0
        # Controller steps the weights -> traffic shifts to new only.
        obj = store.get("TrafficRoute", "svc-route")
        obj["spec"]["backends"] = [{"service": "svc-old", "weight": 0},
                                   {"service": "svc-new", "weight": 100}]
        store.update(obj)
        import time
        time.sleep(0.2)     # watch refresh
        before_new = gw.stats().get(two_backends["svc-new"], 0)
        for _ in range(5):
            post(url, {"prompt_tokens": [6, 7], "max_tokens": 1})
        assert gw.stats()[two_backends["svc-new"]] == before_new + 5
    finally:
        srv.shutdown()
        gw.close()


def test_gateway_no_backends_503(two_backends):
    store = ObjectStore()   # no route at all
    gw = WeightedGateway(store, "missing-route",
                         resolver=lambda svc: two_backends[svc])
    srv, url = gw.serve_background_http()
    try:
        req = urllib.request.Request(
            f"{url}/v1/completions", data=b"{}",
            headers={"Content-Type": "application/json"})
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(req, timeout=10)
        assert e.value.code == 503
    finally:
        srv.shutdown()
        gw.close()


def test_gateway_backend_error_502(two_backends):
    store = ObjectStore()
    make_route(store, {"svc-old": 100})
    gw = WeightedGateway(store, "svc-route",
                         resolver=lambda svc: "http://127.0.0.1:1")  # dead
    srv, url = gw.serve_background_http()
    try:
        req = urllib.request.Request(
            f"{url}/v1/completions", data=b'{"prompt_tokens": [1]}',
            headers={"Content-Type": "application/json"})
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(req, timeout=10)
        assert e.value.code == 502
    finally:
        srv.shutdown()
        gw.close()
