"""tpuctl session: real TCP forwarding to a live coordinator."""

import json
import urllib.request

import pytest

from kuberay_tpu.cli.session import PortForward
from kuberay_tpu.runtime.coordinator_server import CoordinatorServer, MemoryBackend


def test_port_forward_relays_http():
    coord = CoordinatorServer(state=MemoryBackend(), spawn_jobs=False)
    srv, url = coord.serve_background()
    remote_port = int(url.rsplit(":", 1)[1])
    pf = PortForward(0, "127.0.0.1", remote_port)
    try:
        out = json.load(urllib.request.urlopen(
            f"http://127.0.0.1:{pf.local_port}/api/healthz", timeout=10))
        assert out == {"status": "ok"}
        # POST through the tunnel too.
        req = urllib.request.Request(
            f"http://127.0.0.1:{pf.local_port}/api/jobs/",
            data=json.dumps({"submission_id": "tunneled",
                             "entrypoint": "x"}).encode(),
            headers={"Content-Type": "application/json"})
        out = json.load(urllib.request.urlopen(req, timeout=10))
        assert out["submission_id"] == "tunneled"
        assert "tunneled" in coord.jobs
    finally:
        pf.close()
        srv.shutdown()


def test_port_forward_dead_upstream():
    pf = PortForward(0, "127.0.0.1", 1)   # nothing listens on :1
    try:
        with pytest.raises(Exception):
            urllib.request.urlopen(
                f"http://127.0.0.1:{pf.local_port}/x", timeout=5)
    finally:
        pf.close()


def test_session_print_only(capsys):
    from kuberay_tpu.cli.session import run_session
    rc = run_session("head.svc", [(8265, 8265, "dashboard")],
                     print_only=True)
    assert rc == 0
    assert "head.svc:8265" in capsys.readouterr().out
