"""Mixtral MoE: routing math, forward, training, expert-parallel sharding."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from kuberay_tpu.models import mixtral
from kuberay_tpu.parallel.mesh import DEFAULT_RULES, MeshSpec, logical_to_sharding

CFG = mixtral.CONFIGS["mixtral_tiny"]


def make_batch(key, batch=2, seq=16):
    tokens = jax.random.randint(key, (batch, seq), 0, CFG.vocab_size)
    return tokens, jnp.roll(tokens, -1, axis=1)


def test_forward_shapes():
    params = mixtral.init_params(CFG, jax.random.PRNGKey(0))
    tokens, _ = make_batch(jax.random.PRNGKey(1))
    logits, aux = mixtral.forward(CFG, params, tokens)
    assert logits.shape == (2, 16, CFG.vocab_size)
    assert bool(jnp.isfinite(logits).all())
    assert float(aux["load_balance"]) > 0


def test_moe_capacity_drops_overflow():
    """With capacity_factor -> tiny, most tokens drop; output stays finite
    and bounded (dropped tokens contribute zero, not garbage)."""
    import dataclasses
    cfg = dataclasses.replace(CFG, capacity_factor=0.05)
    params = mixtral.init_params(cfg, jax.random.PRNGKey(0))
    tokens, _ = make_batch(jax.random.PRNGKey(1))
    logits, _ = mixtral.forward(cfg, params, tokens)
    assert bool(jnp.isfinite(logits).all())


def test_router_load_balance_uniform_is_one():
    """For a perfectly uniform router, the Switch penalty -> aux_weight."""
    B, S, E = 4, 8, CFG.n_experts
    # Uniform probabilities: me = 1/E; top-1 assignments spread evenly.
    me = jnp.full((E,), 1.0 / E)
    ce = jnp.full((E,), 1.0 / E)
    penalty = E * jnp.sum(me * ce)
    np.testing.assert_allclose(float(penalty), 1.0, rtol=1e-6)


def test_training_reduces_loss():
    params = mixtral.init_params(CFG, jax.random.PRNGKey(0))
    tokens, targets = make_batch(jax.random.PRNGKey(1))
    opt = optax.adam(1e-2)
    opt_state = opt.init(params)

    @jax.jit
    def step(params, opt_state):
        (total, metrics), grads = jax.value_and_grad(
            lambda p: mixtral.loss_fn(CFG, p, tokens, targets),
            has_aux=True)(params)
        updates, opt_state = opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, metrics

    first = None
    for _ in range(15):
        params, opt_state, metrics = step(params, opt_state)
        if first is None:
            first = float(metrics["loss"])
    assert float(metrics["loss"]) < first * 0.8


def test_expert_parallel_sharding():
    """Experts shard over ep; forward agrees with unsharded execution."""
    mesh = MeshSpec(dp=2, fsdp=1, tp=1, sp=1, ep=4).build(jax.devices()[:8])
    params = mixtral.init_params(CFG, jax.random.PRNGKey(0))
    axes = mixtral.param_axes(CFG)
    shardings = jax.tree.map(
        lambda a: logical_to_sharding(DEFAULT_RULES, mesh, a), axes,
        is_leaf=lambda x: isinstance(x, tuple))
    sharded = jax.device_put(params, shardings)
    wg = sharded["layers"]["w_gate"]
    assert wg.sharding.spec == P(None, "ep", "fsdp", "tp")
    tokens, _ = make_batch(jax.random.PRNGKey(1), batch=4)
    ref_logits, _ = mixtral.forward(CFG, params, tokens)
    out_logits, _ = jax.jit(
        lambda p, t: mixtral.forward(CFG, p, t))(sharded, tokens)
    np.testing.assert_allclose(np.asarray(out_logits), np.asarray(ref_logits),
                               rtol=2e-4, atol=2e-4)
