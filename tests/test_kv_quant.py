"""int8 KV cache: quantized storage with dequantized attention reads."""

import jax
import jax.numpy as jnp
import numpy as np

from kuberay_tpu.models.llama import CONFIGS, init_params
from kuberay_tpu.serve.engine import Request, ServeEngine
from kuberay_tpu.serve.kv_cache import (
    dequantize_kv,
    forward_with_cache,
    init_kv_cache,
    make_quantized_forward,
    quantize_kv,
)

CFG = CONFIGS["llama_tiny"]
PARAMS = init_params(CFG, jax.random.PRNGKey(0))


def test_quantize_roundtrip_error_bounded():
    x = jax.random.normal(jax.random.PRNGKey(1), (4, 7, 2, 16))
    q, s = quantize_kv(x)
    back = dequantize_kv(q, s, jnp.float32)
    # Symmetric per-vector int8: error <= scale/2 = absmax/254.
    bound = np.abs(np.asarray(x)).max(-1, keepdims=True) / 254.0 + 1e-6
    assert np.all(np.abs(np.asarray(back - x)) <= bound)


def test_cache_bytes_halved():
    dense = init_kv_cache(CFG, slots=4, max_len=64)
    quant = init_kv_cache(CFG, slots=4, max_len=64, quant="int8")
    dense_bytes = sum(a.nbytes for a in jax.tree.leaves(dense))
    quant_bytes = sum(a.nbytes for a in jax.tree.leaves(quant))
    # int8 payload + f32 scales: well under the fp32-tiny / bf16-real size.
    assert quant_bytes < 0.6 * dense_bytes


def test_quantized_logits_close_to_dense():
    """Prefill + one decode step: int8-cache logits track the exact-cache
    logits closely (same params, same tokens)."""
    B, T = 2, 12
    tokens = jax.random.randint(jax.random.PRNGKey(2), (B, T), 1,
                                CFG.vocab_size)
    start = jnp.zeros((B,), jnp.int32)

    dense_cache = init_kv_cache(CFG, B, 32)
    q_cache = init_kv_cache(CFG, B, 32, quant="int8")
    qfwd = make_quantized_forward()

    ld, dense_cache = forward_with_cache(CFG, PARAMS, tokens, dense_cache,
                                         start)
    lq, q_cache = qfwd(CFG, PARAMS, tokens, q_cache, start)
    # Cosine similarity of the final-position logits.
    a = np.asarray(ld[:, -1]).astype(np.float64)
    b = np.asarray(lq[:, -1]).astype(np.float64)
    cos = (a * b).sum(-1) / (np.linalg.norm(a, axis=-1)
                             * np.linalg.norm(b, axis=-1))
    assert np.all(cos > 0.999), cos

    # Decode step at start=T.
    nxt = jnp.argmax(ld[:, -1], -1).astype(jnp.int32)[:, None]
    ld2, _ = forward_with_cache(CFG, PARAMS, nxt, dense_cache,
                                jnp.full((B,), T, jnp.int32))
    lq2, _ = qfwd(CFG, PARAMS, nxt, q_cache, jnp.full((B,), T, jnp.int32))
    a = np.asarray(ld2[:, 0]).astype(np.float64)
    b = np.asarray(lq2[:, 0]).astype(np.float64)
    cos = (a * b).sum(-1) / (np.linalg.norm(a, axis=-1)
                             * np.linalg.norm(b, axis=-1))
    assert np.all(cos > 0.999), cos


def test_quant_decode_kernel_matches_xla():
    """Pallas int8 decode kernel (interpret mode) == dequant-then-dense
    reference, including short lengths that exercise the DMA skip."""
    from kuberay_tpu.ops.decode_attention import (
        decode_attention_quant_pallas,
        decode_attention_quant_xla,
        decode_attention_xla,
    )
    S, M, Hq, Hkv, D = 4, 64, 8, 4, 16
    ks_ = jax.random.split(jax.random.PRNGKey(5), 3)
    q = jax.random.normal(ks_[0], (S, Hq, D), jnp.float32)
    kraw = jax.random.normal(ks_[1], (S, M, Hkv, D), jnp.float32)
    vraw = jax.random.normal(ks_[2], (S, M, Hkv, D), jnp.float32)
    kq, ks = quantize_kv(kraw)
    vq, vs = quantize_kv(vraw)
    # Cache layout: scales position-on-lanes.
    ks = jnp.moveaxis(ks[..., 0], -1, 1)           # [S, Hkv, M]
    vs = jnp.moveaxis(vs[..., 0], -1, 1)
    for lens in (jnp.array([64, 17, 1, 33]), jnp.full((S,), M)):
        want = decode_attention_quant_xla(q, kq, ks, vq, vs, lens)
        got = decode_attention_quant_pallas(q, kq, ks, vq, vs, lens,
                                            bkv=16, interpret=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-4, atol=2e-4)
        # And the whole quant pipeline tracks the unquantized attention.
        exact = decode_attention_xla(q, kraw, vraw, lens)
        err = np.abs(np.asarray(got) - np.asarray(exact)).max()
        assert err < 0.05, err


def test_engine_runs_with_int8_cache():
    eng = ServeEngine(CFG, PARAMS, max_slots=2, max_len=64, kv_quant="int8")
    eng.add_request(Request("a", [3, 4, 5, 6, 7], max_new_tokens=6))
    eng.add_request(Request("b", [9, 8, 7], max_new_tokens=4))
    out = {r.request_id: r for r in eng.run()}
    assert len(out["a"].tokens) == 6 and len(out["b"].tokens) == 4
    # Greedy tokens mostly agree with the exact-cache engine on a tiny
    # model; at minimum the FIRST token (pure prefill) must match.
    exact = ServeEngine(CFG, PARAMS, max_slots=2, max_len=64)
    exact.add_request(Request("a", [3, 4, 5, 6, 7], max_new_tokens=6))
    ref = exact.run()[0]
    assert out["a"].tokens[0] == ref.tokens[0]


def test_int8_composes_with_chunked_prefill():
    def run(**kw):
        eng = ServeEngine(CFG, PARAMS, max_slots=2, max_len=64, **kw)
        eng.add_request(Request("r", list(range(1, 20)), max_new_tokens=5))
        return eng.run()[0].tokens
    assert run(kv_quant="int8", prefill_chunk=8) == run(kv_quant="int8")


def test_mixtral_with_int8_cache():
    from kuberay_tpu.models import mixtral
    mcfg = mixtral.CONFIGS["mixtral_tiny"]
    mparams = mixtral.init_params(mcfg, jax.random.PRNGKey(3))
    eng = ServeEngine(mcfg, mparams, max_slots=2, max_len=64,
                      kv_quant="int8")
    eng.add_request(Request("m", [2, 3, 5, 8], max_new_tokens=4))
    out = eng.run()[0]
    assert len(out.tokens) == 4
