"""Helm chart rendering + RBAC consistency (ref helm-chart/
kuberay-operator + scripts/rbac-check.py).  Rendered with the in-repo
subset renderer so CI needs no helm binary; the chart itself is
standard helm syntax."""

import json
import pathlib
import subprocess
import sys

import pytest

REPO = pathlib.Path(__file__).resolve().parent.parent
CHART = str(REPO / "helm-chart/kuberay-tpu-operator")

sys.path.insert(0, str(REPO / "scripts"))
from render_chart import ChartError, render_chart, render_template  # noqa: E402


def by_kind(docs, kind):
    return [d for d in docs if d.get("kind") == kind]


def test_default_render_shape():
    docs = render_chart(CHART, namespace="kuberay-tpu-system")
    kinds = sorted({d["kind"] for d in docs})
    assert kinds == ["ClusterRole", "ClusterRoleBinding", "ConfigMap",
                     "Deployment", "Role", "RoleBinding", "Service",
                     "ServiceAccount"]
    dep = by_kind(docs, "Deployment")[0]
    assert dep["metadata"]["namespace"] == "kuberay-tpu-system"
    c = dep["spec"]["template"]["spec"]["containers"][0]
    assert c["image"] == "registry.local/kuberay-tpu/operator:latest"
    assert "--leader-election" in c["args"]
    # Probes hit the pod IP, so the API must bind all interfaces, and the
    # mounted ConfigMap must actually be consumed via --config.
    assert "--api-host=0.0.0.0" in c["args"]
    assert "--config=/etc/kuberay-tpu/config.json" in c["args"]
    # ConfigMap payload is valid operator config JSON.
    cm = by_kind(docs, "ConfigMap")[0]
    cfg = json.loads(cm["data"]["config.json"])
    assert cfg["enableLeaderElection"] is True
    # Leader election needs the Lease role.
    role = by_kind(docs, "Role")[0]
    assert any("leases" in r.get("resources", []) for r in role["rules"])


def test_namespaced_mode_swaps_clusterrole_for_roles():
    docs = render_chart(CHART, sets=["watchNamespaces=[team-a,team-b]"])
    operator_croles = [d for d in by_kind(docs, "ClusterRole")
                       if "editor" not in d["metadata"]["name"]
                       and "viewer" not in d["metadata"]["name"]]
    assert operator_croles == []
    roles = [d for d in by_kind(docs, "Role")
             if "leader-election" not in d["metadata"]["name"]]
    assert sorted(r["metadata"]["namespace"] for r in roles) == \
        ["team-a", "team-b"]


def test_toggles():
    docs = render_chart(CHART, sets=["metrics.serviceMonitor.enabled=true"])
    sm = by_kind(docs, "ServiceMonitor")
    assert len(sm) == 1
    # Metrics are served on the API port; the monitor must scrape a port
    # that actually has a listener.
    assert sm[0]["spec"]["endpoints"][0]["port"] == "api"
    svc = by_kind(docs, "Service")[0]
    assert [p["name"] for p in svc["spec"]["ports"]] == ["api"]
    docs = render_chart(CHART, sets=["serviceAccount.create=false"])
    assert by_kind(docs, "ServiceAccount") == []
    docs = render_chart(CHART, sets=["leaderElection.enabled=false",
                                     "historyArchiveURL=s3://arch"])
    dep = by_kind(docs, "Deployment")[0]
    args = dep["spec"]["template"]["spec"]["containers"][0]["args"]
    assert "--leader-election" not in args
    assert "--history-archive=s3://arch" in args


def test_editor_viewer_roles_per_kind():
    docs = render_chart(CHART)
    names = {d["metadata"]["name"] for d in by_kind(docs, "ClusterRole")}
    for kind in ("tpujob", "tpuservice", "tpucronjob", "tpucluster"):
        assert f"{kind}-editor" in names and f"{kind}-viewer" in names


def test_renderer_rejects_unsupported_syntax():
    with pytest.raises(ChartError):
        render_template("{{ lookup \"v1\" \"Pod\" }}", {}, "r", "ns", "c")


def test_rbac_check_passes():
    out = subprocess.run(
        [sys.executable, str(REPO / "scripts/rbac_check.py")],
        capture_output=True, text=True)
    assert out.returncode == 0, out.stdout + out.stderr
    assert "chart == manifest" in out.stdout


def test_crds_shipped_with_chart():
    chart_crds = sorted(p.name for p in
                        (REPO / "helm-chart/kuberay-tpu-operator/crds")
                        .glob("*.yaml"))
    base_crds = sorted(p.name for p in
                       (REPO / "config/crd/bases").glob("*.yaml"))
    assert chart_crds == base_crds and len(chart_crds) >= 6


def test_openapi_spec_current_and_served():
    """docs/openapi.json is generated from the CRD schemas (the typed
    contract ratified in ARCHITECTURE.md) and served by the apiserver."""
    import urllib.request

    out = subprocess.run(
        [sys.executable, str(REPO / "scripts/gen_openapi.py"), "--check"],
        capture_output=True, text=True)
    assert out.returncode == 0, out.stdout

    spec = json.loads((REPO / "docs/openapi.json").read_text())
    assert spec["openapi"].startswith("3.")
    base = "/apis/tpu.dev/v1/namespaces/{namespace}/tpuclusters"
    assert set(spec["paths"][base]) == {"get", "post"}
    assert set(spec["paths"][base + "/{name}"]) == {"get", "put", "delete"}
    assert base + "/{name}/status" in spec["paths"]
    assert "TpuJob" in spec["components"]["schemas"]

    from kuberay_tpu.apiserver.server import serve_background
    from kuberay_tpu.controlplane.store import ObjectStore
    srv, url = serve_background(ObjectStore())
    try:
        served = json.load(urllib.request.urlopen(f"{url}/openapi.json"))
        assert served["info"]["title"] == "kuberay-tpu apiserver"
    finally:
        srv.shutdown()
