"""Helm chart rendering + RBAC consistency (ref helm-chart/
kuberay-operator + scripts/rbac-check.py).  Rendered with the in-repo
subset renderer so CI needs no helm binary; the chart itself is
standard helm syntax."""

import json
import pathlib
import subprocess
import sys

import pytest

REPO = pathlib.Path(__file__).resolve().parent.parent
CHART = str(REPO / "helm-chart/kuberay-tpu-operator")

sys.path.insert(0, str(REPO / "scripts"))
from render_chart import ChartError, render_chart, render_template  # noqa: E402


def by_kind(docs, kind):
    return [d for d in docs if d.get("kind") == kind]


def test_default_render_shape():
    docs = render_chart(CHART, namespace="kuberay-tpu-system")
    kinds = sorted({d["kind"] for d in docs})
    assert kinds == ["ClusterRole", "ClusterRoleBinding", "ConfigMap",
                     "Deployment", "Role", "RoleBinding", "Service",
                     "ServiceAccount"]
    dep = by_kind(docs, "Deployment")[0]
    assert dep["metadata"]["namespace"] == "kuberay-tpu-system"
    c = dep["spec"]["template"]["spec"]["containers"][0]
    assert c["image"] == "registry.local/kuberay-tpu/operator:latest"
    assert "--leader-election" in c["args"]
    # Probes hit the pod IP, so the API must bind all interfaces, and the
    # mounted ConfigMap must actually be consumed via --config.
    assert "--api-host=0.0.0.0" in c["args"]
    assert "--config=/etc/kuberay-tpu/config.json" in c["args"]
    # ConfigMap payload is valid operator config JSON.
    cm = by_kind(docs, "ConfigMap")[0]
    cfg = json.loads(cm["data"]["config.json"])
    assert cfg["enableLeaderElection"] is True
    # Leader election needs the Lease role.
    role = by_kind(docs, "Role")[0]
    assert any("leases" in r.get("resources", []) for r in role["rules"])


def test_namespaced_mode_swaps_clusterrole_for_roles():
    docs = render_chart(CHART, sets=["watchNamespaces=[team-a,team-b]"])
    operator_croles = [d for d in by_kind(docs, "ClusterRole")
                       if "editor" not in d["metadata"]["name"]
                       and "viewer" not in d["metadata"]["name"]]
    assert operator_croles == []
    roles = [d for d in by_kind(docs, "Role")
             if "leader-election" not in d["metadata"]["name"]]
    assert sorted(r["metadata"]["namespace"] for r in roles) == \
        ["team-a", "team-b"]


def test_toggles():
    docs = render_chart(CHART, sets=["metrics.serviceMonitor.enabled=true"])
    sm = by_kind(docs, "ServiceMonitor")
    assert len(sm) == 1
    # Metrics are served on the API port; the monitor must scrape a port
    # that actually has a listener.
    assert sm[0]["spec"]["endpoints"][0]["port"] == "api"
    svc = by_kind(docs, "Service")[0]
    assert [p["name"] for p in svc["spec"]["ports"]] == ["api"]
    docs = render_chart(CHART, sets=["serviceAccount.create=false"])
    assert by_kind(docs, "ServiceAccount") == []
    docs = render_chart(CHART, sets=["leaderElection.enabled=false",
                                     "historyArchiveURL=s3://arch"])
    dep = by_kind(docs, "Deployment")[0]
    args = dep["spec"]["template"]["spec"]["containers"][0]["args"]
    assert "--leader-election" not in args
    assert "--history-archive=s3://arch" in args


def test_editor_viewer_roles_per_kind():
    docs = render_chart(CHART)
    names = {d["metadata"]["name"] for d in by_kind(docs, "ClusterRole")}
    for kind in ("tpujob", "tpuservice", "tpucronjob", "tpucluster"):
        assert f"{kind}-editor" in names and f"{kind}-viewer" in names


def test_renderer_rejects_unsupported_syntax():
    with pytest.raises(ChartError):
        render_template("{{ lookup \"v1\" \"Pod\" }}", {}, "r", "ns", "c")


def test_rbac_check_passes():
    out = subprocess.run(
        [sys.executable, str(REPO / "scripts/rbac_check.py")],
        capture_output=True, text=True)
    assert out.returncode == 0, out.stdout + out.stderr
    assert "chart == manifest" in out.stdout


def test_crds_shipped_with_chart():
    chart_crds = sorted(p.name for p in
                        (REPO / "helm-chart/kuberay-tpu-operator/crds")
                        .glob("*.yaml"))
    base_crds = sorted(p.name for p in
                       (REPO / "config/crd/bases").glob("*.yaml"))
    assert chart_crds == base_crds and len(chart_crds) >= 6


def test_openapi_spec_current_and_served():
    """docs/openapi.json is generated from the CRD schemas (the typed
    contract ratified in ARCHITECTURE.md) and served by the apiserver."""
    import urllib.request

    out = subprocess.run(
        [sys.executable, str(REPO / "scripts/gen_openapi.py"), "--check"],
        capture_output=True, text=True)
    assert out.returncode == 0, out.stdout

    spec = json.loads((REPO / "docs/openapi.json").read_text())
    assert spec["openapi"].startswith("3.")
    base = "/apis/tpu.dev/v1/namespaces/{namespace}/tpuclusters"
    assert set(spec["paths"][base]) == {"get", "post"}
    assert set(spec["paths"][base + "/{name}"]) == {"get", "put", "delete"}
    assert base + "/{name}/status" in spec["paths"]
    assert "TpuJob" in spec["components"]["schemas"]

    from kuberay_tpu.apiserver.server import serve_background
    from kuberay_tpu.controlplane.store import ObjectStore
    srv, url = serve_background(ObjectStore())
    try:
        served = json.load(urllib.request.urlopen(f"{url}/openapi.json"))
        assert served["info"]["title"] == "kuberay-tpu apiserver"
    finally:
        srv.shutdown()


# ---------------------------------------------------------------------------
# Workload + apiserver charts (ref helm-chart/ray-cluster and
# helm-chart/kuberay-apiserver; VERDICT r3 item 8)


def test_tpu_cluster_chart_renders_admission_valid_cr():
    """The rendered TpuCluster must pass the framework's OWN admission
    validation — the chart and the API can never drift apart silently."""
    from kuberay_tpu.api.tpucluster import TpuCluster
    from kuberay_tpu.utils.validation import validate_cluster

    docs = render_chart(str(REPO / "helm-chart/tpu-cluster"),
                        release="demo")
    (cr,) = docs
    assert cr["kind"] == "TpuCluster"
    assert validate_cluster(TpuCluster.from_dict(cr)) == []
    g = cr["spec"]["workerGroupSpecs"][0]
    assert g["topology"] == "2x4" and g["maxReplicas"] == 4


def test_tpu_cluster_chart_toggles():
    docs = render_chart(
        str(REPO / "helm-chart/tpu-cluster"), release="asc",
        sets=["enableInTreeAutoscaling=true",
              "gangSchedulingQueue=research",
              "head.enableIngress=true"])
    (cr,) = docs
    assert cr["spec"]["enableInTreeAutoscaling"] is True
    assert cr["spec"]["gangSchedulingQueue"] == "research"
    assert cr["spec"]["headGroupSpec"]["enableIngress"] is True
    from kuberay_tpu.api.tpucluster import TpuCluster
    from kuberay_tpu.utils.validation import validate_cluster
    assert validate_cluster(TpuCluster.from_dict(cr)) == []


def test_apiserver_chart_shapes():
    chart = str(REPO / "helm-chart/kuberay-tpu-apiserver")
    docs = render_chart(chart, release="api")
    kinds = sorted(d["kind"] for d in docs)
    assert kinds == ["Deployment", "Service", "ServiceAccount"]
    dep = by_kind(docs, "Deployment")[0]
    args = dep["spec"]["template"]["spec"]["containers"][0]["args"]
    assert "--journal=/data/journal.bin" not in args   # off by default
    # Persistence + auth wire volumes and args together.
    docs = render_chart(chart, release="api",
                        sets=["persistence.enabled=true",
                              "authSecret=tok"])
    assert sorted(d["kind"] for d in docs) == [
        "Deployment", "PersistentVolumeClaim", "Service", "ServiceAccount"]
    dep = by_kind(docs, "Deployment")[0]
    ctr = dep["spec"]["template"]["spec"]["containers"][0]
    assert "--journal=/data/journal.bin" in ctr["args"]
    assert "--token-file=/etc/apiserver-auth/token" in ctr["args"]
    mounts = {m["name"] for m in ctr["volumeMounts"]}
    vols = {v["name"] for v in dep["spec"]["template"]["spec"]["volumes"]}
    assert mounts == vols == {"data", "auth"}
    svc = by_kind(docs, "Service")[0]
    assert svc["spec"]["ports"][0]["port"] == 8765


def test_standalone_apiserver_process_boots(tmp_path):
    """python -m kuberay_tpu.apiserver: boots, serves CRUD, persists
    through its journal across a restart."""
    import json as _json
    import time
    import urllib.request

    import socket
    s = socket.socket(); s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]; s.close()
    journal = str(tmp_path / "journal.bin")

    def boot():
        return subprocess.Popen(
            [sys.executable, "-m", "kuberay_tpu.apiserver",
             "--host", "127.0.0.1", "--port", str(port),
             "--journal", journal],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)

    def wait_healthy(proc, timeout=30):
        deadline = time.time() + timeout
        while time.time() < deadline:
            try:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/healthz", timeout=1)
                return True
            except OSError:
                if proc.poll() is not None:
                    raise AssertionError(proc.communicate()[0][-2000:])
                time.sleep(0.1)
        return False

    p = boot()
    try:
        assert wait_healthy(p)
        from tests.test_api_types import make_cluster
        body = _json.dumps(make_cluster("persisted").to_dict()).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/apis/tpu.dev/v1/namespaces/default/"
            "tpuclusters", data=body, method="POST",
            headers={"Content-Type": "application/json"})
        assert urllib.request.urlopen(req, timeout=5).status == 201
    finally:
        p.terminate(); p.wait(timeout=10)
    # Restart: the journal replays the CR.
    p = boot()
    try:
        assert wait_healthy(p)
        got = _json.load(urllib.request.urlopen(
            f"http://127.0.0.1:{port}/apis/tpu.dev/v1/namespaces/default/"
            "tpuclusters/persisted", timeout=5))
        assert got["metadata"]["name"] == "persisted"
    finally:
        p.terminate(); p.wait(timeout=10)
