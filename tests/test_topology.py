"""Topology math: the invariant base for slice-atomic scheduling."""

import pytest

from kuberay_tpu.topology import (
    SliceTopology,
    TopologyError,
    get_generation,
    mesh_shape_for,
    parse_topology,
)


def test_parse_topology():
    assert parse_topology("4x4") == (4, 4)
    assert parse_topology("2x2x2") == (2, 2, 2)
    assert parse_topology("16x16") == (16, 16)
    with pytest.raises(TopologyError):
        parse_topology("4xx4")
    with pytest.raises(TopologyError):
        parse_topology("")
    with pytest.raises(TopologyError):
        parse_topology("0x4")


def test_generation_aliases():
    assert get_generation("v5litepod").name == "v5e"
    assert get_generation("Trillium").name == "v6e"
    with pytest.raises(TopologyError):
        get_generation("v99")


@pytest.mark.parametrize(
    "gen,topo,chips,hosts,chips_per_host",
    [
        ("v5e", "2x2", 4, 1, 4),        # single-host v5e-4 (BASELINE config #2)
        ("v5e", "2x4", 8, 1, 8),        # single-host 8-chip attachment
        ("v5e", "4x4", 16, 4, 4),       # v5e-16 (BASELINE config #4)
        ("v5e", "16x16", 256, 64, 4),
        ("v5p", "2x2x2", 8, 2, 4),
        ("v5p", "4x4x4", 64, 16, 4),    # v5p-64 (BASELINE config #3: 4x4 PodSlice)
        ("v5p", "2x2x4", 16, 4, 4),     # v5p-32-ish two-group EP (config #5)
        # ray-job.tpu-v6e-16-multihost.yaml: numOfHosts: 4, google.com/tpu: 4
        ("v6e", "4x4", 16, 4, 4),
        ("v4", "2x2x4", 16, 4, 4),
    ],
)
def test_slice_math(gen, topo, chips, hosts, chips_per_host):
    s = SliceTopology.create(gen, topo)
    assert s.num_chips == chips
    assert s.num_hosts == hosts
    assert s.chips_per_host == chips_per_host
    assert s.is_multi_host == (hosts > 1)


def test_dims_mismatch():
    with pytest.raises(TopologyError):
        SliceTopology.create("v5e", "2x2x2")   # v5e is 2D
    with pytest.raises(TopologyError):
        SliceTopology.create("v5p", "4x4")     # v5p is 3D


def test_ring_order_is_permutation():
    for gen, topo in [("v5e", "4x4"), ("v5p", "4x4x4"), ("v5e", "16x16")]:
        s = SliceTopology.create(gen, topo)
        order = s.host_ring_order()
        assert sorted(order) == list(range(s.num_hosts))


def test_ring_order_3d_host_grid_neighborwise():
    # v5p 8x8x8: 512 chips / 4 per host = 128 hosts; hosts own 2x2x1 chip
    # blocks, so the host grid is (4, 4, 8).
    s = SliceTopology.create("v5p", "8x8x8")
    grid = s.host_grid_dims()
    assert s.num_hosts == 128 and grid == (4, 4, 8)
    order = list(s.host_ring_order())
    assert sorted(order) == list(range(128))
    # Every consecutive hop moves exactly one grid coordinate by 1.
    strides = (grid[1] * grid[2], grid[2], 1)

    def coords(i):
        return (i // strides[0], (i // strides[1]) % grid[1], i % grid[2])

    for a, b in zip(order, order[1:]):
        ca, cb = coords(a), coords(b)
        assert sum(abs(x - y) for x, y in zip(ca, cb)) == 1, (ca, cb)


def test_invalid_gke_topologies_rejected():
    with pytest.raises(TopologyError):
        SliceTopology.create("v5e", "2x12")   # divisible by 8 but no such pool
    with pytest.raises(TopologyError):
        SliceTopology.create("v5e", "1x8")
    with pytest.raises(TopologyError):
        SliceTopology.create("v5p", "2x2x6")  # 6 is not 1, 2, or mult of 4


def test_ring_order_snake_is_neighborwise():
    # 64 hosts of a v5e 16x16: hosts own 2x2 chip blocks -> host grid (8, 8).
    s = SliceTopology.create("v5e", "16x16")
    assert s.host_grid_dims() == (8, 8)
    order = list(s.host_ring_order())
    assert len(order) == 64
    # Consecutive entries differ by a single grid step (row or col neighbor).
    cols = 8
    for a, b in zip(order, order[1:]):
        ra, ca = divmod(a, cols)
        rb, cb = divmod(b, cols)
        assert abs(ra - rb) + abs(ca - cb) == 1


def test_host_grid_single_host():
    assert SliceTopology.create("v5e", "2x2").host_grid_dims() == (1,)


def test_host_grid_degenerate_axis():
    # v5p 1x4x8: the 2x2 board can't straddle the size-1 axis; blocks land
    # on the remaining axes -> grid (1, 2, 4), ring still neighbor-wise.
    s = SliceTopology.create("v5p", "1x4x8")
    assert s.num_hosts == 8
    assert s.host_block_dims() == (1, 2, 2)
    assert s.host_grid_dims() == (1, 2, 4)
    order = list(s.host_ring_order())
    assert sorted(order) == list(range(8))
    for a, b in zip(order, order[1:]):
        ra, ca = divmod(a, 4)
        rb, cb = divmod(b, 4)
        assert abs(ra - rb) + abs(ca - cb) == 1


def test_transposed_2d_topology_rejected():
    with pytest.raises(TopologyError):
        SliceTopology.create("v5e", "8x4")   # only canonical '4x8' exists


def test_mesh_shape_bad_num_slices():
    s = SliceTopology.create("v5p", "4x4x4")
    with pytest.raises(TopologyError):
        mesh_shape_for(s, num_slices=0)


def test_mesh_shape():
    s = SliceTopology.create("v5p", "4x4x4")
    assert mesh_shape_for(s) == (1, 64)
    assert mesh_shape_for(s, num_slices=2, model_parallelism=16) == (8, 16)
    with pytest.raises(TopologyError):
        mesh_shape_for(s, model_parallelism=7)
    with pytest.raises(TopologyError):
        mesh_shape_for(s, model_parallelism=0)
    with pytest.raises(TopologyError):
        mesh_shape_for(s, model_parallelism=-4)
