"""Prefix-cache-aware gateway routing: scoring, ε-fallback, admission
shedding, connect-failure retry, lifecycle — all against dummy HTTP
backends (no jax), so the scheduler itself is what's under test."""

import json
import random
import threading
import time
import urllib.request
from http.server import ThreadingHTTPServer

import pytest

from kuberay_tpu.controlplane.store import ObjectStore
from kuberay_tpu.serve.gateway import GatewayConfig, WeightedGateway
from kuberay_tpu.serve.prefix import PrefixIndex, affinity_score, block_hashes
from kuberay_tpu.utils.httpjson import JsonHandler, serve_background
from kuberay_tpu.utils.metrics import MetricsRegistry


def make_route(store, weights, name="route"):
    store.create({
        "apiVersion": "tpu.dev/v1", "kind": "TrafficRoute",
        "metadata": {"name": name, "namespace": "default"},
        "spec": {"backends": [
            {"service": svc, "weight": w} for svc, w in weights.items()]},
        "status": {},
    })


def set_route(store, weights, name="route"):
    obj = store.get("TrafficRoute", name)
    obj["spec"]["backends"] = [
        {"service": svc, "weight": w} for svc, w in weights.items()]
    store.update(obj)


class DummyBackend:
    """Minimal serve stand-in: answers /v1/completions with its own name,
    optional latency, and optional load-report headers."""

    def __init__(self, name, delay=0.0, headers=None):
        self.name = name
        self.delay = delay
        self.extra_headers = dict(headers or {})
        self.hits = 0
        backend = self

        class Handler(JsonHandler):
            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                self.rfile.read(n)
                if backend.delay:
                    time.sleep(backend.delay)
                backend.hits += 1
                self._send(200, {"served_by": backend.name},
                           headers=backend.extra_headers)

        self.srv, self.url = serve_background(
            ThreadingHTTPServer(("127.0.0.1", 0), Handler),
            f"dummy-{name}")

    def close(self):
        self.srv.shutdown()


@pytest.fixture
def backends():
    made = []

    def make(name, **kw):
        b = DummyBackend(name, **kw)
        made.append(b)
        return b
    yield make
    for b in made:
        b.close()


def make_gateway(store, resolver, seed=0, **cfg):
    return WeightedGateway(
        store, "route", resolver=resolver, poll_interval=30.0,
        rng=random.Random(seed), config=GatewayConfig(**cfg))


def train(gw, service, prompt):
    """Teach the gateway that ``service`` holds ``prompt``'s prefix (what
    a successful forward does)."""
    with gw._lock:
        gw._states[service].index.insert(
            block_hashes(prompt, gw.config.block_size))


def set_queue_depth(gw, service, depth):
    with gw._lock:
        gw._states[service].queue_depth = depth


# ---------------------------------------------------------------------------
# scoring
# ---------------------------------------------------------------------------

BS = 16
PROMPT = list(range(1, 4 * BS + 1))          # 4 full blocks

# (name, trained-blocks on A, A queue, B queue, alpha, beta, expect)
SCORE_TABLE = [
    ("affinity wins on idle backends", 4, 0, 0, 4.0, 1.0, "a"),
    ("no affinity -> lower queue wins", 0, 5, 0, 4.0, 1.0, "b"),
    ("deep hit beats moderate queue", 3, 5, 0, 4.0, 1.0, "a"),
    ("queue eats the prefix saving", 2, 10, 0, 4.0, 1.0, "b"),
    ("beta scales the queue penalty", 3, 5, 0, 4.0, 3.0, "b"),
    ("alpha scales the hit reward", 2, 10, 0, 8.0, 1.0, "a"),
]


@pytest.mark.parametrize("name,ablk,aq,bq,alpha,beta,expect", SCORE_TABLE)
def test_score_tradeoff_table(name, ablk, aq, bq, alpha, beta, expect):
    store = ObjectStore()
    make_route(store, {"a": 50, "b": 50})
    with make_gateway(store, lambda s: f"http://{s}", epsilon=0.0,
                      alpha=alpha, beta=beta, block_size=BS) as gw:
        if ablk:
            train(gw, "a", PROMPT[:ablk * BS])
        set_queue_depth(gw, "a", aq)
        set_queue_depth(gw, "b", bq)
        assert gw.pick_backend(PROMPT) == f"http://{expect}", name


def test_score_function_is_the_documented_formula():
    assert affinity_score(3, 5, alpha=4.0, beta=1.0) == 3 * 4.0 - 5
    assert affinity_score(0, 2, alpha=4.0, beta=0.5) == -1.0


def test_partial_prefix_hit_depth_is_longest_prefix():
    idx = PrefixIndex()
    idx.insert(block_hashes(PROMPT[:2 * BS], BS))
    h = block_hashes(PROMPT, BS)
    assert idx.hit_depth(h) == 2
    # A diverging block breaks the chain even if later tokens re-align.
    other = PROMPT[:BS] + [999] * BS + PROMPT[2 * BS:]
    assert idx.hit_depth(block_hashes(other, BS)) == 1


def test_prefix_index_lru_bound():
    idx = PrefixIndex(capacity=3)
    a = block_hashes(list(range(2 * BS)), BS)          # 2 hashes
    b = block_hashes(list(range(100, 100 + 2 * BS)), BS)
    idx.insert(a)
    idx.insert(b)                                      # a[0] evicted
    assert len(idx) == 3
    assert idx.hit_depth(a) == 0                       # prefix chain broken
    assert idx.hit_depth(b) == 2


def test_prefix_index_partial_eviction_returns_surviving_depth():
    """Tail blocks evicted under capacity pressure: hit_depth must
    report the SURVIVING prefix depth — routing on a stale full-chain
    hit would send the request to a replica that re-prefills most of
    the prompt anyway."""
    idx = PrefixIndex(capacity=4)
    a = block_hashes(PROMPT, BS)                       # 4 hashes
    idx.insert(a)
    assert idx.hit_depth(a) == 4
    # A routing probe touches the chain HEAD (hot prefix), then two
    # fresh entries arrive: the LRU victims are a's tail blocks.
    idx.hit_depth(a[:2])
    idx.insert(block_hashes(list(range(500, 500 + 2 * BS)), BS))
    assert len(idx) == 4
    assert idx.hit_depth(a) == 2           # surviving prefix, never 4


def test_prefix_index_head_eviction_breaks_whole_chain():
    """Head block evicted while tail blocks remain resident: the chain
    walk must return 0 (membership of later blocks alone is unservable
    — match_prefix stops at the first allocator miss)."""
    idx = PrefixIndex(capacity=3)
    a = block_hashes(PROMPT, BS)           # 4 hashes -> a[0] evicted
    idx.insert(a)
    assert len(idx) == 3
    assert idx.hit_depth(a) == 0           # despite 3 resident members


def test_prefix_index_probed_prefix_survives_cold_churn():
    """A hot prefix that keeps being probed (routed to) stays resident
    through sustained cold-traffic churn — the probe's LRU touch is
    what makes affinity stable under capacity pressure."""
    idx = PrefixIndex(capacity=6)
    hot = block_hashes(PROMPT[:2 * BS], BS)
    idx.insert(hot)
    for i in range(20):
        assert idx.hit_depth(hot) == 2     # routing probe, every round
        cold = list(range(1000 + 64 * i, 1000 + 64 * i + 4 * BS))
        idx.insert(block_hashes(cold, BS))
        assert len(idx) <= 6
    assert idx.hit_depth(hot) == 2


# ---------------------------------------------------------------------------
# ε-fallback + TrafficRoute weight gating
# ---------------------------------------------------------------------------

def test_epsilon_one_is_pure_weighted_random():
    store = ObjectStore()
    make_route(store, {"a": 75, "b": 25})
    with make_gateway(store, lambda s: f"http://{s}", seed=7,
                      epsilon=1.0) as gw:
        # Deep affinity on b must be IGNORED on the ε path.
        train(gw, "b", PROMPT)
        picks = [gw.pick_backend(PROMPT) for _ in range(600)]
    frac_a = picks.count("http://a") / len(picks)
    assert 0.68 <= frac_a <= 0.82, frac_a


def test_epsilon_zero_routes_all_affine_traffic():
    store = ObjectStore()
    make_route(store, {"a": 50, "b": 50})
    with make_gateway(store, lambda s: f"http://{s}", epsilon=0.0) as gw:
        train(gw, "b", PROMPT)
        assert all(gw.pick_backend(PROMPT) == "http://b"
                   for _ in range(50))


def test_weight_shift_honored_mid_upgrade():
    """The rolling-upgrade traffic replay: the service controller steps
    TrafficRoute weights old->new while affine traffic keeps hitting the
    OLD cluster's prefix cache — weight 0 must still mean zero traffic,
    affinity notwithstanding (the upgrade gate is authoritative)."""
    store = ObjectStore()
    make_route(store, {"old": 100, "new": 0})
    with make_gateway(store, lambda s: f"http://{s}", seed=3,
                      epsilon=0.05) as gw:
        train(gw, "old", PROMPT)
        assert all(gw.pick_backend(PROMPT) == "http://old"
                   for _ in range(30))
        # Controller steps the canary; both eligible now — affinity may
        # prefer old, but new must be reachable on the ε path.
        set_route(store, {"old": 50, "new": 50})
        gw._refresh()
        picks = {gw.pick_backend(PROMPT) for _ in range(300)}
        assert picks == {"http://old", "http://new"}
        # Final step: old is weight-0.  The trained index on old must
        # not leak a single request past the gate.
        set_route(store, {"old": 0, "new": 100})
        gw._refresh()
        assert all(gw.pick_backend(PROMPT) == "http://new"
                   for _ in range(50))


def test_weight_shift_via_watch_thread(backends):
    """Same invariant end to end over HTTP, weights updated through the
    route-watch thread rather than a direct refresh."""
    old = backends("old")
    new = backends("new")
    urls = {"old": old.url, "new": new.url}
    store = ObjectStore()
    make_route(store, {"old": 100, "new": 0})
    gw = WeightedGateway(store, "route", resolver=lambda s: urls[s],
                         poll_interval=0.05, rng=random.Random(0))
    try:
        for _ in range(4):
            code, body = gw.forward("/v1/completions",
                                    json.dumps({"prompt_tokens": PROMPT})
                                    .encode())
            assert code == 200 and json.loads(body)["served_by"] == "old"
        set_route(store, {"old": 0, "new": 100})
        time.sleep(0.2)                                  # watch refresh
        for _ in range(4):
            code, body = gw.forward("/v1/completions",
                                    json.dumps({"prompt_tokens": PROMPT})
                                    .encode())
            assert code == 200 and json.loads(body)["served_by"] == "new"
        assert new.hits == 4
    finally:
        gw.stop()


# ---------------------------------------------------------------------------
# admission: bounded queue, deadline shedding, backpressure
# ---------------------------------------------------------------------------

def test_saturated_gateway_sheds_with_retry_after(backends):
    slow = backends("slow", delay=0.6)
    store = ObjectStore()
    make_route(store, {"slow": 100})
    reg = MetricsRegistry()
    gw = WeightedGateway(store, "route", resolver=lambda s: slow.url,
                         poll_interval=30.0, metrics=reg,
                         rng=random.Random(0),
                         config=GatewayConfig(max_inflight=1, max_queue=0,
                                              queue_timeout=5.0))
    try:
        results = []

        def go():
            results.append(gw.forward_ex(
                "/v1/completions", b'{"prompt_tokens": [1, 2]}'))
        t = threading.Thread(target=go)
        t.start()
        time.sleep(0.15)                    # first request is in flight
        code, payload, headers = gw.forward_ex(
            "/v1/completions", b'{"prompt_tokens": [3, 4]}')
        t.join()
        assert code == 429
        assert "Retry-After" in headers
        assert b"overloaded" in payload
        assert results[0][0] == 200         # in-flight request unaffected
        text = reg.render()
        assert 'tpu_gateway_shed_total{reason="queue_full"} 1.0' in text
        assert ('tpu_gateway_requests_total{backend="none",code="429"} 1.0'
                in text)
    finally:
        gw.stop()


def test_queued_request_sheds_on_deadline(backends):
    slow = backends("slow", delay=1.0)
    store = ObjectStore()
    make_route(store, {"slow": 100})
    reg = MetricsRegistry()
    gw = WeightedGateway(store, "route", resolver=lambda s: slow.url,
                         poll_interval=30.0, metrics=reg,
                         rng=random.Random(0),
                         config=GatewayConfig(max_inflight=1, max_queue=8,
                                              queue_timeout=0.2))
    try:
        t = threading.Thread(target=gw.forward, args=(
            "/v1/completions", b'{"prompt_tokens": [1]}'))
        t.start()
        time.sleep(0.15)
        t0 = time.monotonic()
        code, _, headers = gw.forward_ex("/v1/completions",
                                         b'{"prompt_tokens": [2]}')
        waited = time.monotonic() - t0
        t.join()
        assert code == 429
        assert waited < 0.8                 # shed at the deadline, not 1s+
        assert "Retry-After" in headers
        assert ('tpu_gateway_shed_total{reason="deadline"} 1.0'
                in reg.render())
    finally:
        gw.stop()


def test_queued_request_proceeds_when_slot_frees(backends):
    quick = backends("quick", delay=0.15)
    store = ObjectStore()
    make_route(store, {"quick": 100})
    gw = WeightedGateway(store, "route", resolver=lambda s: quick.url,
                         poll_interval=30.0, rng=random.Random(0),
                         config=GatewayConfig(max_inflight=1, max_queue=8,
                                              queue_timeout=5.0))
    try:
        t = threading.Thread(target=gw.forward, args=(
            "/v1/completions", b'{"prompt_tokens": [1]}'))
        t.start()
        time.sleep(0.05)
        code, body = gw.forward("/v1/completions",
                                b'{"prompt_tokens": [2]}')
        t.join()
        assert code == 200                  # waited for the slot, no shed
        assert quick.hits == 2
    finally:
        gw.stop()


def test_header_feedback_updates_routing_state(backends):
    loaded = backends("loaded", headers={"X-TPU-Queue-Depth": "7",
                                         "X-TPU-KV-Free-Blocks": "3",
                                         "X-TPU-KV-Total-Blocks": "12"})
    store = ObjectStore()
    make_route(store, {"loaded": 100})
    gw = WeightedGateway(store, "route", resolver=lambda s: loaded.url,
                         poll_interval=30.0, rng=random.Random(0))
    try:
        code, _ = gw.forward("/v1/completions", b'{"prompt_tokens": [1]}')
        assert code == 200
        (state,) = gw.backend_stats()
        assert state["queue_depth"] == 7
        assert state["kv_occupancy"] == 0.75
        assert gw.total_queue_depth() == 7
    finally:
        gw.stop()


# ---------------------------------------------------------------------------
# retry on connect failure
# ---------------------------------------------------------------------------

def test_connect_failure_retries_next_best_excluding_dead(backends):
    live = backends("live")
    urls = {"dead": "http://127.0.0.1:1", "live": live.url}
    store = ObjectStore()
    make_route(store, {"dead": 50, "live": 50})
    reg = MetricsRegistry()
    gw = WeightedGateway(store, "route", resolver=lambda s: urls[s],
                         poll_interval=30.0, metrics=reg,
                         rng=random.Random(0),
                         config=GatewayConfig(epsilon=0.0))
    try:
        # Affinity pins the pick to the DEAD backend; the retry must land
        # on the live one with the dead one excluded.
        train(gw, "dead", PROMPT)
        code, body = gw.forward(
            "/v1/completions",
            json.dumps({"prompt_tokens": PROMPT}).encode())
        assert code == 200
        assert json.loads(body)["served_by"] == "live"
        assert ('tpu_gateway_requests_total{backend="live",code="200"} 1.0'
                in reg.render())
    finally:
        gw.stop()


def test_all_backends_dead_is_502():
    store = ObjectStore()
    make_route(store, {"d1": 50, "d2": 50})
    gw = WeightedGateway(store, "route",
                         resolver=lambda s: "http://127.0.0.1:1",
                         poll_interval=30.0, rng=random.Random(0))
    try:
        code, body = gw.forward("/v1/completions",
                                b'{"prompt_tokens": [1]}')
        assert code == 502
        assert b"backend error" in body
    finally:
        gw.stop()


def test_successful_forward_trains_affinity(backends):
    a = backends("a")
    b = backends("b")
    urls = {"a": a.url, "b": b.url}
    store = ObjectStore()
    make_route(store, {"a": 50, "b": 50})
    reg = MetricsRegistry()
    gw = WeightedGateway(store, "route", resolver=lambda s: urls[s],
                         poll_interval=30.0, metrics=reg,
                         rng=random.Random(0),
                         config=GatewayConfig(epsilon=0.0))
    try:
        body = json.dumps({"prompt_tokens": PROMPT}).encode()
        gw.forward("/v1/completions", body)
        first = next(s for s in gw.backend_stats() if s["picks"] == 1)
        assert first["prefix_index_size"] == 4      # learned the prompt
        # Every later same-prefix request sticks to the learned backend.
        for _ in range(5):
            gw.forward("/v1/completions", body)
        assert a.hits + b.hits == 6
        assert max(a.hits, b.hits) == 6             # all on one replica
        text = reg.render()
        assert ("tpu_gateway_prefix_cache_hits_total{backend=\""
                + ("a" if a.hits else "b") + "\"} 5.0") in text
    finally:
        gw.stop()


# ---------------------------------------------------------------------------
# lifecycle / determinism
# ---------------------------------------------------------------------------

def test_stop_joins_route_watch_thread():
    store = ObjectStore()
    make_route(store, {"a": 100})
    gw = WeightedGateway(store, "route", resolver=lambda s: f"http://{s}",
                         poll_interval=0.01)
    assert gw._watch_thread.is_alive()
    gw.stop()
    assert not gw._watch_thread.is_alive()
    gw.stop()                              # idempotent


def test_context_manager_stops():
    store = ObjectStore()
    make_route(store, {"a": 100})
    with WeightedGateway(store, "route",
                         resolver=lambda s: f"http://{s}",
                         poll_interval=0.01) as gw:
        thread = gw._watch_thread
        assert thread.is_alive()
    assert not thread.is_alive()


def test_injected_rng_makes_picks_reproducible():
    store = ObjectStore()
    make_route(store, {"a": 60, "b": 40})

    def run(seed):
        with make_gateway(store, lambda s: f"http://{s}", seed=seed,
                          epsilon=1.0) as gw:
            return [gw.pick_backend() for _ in range(64)]
    assert run(5) == run(5)
    assert run(5) != run(6)


# ---------------------------------------------------------------------------
# disaggregated two-hop scheduling (prefill tier -> KV transfer -> decode)
# ---------------------------------------------------------------------------

def make_tier_route(store, tiers, name="route"):
    store.create({
        "apiVersion": "tpu.dev/v1", "kind": "TrafficRoute",
        "metadata": {"name": name, "namespace": "default"},
        "spec": {"backends": [
            {"service": svc, "weight": 1, "tier": t}
            for svc, t in tiers.items()]},
        "status": {},
    })


class TierBackend:
    """Jax-free disaggregated serve stand-in: a completions endpoint plus
    the KV-transfer protocol surface (/v1/kv/resident|export|import),
    recording every call so tests can assert the two-hop wire order."""

    def __init__(self, name, resident_blocks=0, block_size=BS):
        self.name = name
        self.resident_blocks = resident_blocks
        self.block_size = block_size
        self.calls = []                   # (path, body-dict), arrival order
        backend = self

        class Handler(JsonHandler):
            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                doc = json.loads(self.rfile.read(n) or b"{}")
                backend.calls.append((self.path, doc))
                if self.path == "/v1/kv/resident":
                    return self._send(
                        200, {"resident_blocks": backend.resident_blocks})
                if self.path == "/v1/kv/export":
                    total = len(doc["prompt_tokens"]) // backend.block_size
                    skip = int(doc.get("skip_blocks", 0))
                    blocks = [{"index": i, "hash": i + 1, "k": "", "v": ""}
                              for i in range(skip, total)]
                    return self._send(200, {"blocks": blocks})
                if self.path == "/v1/kv/import":
                    pre = backend.resident_blocks
                    blocks = doc.get("blocks", [])
                    backend.resident_blocks = pre + len(blocks)
                    return self._send(200, {"imported": len(blocks),
                                            "skipped": pre})
                mt = int(doc.get("max_tokens", 8))
                return self._send(200, {"tokens": [7000 + i
                                                   for i in range(mt)],
                                        "served_by": backend.name})

        self.srv, self.url = serve_background(
            ThreadingHTTPServer(("127.0.0.1", 0), Handler), f"tier-{name}")

    def kv_paths(self):
        return [p for p, _ in self.calls if p.startswith("/v1/kv")]

    def close(self):
        self.srv.shutdown()


@pytest.fixture
def tier_fleet():
    pf, de = TierBackend("pf"), TierBackend("de")
    store = ObjectStore()
    make_tier_route(store, {"pf": "prefill", "de": "decode"})
    urls = {"pf": pf.url, "de": de.url}
    metrics = MetricsRegistry()
    gw = WeightedGateway(store, "route", resolver=lambda s: urls[s],
                         poll_interval=30.0, metrics=metrics,
                         rng=random.Random(0),
                         config=GatewayConfig(epsilon=0.0, block_size=BS))
    yield gw, pf, de, metrics
    gw.stop()
    pf.close()
    de.close()


def test_two_hop_prefill_decode_splice(tier_fleet):
    gw, pf, de, metrics = tier_fleet
    body = json.dumps({"prompt_tokens": PROMPT, "max_tokens": 6}).encode()
    code, payload = gw.forward("/v1/completions", body)
    doc = json.loads(payload)
    assert code == 200
    assert len(doc["tokens"]) == 6          # tok0 + 5 decode tokens
    assert doc["disagg"]["prefill"] == "pf"
    assert doc["disagg"]["decode"] == "de"
    assert doc["disagg"]["kv_sent"] == 4
    assert doc["disagg"]["kv_skipped"] == 0
    # The gateway's own hop-1 wall rides next to the engine-measured
    # ttft_ms (the merged TTFT stays comparable with colocated fleets).
    assert doc["disagg"]["prefill_hop_ms"] >= 0
    assert doc["ttft_ms"] >= 0
    # Hop 1 asked the prefill tier for exactly one token; hop 2 seeded
    # the decode tier with prompt + that token and the remaining budget.
    pf_gen = next(d for p, d in pf.calls if p.endswith("completions"))
    de_gen = next(d for p, d in de.calls if p.endswith("completions"))
    assert pf_gen["max_tokens"] == 1
    assert de_gen["max_tokens"] == 5
    assert de_gen["prompt_tokens"] == PROMPT + doc["tokens"][:1]
    # KV wire order: probe the decode replica, export the delta from
    # prefill, import into decode.
    assert de.kv_paths() == ["/v1/kv/resident", "/v1/kv/import"]
    assert pf.kv_paths() == ["/v1/kv/export"]
    text = metrics.render()
    assert 'tpu_serve_kv_transfer_blocks_total{outcome="sent"} 4.0' in text
    # Per-hop latency lands in per-tier phases (the per-tier SLO input).
    assert 'phase="gateway-prefill"' in text
    assert 'phase="gateway-decode"' in text


def test_two_hop_delta_only_skips_resident_blocks(tier_fleet):
    gw, pf, de, metrics = tier_fleet
    body = json.dumps({"prompt_tokens": PROMPT, "max_tokens": 4}).encode()
    assert gw.forward("/v1/completions", body)[0] == 200
    code, payload = gw.forward("/v1/completions", body)
    doc = json.loads(payload)
    assert code == 200
    # Second pass: every block already resident on the decode replica —
    # the probe short-circuits, nothing is exported or re-imported.
    assert doc["disagg"]["kv_sent"] == 0
    assert doc["disagg"]["kv_skipped"] == 4
    assert pf.kv_paths() == ["/v1/kv/export"]                # first pass only
    assert de.kv_paths() == ["/v1/kv/resident"] * 2 + ["/v1/kv/import"] \
        or de.kv_paths() == ["/v1/kv/resident", "/v1/kv/import",
                             "/v1/kv/resident"]
    text = metrics.render()
    assert 'tpu_serve_kv_transfer_blocks_total{outcome="sent"} 4.0' in text
    assert ('tpu_serve_kv_transfer_blocks_total{outcome="skipped"} 4.0'
            in text)


def test_two_hop_single_token_skips_decode_hop(tier_fleet):
    gw, pf, de, _ = tier_fleet
    body = json.dumps({"prompt_tokens": PROMPT, "max_tokens": 1}).encode()
    code, payload = gw.forward("/v1/completions", body)
    doc = json.loads(payload)
    assert code == 200 and len(doc["tokens"]) == 1
    assert doc["disagg"]["decode"] is None
    assert de.calls == []                   # decode tier never touched


def test_two_hop_promptless_falls_back_single_hop(tier_fleet):
    gw, pf, de, _ = tier_fleet
    code, payload = gw.forward("/v1/completions",
                               json.dumps({"max_tokens": 3}).encode())
    doc = json.loads(payload)
    assert code == 200 and "disagg" not in doc
    assert pf.kv_paths() == [] and de.kv_paths() == []


def test_mixed_route_never_two_hops(backends):
    a = backends("a")
    store = ObjectStore()
    make_route(store, {"a": 100})
    with make_gateway(store, lambda s: a.url, epsilon=0.0,
                      block_size=BS) as gw:
        body = json.dumps({"prompt_tokens": PROMPT,
                           "max_tokens": 4}).encode()
        code, payload = gw.forward("/v1/completions", body)
    assert code == 200
    assert json.loads(payload) == {"served_by": "a"}
    assert a.hits == 1


def test_two_hop_trace_tree_is_connected(tier_fleet):
    from kuberay_tpu.obs.trace import Tracer, span_tree
    gw, pf, de, _ = tier_fleet
    tracer = Tracer()
    gw.tracer = tracer
    body = json.dumps({"prompt_tokens": PROMPT, "max_tokens": 6}).encode()
    code, payload, headers = gw.forward_ex("/v1/completions", body)
    assert code == 200
    tid = headers["traceparent"].split("-")[1]
    mine = [s for s in tracer.export() if s["trace_id"] == tid]
    roots = span_tree(mine)
    assert len(roots) == 1                  # ONE connected tree
    assert roots[0]["name"] == "serve-request"
    names = [c["name"] for c in roots[0]["children"]]
    for want in ("prefill-forward", "kv-transfer", "decode-forward"):
        assert want in names, names
    kv = next(c for c in roots[0]["children"] if c["name"] == "kv-transfer")
    assert kv["attrs"]["blocks_sent"] == 4
    assert kv["attrs"]["src"] == "pf" and kv["attrs"]["dst"] == "de"
    # The transfer happens between the two forwards.
    pf_span = next(c for c in roots[0]["children"]
                   if c["name"] == "prefill-forward")
    de_span = next(c for c in roots[0]["children"]
                   if c["name"] == "decode-forward")
    assert pf_span["end"] <= kv["start"] <= de_span["start"]


def test_tier_queue_depth_is_per_tier(tier_fleet):
    gw, pf, de, _ = tier_fleet
    with gw._lock:
        gw._states["pf"].queue_depth = 3
        gw._states["de"].queue_depth = 5
        gw._states["de"].inflight = 1
    assert gw.tier_queue_depth("prefill") == 3
    assert gw.tier_queue_depth("decode") == 6
    assert gw.total_queue_depth() == 9


def test_backend_stats_reports_tier(tier_fleet):
    gw, pf, de, _ = tier_fleet
    tiers = {b["service"]: b["tier"] for b in gw.backend_stats()}
    assert tiers == {"pf": "prefill", "de": "decode"}


def test_export_request_carries_kv_max_blocks():
    pf, de = TierBackend("pf"), TierBackend("de", resident_blocks=1)
    store = ObjectStore()
    make_tier_route(store, {"pf": "prefill", "de": "decode"})
    urls = {"pf": pf.url, "de": de.url}
    gw = WeightedGateway(store, "route", resolver=lambda s: urls[s],
                         poll_interval=30.0, rng=random.Random(0),
                         config=GatewayConfig(epsilon=0.0, block_size=BS,
                                              kv_max_blocks=2))
    try:
        body = json.dumps({"prompt_tokens": PROMPT,
                           "max_tokens": 4}).encode()
        code, _ = gw.forward("/v1/completions", body)
        assert code == 200
        # The budget travels with the export request (the exporter
        # truncates server-side so the capped pages never hit the wire).
        export = next(d for p, d in pf.calls if p == "/v1/kv/export")
        assert export["skip_blocks"] == 1
        assert export["max_blocks"] == 2
    finally:
        gw.stop()
        pf.close()
        de.close()


@pytest.mark.parametrize("prefill_beta,expect", [(None, "pa"), (8.0, "pb")])
def test_prefill_beta_spreads_bursts_off_the_affine_replica(
        prefill_beta, expect):
    # pa holds the whole prompt's prefix (hit depth 4, score 4*4=16)
    # but reports a queue of 5.  The default load weight (beta=1) keeps
    # the burst home (16 - 5 > 0); prefill_beta=8 makes the idle peer
    # win (16 - 40 < 0) — the prefill tier trades a cheap preamble
    # re-prefill for not convoying.
    pa, pb, de = TierBackend("pa"), TierBackend("pb"), TierBackend("de")
    store = ObjectStore()
    make_tier_route(store, {"pa": "prefill", "pb": "prefill",
                            "de": "decode"})
    urls = {"pa": pa.url, "pb": pb.url, "de": de.url}
    gw = WeightedGateway(store, "route", resolver=lambda s: urls[s],
                         poll_interval=30.0, rng=random.Random(0),
                         config=GatewayConfig(epsilon=0.0, block_size=BS,
                                              prefill_beta=prefill_beta))
    try:
        with gw._lock:
            gw._states["pa"].index.insert(block_hashes(PROMPT, BS))
            gw._states["pa"].queue_depth = 5
        body = json.dumps({"prompt_tokens": PROMPT,
                           "max_tokens": 2}).encode()
        code, payload = gw.forward("/v1/completions", body)
        assert code == 200
        assert json.loads(payload)["disagg"]["prefill"] == expect
    finally:
        gw.stop()
        pa.close()
        pb.close()
        de.close()


# ---------------------------------------------------------------------------
# upgrade handshakes: route-deletion fallback, prefix pre-warm replay,
# session-drain ack, and the per-backend attempt series the upgrade
# BurnRateGate reads (docs/upgrades.md)
# ---------------------------------------------------------------------------

def set_route_backends(store, backend_list, name="route"):
    obj = store.get("TrafficRoute", name)
    obj["spec"]["backends"] = backend_list
    store.update(obj)


def test_route_deletion_collapses_onto_survivor(backends):
    """Promotion deletes the route; the gateway must fall back to the
    highest-weight backend it last saw at weight 100 — no window with
    stale weights or zero backends."""
    a = backends("a")
    b = backends("b")
    urls = {"a": a.url, "b": b.url}
    store = ObjectStore()
    make_route(store, {"a": 70, "b": 30})
    gw = WeightedGateway(store, "route", resolver=lambda s: urls[s],
                         poll_interval=30.0, rng=random.Random(0),
                         config=GatewayConfig(epsilon=1.0))
    try:
        gw._refresh()
        store.delete("TrafficRoute", "route")
        gw._refresh()
        with gw._lock:
            weights = {s: st.weight for s, st in gw._states.items()}
        assert weights == {"a": 100, "b": 0}
        for _ in range(6):
            code, body = gw.forward(
                "/v1/completions",
                json.dumps({"prompt_tokens": [1, 2]}).encode())
            assert code == 200
            assert json.loads(body)["served_by"] == "a"
        assert b.hits == 0
    finally:
        gw.stop()


def test_prewarm_replays_hottest_prefixes_once_and_acks(backends):
    blue = backends("blue")
    green = backends("green")
    urls = {"blue": blue.url, "green": green.url}
    store = ObjectStore()
    make_route(store, {"blue": 100})
    reg = MetricsRegistry()
    gw = WeightedGateway(store, "route", resolver=lambda s: urls[s],
                         poll_interval=30.0, metrics=reg,
                         rng=random.Random(0),
                         config=GatewayConfig(block_size=BS))
    try:
        # Live blue traffic teaches the hot-prompt tracker two distinct
        # block-aligned prefixes.
        for _ in range(3):
            gw.forward("/v1/completions",
                       json.dumps({"prompt_tokens": PROMPT}).encode())
        gw.forward("/v1/completions",
                   json.dumps({"prompt_tokens": PROMPT[:2 * BS]}).encode())
        # The controller flags green for pre-warm while it carries no
        # weight yet.
        set_route_backends(store, [
            {"service": "blue", "weight": 100},
            {"service": "green", "weight": 0, "prewarm": 2}])
        before = green.hits
        gw._refresh()
        assert green.hits == before + 2        # one prefill per prefix
        route = store.get("TrafficRoute", "route")
        assert route["status"]["prewarmed"]["green"] == 2
        gw._refresh()                          # ack is idempotent
        assert green.hits == before + 2
        assert ('tpu_upgrade_prewarm_prompts_total{backend="green"} 2.0'
                in reg.render())
    finally:
        gw.stop()


def test_drain_acks_only_when_inflight_reaches_zero(backends):
    a = backends("a")
    b = backends("b")
    urls = {"a": a.url, "b": b.url}
    store = ObjectStore()
    make_route(store, {"a": 50, "b": 50})
    reg = MetricsRegistry()
    gw = WeightedGateway(store, "route", resolver=lambda s: urls[s],
                         poll_interval=30.0, metrics=reg,
                         rng=random.Random(0))
    try:
        gw._refresh()
        # Terminal ramp weights: green (b) at 100, blue (a) draining.
        set_route_backends(store, [
            {"service": "a", "weight": 0, "drain": True},
            {"service": "b", "weight": 100}])
        with gw._lock:
            gw._states["a"].inflight = 1       # admitted work still running
        gw._refresh()
        status = store.get("TrafficRoute", "route").get("status") or {}
        assert "a" not in (status.get("drained") or {})
        with gw._lock:
            gw._states["a"].inflight = 0
        gw._refresh()
        status = store.get("TrafficRoute", "route")["status"]
        assert status["drained"]["a"] is True
        assert "tpu_upgrade_drain_seconds_count" in reg.render()
    finally:
        gw.stop()


def test_backend_attempt_series_record_connect_failures(backends):
    """The BurnRateGate's availability signal: a dead green backend
    lands attempt + error on its OWN series even though failover keeps
    every client response a 200."""
    live = backends("live")
    urls = {"live": live.url, "green": "http://127.0.0.1:1"}
    store = ObjectStore()
    make_route(store, {"live": 50, "green": 50})
    reg = MetricsRegistry()
    gw = WeightedGateway(store, "route", resolver=lambda s: urls[s],
                         poll_interval=30.0, metrics=reg,
                         rng=random.Random(0),
                         config=GatewayConfig(epsilon=1.0))
    try:
        for _ in range(8):
            code, _ = gw.forward(
                "/v1/completions",
                json.dumps({"prompt_tokens": [1, 2]}).encode())
            assert code == 200                 # failover keeps users whole
        attempts = {lbl["backend"]: v for lbl, v in
                    reg.family_snapshot("tpu_gateway_backend_attempts_total")}
        errors = {lbl["backend"]: v for lbl, v in
                  reg.family_snapshot("tpu_gateway_backend_errors_total")}
        assert attempts.get("green", 0) > 0    # the gate's raw signal
        assert errors.get("green") == attempts["green"]
        assert "live" not in errors            # the survivor stays clean
        # Connect failures never reach the latency histogram: the gate's
        # TTFT signal only sees real responses.
        text = reg.render()
        assert ('tpu_gateway_backend_latency_seconds_count'
                '{backend="green"}') not in text
        assert ('tpu_gateway_backend_latency_seconds_count'
                '{backend="live"}') in text
    finally:
        gw.stop()
