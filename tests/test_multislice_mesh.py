"""Hybrid ICI/DCN mesh for multi-slice training (MeshSpec.build_multislice).

The scaling-book layout: data parallelism crosses slices on DCN; fsdp/tp/
sp/ep collectives stay within a slice on ICI.  On the 8-device virtual
CPU mesh, "slices" are contiguous device groups (the ordering the
operator's TPU_WORKER_ID contract guarantees).
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from kuberay_tpu.parallel.mesh import MeshSpec


def device_slice(mesh, num_slices):
    """Map each mesh coordinate to the contiguous slice group its device
    belongs to (device order = slice order on the virtual mesh)."""
    ids = np.vectorize(lambda d: d.id)(mesh.devices)
    per = len(jax.devices()) // num_slices
    return ids // per


def test_dp_crosses_slices_everything_else_within():
    mesh = MeshSpec(dp=2, fsdp=2, tp=2).build_multislice(num_slices=2)
    assert mesh.devices.shape == (2, 1, 2, 2, 1, 1)
    groups = device_slice(mesh, 2)
    # Fixing dp and varying fsdp/tp must stay inside one slice...
    assert np.all(groups[0] == groups[0].flat[0])
    assert np.all(groups[1] == groups[1].flat[0])
    # ...and the dp axis is exactly the cross-slice direction.
    assert groups[0].flat[0] != groups[1].flat[0]


def test_multi_axis_dcn():
    mesh = MeshSpec(dp=2, pp=2, fsdp=2).build_multislice(
        num_slices=4, dcn_axes=("dp", "pp"))
    groups = device_slice(mesh, 4)
    # Each (dp, pp) coordinate pins one slice; fsdp varies within it.
    for i in range(2):
        for j in range(2):
            g = groups[i, j]
            assert np.all(g == g.flat[0])


def test_platform_detected_slices_must_be_equal_sized():
    """Uneven per-slice device counts would silently straddle ICI axes
    across DCN; the builder must refuse."""
    class FakeDev:
        def __init__(self, i, s):
            self.id, self.slice_index = i, s
    devs = [FakeDev(i, 0) for i in range(3)] + \
        [FakeDev(i + 3, 1) for i in range(5)]
    with pytest.raises(ValueError, match="unequal device counts"):
        MeshSpec(dp=2, fsdp=-1).build_multislice(devs)


def test_dcn_size_must_match_slices():
    with pytest.raises(ValueError, match="must exactly cover"):
        MeshSpec(dp=2, fsdp=-1).build_multislice(num_slices=4)
    with pytest.raises(ValueError, match="num_slices required"):
        MeshSpec(dp=2, fsdp=-1).build_multislice()


def test_train_step_over_hybrid_mesh():
    from kuberay_tpu.models import llama
    from kuberay_tpu.train.train_step import TrainConfig, make_sharded_train_fns
    mesh = MeshSpec(dp=2, fsdp=2, tp=2).build_multislice(num_slices=2)
    cfg = llama.CONFIGS["llama_tiny"]
    init, step, _ = make_sharded_train_fns(
        cfg, TrainConfig(warmup_steps=2, decay_steps=10), mesh)
    state = init(jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 16),
                                0, cfg.vocab_size)
    state, metrics = step(state, {"tokens": tokens,
                                  "targets": jnp.roll(tokens, -1, axis=1)})
    assert bool(jnp.isfinite(jnp.asarray(metrics["total_loss"])))


@pytest.mark.timeout(300)
def test_two_slice_launcher_end_to_end():
    """Production-shaped multislice: two processes (one per slice) run the
    REAL launcher under the operator's MEGASCALE env contract — real
    jax.distributed bootstrap, hybrid dp-over-DCN mesh, two train steps."""
    import os
    import subprocess
    import sys

    def spawn(slice_id):
        env = dict(os.environ)
        env.update({
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
            "TPU_WORKER_HOSTNAMES": "localhost",
            "TPU_NUM_PROCESSES": "1",
            "TPU_WORKER_ID": "0",
            "MEGASCALE_NUM_SLICES": "2",
            "MEGASCALE_SLICE_ID": str(slice_id),
        })
        return subprocess.Popen(
            [sys.executable, "-m", "kuberay_tpu.train.launcher", "--model",
             "llama_tiny", "--steps", "2", "--batch", "4",
             "--seq-len", "16", "--tp", "1"],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True)

    procs = [spawn(0), spawn(1)]
    outs = [p.communicate(timeout=280)[0] for p in procs]
    for p, out in zip(procs, outs):
        assert p.returncode == 0, out[-2000:]


def test_launcher_env_contract_builds_hybrid_mesh(monkeypatch):
    from kuberay_tpu.train.launcher import build_mesh
    monkeypatch.setenv("MEGASCALE_NUM_SLICES", "2")
    mesh = build_mesh(tp=2)
    assert dict(zip(mesh.axis_names, mesh.devices.shape))["dp"] == 2
    groups = device_slice(mesh, 2)
    assert groups[0].flat[0] != groups[1].flat[0]
