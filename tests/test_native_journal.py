"""Native journal engine (native/journal.cpp + bindings): frame
roundtrip on both engines, torn-tail recovery, cross-engine replay,
legacy text-journal migration, and store integration."""

import json
import os
import struct
import zlib

import pytest

from kuberay_tpu.controlplane.store import ObjectStore
from kuberay_tpu.native import journal as J

ENGINES = ["python"] + (["native"] if J.native_available() else [])


@pytest.mark.parametrize("engine", ENGINES)
def test_roundtrip(tmp_path, engine):
    path = str(tmp_path / "j.bin")
    j = J.open_journal(path, engine)
    payloads = [b"alpha", b"b" * 10_000, json.dumps({"op": "x"}).encode()]
    for p in payloads:
        j.append(p)
    j.flush()
    j.close()
    assert list(J.replay(path, engine)) == payloads


@pytest.mark.parametrize("writer", ENGINES)
@pytest.mark.parametrize("reader", ENGINES)
def test_cross_engine_replay(tmp_path, writer, reader):
    """Both engines share one file format."""
    path = str(tmp_path / "x.bin")
    j = J.open_journal(path, writer)
    j.append(b"shared-format")
    j.flush()
    j.close()
    assert list(J.replay(path, reader)) == [b"shared-format"]


@pytest.mark.parametrize("engine", ENGINES)
def test_torn_tail_stops_replay(tmp_path, engine):
    path = str(tmp_path / "torn.bin")
    j = J.open_journal(path, engine)
    j.append(b"good-1")
    j.append(b"good-2")
    j.flush()
    j.close()
    good_len = os.path.getsize(path)
    with open(path, "ab") as f:           # crash mid-frame
        f.write(struct.pack("<II", 100, 0) + b"only-part")
    assert list(J.replay(path, engine)) == [b"good-1", b"good-2"]
    assert J.valid_prefix_len(path) == good_len
    # Corrupt CRC also stops replay at the corruption point.
    with open(path, "r+b") as f:
        f.truncate(good_len)
        f.seek(4)                          # first frame's crc field
        f.write(b"\xde\xad\xbe\xef")
    assert list(J.replay(path, engine)) == []


def test_store_truncates_torn_tail_and_continues(tmp_path):
    path = str(tmp_path / "s.journal")
    s1 = ObjectStore(journal_path=path)
    s1.create({"apiVersion": "v1", "kind": "Pod",
               "metadata": {"name": "p1", "namespace": "default"}})
    s1.flush_journal()
    with open(path, "ab") as f:            # crash mid-frame
        f.write(b"\xff\xff\xff\x7f GARBAGE")
    s2 = ObjectStore(journal_path=path)
    assert s2.get("Pod", "p1") is not None
    # New writes after the truncation are replayable.
    s2.create({"apiVersion": "v1", "kind": "Pod",
               "metadata": {"name": "p2", "namespace": "default"}})
    s2.flush_journal()
    s3 = ObjectStore(journal_path=path)
    assert {o["metadata"]["name"] for o in s3.list("Pod")} == {"p1", "p2"}


def test_legacy_text_journal_migrates(tmp_path):
    """Round-1 journals were JSON text lines; opening one replays it and
    rewrites it as a framed snapshot."""
    path = str(tmp_path / "legacy.journal")
    with open(path, "w") as f:
        f.write(json.dumps({"op": "put", "obj": {
            "apiVersion": "v1", "kind": "Pod",
            "metadata": {"name": "old", "namespace": "default",
                         "resourceVersion": 7}}}) + "\n")
        f.write(json.dumps({"op": "del", "key": ["Pod", "default",
                                                 "gone"]}) + "\n")
    s = ObjectStore(journal_path=path)
    assert s.get("Pod", "old")["metadata"]["resourceVersion"] == 7
    s.flush_journal()
    # File is now framed: binary replay sees the snapshot.
    entries = [json.loads(p) for p in J.replay(path)]
    assert entries[0]["op"] == "snapshot"
    # And a reopen still works.
    s2 = ObjectStore(journal_path=path)
    assert s2.get("Pod", "old") is not None


@pytest.mark.skipif(not J.native_available(), reason="no C++ toolchain")
def test_native_flush_is_durable_against_kill(tmp_path):
    """flush() means ON DISK: a SIGKILL'd writer's flushed records
    survive (the round-1 text journal lost these on machine crash; this
    asserts the process-kill half, which buffering alone would also
    lose)."""
    import subprocess
    import sys

    path = str(tmp_path / "kill.bin")
    code = f"""
import os, signal
from kuberay_tpu.native.journal import open_journal
j = open_journal({path!r}, "native")
for i in range(100):
    j.append(f"rec-{{i}}".encode())
j.flush()
print("flushed", flush=True)
os.kill(os.getpid(), signal.SIGKILL)
"""
    out = subprocess.run([sys.executable, "-c", code],
                         capture_output=True, text=True, timeout=60)
    assert "flushed" in out.stdout
    assert out.returncode == -9
    recs = list(J.replay(path))
    assert len(recs) == 100 and recs[-1] == b"rec-99"


def test_store_compaction_on_engine(tmp_path):
    path = str(tmp_path / "c.journal")
    s1 = ObjectStore(journal_path=path, journal_compact_bytes=20_000)
    for i in range(200):
        s1.create({"apiVersion": "v1", "kind": "Pod",
                   "metadata": {"name": f"p{i}", "namespace": "default",
                                "labels": {"tpu.dev/cluster": "c"}}})
    for i in range(150):
        s1.delete("Pod", f"p{i}")
    s1.flush_journal()
    s2 = ObjectStore(journal_path=path)
    assert len(s2.list("Pod")) == 50
    assert len(s2.list("Pod", labels={"tpu.dev/cluster": "c"})) == 50


@pytest.mark.parametrize("engine", ENGINES)
def test_store_acked_create_survives_sigkill(tmp_path, engine):
    """A create() that RETURNED must be on disk — no explicit flush by
    the caller (the public-mutator ack barrier), even if the process is
    SIGKILL'd immediately after."""
    import subprocess
    import sys

    path = str(tmp_path / "ack.journal")
    code = f"""
import os, signal
from kuberay_tpu.controlplane.store import ObjectStore
s = ObjectStore(journal_path={path!r}, journal_engine={engine!r})
s.create({{"apiVersion": "v1", "kind": "Pod",
          "metadata": {{"name": "acked", "namespace": "default"}}}})
print("acked", flush=True)
os.kill(os.getpid(), signal.SIGKILL)
"""
    out = subprocess.run([sys.executable, "-c", code],
                         capture_output=True, text=True, timeout=60)
    assert "acked" in out.stdout and out.returncode == -9
    s2 = ObjectStore(journal_path=path, journal_engine=engine)
    assert s2.get("Pod", "acked") is not None
