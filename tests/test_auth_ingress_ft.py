"""Token auth, ingress builder, and the coordinator-state cleanup
(GCS-FT deletion) path — the remaining reference feature-area tests
(ref raycluster_auth_test.go, common/ingress.go, the Redis cleanup Job
finalizer path at raycluster_controller.go:193-326)."""

import pytest

from kuberay_tpu.api.tpucluster import HeadStateOptions
from kuberay_tpu.builders.auth import ENV_AUTH_TOKEN, auth_secret_name
from kuberay_tpu.builders.ingress import build_head_ingress, build_head_route
from kuberay_tpu.runtime.coordinator_client import CoordinatorClient, CoordinatorError
from kuberay_tpu.runtime.coordinator_server import CoordinatorServer, MemoryBackend
from kuberay_tpu.utils import constants as C
from tests.test_api_types import make_cluster
from tests.test_cluster_controller import Harness


def test_auth_secret_and_env_wiring():
    h = Harness()
    c = make_cluster(accelerator="v5p", topology="2x2x2", replicas=1)
    c.spec.enableTokenAuth = True
    h.store.create(c.to_dict())
    h.settle()
    secret = h.store.get("Secret", auth_secret_name("demo"))
    assert len(secret["stringData"]["token"]) > 20
    # Every container sources the token from the secret.
    for pod in h.pods():
        env = pod["spec"]["containers"][0]["env"]
        entry = next(e for e in env if e["name"] == ENV_AUTH_TOKEN)
        assert entry["valueFrom"]["secretKeyRef"]["name"] == \
            auth_secret_name("demo")
    # Reconciles never rotate the token.
    token = secret["stringData"]["token"]
    h.settle()
    assert h.store.get("Secret", auth_secret_name("demo"))["stringData"][
        "token"] == token


def test_coordinator_enforces_bearer_auth():
    server = CoordinatorServer(state=MemoryBackend(), spawn_jobs=False,
                               auth_token="sekret")
    srv, url = server.serve_background()
    try:
        anon = CoordinatorClient(url, auth_token="")
        assert anon.healthz()                      # healthz stays open
        with pytest.raises(CoordinatorError) as e:
            anon.list_jobs()
        assert "401" in str(e.value)
        with pytest.raises(CoordinatorError):
            anon.submit_job("j", "echo x")
        wrong = CoordinatorClient(url, auth_token="nope")
        with pytest.raises(CoordinatorError):
            wrong.list_jobs()
        ok = CoordinatorClient(url, auth_token="sekret")
        assert ok.list_jobs() == []
        ok.submit_job("j1", "echo x")
        assert ok.get_job_info("j1").job_id == "j1"
    finally:
        srv.shutdown()


def test_ingress_built_when_enabled():
    h = Harness()
    c = make_cluster(accelerator="v5e", topology="2x2", replicas=0)
    c.spec.headGroupSpec.enableIngress = True
    h.store.create(c.to_dict())
    h.settle()
    ing = h.store.get("Ingress", "demo-head-ingress")
    paths = ing["spec"]["rules"][0]["http"]["paths"]
    assert {p["path"] for p in paths} == {"/demo", "/demo/serve"}
    assert paths[0]["backend"]["service"]["name"] == "demo-head-svc"
    # Off by default.
    h2 = Harness()
    h2.store.create(make_cluster(accelerator="v5e", topology="2x2").to_dict())
    h2.settle()
    assert h2.store.try_get("Ingress", "demo-head-ingress") is None


def test_openshift_route_shape():
    c = make_cluster()
    c.metadata.annotations = {"haproxy.router.openshift.io/timeout": "30s"}
    route = build_head_route(c)
    assert route["kind"] == "Route"
    assert route["spec"]["to"] == {"kind": "Service",
                                   "name": "demo-head-svc", "weight": 100}
    assert route["spec"]["wildcardPolicy"] == "None"
    # Cluster annotations pass through as route customization
    # (ref openshift.go:28-30).
    assert route["metadata"]["annotations"][
        "haproxy.router.openshift.io/timeout"] == "30s"


def test_openshift_route_created_by_operator_knob():
    """config.useOpenShiftRoute flips the ingress seam to emit a Route
    (ref: the reference switches on detected cluster type)."""
    from kuberay_tpu.api.config import OperatorConfiguration
    from kuberay_tpu.operator import Operator

    op = Operator(OperatorConfiguration(useOpenShiftRoute=True),
                  fake_kubelet=True)
    c = make_cluster(accelerator="v5e", topology="2x2")
    c.spec.headGroupSpec.enableIngress = True
    op.store.create(c.to_dict())
    for _ in range(4):
        op.manager.flush_delayed()
        op.manager.run_until_idle()
        op.kubelet.step()
    assert op.store.try_get("Route", "demo-head-route") is not None
    assert op.store.try_get("Ingress", "demo-head-ingress") is None


def test_external_state_cleanup_finalizer_flow():
    """Deletion of an external-backend cluster: pods removed, a cleanup Job
    is launched, and the finalizer holds the CR until the Job succeeds."""
    h = Harness()
    c = make_cluster(accelerator="v5e", topology="2x2", replicas=1)
    c.spec.headStateOptions = HeadStateOptions(
        backend="external", externalStorageAddress="redis:6379")
    h.store.create(c.to_dict())
    h.settle()
    assert C.FINALIZER_GCS_FT in h.store.get(
        "TpuCluster", "demo")["metadata"]["finalizers"]

    h.store.delete("TpuCluster", "demo")
    h.settle()
    # CR still present (finalizer), pods gone, cleanup Job exists.
    cr = h.store.get("TpuCluster", "demo")
    assert cr["metadata"]["deletionTimestamp"]
    assert h.pods() == []
    job = h.store.get("Job", "demo-state-cleanup")
    cmd = job["spec"]["template"]["spec"]["containers"][0]["command"]
    assert "redis:6379" in cmd
    # Cleanup completes -> finalizer released -> CR removed.
    job["status"] = {"succeeded": 1}
    h.store.update_status(job)
    h.settle()
    assert h.store.try_get("TpuCluster", "demo") is None


def test_cleanup_timeout_survives_missing_creation_timestamp():
    """A store backend that omits creationTimestamp must NOT make the
    deletion timeout instantly true (finalizer released without the
    cleanup ever running): the controller stamps an observation-time
    annotation and waits the full window from there (VERDICT r1 weak
    item 5).  creationTimestamp is scrubbed from the store's internal
    copy because the public update() force-restores it — the scrub
    simulates a foreign backend, not a writable field."""
    import time as _time

    h = Harness()
    c = make_cluster(accelerator="v5e", topology="2x2", replicas=0)
    c.spec.headStateOptions = HeadStateOptions(
        backend="external", externalStorageAddress="redis:6379")
    h.store.create(c.to_dict())
    h.settle()
    h.store.delete("TpuCluster", "demo")
    h.settle()

    def scrub():
        for key, obj in h.store._objects.items():
            if obj["metadata"]["name"] == "demo-state-cleanup":
                obj["metadata"].pop("creationTimestamp", None)
    scrub()
    h.settle()
    scrub()   # the annotation write re-persists it; scrub again
    h.settle()
    # Default 300s window: CR must still be held by the finalizer, and
    # the fallback clock annotation must now exist.
    assert h.store.try_get("TpuCluster", "demo") is not None
    ann = h.store.get("Job", "demo-state-cleanup")["metadata"].get(
        "annotations", {})
    assert float(ann[C.ANNOTATION_CLEANUP_OBSERVED_AT]) > 0
    # Age the annotation past the window: finalizer must release.
    job = h.store.get("Job", "demo-state-cleanup")
    job["metadata"]["annotations"][C.ANNOTATION_CLEANUP_OBSERVED_AT] = \
        str(_time.time() - 301)
    h.store.update(job)
    scrub()
    h.settle()
    h.settle()
    assert h.store.try_get("TpuCluster", "demo") is None


def test_external_state_cleanup_timeout():
    """A wedged cleanup Job must not hold the CR hostage forever: the
    timeout annotation releases the finalizer."""
    h = Harness()
    c = make_cluster(accelerator="v5e", topology="2x2", replicas=0)
    c.spec.headStateOptions = HeadStateOptions(
        backend="external", externalStorageAddress="redis:6379")
    c.metadata.annotations = {C.ANNOTATION_FT_DELETION_TIMEOUT: "0"}
    h.store.create(c.to_dict())
    h.settle()
    h.store.delete("TpuCluster", "demo")
    h.settle()   # first pass creates the Job; timeout=0 releases next pass
    h.settle()
    assert h.store.try_get("TpuCluster", "demo") is None
