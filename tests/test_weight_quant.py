"""int8 weight quantization (W8A16 serving): round-trip error bounds,
logits fidelity, dtype/footprint claims, and composition with every
engine mode (paged, kv-quant, speculative, chunked, tp mesh, Mixtral)."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kuberay_tpu.models import llama
from kuberay_tpu.serve.engine import Request, ServeEngine
from kuberay_tpu.serve.weight_quant import (
    dequantize_weights,
    make_weight_dequant_forward,
    quantization_error,
    quantize_weights,
)

CFG = llama.CONFIGS["llama_tiny"]
PARAMS = llama.init_params(CFG, jax.random.PRNGKey(0))


def test_roundtrip_error_bounded_and_structure():
    q = quantize_weights(PARAMS)
    # Matmul weights became int8+scale pairs; norms/embed untouched.
    assert q["layers"]["wq"]["q8"].dtype == jnp.int8
    assert q["layers"]["w_down"]["s8"].dtype == jnp.float32
    assert q["embed"].dtype == PARAMS["embed"].dtype
    # Per-channel symmetric int8: relative error ~<= 1/127 per channel
    # amplitude (global bound is looser; 2% is comfortably above it).
    assert quantization_error(PARAMS) < 0.02
    d = dequantize_weights(q)
    assert d["layers"]["wq"].shape == PARAMS["layers"]["wq"].shape
    assert d["layers"]["wq"].dtype == jnp.bfloat16


def test_footprint_roughly_halved():
    def nbytes(tree):
        return sum(x.size * x.dtype.itemsize
                   for x in jax.tree.leaves(tree))
    dense_layers = nbytes(PARAMS["layers"])
    quant_layers = nbytes(quantize_weights(PARAMS)["layers"])
    # bf16 -> int8 (+tiny scales): close to half.
    assert quant_layers < 0.6 * dense_layers


def test_logits_close_to_dense():
    from kuberay_tpu.models.llama import forward

    toks = jnp.asarray([[1, 2, 3, 4, 5, 6, 7, 8]], jnp.int32)
    ref = forward(CFG, PARAMS, toks).astype(jnp.float32)
    qfwd = make_weight_dequant_forward(
        lambda cfg, p, t: forward(cfg, p, t))
    got = qfwd(CFG, quantize_weights(PARAMS), toks).astype(jnp.float32)
    # Quantization noise, not corruption: close on the logit scale.
    scale = float(jnp.max(jnp.abs(ref)))
    assert float(jnp.max(jnp.abs(ref - got))) < 0.1 * max(scale, 1.0)


def run_engine(engine_cls=ServeEngine, cfg=CFG, params=PARAMS, **kw):
    eng = engine_cls(cfg, params, max_slots=2, max_len=64, **kw)
    for i, p in enumerate([[1, 2, 3, 4, 5], [9, 8, 7]]):
        eng.add_request(Request(f"r{i}", p, max_new_tokens=8,
                                temperature=0.7 if i == 1 else 0.0))
    return {r.request_id: r.tokens for r in eng.run()}


def test_engine_modes_compose_with_weight_quant():
    from kuberay_tpu.serve.paged_engine import PagedServeEngine

    base = run_engine(weight_quant="int8")
    assert all(len(v) == 8 for v in base.values())
    # Deterministic under the quantized weights.
    assert run_engine(weight_quant="int8") == base
    # Paged + prefix cache + chunked + speculative + kv-quant all run.
    paged = run_engine(PagedServeEngine, weight_quant="int8",
                       block_size=8)
    assert all(len(v) == 8 for v in paged.values())
    combo = run_engine(PagedServeEngine, weight_quant="int8",
                       block_size=8, prefill_chunk=8, speculative=2,
                       kv_quant="int8", decode_impl="xla")
    assert all(len(v) == 8 for v in combo.values())


def test_weight_quant_under_tp_mesh_token_identical():
    """Sharded quantize: per-channel scales reduce shard-local; the tp
    engine must reproduce the single-device quantized engine exactly."""
    from kuberay_tpu.serve.sharding import serve_mesh

    ref = run_engine(weight_quant="int8")
    tp = run_engine(weight_quant="int8", mesh=serve_mesh(2))
    assert ref == tp


def test_mixtral_weight_quant():
    from kuberay_tpu.models import mixtral

    cfg = mixtral.CONFIGS["mixtral_tiny"]
    params = mixtral.init_params(cfg, jax.random.PRNGKey(0))
    out = run_engine(cfg=cfg, params=params, weight_quant="int8")
    assert all(len(v) == 8 for v in out.values())


def test_unknown_weight_quant_rejected():
    with pytest.raises(ValueError, match="weight_quant"):
        ServeEngine(CFG, PARAMS, max_slots=2, max_len=64,
                    weight_quant="int4")


def test_per_layer_scales_survive_loud_layer():
    """A 10x louder layer must not crush another layer's int8
    resolution: scales reduce over the contraction axis only, so each
    layer (and Mixtral expert) keeps its own scale."""
    p2 = jax.tree.map(lambda x: x, PARAMS)
    wq = np.array(p2["layers"]["wq"], np.float32)   # writable copy
    wq[0] *= 10.0                       # layer 0 loud, others quiet
    p2 = {**p2, "layers": {**p2["layers"],
                           "wq": jnp.asarray(wq, PARAMS["layers"]["wq"].dtype)}}
    q = quantize_weights(p2)
    # Scale shape keeps the layer axis: [L, 1, out].
    assert q["layers"]["wq"]["s8"].shape[0] == wq.shape[0]
    d = np.asarray(dequantize_weights(q, dtype=jnp.float32)["layers"]["wq"])
    # Quiet layer 1's relative error is unaffected by the loud layer 0.
    rel = np.max(np.abs(d[1] - wq[1])) / np.max(np.abs(wq[1]))
    assert rel < 0.02, rel
