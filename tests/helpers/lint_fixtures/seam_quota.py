"""Quota-seam fixtures: wrapper bypass (positive), suppressed, clean.

The per-file ``capacity-through-quota-seam`` rule only sees direct
scheduler asks inside the seam-owning class — ``_ask_direct`` is a
module-level wrapper, invisible to it by construction.
"""


class FixtureQuotaController:
    """POSITIVE: ``_fast_path`` reaches the scheduler ask through a
    module-level wrapper, bypassing ``_admission_verdict``."""

    def __init__(self, scheduler):
        self.scheduler = scheduler

    def _admission_verdict(self, cluster):
        return self.scheduler.on_cluster_submission(cluster)

    def _fast_path(self, cluster):
        return _ask_direct(self.scheduler, cluster)


def _ask_direct(scheduler, cluster):
    return scheduler.on_cluster_submission(cluster)


class FixtureQuotaSuppressed:
    """SUPPRESSED: same bypass shape, waived with a reason."""

    def __init__(self, scheduler):
        self.scheduler = scheduler

    def _admission_verdict(self, cluster):
        return self.scheduler.on_job_submission(cluster)

    def _probe(self, cluster):
        return _peek_quota(self.scheduler, cluster)


def _peek_quota(scheduler, cluster):
    # kuberay-lint: disable-next-line=transitive-seam-bypass -- fixture: dry-run probe, does not claim quota
    return scheduler.on_job_submission(cluster)


class FixtureQuotaClean:
    """NEGATIVE: every path funnels through the seam."""

    def __init__(self, scheduler):
        self.scheduler = scheduler

    def _admission_verdict(self, cluster):
        return self.scheduler.on_cluster_submission(cluster)

    def launch(self, cluster):
        if not self._admission_verdict(cluster):
            return "quota-held"
        return "launched"
