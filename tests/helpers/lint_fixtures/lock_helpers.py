"""Cross-module blocking helpers for the lock fixtures: the I/O sits
one module and two calls away from the lock that holds it."""

import time


def push_remote(payload):
    return _post(payload)


def _post(payload):
    time.sleep(0.05)
    return payload
