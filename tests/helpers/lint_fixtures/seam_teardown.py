"""Drain-seam fixtures: wrapper bypass (positive), suppressed, clean.

The per-file ``slice-teardown-through-drain-seam`` rule sees direct
``self._delete_pod`` calls in ``_reconcile_worker_group``;
``_evict_all`` is the wrapper that defeats it.
"""


class FixtureGroupController:
    """POSITIVE: group reconcile deletes slice pods via a module-level
    helper, never entering ``_delete_slice``'s drain protocol."""

    def _delete_slice(self, cluster, plist, group):
        for p in plist:
            self._delete_pod(p, group)
        return True

    def _reconcile_worker_group(self, cluster, group, slices):
        for idx, plist in slices.items():
            _evict_all(self, plist)


def _evict_all(ctrl, plist):
    for p in plist:
        ctrl._delete_pod(p)


class FixtureGroupSuppressed:
    """SUPPRESSED: same shape, waived with a reason."""

    def _delete_slice(self, cluster, plist, group):
        for p in plist:
            self._delete_pod(p, group)
        return True

    def _reconcile_worker_group(self, cluster, group, slices):
        for idx, plist in slices.items():
            _purge_failed(self, plist)


def _purge_failed(ctrl, plist):
    for p in plist:
        # kuberay-lint: disable-next-line=transitive-seam-bypass -- fixture: already-failed pods have nothing left to drain
        ctrl._delete_pod(p)


class FixtureGroupClean:
    """NEGATIVE: teardown routes through the seam."""

    def _delete_slice(self, cluster, plist, group):
        for p in plist:
            self._delete_pod(p, group)
        return True

    def _reconcile_worker_group(self, cluster, group, slices):
        for idx, plist in slices.items():
            if not self._delete_slice(cluster, plist, group):
                return 1.0
