"""POSITIVE: determinism taint through wrappers.

``reconcile`` never touches ``time``/``uuid`` itself — the entropy sits
two hops down, where the per-file pass has no reason to look.  The
sim-determinism rule must flag both sinks with the chain from
``reconcile``.
"""

import time
import uuid


def _fresh_suffix():
    return uuid.uuid4().hex[:8]


def _stamp_started():
    return time.time()


class FixtureTaintedController:
    KIND = "FixtureTainted"

    def reconcile(self, name, namespace="default"):
        token = _fresh_suffix()
        started = _stamp_started()
        return token, started
