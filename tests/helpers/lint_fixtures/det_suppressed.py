"""SUPPRESSED: a determinism sink silenced with a justified comment."""

import uuid


def _fallback_uid():
    # kuberay-lint: disable-next-line=sim-determinism -- fixture: exercises the suppressed-with-reason shape the analyzer must honor
    return uuid.uuid4().hex


class FixtureWaivedUidController:
    KIND = "FixtureWaivedUid"

    def reconcile(self, name, namespace="default"):
        return _fallback_uid()
