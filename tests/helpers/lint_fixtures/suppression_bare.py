"""Suppression-hygiene fixtures: a bare suppression (positive — it is
itself a finding) next to a justified one (negative)."""


def fanout_bare(items):
    for item in items:
        try:
            item()
        except Exception:
            pass  # kuberay-lint: disable=exception-swallow


def fanout_justified(items):
    for item in items:
        try:
            item()
        except Exception:
            pass  # kuberay-lint: disable=exception-swallow -- best-effort fan-out; per-item failures are expected and non-actionable
