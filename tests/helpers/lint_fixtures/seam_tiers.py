"""Tier-seam fixtures: internals bypass (positive), suppressed, clean.

The per-file ``kv-block-through-tier-seam`` rule exempts the store
class itself (any class defining both ``checkout`` and ``admit``);
everything else touching ``<...tiers...>._underscore`` is a ledger
desync.
"""

from collections import OrderedDict


class FixtureTierStore:
    """The seam owner: defines checkout + admit, so its own underscore
    internals are fair game."""

    def __init__(self):
        self._host = OrderedDict()
        self._spill = OrderedDict()

    def admit(self, h, tokens, payload):
        self._host[h] = (tuple(tokens), payload)
        return True

    def checkout(self, h, tokens):
        hit = self._host.get(h)
        if hit and hit[0] == tuple(tokens):
            self._host.move_to_end(h)
            return hit[1]
        return None

    def discard(self, h):
        return int(self._host.pop(h, None) is not None)


class FixtureEngineBypass:
    """POSITIVE: frees a block by popping the store's host dict
    directly — the gauges and advert log never hear about it, so the
    fleet index keeps advertising the dead block."""

    def __init__(self):
        self.tiers = FixtureTierStore()

    def _fast_free(self, h):
        self.tiers._host.pop(h, None)


class FixtureEngineSuppressed:
    """SUPPRESSED: same shape, waived with a reason."""

    def __init__(self):
        self.tier_store = FixtureTierStore()

    def _debug_dump(self):
        # kuberay-lint: disable-next-line=kv-block-through-tier-seam -- fixture: read-only introspection in a debug handler, never mutates residency
        return dict(self.tier_store._host)


class FixtureEngineClean:
    """NEGATIVE: residency changes go through the seam; public stats
    and non-store underscore attrs stay quiet."""

    def __init__(self):
        self.tiers = FixtureTierStore()
        self._pending = []

    def free(self, h):
        return self.tiers.discard(h)

    def resume(self, h, tokens):
        return self.tiers.checkout(h, tokens)

    def drain(self):
        while self._pending:
            h, blk = self._pending.pop()
            self.tiers.admit(h, blk, tuple(blk))
