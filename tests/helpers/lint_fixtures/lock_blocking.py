"""Lock fixtures: cross-module blocking under a lock (positive),
suppressed, clean.

The per-file ``blocking-under-lock`` rule judges one class at a time;
``push_remote`` lives in ``lock_helpers`` and only its callee sleeps,
so nothing in THIS file looks blocking without the call graph.
"""

import threading

from tests.helpers.lint_fixtures.lock_helpers import push_remote


class FixtureLockedCache:
    """POSITIVE: the locked region reaches ``time.sleep`` two modules
    of wrappers away."""

    def __init__(self):
        self._lock = threading.Lock()
        self._items = {}

    def put(self, key, value):
        with self._lock:
            self._items = {**self._items, key: value}
            push_remote(value)


class FixtureLockedSuppressed:
    """SUPPRESSED: same shape, waived with a reason."""

    def __init__(self):
        self._lock = threading.Lock()
        self._items = {}

    def put(self, key, value):
        with self._lock:
            self._items = {**self._items, key: value}
            # kuberay-lint: disable-next-line=transitive-blocking-under-lock -- fixture: bounded 50 ms flush, measured acceptable
            push_remote(value)


class FixtureLockedClean:
    """NEGATIVE: mutate under the lock, flush after release."""

    def __init__(self):
        self._lock = threading.Lock()
        self._items = {}

    def put(self, key, value):
        with self._lock:
            self._items = {**self._items, key: value}
        push_remote(value)
