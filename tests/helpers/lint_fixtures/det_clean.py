"""NEGATIVE: the sanctioned determinism seams.

A seeded ``random.Random`` and an injected clock are exactly what the
rule asks for; this controller must produce zero findings.
"""

import random


class FixtureSeededController:
    KIND = "FixtureSeeded"

    def __init__(self, seed, clock):
        self._rng = random.Random(seed)
        self._clock = clock

    def reconcile(self, name, namespace="default"):
        jitter = self._rng.random()
        return self._clock.now() + jitter
