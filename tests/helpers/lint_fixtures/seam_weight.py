"""Weight-gate fixtures: wrapper bypass (positive), suppressed, clean.

The per-file ``traffic-weight-through-gate`` rule checks writes inside
the orchestrator class; ``_force_green`` lives outside it.
"""


class FixtureUpgradeOrchestrator:
    """POSITIVE: ``_self_heal`` rewrites the ramp weight through a
    module-level helper, skipping the burn-rate verdict."""

    def _apply_upgrade_decision(self, svc, decision):
        svc.status.pendingServiceStatus.trafficWeightPercent = \
            decision.green_weight

    def _self_heal(self, svc):
        _force_green(svc)


def _force_green(svc):
    svc.status.pendingServiceStatus.trafficWeightPercent = 100


class FixtureUpgradeSuppressed:
    """SUPPRESSED: same shape, waived with a reason."""

    def _apply_upgrade_decision(self, svc, decision):
        svc.status.pendingServiceStatus.trafficWeightPercent = \
            decision.green_weight

    def _rollback_hatch(self, svc):
        _zero_green(svc)


def _zero_green(svc):
    # kuberay-lint: disable-next-line=transitive-seam-bypass -- fixture: emergency rollback hatch, operator-invoked only
    svc.status.pendingServiceStatus.trafficWeightPercent = 0


class FixtureUpgradeClean:
    """NEGATIVE: weight writes stay inside the seam and the terminal
    ``_promote``."""

    def _apply_upgrade_decision(self, svc, decision):
        svc.status.pendingServiceStatus.trafficWeightPercent = \
            decision.green_weight

    def _promote(self, svc):
        svc.status.activeServiceStatus.trafficWeightPercent = 100

    def step(self, svc, decision):
        self._apply_upgrade_decision(svc, decision)
