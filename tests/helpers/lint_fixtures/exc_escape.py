"""Exception-escape fixtures: multi-hop escape (positive), sanctioned
Conflict, handled (negatives), and suppressed.

The raise sits two calls below ``reconcile``; only the escape analysis
over the call graph can connect them.
"""


class FixtureError(Exception):
    pass


class Conflict(Exception):
    pass


def _load(store, name):
    return _fetch(store, name)


def _fetch(store, name):
    if name not in store:
        raise FixtureError(name)
    return store[name]


class FixtureEscapeController:
    """POSITIVE: FixtureError escapes reconcile via two wrappers."""

    KIND = "FixtureEscape"

    def reconcile(self, name, namespace="default"):
        return _load({}, name)


class FixtureConflictController:
    """NEGATIVE: Conflict is the sanctioned rv-retry signal."""

    KIND = "FixtureConflict"

    def reconcile(self, name, namespace="default"):
        if name == "stale":
            raise Conflict(name)
        return None


class FixtureHandledController:
    """NEGATIVE: the escape is caught and converted to a requeue."""

    KIND = "FixtureHandled"

    def reconcile(self, name, namespace="default"):
        try:
            return _load({}, name)
        except FixtureError:
            return 5.0


class FixtureWaivedEscapeController:
    """SUPPRESSED: the escape is waived with a reason."""

    KIND = "FixtureWaivedEscape"

    def reconcile(self, name, namespace="default"):
        # kuberay-lint: disable-next-line=reconcile-exception-escape -- fixture: FixtureError here means corrupted state; backoff is the intended handling
        return _load({}, name)
