"""Kill-a-follower e2e worker (spawned by test_group_health.py).

Host 0: MultihostServeEngine + GroupMonitor + ServeFrontend + HTTP
server; submits a long request, then waits for the group to degrade
(the parent SIGKILLs the follower mid-decode).  Prints marker lines the
test asserts on and exits 0 — the real pod would now fail its readiness
probe and be replaced with its whole slice.

Follower: engine + heartbeat thread + follower_loop (killed by parent).

Env: TPU_GROUP_HEALTH_PORT (parent-chosen), READY_FILE (host 0 touches
it after the first completed device step so the parent kills the
follower only once serving is genuinely in flight).
"""

import json
import os
import sys
import threading
import time
import urllib.error
import urllib.request


def main() -> None:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax
    jax.config.update("jax_platforms", "cpu")
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))
    from kuberay_tpu.train.launcher import initialize_distributed
    initialize_distributed()
    import dataclasses

    from kuberay_tpu.models import llama
    from kuberay_tpu.serve.engine import ServeEngine
    from kuberay_tpu.serve.group_health import (
        GroupMonitor,
        start_heartbeat,
    )
    from kuberay_tpu.serve.multihost import (
        MultihostServeEngine,
        follower_loop,
    )
    from kuberay_tpu.serve.server import ServeFrontend
    from kuberay_tpu.serve.sharding import serve_mesh

    cfg = dataclasses.replace(llama.CONFIGS["llama_tiny"],
                              n_heads=8, n_kv_heads=4)
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    mesh = serve_mesh(len(jax.devices()))
    kw = dict(max_slots=2, max_len=256, mesh=mesh)
    hb_port = int(os.environ["TPU_GROUP_HEALTH_PORT"])

    if jax.process_index() != 0:
        follower = ServeEngine(cfg, params, **kw)
        start_heartbeat("127.0.0.1", hb_port, jax.process_index(),
                        interval=0.3)
        print("FOLLOWER_READY", flush=True)
        follower_loop(follower)
        print("FOLLOWER_STOPPED", flush=True)
        return

    eng = MultihostServeEngine(cfg, params, **kw)
    monitor = GroupMonitor(expected=[1], miss_timeout=3.0,
                           step_timeout=10.0, grace=120.0)
    monitor.listen(port=hb_port)
    frontend = ServeFrontend(
        eng, monitor=monitor,
        on_degraded=lambda r: print(f"DEGRADED {r}", flush=True))
    srv, url = frontend.serve_background()

    ready_file = os.environ["READY_FILE"]
    results = []

    def long_request():
        t0 = time.time()
        resp = frontend.submit([1, 2, 3, 4, 5], max_tokens=2000,
                               timeout=240.0)
        results.append((resp, time.time() - t0))
        print(f"SUBMIT_DONE none={resp is None} "
              f"secs={time.time() - t0:.1f}", flush=True)

    t = threading.Thread(target=long_request, daemon=True)
    t.start()

    # Signal the parent once decoding is genuinely in flight.
    while eng.num_active == 0 and frontend.degraded is None:
        time.sleep(0.05)
    time.sleep(1.0)                      # a few decode broadcasts
    with open(ready_file, "w") as f:
        f.write("serving\n")
    print("SERVING_IN_FLIGHT", flush=True)

    deadline = time.time() + 120
    while frontend.degraded is None and time.time() < deadline:
        time.sleep(0.2)
    if frontend.degraded is None:
        print("NEVER_DEGRADED", flush=True)
        sys.exit(2)

    # The in-flight submit must fail FAST (drained), not hang to its
    # 240 s client timeout.
    t.join(timeout=30)
    print(f"SUBMIT_FAILED_FAST joined={not t.is_alive()} "
          f"none={bool(results and results[0][0] is None)}", flush=True)

    # Readiness flips: /healthz must be 503 now.
    try:
        urllib.request.urlopen(f"{url}/healthz", timeout=5)
        print("HEALTHZ_STILL_OK", flush=True)
    except urllib.error.HTTPError as e:
        body = json.loads(e.read())
        print(f"HEALTHZ_503 code={e.code} reason={body.get('reason')!r}",
              flush=True)

    # New submissions are rejected immediately.
    t0 = time.time()
    resp = frontend.submit([1, 2, 3], max_tokens=4, timeout=30.0)
    print(f"NEW_SUBMIT_REJECTED none={resp is None} "
          f"secs={time.time() - t0:.2f}", flush=True)

    # Shutdown must not hang on the dead collective.
    srv.shutdown()
    frontend.close(timeout=None)
    eng.stop()                           # skipped broadcast (degraded)
    monitor.close()
    print("CLEAN_EXIT", flush=True)
    sys.stdout.flush()
    # Skip atexit: jax.distributed's shutdown barrier would fail against
    # the dead peer (the real pod is SIGKILLed by slice replacement at
    # this point anyway).
    os._exit(0)


if __name__ == "__main__":
    main()
