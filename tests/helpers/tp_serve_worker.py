"""Multi-host TP serving worker (spawned by test_tp_serving_multihost).

Process 0 schedules (MultihostServeEngine + step-plan broadcast); process
1+ replay via follower_loop.  Mirrors what every host of a TpuService
slice runs through ``python -m kuberay_tpu.serve.server --tp 0``.
"""

import json
import os
import sys

# ONE request set shared by the worker and both single-process reference
# blocks in tests/test_tp_serving.py — drift here fails as an opaque
# token mismatch, so it must not be copy-pasted.
LOCKSTEP_REQUESTS = [
    # (prompt, kwargs)
    ([1, 2, 3, 4, 5], dict(max_new_tokens=8)),
    ([9, 8, 7], dict(max_new_tokens=8, temperature=0.8, top_p=0.9,
                     top_k=16)),
]


def main() -> None:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax
    jax.config.update("jax_platforms", "cpu")
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))
    from kuberay_tpu.train.launcher import initialize_distributed
    initialize_distributed()
    from kuberay_tpu.models import llama
    from kuberay_tpu.serve.engine import Request, ServeEngine
    from kuberay_tpu.serve.multihost import (
        MultihostServeEngine,
        follower_loop,
    )
    from kuberay_tpu.serve.sharding import serve_mesh

    import dataclasses
    # tp=4 needs 4 kv heads; widen the tiny config (matches the test's
    # single-process reference).
    cfg = dataclasses.replace(llama.CONFIGS["llama_tiny"],
                              n_heads=8, n_kv_heads=4)
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    mesh = serve_mesh(len(jax.devices()))
    paged = "--paged" in sys.argv
    kw = dict(max_slots=2, max_len=64, mesh=mesh)
    if paged:
        kw["block_size"] = 8
    if jax.process_index() == 0:
        if paged:
            from kuberay_tpu.serve.multihost import MultihostPagedServeEngine
            eng = MultihostPagedServeEngine(cfg, params, **kw)
        else:
            eng = MultihostServeEngine(cfg, params, **kw)
        # r1 samples with filters: the samp row rides the broadcast
        # plan and BOTH processes must select the filtered compiled
        # sampler variant (derived from the plan, not local state).
        for i, (p, kw) in enumerate(LOCKSTEP_REQUESTS):
            eng.add_request(Request(f"r{i}", p, **kw))
        out = {r.request_id: r.tokens for r in eng.run()}
        eng.stop()
        print("RESULT " + json.dumps(out), flush=True)
    else:
        if paged:
            from kuberay_tpu.serve.paged_engine import PagedServeEngine
            follower = PagedServeEngine(cfg, params, **kw)
        else:
            follower = ServeEngine(cfg, params, **kw)
        n = follower_loop(follower)
        print(f"FOLLOWER replayed {n} calls", flush=True)


if __name__ == "__main__":
    main()
