"""kuberay_tpu.analysis: the reconcile-invariant lint gate.

Two halves:

1. every rule fires on a purpose-built bad fixture (and stays quiet on
   the matching good one) — the rules' own regression tests;
2. the FULL rule set runs over the real ``kuberay_tpu/`` tree and must
   come back clean — the gate that blocks invariant regressions from
   landing (suppressions carry their justification in the source).
"""

from __future__ import annotations

import os
import textwrap

import pytest

from kuberay_tpu.analysis import RULES, analyze_paths, analyze_source
from kuberay_tpu.analysis.reporters import render_human, render_json

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _rules_fired(src, only=None, **kw):
    findings = analyze_source(textwrap.dedent(src), only=only, **kw)
    return findings, {f.rule for f in findings}


# ---------------------------------------------------------------------------
# rule registry sanity
# ---------------------------------------------------------------------------

def test_rule_registry_complete():
    assert {"rv-precondition", "lock-discipline", "blocking-under-lock",
            "exception-swallow", "tpu-env-completeness",
            "requeue-observability",
            "phase-transition-recorded",
            "no-io-under-store-lock",
            "shard-affinity",
            "slice-teardown-through-drain-seam",
            "traffic-weight-through-gate",
            "capacity-through-quota-seam",
            "kv-block-through-tier-seam",
            # whole-program (call-graph) rules
            "sim-determinism",
            "transitive-seam-bypass",
            "transitive-blocking-under-lock",
            "reconcile-exception-escape",
            "suppression-without-reason"} <= set(RULES)
    for cls in RULES.values():
        assert cls.DESCRIPTION and cls.INVARIANT


# ---------------------------------------------------------------------------
# rv-precondition
# ---------------------------------------------------------------------------

def test_rv_precondition_flags_pre_write_refresh():
    findings, fired = _rules_fired("""
        def _update_status(self, cluster):
            obj = cluster.to_dict()
            cur = self.store.try_get(self.KIND, cluster.metadata.name)
            self.store.update_status(carry_rv(obj, cur))
    """)
    assert "rv-precondition" in fired
    assert "re-read 'cur'" in findings[0].message


def test_rv_precondition_flags_explicit_rv_cross_stamp():
    _, fired = _rules_fired("""
        def write(self, job):
            obj = job.to_dict()
            cur = self.store.try_get("TpuJob", job.metadata.name)
            obj["metadata"]["resourceVersion"] = \\
                cur["metadata"]["resourceVersion"]
            self.store.update_status(obj)
    """)
    assert "rv-precondition" in fired


def test_rv_precondition_flags_helper_reread_rmw():
    _, fired = _rules_fired("""
        def _clear(self, cluster, executed):
            obj = self.store.try_get(self.KIND, cluster.metadata.name,
                                     cluster.metadata.namespace)
            obj["spec"]["slicesToDelete"] = []
            self.store.update(obj)
    """)
    assert "rv-precondition" in fired


def test_rv_precondition_allows_single_read_modify_write():
    # The fake-kubelet shape: one read, mutate, write with ITS rv.
    _, fired = _rules_fired("""
        def step(self):
            pod = self.store.try_get("Pod", "p", "default")
            pod["status"] = {"phase": "Running"}
            self.store.update_status(pod)
    """)
    assert "rv-precondition" not in fired


def test_rv_precondition_allows_carry_rv_from_same_read():
    _, fired = _rules_fired("""
        def refresh(self):
            cur = self.store.try_get(self.KIND, "x")
            cur["status"] = {}
            self.store.update_status(carry_rv(cur, cur))
    """)
    assert "rv-precondition" not in fired


def test_rv_precondition_ignores_plain_dict_get():
    _, fired = _rules_fired("""
        def lookup(self, cluster):
            obj = cluster.to_dict()
            cur = labels.get("tpu.dev/cluster")
            self.store.update_status(carry_rv(obj, snapshot))
    """)
    assert "rv-precondition" not in fired


# ---------------------------------------------------------------------------
# lock-discipline
# ---------------------------------------------------------------------------

LOCKED_CLASS_BAD = """
    import threading

    class Counter:
        def __init__(self):
            self._lock = threading.Lock()
            self._value = 0

        def bump(self):
            with self._lock:
                self._value = self._value + 1

        def read(self):
            return self._value
"""


def test_lock_discipline_flags_unguarded_access():
    findings, fired = _rules_fired(LOCKED_CLASS_BAD)
    assert "lock-discipline" in fired
    assert "_value" in findings[0].message
    assert "read()" in findings[0].message


def test_lock_discipline_accepts_guarded_access():
    _, fired = _rules_fired("""
        import threading

        class Counter:
            def __init__(self):
                self._lock = threading.Lock()
                self._value = 0

            def bump(self):
                with self._lock:
                    self._value = self._value + 1

            def read(self):
                with self._lock:
                    return self._value
    """)
    assert "lock-discipline" not in fired


def test_lock_discipline_interprocedural_helper_ok():
    # _notify-style helper: every call site holds the lock.
    _, fired = _rules_fired("""
        import threading

        class Store:
            def __init__(self):
                self._lock = threading.Lock()
                self._rv = 0

            def _next_rv(self):
                self._rv = self._rv + 1
                return self._rv

            def create(self):
                with self._lock:
                    return self._next_rv()

            def update(self):
                with self._lock:
                    return self._next_rv()
    """)
    assert "lock-discipline" not in fired


def test_lock_discipline_init_only_helper_ok():
    # Construction-time helpers are single-threaded.
    _, fired = _rules_fired("""
        import threading

        class Store:
            def __init__(self):
                self._lock = threading.Lock()
                self._objects = {}
                self._replay()

            def _replay(self):
                self._objects = {"seed": 1}

            def put(self, k, v):
                with self._lock:
                    self._objects = {**self._objects, k: v}
    """)
    assert "lock-discipline" not in fired


def test_lock_discipline_condition_counts_as_lock():
    _, fired = _rules_fired("""
        import threading

        class Q:
            def __init__(self):
                self._lock = threading.RLock()
                self._cond = threading.Condition(self._lock)
                self._backlog = []

            def push(self, x):
                with self._cond:
                    self._backlog = self._backlog + [x]

            def peek(self):
                with self._lock:
                    return self._backlog
    """)
    assert "lock-discipline" not in fired


# ---------------------------------------------------------------------------
# blocking-under-lock
# ---------------------------------------------------------------------------

def test_blocking_under_lock_flags_sleep():
    findings, fired = _rules_fired("""
        import threading
        import time

        class C:
            def __init__(self):
                self._lock = threading.Lock()

            def slow(self):
                with self._lock:
                    time.sleep(1)
    """)
    assert "blocking-under-lock" in fired
    assert "time.sleep" in findings[0].message


def test_blocking_under_lock_flags_interprocedural():
    _, fired = _rules_fired("""
        import threading
        import subprocess

        class C:
            def __init__(self):
                self._lock = threading.Lock()

            def _spawn(self):
                subprocess.run(["true"])

            def locked(self):
                with self._lock:
                    self._spawn()
    """)
    assert "blocking-under-lock" in fired


def test_blocking_under_lock_allows_condition_wait_and_outside_io():
    _, fired = _rules_fired("""
        import threading
        import time

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self._cond = threading.Condition(self._lock)

            def wait(self):
                with self._cond:
                    self._cond.wait(1.0)

            def nap(self):
                time.sleep(0.1)
    """)
    assert "blocking-under-lock" not in fired


# ---------------------------------------------------------------------------
# exception-swallow
# ---------------------------------------------------------------------------

def test_exception_swallow_flags_bare_except_in_loop():
    _, fired = _rules_fired("""
        def fanout(items):
            for item in items:
                try:
                    item()
                except:
                    pass
    """)
    assert "exception-swallow" in fired


def test_exception_swallow_flags_broad_pass_in_reconcile():
    _, fired = _rules_fired("""
        def reconcile(self, name):
            try:
                self._do(name)
            except Exception:
                pass
    """)
    assert "exception-swallow" in fired


def test_exception_swallow_allows_logged_and_specific():
    _, fired = _rules_fired("""
        def reconcile(self, name):
            try:
                self._do(name)
            except Exception:
                log.exception("reconcile failed")
            try:
                self._cleanup(name)
            except KeyError:
                pass
    """)
    assert "exception-swallow" not in fired


def test_exception_swallow_ignores_non_loop_helpers():
    _, fired = _rules_fired("""
        def parse(text):
            try:
                return int(text)
            except Exception:
                pass
    """)
    assert "exception-swallow" not in fired


# ---------------------------------------------------------------------------
# tpu-env-completeness
# ---------------------------------------------------------------------------

def test_tpu_env_flags_partial_identity():
    findings, fired = _rules_fired("""
        def build_worker(pod):
            env = {"TPU_WORKER_ID": "0",
                   "TPU_WORKER_HOSTNAMES": "a,b"}
            return env
    """)
    assert "tpu-env-completeness" in fired
    assert "TPU_TOPOLOGY" in findings[0].message


def test_tpu_env_flags_lone_selector_setdefault():
    _, fired = _rules_fired("""
        def place(spec):
            sel = spec.setdefault("nodeSelector", {})
            sel.setdefault("cloud.google.com/gke-tpu-accelerator", "x")
    """)
    assert "tpu-env-completeness" in fired


def test_tpu_env_accepts_complete_set_and_reads():
    _, fired = _rules_fired("""
        import os

        def build_worker(C, topo, host_idx):
            env = {C.ENV_TPU_WORKER_ID: str(host_idx),
                   C.ENV_TPU_WORKER_HOSTNAMES: "a,b",
                   C.ENV_TPU_TOPOLOGY: topo}
            sel = {}
            sel.setdefault("cloud.google.com/gke-tpu-accelerator", "x")
            sel.setdefault("cloud.google.com/gke-tpu-topology", topo)
            return env, sel

        def launcher():
            return os.environ["TPU_WORKER_ID"]
    """)
    assert "tpu-env-completeness" not in fired


# ---------------------------------------------------------------------------
# suppressions + reporters + parse errors
# ---------------------------------------------------------------------------

def test_suppression_same_line_and_next_line_and_file():
    base = """
        def fanout(items):
            for item in items:
                try:
                    item()
                except Exception:
                    pass{inline}
    """
    _, fired = _rules_fired(base.format(
        inline="   # kuberay-lint: disable=exception-swallow"))
    assert "exception-swallow" not in fired

    _, fired = _rules_fired("""
        def fanout(items):
            for item in items:
                try:
                    item()
                # kuberay-lint: disable-next-line=exception-swallow
                except Exception:
                    pass
    """)
    assert "exception-swallow" not in fired

    _, fired = _rules_fired("""
        # kuberay-lint: disable-file=exception-swallow
        def fanout(items):
            for item in items:
                try:
                    item()
                except Exception:
                    pass
    """)
    assert "exception-swallow" not in fired


def test_suppression_audit_mode_keeps_findings():
    findings, fired = _rules_fired("""
        def fanout(items):
            for item in items:
                try:
                    item()
                except Exception:
                    pass  # kuberay-lint: disable=exception-swallow
    """, keep_suppressed=True)
    assert "exception-swallow" in fired


def test_parse_error_is_a_finding():
    findings, fired = _rules_fired("def broken(:\n")
    assert fired == {"parse-error"}


def test_reporters_render():
    findings, _ = _rules_fired(LOCKED_CLASS_BAD)
    human = render_human(findings)
    assert "[lock-discipline]" in human and "finding(s)" in human
    js = render_json(findings)
    assert '"lock-discipline"' in js
    assert render_human([]).startswith("kuberay-lint: clean")


def test_cli_exit_codes(tmp_path):
    from kuberay_tpu.analysis.__main__ import main
    bad = tmp_path / "bad.py"
    bad.write_text(textwrap.dedent(LOCKED_CLASS_BAD))
    good = tmp_path / "good.py"
    good.write_text("x = 1\n")
    assert main([str(good)]) == 0
    assert main([str(bad)]) == 1
    assert main([str(bad), "--rules", "tpu-env-completeness"]) == 0
    assert main(["--list-rules"]) == 0
    assert main([str(bad), "--rules", "no-such-rule"]) == 2


# ---------------------------------------------------------------------------
# requeue-observability
# ---------------------------------------------------------------------------

def test_requeue_observability_flags_silent_requeue_return():
    _, fired = _rules_fired("""
        class C:
            def reconcile(self, name, ns):
                try:
                    self._do(name)
                except CoordinatorError as e:
                    self._set_message(str(e))
                    return 2.0
    """)
    assert "requeue-observability" in fired


def test_requeue_observability_flags_silent_requeue_assignment():
    _, fired = _rules_fired("""
        class C:
            def _process(self, key):
                try:
                    self._do(key)
                except Exception as e:
                    log.debug("failed: %s", e)
                    requeue = 5.0
                if requeue:
                    self.enqueue(key, after=requeue)
    """)
    assert "requeue-observability" in fired


def test_requeue_observability_flags_delegated_requeue_kwarg():
    _, fired = _rules_fired("""
        class C:
            def _state_running(self, job):
                try:
                    self._poll(job)
                except CoordinatorError:
                    return self._to(job, "RETRYING", requeue=0.1)
    """)
    assert "requeue-observability" in fired


def test_requeue_observability_accepts_metric_and_span_evidence():
    _, fired = _rules_fired("""
        class C:
            def reconcile(self, name, ns):
                try:
                    self._do(name)
                except Conflict as e:
                    self.metrics.reconcile_conflict(self.KIND)
                    return 0.05
                except CoordinatorError as e:
                    self.tracer.record_error("coordinator", str(e))
                    return 2.0
                except Exception as e:
                    self.registry.inc("tpu_reconcile_errors_total",
                                      {"kind": self.KIND})
                    return 5.0

            def _process(self, key):
                try:
                    self._do(key)
                except Exception as e:
                    span.error(str(e))
                    requeue = 5.0
    """)
    assert "requeue-observability" not in fired


def test_requeue_observability_ignores_non_requeue_and_log_error():
    _, fired = _rules_fired("""
        class C:
            def reconcile(self, name, ns):
                try:
                    self._do(name)
                except NotFound:
                    return None
                except CoordinatorError:
                    pass
                return 2.0

            def helper(self):
                # Not a reconcile-shaped function: out of scope.
                try:
                    self._do()
                except Exception:
                    return 1.0
    """)
    assert "requeue-observability" not in fired


def test_requeue_observability_log_error_is_not_evidence():
    _, fired = _rules_fired("""
        class C:
            def reconcile(self, name, ns):
                try:
                    self._do(name)
                except Exception as e:
                    self._log.error("failed: %s", e)
                    return 5.0
    """)
    assert "requeue-observability" in fired


# ---------------------------------------------------------------------------
# phase-transition-recorded
# ---------------------------------------------------------------------------

def test_phase_transition_flags_unrecorded_state_write():
    findings, fired = _rules_fired("""
        class C:
            def _update_status(self, cluster):
                status = cluster.status
                status.state = "ready"
    """)
    assert "phase-transition-recorded" in fired
    assert "'state'" in findings[0].message


def test_phase_transition_flags_job_deployment_status():
    _, fired = _rules_fired("""
        def _to(self, job, state):
            job.status.jobDeploymentStatus = state
            self._update(job)
    """)
    assert "phase-transition-recorded" in fired


def test_phase_transition_flags_subscript_state_write():
    _, fired = _rules_fired("""
        def _set_status(self, obj, state):
            st = obj.setdefault("status", {})
            st["state"] = state
    """)
    assert "phase-transition-recorded" in fired


def test_phase_transition_quiet_when_recorded():
    _, fired = _rules_fired("""
        class C:
            def _update_status(self, cluster):
                status = cluster.status
                self.transitions.record(self.KIND, "default",
                                        cluster.name, "ready",
                                        old_state=status.state)
                status.state = "ready"
    """)
    assert "phase-transition-recorded" not in fired


def test_phase_transition_ignores_non_status_state_attrs():
    """``self.state = backend`` (the coordinator's state backend) and
    plain dict writes without a status receiver are not CR phases."""
    _, fired = _rules_fired("""
        class Coord:
            def __init__(self, state):
                self.state = state or backend_from_env()

            def run(self):
                d = {}
                d["state"] = "whatever"
    """)
    assert "phase-transition-recorded" not in fired


def test_phase_transition_accepts_observe_state_evidence():
    _, fired = _rules_fired("""
        def _sync(self, job, ledger):
            ledger.observe_state("TpuJob", "ns", job.name, "Running")
            job.status.jobDeploymentStatus = "Running"
    """)
    assert "phase-transition-recorded" not in fired


# ---------------------------------------------------------------------------
# no-io-under-store-lock
# ---------------------------------------------------------------------------

def test_no_io_under_store_lock_flags_serialize_journal_dispatch():
    findings, fired = _rules_fired("""
        import json, threading
        class Store:
            def __init__(self):
                self._lock = threading.Lock()
                self._journal = None
                self._watchers = []
            def put(self, obj):
                with self._lock:
                    self._journal.append(json.dumps(obj).encode())
                    for w in list(self._watchers):
                        w(obj)
    """, only=["no-io-under-store-lock"])
    assert "no-io-under-store-lock" in fired
    messages = " ".join(f.message for f in findings)
    assert "serializes" in messages
    assert "journal I/O" in messages
    assert "watcher callback" in messages


def test_no_io_under_store_lock_quiet_on_queued_offlock_pattern():
    """The shipped discipline: queue under the primary lock, serialize/
    append/dispatch under auxiliary locks after release."""
    _, fired = _rules_fired("""
        import json, threading
        class Store:
            def __init__(self):
                self._lock = threading.Lock()
                self._journal_lock = threading.Lock()
                self._journal = None
                self._pending = []
                self._subs = []
            def put(self, obj):
                with self._lock:
                    self._pending.append(obj)
                with self._journal_lock:
                    self._journal.append(json.dumps(obj).encode())
                for sub in list(self._subs):
                    sub.fn(obj)
    """, only=["no-io-under-store-lock"])
    assert "no-io-under-store-lock" not in fired


def test_no_io_under_store_lock_catches_sub_fn_dispatch():
    _, fired = _rules_fired("""
        import threading
        class Store:
            def __init__(self):
                self._lock = threading.Lock()
                self._subscribers = []
            def put(self, ev):
                with self._lock:
                    for sub in self._subscribers:
                        sub.fn(ev)
    """, only=["no-io-under-store-lock"])
    assert "no-io-under-store-lock" in fired


def test_no_io_under_store_lock_ignores_other_locks():
    """Auxiliary locks exist precisely to serialize I/O off the hot
    mutex — only ``self._lock`` regions count."""
    _, fired = _rules_fired("""
        import json, threading
        class Store:
            def __init__(self):
                self._journal_lock = threading.Lock()
                self._lock = threading.Lock()
                self._journal = None
            def drain(self):
                with self._journal_lock:
                    self._journal.append(json.dumps({}).encode())
    """, only=["no-io-under-store-lock"])
    assert "no-io-under-store-lock" not in fired


# ---------------------------------------------------------------------------
# shard-affinity
# ---------------------------------------------------------------------------

def test_shard_affinity_flags_direct_pool_add_outside_router():
    _, fired = _rules_fired("""
        class TpuThingController:
            def kick(self, key):
                self.manager._pool.add(key)
    """, only=["shard-affinity"],
        path="kuberay_tpu/controlplane/cluster_controller.py")
    assert "shard-affinity" in fired


def test_shard_affinity_flags_private_workqueue_and_add_after():
    findings, fired = _rules_fired("""
        from kuberay_tpu.controlplane.workqueue import WorkQueue

        class Rogue:
            def __init__(self):
                self.wq = WorkQueue()

            def later(self, key):
                self.wq.add_after(key, 5.0)
    """, only=["shard-affinity"], path="kuberay_tpu/operator.py")
    assert "shard-affinity" in fired
    assert len(findings) == 2            # the ctor AND the add_after


def test_shard_affinity_quiet_in_router_modules_and_on_plain_sets():
    _, fired = _rules_fired("""
        class Manager:
            def enqueue(self, key):
                self._pool.add(key)
    """, only=["shard-affinity"],
        path="kuberay_tpu/controlplane/manager.py")
    assert fired == set()
    _, fired = _rules_fired("""
        def track(seen, used, key):
            seen.add(key)        # a set, not a pool
            used.add(key)
    """, only=["shard-affinity"],
        path="kuberay_tpu/controlplane/cluster_controller.py")
    assert fired == set()


# ---------------------------------------------------------------------------
# metric-catalog-sync
# ---------------------------------------------------------------------------

def _mini_repo(tmp_path, doc_body, module_body):
    """A throwaway repo shape the rule can resolve: docs/observability.md
    plus one package module."""
    (tmp_path / "docs").mkdir()
    (tmp_path / "docs" / "observability.md").write_text(doc_body)
    pkg = tmp_path / "kuberay_tpu" / "utils"
    pkg.mkdir(parents=True)
    mod = pkg / "metrics.py"
    mod.write_text(textwrap.dedent(module_body))
    return str(mod)


def test_metric_catalog_sync_flags_undocumented_family(tmp_path):
    from kuberay_tpu.analysis.core import analyze_file

    path = _mini_repo(
        tmp_path,
        "| `tpu_known_total` | counter | — | documented |\n",
        """
        def hit(registry):
            registry.inc("tpu_known_total")
            registry.inc("tpu_mystery_total")
        """)
    findings = analyze_file(path, only=["metric-catalog-sync"])
    assert [f for f in findings if "tpu_mystery_total" in f.message]
    assert not [f for f in findings if "tpu_known_total" in f.message]


def test_metric_catalog_sync_wildcard_row_covers_prefix(tmp_path):
    from kuberay_tpu.analysis.core import analyze_file

    path = _mini_repo(
        tmp_path,
        "| `tpu_serve_*` | counter | — | passthrough |\n",
        """
        def hit(registry):
            registry.set_gauge("tpu_serve_queue_depth", 3)
        """)
    assert analyze_file(path, only=["metric-catalog-sync"]) == []


def test_metric_catalog_sync_flags_stale_doc_row(tmp_path):
    from kuberay_tpu.analysis.core import analyze_file

    # The anchor module (utils/metrics.py) triggers the doc->code sweep;
    # `tpu_ghost_total` has a catalog row but no code behind it.
    path = _mini_repo(
        tmp_path,
        "| `tpu_real_total` | counter | — | lives |\n"
        "| `tpu_ghost_total` | counter | — | stale |\n",
        """
        def hit(registry):
            registry.inc("tpu_real_total")
        """)
    findings = analyze_file(path, only=["metric-catalog-sync"])
    assert [f for f in findings if "tpu_ghost_total" in f.message]
    assert not [f for f in findings if "tpu_real_total" in f.message]


def test_metric_catalog_sync_skips_synthetic_sources():
    # analyze_source snippets have no repo to resolve the doc against.
    _, fired = _rules_fired("""
        def hit(registry):
            registry.inc("tpu_definitely_undocumented_total")
    """, only=["metric-catalog-sync"])
    assert fired == set()


def test_metric_catalog_sync_real_doc_and_tree_agree():
    """The live contract: the shipping package and the shipping catalog
    are in sync, both directions (this is what tools/lint.sh enforces)."""
    findings = [f for f in _tree_report().findings
                if f.rule == "metric-catalog-sync"]
    assert findings == [], "\n" + render_human(findings)


# ---------------------------------------------------------------------------
# slice-teardown-through-drain-seam
# ---------------------------------------------------------------------------

def test_drain_seam_flags_direct_delete_in_group_reconcile():
    findings, fired = _rules_fired("""
        class Controller:
            def _delete_slice(self, cluster, plist, group):
                for p in plist:
                    self._delete_pod(p, group)
                return True

            def _reconcile_worker_group(self, cluster, group, pods):
                for p in pods:
                    self._delete_pod(p)
    """, only=["slice-teardown-through-drain-seam"])
    assert "slice-teardown-through-drain-seam" in fired
    assert "_delete_slice" in findings[0].message


def test_drain_seam_quiet_when_teardown_routes_through_seam():
    _, fired = _rules_fired("""
        class Controller:
            def _delete_slice(self, cluster, plist, group):
                for p in plist:
                    self._delete_pod(p, group)
                return True

            def _reconcile_worker_group(self, cluster, group, slices):
                for idx, plist in slices.items():
                    if not self._delete_slice(cluster, plist, group):
                        return 1.0
    """, only=["slice-teardown-through-drain-seam"])
    assert fired == set()


def test_drain_seam_ignores_classes_without_the_seam():
    # No _delete_slice defined: the class predates the drain seam (or
    # isn't slice-atomic at all); the rule does not apply.
    _, fired = _rules_fired("""
        class Legacy:
            def _reconcile_worker_group(self, cluster, group, pods):
                for p in pods:
                    self._delete_pod(p)
    """, only=["slice-teardown-through-drain-seam"])
    assert fired == set()


# ---------------------------------------------------------------------------
# the gate: the real tree is clean
# ---------------------------------------------------------------------------

_TREE_REPORT = []


def _tree_report():
    # ONE whole-tree pass shared by the gate tests below — the project
    # graph build is the expensive part, and the report already carries
    # both the live findings and the suppression ledger.
    if not _TREE_REPORT:
        tree = os.path.join(REPO_ROOT, "kuberay_tpu")
        _TREE_REPORT.append(analyze_paths([tree]))
    return _TREE_REPORT[0]


def test_kuberay_tpu_tree_is_clean():
    """The full rule set over the shipping package.  A finding here is a
    real invariant regression (or needs an explicit, justified
    suppression comment at the site)."""
    findings = _tree_report().findings
    assert findings == [], "\n" + render_human(findings)


def test_known_suppressions_are_few_and_intentional():
    """Audit mode: suppressed findings exist (we suppress with
    justification rather than weaken rules), but the count is pinned so
    a drive-by suppression spree shows up in review.

    Current ledger: 9 reconcile-exception-escape (feature-gate typos and
    status-write-failure paths where crashing into backoff is correct),
    6 transitive-blocking-under-lock (journal compaction under the
    store lock, by design — file-level suppression in store.py — plus
    the coordinator connection mutex), 2 blocking-under-lock,
    1 lock-discipline, 1 sim-determinism (auth token entropy)."""
    counts = _tree_report().suppressed_counts
    assert sum(counts.values()) == 19, counts


# ---------------------------------------------------------------------------
# traffic-weight-through-gate
# ---------------------------------------------------------------------------

def test_weight_gate_flags_side_channel_write():
    findings, fired = _rules_fired("""
    class Controller:
        def _apply_upgrade_decision(self, svc, decision):
            svc.status.pendingServiceStatus.trafficWeightPercent = \
                decision.green_weight

        def _self_heal(self, svc):
            svc.status.pendingServiceStatus.trafficWeightPercent = 100
    """, only=["traffic-weight-through-gate"])
    assert fired == {"traffic-weight-through-gate"}
    assert "_self_heal" in findings[0].message


def test_weight_gate_allows_seam_and_terminal_promote():
    _, fired = _rules_fired("""
    class Controller:
        def _apply_upgrade_decision(self, svc, decision):
            svc.status.pendingServiceStatus.trafficWeightPercent = \
                decision.green_weight
            svc.status.activeServiceStatus.trafficWeightPercent = \
                100 - decision.green_weight

        def _promote(self, svc):
            svc.status.activeServiceStatus.trafficWeightPercent = 100
    """, only=["traffic-weight-through-gate"])
    assert fired == set()


def test_weight_gate_ignores_classes_without_the_seam():
    # The open-loop timer stepper (no orchestrator seam) is a different
    # controller shape, not a violation of this one's funnel.
    _, fired = _rules_fired("""
    class LegacyTimer:
        def step(self, svc):
            svc.status.pendingServiceStatus.trafficWeightPercent = 10
    """, only=["traffic-weight-through-gate"])
    assert fired == set()


# ---------------------------------------------------------------------------
# capacity-through-quota-seam
# ---------------------------------------------------------------------------

def test_quota_seam_flags_direct_scheduler_ask():
    findings, fired = _rules_fired("""
    class Controller:
        def _admission_verdict(self, cluster):
            return self.scheduler.on_cluster_submission(cluster.to_dict())

        def _fast_path(self, cluster):
            return self.scheduler.on_cluster_submission(cluster.to_dict())
    """, only=["capacity-through-quota-seam"])
    assert "capacity-through-quota-seam" in fired
    assert "_fast_path" in findings[0].message


def test_quota_seam_flags_create_with_no_earlier_verdict():
    findings, fired = _rules_fired("""
    class Controller:
        def _admission_verdict(self, cluster):
            return self.scheduler.on_cluster_submission(cluster.to_dict())

        def _reconcile_pods(self, cluster, raw):
            pod = build_head_pod(cluster, self.config_env)
            self._create_pod(pod, "head")
            verdict = self._admission_verdict(cluster)
    """, only=["capacity-through-quota-seam"])
    assert "capacity-through-quota-seam" in fired
    assert "no earlier _admission_verdict" in findings[0].message


def test_quota_seam_quiet_when_creates_sit_downstream():
    _, fired = _rules_fired("""
    class Controller:
        def _admission_verdict(self, cluster):
            return self.scheduler.on_cluster_submission(cluster.to_dict())

        def _reconcile_pods(self, cluster, raw):
            verdict = self._admission_verdict(cluster)
            if not verdict:
                return 5.0
            pod = build_head_pod(cluster, self.config_env)
            self._create_pod(pod, "head")
    """, only=["capacity-through-quota-seam"])
    assert fired == set()


def test_quota_seam_ignores_seamless_classes_and_bare_launchers():
    # The cron-controller shape: a seam but no pod loop (it launches
    # TpuJobs, not pods) — and a seamless class creating pods is a
    # different controller shape, not a funnel violation.
    _, fired = _rules_fired("""
    class CronController:
        def _admission_verdict(self, job):
            return self.scheduler.quota.admit(self._demand(job))

        def _launch(self, cron, job):
            if not self._admission_verdict(job):
                return "quota-held"

    class AdmissionFreeController:
        def _reconcile_pods(self, cluster, raw):
            self._create_pod(build_head_pod(cluster, None), "head")
    """, only=["capacity-through-quota-seam"])
    assert fired == set()


# ---------------------------------------------------------------------------
# kv-block-through-tier-seam
# ---------------------------------------------------------------------------

def test_tier_seam_flags_underscore_poke_on_tiers_receiver():
    findings, fired = _rules_fired("""
    class Engine:
        def _fast_free(self, h):
            self.tiers._host.pop(h, None)
    """, only=["kv-block-through-tier-seam"])
    assert fired == {"kv-block-through-tier-seam"}
    assert "self.tiers._host" in findings[0].message


def test_tier_seam_flags_tier_store_alias_and_deep_chain():
    findings, fired = _rules_fired("""
    def drain(eng):
        eng.tier_store._pending.clear()
        eng.kv_store._spill[7] = ("blk",)
    """, only=["kv-block-through-tier-seam"])
    assert len(findings) == 2


def test_tier_seam_exempts_the_store_class_itself():
    _, fired = _rules_fired("""
    class KvTierStore:
        def admit(self, h, tokens, payload):
            self._host[h] = payload

        def checkout(self, h, tokens):
            return self._host.get(h)

        def _evict(self):
            self._spill.popitem(last=False)
    """, only=["kv-block-through-tier-seam"])
    assert fired == set()


def test_tier_seam_quiet_on_public_api_and_unrelated_receivers():
    _, fired = _rules_fired("""
    class Engine:
        def free(self, h):
            self.tiers.discard(h)
            self.tiers.stats()
            self._pending.pop()
            self.allocator._free_list.append(h)
    """, only=["kv-block-through-tier-seam"])
    assert fired == set()


def test_tier_seam_fixture_positive_suppressed_negative():
    # One live finding (the bypass), one justified suppression (the
    # debug dump), and the clean class stays quiet.
    fixdir = os.path.join(REPO_ROOT, "tests", "helpers", "lint_fixtures")
    report = analyze_paths(
        [os.path.join(fixdir, "seam_tiers.py")],
        only=["kv-block-through-tier-seam"])
    assert len(report.findings) == 1
    assert "self.tiers._host" in report.findings[0].message
    assert report.suppressed_counts == {"kv-block-through-tier-seam": 1}, \
        "the waived _debug_dump poke must be ledgered"
