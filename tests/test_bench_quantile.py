"""Pin the serve-bench percentile estimator on small samples.

The old truncating index ``int(n * 0.99) - 1`` never reports the tail
sample at small n (for n=21 it lands on the 20th of 21 values) — the
exact outlier a p99 exists to surface.  These tests pin the interpolated
estimate so the benchmark's headline latency number can't silently
regress back to ~p90.
"""

import importlib.util
import os

import pytest

_BENCH = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                      "benchmark", "serve_bench.py")


def _load_bench():
    spec = importlib.util.spec_from_file_location("serve_bench", _BENCH)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_p99_n21_is_interpolated_not_truncated():
    bench = _load_bench()
    samples = list(range(1, 22))        # n=21: 1..21
    # Truncating index int(21*0.99)-1 = 19 -> sample 20 (ignores the
    # tail).  Interpolated p99 sits between the two largest samples.
    assert bench.percentile(samples, 99) == pytest.approx(20.8)
    assert bench.percentile(samples, 99) > samples[int(21 * 0.99) - 1]
    # Order-independent.
    assert bench.percentile(list(reversed(samples)), 99) == \
        pytest.approx(20.8)


def test_percentile_edges():
    bench = _load_bench()
    assert bench.percentile([7.0], 99) == 7.0
    assert bench.percentile([1.0, 2.0], 50) == pytest.approx(1.5)
    assert bench.percentile(list(range(1, 22)), 50) == 11
    with pytest.raises(ValueError):
        bench.percentile([], 99)
