"""Sim-gated acceptance for the straggler microscope (ISSUE 11).

The ``straggler-drill`` scenario seeds ``slow_host`` fault windows (one
host at 3x the fleet pace for a drawn number of consecutive steps)
against a multi-slice training cluster emitting per-host heartbeats
under the virtual clock.  The gates:

1. **Determinism** — same seed, same journal hash, same verdicts,
   same injected windows, seeds 0..9.
2. **Detection** — every completed slow window is flagged within the
   K-consecutive-step budget and names the injected host (the
   ``straggler-detection`` invariant checker enforces this inside
   ``run()``; the test re-derives it independently and asserts the
   gate is non-vacuous).
3. **Exactness** — goodput ``stalled-on-straggler`` seconds equal the
   injected fault windows to the float, and sum(phases) == total.
4. **Replay invariance** — journal hashes are byte-identical with
   step telemetry on or off (telemetry is observational-only).
"""

from __future__ import annotations

import pytest

from kuberay_tpu.sim.faults import SLOW_HOST
from kuberay_tpu.sim.harness import SimHarness
from kuberay_tpu.sim.scenarios import get_scenario

JOB = "default/drill-train"


def _drill(seed, steps_on=True, ticks=12):
    with SimHarness(seed, scenario=get_scenario("straggler-drill"),
                    steps=steps_on, goodput=steps_on) as h:
        res = h.run(ticks)
        snap = {
            "hash": res.journal_hash,
            "ok": res.ok,
            "faults": dict(res.faults_injected),
            "log": [dict(e) for e in h.slow_host_log],
            "verdicts": (h.steps.stragglers(JOB) if h.steps is not None
                         else None),
            "now": h.clock.now(),
            "rollup": (h.goodput.rollup("TpuCluster", "default",
                                        "drill-train", now=h.clock.now())
                       if steps_on else None),
        }
    return res, snap


@pytest.mark.timeout(300)
@pytest.mark.parametrize("seed", range(10))
def test_straggler_drill_deterministic(seed):
    """Same seed -> byte-identical journal, identical fault windows,
    identical verdicts.  Seeds 0..9, each run twice."""
    res_a, a = _drill(seed)
    res_b, b = _drill(seed)
    assert res_a.ok, res_a.violations
    assert a["hash"] == b["hash"]
    assert a["faults"] == b["faults"]
    assert a["log"] == b["log"]
    assert a["verdicts"] == b["verdicts"]


@pytest.mark.timeout(120)
def test_detection_within_k_steps_with_identity():
    """Every completed injected window produced a verdict naming the
    injected host, detected within straggler_steps heartbeats of the
    first slow step — re-derived here, independent of the checker."""
    with SimHarness(0, scenario=get_scenario("straggler-drill"),
                    steps=True) as h:
        res = h.run(12)
        assert res.ok, res.violations
        # Non-vacuous: the drill actually injected slow-host windows.
        assert res.faults_injected.get(SLOW_HOST, 0) >= 1
        completed = [e for e in h.slow_host_log
                     if e["clear_ts"] is not None]
        assert completed, "no slow window completed in 12 ticks"
        verdicts = h.steps.stragglers(JOB)
        k = h.steps.straggler_steps
        for entry in completed:
            match = [v for v in verdicts
                     if v["host"] == entry["host"]
                     and v["first_slow_step"] == entry["first_slow_step"]]
            assert match, f"window {entry} never flagged"
            v = match[0]
            assert v["detected_step"] - v["first_slow_step"] + 1 <= k
            assert v["first_slow_ts"] == entry["first_slow_ts"]
            assert v["cleared_step"] == entry["clear_step"]
            assert v["skew"] == pytest.approx(3.0, abs=0.25)
        # The export artifact carries the tracker snapshot.
        export = h.export_trace()
        assert export["steps"]["jobs"][0]["job"] == JOB


@pytest.mark.timeout(120)
def test_goodput_stalled_seconds_equal_fault_window_exactly():
    """stalled-on-straggler == sum of the injected windows, to the
    float: [first slow heartbeat, first normal heartbeat] per completed
    window, plus first-slow-to-now for a window still open at the end.
    The partition discipline survives the sub-attribution."""
    with SimHarness(0, scenario=get_scenario("straggler-drill"),
                    steps=True, goodput=True) as h:
        res = h.run(12)
        assert res.ok, res.violations
        assert h.slow_host_log
        now = h.clock.now()
        expected = 0.0
        for e in h.slow_host_log:
            end = e["clear_ts"] if e["clear_ts"] is not None else now
            expected += end - e["first_slow_ts"]
        roll = h.goodput.rollup("TpuCluster", "default", "drill-train",
                                now=now)
    assert expected > 0.0
    assert roll["phases"]["stalled-on-straggler"] == pytest.approx(
        expected, abs=1e-6)
    assert sum(roll["phases"].values()) == pytest.approx(roll["total"],
                                                         abs=1e-6)
    # The stall never counts as interrupted/recovery — the slice kept
    # running, just slowly.
    assert roll["phases"]["interrupted"] == 0.0
    assert roll["phases"]["recovery"] == 0.0


@pytest.mark.timeout(300)
@pytest.mark.parametrize("name", ["straggler-drill", "rolling-upgrade"])
def test_journal_hash_invariant_with_telemetry_on_or_off(name):
    """The replay contract: telemetry on vs off produces byte-identical
    journal hashes — for the drill itself AND a legacy scenario."""
    ticks = 12 if name == "straggler-drill" else 2
    with SimHarness(0, scenario=get_scenario(name), steps=True) as h:
        on = h.run(ticks)
    with SimHarness(0, scenario=get_scenario(name)) as h:
        off = h.run(ticks)
        assert h.steps is None
    assert on.ok and off.ok
    assert on.journal_hash == off.journal_hash
    assert on.journal_len == off.journal_len
    assert on.faults_injected == off.faults_injected
