"""Grouped (ragged_dot) expert FFN vs the dense all-experts reference."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kuberay_tpu.models.mixtral import CONFIGS, moe_ffn_dropless
from kuberay_tpu.ops.moe_matmul import (
    dropless_reference,
    grouped_moe_ffn,
    moe_ffn_flops,
)


def _rand_moe(T=24, d=32, f=48, E=4, K=2, seed=0, dtype=jnp.float32):
    ks = jax.random.split(jax.random.PRNGKey(seed), 6)
    xt = jax.random.normal(ks[0], (T, d), dtype)
    wg = jax.random.normal(ks[1], (E, d, f), dtype) * 0.1
    wu = jax.random.normal(ks[2], (E, d, f), dtype) * 0.1
    wd = jax.random.normal(ks[3], (E, f, d), dtype) * 0.1
    logits = jax.random.normal(ks[4], (T, E))
    topw, topi = jax.lax.top_k(jax.nn.softmax(logits, -1), K)
    topw = topw / topw.sum(-1, keepdims=True)
    return xt, wg, wu, wd, topi, topw


def test_grouped_matches_dense_reference():
    xt, wg, wu, wd, topi, topw = _rand_moe()
    got = jax.jit(grouped_moe_ffn)(xt, wg, wu, wd, topi, topw)
    want = dropless_reference(xt, wg, wu, wd, topi, topw)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_grouped_handles_skewed_routing():
    """All tokens on one expert (empty groups elsewhere) must still work —
    ragged groups of size 0 and size TK."""
    xt, wg, wu, wd, topi, topw = _rand_moe(T=8, K=2)
    topi = jnp.zeros_like(topi).at[:, 1].set(3)   # experts 0 and 3 only
    got = jax.jit(grouped_moe_ffn)(xt, wg, wu, wd, topi, topw)
    want = dropless_reference(xt, wg, wu, wd, topi, topw)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_masked_tokens_contribute_nothing():
    """Zero combine weight (masked slot) must produce a zero output row in
    both implementations."""
    xt, wg, wu, wd, topi, topw = _rand_moe(T=6)
    topw = topw.at[2].set(0.0)
    for fn in (grouped_moe_ffn, dropless_reference):
        out = fn(xt, wg, wu, wd, topi, topw)
        np.testing.assert_allclose(np.asarray(out[2]), 0.0, atol=1e-6)


def test_model_level_impl_parity():
    """moe_ffn_dropless(grouped) == moe_ffn_dropless(dense) through the
    real Mixtral layer params (router included)."""
    cfg = CONFIGS["mixtral_tiny"]
    from kuberay_tpu.models.mixtral import init_params
    params = init_params(cfg, jax.random.PRNGKey(0))
    lp = jax.tree.map(lambda a: a[0], params["layers"])
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 8, cfg.d_model),
                          cfg.dtype)
    mask = jnp.ones((2, 8)).at[1, 5:].set(0)
    got = moe_ffn_dropless(cfg, x, lp, token_mask=mask, impl="grouped")
    want = moe_ffn_dropless(cfg, x, lp, token_mask=mask, impl="dense")
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_flops_accounting():
    f = moe_ffn_flops(T=64, d=128, f=256, n_experts=8, top_k=2)
    assert f["dropless"] / f["grouped"] == pytest.approx(4.0)


def test_serving_decode_uses_grouped_and_matches():
    """End-to-end decode step through forward_with_cache_mixtral stays
    numerically sane with the grouped default (smoke: finite, non-zero)."""
    from kuberay_tpu.serve.kv_cache import (
        forward_with_cache_mixtral,
        init_kv_cache,
    )
    cfg = CONFIGS["mixtral_tiny"]
    from kuberay_tpu.models.mixtral import init_params
    params = init_params(cfg, jax.random.PRNGKey(0))
    cache = init_kv_cache(cfg, slots=2, max_len=16)
    tokens = jnp.array([[5], [7]], jnp.int32)
    logits, _cache = forward_with_cache_mixtral(
        cfg, params, tokens, cache, start=jnp.array([0, 0], jnp.int32))
    assert bool(jnp.all(jnp.isfinite(logits.astype(jnp.float32))))
