"""Serve request tracing gate (kuberay_tpu.obs + serve): traceparent
propagation, explicit-context span recording, virtual-clock exactness
— the gateway-queue/route-decision/forward and engine-queue/kv-alloc/
prefill/decode children union-cover the measured latencies exactly
under an injected clock — tail-sampling retention, backend lifecycle
flight records, and the end-to-end HTTP contract: one trace id on the
response header resolves to a tree holding BOTH gateway and engine
spans.
"""

import json
import urllib.request

import pytest

from kuberay_tpu.controlplane.store import ObjectStore
from kuberay_tpu.obs import FlightRecorder, NOOP_TRACER, Tracer, span_tree
from kuberay_tpu.obs.trace import Span, SpanStore, TraceContext
from kuberay_tpu.sim.clock import VirtualClock
from kuberay_tpu.utils.metrics import MetricsRegistry


def _route_obj(name, backends, namespace="default"):
    return {"apiVersion": "tpu.dev/v1", "kind": "TrafficRoute",
            "metadata": {"name": name, "namespace": namespace},
            "spec": {"backends": backends}, "status": {}}


# ---------------------------------------------------------------------------
# traceparent propagation
# ---------------------------------------------------------------------------

def test_traceparent_round_trip():
    ctx = TraceContext("t000001", "s000002")
    header = ctx.to_traceparent()
    assert header == "00-t000001-s000002-01"
    back = TraceContext.from_traceparent(header)
    assert back.trace_id == "t000001" and back.span_id == "s000002"


def test_traceparent_malformed_headers_yield_none():
    bad = [None, "", "garbage", "00-a-b",          # wrong shape
           "01-t000001-s000002-01",                # unknown version
           "00--s000002-01", "00-t000001--01"]     # empty ids
    for header in bad:
        assert TraceContext.from_traceparent(header) is None, header


def test_noop_tracer_serve_api_is_silent():
    t = NOOP_TRACER
    assert t.start_request("serve-request") is None
    t.record_span(None, "forward", 0.0, 1.0)
    t.finish_request(None)
    assert t.export() == []


# ---------------------------------------------------------------------------
# explicit-context request spans
# ---------------------------------------------------------------------------

def test_request_root_and_explicit_children_virtual_clock():
    clock = VirtualClock(start=100.0)
    tracer = Tracer(clock=clock)
    ctx = tracer.start_request("serve-request", path="/v1/completions")
    tracer.record_span(ctx, "gateway-queue", 100.0, 101.0)
    tracer.record_span(ctx, "forward", 101.0, 104.0, backend="replica-0")
    clock.advance(5.0)
    tracer.finish_request(ctx, status="error", error="http 503")
    spans = tracer.export(ctx.trace_id)
    by_name = {s["name"]: s for s in spans}
    root = by_name["serve-request"]
    assert root["parent_id"] == ""
    assert root["attrs"]["path"] == "/v1/completions"
    assert root["start"] == 100.0 and root["end"] == 105.0
    assert root["status"] == "error" and root["error"] == "http 503"
    for child in ("gateway-queue", "forward"):
        assert by_name[child]["parent_id"] == root["span_id"]
    # finish_request is idempotent: a second finish cannot shrink or
    # re-status the already-closed root.
    clock.advance(50.0)
    tracer.finish_request(ctx)
    root2 = [s for s in tracer.export(ctx.trace_id)
             if s["name"] == "serve-request"][0]
    assert root2["end"] == 105.0 and root2["status"] == "error"


def test_span_store_tail_sampling_keeps_interesting_spans():
    store = SpanStore(max_spans=16)
    for i in range(20):
        store.add(Span("t1", f"s-warm{i}", "", "fast", 0.0, 0.01))
    store.add(Span("t1", "s-slow1", "", "slow", 0.0, 5.0))
    store.add(Span("t1", "s-slow2", "", "slow", 0.0, 6.0))
    store.add(Span("t1", "s-err", "", "boom", 0.0, 0.1,
                   status="error", error="x"))
    store.add(Span("t1", "s-open", "", "open", start=0.0))        # open
    for i in range(10):
        store.add(Span("t1", f"s-fast{i}", "", "fast", 0.0, 0.01))
    assert len(store) == 16
    assert store.dropped == 18
    kept = {s["span_id"] for s in store.export()}
    # Fast successful spans are shed first: the open span, the error
    # span and the slowest spans all survive the churn.
    assert {"s-open", "s-err", "s-slow1", "s-slow2"} <= kept


# ---------------------------------------------------------------------------
# gateway spans under a virtual clock
# ---------------------------------------------------------------------------

def test_gateway_503_still_mints_trace_and_traceparent():
    from kuberay_tpu.serve.gateway import WeightedGateway
    clock = VirtualClock(start=0.0)
    tracer = Tracer(clock=clock)
    gw = WeightedGateway(ObjectStore(), "route", poll_interval=30.0,
                         tracer=tracer, clock=clock)
    try:
        code, _, hdrs = gw.forward_ex("/v1/completions", b"{}")
        assert code == 503
        ctx = TraceContext.from_traceparent(hdrs["traceparent"])
        assert ctx is not None
        root = [s for s in tracer.export(ctx.trace_id)
                if s["name"] == "serve-request"][0]
        assert root["status"] == "error" and "503" in root["error"]
    finally:
        gw.stop()


def test_gateway_spans_virtual_clock_exactness():
    """The forward span measures exactly the backend round-trip in
    virtual time, and the serve-request root covers its children."""
    from kuberay_tpu.serve.gateway import WeightedGateway
    clock = VirtualClock(start=200.0)
    tracer = Tracer(clock=clock)
    store = ObjectStore()
    store.create(_route_obj("route",
                            [{"service": "replica-0", "weight": 1}]))
    gw = WeightedGateway(store, "route",
                         resolver=lambda s: f"http://{s}.test:1",
                         poll_interval=30.0, tracer=tracer, clock=clock)

    def fake_request(base_url, path, body, timeout, trace_ctx=None):
        assert trace_ctx is not None          # header crosses the hop
        clock.advance(3.0)
        return 200, b"{}", {}

    gw._request = fake_request
    try:
        code, _, hdrs = gw.forward_ex("/v1/completions", b"{}")
        assert code == 200
        trace_id = hdrs["traceparent"].split("-")[1]
        by_name = {s["name"]: s for s in tracer.export(trace_id)}
        root = by_name["serve-request"]
        fwd = by_name["forward"]
        route = by_name["route-decision"]
        assert by_name["gateway-queue"]["parent_id"] == root["span_id"]
        assert fwd["end"] - fwd["start"] == pytest.approx(3.0)
        assert fwd["attrs"]["code"] == 200
        assert route["attrs"]["backend"] == "replica-0"
        assert root["start"] == 200.0
        assert root["end"] == pytest.approx(203.0)
        # Children live inside the root window — the trace decomposes
        # the request wall-clock with no span leaking outside it.
        for s in by_name.values():
            assert s["start"] >= root["start"] - 1e-9
            assert s["end"] <= root["end"] + 1e-9
    finally:
        gw.stop()


def test_gateway_flight_records_weight_exclude_retry():
    """Backend lifecycle lands in the flight recorder keyed
    ("Backend", ns, service): weight steps at route sync, exclusion on
    connect failure, retry-failover on the replacement pick."""
    from kuberay_tpu.serve.gateway import WeightedGateway
    store = ObjectStore()
    store.create(_route_obj("route", [{"service": "a", "weight": 1},
                                      {"service": "b", "weight": 2}]))
    flight = FlightRecorder()
    gw = WeightedGateway(store, "route",
                         resolver=lambda s: f"http://{s}.test:1",
                         poll_interval=30.0, flight=flight)

    def dead_request(base_url, path, body, timeout, trace_ctx=None):
        raise ConnectionError("refused")

    gw._request = dead_request
    try:
        for svc, weight in (("a", 1), ("b", 2)):
            recs = flight.timeline("Backend", "default", svc)
            assert any(r["type"] == "weight"
                       and r["detail"] == f"0 -> {weight}"
                       for r in recs), recs
        code, _, _ = gw.forward_ex("/v1/completions", b"{}", timeout=1.0)
        assert code == 502
        all_recs = (flight.timeline("Backend", "default", "a")
                    + flight.timeline("Backend", "default", "b"))
        excludes = [r for r in all_recs if r["type"] == "exclude"]
        retries = [r for r in all_recs if r["type"] == "retry"]
        assert len(excludes) == 2                # both backends tried+failed
        assert len(retries) == 1                 # one failover hop
        assert "failover from" in retries[0]["detail"]
    finally:
        gw.stop()


# ---------------------------------------------------------------------------
# engine spans: virtual-clock exactness (the acceptance contract)
# ---------------------------------------------------------------------------

def _union_length(intervals):
    total, cur = 0.0, None
    for a, b in sorted(i for i in intervals if i[1] > i[0]):
        if cur is None or a > cur[1]:
            if cur is not None:
                total += cur[1] - cur[0]
            cur = [a, b]
        else:
            cur[1] = max(cur[1], b)
    if cur is not None:
        total += cur[1] - cur[0]
    return total


@pytest.mark.timeout(300)
def test_engine_spans_union_cover_ttft_exactly_virtual_clock():
    """Under an injected clock, engine-queue + prefill union-cover the
    TTFT observation EXACTLY, and the histogram exemplar carries the
    request's trace id stamped at the same instant the prefill span
    ends — one consistent story across spans, metric and exemplar."""
    import jax

    from kuberay_tpu.models import llama
    from kuberay_tpu.serve.engine import Request, ServeEngine

    cfg = llama.CONFIGS["llama_tiny"]
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    clock = VirtualClock(start=1000.0)
    tracer = Tracer(clock=clock)
    reg = MetricsRegistry()
    engine = ServeEngine(cfg, params, max_slots=1, max_len=32,
                         metrics=reg, tracer=tracer, clock=clock)
    ctx = tracer.start_request("serve-request")
    engine.add_request(Request("r1", [1, 2, 3], max_new_tokens=3,
                               trace=ctx))
    clock.advance(2.0)                       # the whole queue wait
    engine.run()
    tracer.finish_request(ctx)

    by_name = {s["name"]: s for s in tracer.export(ctx.trace_id)}
    qspan, pspan, dspan = (by_name["engine-queue"], by_name["prefill"],
                           by_name["decode"])
    assert qspan["start"] == 1000.0 and qspan["end"] == 1002.0
    assert pspan["start"] == 1002.0 and pspan["end"] == 1002.0
    assert dspan["start"] == 1002.0          # decode begins at first token
    assert pspan["attrs"]["prompt_len"] == 3
    assert dspan["attrs"]["tokens"] >= 1

    snap = reg.histogram_snapshot("tpu_serve_request_duration_seconds",
                                  {"phase": "ttft"})
    assert snap["n"] == 1
    ttft = snap["sum"]
    assert ttft == pytest.approx(2.0)
    covered = _union_length([(qspan["start"], qspan["end"]),
                             (pspan["start"], pspan["end"])])
    assert covered == pytest.approx(ttft, abs=1e-9)
    # The exemplar on the landing bucket: this trace, stamped at the
    # prefill span's end (= the first-token instant).
    exemplars = [e for e in snap["exemplars"] if e is not None]
    assert exemplars == [(ctx.trace_id, pytest.approx(2.0), 1002.0)]


@pytest.mark.timeout(300)
def test_paged_engine_adds_kv_alloc_span():
    import jax

    from kuberay_tpu.models import llama
    from kuberay_tpu.serve.engine import Request
    from kuberay_tpu.serve.paged_engine import PagedServeEngine

    cfg = llama.CONFIGS["llama_tiny"]
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    clock = VirtualClock(start=0.0)
    tracer = Tracer(clock=clock)
    engine = PagedServeEngine(cfg, params, max_slots=1, max_len=48,
                              block_size=16, tracer=tracer, clock=clock)
    ctx = tracer.start_request("serve-request")
    engine.add_request(Request("r1", [1, 2, 3, 4], max_new_tokens=2,
                               trace=ctx))
    engine.run()
    tracer.finish_request(ctx)
    by_name = {s["name"]: s for s in tracer.export(ctx.trace_id)}
    assert {"engine-queue", "kv-alloc", "prefill", "decode"} <= \
        set(by_name)
    kv = by_name["kv-alloc"]
    assert kv["parent_id"] == ctx.span_id
    assert kv["attrs"]["blocks"] >= 1
    assert kv["attrs"]["cached_tokens"] == 0


# ---------------------------------------------------------------------------
# end to end over real HTTP: gateway -> replica -> engine, one trace
# ---------------------------------------------------------------------------

@pytest.mark.timeout(300)
def test_end_to_end_http_trace_union():
    """The tentpole contract: a completion through gateway + replica
    sharing one tracer yields ONE trace whose response traceparent
    resolves to gateway spans AND engine spans, all parented under the
    serve-request root and contained in its window."""
    import jax

    from kuberay_tpu.models import llama
    from kuberay_tpu.serve.gateway import WeightedGateway
    from kuberay_tpu.serve.paged_engine import PagedServeEngine
    from kuberay_tpu.serve.server import ServeFrontend

    cfg = llama.CONFIGS["llama_tiny"]
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    tracer = Tracer(max_spans=4096)
    eng = PagedServeEngine(cfg, params, max_slots=2, max_len=48,
                           block_size=16, tracer=tracer)
    fe = ServeFrontend(eng, max_queue=8)
    srv, replica_url = fe.serve_background()
    store = ObjectStore()
    store.create(_route_obj("route",
                            [{"service": "replica-0", "weight": 1}]))
    gw = WeightedGateway(store, "route", resolver=lambda s: replica_url,
                         poll_interval=30.0, tracer=tracer)
    try:
        body = json.dumps({"prompt_tokens": [1, 2, 3, 4],
                           "max_tokens": 4}).encode()
        code, _, hdrs = gw.forward_ex("/v1/completions", body)
        assert code == 200
        trace_id = hdrs["traceparent"].split("-")[1]
        spans = tracer.export(trace_id)
        by_name = {s["name"]: s for s in spans}
        assert {"serve-request", "gateway-queue", "route-decision",
                "forward", "engine-queue", "kv-alloc", "prefill",
                "decode"} <= set(by_name), sorted(by_name)
        root = by_name["serve-request"]
        # The traceparent parented the REMOTE engine spans directly on
        # the gateway-minted root: one flat tree, no orphans.
        for s in spans:
            if s is not root:
                assert s["parent_id"] == root["span_id"], s
            assert s["start"] >= root["start"] - 1e-6
            assert s["end"] <= root["end"] + 1e-6
        trees = span_tree(spans)
        assert len(trees) == 1
        assert len(trees[0]["children"]) == len(spans) - 1
    finally:
        gw.stop()
        srv.shutdown()
        fe.close()
