"""Pipeline parallelism: pipelined == sequential, grads flow, real models."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kuberay_tpu.models import llama
from kuberay_tpu.parallel.mesh import MeshSpec
from kuberay_tpu.parallel.pipeline import pipeline_apply


def simple_layer(h, lp):
    return jnp.tanh(h @ lp["w"] + lp["b"])


def make_stack(n_layers=8, d=16, seed=0):
    k = jax.random.split(jax.random.PRNGKey(seed), 2)
    return {
        "w": jax.random.normal(k[0], (n_layers, d, d)) * 0.3,
        "b": jax.random.normal(k[1], (n_layers, d)) * 0.1,
    }


def sequential(stack, x):
    def body(h, lp):
        return simple_layer(h, lp), None
    out, _ = jax.lax.scan(body, x, stack)
    return out


@pytest.mark.parametrize("n_micro", [4, 8])
def test_pipeline_matches_sequential(n_micro):
    mesh = MeshSpec(pp=4, fsdp=1).build(jax.devices()[:4])
    stack = make_stack()
    x = jax.random.normal(jax.random.PRNGKey(1), (8, 16))
    ref = sequential(stack, x)
    got = pipeline_apply(simple_layer, stack, x, mesh,
                         n_microbatches=n_micro)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_pipeline_gradients_match():
    mesh = MeshSpec(pp=4, fsdp=1).build(jax.devices()[:4])
    stack = make_stack()
    x = jax.random.normal(jax.random.PRNGKey(1), (8, 16))

    g_ref = jax.grad(lambda s: (sequential(s, x) ** 2).sum())(stack)
    g_pp = jax.grad(
        lambda s: (pipeline_apply(simple_layer, s, x, mesh) ** 2).sum())(stack)
    for a, b in zip(jax.tree.leaves(g_pp), jax.tree.leaves(g_ref)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4)


def test_pipeline_llama_layers():
    """Pipeline the real Llama block stack across 2 stages."""
    cfg = llama.CONFIGS["llama_tiny"]
    mesh = MeshSpec(pp=2, fsdp=1).build(jax.devices()[:2])
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0,
                                cfg.vocab_size)
    x = jnp.take(params["embed"], tokens, axis=0)
    from kuberay_tpu.ops.rope import rope_frequencies
    cos, sin = rope_frequencies(cfg.head_dim, 16, cfg.rope_theta)

    def layer(h, lp):
        return llama._layer(cfg, h, lp, cos, sin)

    ref, _ = jax.lax.scan(lambda h, lp: (layer(h, lp), None), x,
                          params["layers"])
    got = pipeline_apply(layer, params["layers"], x, mesh, n_microbatches=2)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-3, atol=2e-3)


def test_pipeline_validation_errors():
    mesh = MeshSpec(pp=4, fsdp=1).build(jax.devices()[:4])
    stack = make_stack(n_layers=6)     # not divisible by 4
    x = jnp.zeros((8, 16))
    with pytest.raises(ValueError):
        pipeline_apply(simple_layer, stack, x, mesh)
    stack = make_stack(n_layers=8)
    with pytest.raises(ValueError):
        pipeline_apply(simple_layer, stack, x, mesh, n_microbatches=3)
