"""Incident forensics engine gate (kuberay_tpu.obs.incident): scripted
triggers open windowed, ranked bundles; the first-deviation ranker is
deterministic (ties lexicographic, byte-identical verdicts across
independent builds); every trigger kind fires from its surface; the
known-cause drills produce bundles whose TOP suspect names the injected
fault; the export is byte-identical across re-runs and the journal hash
is invariant to the engine being mounted; /debug/incidents serves with
the shared ?limit contract; archived bundles round-trip byte-for-byte
through the history replay API; and the flight recorder's timeline
snapshots survive a concurrent-writer hammer (the incident capture path
serializes them outside the lock).
"""

import json
import threading
import urllib.error
import urllib.request

import pytest

from kuberay_tpu.controlplane.store import ObjectStore
from kuberay_tpu.obs.flight import FlightRecorder
from kuberay_tpu.obs.incident import INCIDENT_SCHEMA, IncidentEngine
from kuberay_tpu.sim.clock import VirtualClock
from kuberay_tpu.sim.harness import SimHarness
from kuberay_tpu.sim.scenarios import get_scenario
from kuberay_tpu.utils.metrics import MetricsRegistry


class _Audit:
    """DecisionAudit stand-in: newest-first ring, like the real one."""

    def __init__(self):
        self.entries = []

    def to_list(self):
        return list(self.entries)


class _Steps:
    def __init__(self, verdicts):
        self._verdicts = verdicts

    def stragglers(self):
        return [dict(v) for v in self._verdicts]


class _Quota:
    def __init__(self, decisions):
        self._decisions = decisions

    def debug_snapshot(self):
        return {"decisions": [dict(d) for d in self._decisions]}


# ---------------------------------------------------------------------------
# trigger matrix + ranking, scripted
# ---------------------------------------------------------------------------

def test_alert_trigger_ranks_backend_errors_top_and_dedupes():
    """A fired alert opens exactly one bundle; the backend whose error
    series deviated FIRST outranks everything, linked by the backend
    label; re-delivering the same firing alert opens nothing."""
    clock = VirtualClock(start=0.0)
    reg = MetricsRegistry()
    eng = IncidentEngine(clock=clock, registry=reg)
    reg.inc("tpu_gateway_backend_errors_total", {"backend": "green-svc"})
    assert eng.evaluate() == []                      # t=0: deviation noted
    clock.advance(30.0)
    alert = {"name": "serve-availability", "window": "fast",
             "since": 30.0, "burn_rate": 100.0, "state": "firing",
             "series": {"backend": "green-svc"},
             "exemplar": {"trace_id": "t000042"}}
    opened = eng.evaluate(fired=[alert])
    assert len(opened) == 1
    b = opened[0]
    assert b["schema"] == INCIDENT_SCHEMA and b["id"] == "inc000001"
    assert b["trigger"] == "alert"
    assert b["window"] == {"start": -90.0, "end": 30.0}   # 120s lookback
    top = b["suspects"][0]
    assert top["kind"] == "backend-errors" and top["key"] == "green-svc"
    assert top["linkage"] == 2 and top["lead_s"] == 30.0
    assert b["verdict"] == (
        "gateway errors on backend green-svc began 30.0s before alert; "
        "backend-errors green-svc is the top suspect")
    assert b["alert"]["name"] == "serve-availability"
    assert eng.evaluate(fired=[alert]) == []         # dedupe across ticks
    # The metric side: one bundle counted, a non-zero size gauge.
    counts = dict((tuple(sorted(labels.items())), v) for labels, v
                  in reg.family_snapshot("tpu_incidents_total"))
    assert counts == {(("trigger", "alert"),): 1.0}
    sizes = list(reg.family_snapshot("tpu_incident_bundle_bytes"))
    assert sizes and sizes[0][1] > 100.0


def test_rollback_outranks_its_own_audit_trail():
    """The drill semantics in miniature: the green backend's error
    series deviates BEFORE the gate rolls the ramp back, so it must top
    the ranking — the upgrade's own audit entry (same linkage via the
    entity, later first_ts) stays a consequence, not the cause."""
    clock = VirtualClock(start=0.0)
    reg = MetricsRegistry()
    audit = _Audit()
    eng = IncidentEngine(clock=clock, registry=reg, audit=audit)
    clock.advance(40.0)
    reg.inc("tpu_gateway_backend_errors_total",
            {"backend": "fleet-green-serve-svc"})
    assert eng.evaluate() == []                      # t=40: deviation
    clock.advance(10.0)                              # t=50: the verdict
    audit.entries.append({
        "kind": "upgrade", "action": "rollback", "ts": 50.0,
        "namespace": "default", "service": "fleet", "green_weight": 25,
        "reason": "fast-burn firing",
        "alert": {"series": {"backend": "fleet-green-serve-svc"},
                  "exemplar": {"trace_id": "t000007"}}})
    opened = eng.evaluate()
    assert [b["trigger"] for b in opened] == ["rollback"]
    b = opened[0]
    assert b["entity"] == {"kind": "TpuService", "namespace": "default",
                           "name": "fleet"}
    kinds = [s["kind"] for s in b["suspects"]]
    assert kinds[0] == "backend-errors"
    assert b["suspects"][0]["key"] == "fleet-green-serve-svc"
    assert b["suspects"][0]["lead_s"] == 10.0
    assert "upgrade" in kinds
    upgrade = [s for s in b["suspects"] if s["kind"] == "upgrade"][0]
    # The deliberate design: upgrade deviations carry NO backend label,
    # so the real cause's +2 backend linkage cannot be matched by the
    # ramp's own trail.
    assert upgrade["backend"] == ""
    assert eng.evaluate() == []                      # same verdict: once


def test_ranker_ties_break_lexicographically_byte_identical():
    """Two deviations with identical linkage and first_ts order by
    (kind, key); two independently built engines fed the same script
    emit byte-identical bundles."""
    def build():
        clock = VirtualClock(start=0.0)
        reg = MetricsRegistry()
        eng = IncidentEngine(clock=clock, registry=reg)
        reg.inc("tpu_gateway_backend_errors_total", {"backend": "b-svc"})
        reg.inc("tpu_gateway_backend_errors_total", {"backend": "a-svc"})
        eng.evaluate()
        clock.advance(5.0)
        return eng.evaluate(fired=[{
            "name": "serve-ttft", "window": "fast", "since": 5.0,
            "burn_rate": 20.0, "series": {"backend": "other"}}])[0]

    b1, b2 = build(), build()
    assert [s["key"] for s in b1["suspects"]] == ["a-svc", "b-svc"]
    assert all(s["linkage"] == 0 for s in b1["suspects"])
    assert json.dumps(b1, sort_keys=True) == json.dumps(b2, sort_keys=True)


def test_straggler_trigger_links_entity_and_host():
    clock = VirtualClock(start=20.0)
    eng = IncidentEngine(clock=clock, steps=_Steps([{
        "job": "default/drill", "host": "h3",
        "first_slow_ts": 12.0, "first_slow_step": 4}]))
    opened = eng.evaluate()
    assert [b["trigger"] for b in opened] == ["straggler"]
    b = opened[0]
    assert b["entity"]["name"] == "drill"
    top = b["suspects"][0]
    assert top["kind"] == "straggler" and top["host"] == "h3"
    assert top["linkage"] == 3                       # entity 2 + host 1
    assert b["evidence"]["steps"][0]["host"] == "h3"
    assert eng.evaluate() == []


def test_quota_reclaim_notice_is_both_trigger_and_suspect():
    """A reclaim NOTICE is admitted=True/evict=False yet still opens a
    bundle and ranks as the first deviation — the deadline-cron drill's
    gate depends on the notice, not just the eventual eviction."""
    clock = VirtualClock(start=20.0)
    eng = IncidentEngine(clock=clock, quota=_Quota([{
        "ts": 15.0, "namespace": "default", "name": "hog",
        "kind": "TpuCluster", "reason": "reclaim-noticed",
        "admitted": True, "evict": False, "chips": 8, "tenant": "t1"}]))
    opened = eng.evaluate()
    assert [b["trigger"] for b in opened] == ["quota-reclaim"]
    top = opened[0]["suspects"][0]
    assert top["kind"] == "quota"
    assert top["key"] == "default/hog:reclaim-noticed"
    assert top["linkage"] == 2                       # entity match
    assert eng.evaluate() == []


def test_feed_rows_trigger_preemption_bundles():
    clock = VirtualClock(start=5.0)
    eng = IncidentEngine(clock=clock)
    rows = [{"kind": "preemption-notice", "key": "default/s0",
             "ts": 3.0, "trigger": True,
             "summary": "preemption notice on slice s0"}]
    eng.add_feed(lambda: list(rows))
    opened = eng.evaluate()
    assert [b["trigger"] for b in opened] == ["preemption"]
    top = opened[0]["suspects"][0]
    assert top["kind"] == "preemption-notice" and top["key"] == "default/s0"
    assert eng.evaluate() == []                      # feed re-read, no dup


def test_violation_trigger_dedupes_and_capacity_evicts_oldest():
    clock = VirtualClock(start=0.0)
    eng = IncidentEngine(clock=clock, capacity=2)
    assert len(eng.observe_violations(["invariant-x broke"])) == 1
    assert eng.observe_violations(["invariant-x broke"]) == []
    eng.observe_violations(["invariant-y broke"])
    eng.observe_violations(["invariant-z broke"])
    ids = [b["id"] for b in eng.bundles()]
    assert ids == ["inc000003", "inc000002"]         # newest first, capped
    assert eng.get("inc000001") is None


def test_query_surfaces_return_copies_not_aliases():
    clock = VirtualClock(start=0.0)
    eng = IncidentEngine(clock=clock)
    eng.observe_violations(["inv broke"])
    b = eng.get("inc000001")
    b["detail"] = "mutated"
    b["suspects"].append({"kind": "fake"})
    assert eng.get("inc000001")["detail"] == "inv broke"
    assert eng.get("inc000001")["suspects"] == []
    listing = eng.bundles()
    listing[0]["trigger"] = "mutated"
    assert eng.bundles()[0]["trigger"] == "violation"


# ---------------------------------------------------------------------------
# the known-cause drills: the top suspect must name the injected fault
# ---------------------------------------------------------------------------

@pytest.mark.timeout(300)
def test_dead_green_drill_byte_identical_export_and_hash_invariance():
    """The acceptance gate in one place: the dead-green-upgrade drill's
    rollback bundle top-ranks the dead green backend's error series (not
    the ramp's own audit trail); the export is byte-identical across
    re-runs; and mounting the engine leaves the journal hash untouched."""
    sc = get_scenario("dead-green-upgrade")
    with SimHarness(3, scenario=sc, incidents=True) as h:
        r1 = h.run(sc.default_steps)
        doc1 = h.export_incidents()
    with SimHarness(3, scenario=sc, incidents=True) as h:
        r2 = h.run(sc.default_steps)
        doc2 = h.export_incidents()
    with SimHarness(3, scenario=sc) as h:             # engine off
        r3 = h.run(sc.default_steps)
    assert r1.ok and r2.ok and r3.ok
    assert json.dumps(doc1, sort_keys=True) == \
        json.dumps(doc2, sort_keys=True)
    assert r1.journal_hash == r2.journal_hash == r3.journal_hash
    assert doc1["schema"] == "tpu-incident-export/v1"
    rollbacks = [b for b in doc1["incidents"]
                 if b["trigger"] == "rollback"]
    assert rollbacks, [b["trigger"] for b in doc1["incidents"]]
    tops = [b["suspects"][0] for b in rollbacks if b["suspects"]]
    named = [t for t in tops if t["kind"] == "backend-errors"
             and "serve-svc" in t["key"]]
    assert named, [(t["kind"], t["key"]) for t in tops]
    assert "backend-errors" in \
        [b for b in rollbacks if b["suspects"]][0]["verdict"]


@pytest.mark.timeout(300)
def test_straggler_drill_incident_names_the_slow_host():
    sc = get_scenario("straggler-drill")
    with SimHarness(0, scenario=sc, steps=True, incidents=True) as h:
        res = h.run(sc.default_steps)
        doc = h.export_incidents()
    assert res.ok
    bundles = [b for b in doc["incidents"] if b["trigger"] == "straggler"]
    assert bundles, [b["trigger"] for b in doc["incidents"]]
    top = bundles[0]["suspects"][0]
    assert top["kind"] == "straggler"
    assert top["host"] and top["host"] in bundles[0]["detail"]


@pytest.mark.timeout(300)
def test_preemption_drill_incident_tops_the_notice():
    sc = get_scenario("preemption-drill")
    with SimHarness(0, scenario=sc, incidents=True) as h:
        res = h.run(sc.default_steps)
        doc = h.export_incidents()
    assert res.ok
    bundles = [b for b in doc["incidents"]
               if b["trigger"] == "preemption"]
    assert bundles, [b["trigger"] for b in doc["incidents"]]
    top = bundles[0]["suspects"][0]
    assert top["kind"] == "preemption-notice"


@pytest.mark.timeout(300)
def test_deadline_cron_fleet_incident_tops_the_reclaim():
    sc = get_scenario("deadline-cron-fleet")
    with SimHarness(0, scenario=sc, incidents=True) as h:
        res = h.run(sc.default_steps)
        doc = h.export_incidents()
    assert res.ok
    bundles = [b for b in doc["incidents"]
               if b["trigger"] == "quota-reclaim"]
    assert bundles, [b["trigger"] for b in doc["incidents"]]
    top = bundles[0]["suspects"][0]
    assert top["kind"] == "quota" and "reclaim" in top["key"]


# ---------------------------------------------------------------------------
# serving surface + the shared ?limit contract
# ---------------------------------------------------------------------------

def test_debug_incidents_serves_limits_and_404s():
    from kuberay_tpu.apiserver.server import serve_background
    clock = VirtualClock(start=0.0)
    eng = IncidentEngine(clock=clock)
    for name in ("inv-a", "inv-b", "inv-c"):
        eng.observe_violations([f"{name} broke"])
    srv, url = serve_background(ObjectStore(), incidents=eng)
    try:
        with urllib.request.urlopen(f"{url}/debug/incidents") as resp:
            doc = json.load(resp)
        assert doc["count"] == 3
        assert [r["id"] for r in doc["incidents"]] == \
            ["inc000003", "inc000002", "inc000001"]  # newest first
        assert doc["incidents"][0]["verdict"]
        with urllib.request.urlopen(
                f"{url}/debug/incidents/inc000002") as resp:
            bundle = json.load(resp)
        assert bundle == eng.get("inc000002")
        # The shared ?limit contract: N rows, N<1 clamps to 1, a
        # malformed value falls back to the endpoint default.
        for query, expect in (("?limit=2", 2), ("?limit=0", 1),
                              ("?limit=-3", 1), ("?limit=bogus", 3)):
            with urllib.request.urlopen(
                    f"{url}/debug/incidents{query}") as resp:
                assert len(json.load(resp)["incidents"]) == expect, query
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(f"{url}/debug/incidents/nope")
        assert ei.value.code == 404
    finally:
        srv.shutdown()
    srv, url = serve_background(ObjectStore())       # no engine mounted
    try:
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(f"{url}/debug/incidents")
        assert ei.value.code == 404
    finally:
        srv.shutdown()


def test_debug_limit_contract_on_alert_ring_and_traces():
    """The same ?limit=N plumbing bounds the other list endpoints: the
    alert history ring keeps its NEWEST entries, the trace export its
    newest spans."""
    from kuberay_tpu.apiserver.server import serve_background
    from kuberay_tpu.obs.alerts import AlertEngine, SloSpec
    from kuberay_tpu.obs.trace import Tracer
    clock = VirtualClock(start=0.0)
    reg = MetricsRegistry()
    spec = SloSpec(name="serve-ttft", kind="latency",
                   metric="tpu_serve_request_duration_seconds",
                   labels=(("phase", "ttft"),), threshold_s=0.5,
                   objective=0.99, slow_window_s=300.0, slow_burn=14.0)
    eng = AlertEngine(reg, specs=[spec], clock=clock)
    for _ in range(5):
        reg.observe("tpu_serve_request_duration_seconds", 0.1,
                    {"phase": "ttft"}, buckets=(0.25, 0.5, 1.0))
    eng.evaluate()
    for _ in range(3):                               # 3 flaps, 4 entries each
        clock.advance(10.0)
        for _ in range(5):
            reg.observe("tpu_serve_request_duration_seconds", 1.0,
                        {"phase": "ttft"}, buckets=(0.25, 0.5, 1.0))
        eng.evaluate()
        clock.advance(400.0)
        eng.evaluate()
    tracer = Tracer(clock=clock)
    for i in range(4):
        with tracer.span(f"s{i}"):
            pass
    srv, url = serve_background(ObjectStore(), alerts=eng, tracer=tracer)
    try:
        with urllib.request.urlopen(f"{url}/debug/alerts") as resp:
            full = json.load(resp)["ring"]
        assert len(full) == 12
        with urllib.request.urlopen(
                f"{url}/debug/alerts?limit=2") as resp:
            ring = json.load(resp)["ring"]
        assert ring == full[-2:]                     # newest survive
        with urllib.request.urlopen(
                f"{url}/debug/traces?limit=2") as resp:
            spans = json.load(resp)["spans"]
        assert [s["name"] for s in spans] == ["s2", "s3"]
    finally:
        srv.shutdown()


# ---------------------------------------------------------------------------
# history archive round-trip: served bytes == archived bytes
# ---------------------------------------------------------------------------

def test_incident_archive_roundtrips_byte_identical(tmp_path):
    from kuberay_tpu.history.server import HistoryCollector, HistoryServer
    from kuberay_tpu.history.storage import LocalStorage
    from kuberay_tpu.utils import constants as C
    from tests.test_api_types import make_cluster

    clock = VirtualClock(start=0.0)
    eng = IncidentEngine(clock=clock, steps=_Steps([{
        "job": "default/doomed", "host": "h1",
        "first_slow_ts": 1.0, "first_slow_step": 2}]))
    assert eng.evaluate()                            # entity default/doomed
    store = ObjectStore()
    storage = LocalStorage(str(tmp_path / "arch"))
    col = HistoryCollector(store, storage, incidents=eng)
    store.create(make_cluster(name="doomed").to_dict())
    store.delete(C.KIND_CLUSTER, "doomed")
    col.close()

    archived = storage.get("meta/default/doomed/incidents.json")
    assert archived is not None
    srv, url = HistoryServer(storage).serve_background()
    try:
        with urllib.request.urlopen(
                f"{url}/api/history/incidents/default/doomed") as resp:
            served = resp.read()
        assert served == archived                    # byte-for-byte
        doc = json.loads(served)
        assert doc["incidents"][0]["trigger"] == "straggler"
        assert doc["incidents"][0]["entity"]["name"] == "doomed"
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(
                f"{url}/api/history/incidents/default/nothing")
        assert ei.value.code == 404
    finally:
        srv.shutdown()


# ---------------------------------------------------------------------------
# flight ring snapshots under concurrent writers (the capture path)
# ---------------------------------------------------------------------------

def test_flight_timeline_snapshot_survives_concurrent_hammer():
    """timeline() must hand back COPIES: the incident/debug paths
    serialize the snapshot outside the recorder lock while writers keep
    rotating the ring — a live view would race json.dumps or mutate an
    in-flight response."""
    fr = FlightRecorder(capacity=64)
    stop = threading.Event()
    errors = []

    def hammer():
        i = 0
        try:
            while not stop.is_set():
                fr.record("TpuCluster", "default", "c", "watch",
                          f"d{i}", seq=i)
                i += 1
        except Exception as e:                       # pragma: no cover
            errors.append(e)

    threads = [threading.Thread(target=hammer) for _ in range(4)]
    for t in threads:
        t.start()
    try:
        for _ in range(300):
            snap = fr.timeline("TpuCluster", "default", "c")
            json.dumps(snap)                         # must never race
            assert all(r["type"] == "watch" for r in snap)
    finally:
        stop.set()
        for t in threads:
            t.join()
    assert not errors
    # And the snapshot is a copy, not an alias into the ring.
    snap = fr.timeline("TpuCluster", "default", "c")
    snap[0]["type"] = "mutated"
    assert fr.timeline("TpuCluster", "default", "c")[0]["type"] == "watch"
