"""API types: serialization round-trips, derived fields, conditions."""

from kuberay_tpu.api.common import Condition, ObjectMeta, set_condition
from kuberay_tpu.api.tpucluster import (
    HeadGroupSpec,
    TpuCluster,
    TpuClusterSpec,
    WorkerGroupSpec,
)
from kuberay_tpu.api.tpujob import TpuJob, TpuJobSpec
from kuberay_tpu.api.tpuservice import TpuService
from kuberay_tpu.api.common import Container, PodSpec, PodTemplateSpec
from kuberay_tpu.utils.names import (
    slice_name,
    spec_hash_without_scale,
    truncate_name,
    worker_pod_name,
)


def make_template(image="tpu-runtime:latest"):
    return PodTemplateSpec(
        spec=PodSpec(containers=[Container(name="worker", image=image)])
    )


def make_cluster(name="demo", accelerator="v5p", topology="2x2x2", replicas=1):
    return TpuCluster(
        metadata=ObjectMeta(name=name),
        spec=TpuClusterSpec(
            headGroupSpec=HeadGroupSpec(template=make_template()),
            workerGroupSpecs=[
                WorkerGroupSpec(
                    groupName="workers",
                    accelerator=accelerator,
                    topology=topology,
                    replicas=replicas,
                    maxReplicas=max(replicas, 1),
                    template=make_template(),
                )
            ],
        ),
    )


def test_cluster_roundtrip():
    c = make_cluster()
    d = c.to_dict()
    c2 = TpuCluster.from_dict(d)
    assert c2.to_dict() == d
    assert c2.spec.workerGroupSpecs[0].num_hosts == 2
    assert c2.spec.workerGroupSpecs[0].groupName == "workers"


def test_none_fields_pruned():
    c = make_cluster()
    d = c.to_dict()
    assert "autoscalerOptions" not in d["spec"]
    assert "deletionTimestamp" not in d["metadata"]


def test_job_roundtrip():
    j = TpuJob(
        metadata=ObjectMeta(name="train"),
        spec=TpuJobSpec(entrypoint="python -m train", clusterSpec=make_cluster().spec),
    )
    d = j.to_dict()
    j2 = TpuJob.from_dict(d)
    assert j2.spec.clusterSpec.workerGroupSpecs[0].accelerator == "v5p"
    assert j2.to_dict() == d


def test_worker_group_num_hosts_derived():
    g = WorkerGroupSpec(groupName="g", accelerator="v5e", topology="4x4")
    assert g.num_hosts == 4  # GKE multi-host v5e: 4-chip VMs
    g2 = WorkerGroupSpec(groupName="g", accelerator="v5e", topology="2x2")
    assert g2.num_hosts == 1


def test_set_condition_transitions():
    conds = []
    changed = set_condition(conds, Condition(type="Ready", status="True", reason="AllUp"))
    assert changed and len(conds) == 1
    t0 = conds[0].lastTransitionTime
    # Same status+reason+message: no change, timestamp preserved.
    assert not set_condition(conds, Condition(type="Ready", status="True", reason="AllUp"))
    assert conds[0].lastTransitionTime == t0
    # Same status, new reason: changed but transition time preserved.
    assert set_condition(conds, Condition(type="Ready", status="True", reason="Other"))
    assert conds[0].lastTransitionTime == t0
    # Status flip: transition time moves.
    assert set_condition(conds, Condition(type="Ready", status="False", reason="Down"))
    assert conds[0].lastTransitionTime >= t0


def test_truncate_name_stable():
    long = "a" * 100
    t1, t2 = truncate_name(long), truncate_name(long)
    assert t1 == t2 and len(t1) == 63
    assert truncate_name("short") == "short"
    assert len(worker_pod_name("c" * 60, "group", 10, 3)) <= 63
    assert slice_name("c", "g", 0) == "c-g-0"


def test_spec_hash_ignores_scale():
    c1 = make_cluster(replicas=1)
    c2 = make_cluster(replicas=5)
    c2.spec.workerGroupSpecs[0].scaleStrategy.slicesToDelete = ["x"]
    assert spec_hash_without_scale(c1.spec.to_dict()) == \
        spec_hash_without_scale(c2.spec.to_dict())
    # But a real spec change (image) changes the hash.
    c3 = make_cluster()
    c3.spec.workerGroupSpecs[0].template.spec.containers[0].image = "other:img"
    assert spec_hash_without_scale(c1.spec.to_dict()) != \
        spec_hash_without_scale(c3.spec.to_dict())
