"""Shard-invariant gate (ISSUE 6): hash-sharded reconcile pools.

The contract under test, in three parts:

1. **Global per-key serialization survives sharding** — a key hashes to
   exactly one pool, so no key is ever processed concurrently across
   pools, even under a multi-worker stress storm with hot keys.
2. **Stable assignment** — shard_of is a pure crc32 of the key: stable
   under requeue (add_after routes to the same pool) and across
   processes (pinned golden values).
3. **Per-shard lease handoff drains cleanly** — releasing a shard
   pauses its pool, waits out in-flight keys, and never disturbs the
   other shards; the ShardLeaseElector moves leases with the same
   no-overlap guarantee.
"""

import random
import threading
import time
from collections import defaultdict

from kuberay_tpu.controlplane.leader import (
    ShardLeaseElector,
    shard_lease_name,
)
from kuberay_tpu.controlplane.manager import Manager
from kuberay_tpu.controlplane.sharding import ShardedQueuePool, shard_of
from kuberay_tpu.controlplane.store import ObjectStore


def k(name, kind="TpuCluster", ns="default"):
    return (kind, ns, name)


# ---------------------------------------------------------------------------
# stable assignment
# ---------------------------------------------------------------------------

def test_shard_of_is_stable_and_in_range():
    keys = [k(f"c-{i}") for i in range(200)]
    for key in keys:
        s = shard_of(key, 4)
        assert 0 <= s < 4
        # Pure function: identical on every call (requeue stability).
        assert all(shard_of(key, 4) == s for _ in range(5))
    # Spread: 200 keys over 4 shards never collapse onto one pool.
    buckets = {shard_of(key, 4) for key in keys}
    assert buckets == {0, 1, 2, 3}


def test_shard_of_golden_values_cross_process_contract():
    """crc32, not hash(): these exact values must hold in ANY process —
    per-shard lease ownership depends on every replica agreeing."""
    assert shard_of(("TpuCluster", "default", "storm-0001"), 4) == \
        shard_of(("TpuCluster", "default", "storm-0001"), 4)
    import zlib
    for key in [("TpuCluster", "default", "a"), ("Pod", "ns2", "w-17")]:
        want = zlib.crc32(f"{key[0]}/{key[1]}/{key[2]}".encode()) % 4
        assert shard_of(key, 4) == want
    assert shard_of(("TpuCluster", "default", "x"), 1) == 0


def test_pool_routes_requeues_to_same_shard():
    now = [0.0]
    pool = ShardedQueuePool(4, now_fn=lambda: now[0])
    key = k("requeue-me")
    home = pool.shard_of(key)
    pool.add_after(key, 5.0)
    now[0] = 5.0
    for i in range(4):
        got = pool.get(i, block=False)
        if got is not None:
            assert i == home and got == key
            pool.done(got)
    # And the immediate path lands on the same pool.
    pool.add(key)
    assert pool.get(home, block=False) == key


# ---------------------------------------------------------------------------
# global per-key serialization across pools (stress)
# ---------------------------------------------------------------------------

def test_stress_no_key_processed_concurrently_across_pools():
    """4 shards x 2 pinned workers each, producers hammering hot keys:
    a per-key in-flight counter proves global per-key serialization,
    and a generation check proves nothing is lost to coalescing."""
    pool = ShardedQueuePool(4)
    hot = [k(f"hot-{i}") for i in range(10)]
    adds = defaultdict(int)
    seen = defaultdict(int)
    inflight = defaultdict(int)
    processed = defaultdict(int)
    violations = []
    wrong_pool = []
    state_lock = threading.Lock()

    def producer(seed):
        rng = random.Random(seed)
        for _ in range(300):
            key = rng.choice(hot)
            with state_lock:
                adds[key] += 1
            pool.add(key)
            if rng.random() < 0.05:
                time.sleep(0.0005)

    def worker(shard):
        while True:
            key = pool.get(shard, block=True)
            if key is None:
                return
            if pool.shard_of(key) != shard:
                wrong_pool.append((key, shard))
            with state_lock:
                inflight[key] += 1
                if inflight[key] > 1:
                    violations.append(key)
                gen = adds[key]
            time.sleep(0.0002)
            with state_lock:
                seen[key] = max(seen[key], gen)
                processed[key] += 1
                inflight[key] -= 1
            pool.done(key)

    workers = [threading.Thread(target=worker, args=(s,))
               for s in range(4) for _ in range(2)]
    producers = [threading.Thread(target=producer, args=(s,))
                 for s in range(4)]
    for t in workers + producers:
        t.start()
    for t in producers:
        t.join(timeout=30.0)
    deadline = time.time() + 30.0
    while time.time() < deadline:
        if pool.depth() == 0 and not any(
                q._processing or q._dirty for q in pool.queues):
            break
        time.sleep(0.005)
    pool.shutdown()
    for t in workers:
        t.join(timeout=10.0)

    assert not violations, \
        f"keys processed concurrently across pools: {set(violations)}"
    assert not wrong_pool, f"keys on a foreign pool: {wrong_pool}"
    for key in hot:
        assert processed[key] >= 1, f"{key} never processed"
        assert seen[key] == adds[key], \
            f"{key}: last pass saw generation {seen[key]} of {adds[key]}"


# ---------------------------------------------------------------------------
# manager-level sharding
# ---------------------------------------------------------------------------

def test_manager_shards_1_is_the_classic_queue():
    store = ObjectStore()
    m = Manager(store)
    assert m.shards == 1
    order = []
    m.register("Thing", lambda name, ns: order.append(name) or None)
    for name in ("c", "a", "b"):
        m.enqueue(("Thing", "default", name))
    m.run_until_idle()
    assert order == ["c", "a", "b"]     # FIFO, exactly the old behavior


def test_manager_sharded_run_until_idle_processes_everything():
    store = ObjectStore()
    m = Manager(store, shards=4)
    seen = set()
    m.register("Thing", lambda name, ns: seen.add(name) or None)
    names = [f"obj-{i}" for i in range(40)]
    for name in names:
        m.enqueue(("Thing", "default", name))
    m.run_until_idle()
    assert seen == set(names)


def test_manager_sharded_workers_are_pinned(monkeypatch):
    """start(workers=1) on 3 shards: every processed key ran on the
    worker thread pinned to its home shard."""
    store = ObjectStore()
    m = Manager(store, shards=3)
    mismatches = []
    done = threading.Event()
    total = 30
    count = [0]

    def reconcile(name, ns):
        key = ("Thing", ns, name)
        tname = threading.current_thread().name
        want = f"reconciler-s{m.shard_of(key)}-0"
        if tname != want:
            mismatches.append((key, tname, want))
        count[0] += 1
        if count[0] >= total:
            done.set()
        return None

    m.register("Thing", reconcile)
    m.start(workers=1)
    try:
        for i in range(total):
            m.enqueue(("Thing", "default", f"obj-{i}"))
        assert done.wait(timeout=10.0), f"only {count[0]}/{total} ran"
    finally:
        m.stop()
    assert not mismatches, mismatches[:5]


def test_release_shard_drains_in_flight_and_spares_other_shards():
    store = ObjectStore()
    m = Manager(store, shards=2)
    in_flight = threading.Event()
    release_gate = threading.Event()
    processed = []
    lock = threading.Lock()

    def reconcile(name, ns):
        key = ("Thing", ns, name)
        with lock:
            processed.append((m.shard_of(key), name,
                              time.monotonic()))
        if name == "slow":
            in_flight.set()
            release_gate.wait(timeout=10.0)
        return None

    m.register("Thing", reconcile)
    # Find names on distinct shards.
    shard_names = {}
    i = 0
    while len(shard_names) < 2:
        name = f"probe-{i}"
        shard_names.setdefault(
            m.shard_of(("Thing", "default", name)), name)
        i += 1
    slow_shard = m.shard_of(("Thing", "default", "slow"))
    other_shard = next(s for s in (0, 1) if s != slow_shard)

    m.start(workers=1)
    try:
        m.enqueue(("Thing", "default", "slow"))
        assert in_flight.wait(timeout=5.0)
        # Queue more work behind the in-flight key on the same shard.
        n_queued = 0
        for j in range(40):
            key = ("Thing", "default", f"later-{j}")
            if m.shard_of(key) == slow_shard:
                m.enqueue(key)
                n_queued += 1

        result = {}

        def releaser():
            result["drained"] = m.release_shard(slow_shard,
                                                drain_timeout=10.0)
            result["at"] = time.monotonic()

        t = threading.Thread(target=releaser)
        t.start()
        time.sleep(0.1)
        assert "drained" not in result     # blocked on the in-flight key
        release_gate.set()                 # let the reconcile finish
        t.join(timeout=10.0)
        assert result.get("drained") is True

        # Nothing processed on the released shard after the drain
        # returned, and the queued backlog stayed parked.
        time.sleep(0.2)
        with lock:
            late = [p for p in processed
                    if p[0] == slow_shard and p[2] > result["at"]]
        assert late == [], late
        # The other shard keeps reconciling.
        m.enqueue(("Thing", "default", shard_names[other_shard]))
        deadline = time.time() + 5.0
        while time.time() < deadline:
            with lock:
                if any(p[0] == other_shard and
                       p[1] == shard_names[other_shard]
                       for p in processed):
                    break
            time.sleep(0.02)
        with lock:
            assert any(p[0] == other_shard and
                       p[1] == shard_names[other_shard]
                       for p in processed)

        # Re-acquiring resumes the parked backlog (level-triggered).
        with lock:
            before = len([p for p in processed if p[0] == slow_shard])
        relisted = m.acquire_shard(slow_shard)
        assert relisted >= 0
        deadline = time.time() + 5.0
        while time.time() < deadline:
            with lock:
                after = len([p for p in processed
                             if p[0] == slow_shard])
            if after >= before + n_queued:
                break
            time.sleep(0.02)
        assert after >= before + n_queued
    finally:
        release_gate.set()
        m.stop()


# ---------------------------------------------------------------------------
# per-shard leases
# ---------------------------------------------------------------------------

def test_shard_lease_split_with_max_owned():
    """Two replicas, 4 shards, max_owned=2 each: the fleet converges to
    an even split with every lease held by exactly one identity."""
    store = ObjectStore()
    acquired = defaultdict(set)
    a = ShardLeaseElector(store, 4, identity="rep-a", max_owned=2,
                          lease_duration=30.0,
                          on_acquired=lambda s: acquired["a"].add(s),
                          on_released=lambda s: acquired["a"].discard(s))
    b = ShardLeaseElector(store, 4, identity="rep-b", max_owned=2,
                          lease_duration=30.0,
                          on_acquired=lambda s: acquired["b"].add(s),
                          on_released=lambda s: acquired["b"].discard(s))
    for _ in range(3):
        a.tick()
        b.tick()
    assert len(a.owned()) == 2 and len(b.owned()) == 2
    assert a.owned() | b.owned() == {0, 1, 2, 3}
    assert a.owned() & b.owned() == set()
    for shard in range(4):
        lease = store.get("Lease", shard_lease_name(shard))
        holder = lease["spec"]["holderIdentity"]
        assert holder in ("rep-a", "rep-b")
        assert shard in (a.owned() if holder == "rep-a" else b.owned())


def test_shard_lease_handoff_on_release_and_expiry():
    store = ObjectStore()
    a = ShardLeaseElector(store, 2, identity="rep-a", lease_duration=30.0)
    a.tick()
    assert a.owned() == {0, 1}
    # Voluntary shed: renewTime zeroed, peer absorbs immediately.
    a.release_shard(0)
    assert a.owned() == {1}
    b = ShardLeaseElector(store, 2, identity="rep-b", max_owned=1,
                          lease_duration=30.0)
    b.tick()
    assert b.owned() == {0}
    # Expiry takeover: rep-a dies (stops renewing); with the duration
    # elapsed, rep-b (cap lifted) absorbs shard 1 too.
    b.max_owned = None
    lease = store.get("Lease", shard_lease_name(1))
    lease["spec"]["renewTime"] = 0.0
    store.update(lease)
    b.tick()
    assert b.owned() == {0, 1}


def test_shard_lease_elector_drives_manager_ownership():
    """The operator wiring end-to-end: elector callbacks flip Manager
    shard ownership, and a lost lease pauses that pool."""
    store = ObjectStore()
    m = Manager(store, shards=2)
    for shard in range(2):
        m.release_shard(shard)
    assert m.owned_shards() == set()
    elector = ShardLeaseElector(store, 2, identity="rep-a",
                                lease_duration=30.0,
                                on_acquired=m.acquire_shard,
                                on_released=m.release_shard)
    elector.tick()
    assert m.owned_shards() == {0, 1}
    elector.release_shard(0)
    assert m.owned_shards() == {1}
    # The released pool is paused: keys park instead of being handed out.
    probe = None
    for i in range(20):
        key = ("Thing", "default", f"p-{i}")
        if m.shard_of(key) == 0:
            probe = key
            break
    assert probe is not None
    m.register("Thing", lambda name, ns: None)
    m.enqueue(probe)
    assert m._pool.get(0, block=False) is None
