"""Slice autoscaler: decision core + end-to-end protocol with the cluster
controller (ref e2eautoscaler scale-up/down specs, in slice units)."""

import pytest

from kuberay_tpu.api.tpucluster import AutoscalerOptions
from kuberay_tpu.controlplane.autoscaler import (
    DecisionAudit,
    SliceAutoscaler,
    SliceInfo,
    apply_decisions,
    decide,
)
from kuberay_tpu.utils import constants as C
from tests.test_api_types import make_cluster
from tests.test_cluster_controller import Harness


def make_autoscaling_cluster(replicas=1, min_r=0, max_r=4):
    c = make_cluster(accelerator="v5p", topology="2x2x2", replicas=replicas)
    c.spec.enableInTreeAutoscaling = True
    c.spec.autoscalerOptions = AutoscalerOptions(idleTimeoutSeconds=0)
    g = c.spec.workerGroupSpecs[0]
    g.minReplicas, g.maxReplicas = min_r, max_r
    return c


def test_decide_upscale_default_one_step():
    c = make_autoscaling_cluster(replicas=1)
    d = decide(c, demand={"workers": 4}, slices=[])
    assert len(d) == 1 and d[0].replicas == 2  # one slice per pass


def test_decide_upscale_aggressive():
    c = make_autoscaling_cluster(replicas=1)
    d = decide(c, demand={"workers": 3}, slices=[], upscaling_mode="Aggressive")
    assert d[0].replicas == 3


def test_decide_upscale_clamped_to_max():
    c = make_autoscaling_cluster(replicas=3, max_r=3)
    assert decide(c, demand={"workers": 9}, slices=[]) == []


def test_decide_downscale_names_idle_victims():
    c = make_autoscaling_cluster(replicas=3, min_r=1)
    slices = [
        SliceInfo("s0", "workers", ready=True, idle_seconds=300),
        SliceInfo("s1", "workers", ready=True, idle_seconds=10),
        SliceInfo("s2", "workers", ready=True, idle_seconds=600),
    ]
    d = decide(c, demand={"workers": 1}, slices=slices, idle_timeout=60)
    assert d[0].replicas == 1
    assert set(d[0].slices_to_delete) == {"s0", "s2"}  # only idle ones


def test_decide_respects_min_replicas():
    c = make_autoscaling_cluster(replicas=2, min_r=2)
    slices = [SliceInfo(f"s{i}", "workers", True, 999) for i in range(2)]
    assert decide(c, demand={}, slices=slices, idle_timeout=60) == []


def test_end_to_end_scale_cycle():
    """Autoscaler patches the CR; the cluster controller executes it."""
    h = Harness()
    c = make_autoscaling_cluster(replicas=1)
    h.store.create(c.to_dict())
    h.settle()
    assert len(h.pods(**{C.LABEL_NODE_TYPE: "worker"})) == 2

    auto = SliceAutoscaler(h.store)
    # Upscale: pretend demand wants 2 slices.
    cluster = h.cluster()
    decisions = decide(cluster, demand={"workers": 2}, slices=[])
    assert apply_decisions(h.store, "demo", "default", decisions)
    h.settle()
    assert len(h.pods(**{C.LABEL_NODE_TYPE: "worker"})) == 4
    assert h.cluster().status.readySlices == 2

    # Downscale: both slices idle, demand zero -> min (0).
    cluster = h.cluster()
    slices = [SliceInfo(f"demo-workers-{i}", "workers", True, 999)
              for i in range(2)]
    decisions = decide(cluster, demand={}, slices=slices, idle_timeout=60)
    assert apply_decisions(h.store, "demo", "default", decisions)
    h.settle()
    assert len(h.pods(**{C.LABEL_NODE_TYPE: "worker"})) == 0


def test_executed_victims_cleared_from_spec():
    """Stale slicesToDelete entries must not re-kill recreated slices."""
    h = Harness()
    c = make_autoscaling_cluster(replicas=2)
    h.store.create(c.to_dict())
    h.settle()
    obj = h.store.get(C.KIND_CLUSTER, "demo")
    obj["spec"]["workerGroupSpecs"][0]["replicas"] = 1
    obj["spec"]["workerGroupSpecs"][0]["scaleStrategy"] = {
        "slicesToDelete": ["demo-workers-1"]}
    h.store.update(obj)
    h.settle()
    spec = h.store.get(C.KIND_CLUSTER, "demo")["spec"]
    assert spec["workerGroupSpecs"][0].get("scaleStrategy", {}).get(
        "slicesToDelete", []) == []
    # Scale back up: index 1 is recreated and SURVIVES (no stale victim).
    obj = h.store.get(C.KIND_CLUSTER, "demo")
    obj["spec"]["workerGroupSpecs"][0]["replicas"] = 2
    h.store.update(obj)
    h.settle()
    assert h.cluster().status.readySlices == 2


def test_slice_autoscaler_demand_from_jobs():
    """Demand derives from live TpuJobs bound to the cluster."""
    h = Harness()
    c = make_autoscaling_cluster(replicas=1)
    h.store.create(c.to_dict())
    h.settle()
    # A running job wants 3 slices of group "workers" on this cluster.
    h.store.create({
        "apiVersion": C.API_VERSION, "kind": C.KIND_JOB,
        "metadata": {"name": "big", "namespace": "default"},
        "spec": {"entrypoint": "x", "clusterSpec": {
            "workerGroupSpecs": [{"groupName": "workers", "replicas": 3}]}},
        "status": {"clusterName": "demo", "jobDeploymentStatus": "Running"},
    })
    auto = SliceAutoscaler(h.store)
    assert auto.reconcile("demo")
    h.settle()
    assert h.cluster().spec.workerGroupSpecs[0].replicas == 2  # one step
    assert auto.reconcile("demo")
    h.settle()
    assert h.cluster().spec.workerGroupSpecs[0].replicas == 3


def test_decision_audit_records_signals_and_verdict():
    """Every applied decision lands in the bounded audit ring — input
    signals (demand, per-slice idleness) next to the verdict — and
    increments tpu_autoscaler_decisions_total{kind,direction}."""
    from kuberay_tpu.utils.metrics import ControlPlaneMetrics

    h = Harness()
    c = make_autoscaling_cluster(replicas=1)
    h.store.create(c.to_dict())
    h.settle()
    h.store.create({
        "apiVersion": C.API_VERSION, "kind": C.KIND_JOB,
        "metadata": {"name": "big", "namespace": "default"},
        "spec": {"entrypoint": "x", "clusterSpec": {
            "workerGroupSpecs": [{"groupName": "workers", "replicas": 3}]}},
        "status": {"clusterName": "demo", "jobDeploymentStatus": "Running"},
    })
    metrics = ControlPlaneMetrics()
    audit = DecisionAudit(metrics=metrics)
    auto = SliceAutoscaler(h.store, audit=audit)
    assert auto.reconcile("demo")
    assert len(audit) == 1 and audit.total == 1
    entry = audit.to_list()[0]
    assert entry["cluster"] == "demo" and entry["group"] == "workers"
    assert entry["direction"] == "up"
    assert entry["replicas_before"] == 1 and entry["replicas_after"] == 2
    assert entry["applied"] is True
    assert entry["signals"]["demand"] == 3
    assert "slices" in entry["signals"]
    text = metrics.render()
    assert ('tpu_autoscaler_decisions_total{direction="up",'
            'kind="TpuCluster"} 1.0') in text

    # Downscale decisions audit with the idle-slice evidence.
    h.settle()
    h.store.delete(C.KIND_JOB, "big")
    cluster = h.cluster()
    slices = [SliceInfo(f"demo-workers-{i}", "workers", True, 999)
              for i in range(2)]
    decisions = decide(cluster, demand={}, slices=slices, idle_timeout=60)
    for d in decisions:
        audit.record("default", "demo", d, current=2, demand={},
                     slices=slices, applied=False)
    down = audit.to_list()[0]              # newest first
    assert down["direction"] == "down"
    assert down["slices_to_delete"]
    assert down["signals"]["slices"][0]["idle_seconds"] == 999
    assert ('tpu_autoscaler_decisions_total{direction="down",'
            'kind="TpuCluster"} 1.0') in metrics.render()


def test_decision_audit_ring_is_bounded():
    audit = DecisionAudit(capacity=4)
    from kuberay_tpu.controlplane.autoscaler import GroupDecision
    for i in range(10):
        audit.record("default", "demo",
                     GroupDecision("workers", i + 1, [], "test"),
                     current=i, demand={}, slices=[], applied=False)
    assert len(audit) == 4 and audit.total == 10
    newest = audit.to_list()[0]
    assert newest["replicas_after"] == 10


@pytest.mark.timeout(60)
def test_sidecar_live_process_patches_replicas():
    """The builder's injected command (`python -m
    kuberay_tpu.autoscaler.sidecar`, builders/pod.py) must be a real
    module that runs against the REST store and patches replicas — the
    ref's autoscaler-sidecar protocol (common/pod.go:736) end to end."""
    import os
    import subprocess
    import sys

    from kuberay_tpu.apiserver.server import serve_background
    from kuberay_tpu.controlplane.store import ObjectStore

    backing = ObjectStore()
    srv, url = serve_background(backing)
    try:
        backing.create(make_autoscaling_cluster(replicas=1).to_dict())
        backing.create({
            "apiVersion": C.API_VERSION, "kind": C.KIND_JOB,
            "metadata": {"name": "big", "namespace": "default"},
            "spec": {"entrypoint": "x", "clusterSpec": {
                "workerGroupSpecs": [{"groupName": "workers",
                                      "replicas": 3}]}},
            "status": {"clusterName": "demo",
                       "jobDeploymentStatus": "Running"},
        })
        out = subprocess.run(
            [sys.executable, "-m", "kuberay_tpu.autoscaler.sidecar",
             "--cluster", "demo", "--namespace", "default",
             "--apiserver", url, "--once"],
            capture_output=True, text=True, timeout=45,
            env={**os.environ, "TPU_AUTOSCALER_IDLE_TIMEOUT": "0"})
        assert out.returncode == 0, out.stdout + out.stderr
        assert "patched demo" in out.stdout, out.stdout + out.stderr
        # The decision audit emits each verdict as a JSON log line.
        assert "autoscaler decision:" in out.stdout, out.stdout
        obj = backing.get(C.KIND_CLUSTER, "demo")
        assert obj["spec"]["workerGroupSpecs"][0]["replicas"] == 2
    finally:
        srv.shutdown()


def test_builder_sidecar_command_is_runnable():
    """The exact command the pod builder injects must import (this is the
    regression the round-2 judge flagged: a crash-looping sidecar)."""
    import importlib

    from kuberay_tpu.builders.pod import build_autoscaler_container
    from tests.test_api_types import make_cluster

    c = make_cluster()
    cmd = build_autoscaler_container(c)["command"]
    assert cmd[:2] == ["python", "-m"]
    mod = importlib.import_module(cmd[2])
    assert hasattr(mod, "main")


def test_per_group_idle_timeout_override():
    """WorkerGroupSpec.idleTimeoutSeconds (ref autoscaler v2): a group
    with its own timeout scales down on ITS clock; 0 inherits the
    cluster-level timeout."""
    from kuberay_tpu.controlplane.autoscaler import SliceInfo, decide
    from tests.test_api_types import make_cluster

    c = make_cluster(accelerator="v5e", topology="2x2", replicas=2)
    c.spec.enableInTreeAutoscaling = True
    g2 = __import__("copy").deepcopy(c.spec.workerGroupSpecs[0])
    g2.groupName = "fast-reap"
    g2.idleTimeoutSeconds = 5
    c.spec.workerGroupSpecs.append(g2)

    slices = [
        SliceInfo("w-s0", "workers", True, idle_seconds=30),
        SliceInfo("w-s1", "workers", True, idle_seconds=30),
        SliceInfo("f-s0", "fast-reap", True, idle_seconds=30),
        SliceInfo("f-s1", "fast-reap", True, idle_seconds=30),
    ]
    # Cluster-level timeout 60: default group NOT idle long enough; the
    # override group (5s) reaps.
    out = {d.group: d for d in decide(c, {}, slices, idle_timeout=60.0)}
    assert "workers" not in out
    assert out["fast-reap"].replicas == 0
    assert sorted(out["fast-reap"].slices_to_delete) == ["f-s0", "f-s1"]


def test_idle_timeout_validation():
    from kuberay_tpu.utils.validation import validate_cluster
    from tests.test_api_types import make_cluster

    c = make_cluster()
    c.spec.workerGroupSpecs[0].idleTimeoutSeconds = 30
    assert any("autoscaling is not enabled" in e
               for e in validate_cluster(c))
    c.spec.enableInTreeAutoscaling = True
    c.spec.workerGroupSpecs[0].maxReplicas = 4
    assert validate_cluster(c) == []
    c.spec.workerGroupSpecs[0].idleTimeoutSeconds = -1
    assert any("idleTimeoutSeconds must be >= 0" in e
               for e in validate_cluster(c))
