"""SLO burn-rate alert engine gate (kuberay_tpu.obs.alerts): scripted
breaches under a virtual clock fire at EXACT virtual times and clear
when the breaching events age out of their window, the latency/
availability/gauge-floor readers count the right events, alerts
cross-link to trace exemplars and flight rings, the history ring is
bounded, /debug/alerts serves (and 404s when absent), and evaluating
under simulation leaves the replay hash byte-identical — the same
observational contract the tracer obeys.
"""

import json
import urllib.error
import urllib.request

import pytest

from kuberay_tpu.controlplane.store import ObjectStore
from kuberay_tpu.obs.alerts import AlertEngine, SloSpec, default_slos
from kuberay_tpu.sim.clock import VirtualClock
from kuberay_tpu.sim.harness import SimHarness
from kuberay_tpu.sim.scenarios import get_scenario
from kuberay_tpu.utils.metrics import MetricsRegistry

TTFT_BUCKETS = (0.25, 0.5, 1.0, 2.0)


def _ttft_spec(**overrides):
    base = dict(name="serve-ttft", kind="latency",
                metric="tpu_serve_request_duration_seconds",
                labels=(("phase", "ttft"),), threshold_s=0.5,
                objective=0.99)
    base.update(overrides)
    return SloSpec(**base)


def _observe_ttft(reg, value, n, exemplar=None, exemplar_ts=None):
    for _ in range(n):
        reg.observe("tpu_serve_request_duration_seconds", value,
                    {"phase": "ttft"}, buckets=TTFT_BUCKETS,
                    exemplar=exemplar, exemplar_ts=exemplar_ts)


# ---------------------------------------------------------------------------
# the scripted-breach acceptance: exact fire and clear times
# ---------------------------------------------------------------------------

def test_fast_burn_fires_once_at_exact_time_and_clears_on_window():
    """A scripted TTFT breach: the fast-window alert fires exactly once
    at the breach's evaluation instant, stays ONE alert while burning,
    and resolves at the first evaluation after the bad events age past
    the fast window — all in exact virtual time."""
    clock = VirtualClock(start=0.0)
    reg = MetricsRegistry()
    eng = AlertEngine(reg, specs=[_ttft_spec()], clock=clock)

    _observe_ttft(reg, 0.1, 6)                       # healthy baseline
    assert eng.evaluate() == []                      # t=0

    clock.advance(10.0)                              # t=10: the breach
    _observe_ttft(reg, 1.0, 5)
    fired = eng.evaluate()
    fast = [a for a in fired if a["window"] == "fast"]
    assert len(fast) == 1
    alert = fast[0]
    assert alert["name"] == "serve-ttft"
    assert alert["state"] == "firing"
    assert alert["since"] == 10.0                    # the exact instant
    # 5 bad of 5 new events against a 1% budget: burn rate 100.
    assert alert["burn_rate"] == pytest.approx(100.0)
    assert alert["bad"] == 5 and alert["total"] == 5
    # The same breach saturates the slow window too (burn 100 >= 6).
    assert {a["window"] for a in fired} == {"fast", "slow"}

    clock.advance(10.0)                              # t=20: still burning
    assert eng.evaluate() == []                      # no re-fire
    assert len([a for a in eng.active()
                if a["window"] == "fast"]) == 1

    clock.advance(380.0)                             # t=400: bad events
    assert eng.evaluate() == []                      # aged out of 300s
    active_windows = {a["window"] for a in eng.active()}
    assert "fast" not in active_windows              # fast resolved...
    assert "slow" in active_windows                  # ...slow still burns
    resolved = [r for r in eng.to_dict()["ring"]
                if r["state"] == "resolved" and r["window"] == "fast"]
    assert len(resolved) == 1
    assert resolved[0]["resolved_at"] == 400.0       # the exact instant

    clock.advance(3600.0)                            # t=4000: slow window
    eng.evaluate()                                   # drained too
    assert eng.active() == []
    states = [(r["window"], r["state"]) for r in eng.to_dict()["ring"]]
    assert states == [("fast", "firing"), ("slow", "firing"),
                      ("fast", "resolved"), ("slow", "resolved")]


def test_min_samples_guard_never_fires_on_thin_data():
    clock = VirtualClock(start=0.0)
    reg = MetricsRegistry()
    eng = AlertEngine(reg, specs=[_ttft_spec()], clock=clock)
    _observe_ttft(reg, 2.0, 3)                       # 100% bad, but 3 < 5
    for _ in range(4):
        eng.evaluate()
        clock.advance(30.0)
    assert eng.active() == [] and eng.to_dict()["ring"] == []


# ---------------------------------------------------------------------------
# the other spec kinds
# ---------------------------------------------------------------------------

def test_availability_counts_sheds_and_5xx_against_total():
    clock = VirtualClock(start=0.0)
    reg = MetricsRegistry()
    spec = SloSpec(name="serve-availability", kind="availability",
                   total_family="tpu_gateway_requests_total",
                   bad_families=("tpu_gateway_shed_total",),
                   objective=0.99)
    eng = AlertEngine(reg, specs=[spec], clock=clock)
    for _ in range(20):
        reg.inc("tpu_gateway_requests_total",
                {"backend": "a", "code": "200"})
    assert eng.evaluate() == []                      # baseline sample

    clock.advance(10.0)
    for _ in range(5):
        reg.inc("tpu_gateway_requests_total",
                {"backend": "a", "code": "200"})
    for _ in range(2):
        reg.inc("tpu_gateway_requests_total",
                {"backend": "a", "code": "500"})
    for _ in range(3):
        reg.inc("tpu_gateway_shed_total", {"reason": "queue_full"})
    fired = eng.evaluate()
    fast = [a for a in fired if a["window"] == "fast"]
    assert len(fast) == 1
    # 5 bad (2 x 5xx + 3 sheds) over 7 new requests, 1% budget.
    assert fast[0]["bad"] == 5 and fast[0]["total"] == 7
    assert fast[0]["burn_rate"] == pytest.approx((5 / 7) / 0.01, rel=1e-3)


def test_gauge_floor_fires_slow_window_with_flight_link():
    """The stock goodput-ratio spec (objective 0.9) tops out at burn 10
    — below the fast threshold (14), above the slow one (6): a starved
    CR pages through the slow window only, linking to its flight ring."""
    clock = VirtualClock(start=0.0)
    reg = MetricsRegistry()
    spec = [s for s in default_slos() if s.name == "goodput-ratio"][0]
    eng = AlertEngine(reg, specs=[spec], clock=clock)
    labels = {"kind": "TpuCluster", "namespace": "default", "name": "demo"}
    reg.set_gauge("tpu_goodput_ratio", 0.2, labels)
    fired = []
    for _ in range(7):
        fired.extend(eng.evaluate())
        clock.advance(10.0)
    assert len(fired) == 1
    alert = fired[0]
    assert alert["window"] == "slow"                 # fast can't trigger
    assert alert["since"] == 50.0                    # 6th tick: 5 deltas
    assert alert["links"]["flight"] == \
        "/debug/flight/TpuCluster/default/demo"

    reg.set_gauge("tpu_goodput_ratio", 0.95, labels)     # recovery
    clock.advance(3700.0)
    eng.evaluate()
    assert eng.active() == []


def test_latency_alert_links_to_offending_exemplar_trace():
    clock = VirtualClock(start=0.0)
    reg = MetricsRegistry()
    eng = AlertEngine(reg, specs=[_ttft_spec()], clock=clock,
                      audit=object())
    _observe_ttft(reg, 0.1, 5)
    eng.evaluate()
    clock.advance(10.0)
    _observe_ttft(reg, 1.5, 5, exemplar="t000777", exemplar_ts=10.0)
    fired = eng.evaluate()
    links = [a for a in fired if a["window"] == "fast"][0]["links"]
    # The link lands on the nested view: the exemplar names a trace,
    # the responder wants its whole span tree.
    assert links["trace"] == "/debug/traces?trace_id=t000777&tree=1"
    assert links["autoscaler"] == "/debug/autoscaler"


def test_alert_ring_is_bounded():
    clock = VirtualClock(start=0.0)
    reg = MetricsRegistry()
    # Identical windows so each flap is exactly one fire + one resolve
    # per window and the flap count is easy to reason about.
    spec = _ttft_spec(slow_window_s=300.0, slow_burn=14.0)
    eng = AlertEngine(reg, specs=[spec], clock=clock, capacity=4)
    _observe_ttft(reg, 0.1, 5)
    eng.evaluate()
    for _ in range(5):                               # 5 flaps, 4/flap
        clock.advance(10.0)
        _observe_ttft(reg, 1.0, 5)
        eng.evaluate()
        clock.advance(400.0)
        eng.evaluate()
    doc = eng.to_dict()
    assert len(doc["ring"]) == 4                     # capacity, not 20
    assert doc["evaluations"] == 11


# ---------------------------------------------------------------------------
# restart survival
# ---------------------------------------------------------------------------

def test_alert_identity_survives_engine_reconstruction():
    """A still-burning breach must stay ONE firing alert across an
    operator restart: reconstructing the engine from ``export_state()``
    re-fires nothing, keeps the original ``since``, and still resolves
    at the exact instant the bad events age out of the window."""
    clock = VirtualClock(start=0.0)
    reg = MetricsRegistry()
    eng = AlertEngine(reg, specs=[_ttft_spec()], clock=clock)
    _observe_ttft(reg, 0.1, 6)
    eng.evaluate()                                   # t=0 baseline
    clock.advance(10.0)
    _observe_ttft(reg, 1.0, 5)                       # t=10: the breach
    fired = eng.evaluate()
    assert {a["window"] for a in fired} == {"fast", "slow"}

    state = eng.export_state()
    json.dumps(state)                                # JSON-ready
    # "Restart": same registry (cumulative series survive scrape
    # targets), fresh engine fed the exported state.
    eng2 = AlertEngine(reg, specs=[_ttft_spec()], clock=clock,
                       state=state)
    clock.advance(10.0)                              # t=20: still burning
    assert eng2.evaluate() == []                     # NO re-fire
    active = [a for a in eng2.active() if a["window"] == "fast"]
    assert len(active) == 1
    assert active[0]["since"] == 10.0                # original identity
    assert eng2.evaluations == 3                     # counter carried over

    clock.advance(380.0)                             # t=400: aged out
    assert eng2.evaluate() == []
    assert "fast" not in {a["window"] for a in eng2.active()}
    resolved = [r for r in eng2.to_dict()["ring"]
                if r["state"] == "resolved" and r["window"] == "fast"]
    assert len(resolved) == 1
    assert resolved[0]["since"] == 10.0              # pre-restart birth
    assert resolved[0]["resolved_at"] == 400.0

    # The contrast: a reconstruction WITHOUT state forgets the breach
    # ever happened — no active alert, no history — which is exactly
    # the amnesia the state handoff exists to prevent.
    eng3 = AlertEngine(reg, specs=[_ttft_spec()], clock=clock)
    eng3.evaluate()                                  # baseline sample only
    assert eng3.active() == [] and eng3.to_dict()["ring"] == []


# ---------------------------------------------------------------------------
# serving surface
# ---------------------------------------------------------------------------

def test_debug_alerts_endpoint_serves_and_404s_when_absent():
    from kuberay_tpu.apiserver.server import serve_background
    clock = VirtualClock(start=0.0)
    reg = MetricsRegistry()
    eng = AlertEngine(reg, specs=[_ttft_spec()], clock=clock)
    _observe_ttft(reg, 0.1, 5)
    eng.evaluate()
    clock.advance(10.0)
    _observe_ttft(reg, 1.0, 5)
    eng.evaluate()
    srv, url = serve_background(ObjectStore(), alerts=eng)
    try:
        with urllib.request.urlopen(f"{url}/debug/alerts") as resp:
            doc = json.load(resp)
        assert [a["name"] for a in doc["active"]] == \
            ["serve-ttft", "serve-ttft"]             # fast + slow
        assert doc["ring"] and doc["evaluations"] == 2
        assert doc["specs"][0]["fast"] == {"window_s": 300.0, "burn": 14.0}
    finally:
        srv.shutdown()
    srv, url = serve_background(ObjectStore())       # no engine mounted
    try:
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(f"{url}/debug/alerts")
        assert ei.value.code == 404
    finally:
        srv.shutdown()


def test_operator_mounts_alert_engine_with_stock_catalog():
    from kuberay_tpu.operator import Operator
    op = Operator(fake_kubelet=True)
    url = op.start(api_port=0)
    try:
        assert isinstance(op.alerts, AlertEngine)
        with urllib.request.urlopen(f"{url}/debug/alerts") as resp:
            doc = json.load(resp)
        assert {s["name"] for s in doc["specs"]} == {
            "serve-ttft", "serve-availability", "goodput-ratio",
            "train-straggler"}
        assert doc["active"] == []                   # healthy at boot
    finally:
        op.stop()


def test_gauge_ceiling_fires_above_floor_with_goodput_link():
    """The train-straggler spec inverts the gauge-floor comparison
    (above=True): a skew ratio sitting ABOVE the 1.5x ceiling burns
    budget, and the firing series deep-links to both the flight ring
    and the goodput ledger of the job's CR."""
    clock = VirtualClock(start=0.0)
    reg = MetricsRegistry()
    spec = [s for s in default_slos() if s.name == "train-straggler"][0]
    assert spec.above and spec.gauge_family == "tpu_train_step_skew_ratio"
    eng = AlertEngine(reg, specs=[spec], clock=clock)
    labels = {"job": "default/drill", "kind": "TpuCluster",
              "namespace": "default", "name": "drill", "host": "s0w3"}
    reg.set_gauge("tpu_train_step_skew_ratio", 3.0, labels)
    fired = []
    for _ in range(7):
        fired.extend(eng.evaluate())
        clock.advance(10.0)
    assert len(fired) == 1                           # slow window only
    alert = fired[0]
    assert alert["name"] == "train-straggler"
    assert alert["series"]["host"] == "s0w3"
    assert alert["links"]["flight"] == \
        "/debug/flight/TpuCluster/default/drill"
    assert alert["links"]["goodput"] == \
        "/debug/goodput/TpuCluster/default/drill"

    # Back under the ceiling: the gauge is healthy, the alert drains.
    reg.set_gauge("tpu_train_step_skew_ratio", 1.0, labels)
    clock.advance(3700.0)
    eng.evaluate()
    assert eng.active() == []


# ---------------------------------------------------------------------------
# observational invariance under simulation
# ---------------------------------------------------------------------------

@pytest.mark.timeout(300)
def test_sim_replay_hash_invariant_with_tracing_and_alerting():
    """The acceptance contract: enabling tracing AND alerting changes
    nothing about a chaos replay — journal hashes stay byte-identical,
    while the alert engine demonstrably evaluated."""
    with SimHarness(0, scenario=get_scenario("rolling-upgrade"),
                    trace=True, alerts=True) as h:
        observed = h.run(3)
        assert h.alerts is not None and h.alerts.evaluations > 0
        export = h.export_trace()
    with SimHarness(0, scenario=get_scenario("rolling-upgrade")) as h:
        plain = h.run(3)
    assert observed.ok and plain.ok
    assert observed.journal_hash == plain.journal_hash
    assert observed.journal_len == plain.journal_len
    assert "active" in export["alerts"]              # artifact carries it
    json.dumps(export)                               # JSON-serializable
