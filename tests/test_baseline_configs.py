"""End-to-end tests for the BASELINE.json target configurations —
the five shapes the rebuild is judged on, driven through the control
plane exactly as a user would submit them."""

import yaml

import pytest

from kuberay_tpu.api.tpujob import JobDeploymentStatus
from kuberay_tpu.scheduler.gang import GangScheduler
from kuberay_tpu.utils import constants as C
from tests.test_job_controller import JobHarness, drive_job


@pytest.fixture
def h():
    return JobHarness()


def test_baseline5_mixtral_ep_two_groups(h):
    """BASELINE #5: expert-parallel job across TWO v5p worker groups —
    cross-group co-scheduling (gang covers both), per-group slice env."""
    fleet = {"chips": 0}
    gang = GangScheduler(h.store,
                         capacity_oracle=lambda d: d["tpuChips"] <= fleet["chips"])
    h.cluster_ctrl.scheduler = gang
    h.job_ctrl.scheduler = gang

    job = yaml.safe_load(open("samples/tpujob.mixtral-ep-two-groups.yaml"))
    job["spec"]["submissionMode"] = "HTTPMode"
    h.store.create(job)
    h.settle()
    # Gang holds the WHOLE job (both groups) while capacity is short.
    assert h.store.list("Pod") == []
    j = h.store.get(C.KIND_JOB, "mixtral-ep")
    assert j["status"]["jobDeploymentStatus"] == JobDeploymentStatus.INITIALIZING

    fleet["chips"] = 32   # 2 groups x v5p 2x2x4 = 16 + 16
    j = drive_job(h, "mixtral-ep")
    assert j.status.jobDeploymentStatus == JobDeploymentStatus.RUNNING
    workers = h.store.list("Pod", labels={C.LABEL_NODE_TYPE: "worker"})
    by_group = {}
    for p in workers:
        by_group.setdefault(p["metadata"]["labels"][C.LABEL_GROUP],
                            []).append(p)
    assert set(by_group) == {"experts-a", "experts-b"}
    assert all(len(v) == 4 for v in by_group.values())  # 4 hosts per slice
    # Both expert groups resolve the SAME coordinator (DCN rendezvous).
    addrs = set()
    for p in workers:
        env = {e["name"]: e.get("value", "") for e in p["spec"]["containers"][0]["env"]}
        addrs.add(env[C.ENV_COORDINATOR_ADDRESS])
        assert env[C.ENV_TPU_TOPOLOGY] == "2x2x4"
    assert len(addrs) == 1
    # PodGroup recorded the all-or-nothing quantum: 1 head + 8 workers.
    pgs = h.store.list("PodGroup")
    assert any(pg["spec"]["minMember"] == 9 for pg in pgs)

    h.coordinator.set_job_status(j.status.jobId, "SUCCEEDED")
    h.settle()
    assert h.store.get(C.KIND_JOB, "mixtral-ep")["status"][
        "jobDeploymentStatus"] == JobDeploymentStatus.COMPLETE


def test_baseline3_llama_v5p64_shape(h):
    """BASELINE #3: the Llama-3-8B pretrain job shape (v5p-64 = 4x4x4)."""
    job = yaml.safe_load(open("samples/tpujob.llama3-8b-v5p-64.yaml"))
    job["spec"]["submissionMode"] = "HTTPMode"
    h.store.create(job)
    j = drive_job(h, "llama3-8b-pretrain")
    assert j.status.jobDeploymentStatus == JobDeploymentStatus.RUNNING
    workers = h.store.list("Pod", labels={C.LABEL_NODE_TYPE: "worker"})
    assert len(workers) == 16    # 64 chips / 4 per host
    env = {e["name"]: e.get("value", "")
           for e in workers[0]["spec"]["containers"][0]["env"]}
    assert env[C.ENV_NUM_PROCESSES] == "16"
    assert "launcher" in j.spec.entrypoint and "llama3_8b" in j.spec.entrypoint
