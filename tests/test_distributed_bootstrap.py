"""Multi-process distributed bootstrap: the launcher's env contract drives
a REAL 2-process jax.distributed cluster over localhost (the comm-backend
proof — SURVEY §5.8: control plane wires addresses, JAX forms the mesh)."""

import os
import pathlib
import subprocess
import sys
import textwrap

import pytest

WORKER = textwrap.dedent("""
    import os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    sys.path.insert(0, %(repo)r)
    from kuberay_tpu.utils.platform import pin_platform_from_env
    pin_platform_from_env()
    from kuberay_tpu.train.launcher import WorkerIdentity
    import jax, jax.numpy as jnp
    ident = WorkerIdentity.from_env()
    jax.distributed.initialize(coordinator_address=os.environ["COORD"],
                               num_processes=ident.num_workers,
                               process_id=ident.worker_id)
    from jax.experimental import multihost_utils
    x = jnp.ones(4) * (ident.worker_id + 1)
    total = multihost_utils.process_allgather(x)
    print(f"RESULT {ident.worker_id} {jax.device_count()} "
          f"{jax.process_count()} {float(total.sum())}", flush=True)
""")


@pytest.mark.timeout(180)
def test_two_process_bootstrap(tmp_path):
    repo = str(pathlib.Path(__file__).resolve().parent.parent)
    script = tmp_path / "worker.py"
    script.write_text(WORKER % {"repo": repo})
    # Free port: hardcoding one makes parallel/repeated runs collide.
    import socket
    with socket.socket() as sk:
        sk.bind(("localhost", 0))
        port = sk.getsockname()[1]

    def spawn(worker_id):
        env = dict(os.environ)
        env.update({
            "TPU_WORKER_HOSTNAMES": "localhost,localhost",
            "TPU_NUM_PROCESSES": "2",
            "TPU_WORKER_ID": str(worker_id),
            "COORD": f"localhost:{port}",
        })
        return subprocess.Popen([sys.executable, str(script)], env=env,
                                stdout=subprocess.PIPE,
                                stderr=subprocess.STDOUT, text=True)

    procs = [spawn(0), spawn(1)]
    outs = [p.communicate(timeout=170)[0] for p in procs]
    for p, out in zip(procs, outs):
        assert p.returncode == 0, out[-2000:]
    results = {}
    for out in outs:
        for line in out.splitlines():
            if line.startswith("RESULT"):
                _, wid, ndev, nproc, total = line.split()
                results[int(wid)] = (int(ndev), int(nproc), float(total))
    assert set(results) == {0, 1}
    for ndev, nproc, total in results.values():
        assert ndev == 4 and nproc == 2
        # worker0 contributes 4x1, worker1 contributes 4x2.
        assert total == 12.0
