"""Leader election: exactly one active operator; takeover on leader loss."""

import time

import pytest

from kuberay_tpu.api.config import OperatorConfiguration
from kuberay_tpu.controlplane.leader import LEASE_NAME, LeaderElector
from kuberay_tpu.controlplane.store import ObjectStore
from kuberay_tpu.operator import Operator
from kuberay_tpu.runtime.coordinator_client import FakeCoordinatorClient
from kuberay_tpu.utils import constants as C
from tests.test_api_types import make_cluster


def wait_for(fn, timeout=15.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if fn():
            return True
        time.sleep(0.05)
    return False


def test_single_winner_and_takeover():
    store = ObjectStore()
    a = LeaderElector(store, identity="a", lease_duration=0.6,
                      renew_interval=0.1)
    b = LeaderElector(store, identity="b", lease_duration=0.6,
                      renew_interval=0.1)
    a.start()
    assert wait_for(lambda: a.is_leader)
    b.start()
    time.sleep(0.5)
    assert a.is_leader and not b.is_leader   # exactly one leader
    lease = store.get("Lease", LEASE_NAME)
    assert lease["spec"]["holderIdentity"] == "a"
    # Leader dies WITHOUT graceful release -> b takes over after expiry.
    a.stop(release=False)
    assert wait_for(lambda: b.is_leader, timeout=5.0)
    assert store.get("Lease", LEASE_NAME)["spec"]["holderIdentity"] == "b"
    b.stop()


def test_graceful_release_hands_over_fast():
    store = ObjectStore()
    a = LeaderElector(store, identity="a", lease_duration=30.0,
                      renew_interval=0.1)
    b = LeaderElector(store, identity="b", lease_duration=30.0,
                      renew_interval=0.1)
    a.start()
    assert wait_for(lambda: a.is_leader)
    b.start()
    a.stop(release=True)        # graceful: zeroes renewTime
    # Takeover well before the 30s lease would expire.
    assert wait_for(lambda: b.is_leader, timeout=5.0)
    b.stop()


def test_two_operators_one_reconciles():
    """Two full operators share a store with leader election: only the
    leader provisions; on leader stop the standby takes over a new CR."""
    store = ObjectStore()
    coord = FakeCoordinatorClient()

    def mk():
        op = Operator(OperatorConfiguration(reconcileConcurrency=1),
                      store=store, client_provider=lambda s: coord,
                      fake_kubelet=True)
        # Fast election for the test.
        return op

    op1, op2 = mk(), mk()
    op1.start(api_port=0, leader_election=True)
    op1.elector.lease_duration = 1.0
    op1.elector.renew_interval = 0.1
    assert wait_for(lambda: op1.elector.is_leader)
    op2.start(api_port=0, leader_election=True)
    op2.elector.lease_duration = 1.0
    op2.elector.renew_interval = 0.1
    time.sleep(0.3)
    assert not op2.elector.is_leader

    store.create(make_cluster(name="led").to_dict())
    assert wait_for(lambda: store.get(C.KIND_CLUSTER, "led").get(
        "status", {}).get("state") == "ready")

    op1.stop()                   # leader leaves; standby must take over
    assert wait_for(lambda: op2.elector.is_leader, timeout=10.0)
    store.create(make_cluster(name="led2").to_dict())
    assert wait_for(lambda: store.get(C.KIND_CLUSTER, "led2").get(
        "status", {}).get("state") == "ready", timeout=20.0)
    op2.stop()


def test_failover_overlap_status_write_409s():
    """The old leader's DELAYED status write must 409, not clobber the
    new leader's status (optimistic concurrency via resourceVersion —
    SURVEY §5.2; the controllers no longer strip rv before status
    writes)."""
    import copy

    from kuberay_tpu.controlplane.cluster_controller import (
        TpuClusterController,
    )
    from kuberay_tpu.controlplane.fake_kubelet import FakeKubelet
    from kuberay_tpu.controlplane.manager import Manager
    from kuberay_tpu.controlplane.store import Conflict

    store = ObjectStore()
    store.create(make_cluster("ov").to_dict())
    # The OLD leader read the object here, then paused (GC/network):
    # everything it does from now on is based on this snapshot.
    snapshot = store.get(C.KIND_CLUSTER, "ov")

    class PausedLeaderStore:
        """Delegates to the live store; only the FIRST cluster read (the
        reconcile-start snapshot — where the pause happened) serves the
        pre-failover copy.  Every later try_get returns the CURRENT
        (post-foreign-write) object, so a controller that refreshes the
        resourceVersion with a pre-write re-read would adopt the new
        leader's rv and silently clobber its status — the write must
        instead carry the snapshot rv and 409."""

        def __init__(self, real, snap):
            self._real, self._snap = real, snap
            self._served_snapshot = False

        def try_get(self, kind, name, namespace="default"):
            if kind == C.KIND_CLUSTER and name == "ov" and \
                    not self._served_snapshot:
                self._served_snapshot = True
                return copy.deepcopy(self._snap)
            return self._real.try_get(kind, name, namespace)

        def __getattr__(self, attr):
            return getattr(self._real, attr)

    # Meanwhile the NEW leader reconciles and writes status (rv moves).
    mgr = Manager(store)
    new_leader = TpuClusterController(store,
                                      expectations=mgr.expectations)
    new_leader.reconcile("ov")
    FakeKubelet(store).step()
    new_leader.reconcile("ov")
    after_failover = store.get(C.KIND_CLUSTER, "ov")
    assert after_failover["metadata"]["resourceVersion"] != \
        snapshot["metadata"]["resourceVersion"]
    assert after_failover["status"].get("state") is not None

    # Old leader resumes: its status write carries the stale rv → 409.
    old_leader = TpuClusterController(PausedLeaderStore(store, snapshot),
                                      expectations=mgr.expectations)
    with pytest.raises(Conflict):
        old_leader.reconcile("ov")
    # The new leader's status survived untouched.
    assert store.get(C.KIND_CLUSTER, "ov")["status"] == \
        after_failover["status"]

    # Through the manager the conflict is routine: swallowed, fast
    # requeue (re-read + recompute), not an error-backoff.  Replay the
    # paused-leader read for the manager-driven pass.
    old_leader.store._served_snapshot = False
    mgr2 = Manager(store)
    mgr2.register(C.KIND_CLUSTER, old_leader.reconcile)
    key = (C.KIND_CLUSTER, "default", "ov")
    mgr2.enqueue(key)
    mgr2.run_until_idle()
    assert any(k == key for _, k in mgr2._delayed)
