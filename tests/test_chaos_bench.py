"""The preemption chaos regression curve (benchmark/chaos_bench.py).

``benchmark/results/chaos_r10.json`` is the committed evidence that the
advance-notice machinery pays for itself: per seed, a warned kill must
cost strictly fewer interrupted+recovery seconds and end at a strictly
higher goodput ratio than the identical unwarned kill.  The whole
pipeline is virtual-clock deterministic, so the gate both (a) asserts
the curve's shape from the committed file and (b) recomputes the runs
and pins them to the committed numbers — a behavior change in the
controllers' preemption path shows up here as a diff, not silently.
"""

from __future__ import annotations

import importlib.util
import json
import os

import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ARTIFACT = os.path.join(REPO_ROOT, "benchmark", "results", "chaos_r10.json")
_BENCH = os.path.join(REPO_ROOT, "benchmark", "chaos_bench.py")


def _load_bench():
    spec = importlib.util.spec_from_file_location("chaos_bench", _BENCH)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def artifact():
    with open(ARTIFACT) as fh:
        return json.load(fh)


def _runs_by(artifact, seed):
    return {r["mode"]: r for r in artifact["runs"] if r["seed"] == seed}


def test_artifact_shape(artifact):
    assert artifact["schema"] == "tpu-chaos-bench/v1"
    assert artifact["seeds"] == [0, 1, 2, 3, 4]
    assert set(artifact["curve"]) == {"warned-warm", "warned-cold",
                                      "unwarned"}
    # One run per (mode, seed), none with invariant violations.
    assert len(artifact["runs"]) == 15
    for r in artifact["runs"]:
        assert r["violations"] == [], r


def test_warned_recovery_strictly_cheaper_every_seed(artifact):
    """The headline claim: at equal fault windows, a warned kill spends
    strictly less downtime and keeps strictly more goodput."""
    for seed in artifact["seeds"]:
        runs = _runs_by(artifact, seed)
        un = runs["unwarned"]
        un_down = un["interrupted_s"] + un["recovery_s"]
        for mode in ("warned-warm", "warned-cold"):
            w = runs[mode]
            # Equal fault window: the paired schedule is shared.
            assert w["warning_window_s"] == un["warning_window_s"]
            assert w["interrupted_s"] + w["recovery_s"] < un_down, \
                (seed, mode)
            assert w["goodput_ratio"] > un["goodput_ratio"], (seed, mode)


def test_warm_claim_beats_cold_provision_every_seed(artifact):
    """The warm pool's specific contribution on top of the notice: zero
    replacement-boot exposure, so warm downtime <= cold per seed (and
    the warm ratio is at least the cold one)."""
    for seed in artifact["seeds"]:
        runs = _runs_by(artifact, seed)
        warm, cold = runs["warned-warm"], runs["warned-cold"]
        assert (warm["interrupted_s"] + warm["recovery_s"]
                <= cold["interrupted_s"] + cold["recovery_s"]), seed
        assert warm["goodput_ratio"] >= cold["goodput_ratio"], seed


def test_recomputed_curve_matches_committed(artifact):
    """Full deterministic replay: rerunning the bench in-process must
    reproduce the committed artifact exactly (virtual clock + seeded
    schedule; no wall time enters the numbers)."""
    bench = _load_bench()
    doc = bench.run_curve(artifact["seeds"])
    assert doc["curve"] == artifact["curve"]
    assert doc["runs"] == artifact["runs"]
