"""Critical-path profile gate (kuberay_tpu.obs.profile): the interval
sweep's decomposition invariant (per-span-kind exclusive self times
partition every root window, for serve trees AND sim slice-ready
chains), the aggregator's fraction contract, the noise-gated trace
diff, the byte-identical sim profile artifact, and the upgrade ramp's
build-vs-build diff landing in the DecisionAudit with the guilty span
kind named.
"""

import json

import pytest

from kuberay_tpu.controlplane.autoscaler import DecisionAudit
from kuberay_tpu.obs.profile import (
    DEFAULT_ROOTS,
    PROFILE_SCHEMA,
    RequestProfiler,
    aggregate,
    describe_regression,
    diff_profiles,
    profile_spans,
    span_kind,
    trace_records,
    worst_regression,
)
from kuberay_tpu.obs.trace import Tracer
from kuberay_tpu.sim.clock import VirtualClock
from kuberay_tpu.sim.faults import FaultPlan
from kuberay_tpu.sim.harness import SimHarness
from kuberay_tpu.sim.scenarios import get_scenario, make_cluster_obj
from kuberay_tpu.utils import constants as C


# ---------------------------------------------------------------------------
# extractor: the interval sweep
# ---------------------------------------------------------------------------

def _span(trace_id, span_id, parent_id, name, start, end):
    return {"trace_id": trace_id, "span_id": span_id,
            "parent_id": parent_id, "name": name,
            "start": start, "end": end}


def test_serve_window_decomposes_exactly():
    spans = [
        _span("t1", "root", "", "serve-request", 0.0, 10.0),
        _span("t1", "q", "root", "gateway-queue", 0.0, 2.0),
        _span("t1", "f", "root", "forward", 2.0, 9.0),
        # Engine children nest INSIDE forward; depth charges them, not
        # the enclosing forward span.
        _span("t1", "p", "f", "prefill", 2.0, 4.0),
        _span("t1", "d", "f", "decode", 4.0, 8.0),
    ]
    recs = trace_records(spans)
    assert len(recs) == 1
    rec = recs[0]
    assert rec["shape"] == "serve"
    assert rec["duration_s"] == 10.0
    # forward's exclusive slice is 8..9 (prefill/decode cover 2..8);
    # the root keeps the uncovered tail 9..10.
    assert rec["self_s"] == {
        "gateway-queue": 2.0, "prefill": 2.0, "decode": 4.0,
        "forward": 1.0, "serve-request": 1.0}
    assert sum(rec["self_s"].values()) == pytest.approx(rec["duration_s"])


def test_overlapping_siblings_never_double_count():
    # Two siblings overlap on [3, 6): a naive duration-minus-children
    # subtraction would charge the window twice.  The sweep charges the
    # later-starting sibling (tie depth) and the sum stays exact.
    spans = [
        _span("t1", "root", "", "serve-request", 0.0, 10.0),
        _span("t1", "a", "root", "prefill", 1.0, 6.0),
        _span("t1", "b", "root", "decode", 3.0, 9.0),
    ]
    rec = trace_records(spans)[0]
    assert sum(rec["self_s"].values()) == pytest.approx(10.0)
    assert rec["self_s"]["prefill"] == pytest.approx(2.0)   # 1..3
    assert rec["self_s"]["decode"] == pytest.approx(6.0)    # 3..9
    assert rec["self_s"]["serve-request"] == pytest.approx(2.0)


def test_children_clip_to_the_root_window():
    # A candidate straddling the window boundary only charges the part
    # inside it; fully-outside spans charge nothing.
    spans = [
        _span("t1", "root", "", "slice-ready", 10.0, 20.0),
        _span("t1", "a", "", "pod-start", 5.0, 14.0),       # clips to 10..14
        _span("t1", "b", "", "queue-wait", 30.0, 40.0),     # outside
    ]
    rec = trace_records(spans, roots={"slice-ready": "control-plane"})[0]
    assert rec["self_s"] == {"pod-start": 4.0, "slice-ready": 6.0}


def test_zero_duration_window_keeps_root_kind():
    spans = [_span("t1", "root", "", "slice-ready", 5.0, 5.0)]
    rec = trace_records(spans, roots={"slice-ready": "control-plane"})[0]
    assert rec["duration_s"] == 0.0
    assert rec["self_s"] == {"slice-ready": 0.0}


def test_span_kind_normalization():
    assert span_kind("chain:TpuCluster/default/x") == "chain"
    assert span_kind("error:coordinator") == "error"
    assert span_kind("decode") == "decode"
    assert set(DEFAULT_ROOTS) == {"serve-request", "slice-ready"}


def test_real_tracer_serve_trace_decomposes():
    """The decomposition invariant over a REAL tracer's serve tree:
    per-span-kind self times sum to the root serve-request duration."""
    clock = VirtualClock(start=50.0)
    tracer = Tracer(clock=clock)
    ctx = tracer.start_request("serve-request", ts=50.0)
    tracer.record_span(ctx, "gateway-queue", 50.0, 50.5)
    tracer.record_span(ctx, "route-decision", 50.5, 50.6)
    fwd_ctx = ctx
    tracer.record_span(fwd_ctx, "forward", 50.6, 53.0)
    tracer.record_span(fwd_ctx, "engine-queue", 50.7, 51.0)
    tracer.record_span(fwd_ctx, "prefill", 51.0, 51.8)
    tracer.record_span(fwd_ctx, "decode", 51.8, 52.9)
    tracer.finish_request(ctx, ts=53.0)
    recs = trace_records(tracer.export())
    assert len(recs) == 1
    rec = recs[0]
    assert rec["duration_s"] == pytest.approx(3.0)
    assert sum(rec["self_s"].values()) == pytest.approx(3.0)
    for kind in ("gateway-queue", "route-decision", "engine-queue",
                 "prefill", "decode"):
        assert kind in rec["self_s"], sorted(rec["self_s"])


# ---------------------------------------------------------------------------
# aggregator
# ---------------------------------------------------------------------------

def test_aggregate_fractions_sum_to_one_per_shape():
    spans = []
    for i in range(4):
        t0 = 10.0 * i
        spans += [
            _span(f"t{i}", f"r{i}", "", "serve-request", t0, t0 + 4.0),
            _span(f"t{i}", f"p{i}", f"r{i}", "prefill", t0, t0 + 1.0),
            _span(f"t{i}", f"d{i}", f"r{i}", "decode", t0 + 1.0,
                  t0 + 3.0 + i * 0.25),
        ]
    doc = profile_spans(spans, meta={"source": "unit"})
    assert doc["schema"] == PROFILE_SCHEMA
    assert doc["meta"]["source"] == "unit"
    serve = doc["shapes"]["serve"]
    assert serve["traces"] == 4
    frac = sum(k["fraction"] for k in serve["kinds"].values())
    assert frac == pytest.approx(1.0, abs=1e-9)
    # Percentiles are per-kind over the self-time samples.
    assert serve["kinds"]["prefill"]["count"] == 4
    assert serve["kinds"]["prefill"]["p50_s"] == pytest.approx(1.0)
    assert serve["kinds"]["decode"]["p99_s"] > \
        serve["kinds"]["decode"]["p50_s"]


def test_aggregate_empty_and_json_stability():
    assert aggregate([]) == {"schema": PROFILE_SCHEMA, "shapes": {}}
    spans = [_span("t1", "r", "", "serve-request", 0.0, 1.0)]
    a = json.dumps(profile_spans(spans), sort_keys=True)
    b = json.dumps(profile_spans(list(reversed(spans))), sort_keys=True)
    assert a == b


# ---------------------------------------------------------------------------
# diff engine: the noise gate
# ---------------------------------------------------------------------------

def _profile_with(kind_metrics, shape="serve"):
    kinds = {k: {"count": n, "total_s": v * n, "fraction": 0.5,
                 "mean_s": v, "p50_s": v, "p90_s": v, "p99_s": v}
             for k, (n, v) in kind_metrics.items()}
    return {"schema": PROFILE_SCHEMA, "shapes": {shape: {
        "traces": max((n for n, _ in kind_metrics.values()), default=0),
        "total_s": 1.0, "duration_p50_s": 0.1, "duration_p90_s": 0.2,
        "duration_p99_s": 0.3, "kinds": kinds}}}


def test_diff_names_the_guilty_kind():
    base = _profile_with({"prefill": (10, 0.10), "decode": (10, 0.20)})
    cand = _profile_with({"prefill": (10, 0.11), "decode": (10, 0.45)})
    diff = diff_profiles(base, cand)
    assert [e["kind"] for e in diff["regressions"]] == ["decode"]
    worst = worst_regression(diff)
    assert worst["kind"] == "decode"
    assert worst["rel_change"] == pytest.approx(1.25)
    assert "decode" in describe_regression(worst)
    assert diff["improvements"] == []
    # prefill moved 10% — under the 25% gate, so neither bucket.
    assert all(e["kind"] != "prefill" for e in diff["regressions"])


def test_diff_min_count_and_missing_side_skip():
    base = _profile_with({"decode": (2, 0.1)})
    cand = _profile_with({"decode": (9, 0.9), "prefill": (9, 0.2)})
    diff = diff_profiles(base, cand, min_count=5)
    assert diff["regressions"] == []
    reasons = {e["kind"]: e["reason"] for e in diff["skipped"]}
    assert reasons["decode"] == "samples 2 < 5"
    assert reasons["prefill"] == "missing-side"


def test_diff_zero_baseline_and_min_delta_gate():
    base = _profile_with({"decode": (10, 0.0)})
    cand = _profile_with({"decode": (10, 0.002)})
    # Zero baseline: relative change is huge but min_delta_s can gate
    # the absolute movement.
    assert diff_profiles(base, cand)["regressions"]
    assert diff_profiles(base, cand,
                         min_delta_s=0.01)["regressions"] == []


def test_diff_improvements_mirror_regressions():
    base = _profile_with({"decode": (10, 0.4)})
    cand = _profile_with({"decode": (10, 0.1)})
    diff = diff_profiles(base, cand)
    assert diff["regressions"] == []
    assert [e["kind"] for e in diff["improvements"]] == ["decode"]
    assert worst_regression(diff) is None
    assert worst_regression(None) is None


def test_self_diff_is_always_clean():
    base = _profile_with({"prefill": (10, 0.1), "decode": (10, 0.2)})
    diff = diff_profiles(base, base)
    assert diff["regressions"] == [] and diff["improvements"] == []


# ---------------------------------------------------------------------------
# RequestProfiler: per-backend scoping
# ---------------------------------------------------------------------------

def test_request_profiler_scopes_to_final_backend():
    tracer = Tracer(clock=VirtualClock(start=0.0))
    profiler = RequestProfiler(tracer)
    for backend, decode_s in (("blue", 0.1), ("green", 0.4)):
        for i in range(3):
            t0 = float(i) + (100.0 if backend == "green" else 0.0)
            ctx = tracer.start_request("serve-request", ts=t0)
            tracer.record_span(ctx, "decode", t0, t0 + decode_s)
            tracer.finish_request(ctx, ts=t0 + decode_s)
            profiler.note(ctx.trace_id, backend)
    blue = profiler.snapshot(backend="blue")
    green = profiler.snapshot(backend="green")
    assert blue["shapes"]["serve"]["traces"] == 3
    assert green["shapes"]["serve"]["kinds"]["decode"]["p90_s"] > \
        blue["shapes"]["serve"]["kinds"]["decode"]["p90_s"]
    # Unscoped snapshot covers everything.
    assert profiler.snapshot()["shapes"]["serve"]["traces"] == 6
    # Unknown backend: empty profile, not an error.
    assert profiler.snapshot(backend="nope")["shapes"] == {}


# ---------------------------------------------------------------------------
# sim: nonzero control-plane decomposition + byte-identical artifact
# ---------------------------------------------------------------------------

@pytest.mark.timeout(120)
def test_sim_slice_ready_profile_decomposes_with_slow_start():
    """A held pod stretches the slice-ready window over real virtual
    time; the control-plane profile must attribute it (pod-start self
    time dominates) and the per-window invariant must hold exactly."""
    quiet = {f: 0.0 for f in FaultPlan(0).profile}
    with SimHarness(0, fault_profile=quiet, trace=True) as h:
        h.store.create(make_cluster_obj("demo", topology="2x2x2",
                                        replicas=1))
        h.manager.run_until_idle()
        pods = [p for p in h.store.list("Pod")
                if p["metadata"]["labels"].get(C.LABEL_GROUP) == "workers"]
        victim = sorted(p["metadata"]["name"] for p in pods)[0]
        h.kubelet.hold_pod(victim, until=h.clock.now() + 40.0)
        h.settle(horizon=120.0)
        spans = h.tracer.export()
        doc = h.export_profile()
    recs = [r for r in trace_records(spans) if r["shape"] == "control-plane"]
    assert recs, "no slice-ready windows extracted"
    for rec in recs:
        assert sum(rec["self_s"].values()) == \
            pytest.approx(rec["duration_s"], abs=1e-6)
    assert any(rec["duration_s"] >= 40.0 for rec in recs)
    cp = doc["shapes"]["control-plane"]
    assert cp["total_s"] >= 40.0
    assert cp["kinds"]["pod-start"]["total_s"] >= 39.0
    frac = sum(k["fraction"] for k in cp["kinds"].values())
    assert frac == pytest.approx(1.0, abs=1e-6)


@pytest.mark.timeout(300)
def test_sim_profile_artifact_byte_identical_and_hash_invariant():
    """Acceptance: two runs of the same (scenario, seed) export the
    SAME profile bytes, and mounting the profiler leaves the journal
    hash untouched (all obs layers stay observational)."""
    docs, hashes = [], []
    for _ in range(2):
        with SimHarness(3, scenario=get_scenario("scale-up-storm"),
                        trace=True) as h:
            result = h.run(2)
            docs.append(json.dumps(h.export_profile(), sort_keys=True))
            hashes.append(result.journal_hash)
    assert docs[0] == docs[1]
    assert hashes[0] == hashes[1]
    with SimHarness(3, scenario=get_scenario("scale-up-storm")) as h:
        untraced = h.run(2)
    assert untraced.journal_hash == hashes[0]
    doc = json.loads(docs[0])
    assert doc["schema"] == PROFILE_SCHEMA
    assert doc["meta"]["journal_hash"] == hashes[0]


# ---------------------------------------------------------------------------
# upgrade gate integration: the diff lands in the DecisionAudit
# ---------------------------------------------------------------------------

def _wire_profiler(h):
    from kuberay_tpu.utils.names import serve_service_name
    tracer = Tracer()
    profiler = RequestProfiler(tracer)
    audit = DecisionAudit(capacity=32)
    h.svc_ctrl.profiler = profiler
    h.svc_ctrl.audit = audit
    s = h.svc()
    blue = serve_service_name(s.status.activeServiceStatus.clusterName)
    green = serve_service_name(s.status.pendingServiceStatus.clusterName)
    return tracer, profiler, audit, blue, green


def _record_serve_traces(tracer, profiler, backend, *, decode_s,
                         base_ts, n=5):
    for i in range(n):
        t0 = base_ts + 10.0 * i
        ctx = tracer.start_request("serve-request", ts=t0)
        tracer.record_span(ctx, "prefill", t0, t0 + 0.05)
        tracer.record_span(ctx, "decode", t0 + 0.05, t0 + 0.05 + decode_s)
        tracer.finish_request(ctx, ts=t0 + 0.05 + decode_s)
        profiler.note(ctx.trace_id, backend)


@pytest.fixture(autouse=True)
def _reset_feature_gates():
    from kuberay_tpu.utils import features
    features.reset()
    yield
    features.reset()


def test_rollback_audit_names_the_decode_regression():
    from kuberay_tpu.api.tpuservice import UpgradeState
    from tests.test_service_controller import (bump_image, gated_harness,
                                               green_weight)
    h, clock, gate = gated_harness()
    bump_image(h, "model:v2")
    h.settle(rounds=6)
    assert green_weight(h) == 50
    tracer, profiler, audit, blue, green = _wire_profiler(h)
    # Candidate build: decode is 8x slower than blue's.
    _record_serve_traces(tracer, profiler, blue, decode_s=0.05,
                         base_ts=100.0)
    _record_serve_traces(tracer, profiler, green, decode_s=0.40,
                         base_ts=1000.0)

    gate.healthy = False
    gate.alert = {"name": "upgrade-green-ttft", "window": "fast"}
    h.settle(rounds=2)
    assert h.svc().status.upgrade.state == UpgradeState.ROLLED_BACK

    entries = [e for e in audit.to_list()
               if e.get("kind") == "upgrade" and e["action"] == "rollback"]
    assert entries, audit.to_list()
    entry = entries[0]
    assert entry["green_weight"] == 0
    diff = entry["profile_diff"]
    assert diff["regressions"], diff
    assert diff["regressions"][0]["kind"] == "decode"
    # The rollback event message names WHERE the candidate got slower.
    msgs = [e["message"] for e in h.store.list("Event")
            if e.get("reason") == "UpgradeRolledBack"]
    assert msgs and any("candidate slower in decode" in m for m in msgs), \
        msgs


def test_clean_candidate_promotes_with_empty_regressions():
    from kuberay_tpu.api.tpuservice import UpgradeState
    from tests.test_service_controller import (bump_image, gated_harness,
                                               green_weight)
    h, clock, gate = gated_harness()
    bump_image(h, "model:v2")
    h.settle(rounds=6)
    assert green_weight(h) == 50
    tracer, profiler, audit, blue, green = _wire_profiler(h)
    # Same shape on both builds: nothing clears the noise gate.
    _record_serve_traces(tracer, profiler, blue, decode_s=0.10,
                         base_ts=100.0)
    _record_serve_traces(tracer, profiler, green, decode_s=0.10,
                         base_ts=1000.0)

    clock.advance(3600.0)
    h.settle(rounds=4)
    assert h.svc().status.upgrade.state == UpgradeState.PROMOTED

    entries = [e for e in audit.to_list()
               if e.get("kind") == "upgrade" and e["action"] == "promote"]
    assert entries, audit.to_list()
    diff = entries[0]["profile_diff"]
    assert diff["regressions"] == []
    assert entries[0]["reason"] == "ramp complete"
