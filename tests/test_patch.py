"""PATCH verbs: json-merge, json-patch, strategic-merge, Server-Side
Apply — engine semantics, store integration, and the HTTP wire surface
(ref apiserversdk/proxy.go:28-40: the V2 contract is that every kube
verb, PATCH included, works against the API server)."""

import json
import threading
import urllib.error
import urllib.request

import pytest

from kuberay_tpu.controlplane.patch import (
    ApplyConflict,
    PatchError,
    apply_ssa,
    field_set,
    fields_from_v1,
    fields_to_v1,
    json_merge_patch,
    json_patch,
    strategic_merge_patch,
)
from kuberay_tpu.controlplane.store import (
    Conflict,
    Invalid,
    NotFound,
    ObjectStore,
)

# ---------------------------------------------------------------------------
# json-merge (RFC 7386)


def test_json_merge_nested_and_null_delete():
    tgt = {"a": {"x": 1, "y": 2}, "b": [1, 2], "c": "keep"}
    out = json_merge_patch(tgt, {"a": {"y": None, "z": 3}, "b": [9]})
    assert out == {"a": {"x": 1, "z": 3}, "b": [9], "c": "keep"}
    # target untouched
    assert tgt["a"] == {"x": 1, "y": 2}


def test_json_merge_scalar_replaces_dict():
    assert json_merge_patch({"a": {"x": 1}}, {"a": 5}) == {"a": 5}
    assert json_merge_patch("anything", {"a": 1}) == {"a": 1}


# ---------------------------------------------------------------------------
# json-patch (RFC 6902)


def test_json_patch_ops():
    doc = {"spec": {"replicas": 1, "groups": ["a", "b"]}}
    out = json_patch(doc, [
        {"op": "test", "path": "/spec/replicas", "value": 1},
        {"op": "replace", "path": "/spec/replicas", "value": 3},
        {"op": "add", "path": "/spec/groups/-", "value": "c"},
        {"op": "add", "path": "/spec/groups/0", "value": "z"},
        {"op": "remove", "path": "/spec/groups/1"},
        {"op": "copy", "from": "/spec/replicas", "path": "/spec/min"},
        {"op": "move", "from": "/spec/min", "path": "/spec/max"},
    ])
    assert out == {"spec": {"replicas": 3, "groups": ["z", "b", "c"],
                            "max": 3}}
    assert doc["spec"]["replicas"] == 1        # atomic w.r.t. input


def test_json_patch_test_failure_aborts():
    doc = {"a": 1, "b": 2}
    with pytest.raises(PatchError):
        json_patch(doc, [
            {"op": "replace", "path": "/a", "value": 9},
            {"op": "test", "path": "/b", "value": 999},
        ])
    assert doc == {"a": 1, "b": 2}


def test_json_patch_escapes_and_errors():
    assert json_patch({"a/b": 1, "m~n": 2}, [
        {"op": "replace", "path": "/a~1b", "value": 9},
        {"op": "replace", "path": "/m~0n", "value": 8},
    ]) == {"a/b": 9, "m~n": 8}
    for bad in (
        [{"op": "replace", "path": "/missing", "value": 1}],
        [{"op": "remove", "path": "/missing"}],
        [{"op": "add", "path": "/list/9", "value": 1}],
        [{"op": "nope", "path": "/a"}],
        {"op": "not-a-list"},
        # Malformed ops must raise PatchError (-> 400/422 at the API),
        # never raw ValueError/IndexError (-> 500).
        [{"op": "move", "path": "/a"}],                  # missing 'from'
        [{"op": "copy", "path": "/a"}],                  # missing 'from'
        [{"op": "move", "path": "/a", "from": ""}],      # whole-doc move
        [{"op": "add", "path": "/list/x", "value": 1}],  # non-numeric idx
        [{"op": "remove", "path": "/list/x"}],
    ):
        with pytest.raises(PatchError):
            json_patch({"a": 1, "list": []}, bad)


# ---------------------------------------------------------------------------
# strategic-merge


def test_strategic_merges_worker_groups_by_name():
    cur = {"spec": {"workerGroupSpecs": [
        {"groupName": "wg1", "replicas": 1, "topology": "2x2"},
        {"groupName": "wg2", "replicas": 2, "topology": "2x4"},
    ], "suspend": False}}
    out = strategic_merge_patch(cur, {"spec": {"workerGroupSpecs": [
        {"groupName": "wg2", "replicas": 5},
        {"groupName": "wg3", "replicas": 1, "topology": "1x1"},
    ]}})
    groups = {g["groupName"]: g for g in out["spec"]["workerGroupSpecs"]}
    assert groups["wg1"] == {"groupName": "wg1", "replicas": 1,
                             "topology": "2x2"}          # untouched
    assert groups["wg2"]["replicas"] == 5
    assert groups["wg2"]["topology"] == "2x4"            # merged, not lost
    assert groups["wg3"]["topology"] == "1x1"            # appended
    assert out["spec"]["suspend"] is False


def test_strategic_patch_delete_and_replace_directives():
    cur = {"spec": {"workerGroupSpecs": [
        {"groupName": "a", "replicas": 1},
        {"groupName": "b", "replicas": 2},
    ]}}
    out = strategic_merge_patch(cur, {"spec": {"workerGroupSpecs": [
        {"groupName": "a", "$patch": "delete"},
    ]}})
    assert [g["groupName"] for g in out["spec"]["workerGroupSpecs"]] == ["b"]
    out2 = strategic_merge_patch(
        {"spec": {"x": {"a": 1, "b": 2}}},
        {"spec": {"x": {"$patch": "replace", "c": 3}}})
    assert out2["spec"]["x"] == {"c": 3}


def test_strategic_finalizers_set_merge_and_atomic_lists():
    cur = {"metadata": {"finalizers": ["f1"]}, "spec": {"plain": [1, 2]}}
    out = strategic_merge_patch(cur, {
        "metadata": {"finalizers": ["f2", "f1"]},
        "spec": {"plain": [9]}})
    assert out["metadata"]["finalizers"] == ["f1", "f2"]   # union, stable
    assert out["spec"]["plain"] == [9]                     # atomic replace


def test_strategic_missing_merge_key_rejected():
    with pytest.raises(PatchError):
        strategic_merge_patch(
            {"spec": {"workerGroupSpecs": [{"groupName": "a"}]}},
            {"spec": {"workerGroupSpecs": [{"replicas": 3}]}})


# ---------------------------------------------------------------------------
# field sets / fieldsV1


def test_field_set_and_v1_roundtrip():
    obj = {
        "apiVersion": "tpu.dev/v1", "kind": "TpuCluster",
        "metadata": {"name": "c", "labels": {"team": "ml"}},
        "spec": {
            "suspend": False,
            "workerGroupSpecs": [
                {"groupName": "wg1", "replicas": 2,
                 "scaleStrategy": {"slicesToDelete": []}},
            ],
        },
        "status": {"phase": "Ready"},
    }
    fs = field_set(obj)
    assert ("spec", "suspend") in fs
    assert ("metadata", "labels", "team") in fs
    item = ("spec", "workerGroupSpecs", ("k", "groupName", '"wg1"'))
    assert item + ("replicas",) in fs
    assert not any(p[0] == "status" for p in fs)           # server-owned
    assert fields_from_v1(fields_to_v1(fs)) == fs


# ---------------------------------------------------------------------------
# Server-Side Apply


def _cluster_applied(mgr_replicas=1):
    return {
        "apiVersion": "tpu.dev/v1", "kind": "TpuCluster",
        "metadata": {"name": "c1", "namespace": "default"},
        "spec": {"suspend": False, "workerGroupSpecs": [
            {"groupName": "wg1", "replicas": mgr_replicas,
             "topology": "2x2"}]},
    }


def test_ssa_create_and_reapply_noop():
    out = apply_ssa(None, _cluster_applied(), "tpuctl")
    mf = out["metadata"]["managedFields"]
    assert len(mf) == 1 and mf[0]["manager"] == "tpuctl"
    assert mf[0]["operation"] == "Apply"
    out2 = apply_ssa(out, _cluster_applied(), "tpuctl")
    assert out2["spec"] == out["spec"]


def test_ssa_conflict_then_force():
    live = apply_ssa(None, _cluster_applied(2), "tpuctl")
    # Another manager applies a different replicas value -> conflict.
    other = {
        "apiVersion": "tpu.dev/v1", "kind": "TpuCluster",
        "metadata": {"name": "c1", "namespace": "default"},
        "spec": {"workerGroupSpecs": [
            {"groupName": "wg1", "replicas": 7}]},
    }
    with pytest.raises(ApplyConflict) as ei:
        apply_ssa(live, other, "tpu-autoscaler")
    assert "tpuctl" in str(ei.value)
    forced = apply_ssa(live, other, "tpu-autoscaler", force=True)
    assert forced["spec"]["workerGroupSpecs"][0]["replicas"] == 7
    # topology untouched (not applied by the other manager)
    assert forced["spec"]["workerGroupSpecs"][0]["topology"] == "2x2"
    # Ownership moved: re-applying as tpuctl now conflicts on replicas.
    with pytest.raises(ApplyConflict):
        apply_ssa(forced, _cluster_applied(2), "tpuctl")


def test_ssa_same_value_co_ownership_no_conflict():
    live = apply_ssa(None, _cluster_applied(3), "a")
    out = apply_ssa(live, _cluster_applied(3), "b")   # identical values
    mgrs = {e["manager"] for e in out["metadata"]["managedFields"]}
    assert mgrs == {"a", "b"}


def test_ssa_stops_applying_field_prunes_it():
    live = apply_ssa(None, _cluster_applied(), "tpuctl")
    slim = _cluster_applied()
    del slim["spec"]["workerGroupSpecs"][0]["topology"]
    out = apply_ssa(live, slim, "tpuctl")
    assert "topology" not in out["spec"]["workerGroupSpecs"][0]
    # ...but not when someone else still owns it (co-owned).
    live2 = apply_ssa(None, _cluster_applied(), "a")
    live2 = apply_ssa(live2, _cluster_applied(), "b")
    out2 = apply_ssa(live2, slim, "a")
    assert out2["spec"]["workerGroupSpecs"][0]["topology"] == "2x2"


def test_ssa_requires_manager():
    with pytest.raises(PatchError):
        apply_ssa(None, _cluster_applied(), "")


def test_ssa_dropping_list_item_removes_it_entirely():
    """Re-applying without a previously applied worker group must delete
    the group, not leave a {'groupName': ...} stub behind."""
    two = _cluster_applied()
    two["spec"]["workerGroupSpecs"].append(
        {"groupName": "wg2", "replicas": 3, "topology": "2x4"})
    live = apply_ssa(None, two, "tpuctl")
    out = apply_ssa(live, _cluster_applied(), "tpuctl")
    assert [g["groupName"] for g in out["spec"]["workerGroupSpecs"]] == \
        ["wg1"]
    # ...unless another manager still owns a field under the item.
    live2 = apply_ssa(None, two, "a")
    wg2_only = {
        "apiVersion": "tpu.dev/v1", "kind": "TpuCluster",
        "metadata": {"name": "c1", "namespace": "default"},
        "spec": {"workerGroupSpecs": [
            {"groupName": "wg2", "replicas": 3}]},
    }
    live2 = apply_ssa(live2, wg2_only, "b")
    out2 = apply_ssa(live2, _cluster_applied(), "a")
    names = [g["groupName"] for g in out2["spec"]["workerGroupSpecs"]]
    assert "wg2" in names                     # b still owns wg2.replicas


def test_store_patch_non_dict_body_rejected():
    st = _mk_store_with_cluster()
    for bad in (None, "x", [1, 2]):
        with pytest.raises(Invalid):
            st.patch("TpuCluster", "c1", "default", bad,
                     patch_type="merge")


# ---------------------------------------------------------------------------
# store integration


def _mk_store_with_cluster():
    st = ObjectStore()
    st.create({
        "apiVersion": "tpu.dev/v1", "kind": "TpuCluster",
        "metadata": {"name": "c1", "namespace": "default",
                     "labels": {"team": "ml"}},
        "spec": {"suspend": False, "workerGroupSpecs": [
            {"groupName": "wg1", "replicas": 1, "topology": "2x2"}]},
    })
    return st


def test_store_merge_patch_bumps_generation_and_notifies():
    st = _mk_store_with_cluster()
    seen = []
    st.watch(lambda ev: seen.append(ev.type))
    out = st.patch("TpuCluster", "c1", "default",
                   {"spec": {"suspend": True}})
    assert out["spec"]["suspend"] is True
    assert out["metadata"]["generation"] == 2
    assert seen == ["MODIFIED"]
    # metadata-only patch: no generation bump
    out2 = st.patch("TpuCluster", "c1", "default",
                    {"metadata": {"labels": {"x": "y"}}})
    assert out2["metadata"]["generation"] == 2
    assert out2["metadata"]["labels"] == {"team": "ml", "x": "y"}


def test_store_patch_rv_precondition():
    st = _mk_store_with_cluster()
    with pytest.raises(Conflict):
        st.patch("TpuCluster", "c1", "default",
                 {"metadata": {"resourceVersion": 999999},
                  "spec": {"suspend": True}})
    cur_rv = st.get("TpuCluster", "c1")["metadata"]["resourceVersion"]
    out = st.patch("TpuCluster", "c1", "default",
                   {"metadata": {"resourceVersion": cur_rv},
                    "spec": {"suspend": True}})
    assert out["spec"]["suspend"] is True


def test_store_patch_identity_immutable():
    st = _mk_store_with_cluster()
    before = st.get("TpuCluster", "c1")
    out = st.patch("TpuCluster", "c1", "default", {
        "kind": "Sneaky",
        "metadata": {"name": "other", "namespace": "elsewhere",
                     "uid": "forged", "creationTimestamp": 0}})
    assert out["kind"] == "TpuCluster"
    assert out["metadata"]["name"] == "c1"
    assert out["metadata"]["uid"] == before["metadata"]["uid"]
    assert out["metadata"]["creationTimestamp"] == \
        before["metadata"]["creationTimestamp"]


def test_store_patch_status_subresource_isolated():
    st = _mk_store_with_cluster()
    out = st.patch("TpuCluster", "c1", "default",
                   {"spec": {"suspend": True},
                    "status": {"phase": "Ready"}},
                   subresource="status")
    assert out["status"] == {"phase": "Ready"}
    assert out["spec"]["suspend"] is False     # spec change ignored
    assert out["metadata"]["generation"] == 1


def test_store_patch_label_index_maintained():
    st = ObjectStore()
    st.create({"kind": "Pod", "metadata": {
        "name": "p1", "namespace": "default",
        "labels": {"tpu.dev/cluster": "c1"}}, "spec": {}})
    st.patch("Pod", "p1", "default",
             {"metadata": {"labels": {"tpu.dev/cluster": "c2"}}})
    assert st.list("Pod", labels={"tpu.dev/cluster": "c2"})
    assert not st.list("Pod", labels={"tpu.dev/cluster": "c1"})


def test_store_patch_notfound_and_bad_type():
    st = ObjectStore()
    with pytest.raises(NotFound):
        st.patch("TpuCluster", "nope", "default", {"spec": {}})
    st = _mk_store_with_cluster()
    with pytest.raises(Invalid):
        st.patch("TpuCluster", "c1", "default", {}, patch_type="bogus")


def test_store_apply_upsert_and_conflict():
    st = ObjectStore()
    applied = _cluster_applied()
    out = st.patch("TpuCluster", "c1", "default", applied,
                   patch_type="apply", field_manager="tpuctl")
    assert out["metadata"]["uid"]
    assert out["metadata"]["managedFields"][0]["manager"] == "tpuctl"
    # Conflicting second manager -> Conflict; force wins.
    other = _cluster_applied(9)
    with pytest.raises(Conflict):
        st.patch("TpuCluster", "c1", "default", other,
                 patch_type="apply", field_manager="autoscaler")
    out = st.patch("TpuCluster", "c1", "default", other,
                   patch_type="apply", field_manager="autoscaler",
                   force=True)
    assert out["spec"]["workerGroupSpecs"][0]["replicas"] == 9


def test_store_patch_removing_finalizer_finalizes_delete():
    st = _mk_store_with_cluster()
    st.add_finalizer("TpuCluster", "c1", "default", "tpu.dev/cleanup")
    st.delete("TpuCluster", "c1")
    assert st.try_get("TpuCluster", "c1") is not None   # held by finalizer
    st.patch("TpuCluster", "c1", "default",
             {"metadata": {"finalizers": []}})
    assert st.try_get("TpuCluster", "c1") is None


def test_store_json_patch_and_strategic():
    st = _mk_store_with_cluster()
    out = st.patch("TpuCluster", "c1", "default", [
        {"op": "replace",
         "path": "/spec/workerGroupSpecs/0/replicas", "value": 4},
    ], patch_type="json")
    assert out["spec"]["workerGroupSpecs"][0]["replicas"] == 4
    out = st.patch("TpuCluster", "c1", "default",
                   {"spec": {"workerGroupSpecs": [
                       {"groupName": "wg1", "replicas": 6}]}},
                   patch_type="strategic")
    g = out["spec"]["workerGroupSpecs"][0]
    assert g["replicas"] == 6 and g["topology"] == "2x2"


# ---------------------------------------------------------------------------
# HTTP wire surface


def _valid_cluster_dict(name="c1"):
    """Admission-valid TpuCluster (the HTTP layer validates PATCHed
    objects, so wire tests need real container templates)."""
    from tests.test_api_types import make_cluster
    d = make_cluster(name, accelerator="v5e", topology="2x2",
                     replicas=1).to_dict()
    d["metadata"]["labels"] = {"team": "ml"}
    d["spec"]["workerGroupSpecs"][0]["maxReplicas"] = 10
    return d


@pytest.fixture()
def api():
    from kuberay_tpu.apiserver.server import serve_background
    st = ObjectStore()
    st.create(_valid_cluster_dict())
    srv, url = serve_background(st)
    yield st, url
    srv.shutdown()


def _http_patch(url, path, body, ctype, expect=200, query=""):
    req = urllib.request.Request(
        url + path + query, data=json.dumps(body).encode(),
        method="PATCH", headers={"Content-Type": ctype})
    try:
        with urllib.request.urlopen(req, timeout=5) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        assert e.code == expect, (e.code, e.read())
        return e.code, json.loads(e.read() or b"{}")


CL = "/apis/tpu.dev/v1/namespaces/default/tpuclusters/c1"


def test_http_merge_and_strategic_patch(api):
    st, url = api
    code, out = _http_patch(url, CL, {"spec": {"suspend": True}},
                            "application/merge-patch+json")
    assert code == 200 and out["spec"]["suspend"] is True
    code, out = _http_patch(
        url, CL,
        {"spec": {"workerGroupSpecs": [{"groupName": "workers",
                                        "replicas": 3}]}},
        "application/strategic-merge-patch+json")
    assert code == 200
    assert out["spec"]["workerGroupSpecs"][0]["replicas"] == 3
    assert out["spec"]["workerGroupSpecs"][0]["topology"] == "2x2"


def test_http_json_patch_and_unsupported_ctype(api):
    st, url = api
    code, out = _http_patch(
        url, CL,
        [{"op": "replace", "path": "/spec/workerGroupSpecs/0/replicas",
          "value": 2}],
        "application/json-patch+json")
    assert code == 200
    _http_patch(url, CL, {}, "text/plain", expect=415)


def test_http_apply_flow(api):
    st, url = api
    applied = _valid_cluster_dict("c2")
    applied["metadata"].pop("labels", None)
    path = "/apis/tpu.dev/v1/namespaces/default/tpuclusters/c2"
    # apply without fieldManager -> 422
    _http_patch(url, path, applied, "application/apply-patch+yaml",
                expect=422)
    code, out = _http_patch(url, path, applied,
                            "application/apply-patch+yaml",
                            query="?fieldManager=tpuctl")
    assert code == 200 and out["metadata"]["managedFields"]
    # conflicting apply -> 409 with the owner named; force -> 200
    applied2 = json.loads(json.dumps(applied))
    applied2["spec"]["workerGroupSpecs"][0]["replicas"] = 5
    code, body = _http_patch(url, path, applied2,
                             "application/apply-patch+yaml",
                             query="?fieldManager=other", expect=409)
    assert "tpuctl" in body.get("message", "")
    code, out = _http_patch(url, path, applied2,
                            "application/apply-patch+yaml",
                            query="?fieldManager=other&force=true")
    assert out["spec"]["workerGroupSpecs"][0]["replicas"] == 5


def test_http_patch_validation_rejects_bad_spec(api):
    st, url = api
    # Admission runs on the PATCHED object: invalid replicas bounds.
    _http_patch(url, CL,
                {"spec": {"workerGroupSpecs": [
                    {"groupName": "workers", "replicas": -5}]}},
                "application/strategic-merge-patch+json", expect=422)


def test_rest_store_patch_roundtrip(api):
    st, url = api
    from kuberay_tpu.controlplane.rest_store import RestObjectStore
    rs = RestObjectStore(url)
    out = rs.patch("TpuCluster", "c1", "default",
                   {"spec": {"suspend": True}})
    assert out["spec"]["suspend"] is True
    rs.patch_labels("TpuCluster", "c1", "default",
                    {"team": None, "tier": "prod"})
    got = rs.get("TpuCluster", "c1")
    assert got["metadata"]["labels"] == {"tier": "prod"}
    rs.add_finalizer("TpuCluster", "c1", "default", "tpu.dev/x")
    rs.add_finalizer("TpuCluster", "c1", "default", "tpu.dev/x")
    assert rs.get("TpuCluster", "c1")["metadata"]["finalizers"] == \
        ["tpu.dev/x"]
    rs.remove_finalizer("TpuCluster", "c1", "default", "tpu.dev/x")
    assert rs.get("TpuCluster", "c1")["metadata"].get("finalizers",
                                                      []) == []


def test_autoscaler_scales_via_patch(api):
    st, url = api
    from kuberay_tpu.controlplane.autoscaler import (
        GroupDecision,
        apply_decisions,
    )
    from kuberay_tpu.controlplane.rest_store import RestObjectStore
    rs = RestObjectStore(url)
    # Concurrent spec edit between decision and patch must survive.
    st.patch("TpuCluster", "c1", "default",
             {"metadata": {"annotations": {"touched": "yes"}}})
    ok = apply_decisions(rs, "c1", "default",
                         [GroupDecision("workers", 4, ["c1-workers-s0"])])
    assert ok
    got = st.get("TpuCluster", "c1")
    g = got["spec"]["workerGroupSpecs"][0]
    assert g["replicas"] == 4
    assert g["scaleStrategy"]["slicesToDelete"] == ["c1-workers-s0"]
    assert g["topology"] == "2x2"                       # untouched
    assert got["metadata"]["annotations"]["touched"] == "yes"
    # Unknown group: never appended.
    ok = apply_decisions(rs, "c1", "default",
                         [GroupDecision("ghost", 1, [])])
    assert not ok
    assert len(st.get("TpuCluster",
                      "c1")["spec"]["workerGroupSpecs"]) == 1
