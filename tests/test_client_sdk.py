"""Python SDK: builder/director presets, spec-surgery utils, typed APIs
with wait-helpers driven against a live operator (ref
clients/python-client tests + kuberay_cluster_builder.py examples)."""

import threading

import pytest

from kuberay_tpu.api.config import OperatorConfiguration
from kuberay_tpu.client import (
    ApiClient,
    ClusterBuilder,
    Director,
    TpuClusterApi,
    TpuJobApi,
    WaitTimeout,
    utils,
)
from kuberay_tpu.operator import Operator
from kuberay_tpu.utils.validation import validate_cluster
from kuberay_tpu.api.tpucluster import TpuCluster


# ---------------------------------------------------------------------------
# Builder / director (no server needed)


def test_builder_fluent_build():
    doc = (ClusterBuilder()
           .with_meta("b1", labels={"team": "ml"})
           .with_head(image="img:1", env={"A": "1"}, enable_ingress=True)
           .with_worker_group("w", "v5e", "4x4", 2, image="img:1")
           .with_autoscaling(1, 4)
           .build())
    assert doc["kind"] == "TpuCluster"
    assert doc["metadata"]["labels"] == {"team": "ml"}
    assert doc["spec"]["headGroupSpec"]["enableIngress"] is True
    g = doc["spec"]["workerGroupSpecs"][0]
    assert (g["replicas"], g["accelerator"], g["topology"]) == (2, "v5e", "4x4")
    # Autoscaling lands on the canonical knobs the operator consumes.
    assert doc["spec"]["enableInTreeAutoscaling"] is True
    assert (g["minReplicas"], g["maxReplicas"]) == (1, 4)
    # Build output passes the admission validator.
    assert validate_cluster(TpuCluster.from_dict(doc)) == []


def test_builder_rejects_bad_topology():
    with pytest.raises(ValueError):
        ClusterBuilder().with_meta("x").with_worker_group(
            "w", "v5e", "3x5", 1)


def test_builder_requires_name():
    with pytest.raises(ValueError):
        ClusterBuilder().with_head().build()


def test_director_presets_validate():
    d = Director()
    for doc in (d.build_basic_cluster("a"), d.build_small_cluster("b"),
                d.build_medium_cluster("c"), d.build_large_cluster("d")):
        assert validate_cluster(TpuCluster.from_dict(doc)) == [], doc["metadata"]
    large = d.build_large_cluster("d")
    g = large["spec"]["workerGroupSpecs"][0]
    assert (g["accelerator"], g["replicas"]) == ("v6e", 4)


def test_spec_surgery_utils():
    doc = Director().build_small_cluster("s")
    doc = utils.duplicate_worker_group(doc, "workers", "workers-b")
    assert [g["groupName"] for g in doc["spec"]["workerGroupSpecs"]] == \
        ["workers", "workers-b"]
    doc = utils.update_worker_group_slices(doc, "workers-b", 3)
    assert doc["spec"]["workerGroupSpecs"][1]["replicas"] == 3
    doc = utils.delete_worker_group(doc, "workers")
    assert [g["groupName"] for g in doc["spec"]["workerGroupSpecs"]] == \
        ["workers-b"]
    with pytest.raises(KeyError):
        utils.delete_worker_group(doc, "nope")
    with pytest.raises(ValueError):
        utils.duplicate_worker_group(doc, "workers-b", "workers-b")


# ---------------------------------------------------------------------------
# Typed APIs against a live operator


@pytest.fixture()
def live_op():
    from kuberay_tpu.runtime.coordinator_client import FakeCoordinatorClient

    coord = FakeCoordinatorClient()
    op = Operator(OperatorConfiguration(), fake_kubelet=True,
                  client_provider=lambda _status: coord)
    op.start(leader_election=False)
    stop = threading.Event()

    def pump():   # drive reconciles + fake kubelet while tests wait;
        # auto-advance submitted jobs PENDING -> RUNNING -> SUCCEEDED
        # (the fake coordinator's driver stand-in)
        while not stop.is_set():
            op.run_until_idle()
            for info in coord.list_jobs():
                if info.status == "PENDING":
                    coord.set_job_status(info.job_id, "RUNNING")
                elif info.status == "RUNNING":
                    coord.set_job_status(info.job_id, "SUCCEEDED")
            stop.wait(0.05)

    t = threading.Thread(target=pump, daemon=True)
    t.start()
    try:
        yield op
    finally:
        stop.set()
        t.join(timeout=5)
        op.stop()


def test_cluster_api_lifecycle(live_op):
    api = ApiClient(live_op.api_url)
    clusters = TpuClusterApi(api)
    clusters.create(Director().build_small_cluster("sdk-c1"))
    status = clusters.wait_until_ready("sdk-c1", timeout=60)
    assert status["state"] == "ready"

    clusters.scale_worker_group("sdk-c1", "workers", 2)
    assert clusters.get("sdk-c1")["spec"]["workerGroupSpecs"][0][
        "replicas"] == 2
    # The operator actually executes the scale (the old alias-keyed write
    # was silently ignored): a second slice's pods appear.  State stays
    # "ready" during scale-up, so wait on the slice count itself.
    assert clusters._wait("sdk-c1", "default",
                          lambda s: s.get("readySlices") == 2,
                          60, 0.2, "readySlices == 2")

    clusters.suspend("sdk-c1")
    assert clusters._wait("sdk-c1", "default",
                          lambda s: s.get("state") == "suspended",
                          30, 0.2, "suspended")["state"] == "suspended"
    clusters.resume("sdk-c1")
    assert clusters.wait_until_ready("sdk-c1", timeout=60)["state"] == "ready"

    assert clusters.delete("sdk-c1") is True
    assert clusters.delete("sdk-c1") is False   # already gone


def test_job_api_submit_and_wait(live_op):
    api = ApiClient(live_op.api_url)
    jobs = TpuJobApi(api)
    jobs.submit(Director().build_job("sdk-j1", "python train.py",
                                     submission_mode="HTTPMode"))
    status = jobs.wait_until_running("sdk-j1", timeout=60)
    assert status["jobDeploymentStatus"] in ("Running", "Complete")
    status = jobs.wait_until_finished("sdk-j1", timeout=120)
    assert status["jobDeploymentStatus"] == "Complete"
    assert jobs.succeeded("sdk-j1")


def test_wait_timeout_carries_status(live_op):
    api = ApiClient(live_op.api_url)
    clusters = TpuClusterApi(api)
    doc = Director().build_small_cluster("sdk-slow")
    doc["spec"]["suspend"] = True          # will never reach ready
    clusters.create(doc)
    with pytest.raises(WaitTimeout) as ei:
        clusters.wait_until_ready("sdk-slow", timeout=1.2, poll=0.2)
    assert isinstance(ei.value.last_status, dict)
