"""Watch bookmark / resume gate (ISSUE 6): the store→informer path.

- ``events_since(strict=True)`` turns backlog truncation into a typed
  :class:`ExpiredError` (the 410-Gone analogue) carrying rv + latest;
- backlog evictions are counted and surfaced
  (``tpu_watch_backlog_evictions_total``);
- periodic BOOKMARK events carry the high-water rv to subscribers —
  never entering the backlog or the journal;
- a reconnecting Manager resumes O(delta): it replays exactly the
  missed events, and only an expired backlog degrades to a relist
  scoped to its REGISTERED kinds — never the whole store;
- sim-gated: a mid-run informer restart + resume converges with a
  journal byte-identical to the no-restart run.
"""

import pytest

from kuberay_tpu.controlplane.manager import Manager
from kuberay_tpu.controlplane.store import Event, ExpiredError, ObjectStore
from kuberay_tpu.sim.harness import SimHarness
from kuberay_tpu.sim.scenarios import make_cluster_obj
from kuberay_tpu.utils import constants as C
from kuberay_tpu.utils.metrics import ControlPlaneMetrics


def _mk(store, kind, name, ns="default"):
    return store.create({"apiVersion": "v1", "kind": kind,
                         "metadata": {"name": name, "namespace": ns},
                         "spec": {}})


# ---------------------------------------------------------------------------
# ExpiredError + eviction accounting
# ---------------------------------------------------------------------------

def test_events_since_strict_raises_typed_expired():
    store = ObjectStore(backlog_max=5)
    for i in range(12):
        _mk(store, "Thing", f"t-{i}")
    # Non-strict keeps the flag contract (apiserver compatibility)...
    events, latest, truncated = store.events_since(1)
    assert truncated and latest == 12
    # ...strict turns it into the 410 analogue with resume metadata.
    with pytest.raises(ExpiredError) as ei:
        store.events_since(1, strict=True)
    assert ei.value.rv == 1 and ei.value.latest == 12
    # A reachable rv never raises.
    events, latest, truncated = store.events_since(11, strict=True)
    assert not truncated and [erv for erv, _ in events] == [12]


def test_backlog_evictions_counted_and_metered():
    metrics = ControlPlaneMetrics()
    store = ObjectStore(backlog_max=4, metrics=metrics)
    for i in range(10):
        _mk(store, "Thing", f"t-{i}")
    assert store.backlog_evictions_total() == 6
    text = metrics.render()
    assert "tpu_watch_backlog_evictions_total 6.0" in text


def test_backlog_max_is_honored():
    store = ObjectStore(backlog_max=3)
    for i in range(8):
        _mk(store, "Thing", f"t-{i}")
    events, latest, truncated = store.events_since(0)
    assert len(events) == 3 and truncated
    with pytest.raises(ValueError):
        ObjectStore(backlog_max=0)


# ---------------------------------------------------------------------------
# bookmarks
# ---------------------------------------------------------------------------

def test_bookmarks_reach_subscribers_but_not_backlog():
    store = ObjectStore(bookmark_interval=3)
    seen = []
    store.watch(lambda ev: seen.append(
        (ev.type, ev.obj.get("metadata", {}).get("resourceVersion"))))
    for i in range(7):
        _mk(store, "Thing", f"t-{i}")
    bookmarks = [rv for t, rv in seen if t == Event.BOOKMARK]
    # rv 3 and 6 cross the interval; each bookmark carries the
    # high-water rv at emission.
    assert bookmarks == [3, 6]
    # The backlog holds only real state events (journal-hash contract).
    events, _, _ = store.events_since(0)
    assert all(ev.type != Event.BOOKMARK for _, ev in events)
    assert len(events) == 7


def test_bookmark_advances_manager_resume_point_past_dropped_spans():
    """Chaos drops every delivery, bookmarks still arrive (they bypass
    the interposer): the manager's resume point keeps advancing, so a
    resume replays a small tail instead of the whole history."""

    class DropAll:
        def on_mutation(self, *a):
            return None

        def on_event(self, ev):
            return []      # drop every real delivery

    store = ObjectStore(bookmark_interval=5)
    manager = Manager(store)
    manager.register("Thing", lambda name, ns: None)
    store.set_interposer(DropAll())
    for i in range(23):
        _mk(store, "Thing", f"t-{i}")
    store.set_interposer(None)
    # Deliveries were all dropped, yet the bookmark high-water advanced.
    assert manager.last_rv == 20
    report = manager.resume()
    assert report["mode"] == "delta"
    assert report["count"] == 3          # only the post-bookmark tail
    assert manager.last_rv == 23


# ---------------------------------------------------------------------------
# O(delta) resume / scoped relist
# ---------------------------------------------------------------------------

def test_disconnected_manager_resumes_with_exact_delta():
    store = ObjectStore()
    manager = Manager(store)
    reconciled = []
    manager.register("Thing", lambda name, ns: reconciled.append(name)
                     or None)
    for i in range(50):
        _mk(store, "Thing", f"t-{i}")
    manager.run_until_idle()
    reconciled.clear()

    manager.disconnect_informer()
    # Three mutations while the informer is down.
    for name in ("t-3", "t-17", "t-41"):
        cur = store.get("Thing", name)
        cur["spec"] = {"rev": 1}
        store.update(cur)
    report = manager.reconnect_informer()
    assert report == {"mode": "delta", "count": 3,
                      "rv": store.resource_version()}
    manager.run_until_idle()
    # O(delta): exactly the touched objects reconciled, not all 50.
    assert sorted(reconciled) == ["t-17", "t-3", "t-41"]


def test_expired_resume_falls_back_to_scoped_relist():
    """After the delta fell off the backlog, resume relists ONLY the
    registered kinds: foreign kinds (here 30 Pods) are never enqueued —
    the restarted shard rejoins in O(subscribed), not O(world)."""
    store = ObjectStore(backlog_max=8)
    manager = Manager(store)
    reconciled = []
    manager.register("Thing", lambda name, ns: reconciled.append(name)
                     or None)
    for i in range(10):
        _mk(store, "Thing", f"t-{i}")
    for i in range(30):
        _mk(store, "Pod", f"p-{i}")      # unregistered kind: out of scope
    manager.run_until_idle()
    reconciled.clear()

    manager.disconnect_informer()
    for i in range(20):                  # blow past backlog_max=8
        cur = store.get("Thing", "t-0")
        cur["spec"] = {"rev": i}
        store.update(cur)
    report = manager.reconnect_informer()
    assert report["mode"] == "relist"
    assert report["count"] == 10         # scoped: Things only, no Pods
    assert report["rv"] == store.resource_version()
    manager.run_until_idle()
    assert sorted(set(reconciled)) == sorted(f"t-{i}" for i in range(10))


# ---------------------------------------------------------------------------
# sim-gated: restart+resume replays to an identical journal
# ---------------------------------------------------------------------------

def _workload_hash(restart: bool) -> str:
    with SimHarness(7) as h:
        h.store.create(make_cluster_obj("alpha", replicas=2,
                                        max_replicas=4))
        h.settle()
        for i, replicas in enumerate((3, 1, 4)):
            outage = restart and i == 1
            if outage:
                h.manager.disconnect_informer()
            cluster = h.store.get(C.KIND_CLUSTER, "alpha")
            cluster["spec"]["workerGroupSpecs"][0]["replicas"] = replicas
            h.store.update(cluster)
            if outage:
                report = h.manager.reconnect_informer()
                assert report["mode"] == "delta"
                assert report["count"] >= 1
            h.settle()
        h._drain_journal()
        return h.journal_hash()


@pytest.mark.timeout(120)
def test_restart_resume_journal_identical_to_no_restart_run():
    assert _workload_hash(restart=False) == _workload_hash(restart=True)
