"""Distributed-state race tests (SURVEY §5.2: the reference runs Go -race
in CI and mitigates logical races architecturally — expectations, single
writer, optimistic concurrency).  Here: hammer the store and controllers
from many threads and assert the invariants hold at quiescence."""

import threading
import time

import pytest

from kuberay_tpu.controlplane.store import (
    AlreadyExists,
    Conflict,
    NotFound,
    ObjectStore,
)
from kuberay_tpu.utils import constants as C
from tests.test_api_types import make_cluster
from tests.test_cluster_controller import Harness


def test_store_concurrent_updates_conflict_correctly():
    """Optimistic concurrency: N racers increment a counter via
    read-modify-write with rv checks; total must equal successful writes."""
    store = ObjectStore()
    store.create({"apiVersion": "v1", "kind": "Counter",
                  "metadata": {"name": "c"}, "spec": {"n": 0}, "status": {}})
    successes = []
    lock = threading.Lock()

    def racer():
        for _ in range(50):
            obj = store.get("Counter", "c")
            obj["spec"]["n"] += 1
            try:
                store.update(obj)
                with lock:
                    successes.append(1)
            except Conflict:
                pass

    threads = [threading.Thread(target=racer) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    final = store.get("Counter", "c")["spec"]["n"]
    assert final == len(successes)
    assert final >= 50  # at least one thread's worth made it


def test_store_concurrent_create_exactly_once():
    store = ObjectStore()
    wins = []
    lock = threading.Lock()

    def creator(i):
        try:
            store.create({"apiVersion": "v1", "kind": "X",
                          "metadata": {"name": "solo"}, "spec": {"by": i},
                          "status": {}})
            with lock:
                wins.append(i)
        except AlreadyExists:
            pass

    threads = [threading.Thread(target=creator, args=(i,)) for i in range(16)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(wins) == 1
    assert store.count("X") == 1


def test_threaded_reconcile_no_partial_slices():
    """Concurrent reconciles + kubelet churn + scale flapping: at
    quiescence every surviving slice is complete (the core invariant)."""
    h = Harness()
    c = make_cluster(accelerator="v5p", topology="2x2x2", replicas=2)
    c.spec.workerGroupSpecs[0].maxReplicas = 4
    h.store.create(c.to_dict())
    h.manager.start(workers=3)
    stop = threading.Event()

    def kubelet_loop():
        while not stop.is_set():
            h.kubelet.step()
            time.sleep(0.01)

    def flapper():
        for replicas in (3, 1, 4, 2, 3, 2):
            for _ in range(5):
                try:
                    obj = h.store.get(C.KIND_CLUSTER, "demo")
                    obj["spec"]["workerGroupSpecs"][0]["replicas"] = replicas
                    h.store.update(obj)
                    break
                except Conflict:
                    time.sleep(0.01)
            time.sleep(0.08)

    kt = threading.Thread(target=kubelet_loop)
    ft = threading.Thread(target=flapper)
    kt.start()
    ft.start()
    ft.join()
    time.sleep(1.0)
    # Let everything settle.
    deadline = time.time() + 20
    while time.time() < deadline:
        h.manager.flush_delayed()
        time.sleep(0.3)
        cluster = h.store.get(C.KIND_CLUSTER, "demo")
        if cluster.get("status", {}).get("readySlices") == 2:
            break
    stop.set()
    kt.join()
    h.manager.stop()

    workers = h.store.list("Pod", labels={C.LABEL_NODE_TYPE: "worker"})
    by_slice = {}
    for p in workers:
        if p["metadata"].get("deletionTimestamp"):
            continue
        by_slice.setdefault(
            p["metadata"]["labels"][C.LABEL_SLICE_NAME], []).append(p)
    # Invariant: every surviving slice has exactly its full host set.
    for sname, plist in by_slice.items():
        hosts = {p["metadata"]["labels"][C.LABEL_HOST_INDEX] for p in plist}
        assert hosts == {"0", "1"}, (sname, hosts)
    assert len(by_slice) == 2
    cluster = h.store.get(C.KIND_CLUSTER, "demo")
    assert cluster["status"]["readySlices"] == 2


def test_expectations_timeout_expiry():
    """A create whose watch event never arrives must unblock the group
    after the timeout (the reference's 30s expectation expiry) — otherwise
    a lost event wedges scaling forever."""
    from kuberay_tpu.controlplane.expectations import ScaleExpectations
    exp = ScaleExpectations(timeout=0.2)
    exp.expect_create("default", "c1", "workers", "pod-a")
    assert not exp.satisfied("default", "c1", "workers")
    time.sleep(0.25)
    assert exp.satisfied("default", "c1", "workers")
    # And a fresh expectation still blocks again.
    exp.expect_delete("default", "c1", "workers", "pod-b")
    assert not exp.satisfied("default", "c1", "workers")
    exp.observe_pod_event("default", "c1", "workers", "pod-b", "DELETED")
    assert exp.satisfied("default", "c1", "workers")


def test_watchers_never_poison_store():
    """A crashing watcher must not break writers (ref: informer isolation)."""
    store = ObjectStore()

    def bad_watcher(ev):
        raise RuntimeError("boom")
    store.watch(bad_watcher)
    store.create(make_cluster().to_dict())     # must not raise
    assert store.count(C.KIND_CLUSTER) == 1


def test_store_journal_survives_restart(tmp_path):
    """etcd-lite durability: the standalone operator's CRs and statuses
    replay across restarts (SURVEY §5.4 resume-after-restart)."""
    journal = str(tmp_path / "store.journal")
    s1 = ObjectStore(journal_path=journal)
    c = make_cluster(name="durable").to_dict()
    s1.create(c)
    obj = s1.get(C.KIND_CLUSTER, "durable")
    obj["status"] = {"state": "ready", "readySlices": 1}
    s1.update_status(obj)
    s1.create({"apiVersion": "v1", "kind": "Pod",
               "metadata": {"name": "p1", "namespace": "default",
                            "labels": {C.LABEL_CLUSTER: "durable"}},
               "spec": {}, "status": {"phase": "Running"}})
    s1.delete("Pod", "p1")     # deletions must replay too
    rv = s1.resource_version()

    s2 = ObjectStore(journal_path=journal)
    got = s2.get(C.KIND_CLUSTER, "durable")
    assert got["status"]["state"] == "ready"
    assert s2.try_get("Pod", "p1") is None
    assert s2.resource_version() >= rv - 1
    # Writes continue after replay (rv monotonicity preserved).
    got["spec"]["workerGroupSpecs"][0]["replicas"] = 0
    s2.update(got)
    # Label index rebuilt from the journal.
    s2.create({"apiVersion": "v1", "kind": "Pod",
               "metadata": {"name": "p2", "namespace": "default",
                            "labels": {C.LABEL_CLUSTER: "durable"}},
               "spec": {}, "status": {}})
    assert len(s2.list("Pod", labels={C.LABEL_CLUSTER: "durable"})) == 1


def test_store_journal_compaction(tmp_path):
    import os
    journal = str(tmp_path / "c.journal")
    s1 = ObjectStore(journal_path=journal, journal_compact_bytes=20_000)
    for i in range(120):
        s1.create({"apiVersion": "v1", "kind": "Pod",
                   "metadata": {"name": f"p{i}", "namespace": "default"},
                   "spec": {"i": i}, "status": {}})
        if i >= 60:
            s1.delete("Pod", f"p{i - 60}")
    size = os.path.getsize(journal)
    assert size < 200_000
    s2 = ObjectStore(journal_path=journal)
    assert s2.count("Pod") == s1.count("Pod")
