"""Serving engine: cache correctness + continuous batching behavior."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kuberay_tpu.models import llama
from kuberay_tpu.serve.engine import Request, ServeEngine, _bucket
from kuberay_tpu.serve.kv_cache import forward_with_cache, init_kv_cache

CFG = llama.CONFIGS["llama_tiny"]


@pytest.fixture(scope="module")
def params():
    return llama.init_params(CFG, jax.random.PRNGKey(0))


def test_cache_matches_full_forward(params):
    """Prefill+decode through the cache == one-shot full forward."""
    tokens = jax.random.randint(jax.random.PRNGKey(1), (1, 12), 0,
                                CFG.vocab_size)
    full_logits = llama.forward(CFG, params, tokens)

    cache = init_kv_cache(CFG, slots=1, max_len=32)
    # Prefill first 8, then decode 4 one at a time.
    logits_p, cache = forward_with_cache(
        CFG, params, tokens[:, :8], cache, jnp.zeros(1, jnp.int32))
    np.testing.assert_allclose(np.asarray(logits_p),
                               np.asarray(full_logits[:, :8]),
                               rtol=2e-3, atol=2e-3)
    for t in range(8, 12):
        logits_t, cache = forward_with_cache(
            CFG, params, tokens[:, t:t + 1], cache,
            jnp.array([t], jnp.int32))
        np.testing.assert_allclose(np.asarray(logits_t[:, 0]),
                                   np.asarray(full_logits[:, t]),
                                   rtol=2e-3, atol=2e-3)


def test_engine_greedy_matches_naive(params):
    """Engine generation == naive argmax loop over the full forward."""
    prompt = [5, 17, 42, 7]
    n_new = 6
    # Naive: repeatedly run the full model.
    seq = list(prompt)
    for _ in range(n_new):
        logits = llama.forward(CFG, params, jnp.asarray([seq]))
        seq.append(int(jnp.argmax(logits[0, -1])))
    expected = seq[len(prompt):]

    eng = ServeEngine(CFG, params, max_slots=2, max_len=64)
    eng.add_request(Request("r1", prompt, max_new_tokens=n_new))
    out = eng.run()
    assert len(out) == 1
    assert out[0].request_id == "r1"
    assert out[0].tokens == expected


def test_continuous_batching_multiple_requests(params):
    eng = ServeEngine(CFG, params, max_slots=2, max_len=64)
    for i in range(4):   # more requests than slots
        eng.add_request(Request(f"r{i}", [1 + i, 2 + i, 3 + i],
                                max_new_tokens=4))
    out = eng.run()
    assert {r.request_id for r in out} == {"r0", "r1", "r2", "r3"}
    assert all(len(r.tokens) == 4 for r in out)
    assert all(r.finish_reason == "length" for r in out)


def test_batched_decode_isolated_per_slot(params):
    """A request's output must not depend on its neighbors in the batch."""
    prompt = [9, 8, 7]
    eng_solo = ServeEngine(CFG, params, max_slots=2, max_len=64)
    eng_solo.add_request(Request("solo", prompt, max_new_tokens=5))
    solo = {r.request_id: r.tokens for r in eng_solo.run()}["solo"]

    eng_busy = ServeEngine(CFG, params, max_slots=2, max_len=64)
    eng_busy.add_request(Request("other", [30, 31, 32, 33, 34],
                                 max_new_tokens=5))
    eng_busy.add_request(Request("solo", prompt, max_new_tokens=5))
    busy = {r.request_id: r.tokens for r in eng_busy.run()}["solo"]
    assert solo == busy


def test_prefill_does_not_corrupt_neighbor_cache(params):
    """Admitting request B mid-way through A's decode must not change A's
    output (B's prefill writes only its own slot's cache rows)."""
    prompt_a = [9, 8, 7]
    eng_solo = ServeEngine(CFG, params, max_slots=2, max_len=64)
    eng_solo.add_request(Request("a", prompt_a, max_new_tokens=8))
    solo = {r.request_id: r.tokens for r in eng_solo.run()}["a"]

    eng = ServeEngine(CFG, params, max_slots=2, max_len=64)
    eng.add_request(Request("a", prompt_a, max_new_tokens=8))
    eng.step()          # A prefills
    eng.step()          # A decodes once
    eng.add_request(Request("b", [40, 41, 42, 43], max_new_tokens=8))
    out = {r.request_id: r.tokens for r in eng.run()}
    assert out["a"] == solo, "B's admission corrupted A's KV cache"
    assert len(out["b"]) == 8


def test_eos_stops_generation(params):
    eng = ServeEngine(CFG, params, max_slots=1, max_len=64)
    # Find greedy first token, use it as EOS -> must stop after 1 token.
    probe = ServeEngine(CFG, params, max_slots=1, max_len=64)
    probe.add_request(Request("p", [3, 4], max_new_tokens=1))
    first = probe.run()[0].tokens[0]
    eng.add_request(Request("r", [3, 4], max_new_tokens=10, eos_token=first))
    out = eng.run()
    assert out[0].finish_reason == "eos"
    assert out[0].tokens == [first]


def test_oversized_prompt_cancelled(params):
    eng = ServeEngine(CFG, params, max_slots=1, max_len=16)
    eng.add_request(Request("big", list(range(20)), max_new_tokens=4))
    out = eng.run()
    assert out[0].finish_reason == "cancelled"


def test_mixtral_serving():
    """The MoE model family serves through the same engine: cache decode
    matches the full forward, generation works end to end."""
    import jax.numpy as jnp
    from kuberay_tpu.models import mixtral
    from kuberay_tpu.serve.kv_cache import (
        forward_with_cache_mixtral, init_kv_cache)

    # Ample expert capacity: full-pass and incremental routing only agree
    # when no token is capacity-dropped (drops depend on batch contention,
    # which single-token decode doesn't have).
    import dataclasses
    mcfg = dataclasses.replace(mixtral.CONFIGS["mixtral_tiny"],
                               capacity_factor=8.0)
    mparams = mixtral.init_params(mcfg, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (1, 10), 0,
                                mcfg.vocab_size)
    full_logits, _ = mixtral.forward(mcfg, mparams, tokens)
    cache = init_kv_cache(mcfg, slots=1, max_len=32)
    logits_p, cache = forward_with_cache_mixtral(
        mcfg, mparams, tokens[:, :6], cache, jnp.zeros(1, jnp.int32))
    np.testing.assert_allclose(np.asarray(logits_p),
                               np.asarray(full_logits[:, :6]),
                               rtol=3e-3, atol=3e-3)
    for t in range(6, 10):
        logits_t, cache = forward_with_cache_mixtral(
            mcfg, mparams, tokens[:, t:t + 1], cache,
            jnp.array([t], jnp.int32))
        np.testing.assert_allclose(np.asarray(logits_t[:, 0]),
                                   np.asarray(full_logits[:, t]),
                                   rtol=3e-3, atol=3e-3)

    eng = ServeEngine(mcfg, mparams, max_slots=2, max_len=64)
    eng.add_request(Request("moe", [3, 4, 5], max_new_tokens=4))
    out = eng.run()
    assert out[0].tokens and len(out[0].tokens) == 4


def test_mixtral_slot_isolation_default_capacity():
    """With the DEFAULT (tight) capacity factor, a request's MoE routing
    must not be perturbed by other slots' tokens — padding/inactive slots
    claim no expert capacity (token masks in moe_ffn)."""
    from kuberay_tpu.models import mixtral

    mcfg = mixtral.CONFIGS["mixtral_tiny"]   # capacity_factor 1.25
    mparams = mixtral.init_params(mcfg, jax.random.PRNGKey(0))
    prompt = [9, 8, 7]

    solo_eng = ServeEngine(mcfg, mparams, max_slots=4, max_len=64)
    solo_eng.add_request(Request("solo", prompt, max_new_tokens=5))
    solo = {r.request_id: r.tokens for r in solo_eng.run()}["solo"]

    busy_eng = ServeEngine(mcfg, mparams, max_slots=4, max_len=64)
    for i in range(3):
        busy_eng.add_request(Request(f"noise{i}",
                                     [40 + i, 50 + i, 60 + i, 70 + i],
                                     max_new_tokens=5))
    busy_eng.add_request(Request("solo", prompt, max_new_tokens=5))
    busy = {r.request_id: r.tokens for r in busy_eng.run()}["solo"]
    assert solo == busy, "MoE routing leaked across serving slots"


def test_bucket():
    assert _bucket(5) == 32
    assert _bucket(33) == 64
    assert _bucket(9999) == 2048


def test_top_k_and_top_p_sampling_semantics():
    """top_k=1 is greedy at any temperature; a vanishing top_p nucleus
    is greedy; disabled filters (top_p=1, top_k=0) reproduce plain
    temperature sampling's support; filters restrict the support."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from kuberay_tpu.serve.engine import ServeEngine

    logits = jnp.asarray([2.0, 1.0, 0.5, -1.0, -3.0])
    keys = [jax.random.PRNGKey(i) for i in range(200)]

    def draws(temp, top_p=1.0, top_k=0, n=200):
        samp = jnp.asarray([temp, top_p, float(top_k)], jnp.float32)
        return {int(ServeEngine._sample(logits, k, samp)) for k in keys[:n]}

    # Greedy regardless of filters.
    assert draws(0.0) == {0}
    # top_k=1 == greedy even when sampling.
    assert draws(1.0, top_k=1) == {0}
    # Tiny nucleus: only the best token's mass fits.
    assert draws(1.0, top_p=1e-6) == {0}
    # Unfiltered sampling at high temperature reaches beyond the top.
    support = draws(5.0)
    assert len(support) >= 4
    # top_k=2 restricts support to the two best tokens.
    assert draws(5.0, top_k=2) <= {0, 1}
    # top_p nucleus: with these logits at temp=1, tokens 0+1 hold ~73%
    # of the mass, so top_p=0.5 keeps {0, 1} at most.
    assert draws(1.0, top_p=0.5) <= {0, 1}


def test_sampled_requests_with_filters_through_engine():
    """End-to-end: requests with top_p/top_k run through the engine
    (prefill + decode + HTTP-shaped params) and the same seed + params
    reproduce identical tokens."""
    import jax

    from kuberay_tpu.models import llama
    from kuberay_tpu.serve.engine import Request, ServeEngine

    cfg = llama.CONFIGS["llama_tiny"]
    params = llama.init_params(cfg, jax.random.PRNGKey(0))

    def run():
        eng = ServeEngine(cfg, params, max_slots=2, max_len=64)
        eng.add_request(Request("r0", [1, 2, 3], max_new_tokens=8,
                                temperature=0.9, top_p=0.8, top_k=12))
        eng.add_request(Request("r1", [4, 5], max_new_tokens=8,
                                temperature=0.0))
        return {r.request_id: r.tokens for r in eng.run()}

    a, b = run(), run()
    assert a == b                       # deterministic under fixed seed
    assert len(a["r0"]) == 8 and len(a["r1"]) == 8


def test_stop_token_ids():
    """Any listed stop token ends generation with reason 'eos', in
    plain decode AND mid-speculative-acceptance."""
    import jax

    from kuberay_tpu.models import llama
    from kuberay_tpu.serve.engine import Request, ServeEngine

    cfg = llama.CONFIGS["llama_tiny"]
    params = llama.init_params(cfg, jax.random.PRNGKey(0))

    # Discover what the model generates greedily, then stop on the 3rd
    # generated token.
    eng = ServeEngine(cfg, params, max_slots=2, max_len=64)
    eng.add_request(Request("probe", [1, 2, 3], max_new_tokens=10))
    probe = {r.request_id: r.tokens for r in eng.run()}["probe"]
    stop_at = probe[2]
    want = probe[:probe.index(stop_at) + 1]   # stop at FIRST occurrence

    eng = ServeEngine(cfg, params, max_slots=2, max_len=64)
    eng.add_request(Request("r", [1, 2, 3], max_new_tokens=10,
                            stop_token_ids=[9999, stop_at]))
    out = eng.run()
    assert out[0].tokens == want
    assert out[0].finish_reason == "eos"

    # Speculative path: same stop honored (repetitive prompt drafts).
    eng = ServeEngine(cfg, params, max_slots=2, max_len=128,
                      speculative=4)
    eng.add_request(Request("probe2", [7, 8, 9] * 8, max_new_tokens=16))
    probe2 = {r.request_id: r.tokens
              for r in eng.run()}["probe2"]
    if len(set(probe2)) > 1:
        stop2 = probe2[min(4, len(probe2) - 1)]
        want = probe2[:probe2.index(stop2) + 1]
        eng = ServeEngine(cfg, params, max_slots=2, max_len=128,
                          speculative=4)
        eng.add_request(Request("r2", [7, 8, 9] * 8, max_new_tokens=16,
                                stop_token_ids=[stop2]))
        out2 = eng.run()
        assert out2[0].tokens == want
        assert out2[0].finish_reason == "eos"


def test_serve_bench_matrix_harness_runs(tmp_path):
    """The published perf harness (benchmark/serve_bench.py --matrix)
    must keep running as engines evolve — it is the round's serving
    performance evidence (docs/serve_benchmark.md)."""
    import json
    import pathlib
    import subprocess
    import sys
    repo = pathlib.Path(__file__).resolve().parent.parent
    out_json = tmp_path / "m.json"
    proc = subprocess.run(
        [sys.executable, str(repo / "benchmark" / "serve_bench.py"),
         "--cpu", "--matrix", "--requests", "3", "--new", "4",
         "--prefix", "8", "--slots", "4",
         "--json-out", str(out_json)],
        capture_output=True, text=True, timeout=900, cwd=str(repo))
    assert proc.returncode == 0, proc.stdout + proc.stderr
    doc = json.loads(out_json.read_text())
    variants = {r["variant"] for r in doc["results"]}
    assert {"dense", "dense_int8kv", "w8a16", "chunked_prefill",
            "speculative", "streaming", "paged",
            "paged_int8kv"} <= variants
    for r in doc["results"]:
        assert r["tokens_per_sec"] > 0
        # TTFT rides the token hook; the bare dense baseline runs
        # hook-free so the streaming row can isolate the hook's cost.
        if r["variant"] == "dense":
            assert "ttft_p50_ms" not in r
        else:
            assert r["ttft_p50_ms"] is not None
