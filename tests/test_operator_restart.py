"""Operator restart / upgrade e2e.

Reference model: ``test/e2eupgrade`` (operator-version upgrade: the new
operator adopts CRs and pods created by the old one without churn) plus
the level-triggered-resume claim of SURVEY §5.4 ("control-plane state is
fully persisted in CR status; resume-after-operator-restart is free").

Here the persistence seam is the journaled ObjectStore: operator A
provisions a cluster, the process "dies", operator B replays the journal
and must (a) adopt everything without deleting or recreating a single
pod, and (b) still execute new spec changes.
"""

import pytest

from kuberay_tpu.api.config import OperatorConfiguration
from kuberay_tpu.controlplane.store import ObjectStore
from kuberay_tpu.operator import Operator
from kuberay_tpu.utils import constants as C
from kuberay_tpu.utils import features
from tests.test_api_types import make_cluster


@pytest.fixture(autouse=True)
def reset_gates():
    features.reset()
    yield
    features.reset()


def settle(op, rounds=8):
    for _ in range(rounds):
        op.run_until_idle()


def pod_uids(store):
    return {p["metadata"]["name"]: p["metadata"]["uid"]
            for p in store.list("Pod")}


def test_restart_adopts_without_churn(tmp_path):
    journal = str(tmp_path / "store.journal")

    # --- generation A: provision a multi-host cluster, then "crash". ---
    store_a = ObjectStore(journal_path=journal)
    op_a = Operator(OperatorConfiguration(), store=store_a, fake_kubelet=True)
    c = make_cluster(accelerator="v5p", topology="2x2x2", replicas=2)
    store_a.create(c.to_dict())
    settle(op_a)
    before = pod_uids(store_a)
    assert len(before) == 5          # head + 2 slices x 2 hosts
    status_a = store_a.get(C.KIND_CLUSTER, "demo")["status"]
    assert status_a["state"] == "ready"
    op_a.kubelet.close()             # process exit

    # --- generation B: fresh operator over the replayed journal. ---
    store_b = ObjectStore(journal_path=journal)
    op_b = Operator(OperatorConfiguration(), store=store_b, fake_kubelet=True)
    # Level-triggered: reconcile everything once, as informer sync would.
    for cl in store_b.list(C.KIND_CLUSTER):
        op_b.manager.enqueue((C.KIND_CLUSTER, "default",
                              cl["metadata"]["name"]))
    settle(op_b)

    after = pod_uids(store_b)
    assert after == before, "restart churned pods (uid or set changed)"
    status_b = store_b.get(C.KIND_CLUSTER, "demo")["status"]
    assert status_b["state"] == "ready"
    assert status_b["readySlices"] == 2

    # --- the new generation still acts on spec changes: scale 2 -> 3. ---
    obj = store_b.get(C.KIND_CLUSTER, "demo")
    obj["spec"]["workerGroupSpecs"][0]["replicas"] = 3
    obj["spec"]["workerGroupSpecs"][0]["maxReplicas"] = 3
    store_b.update(obj)
    settle(op_b)
    grown = pod_uids(store_b)
    assert len(grown) == 7           # head + 3 slices x 2 hosts
    # Old pods untouched; only the new slice's pods are new.
    assert all(grown[name] == uid for name, uid in before.items())
    assert store_b.get(C.KIND_CLUSTER, "demo")["status"]["readySlices"] == 3
    op_b.kubelet.close()


def test_restart_resumes_in_flight_scale_up(tmp_path):
    """Crash mid-provisioning: pods exist but the cluster is not ready yet.
    The next generation must finish the job, reusing the live pods."""
    journal = str(tmp_path / "store.journal")
    store_a = ObjectStore(journal_path=journal)
    op_a = Operator(OperatorConfiguration(), store=store_a, fake_kubelet=True)
    c = make_cluster(accelerator="v5e", topology="4x4", replicas=2)
    store_a.create(c.to_dict())
    # One reconcile pass only: pods created but still Pending, no status yet.
    op_a.manager.run_until_idle()
    created = pod_uids(store_a)
    assert created                      # something is in flight
    assert store_a.get(C.KIND_CLUSTER, "demo")["status"].get("state") != "ready"
    op_a.kubelet.close()

    store_b = ObjectStore(journal_path=journal)
    op_b = Operator(OperatorConfiguration(), store=store_b, fake_kubelet=True)
    for cl in store_b.list(C.KIND_CLUSTER):
        op_b.manager.enqueue((C.KIND_CLUSTER, "default",
                              cl["metadata"]["name"]))
    settle(op_b)
    assert store_b.get(C.KIND_CLUSTER, "demo")["status"]["state"] == "ready"
    after = pod_uids(store_b)
    # Pods that were already created survived the restart un-recreated.
    assert all(after[name] == uid for name, uid in created.items())
    op_b.kubelet.close()
