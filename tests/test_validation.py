"""Validation coverage modeled on utils/validation_test.go's table style."""

import pytest

from kuberay_tpu.api.common import ObjectMeta
from kuberay_tpu.api.tpucronjob import TpuCronJob, TpuCronJobSpec
from kuberay_tpu.api.tpujob import (
    DeletionRule,
    DeletionStrategy,
    JobSubmissionMode,
    TpuJob,
    TpuJobSpec,
)
from kuberay_tpu.api.tpuservice import (
    ClusterUpgradeOptions,
    ServiceUpgradeType,
    TpuService,
    TpuServiceSpec,
)
from kuberay_tpu.utils import features
from kuberay_tpu.utils.validation import (
    validate_cluster,
    validate_cronjob,
    validate_job,
    validate_service,
)
from tests.test_api_types import make_cluster, make_template


@pytest.fixture(autouse=True)
def reset_gates():
    features.reset()
    yield
    features.reset()


def test_valid_cluster_passes():
    assert validate_cluster(make_cluster()) == []


def test_bad_metadata_name():
    c = make_cluster(name="Bad_Name!")
    errs = validate_cluster(c)
    assert any("DNS-1123" in e for e in errs)
    c2 = make_cluster(name="")
    assert any("must be set" in e for e in validate_cluster(c2))


def test_duplicate_group_names():
    c = make_cluster()
    c.spec.workerGroupSpecs.append(c.spec.workerGroupSpecs[0])
    assert any("duplicated" in e for e in validate_cluster(c))


def test_bad_topology_reported():
    c = make_cluster(accelerator="v5e", topology="3x3")
    assert any("not divisible" in e for e in validate_cluster(c))
    c2 = make_cluster(accelerator="v5e", topology="2x12")
    assert any("node pool" in e for e in validate_cluster(c2))


def test_autoscaler_replica_bounds():
    c = make_cluster(replicas=5)
    c.spec.enableInTreeAutoscaling = True
    c.spec.workerGroupSpecs[0].maxReplicas = 3
    errs = validate_cluster(c)
    assert any("within" in e for e in errs)


def test_missing_head_container():
    c = make_cluster()
    c.spec.headGroupSpec.template.spec.containers = []
    assert any("headGroupSpec" in e for e in validate_cluster(c))


def make_job(**kw):
    spec = TpuJobSpec(entrypoint="python -m x", clusterSpec=make_cluster().spec)
    for k, v in kw.items():
        setattr(spec, k, v)
    return TpuJob(metadata=ObjectMeta(name="job"), spec=spec)


def test_valid_job_passes():
    assert validate_job(make_job()) == []


def test_job_cluster_spec_xor_selector():
    j = make_job()
    j.spec.clusterSelector = {"tpu.dev/cluster": "x"}
    assert any("mutually exclusive" in e for e in validate_job(j))
    j2 = make_job()
    j2.spec.clusterSpec = None
    assert any("one of" in e for e in validate_job(j2))


def test_job_interactive_mode_entrypoint():
    j = make_job(submissionMode=JobSubmissionMode.INTERACTIVE)
    assert any("empty in InteractiveMode" in e for e in validate_job(j))
    j2 = make_job(submissionMode=JobSubmissionMode.K8S_JOB, entrypoint="")
    assert any("entrypoint must be set" in e for e in validate_job(j2))


def test_job_deletion_rules_vs_shutdown():
    j = make_job(
        shutdownAfterJobFinishes=True,
        deletionStrategy=DeletionStrategy(
            rules=[DeletionRule(policy="DeleteCluster", condition="Succeeded")]
        ),
    )
    assert any("mutually exclusive" in e for e in validate_job(j))


def test_job_ttl_requires_shutdown():
    j = make_job(ttlSecondsAfterFinished=60, shutdownAfterJobFinishes=False)
    assert any("requires shutdownAfterJobFinishes" in e for e in validate_job(j))


def make_service(strategy=ServiceUpgradeType.NEW_CLUSTER):
    return TpuService(
        metadata=ObjectMeta(name="svc"),
        spec=TpuServiceSpec(
            serveConfig={"model": "llama3-8b"},
            clusterSpec=make_cluster().spec,
            upgradeStrategy=strategy,
        ),
    )


def test_valid_service_passes():
    assert validate_service(make_service()) == []


def test_service_incremental_requires_gate():
    s = make_service(ServiceUpgradeType.INCREMENTAL)
    assert any("gate" in e for e in validate_service(s))
    features.set_gates({"TpuServiceIncrementalUpgrade": True})
    assert validate_service(s) == []


def test_service_upgrade_options_bounds():
    features.set_gates({"TpuServiceIncrementalUpgrade": True})
    s = make_service(ServiceUpgradeType.INCREMENTAL)
    s.spec.upgradeOptions = ClusterUpgradeOptions(stepSizePercent=0)
    assert any("stepSizePercent" in e for e in validate_service(s))


def test_cronjob_requires_gate_and_schedule():
    cj = TpuCronJob(
        metadata=ObjectMeta(name="nightly"),
        spec=TpuCronJobSpec(
            schedule="0 3 * * *",
            jobTemplate=make_job().spec,
        ),
    )
    errs = validate_cronjob(cj)
    assert any("feature gate" in e for e in errs)
    features.set_gates({"TpuCronJob": True})
    assert validate_cronjob(cj) == []
    cj.spec.schedule = "not a cron"
    assert any("schedule" in e for e in validate_cronjob(cj))
