"""Validation coverage modeled on utils/validation_test.go's table style."""

import pytest

from kuberay_tpu.api.common import ObjectMeta
from kuberay_tpu.api.tpucronjob import TpuCronJob, TpuCronJobSpec
from kuberay_tpu.api.tpujob import (
    DeletionRule,
    DeletionStrategy,
    JobSubmissionMode,
    TpuJob,
    TpuJobSpec,
)
from kuberay_tpu.api.tpuservice import (
    ClusterUpgradeOptions,
    ServiceUpgradeType,
    TpuService,
    TpuServiceSpec,
)
from kuberay_tpu.utils import features
from kuberay_tpu.utils.validation import (
    validate_cluster,
    validate_cronjob,
    validate_job,
    validate_service,
)
from tests.test_api_types import make_cluster, make_template


@pytest.fixture(autouse=True)
def reset_gates():
    features.reset()
    yield
    features.reset()


def test_valid_cluster_passes():
    assert validate_cluster(make_cluster()) == []


def test_bad_metadata_name():
    c = make_cluster(name="Bad_Name!")
    errs = validate_cluster(c)
    assert any("DNS-1123" in e for e in errs)
    c2 = make_cluster(name="")
    assert any("must be set" in e for e in validate_cluster(c2))
    # DNS-1035: digit-leading names break derived Service names — but
    # only at CREATE time (legacy objects must stay modifiable), so the
    # error carries the create-only marker that admission interprets.
    c3 = make_cluster(name="9cluster")
    errs3 = validate_cluster(c3)
    assert any("DNS-1035" in e for e in errs3)
    from kuberay_tpu.utils.validation import waive_create_only
    assert waive_create_only(errs3) == []


def test_dns1035_create_only_in_admission():
    """A digit-leading name is refused on create but an EXISTING object
    with such a name stays modifiable (updates re-run admission)."""
    from kuberay_tpu.controlplane.webhooks import validate_admission
    doc = make_cluster(name="9legacy").to_dict()
    create_errs = validate_admission(doc, None)
    assert any("DNS-1035" in e for e in create_errs)
    assert not any(e.startswith("[create-only]") for e in create_errs)
    updated = make_cluster(name="9legacy").to_dict()
    updated["spec"]["suspend"] = True
    assert validate_admission(updated, doc) == []


def test_duplicate_group_names():
    c = make_cluster()
    c.spec.workerGroupSpecs.append(c.spec.workerGroupSpecs[0])
    assert any("duplicated" in e for e in validate_cluster(c))


def test_bad_topology_reported():
    c = make_cluster(accelerator="v5e", topology="3x3")
    assert any("not divisible" in e for e in validate_cluster(c))
    c2 = make_cluster(accelerator="v5e", topology="2x12")
    assert any("node pool" in e for e in validate_cluster(c2))


def test_autoscaler_replica_bounds():
    c = make_cluster(replicas=5)
    c.spec.enableInTreeAutoscaling = True
    c.spec.workerGroupSpecs[0].maxReplicas = 3
    errs = validate_cluster(c)
    assert any("within" in e for e in errs)


def test_missing_head_container():
    c = make_cluster()
    c.spec.headGroupSpec.template.spec.containers = []
    assert any("headGroupSpec" in e for e in validate_cluster(c))


def make_job(**kw):
    spec = TpuJobSpec(entrypoint="python -m x", clusterSpec=make_cluster().spec)
    for k, v in kw.items():
        setattr(spec, k, v)
    return TpuJob(metadata=ObjectMeta(name="job"), spec=spec)


def test_valid_job_passes():
    assert validate_job(make_job()) == []


def test_job_cluster_spec_xor_selector():
    j = make_job()
    j.spec.clusterSelector = {"tpu.dev/cluster": "x"}
    assert any("mutually exclusive" in e for e in validate_job(j))
    j2 = make_job()
    j2.spec.clusterSpec = None
    assert any("one of" in e for e in validate_job(j2))


def test_job_interactive_mode_entrypoint():
    j = make_job(submissionMode=JobSubmissionMode.INTERACTIVE)
    assert any("empty in InteractiveMode" in e for e in validate_job(j))
    j2 = make_job(submissionMode=JobSubmissionMode.K8S_JOB, entrypoint="")
    assert any("entrypoint must be set" in e for e in validate_job(j2))


def test_job_deletion_rules_vs_shutdown():
    j = make_job(
        shutdownAfterJobFinishes=True,
        deletionStrategy=DeletionStrategy(
            rules=[DeletionRule(policy="DeleteCluster", condition="Succeeded")]
        ),
    )
    assert any("mutually exclusive" in e for e in validate_job(j))


def test_job_ttl_requires_shutdown():
    j = make_job(ttlSecondsAfterFinished=60, shutdownAfterJobFinishes=False)
    assert any("requires shutdownAfterJobFinishes" in e for e in validate_job(j))


def make_service(strategy=ServiceUpgradeType.NEW_CLUSTER):
    return TpuService(
        metadata=ObjectMeta(name="svc"),
        spec=TpuServiceSpec(
            serveConfig={"model": "llama3-8b"},
            clusterSpec=make_cluster().spec,
            upgradeStrategy=strategy,
        ),
    )


def test_valid_service_passes():
    assert validate_service(make_service()) == []


def test_service_incremental_requires_gate():
    s = make_service(ServiceUpgradeType.INCREMENTAL)
    assert any("gate" in e for e in validate_service(s))
    features.set_gates({"TpuServiceIncrementalUpgrade": True})
    assert validate_service(s) == []


def test_service_upgrade_options_bounds():
    features.set_gates({"TpuServiceIncrementalUpgrade": True})
    s = make_service(ServiceUpgradeType.INCREMENTAL)
    s.spec.upgradeOptions = ClusterUpgradeOptions(stepSizePercent=0)
    assert any("stepSizePercent" in e for e in validate_service(s))


def test_cronjob_requires_gate_and_schedule():
    cj = TpuCronJob(
        metadata=ObjectMeta(name="nightly"),
        spec=TpuCronJobSpec(
            schedule="0 3 * * *",
            jobTemplate=make_job().spec,
        ),
    )
    errs = validate_cronjob(cj)
    assert any("feature gate" in e for e in errs)
    features.set_gates({"TpuCronJob": True})
    assert validate_cronjob(cj) == []
    cj.spec.schedule = "not a cron"
    assert any("schedule" in e for e in validate_cronjob(cj))


# ---------------------------------------------------------------------------
# Round-4 parity pass (VERDICT r3 item 5): the remaining rule families of
# utils/validation.go:23-831, table-driven like validation_test.go.


def _job(**kw):
    spec = TpuJobSpec(entrypoint="python -m x",
                      clusterSpec=make_cluster().spec)
    for k, v in kw.items():
        setattr(spec, k, v)
    return TpuJob(metadata=ObjectMeta(name="j"), spec=spec)


def _svc(**kw):
    spec = TpuServiceSpec(serveConfig={"applications": [{"name": "llm"}]},
                          clusterSpec=make_cluster().spec)
    for k, v in kw.items():
        setattr(spec, k, v)
    return TpuService(metadata=ObjectMeta(name="s"), spec=spec)


CLUSTER_CASES = [
    # (mutator, expected error fragment)
    ("suspend group under autoscaler",
     lambda c: (setattr(c.spec, "enableInTreeAutoscaling", True),
                setattr(c.spec.workerGroupSpecs[0], "suspend", True)),
     "cannot be suspended with autoscaling"),
    ("group suspend without gate",
     lambda c: (features.set_gates({"DeletionRules": False}),
                setattr(c.spec.workerGroupSpecs[0], "suspend", True)),
     "requires the DeletionRules feature gate"),
    ("conflicting explicit tpu resource",
     lambda c: c.spec.workerGroupSpecs[0].template.spec.containers[0]
     .resources.requests.update({"google.com/tpu": "99"}),
     "conflicts with topology-derived"),
    ("external address on memory backend",
     lambda c: setattr(c.spec, "headStateOptions", __import__(
         "kuberay_tpu.api.tpucluster", fromlist=["HeadStateOptions"]
     ).HeadStateOptions(backend="memory",
                        externalStorageAddress="redis:6379")),
     "only valid for backend=external"),
    ("storage class on external backend",
     lambda c: setattr(c.spec, "headStateOptions", __import__(
         "kuberay_tpu.api.tpucluster", fromlist=["HeadStateOptions"]
     ).HeadStateOptions(backend="external",
                        externalStorageAddress="redis:6379",
                        storageClassName="ssd")),
     "only valid for backend=persistent"),
    ("bad storage size",
     lambda c: setattr(c.spec, "headStateOptions", __import__(
         "kuberay_tpu.api.tpucluster", fromlist=["HeadStateOptions"]
     ).HeadStateOptions(backend="memory", storageSize="10Gigs")),
     "not a valid quantity"),
    ("hand-set state env with options",
     lambda c: (setattr(c.spec, "headStateOptions", __import__(
         "kuberay_tpu.api.tpucluster", fromlist=["HeadStateOptions"]
     ).HeadStateOptions(backend="external",
                        externalStorageAddress="redis:6379")),
         c.spec.headGroupSpec.template.spec.containers[0].env.append(
             __import__("kuberay_tpu.api.common", fromlist=["EnvVar"])
             .EnvVar(name="TPU_HEAD_EXTERNAL_STORAGE_ADDRESS",
                     value="other:6379"))),
     "use headStateOptions.externalStorageAddress"),
    ("state env without options",
     lambda c: c.spec.headGroupSpec.template.spec.containers[0].env.append(
         __import__("kuberay_tpu.api.common", fromlist=["EnvVar"])
         .EnvVar(name="TPU_HEAD_EXTERNAL_STORAGE_ADDRESS", value="r:1")),
     "set headStateOptions"),
    ("negative idle timeout",
     lambda c: setattr(c.spec, "autoscalerOptions", __import__(
         "kuberay_tpu.api.tpucluster", fromlist=["AutoscalerOptions"]
     ).AutoscalerOptions(idleTimeoutSeconds=-5)),
     "idleTimeoutSeconds must be >= 0"),
    ("bad upscaling mode",
     lambda c: setattr(c.spec, "autoscalerOptions", __import__(
         "kuberay_tpu.api.tpucluster", fromlist=["AutoscalerOptions"]
     ).AutoscalerOptions(upscalingMode="Turbo")),
     "upscalingMode"),
    ("bad image pull policy",
     lambda c: setattr(c.spec, "autoscalerOptions", __import__(
         "kuberay_tpu.api.tpucluster", fromlist=["AutoscalerOptions"]
     ).AutoscalerOptions(imagePullPolicy="Sometimes")),
     "imagePullPolicy"),
    ("network policy without gate",
     lambda c: setattr(c.spec, "networkPolicy", __import__(
         "kuberay_tpu.api.tpucluster", fromlist=["NetworkPolicySpec"]
     ).NetworkPolicySpec(enabled=True)),
     "TpuClusterNetworkPolicy"),
    ("bad network policy mode",
     lambda c: (features.set_gates({"TpuClusterNetworkPolicy": True}),
                setattr(c.spec, "networkPolicy", __import__(
                    "kuberay_tpu.api.tpucluster",
                    fromlist=["NetworkPolicySpec"]
                ).NetworkPolicySpec(enabled=True, mode="AllowAll"))),
     "networkPolicy.mode"),
]


@pytest.mark.parametrize("label,mutate,want",
                         CLUSTER_CASES,
                         ids=[c[0] for c in CLUSTER_CASES])
def test_cluster_rule_families(label, mutate, want):
    c = make_cluster()
    mutate(c)
    errs = validate_cluster(c)
    assert any(want in e for e in errs), (label, errs)


def test_upgrade_strategy_rejected_on_child_clusters():
    from kuberay_tpu.api.tpucluster import UpgradeStrategyType
    c = make_cluster()
    c.spec.upgradeStrategy = UpgradeStrategyType.RECREATE
    assert validate_cluster(c) == []
    c.metadata.labels = {"tpu.dev/originated-from-crd": "TpuService"}
    assert any("created by a TpuService" in e for e in validate_cluster(c))


def test_cluster_status_suspend_conditions_exclusive():
    from kuberay_tpu.api.common import Condition
    from kuberay_tpu.api.tpucluster import ClusterConditionType
    from kuberay_tpu.utils.validation import validate_cluster_status
    c = make_cluster()
    assert validate_cluster_status(c) == []
    c.status.conditions = [
        Condition(type=ClusterConditionType.SUSPENDING, status="True"),
        Condition(type=ClusterConditionType.SUSPENDED, status="True"),
    ]
    assert validate_cluster_status(c)


JOB_CASES = [
    ("interactive with retries",
     dict(submissionMode=JobSubmissionMode.INTERACTIVE, entrypoint="",
          backoffLimit=2),
     "backoffLimit cannot be used with InteractiveMode"),
    ("sidecar with submitter template",
     dict(submissionMode=JobSubmissionMode.SIDECAR),
     "does not support submitterConfig.template"),
    ("empty selector value",
     dict(clusterSpec=None, clusterSelector={"tpu.dev/cluster": ""}),
     "values must not be empty"),
]


@pytest.mark.parametrize("label,fields,want", JOB_CASES,
                         ids=[c[0] for c in JOB_CASES])
def test_job_rule_families(label, fields, want):
    from kuberay_tpu.api.common import PodTemplateSpec
    from kuberay_tpu.api.tpujob import SubmitterConfig
    job = _job(**fields)
    if "submitter template" in label:
        job.spec.submitterConfig = SubmitterConfig(
            template=PodTemplateSpec())
    errs = validate_job(job)
    assert any(want in e for e in errs), (label, errs)


def test_sidecar_head_restart_policy_must_be_never():
    job = _job(submissionMode=JobSubmissionMode.SIDECAR)
    job.spec.clusterSpec.headGroupSpec.template.spec.restartPolicy = \
        "Always"
    assert any("restartPolicy must be Never" in e
               for e in validate_job(job))
    job.spec.clusterSpec.headGroupSpec.template.spec.restartPolicy = \
        "Never"
    assert not any("restartPolicy" in e for e in validate_job(job))


def test_deletion_rules_duplicates_and_ttl_order():
    strat = DeletionStrategy(rules=[
        DeletionRule(policy="DeleteWorkers", condition="Succeeded",
                     ttlSeconds=60),
        DeletionRule(policy="DeleteCluster", condition="Succeeded",
                     ttlSeconds=30),       # out of order: Cluster < Workers
        DeletionRule(policy="DeleteWorkers", condition="Succeeded",
                     ttlSeconds=60),       # duplicate pair
    ])
    errs = validate_job(_job(deletionStrategy=strat))
    assert any("duplicates policy" in e for e in errs)
    assert any("must be >= " in e for e in errs)
    # Well-ordered rules pass.
    ok = DeletionStrategy(rules=[
        DeletionRule(policy="DeleteWorkers", condition="Succeeded",
                     ttlSeconds=10),
        DeletionRule(policy="DeleteCluster", condition="Succeeded",
                     ttlSeconds=20),
        DeletionRule(policy="DeleteSelf", condition="Succeeded",
                     ttlSeconds=30),
        DeletionRule(policy="DeleteSelf", condition="Failed",
                     ttlSeconds=0),
    ])
    assert validate_job(_job(deletionStrategy=ok)) == []


def test_deletion_rules_cross_constraints():
    # Selector mode: only self-deletion allowed.
    strat = DeletionStrategy(rules=[
        DeletionRule(policy="DeleteCluster", condition="Succeeded")])
    job = _job(clusterSpec=None,
               clusterSelector={"tpu.dev/cluster": "shared"},
               deletionStrategy=strat)
    assert any("not supported with clusterSelector" in e
               for e in validate_job(job))
    # Autoscaling owns worker deletion.
    job2 = _job(deletionStrategy=DeletionStrategy(rules=[
        DeletionRule(policy="DeleteWorkers", condition="Failed")]))
    job2.spec.clusterSpec.enableInTreeAutoscaling = True
    assert any("not supported with autoscaling" in e
               for e in validate_job(job2))


def test_service_step_size_vs_surge_and_serve_config_shape():
    features.set_gates({"TpuServiceIncrementalUpgrade": True})
    svc = _svc(upgradeStrategy=ServiceUpgradeType.INCREMENTAL,
               upgradeOptions=ClusterUpgradeOptions(
                   stepSizePercent=50, maxSurgePercent=20))
    assert any("stepSizePercent must be <= maxSurgePercent" in e
               for e in validate_service(svc))
    # serveConfig shape: non-list, unnamed, duplicate names.
    assert any("must be a list" in e for e in validate_service(
        _svc(serveConfig={"applications": {"llm": {}}})))
    assert any("non-empty name" in e for e in validate_service(
        _svc(serveConfig={"applications": [{"model": "m"}]})))
    assert any("duplicated" in e for e in validate_service(
        _svc(serveConfig={"applications": [{"name": "a"},
                                           {"name": "a"}]})))
    assert any("serviceUnhealthySecondThreshold" in e
               for e in validate_service(
                   _svc(serviceUnhealthySecondThreshold=-1)))


def test_cronjob_tz_and_bounds():
    features.set_gates({"TpuCronJob": True})
    base = TpuCronJobSpec(schedule="*/5 * * * *",
                          jobTemplate=_job().spec)
    ok = TpuCronJob(metadata=ObjectMeta(name="c"), spec=base)
    assert validate_cronjob(ok) == []
    import dataclasses as _dc
    tz = TpuCronJob(metadata=ObjectMeta(name="c"),
                    spec=_dc.replace(base, schedule="CRON_TZ=UTC * * * * *"))
    assert any("TZ" in e for e in validate_cronjob(tz))
    bad = TpuCronJob(metadata=ObjectMeta(name="c"),
                     spec=_dc.replace(base, startingDeadlineSeconds=-1,
                                      failedJobsHistoryLimit=-1))
    errs = validate_cronjob(bad)
    assert any("startingDeadlineSeconds" in e for e in errs)
    assert any("failedJobsHistoryLimit" in e for e in errs)
    long_name = TpuCronJob(metadata=ObjectMeta(name="c" * 53), spec=base)
    assert any("exceeds 52" in e for e in validate_cronjob(long_name))
