"""Gang scheduler plugins + NetworkPolicy controller tests."""

import json

import pytest

from kuberay_tpu.api.tpucluster import NetworkPolicySpec
from kuberay_tpu.controlplane.networkpolicy_controller import (
    NetworkPolicyController,
    build_network_policies,
)
from kuberay_tpu.controlplane.store import ObjectStore
from kuberay_tpu.scheduler.adapters import KaiAdapter, VolcanoAdapter, YuniKornAdapter
from kuberay_tpu.scheduler.gang import GangScheduler
from kuberay_tpu.scheduler.interface import SchedulerManager, total_cluster_demand
from kuberay_tpu.utils import constants as C
from kuberay_tpu.utils import features
from tests.test_api_types import make_cluster
from tests.test_cluster_controller import Harness


def cluster_dict(replicas=2):
    c = make_cluster(accelerator="v5p", topology="2x2x2", replicas=replicas)
    c.spec.workerGroupSpecs[0].maxReplicas = replicas
    d = c.to_dict()
    d["metadata"]["uid"] = "uid123"
    return d


def test_total_demand():
    d = total_cluster_demand(cluster_dict(replicas=2))
    assert d == {"minMember": 5, "tpuChips": 16}  # head + 2 slices x 2 hosts


def test_gang_creates_pod_group_and_stamps_pods():
    store = ObjectStore()
    gang = GangScheduler(store)
    cd = cluster_dict()
    assert gang.on_cluster_submission(cd)
    pg = store.get("PodGroup", "pg-demo")
    assert pg["spec"]["minMember"] == 5
    assert pg["spec"]["minResources"][C.RESOURCE_TPU] == 16
    pod = {"metadata": {"name": "p"}, "spec": {}}
    gang.add_metadata(cd, pod)
    assert pod["metadata"]["annotations"]["tpu.dev/pod-group"] == "pg-demo"
    gang.cleanup(cd)
    assert store.try_get("PodGroup", "pg-demo") is None


def test_gang_capacity_oracle_holds_admission():
    store = ObjectStore()
    fleet = {"chips": 8}
    gang = GangScheduler(store,
                         capacity_oracle=lambda d: d["tpuChips"] <= fleet["chips"])
    assert not gang.on_cluster_submission(cluster_dict(replicas=2))  # 16 > 8
    assert gang.on_cluster_submission(cluster_dict(replicas=1))      # 8 <= 8


def test_gang_blocks_cluster_controller_until_capacity():
    h = Harness()
    fleet = {"chips": 0}
    h.controller.scheduler = GangScheduler(
        h.store, capacity_oracle=lambda d: d["tpuChips"] <= fleet["chips"])
    c = make_cluster(accelerator="v5p", topology="2x2x2", replicas=1)
    h.store.create(c.to_dict())
    h.settle()
    assert h.pods() == []     # gang held: no partial slice ever exists
    fleet["chips"] = 8
    h.settle()
    assert len(h.pods()) == 3  # head + whole slice admitted together


def test_volcano_adapter_shapes():
    store = ObjectStore()
    v = VolcanoAdapter(store)
    cd = cluster_dict()
    cd["spec"]["gangSchedulingQueue"] = "research"
    assert v.on_cluster_submission(cd)
    pg = store.get("PodGroup", "volcano-pg-demo")
    assert pg["apiVersion"].startswith("scheduling.volcano.sh")
    assert pg["spec"]["queue"] == "research"
    pod = {"metadata": {"name": "p"}, "spec": {}}
    v.add_metadata(cd, pod)
    assert pod["spec"]["schedulerName"] == "volcano"
    assert pod["metadata"]["annotations"]["scheduling.k8s.io/group-name"] == \
        "volcano-pg-demo"


def test_yunikorn_task_groups():
    store = ObjectStore()
    y = YuniKornAdapter(store)
    cd = cluster_dict()
    pod = {"metadata": {"name": "p", "labels": {
        C.LABEL_NODE_TYPE: "worker", C.LABEL_GROUP: "workers"}}, "spec": {}}
    y.add_metadata(cd, pod)
    groups = json.loads(
        pod["metadata"]["annotations"]["yunikorn.apache.org/task-groups"])
    assert {g["name"] for g in groups} == {"head", "group-workers"}
    assert pod["metadata"]["annotations"][
        "yunikorn.apache.org/task-group-name"] == "group-workers"
    assert pod["spec"]["schedulerName"] == "yunikorn"


def test_scheduler_plugins_adapter_shapes():
    """Ref scheduler_plugins.go:48-88: scheduling.x-k8s.io/v1alpha1
    PodGroup named after the cluster + pod-group label on every pod."""
    from kuberay_tpu.scheduler.adapters import SchedulerPluginsAdapter

    store = ObjectStore()
    sp = SchedulerPluginsAdapter(store)
    cd = cluster_dict()
    cd["metadata"]["uid"] = "u1"
    assert sp.on_cluster_submission(cd)
    pg = store.get("PodGroup", "demo")
    assert pg["apiVersion"] == "scheduling.x-k8s.io/v1alpha1"
    # head + workers (ref CalculateDesiredReplicas + 1).
    assert pg["spec"]["minMember"] >= 2
    assert C.RESOURCE_TPU in pg["spec"]["minResources"]
    assert pg["metadata"]["ownerReferences"][0]["uid"] == "u1"
    pod = {"metadata": {"name": "p"}, "spec": {}}
    sp.add_metadata(cd, pod)
    assert pod["metadata"]["labels"]["scheduling.x-k8s.io/pod-group"] == \
        "demo"
    assert pod["spec"]["schedulerName"] == "scheduler-plugins-scheduler"
    # Idempotent resubmission; cleanup removes the PodGroup.
    assert sp.on_cluster_submission(cd)
    sp.cleanup(cd)
    assert store.try_get("PodGroup", "demo") is None
    sp.cleanup(cd)     # second cleanup is a no-op


def test_kai_rejects_k8s_job_mode():
    k = KaiAdapter(ObjectStore())
    assert not k.on_job_submission({"spec": {"submissionMode": "K8sJobMode"}})
    assert k.on_job_submission({"spec": {"submissionMode": "HTTPMode"}})


def test_scheduler_manager_selection():
    m = SchedulerManager()
    store = ObjectStore()
    m.register(GangScheduler(store))
    assert m.get("") is None
    assert m.get("gang").name == "gang"
    with pytest.raises(KeyError):
        m.get("nope")


def test_network_policies_built():
    c = make_cluster()
    c.spec.networkPolicy = NetworkPolicySpec(
        enabled=True, mode="DenyAllEgress", allowNamespaces=["monitoring"])
    pols = build_network_policies(c)
    assert len(pols) == 2
    head = next(p for p in pols if p["metadata"]["name"].endswith("head"))
    assert "Egress" in head["spec"]["policyTypes"]
    assert head["spec"]["egress"]
    assert any("namespaceSelector" in f
               for rule in head["spec"]["ingress"] for f in rule.get("from", []))


def test_network_policy_controller_gated():
    features.reset()
    store = ObjectStore()
    c = make_cluster()
    c.spec.networkPolicy = NetworkPolicySpec(enabled=True)
    store.create(c.to_dict())
    ctrl = NetworkPolicyController(store)
    ctrl.reconcile("demo")
    assert store.list("NetworkPolicy") == []     # gate off
    features.set_gates({"TpuClusterNetworkPolicy": True})
    ctrl.reconcile("demo")
    assert len(store.list("NetworkPolicy")) == 2
    features.reset()
