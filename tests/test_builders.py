"""Builder unit tests (ref common/pod_test.go 2.6k LoC tier: exhaustive
pure-function checks on env precedence, labels, resources, services)."""

from kuberay_tpu.api.common import Container, EnvVar, ObjectMeta, PodSpec, PodTemplateSpec
from kuberay_tpu.api.tpucluster import AutoscalerOptions, HeadStateOptions
from kuberay_tpu.builders.job import build_submit_command, build_submitter_job
from kuberay_tpu.builders.pod import (
    build_head_pod,
    build_slice_pods,
    build_worker_pod,
    coordinator_address,
    slice_hostnames,
)
from kuberay_tpu.builders.service import (
    build_head_service,
    build_headless_service,
    build_serve_service,
    needs_headless_service,
)
from kuberay_tpu.api.tpujob import TpuJob, TpuJobSpec
from kuberay_tpu.utils import constants as C
from tests.test_api_types import make_cluster


def env_of(pod, container=0):
    return {e["name"]: e.get("value", "")
            for e in pod["spec"]["containers"][container].get("env", [])}


def test_user_env_wins_over_injected():
    c = make_cluster()
    c.spec.workerGroupSpecs[0].template.spec.containers[0].env = [
        EnvVar(name=C.ENV_TPU_WORKER_ID, value="user-override"),
        EnvVar(name="MY_VAR", value="keep"),
    ]
    pod = build_worker_pod(c, c.spec.workerGroupSpecs[0], 0, 3)
    env = env_of(pod)
    assert env[C.ENV_TPU_WORKER_ID] == "user-override"   # ref setContainerEnvVars
    assert env["MY_VAR"] == "keep"


def test_config_env_weaker_than_injected():
    c = make_cluster()
    pod = build_worker_pod(c, c.spec.workerGroupSpecs[0], 0, 0,
                           config_env={"EXTRA": "from-config",
                                       C.ENV_TPU_WORKER_ID: "cfg"})
    env = env_of(pod)
    assert env["EXTRA"] == "from-config"
    # Identity env is authoritative over operator defaults.
    assert env[C.ENV_TPU_WORKER_ID] == "0"


def test_worker_resources_not_clobbered():
    c = make_cluster()
    c.spec.workerGroupSpecs[0].template.spec.containers[0].resources.requests = {
        "cpu": "14", C.RESOURCE_TPU: "99"}
    pod = build_worker_pod(c, c.spec.workerGroupSpecs[0], 0, 0)
    req = pod["spec"]["containers"][0]["resources"]["requests"]
    assert req["cpu"] == "14"
    assert req[C.RESOURCE_TPU] == "99"     # explicit user value respected
    # limits got the default chip count.
    lim = pod["spec"]["containers"][0]["resources"]["limits"]
    assert lim[C.RESOURCE_TPU] == "4"


def test_slice_hostnames_are_ring_stable():
    c = make_cluster(accelerator="v5p", topology="2x2x2")
    names = slice_hostnames(c, c.spec.workerGroupSpecs[0], 1)
    assert names == [
        f"demo-workers-1-0.demo-headless.default.svc",
        f"demo-workers-1-1.demo-headless.default.svc",
    ]
    pods = build_slice_pods(c, c.spec.workerGroupSpecs[0], 1)
    for h, p in enumerate(pods):
        assert p["spec"]["hostname"] == f"demo-workers-1-{h}"
        assert p["spec"]["subdomain"] == "demo-headless"


def test_head_pod_ports_and_autoscaler_sidecar():
    c = make_cluster()
    c.spec.enableInTreeAutoscaling = True
    c.spec.autoscalerOptions = AutoscalerOptions(idleTimeoutSeconds=42,
                                                 image="as:1")
    pod = build_head_pod(c)
    names = {p["name"] for p in pod["spec"]["containers"][0]["ports"]}
    assert names == {"coordinator", "dashboard", "metrics", "serve"}
    sidecar = pod["spec"]["containers"][1]
    assert sidecar["name"] == "autoscaler"
    assert sidecar["image"] == "as:1"
    assert {"name": "TPU_AUTOSCALER_IDLE_TIMEOUT", "value": "42"} in sidecar["env"]


def test_head_external_state_env():
    c = make_cluster()
    c.metadata.uid = "uid42"
    c.spec.headStateOptions = HeadStateOptions(
        backend="external", externalStorageAddress="redis:6379")
    pod = build_head_pod(c)
    env = env_of(pod)
    assert env["TPU_HEAD_EXTERNAL_STORAGE_ADDRESS"] == "redis:6379"
    assert env["TPU_HEAD_EXTERNAL_STORAGE_NAMESPACE"] == "uid42"


def test_megascale_env_only_multislice():
    c = make_cluster(accelerator="v5p", topology="2x2x2")
    g = c.spec.workerGroupSpecs[0]
    single = build_worker_pod(c, g, 0, 0)
    assert C.ENV_MEGASCALE_NUM_SLICES not in env_of(single)
    multi = build_worker_pod(c, g, 0, 0, num_slices_in_job=4,
                             megascale_slice_id=2)
    env = env_of(multi)
    assert env[C.ENV_MEGASCALE_NUM_SLICES] == "4"
    assert env[C.ENV_MEGASCALE_SLICE_ID] == "2"
    assert env[C.ENV_MEGASCALE_COORDINATOR_ADDRESS] == coordinator_address(c)


def test_owner_refs_on_everything():
    c = make_cluster()
    c.metadata.uid = "u1"
    for obj in (build_head_pod(c),
                build_worker_pod(c, c.spec.workerGroupSpecs[0], 0, 0),
                build_head_service(c), build_headless_service(c),
                build_serve_service(c)):
        ref = obj["metadata"]["ownerReferences"][0]
        assert ref["uid"] == "u1" and ref["kind"] == C.KIND_CLUSTER
        assert ref["controller"] is True


def test_headless_only_for_multihost():
    assert not needs_headless_service(
        make_cluster(accelerator="v5e", topology="2x2"))
    assert needs_headless_service(
        make_cluster(accelerator="v5p", topology="2x2x2"))
    svc = build_headless_service(make_cluster(accelerator="v5p",
                                              topology="2x2x2"))
    assert svc["spec"]["clusterIP"] == "None"
    assert svc["spec"]["publishNotReadyAddresses"] is True


def test_scheduler_name_propagates():
    c = make_cluster()
    c.spec.schedulerName = "volcano"
    pod = build_worker_pod(c, c.spec.workerGroupSpecs[0], 0, 0)
    assert pod["spec"]["schedulerName"] == "volcano"
    # Head and workers must land on the SAME scheduler.
    head = build_head_pod(c)
    assert head["spec"]["schedulerName"] == "volcano"


def test_submit_command_shape():
    c = make_cluster()
    job = TpuJob(metadata=ObjectMeta(name="j1"),
                 spec=TpuJobSpec(entrypoint="python -m t --flag 'x y'"))
    job.status.jobId = "j1-abc"
    cmd = build_submit_command(job, c)
    assert "--job-id j1-abc" in cmd
    assert "python -m t --flag 'x y'" in cmd
    assert "exec" in cmd                      # attach replaces the shell
    sub = build_submitter_job(job, c)
    assert sub["metadata"]["name"] == "j1-submitter"
    assert sub["metadata"]["labels"][C.LABEL_ORIGINATED_FROM_CRD] == C.KIND_JOB
    assert sub["spec"]["template"]["spec"]["restartPolicy"] == "Never"


def test_worker_pod_name_determinism_and_length():
    c = make_cluster(name="a" * 40)
    pod1 = build_worker_pod(c, c.spec.workerGroupSpecs[0], 3, 1)
    pod2 = build_worker_pod(c, c.spec.workerGroupSpecs[0], 3, 1)
    assert pod1["metadata"]["name"] == pod2["metadata"]["name"]
    assert len(pod1["metadata"]["name"]) <= 63


def test_probe_injection():
    """Ref initLivenessAndReadinessProbe (pod.go:539) +
    getEnableProbesInjection (:406): head probes the coordinator API,
    workers exec-check connectivity to the head, TpuService-owned
    workers additionally gate readiness on the local serve /healthz
    (which 503s on lockstep-group degradation)."""
    import json
    import os

    from kuberay_tpu.builders.pod import build_head_pod, build_worker_pod

    c = make_cluster("demo", accelerator="v5e", topology="2x2")
    head = build_head_pod(c)["spec"]["containers"][0]
    assert head["livenessProbe"]["httpGet"]["path"] == "/api/healthz"
    assert head["readinessProbe"]["httpGet"]["port"] == C.PORT_DASHBOARD
    w = build_worker_pod(c, c.spec.workerGroupSpecs[0], 0, 0)
    wp = w["spec"]["containers"][0]
    assert "TPU_COORDINATOR_ADDRESS" in \
        " ".join(wp["readinessProbe"]["exec"]["command"])
    assert "/healthz" not in json.dumps(wp["readinessProbe"]).replace(
        "/api/healthz", "")
    # Serve-owned cluster: readiness also requires the serve endpoint.
    c.metadata.labels = {C.LABEL_ORIGINATED_FROM_CRD: C.KIND_SERVICE}
    w2 = build_worker_pod(c, c.spec.workerGroupSpecs[0], 0, 0)
    ready = " ".join(w2["spec"]["containers"][0]["readinessProbe"]
                     ["exec"]["command"])
    assert f"localhost:{C.PORT_SERVE}/healthz" in ready
    # Followers (host > 0) run no HTTP frontend: probing PORT_SERVE
    # there would pin them NotReady forever.
    w3 = build_worker_pod(c, c.spec.workerGroupSpecs[0], 0, 1)
    ready3 = " ".join(w3["spec"]["containers"][0]["readinessProbe"]
                      ["exec"]["command"])
    assert f"localhost:{C.PORT_SERVE}" not in ready3
    # Liveness unchanged (a degraded group must be REPLACED by the
    # controller, not restart-looped by the kubelet).
    live = " ".join(w2["spec"]["containers"][0]["livenessProbe"]
                    ["exec"]["command"])
    assert f"localhost:{C.PORT_SERVE}" not in live
    # Opt-out knob (ref ENABLE_PROBES_INJECTION).
    os.environ["ENABLE_PROBES_INJECTION"] = "false"
    try:
        bare = build_head_pod(c)["spec"]["containers"][0]
        assert "livenessProbe" not in bare
    finally:
        del os.environ["ENABLE_PROBES_INJECTION"]
