"""SLO-driven slice autoscaling: serve TTFT/queue-depth histograms ->
SliceAutoscaler demand floors, under the sim VirtualClock — scale-up on
a sustained breach, hysteresis hold, idle release back down, every
verdict in the /debug/autoscaler audit ring."""

import json
import urllib.request

from kuberay_tpu.api.tpucluster import AutoscalerOptions
from kuberay_tpu.controlplane.autoscaler import DecisionAudit, SliceAutoscaler
from kuberay_tpu.controlplane.slo import (
    ServeSloSignal,
    SloPolicy,
    TTFT_METRIC,
    histogram_delta_p99,
)
from kuberay_tpu.sim.clock import VirtualClock
from kuberay_tpu.utils import constants as C
from kuberay_tpu.utils.metrics import SERVE_LATENCY_BUCKETS, MetricsRegistry
from tests.test_api_types import make_cluster
from tests.test_cluster_controller import Harness


# ---------------------------------------------------------------------------
# windowed p99 math
# ---------------------------------------------------------------------------

def _snap(reg):
    return reg.histogram_snapshot(TTFT_METRIC, {"phase": "ttft"})


def _observe(reg, values):
    for v in values:
        reg.observe(TTFT_METRIC, v, {"phase": "ttft"},
                    buckets=SERVE_LATENCY_BUCKETS)


def test_histogram_delta_p99_windows_between_snapshots():
    reg = MetricsRegistry()
    _observe(reg, [0.01] * 100)
    first = _snap(reg)
    p99, n = histogram_delta_p99(None, first)
    assert n == 100 and p99 <= 0.01
    # Second window is slow — the delta must see ONLY the new samples.
    _observe(reg, [2.0] * 50)
    second = _snap(reg)
    p99, n = histogram_delta_p99(first, second)
    assert n == 50
    assert 1.0 < p99 <= 2.5
    # Empty window: no new observations, no phantom breach.
    p99, n = histogram_delta_p99(second, _snap(reg))
    assert (p99, n) == (0.0, 0)


def test_histogram_delta_p99_handles_missing_series():
    assert histogram_delta_p99(None, None) == (0.0, 0)


# ---------------------------------------------------------------------------
# signal state machine (pure, virtual-clocked)
# ---------------------------------------------------------------------------

def make_signal(reg, clock, **policy):
    pol = dict(group="workers", ttft_p99_target_s=0.5, queue_depth_high=16,
               min_samples=3, breach_seconds=15.0, clear_seconds=60.0,
               cooldown_seconds=30.0)
    pol.update(policy)
    return ServeSloSignal(reg, SloPolicy(**pol), clock=clock)


def test_breach_must_sustain_before_scale_up():
    clock = VirtualClock()
    reg = MetricsRegistry()
    slo = make_signal(reg, clock)
    _observe(reg, [2.0] * 10)
    floor, info = slo.demand_floor(1)
    assert info["state"] == "breaching" and floor == 1   # not sustained yet
    clock.advance(16.0)
    _observe(reg, [2.0] * 10)
    floor, info = slo.demand_floor(1)
    assert info["state"] == "scale_up" and floor == 2
    assert info["ttft_p99_s"] > 0.5
    # Cooldown: continued breach does NOT immediately re-fire.
    clock.advance(5.0)
    _observe(reg, [2.0] * 10)
    floor, info = slo.demand_floor(2)
    assert info["state"] == "breaching" and floor == 2
    # ... but does after the cooldown elapses.
    clock.advance(30.0)
    _observe(reg, [2.0] * 10)
    floor, info = slo.demand_floor(2)
    assert info["state"] == "scale_up" and floor == 3


def test_clear_holds_then_releases():
    clock = VirtualClock()
    reg = MetricsRegistry()
    slo = make_signal(reg, clock)
    _observe(reg, [0.01] * 10)
    floor, info = slo.demand_floor(3)
    assert info["state"] == "holding" and floor == 3     # hysteresis hold
    clock.advance(61.0)
    floor, info = slo.demand_floor(3)
    assert info["state"] == "clear" and floor == 0       # released
    # A fresh breach restarts the whole ladder.
    _observe(reg, [2.0] * 10)
    floor, info = slo.demand_floor(3)
    assert info["state"] == "breaching" and floor == 3


def test_queue_depth_alone_breaches():
    clock = VirtualClock()
    reg = MetricsRegistry()
    depth = [40]
    slo = ServeSloSignal(
        reg, SloPolicy(group="workers", queue_depth_high=16,
                       breach_seconds=10.0, cooldown_seconds=0.0),
        queue_depth_fn=lambda: depth[0], clock=clock)
    floor, info = slo.demand_floor(1)
    assert info["state"] == "breaching" and info["queue_depth"] == 40
    clock.advance(11.0)
    floor, info = slo.demand_floor(1)
    assert info["state"] == "scale_up" and floor == 2


def test_flapping_latency_never_oscillates_replicas():
    """Alternating breach/clear windows shorter than the hysteresis
    thresholds must keep the floor pinned at current — no up, no
    release."""
    clock = VirtualClock()
    reg = MetricsRegistry()
    slo = make_signal(reg, clock)
    for i in range(12):
        _observe(reg, [2.0 if i % 2 == 0 else 0.01] * 5)
        floor, info = slo.demand_floor(2)
        assert info["state"] in ("breaching", "holding")
        assert floor == 2
        clock.advance(5.0)


# ---------------------------------------------------------------------------
# end to end: SliceAutoscaler + cluster controller under virtual time
# ---------------------------------------------------------------------------

def make_serve_cluster(replicas=1, min_r=1, max_r=4, idle_timeout=60):
    c = make_cluster(accelerator="v5p", topology="2x2x2", replicas=replicas)
    c.spec.enableInTreeAutoscaling = True
    c.spec.autoscalerOptions = AutoscalerOptions(
        idleTimeoutSeconds=idle_timeout)
    g = c.spec.workerGroupSpecs[0]
    g.minReplicas, g.maxReplicas = min_r, max_r
    return c


def test_slo_scale_up_and_back_down_sim_clocked():
    clock = VirtualClock()
    reg = MetricsRegistry()
    slo = make_signal(reg, clock)
    h = Harness()
    h.store.create(make_serve_cluster().to_dict())
    h.settle()
    audit = DecisionAudit(clock=clock)
    auto = SliceAutoscaler(h.store, audit=audit, slo=slo, clock=clock)

    # Sustained TTFT breach -> one-slice scale-up.
    _observe(reg, [2.0] * 10)
    assert not auto.reconcile("demo")            # breaching, not sustained
    clock.advance(16.0)
    _observe(reg, [2.0] * 10)
    assert auto.reconcile("demo")
    h.settle()
    assert h.cluster().spec.workerGroupSpecs[0].replicas == 2
    up = audit.to_list()[0]
    assert up["direction"] == "up" and up["applied"] is True
    assert up["signals"]["slo"]["state"] == "scale_up"
    assert up["signals"]["slo"]["ttft_p99_s"] > 0.5
    assert up["signals"]["demand"] == 2

    # Latency recovers: hysteresis HOLDS the extra slice (demand floor ==
    # current keeps the group claimed; idle reaper can't touch it).
    _observe(reg, [0.01] * 10)
    clock.advance(10.0)
    assert not auto.reconcile("demo")
    assert h.cluster().spec.workerGroupSpecs[0].replicas == 2

    # Sustained clear releases the floor; the slices then age into the
    # idle timeout and the existing downscale path reaps back to min.
    clock.advance(61.0)
    assert not auto.reconcile("demo")            # released; idle clocks start
    clock.advance(61.0)
    assert auto.reconcile("demo")                # idle >= 60s -> downscale
    h.settle()
    assert h.cluster().spec.workerGroupSpecs[0].replicas == 1
    down = audit.to_list()[0]
    assert down["direction"] == "down"
    assert down["slices_to_delete"]
    assert down["signals"]["slo"]["state"] == "clear"


def test_slo_demand_merges_with_job_demand():
    """Job demand above the SLO floor wins (max merge) — the SLO path
    augments the resource path, never suppresses it."""
    clock = VirtualClock()
    reg = MetricsRegistry()
    slo = make_signal(reg, clock)
    h = Harness()
    h.store.create(make_serve_cluster().to_dict())
    h.settle()
    h.store.create({
        "apiVersion": C.API_VERSION, "kind": C.KIND_JOB,
        "metadata": {"name": "big", "namespace": "default"},
        "spec": {"entrypoint": "x", "clusterSpec": {
            "workerGroupSpecs": [{"groupName": "workers", "replicas": 3}]}},
        "status": {"clusterName": "demo", "jobDeploymentStatus": "Running"},
    })
    auto = SliceAutoscaler(h.store, slo=slo, clock=clock)
    assert auto.reconcile("demo")                # job demand 3 -> step up
    h.settle()
    assert h.cluster().spec.workerGroupSpecs[0].replicas == 2


def test_slo_decisions_visible_at_debug_endpoint():
    from kuberay_tpu.apiserver.server import serve_background
    from kuberay_tpu.controlplane.store import ObjectStore

    clock = VirtualClock()
    reg = MetricsRegistry()
    slo = make_signal(reg, clock, breach_seconds=0.0, cooldown_seconds=0.0)
    h = Harness()
    h.store.create(make_serve_cluster().to_dict())
    h.settle()
    audit = DecisionAudit(clock=clock)
    auto = SliceAutoscaler(h.store, audit=audit, slo=slo, clock=clock)
    _observe(reg, [2.0] * 10)
    clock.advance(1.0)
    _observe(reg, [2.0] * 10)
    assert auto.reconcile("demo")

    srv, url = serve_background(ObjectStore(), autoscaler=audit)
    try:
        doc = json.load(urllib.request.urlopen(f"{url}/debug/autoscaler",
                                               timeout=5))
        assert doc["decisions"], "audit ring empty at /debug/autoscaler"
        entry = doc["decisions"][0]
        assert entry["direction"] == "up"
        slo_sig = entry["signals"]["slo"]
        assert slo_sig["state"] == "scale_up"
        assert slo_sig["ttft_p99_s"] > slo_sig["ttft_p99_target_s"]
    finally:
        srv.shutdown()


# ---------------------------------------------------------------------------
# per-tier signals (disaggregated prefill/decode fleet)
# ---------------------------------------------------------------------------

def _observe_tier(reg, phase, values):
    for v in values:
        reg.observe(TTFT_METRIC, v, {"phase": phase},
                    buckets=SERVE_LATENCY_BUCKETS)


def make_disagg_cluster():
    """Two worker groups — one per tier — on one serve cluster."""
    import copy

    c = make_serve_cluster()
    c.spec.workerGroupSpecs[0].groupName = "prefill"
    g2 = copy.deepcopy(c.spec.workerGroupSpecs[0])
    g2.groupName = "decode"
    c.spec.workerGroupSpecs.append(g2)
    return c


def tier_signal(reg, clock, tier):
    return ServeSloSignal(
        reg, SloPolicy(group=tier, ttft_p99_target_s=0.5, min_samples=3,
                       breach_seconds=15.0, clear_seconds=600.0,
                       cooldown_seconds=30.0),
        clock=clock, labels={"phase": f"gateway-{tier}"})


def test_per_tier_slo_scales_only_breaching_tier():
    """A prompt-heavy burst breaches only the prefill-phase histogram:
    the prefill worker group steps up, the decode group never moves —
    and vice versa.  Each audit record names its own tier's series."""
    clock = VirtualClock()
    reg = MetricsRegistry()
    h = Harness()
    h.store.create(make_disagg_cluster().to_dict())
    h.settle()
    audit = DecisionAudit(clock=clock)
    auto = SliceAutoscaler(
        h.store, audit=audit, clock=clock,
        slo=[tier_signal(reg, clock, "prefill"),
             tier_signal(reg, clock, "decode")])

    def replicas():
        return {g.groupName: g.replicas
                for g in h.cluster().spec.workerGroupSpecs}

    # Prefill-bound burst: long prompts inflate hop-1 TTFT only.
    _observe_tier(reg, "gateway-prefill", [2.0] * 10)
    assert not auto.reconcile("demo")            # not sustained yet
    clock.advance(16.0)
    _observe_tier(reg, "gateway-prefill", [2.0] * 10)
    assert auto.reconcile("demo")
    h.settle()
    assert replicas() == {"prefill": 2, "decode": 1}
    up = [e for e in audit.to_list() if e["direction"] == "up"][0]
    assert up["group"] == "prefill"
    assert up["signals"]["slo"]["series"] == {"phase": "gateway-prefill"}
    assert up["signals"]["slo"]["state"] == "scale_up"
    # No scale-up was ever attributed to the quiet decode tier.
    assert all(e["group"] != "decode" or e["direction"] != "up"
               for e in audit.to_list())

    # Decode-bound burst (long generations): the mirror case.
    clock.advance(120.0)
    _observe_tier(reg, "gateway-decode", [3.0] * 10)
    auto.reconcile("demo")
    clock.advance(16.0)
    _observe_tier(reg, "gateway-decode", [3.0] * 10)
    assert auto.reconcile("demo")
    h.settle()
    assert replicas()["decode"] == 2
    up = audit.to_list()[0]
    assert up["group"] == "decode" and up["direction"] == "up"
    assert up["signals"]["slo"]["series"] == {"phase": "gateway-decode"}
