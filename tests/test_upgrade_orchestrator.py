"""Decision-table units for the pure upgrade core (docs/upgrades.md):
every UpgradeOrchestrator action at its exact trigger, ring-cap math,
and BurnRateGate verdicts over green-scoped gateway series under a
virtual clock."""

import pytest

from kuberay_tpu.controlplane.upgrade import (
    ABORT,
    HOLD,
    PREWARM,
    PROMOTE,
    ROLLBACK,
    STEP,
    WAIT_DRAIN,
    WAIT_RING,
    BurnRateGate,
    UpgradeObservation,
    UpgradeOrchestrator,
)
from kuberay_tpu.sim.clock import VirtualClock
from kuberay_tpu.utils.metrics import MetricsRegistry

TTFT_BUCKETS = (0.25, 0.5, 1.0, 2.0)


def obs(**kw):
    base = dict(now=100.0, green_weight=0, step_size=10, interval_s=30.0,
                last_step_time=0.0, ready_slices=2, desired_slices=2)
    base.update(kw)
    return UpgradeObservation(**base)


@pytest.fixture
def orch():
    return UpgradeOrchestrator()


# ---------------------------------------------------------------------------
# ring cap: weight never outruns whole ICI rings
# ---------------------------------------------------------------------------

def test_ring_cap_math(orch):
    assert orch.ring_cap(0, 0) == 100      # no rings desired: uncapped
    assert orch.ring_cap(0, 2) == 0
    assert orch.ring_cap(1, 2) == 50
    assert orch.ring_cap(2, 2) == 100
    assert orch.ring_cap(5, 2) == 100      # ready overshoot clamps
    assert orch.ring_cap(1, 3) == 33       # floor, never round up


def test_step_up_clamped_to_ring_cap(orch):
    d = orch.decide(obs(green_weight=40, step_size=25,
                        ready_slices=1, desired_slices=2))
    assert d.action == STEP and d.green_weight == 50   # not 65


def test_wait_ring_at_cap_while_wave_provisions(orch):
    d = orch.decide(obs(green_weight=50, step_size=25,
                        ready_slices=1, desired_slices=2))
    assert d.action == WAIT_RING and d.green_weight == 50


def test_ring_degradation_steps_down_ignoring_interval(orch):
    # A ring died mid-wave: retreat immediately, even though the step
    # interval has not elapsed.
    d = orch.decide(obs(green_weight=50, last_step_time=99.0,
                        ready_slices=0, desired_slices=2))
    assert d.action == STEP and d.green_weight == 0


# ---------------------------------------------------------------------------
# the gate outranks everything
# ---------------------------------------------------------------------------

def test_firing_gate_rolls_back_with_alert_attached(orch):
    alert = {"name": "upgrade-green-availability", "window": "fast"}
    d = orch.decide(obs(green_weight=30, gate_healthy=False,
                        firing_alert=alert))
    assert d.action == ROLLBACK and d.green_weight == 0
    assert d.alert == alert


def test_firing_gate_past_budget_aborts(orch):
    d = orch.decide(obs(green_weight=30, gate_healthy=False,
                        rollbacks=2, max_rollbacks=2))
    assert d.action == ABORT


def test_firing_gate_at_weight_zero_holds(orch):
    d = orch.decide(obs(green_weight=0, gate_healthy=False))
    assert d.action == HOLD and d.green_weight == 0


def test_post_rollback_hold_then_reramp(orch):
    held = obs(now=100.0, green_weight=0, rollbacks=1,
               last_rollback_time=90.0, hold_seconds=60.0)
    d = orch.decide(held)
    assert d.action == HOLD
    assert d.requeue_after == pytest.approx(50.0)
    again = obs(now=151.0, green_weight=0, rollbacks=1,
                last_rollback_time=90.0, hold_seconds=60.0,
                last_step_time=0.0)
    d = orch.decide(again)
    assert d.action == STEP and d.green_weight == 10


# ---------------------------------------------------------------------------
# prewarm, drain, promote
# ---------------------------------------------------------------------------

def test_first_step_waits_for_prewarm_ack(orch):
    d = orch.decide(obs(green_weight=0, prewarm_requested=True,
                        prewarm_done=False))
    assert d.action == PREWARM and d.green_weight == 0
    d = orch.decide(obs(green_weight=0, prewarm_requested=True,
                        prewarm_done=True))
    assert d.action == STEP and d.green_weight == 10


def test_prewarm_only_gates_weight_zero(orch):
    # Once traffic flows the replay ack is history, not a gate.
    d = orch.decide(obs(green_weight=10, prewarm_requested=True,
                        prewarm_done=False))
    assert d.action == STEP and d.green_weight == 20


def test_promote_waits_for_drain_until_timeout(orch):
    waiting = obs(now=100.0, green_weight=100, drain_requested=True,
                  drain_done=False, drain_started_at=95.0,
                  drain_timeout_s=30.0)
    assert orch.decide(waiting).action == WAIT_DRAIN
    acked = obs(now=101.0, green_weight=100, drain_requested=True,
                drain_done=True, drain_started_at=95.0,
                drain_timeout_s=30.0)
    assert orch.decide(acked).action == PROMOTE
    expired = obs(now=126.0, green_weight=100, drain_requested=True,
                  drain_done=False, drain_started_at=95.0,
                  drain_timeout_s=30.0)
    assert orch.decide(expired).action == PROMOTE


def test_no_drain_requested_promotes_at_100(orch):
    assert orch.decide(obs(green_weight=100)).action == PROMOTE


# ---------------------------------------------------------------------------
# the timer leg survives inside the closed loop
# ---------------------------------------------------------------------------

def test_interval_not_elapsed_holds(orch):
    d = orch.decide(obs(now=100.0, green_weight=20, last_step_time=80.0,
                        interval_s=30.0))
    assert d.action == HOLD and d.green_weight == 20
    assert d.requeue_after == pytest.approx(10.0)


def test_step_advances_by_step_size_capped_at_100(orch):
    d = orch.decide(obs(green_weight=95, step_size=25))
    assert d.action == STEP and d.green_weight == 100


# ---------------------------------------------------------------------------
# BurnRateGate: green-scoped verdicts over the per-backend series
# ---------------------------------------------------------------------------

def _attempts(reg, backend, n, errors=0):
    for _ in range(n):
        reg.inc("tpu_gateway_backend_attempts_total", {"backend": backend})
    for _ in range(errors):
        reg.inc("tpu_gateway_backend_errors_total", {"backend": backend})


def test_gate_connect_failures_fire_availability(orch):
    clock = VirtualClock(start=0.0)
    reg = MetricsRegistry()
    gate = BurnRateGate(reg, clock=clock)
    _attempts(reg, "green-svc", 20)
    _attempts(reg, "blue-svc", 20)
    healthy, alert = gate.verdict("green-svc")      # baseline sample
    assert healthy and alert is None

    clock.advance(10.0)
    _attempts(reg, "green-svc", 6, errors=6)        # the dead build
    _attempts(reg, "blue-svc", 6)                   # blue stays clean
    healthy, alert = gate.verdict("green-svc")
    assert not healthy
    assert alert["name"] == "upgrade-green-availability"
    assert alert["window"] == "fast"
    # Scoping: blue's own series never trips blue's gate.
    assert gate.verdict("blue-svc") == (True, None)


def test_gate_ttft_breach_fires_latency(orch):
    clock = VirtualClock(start=0.0)
    reg = MetricsRegistry()
    gate = BurnRateGate(reg, clock=clock, ttft_target_s=0.5)
    for _ in range(8):
        reg.observe("tpu_gateway_backend_latency_seconds", 0.1,
                    {"backend": "green-svc"}, buckets=TTFT_BUCKETS)
    assert gate.verdict("green-svc") == (True, None)
    clock.advance(10.0)
    for _ in range(6):
        reg.observe("tpu_gateway_backend_latency_seconds", 1.5,
                    {"backend": "green-svc"}, buckets=TTFT_BUCKETS)
    healthy, alert = gate.verdict("green-svc")
    assert not healthy and alert["name"] == "upgrade-green-ttft"


def test_gate_forget_resets_windows(orch):
    clock = VirtualClock(start=0.0)
    reg = MetricsRegistry()
    gate = BurnRateGate(reg, clock=clock)
    _attempts(reg, "green-svc", 20)
    gate.verdict("green-svc")
    clock.advance(10.0)
    _attempts(reg, "green-svc", 6, errors=6)
    assert gate.verdict("green-svc")[0] is False
    # After promote/abort the engine is dropped: a later upgrade of the
    # same backend name baselines afresh instead of inheriting the old
    # firing window.
    gate.forget("green-svc")
    assert gate.verdict("green-svc") == (True, None)
