"""Runtime components: coordinator server, submit tool, launcher identity,
checkpointing."""

import json
import os
import subprocess
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kuberay_tpu.runtime.coordinator_client import CoordinatorClient
from kuberay_tpu.runtime.coordinator_server import (
    CoordinatorServer,
    FileBackend,
    MemoryBackend,
)
from kuberay_tpu.train.launcher import WorkerIdentity
from kuberay_tpu.utils import constants as C


@pytest.fixture
def coord():
    server = CoordinatorServer(state=MemoryBackend(), spawn_jobs=True,
                               log_dir="/tmp/test-coord-logs")
    srv, url = server.serve_background()
    yield server, url
    srv.shutdown()


def test_job_submit_roundtrip(coord):
    server, url = coord
    client = CoordinatorClient(url)
    jid = client.submit_job("j1", "echo done")
    assert jid == "j1"
    for _ in range(50):
        info = client.get_job_info("j1")
        if info.status in ("SUCCEEDED", "FAILED"):
            break
        time.sleep(0.1)
    assert info.status == "SUCCEEDED"
    # Idempotent resubmission does not spawn a second process.
    client.submit_job("j1", "echo again")
    assert len(server.jobs) == 1


def test_job_failure_and_stop(coord):
    server, url = coord
    client = CoordinatorClient(url)
    client.submit_job("bad", "exit 3")
    for _ in range(50):
        info = client.get_job_info("bad")
        if info.status in ("SUCCEEDED", "FAILED"):
            break
        time.sleep(0.1)
    assert info.status == "FAILED"
    assert "exit code 3" in info.message

    client.submit_job("long", "sleep 30")
    time.sleep(0.3)
    client.stop_job("long")
    info = client.get_job_info("long")
    assert info.status == "STOPPED"


def test_checkpoint_drain_endpoint(coord):
    """POST /api/checkpoint (the operator's drain hook on a preemption
    notice): recorded server-side and fanned out to the installed
    on_checkpoint callback; a hook failure is reported, not raised."""
    server, url = coord
    client = CoordinatorClient(url)
    seen = []
    server.on_checkpoint = lambda tag, reason: seen.append((tag, reason))
    out = client.request_checkpoint(tag="preempt-slice-0")
    assert out == {"requested": True, "tag": "preempt-slice-0"}
    assert seen == [("preempt-slice-0", "preemption")]
    assert [r["tag"] for r in server.checkpoint_requests] == \
        ["preempt-slice-0"]

    def boom(tag, reason):
        raise RuntimeError("save failed")

    server.on_checkpoint = boom
    out = client.request_checkpoint(tag="t2", reason="manual")
    assert out["requested"] is True and "save failed" in out["error"]
    assert [r["reason"] for r in server.checkpoint_requests] == \
        ["preemption", "manual"]


def test_serve_config_and_status(coord):
    server, url = coord
    client = CoordinatorClient(url)
    client.update_serve_apps({"applications": [{"name": "llm"}]})
    apps = client.get_serve_apps()
    assert apps["llm"]["status"] == "DEPLOYING"
    server.set_app_status("llm", "RUNNING")
    assert client.get_serve_apps()["llm"]["status"] == "RUNNING"


def test_record_events_server_side_received_at_beats_skewed_clients():
    """Regression: every ingested event is stamped with a server-side
    ``received_at`` + monotonic ``received_seq``; client ``ts`` values
    (kept for display) and even a client-forged ``received_at`` never
    drive ordering or attribution."""
    server = CoordinatorServer(state=MemoryBackend(), spawn_jobs=False)
    t0 = time.time()
    # Client A's clock is a day ahead; client B's is decades behind; one
    # event even forges received_at.
    n = server.record_events([
        {"ts": t0 + 86400, "name": "late-clock", "job_id": "j"},
        {"ts": 17.0, "name": "early-clock", "job_id": "j",
         "received_at": 1.0, "received_seq": 999999},
    ])
    assert n == 2
    evs = server.list_events(job_id="j")
    # Arrival order preserved; server stamps overwrite forged ones.
    assert [e["name"] for e in evs] == ["late-clock", "early-clock"]
    for e in evs:
        assert t0 - 5 <= e["received_at"] <= time.time() + 5
    assert evs[0]["received_seq"] < evs[1]["received_seq"]
    # Client timestamps survive untouched for display.
    assert evs[0]["ts"] == t0 + 86400 and evs[1]["ts"] == 17.0


def test_checkpoint_requests_bounded_with_dropped_count():
    """Regression: checkpoint_requests grew without bound — a flapping
    drain loop could OOM the head.  Now a capped deque (oldest evicted)
    with an explicit dropped counter."""
    from kuberay_tpu.runtime.coordinator_server import (
        CHECKPOINT_REQUESTS_MAX)
    server = CoordinatorServer(state=MemoryBackend(), spawn_jobs=False)
    for i in range(CHECKPOINT_REQUESTS_MAX + 50):
        server.request_checkpoint(tag=f"t{i}")
    assert len(server.checkpoint_requests) == CHECKPOINT_REQUESTS_MAX
    assert server.checkpoint_requests_dropped == 50
    # Oldest evicted, newest kept.
    assert server.checkpoint_requests[0]["tag"] == "t50"
    assert server.checkpoint_requests[-1]["tag"] == \
        f"t{CHECKPOINT_REQUESTS_MAX + 49}"


def test_record_events_backpressure_bounded_and_ordered():
    """A multi-host heartbeat burst (8 hosts x 5k events) cannot grow
    the event ring past its cap, and received_seq stays strictly
    increasing across batches — the ordering contract downstream
    consumers (history replay, the step tracker) key on."""
    server = CoordinatorServer(state=MemoryBackend(), spawn_jobs=False)
    cap = server.events.maxlen
    for host in range(8):
        server.record_events([
            {"type": "step", "name": "step_heartbeat", "job_id": "j",
             "host": f"s0w{host}",
             "args": {"step": i, "dur_s": 0.1}}
            for i in range(5000)])
    assert len(server.events) == cap                 # bounded memory
    seqs = [e["received_seq"] for e in server.events]
    assert seqs == sorted(seqs)
    assert len(set(seqs)) == len(seqs)               # strictly increasing


def test_record_events_feeds_step_tracker_with_received_at():
    """step_heartbeat events reach the mounted StepTracker stamped with
    the server's received_at; malformed heartbeats are skipped without
    poisoning the batch; non-heartbeat events don't touch the tracker."""
    from kuberay_tpu.obs.steps import StepTracker
    tracker = StepTracker(window=8)
    server = CoordinatorServer(state=MemoryBackend(), spawn_jobs=False,
                               steps=tracker)
    t0 = time.time()
    n = server.record_events([
        {"type": "step", "name": "step_heartbeat", "job_id": "train",
         "host": "s0w0", "ts": 1.0,      # skewed client clock: ignored
         "args": {"step": 7, "dur_s": 0.25, "tokens": 512.0,
                  "collective_wait_s": 0.02}},
        {"type": "step", "name": "step_heartbeat", "job_id": "train",
         "host": "s0w1", "args": {"step": 7, "dur_s": "not-a-float"}},
        {"type": "step", "name": "train_step", "job_id": "train",
         "args": {"step": 7, "loss": 2.0}},          # summary, not a beat
        {"type": "step", "name": "step_heartbeat", "job_id": "train",
         "args": {"step": 7, "dur_s": 0.3}},         # no host: not a beat
    ])
    assert n == 4                                    # all recorded as events
    doc = server.steps.job_doc("train")
    assert doc is not None
    assert [h["host"] for h in doc["hosts"]] == ["s0w0"]
    h = doc["hosts"][0]
    assert h["last_step"] == 7 and h["p50_s"] == 0.25
    # The tracker saw the server's stamp, not the client's ts=1.0.
    assert h["last_ts"] >= t0 - 5
    ev = server.list_events(job_id="train")[0]
    assert h["last_ts"] == ev["received_at"]


def test_head_restart_recovery(tmp_path):
    """File backend: job registry survives a head restart; in-flight jobs
    are marked FAILED (the operator's retry machinery takes over)."""
    state_dir = str(tmp_path / "state")
    s1 = CoordinatorServer(state=FileBackend(state_dir), spawn_jobs=False)
    s1.submit("done-job", "echo x")
    s1.jobs["done-job"].status = "SUCCEEDED"
    s1._persist_job(s1.jobs["done-job"])
    s1.submit("inflight", "sleep 99")
    s1.jobs["inflight"].status = "RUNNING"
    s1._persist_job(s1.jobs["inflight"])
    # "Restart" the head.
    s2 = CoordinatorServer(state=FileBackend(state_dir), spawn_jobs=False)
    assert s2.jobs["done-job"].status == "SUCCEEDED"
    assert s2.jobs["inflight"].status == "FAILED"
    assert "restarted" in s2.jobs["inflight"].message


def test_submit_tool_against_live_coordinator(coord):
    server, url = coord
    host_port = url.removeprefix("http://")
    host, port = host_port.split(":")
    # Patch the dashboard port via a tiny wrapper: call main with address
    # pointing at our ephemeral port through CoordinatorClient monkeypatch.
    from kuberay_tpu.runtime import submit as submit_mod

    class _Client(CoordinatorClient):
        def __init__(self, base_url, timeout=5.0):
            super().__init__(url, timeout)

    orig = submit_mod.CoordinatorClient
    submit_mod.CoordinatorClient = _Client
    try:
        rc = submit_mod.main(["--address", host, "--job-id", "cli-job",
                              "--", "echo", "from-submit"])
    finally:
        submit_mod.CoordinatorClient = orig
    assert rc == 0
    assert server.jobs["cli-job"].status == "SUCCEEDED"


def test_worker_identity_from_env():
    env = {
        C.ENV_TPU_WORKER_ID: "3",
        C.ENV_NUM_PROCESSES: "4",
        C.ENV_TPU_WORKER_HOSTNAMES: "h0.svc,h1.svc,h2.svc,h3.svc",
        C.ENV_TPU_TOPOLOGY: "4x4",
        C.ENV_MEGASCALE_NUM_SLICES: "2",
        C.ENV_MEGASCALE_SLICE_ID: "1",
    }
    ident = WorkerIdentity.from_env(env)
    assert ident.worker_id == 3
    assert ident.num_workers == 4
    assert ident.coordinator == f"h0.svc:{C.PORT_MXLA}"
    assert ident.is_distributed
    assert ident.global_process_id == 7   # slice 1, worker 3
    assert ident.global_process_count == 8


def test_worker_identity_single_host():
    ident = WorkerIdentity.from_env({})
    assert not ident.is_distributed
    assert ident.global_process_id == 0


def test_checkpoint_save_restore(tmp_path):
    from kuberay_tpu.models import llama
    from kuberay_tpu.train import checkpoint as ckpt
    from kuberay_tpu.train.train_step import (
        TrainConfig, init_train_state, make_optimizer, make_train_step)

    cfg = llama.CONFIGS["llama_tiny"]
    tc = TrainConfig(warmup_steps=2, decay_steps=10)
    opt = make_optimizer(tc)
    state = init_train_state(cfg, opt, jax.random.PRNGKey(0))
    step = make_train_step(cfg, tc, opt)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0,
                                cfg.vocab_size)
    batch = {"tokens": tokens, "targets": jnp.roll(tokens, -1, 1)}
    state, _ = step(state, batch)
    state, _ = step(state, batch)

    ckpt_dir = str(tmp_path / "ckpt")
    ckpt.save(ckpt_dir, state, 2)
    assert ckpt.latest_step(ckpt_dir) == 2

    restored = ckpt.restore_latest(
        ckpt_dir, lambda k: init_train_state(cfg, opt, k),
        jax.random.PRNGKey(0))
    assert int(restored["step"]) == 2
    for a, b in zip(jax.tree.leaves(restored["params"]),
                    jax.tree.leaves(state["params"])):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # Training continues bit-identically from the restored state.
    s1, m1 = step(restored, batch)
    np.testing.assert_allclose(float(m1["loss"]), float(m1["loss"]))


def test_checkpoint_writer_async_overlap(tmp_path):
    """CheckpointWriter: fire-and-forget saves with ongoing training
    mutating (donating) the state — Orbax snapshots to host before
    save_async returns, so later steps can't corrupt the write; all
    periodic checkpoints land and restore bit-identically."""
    from kuberay_tpu.models import llama
    from kuberay_tpu.train import checkpoint as ckpt
    from kuberay_tpu.train.train_step import (
        TrainConfig, init_train_state, make_optimizer, make_train_step)

    cfg = llama.CONFIGS["llama_tiny"]
    tc = TrainConfig(warmup_steps=2, decay_steps=10)
    opt = make_optimizer(tc)
    state = init_train_state(cfg, opt, jax.random.PRNGKey(0))
    step = make_train_step(cfg, tc, opt)   # donates state buffers
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0,
                                cfg.vocab_size)
    batch = {"tokens": tokens, "targets": jnp.roll(tokens, -1, 1)}

    ckpt_dir = str(tmp_path / "ckpt")
    snap2_params = None
    with ckpt.CheckpointWriter(ckpt_dir, keep=3) as w:
        for i in range(4):
            state, _ = step(state, batch)
            if i == 1:
                snap2_params = jax.tree.map(np.asarray, state["params"])
                w.save_async(state, 2)     # training continues below
            if i == 3:
                w.save_async(state, 4)
    assert ckpt.latest_step(ckpt_dir) == 4
    restored2 = ckpt.restore(
        ckpt_dir, 2, jax.eval_shape(
            lambda k: init_train_state(cfg, opt, k),
            jax.random.PRNGKey(0)))
    # The step-2 checkpoint holds step-2 values, NOT later mutations.
    for a, b in zip(jax.tree.leaves(restored2["params"]),
                    jax.tree.leaves(snap2_params)):
        np.testing.assert_array_equal(np.asarray(a), b)
    assert int(restored2["step"]) == 2


def test_checkpoint_writer_surfaces_background_failure():
    """Regression: a commit that died on Orbax's background write
    thread only surfaced at the NEXT manager interaction — a training
    loop whose final save failed exited "cleanly" with a missing
    checkpoint.  The writer must store the failure and re-raise it from
    wait() and close() (still closing the manager), and refuse a new
    save on top of an unacknowledged failure."""
    from kuberay_tpu.train.checkpoint import CheckpointWriter

    class FakeManager:
        def __init__(self):
            self.closed = False
            self.fail_on_wait = None

        def save(self, step, args=None):
            pass

        def wait_until_finished(self):
            if self.fail_on_wait is not None:
                err, self.fail_on_wait = self.fail_on_wait, None
                raise err

        def close(self):
            self.closed = True

    # Bypass __init__ (it builds a real Orbax manager); wire the fake.
    w = CheckpointWriter.__new__(CheckpointWriter)
    mgr = FakeManager()
    w._mgr = mgr
    w._error = None

    mgr.fail_on_wait = RuntimeError("async commit failed")
    with pytest.raises(RuntimeError, match="async commit failed"):
        w.wait()
    # Sticky: close() re-raises the same failure AND closes the manager
    # (the fake's wait no longer raises — the stored error does).
    with pytest.raises(RuntimeError, match="async commit failed"):
        w.close()
    assert mgr.closed
    # A new save on top of an unacknowledged failure must refuse too.
    with pytest.raises(RuntimeError, match="async commit failed"):
        w.save_async({}, 1)


def test_load_params_for_serving(tmp_path):
    """Train-to-serve handoff: restore only the params subtree from a
    train checkpoint, cast + (optionally) shard for serving."""
    from kuberay_tpu.models import llama
    from kuberay_tpu.train import checkpoint as ckpt
    from kuberay_tpu.train.train_step import (
        TrainConfig, init_train_state, make_optimizer, make_train_step)

    cfg = llama.CONFIGS["llama_tiny"]
    tc = TrainConfig(warmup_steps=2, decay_steps=10)
    opt = make_optimizer(tc)
    state = init_train_state(cfg, opt, jax.random.PRNGKey(0))
    step = make_train_step(cfg, tc, opt)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0,
                                cfg.vocab_size)
    state, _ = step(state, {"tokens": tokens,
                            "targets": jnp.roll(tokens, -1, 1)})
    want = jax.tree.map(np.asarray, state["params"])
    d = str(tmp_path / "ck")
    ckpt.save(d, state, 1)

    assert ckpt.load_params_for_serving(str(tmp_path / "none")) is None
    # Missing dir must not be created as a side effect.
    assert not (tmp_path / "none").exists()
    # Explicit missing step: clean None, not an orbax traceback.
    assert ckpt.load_params_for_serving(d, step=999) is None
    got = ckpt.load_params_for_serving(d, dtype=cfg.dtype)
    for a, b in zip(jax.tree.leaves(got), jax.tree.leaves(want)):
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b, np.float32),
                                   rtol=1e-2, atol=1e-2)
    # Engine serves the restored weights.
    from kuberay_tpu.serve.engine import Request, ServeEngine
    eng = ServeEngine(cfg, got, max_slots=2, max_len=64)
    eng.add_request(Request("r", [1, 2, 3], max_new_tokens=4))
    assert len(eng.run()[0].tokens) == 4
    # Sharded restore lands on the serve mesh.
    from kuberay_tpu.serve.sharding import param_shardings, serve_mesh
    mesh = serve_mesh(2)
    sharded = ckpt.load_params_for_serving(
        d, shardings=param_shardings(cfg, mesh), dtype=cfg.dtype)
    wq = sharded["layers"]["wq"]
    assert not wq.sharding.is_fully_replicated
