"""QuotaManager ledger semantics + PodGroup verdict lifecycle.

Edge cases the sim's invariant checkers can't isolate: a
zero-guaranteed queue borrowing the whole pool, reclaim racing a
voluntary release, a gang exactly at (and just over) its ceiling, the
deterministic youngest-first victim tie-break, and the PodGroup status
/ ``tpu_gang_admission_total`` evidence trail the gang scheduler leaves
for every verdict.
"""

from __future__ import annotations

from kuberay_tpu.controlplane.quota import QuotaManager, build_demand
from kuberay_tpu.controlplane.store import ObjectStore
from kuberay_tpu.scheduler.gang import GangScheduler
from kuberay_tpu.sim.scenarios import make_quota_pool_obj
from kuberay_tpu.utils import constants as C
from kuberay_tpu.utils.metrics import ControlPlaneMetrics
from tests.test_api_types import make_cluster

NOTICE_S = 30.0
BOUND_S = 120.0


def mk_quota(tenants, total=16):
    """(quota, clock, preempts) over a one-pool store with a fake clock
    and a recording preemptor (no pods exist, so the default preemptor
    would have nothing to stamp anyway)."""
    store = ObjectStore()
    store.create(make_quota_pool_obj("pool", total, tenants,
                                     starvation=BOUND_S, notice=NOTICE_S))
    clock = {"t": 100.0}
    preempts = []
    quota = QuotaManager(
        store, clock=lambda: clock["t"],
        preemptor=lambda claim, deadline: preempts.append(claim["key"]))
    return quota, clock, preempts


def demand(name, tenant, chips, queue="default", priority=0):
    return {"kind": C.KIND_JOB, "namespace": "default", "name": name,
            "tpuChips": chips, "chips": chips, "minMember": 1,
            "tenant": tenant, "queue": queue, "priority": priority,
            "key": (C.KIND_JOB, "default", name)}


def test_zero_guaranteed_queue_borrows_everything():
    quota, _, _ = mk_quota([("owner", [("default", 16, 0, True)]),
                            ("free", [("default", 0, 0, True)])])
    # With the owner idle, the zero-guarantee queue may borrow the
    # whole pool — borrowing is only bounded by ceiling and capacity.
    assert quota.admit(demand("f1", "free", 8)).admitted
    assert quota.admit(demand("f2", "free", 8)).admitted
    snap = quota.debug_snapshot()
    assert sum(c["chips"] for c in snap["claims"]) == 16
    assert all(c["borrowed"] == c["chips"] for c in snap["claims"])


def test_gang_exactly_at_ceiling_and_one_over():
    quota, _, _ = mk_quota([("team", [("default", 4, 8, True)])])
    # Exactly at the ceiling: admissible (the bound is inclusive).
    assert quota.admit(demand("fit", "team", 8)).admitted
    # The queue is now full: a further gang is contention, hence pending.
    held = quota.admit(demand("more", "team", 4))
    assert not held.admitted and held.reason == "queue-ceiling"
    assert [p["name"] for p in quota.debug_snapshot()["pending"]] == ["more"]
    # Over the ceiling: a config-shaped rejection, never pending (it
    # could not be satisfied by any amount of waiting).
    over = quota.admit(demand("big", "team", 12))
    assert not over.admitted and over.reason == "gang-exceeds-ceiling"
    assert "big" not in [p["name"] for p in
                         quota.debug_snapshot()["pending"]]


def test_unknown_tenant_is_config_error_not_contention():
    quota, _, _ = mk_quota([("team", [("default", 4, 8, True)])])
    v = quota.admit(demand("x", "nobody", 4))
    assert not v.admitted and v.reason == "unknown-tenant-or-queue"
    assert quota.debug_snapshot()["pending"] == []


def test_reclaim_racing_voluntary_release():
    quota, _, preempts = mk_quota([("prod", [("default", 16, 0, True)]),
                                   ("free", [("default", 0, 0, True)])])
    assert quota.admit(demand("borrower", "free", 16)).admitted
    # The guaranteed claim can't fit -> pending + reclaim notice fired.
    assert not quota.admit(demand("pri", "prod", 16)).admitted
    assert preempts == [(C.KIND_JOB, "default", "borrower")]
    # The victim releases voluntarily before its notice deadline...
    quota.release({"key": (C.KIND_JOB, "default", "borrower")})
    # ...and the freed chips belong to the guaranteed waiter: another
    # borrower asking first is held off by the reservation.
    late = quota.admit(demand("opportunist", "free", 8))
    assert not late.admitted and late.reason == "reserved-for-escalated"
    assert quota.admit(demand("pri", "prod", 16)).admitted
    snap = quota.debug_snapshot()
    assert [c["name"] for c in snap["claims"]] == ["pri"]


def test_reclaim_victim_tie_breaks_youngest_first():
    quota, _, preempts = mk_quota([("prod", [("default", 16, 0, True)]),
                                   ("free", [("default", 0, 0, True)])])
    assert quota.admit(demand("older", "free", 8)).admitted
    assert quota.admit(demand("younger", "free", 8)).admitted
    assert not quota.admit(demand("pri", "prod", 8)).admitted
    # Equal priority: the younger borrower is warned, the older lives.
    assert preempts == [(C.KIND_JOB, "default", "younger")]
    claims = {c["name"]: c for c in quota.debug_snapshot()["claims"]}
    assert claims["younger"]["evicting"] and not claims["older"]["evicting"]
    # Level-triggered re-ask while the victim drains must not cascade
    # onto the next borrower: the in-flight reclaim covers the shortfall.
    assert not quota.admit(demand("pri", "prod", 8)).admitted
    assert len(preempts) == 1


def test_elastic_shrink_cancels_eviction():
    quota, _, _ = mk_quota([("prod", [("default", 16, 0, True)]),
                            ("free", [("default", 0, 0, True)])])
    assert quota.admit(demand("elastic", "free", 16)).admitted
    assert not quota.admit(demand("pri", "prod", 4)).admitted
    claims = {c["name"]: c for c in quota.debug_snapshot()["claims"]}
    assert claims["elastic"]["reclaim_target"] == 12
    # Shrinking to the reclaim target cancels the eviction entirely.
    v = quota.admit(demand("elastic", "free", 12))
    assert v.admitted and v.reason == "resized-shrink"
    claims = {c["name"]: c for c in quota.debug_snapshot()["claims"]}
    assert not claims["elastic"]["evicting"]
    assert quota.admit(demand("pri", "prod", 4)).admitted


def test_eviction_completes_after_deadline():
    quota, clock, _ = mk_quota([("prod", [("default", 16, 0, True)]),
                                ("free", [("default", 0, 0, True)])])
    assert quota.admit(demand("borrower", "free", 16)).admitted
    assert not quota.admit(demand("pri", "prod", 16)).admitted
    # Inside the notice window the victim stays admitted (it may still
    # shrink or checkpoint).
    assert quota.admit(demand("borrower", "free", 16)).reason == \
        "reclaim-notice"
    clock["t"] += NOTICE_S + 1.0
    # Past the deadline with no live pods the claim is freed and the
    # gang re-queues like any other — and loses to the reservation.
    v = quota.admit(demand("borrower", "free", 16))
    assert not v.admitted and not v.evict
    assert quota.admit(demand("pri", "prod", 16)).admitted


def test_starvation_escalates_past_bound():
    quota, clock, _ = mk_quota([("owner", [("default", 16, 0, True)]),
                                ("free", [("default", 0, 0, True)])])
    # The pool is full of *guaranteed* (unreclaimable) capacity.
    assert quota.admit(demand("o1", "owner", 16)).admitted
    assert not quota.admit(demand("f1", "free", 4)).admitted
    # Keep re-asking like a live controller (a gang silent for a whole
    # bound is GC'd as abandoned), crossing the bound on the last ask.
    clock["t"] += BOUND_S / 2
    assert not quota.admit(demand("f1", "free", 4)).admitted
    clock["t"] += BOUND_S / 2 + 1.0
    v = quota.admit(demand("f1", "free", 4))
    assert not v.admitted and v.escalated
    pend = quota.debug_snapshot()["pending"]
    assert [p["escalated"] for p in pend] == [True]
    # Once the owner releases, the escalated gang gets the capacity.
    quota.release({"key": (C.KIND_JOB, "default", "o1")})
    assert quota.admit(demand("f1", "free", 4)).admitted


def _gang_cluster(name, tenant, chips_replicas=1):
    c = make_cluster(accelerator="v5p", topology="2x2x2",
                     replicas=chips_replicas)
    d = c.to_dict()
    d["metadata"]["name"] = name
    d["metadata"]["uid"] = f"uid-{name}"
    d["spec"]["tenant"] = tenant
    return d


def _counter(metrics, name, **labels):
    key = (name, tuple(sorted(labels.items())))
    return metrics.registry._counters.get(key, 0.0)


def test_pod_group_status_records_every_verdict():
    store = ObjectStore()
    store.create(make_quota_pool_obj(
        "pool", 8, [("team", [("default", 8, 0, True)])],
        starvation=BOUND_S, notice=NOTICE_S))
    clock = {"t": 50.0}
    metrics = ControlPlaneMetrics()
    quota = QuotaManager(store, metrics=metrics, clock=lambda: clock["t"])
    gang = GangScheduler(store, quota=quota, metrics=metrics,
                         clock=lambda: clock["t"])

    first = _gang_cluster("one", "team")        # 8 chips: fills the pool
    assert gang.on_cluster_submission(first)
    pg = store.get("PodGroup", "pg-one")
    assert pg["status"]["phase"] == "Admitted"
    assert pg["status"]["reason"] == "admitted"
    admitted_at = pg["status"]["admittedAt"]
    assert admitted_at == 50.0
    assert _counter(metrics, "tpu_gang_admission_total",
                    verdict="admitted") == 1.0

    # Level-triggered re-submission: status stays put, admittedAt is
    # stamped once (first admission), not rewritten per reconcile.
    clock["t"] = 60.0
    assert gang.on_cluster_submission(first)
    assert store.get("PodGroup", "pg-one")["status"]["admittedAt"] == \
        admitted_at

    # A denied gang gets a Pending PodGroup with the denial reason and
    # the denied counter ticks — the operator-visible evidence.
    second = _gang_cluster("two", "team")
    assert not gang.on_cluster_submission(second)
    pg = store.get("PodGroup", "pg-two")
    assert pg["status"]["phase"] == "Pending"
    assert pg["status"]["reason"] == "queue-ceiling"
    assert "admittedAt" not in pg["status"]
    assert _counter(metrics, "tpu_gang_admission_total",
                    verdict="denied") == 1.0

    # cleanup() releases the quota claim: the held gang now fits.
    gang.cleanup(first)
    assert store.try_get("PodGroup", "pg-one") is None
    assert gang.on_cluster_submission(second)
    assert store.get("PodGroup", "pg-two")["status"]["phase"] == "Admitted"


def test_build_demand_carries_quota_identity():
    d = _gang_cluster("idy", "team")
    d["spec"]["priority"] = 7
    d["spec"]["gangSchedulingQueue"] = "q1"
    dem = build_demand(d)
    assert dem["tenant"] == "team" and dem["priority"] == 7
    assert dem["queue"] == "q1"
    assert dem["key"] == (C.KIND_CLUSTER, "default", "idy")
