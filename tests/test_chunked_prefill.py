"""Chunked prefill: long prompts stream into the cache in fixed chunks
with decode steps interleaved (vLLM-style), without changing outputs."""

import jax
import jax.numpy as jnp
import numpy as np

from kuberay_tpu.models.llama import CONFIGS, init_params
from kuberay_tpu.serve.engine import Request, ServeEngine

CFG = CONFIGS["llama_tiny"]
PARAMS = init_params(CFG, jax.random.PRNGKey(0))


def make_engine(**kw):
    kw.setdefault("max_slots", 4)
    kw.setdefault("max_len", 128)
    return ServeEngine(CFG, PARAMS, **kw)


def prompts(seed=0):
    rng = np.random.default_rng(seed)
    lens = [3, 17, 40, 9, 33]
    return [rng.integers(1, CFG.vocab_size, size=n).tolist() for n in lens]


def run_all(engine):
    for i, p in enumerate(prompts()):
        engine.add_request(Request(f"r{i}", p, max_new_tokens=8))
    out = engine.run()
    return {r.request_id: (r.tokens, r.finish_reason) for r in out}


def test_chunked_outputs_match_unchunked():
    want = run_all(make_engine())
    got = run_all(make_engine(prefill_chunk=8))
    assert got == want


def test_chunk_equals_prompt_len_is_whole_prefill():
    got = run_all(make_engine(prefill_chunk=64))
    want = run_all(make_engine())
    assert got == want


def test_single_compiled_prefill_shape():
    """Every admission reuses ONE chunk-shaped program regardless of
    prompt length (the unchunked engine compiles one per bucket)."""
    eng = make_engine(prefill_chunk=8)
    run_all(eng)
    cache_size = getattr(eng._prefill, "_cache_size", None)
    if cache_size is not None:
        assert cache_size() == 1


def test_decode_interleaves_with_long_prefill():
    """While a long prompt streams in chunk by chunk, an already-active
    slot keeps generating tokens."""
    eng = make_engine(prefill_chunk=8)
    eng.add_request(Request("short", [5, 6, 7], max_new_tokens=30))
    eng.step()                       # admits + starts decoding "short"
    assert eng.num_active == 1
    eng.add_request(Request("long", list(range(1, 41)), max_new_tokens=4))
    progressed = 0
    while eng._inflight is not None or eng.queue:
        before = len(eng.generated[0]) if eng.active[0] else 0
        eng.step()
        after = len(eng.generated[0]) if eng.active[0] else before
        if eng._inflight is not None and after > before:
            progressed += 1
    # 40-token prompt / 8-token chunks = 5 chunks -> at least a few decode
    # steps landed while the prefill was in flight.
    assert progressed >= 3
    out = {r.request_id for r in eng.run()}
    assert "long" in out and ("short" in out or eng.num_active == 0)


def test_chunked_outputs_match_unchunked_mixtral():
    """MoE serving prefill routes droplessly (per-token), so chunk
    boundaries cannot change expert assignment — outputs are identical."""
    from kuberay_tpu.models import mixtral
    cfg = mixtral.CONFIGS["mixtral_tiny"]
    params = mixtral.init_params(cfg, jax.random.PRNGKey(0))

    def run(chunk):
        eng = ServeEngine(cfg, params, max_slots=2, max_len=64,
                          prefill_chunk=chunk)
        for i, p in enumerate(prompts()[:3]):
            eng.add_request(Request(f"m{i}", [t % cfg.vocab_size for t in p],
                                    max_new_tokens=4))
        return {r.request_id: r.tokens for r in eng.run()}

    assert run(8) == run(0)


def test_at_most_one_chunk_per_step():
    """Even on the step where an admission's final chunk lands, the next
    queued request must wait — the per-step stall bound is one chunk."""
    eng = make_engine(prefill_chunk=8, max_slots=4)
    calls = []
    real = eng._prefill

    def counting_prefill(*a, **kw):
        calls[-1] += 1
        return real(*a, **kw)
    eng._prefill = counting_prefill
    for i in range(3):
        eng.add_request(Request(f"r{i}", list(range(1, 20)),  # 3 chunks
                                max_new_tokens=2))
    while eng.has_work():
        calls.append(0)
        eng.step()
    assert max(calls) <= 1


def paged_engine(**kw):
    from kuberay_tpu.serve.paged_engine import PagedServeEngine
    kw.setdefault("max_slots", 4)
    kw.setdefault("max_len", 128)
    kw.setdefault("block_size", 8)
    return PagedServeEngine(CFG, PARAMS, **kw)


def test_paged_chunked_outputs_match_whole_prompt():
    def run(chunk):
        eng = paged_engine(prefill_chunk=chunk)
        for i, p in enumerate(prompts()):
            eng.add_request(Request(f"r{i}", p, max_new_tokens=8))
        return {r.request_id: (r.tokens, r.finish_reason)
                for r in eng.run()}
    assert run(8) == run(0)


def test_paged_chunked_prefix_caching_still_works():
    """A repeat prompt under chunked prefill reuses cached blocks and
    reproduces the cold tokens — chunk boundaries don't break sharing."""
    shared = list(range(1, 25))                   # 3 full 8-token blocks
    cold = paged_engine(prefill_chunk=8)
    cold.add_request(Request("a", shared + [40], max_new_tokens=4))
    expected = cold.run()[0].tokens

    eng = paged_engine(prefill_chunk=8)
    eng.add_request(Request("warm", shared + [40], max_new_tokens=4))
    eng.run()
    eng.add_request(Request("again", shared + [40], max_new_tokens=4))
    out = eng.run()
    assert out[0].tokens == expected
    assert eng.stats["prefix_hit_tokens"] > 0


def test_paged_chunked_memory_blocking_and_recovery():
    """When the pool can't hold a new prompt, the chunked admission
    blocks without leaking blocks, then proceeds after slots free up."""
    # 29-token prompts need 4 blocks each; a 5-block pool forces "b" to
    # wait until "a" finishes and releases.
    eng = paged_engine(prefill_chunk=8, max_slots=2, num_blocks=5)
    eng.add_request(Request("a", list(range(1, 30)), max_new_tokens=3))
    eng.add_request(Request("b", list(range(31, 60)), max_new_tokens=3))
    out = eng.run()
    assert sorted(r.request_id for r in out) == ["a", "b"]
    assert all(r.finish_reason in ("length", "eos") for r in out)
    assert eng.stats["free_blocks"] == eng.stats["num_blocks"]


def test_paged_chunked_impossible_prompt_cancelled():
    eng = paged_engine(prefill_chunk=8, max_slots=1, num_blocks=4)
    eng.add_request(Request("big", list(range(1, 100)), max_new_tokens=2))
    out = eng.run()
    assert out[0].finish_reason == "cancelled"


def test_inflight_blocks_reuse_of_slot_only():
    """The chunking slot is reserved: admission of other requests resumes
    after the in-flight prefill finishes, and nothing deadlocks with a
    full slot set."""
    eng = make_engine(prefill_chunk=8, max_slots=2)
    for i in range(4):
        eng.add_request(Request(f"r{i}", list(range(1, 20)),
                                max_new_tokens=3))
    out = eng.run()
    assert sorted(r.request_id for r in out) == ["r0", "r1", "r2", "r3"]
    assert all(len(r.tokens) == 3 for r in out)
