"""Golden metadata shapes for the external gang-scheduler adapters.

Each adapter's pod labels/annotations are a wire contract with a
scheduler we don't control (SURVEY.md §2.1) — the exact key names and
values are what Volcano / YuniKorn / KAI / coscheduling parse, so these
tests pin the *complete* stamped metadata as golden dicts (not just
spot-checked keys) plus the cleanup() lifecycle for every adapter.
"""

from __future__ import annotations

import json

from kuberay_tpu.controlplane.store import ObjectStore
from kuberay_tpu.scheduler.adapters import (KaiAdapter,
                                            SchedulerPluginsAdapter,
                                            VolcanoAdapter, YuniKornAdapter)
from kuberay_tpu.scheduler.gang import GangScheduler
from kuberay_tpu.utils import constants as C
from tests.test_api_types import make_cluster


def _cluster(queue="research"):
    c = make_cluster(accelerator="v5p", topology="2x2x2", replicas=2)
    c.spec.workerGroupSpecs[0].maxReplicas = 2
    d = c.to_dict()
    d["metadata"]["uid"] = "uid123"
    if queue:
        d["spec"]["gangSchedulingQueue"] = queue
    return d


def _worker_pod():
    return {"metadata": {"name": "p", "labels": {
        C.LABEL_NODE_TYPE: C.NODE_TYPE_WORKER,
        C.LABEL_GROUP: "workers"}}, "spec": {}}


def _head_pod():
    return {"metadata": {"name": "h", "labels": {
        C.LABEL_NODE_TYPE: C.NODE_TYPE_HEAD}}, "spec": {}}


def test_volcano_golden_metadata_and_cleanup():
    store = ObjectStore()
    v = VolcanoAdapter(store)
    cd = _cluster()
    assert v.on_cluster_submission(cd)
    pod = _worker_pod()
    v.add_metadata(cd, pod)
    assert pod["metadata"]["annotations"] == {
        "scheduling.k8s.io/group-name": "volcano-pg-demo",
        "scheduling.volcano.sh/queue-name": "research",
    }
    assert pod["spec"]["schedulerName"] == "volcano"
    pg = store.get("PodGroup", "volcano-pg-demo")
    assert pg["spec"] == {
        "minMember": 5,  # head + 2 slices x 2 hosts
        "minResources": {C.RESOURCE_TPU: 16},
        "queue": "research",
    }
    # No queue configured: the queue annotation is omitted entirely
    # (volcano falls back to its own default queue).
    bare = _worker_pod()
    v.add_metadata(_cluster(queue=""), bare)
    assert "scheduling.volcano.sh/queue-name" not in \
        bare["metadata"]["annotations"]
    v.cleanup(cd)
    assert store.try_get("PodGroup", "volcano-pg-demo") is None
    v.cleanup(cd)   # idempotent


def test_yunikorn_golden_metadata_and_cleanup():
    store = ObjectStore()
    y = YuniKornAdapter(store)
    cd = _cluster()
    assert y.on_cluster_submission(cd)
    worker, head = _worker_pod(), _head_pod()
    y.add_metadata(cd, worker)
    y.add_metadata(cd, head)
    assert worker["metadata"]["labels"]["applicationId"] == "demo"
    assert worker["metadata"]["labels"]["queue"] == "research"
    assert worker["spec"]["schedulerName"] == "yunikorn"
    # The task-groups JSON is the gang contract: head singleton plus one
    # group per worker group sized replicas x hosts.
    groups = json.loads(
        worker["metadata"]["annotations"]["yunikorn.apache.org/task-groups"])
    assert groups == [
        {"name": "head", "minMember": 1},
        {"name": "group-workers", "minMember": 4,
         "minResource": {C.RESOURCE_TPU: "4"}},
    ]
    assert worker["metadata"]["annotations"][
        "yunikorn.apache.org/task-group-name"] == "group-workers"
    assert head["metadata"]["annotations"][
        "yunikorn.apache.org/task-group-name"] == "head"
    y.cleanup(cd)   # stateless: nothing stored, nothing to fail


def test_scheduler_plugins_golden_metadata_and_cleanup():
    store = ObjectStore()
    sp = SchedulerPluginsAdapter(store)
    cd = _cluster()
    assert sp.on_cluster_submission(cd)
    pg = store.get("PodGroup", "demo")
    assert pg["apiVersion"] == "scheduling.x-k8s.io/v1alpha1"
    assert pg["spec"] == {"minMember": 5,
                          "minResources": {C.RESOURCE_TPU: 16}}
    assert pg["metadata"]["ownerReferences"][0]["uid"] == "uid123"
    pod = _worker_pod()
    sp.add_metadata(cd, pod)
    assert pod["metadata"]["labels"]["scheduling.x-k8s.io/pod-group"] == \
        "demo"
    assert pod["spec"]["schedulerName"] == "scheduler-plugins-scheduler"
    sp.cleanup(cd)
    assert store.try_get("PodGroup", "demo") is None
    sp.cleanup(cd)  # idempotent


def test_kai_golden_metadata_and_cleanup():
    k = KaiAdapter(ObjectStore())
    pod = _worker_pod()
    k.add_metadata(_cluster(), pod)
    assert pod["metadata"]["labels"]["kai.scheduler/queue"] == "research"
    assert pod["spec"]["schedulerName"] == "kai-scheduler"
    # No queue -> KAI's literal "default" queue (not omitted: KAI
    # requires the label).
    bare = _worker_pod()
    k.add_metadata(_cluster(queue=""), bare)
    assert bare["metadata"]["labels"]["kai.scheduler/queue"] == "default"
    k.cleanup(_cluster())   # stateless no-op


def test_builtin_gang_golden_metadata_and_cleanup():
    store = ObjectStore()
    gang = GangScheduler(store)
    cd = _cluster()
    assert gang.on_cluster_submission(cd)
    pod = _worker_pod()
    gang.add_metadata(cd, pod)
    assert pod["metadata"]["annotations"] == {"tpu.dev/pod-group": "pg-demo"}
    assert pod["metadata"]["labels"]["tpu.dev/queue"] == "research"
    pg = store.get("PodGroup", "pg-demo")
    assert pg["spec"] == {"minMember": 5,
                          "minResources": {C.RESOURCE_TPU: 16}}
    assert pg["metadata"]["labels"] == {"tpu.dev/queue": "research"}
    assert pg["metadata"]["ownerReferences"][0]["uid"] == "uid123"
    gang.cleanup(cd)
    assert store.try_get("PodGroup", "pg-demo") is None
    gang.cleanup(cd)    # idempotent (and quota-less: no release crash)
