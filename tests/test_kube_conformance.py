"""Conformance against a SIMULATED REAL kube-apiserver.

The repo's own apiserver speaks the watch protocol, but testing the
client against it alone is self-conformance.  ``SimKube`` here mimics the
quirks a real kube-apiserver + etcd exhibits that the in-house server
does not (ref envtest role, suite_test.go:78):

- **non-contiguous string resourceVersions** (etcd revisions jump);
- **RFC3339 creationTimestamp strings** and ``managedFields`` blobs in
  metadata (server-side bookkeeping the client must tolerate);
- **chunked LIST**: honors ``?limit=`` and answers with
  ``metadata.continue`` tokens + ``remainingItemCount``;
- **bounded watch history**: events older than the window are evicted;
  resuming from an evicted rv yields the K8s ERROR line
  ``{"type":"ERROR","object":{"kind":"Status","code":410}}``;
- **bookmarks** on an interval, not only at quiet moments;
- 409s carrying "already exists" vs rv-conflict messages.

The final test drives the REAL cluster controller over a RestObjectStore
against SimKube and forces a mid-reconcile 410 relist: the done-criterion
is no double-created slice pods (VERDICT r2 item 7).
"""

import itertools
import json
import re
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
from datetime import datetime, timezone
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from kuberay_tpu.controlplane.rest_store import RestObjectStore
from kuberay_tpu.utils import constants as C
from tests.test_api_types import make_cluster

_PLURAL_TO_KIND = {**{v: k for k, v in C.CRD_PLURALS.items()},
                   **{v: k for k, v in C.CORE_PLURALS.items()}}


def _now_rfc3339():
    return datetime.now(timezone.utc).strftime("%Y-%m-%dT%H:%M:%SZ")


def _sim_merge(cur, patch, strategic):
    """SimKube's own merge-patch walk (null deletes; dicts recurse;
    lists replace — except workerGroupSpecs under strategic, which
    merges by groupName per the kube strategic-merge spec)."""
    if not isinstance(patch, dict) or not isinstance(cur, dict):
        return json.loads(json.dumps(patch))
    out = dict(cur)
    for k, v in patch.items():
        if v is None:
            out.pop(k, None)
        elif strategic and k == "workerGroupSpecs" and isinstance(v, list):
            existing = [dict(g) for g in out.get(k) or []]
            names = [g.get("groupName") for g in existing]
            for e in v:
                gn = e.get("groupName")
                if gn in names:
                    i = names.index(gn)
                    existing[i] = _sim_merge(existing[i], e, strategic)
                else:
                    names.append(gn)
                    existing.append(json.loads(json.dumps(e)))
            out[k] = existing
        elif isinstance(v, dict):
            out[k] = _sim_merge(out.get(k) or {}, v, strategic)
        else:
            out[k] = json.loads(json.dumps(v))
    return out


class SimKube:
    """In-memory kube-apiserver lookalike (see module docstring)."""

    def __init__(self, history_window: int = 64, bookmark_every: float = 0.2,
                 page_limit_cap: int = 10_000):
        self.cond = threading.Condition()
        self.objs = {}                  # (kind, ns, name) -> obj
        self._rv = 1000
        self._uid = itertools.count(1)
        self.history = []               # (rv:int, type, obj snapshot)
        self.window = history_window
        self.evicted_through = 0        # max rv dropped from history
        self.bookmark_every = bookmark_every
        self.page_limit_cap = page_limit_cap

    # -- state ---------------------------------------------------------

    def _bump(self) -> int:
        # etcd revisions are shared across kinds and jump unpredictably.
        self._rv += 3 + (self._rv % 5)
        return self._rv

    def _record(self, etype: str, obj: dict):
        self.history.append((int(obj["metadata"]["resourceVersion"]),
                             etype, json.loads(json.dumps(obj))))
        while len(self.history) > self.window:
            rv, _, _ = self.history.pop(0)
            self.evicted_through = max(self.evicted_through, rv)
        self.cond.notify_all()

    def create(self, kind, ns, obj):
        name = obj.get("metadata", {}).get("name", "")
        key = (kind, ns, name)
        with self.cond:
            if key in self.objs:
                return None
            md = obj.setdefault("metadata", {})
            md["namespace"] = ns
            md["uid"] = f"sim-{next(self._uid)}"
            md["resourceVersion"] = str(self._bump())
            md["creationTimestamp"] = _now_rfc3339()
            md["managedFields"] = [{
                "manager": "simkube", "operation": "Update",
                "apiVersion": obj.get("apiVersion", "v1"),
                "time": md["creationTimestamp"]}]
            obj["kind"] = kind
            self.objs[key] = obj
            self._record("ADDED", obj)
            return obj

    def update(self, kind, ns, name, body, status_only=False):
        key = (kind, ns, name)
        with self.cond:
            cur = self.objs.get(key)
            if cur is None:
                return None, 404
            sent_rv = body.get("metadata", {}).get("resourceVersion")
            if sent_rv and sent_rv != cur["metadata"]["resourceVersion"]:
                return None, 409
            if status_only:
                cur["status"] = body.get("status", {})
            else:
                preserved = {k: cur["metadata"][k] for k in
                             ("uid", "creationTimestamp", "managedFields")}
                cur.update({k: v for k, v in body.items()
                            if k != "metadata"})
                cur["metadata"] = {**body.get("metadata", {}), **preserved,
                                   "namespace": ns}
            cur["metadata"]["resourceVersion"] = str(self._bump())
            self._record("MODIFIED", cur)
            return cur, 200

    def delete(self, kind, ns, name):
        with self.cond:
            obj = self.objs.pop((kind, ns, name), None)
            if obj is None:
                return False
            obj["metadata"]["resourceVersion"] = str(self._bump())
            self._record("DELETED", obj)
            return True

    def patch(self, kind, ns, name, body, strategic):
        """Kube-style merge/strategic PATCH — implemented INDEPENDENTLY
        of kuberay_tpu.controlplane.patch (same public spec, different
        code) so client-vs-server agreement is real conformance, not one
        implementation talking to itself."""
        key = (kind, ns, name)
        with self.cond:
            cur = self.objs.get(key)
            if cur is None:
                return None, 404
            sent_rv = (body.get("metadata") or {}).get("resourceVersion") \
                if isinstance(body, dict) else None
            if sent_rv and sent_rv != cur["metadata"]["resourceVersion"]:
                return None, 409
            merged = _sim_merge(cur, body, strategic)
            preserved = {k: cur["metadata"][k]
                         for k in ("uid", "creationTimestamp",
                                   "managedFields")
                         if k in cur["metadata"]}
            merged["metadata"] = {**merged.get("metadata", {}),
                                  **preserved,
                                  "namespace": ns, "name": name}
            merged["kind"] = kind
            merged["status"] = cur.get("status", {})   # status subresource
            merged["metadata"]["resourceVersion"] = str(self._bump())
            self.objs[key] = merged
            self._record("MODIFIED", merged)
            return merged, 200

    # -- HTTP ------------------------------------------------------------

    def make_server(self):
        sim = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def _send(self, code, body):
                data = json.dumps(body).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def _route(self):
                path = urllib.parse.urlsplit(self.path).path
                m = re.match(
                    r"^/(?:apis/tpu\.dev/v1|api/v1)"
                    r"(?:/namespaces/(?P<ns>[^/]+))?/(?P<plural>[^/]+)"
                    r"(?:/(?P<name>[^/]+))?(?:/(?P<sub>status))?$", path)
                if not m or m.group("plural") not in _PLURAL_TO_KIND:
                    return None
                return (_PLURAL_TO_KIND[m.group("plural")], m.group("ns"),
                        m.group("name"), m.group("sub"))

            def do_GET(self):
                r = self._route()
                if r is None:
                    return self._send(404, {"message": "unknown path"})
                kind, ns, name, _ = r
                q = urllib.parse.parse_qs(
                    urllib.parse.urlsplit(self.path).query)
                if name:
                    with sim.cond:
                        obj = sim.objs.get((kind, ns, name))
                    if obj is None:
                        return self._send(404, {"message": "not found"})
                    return self._send(200, obj)
                if q.get("watch", ["false"])[0] in ("true", "1"):
                    return self._watch(kind, ns, q)
                return self._list(kind, ns, q)

            def _list(self, kind, ns, q):
                sel = {}
                for part in (q.get("labelSelector") or [""])[0].split(","):
                    if "=" in part:
                        k, v = part.split("=", 1)
                        sel[k] = v
                with sim.cond:
                    rows = sorted(
                        (o for (k, n, _nm), o in sim.objs.items()
                         if k == kind and (ns is None or n == ns)
                         and all(o["metadata"].get("labels", {})
                                 .get(sk) == sv for sk, sv in sel.items())),
                        key=lambda o: o["metadata"]["name"])
                    rv = str(sim._rv)
                limit = min(int((q.get("limit") or [0])[0] or 0)
                            or sim.page_limit_cap, sim.page_limit_cap)
                offset = int((q.get("continue") or ["0"])[0] or 0)
                page = rows[offset:offset + limit]
                meta = {"resourceVersion": rv}
                if offset + limit < len(rows):
                    meta["continue"] = str(offset + limit)
                    meta["remainingItemCount"] = len(rows) - offset - limit
                return self._send(200, {
                    "kind": f"{kind}List", "apiVersion": "v1",
                    "metadata": meta, "items": page})

            def _watch(self, kind, ns, q):
                try:
                    rv = int((q.get("resourceVersion") or ["0"])[0] or 0)
                except ValueError:
                    return self._send(400, {"message": "bad rv"})
                hold = float((q.get("timeoutSeconds") or ["5"])[0])
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Transfer-Encoding", "chunked")
                self.end_headers()

                def emit(doc) -> bool:
                    data = json.dumps(doc).encode() + b"\n"
                    try:
                        self.wfile.write(
                            f"{len(data):x}\r\n".encode() + data + b"\r\n")
                        self.wfile.flush()
                        return True
                    except OSError:
                        return False

                deadline = time.time() + hold
                last_bookmark = time.time()
                with sim.cond:
                    if rv and rv < sim.evicted_through:
                        emit({"type": "ERROR", "object": {
                            "kind": "Status", "apiVersion": "v1",
                            "status": "Failure", "reason": "Expired",
                            "code": 410,
                            "message": "too old resource version"}})
                        try:
                            self.wfile.write(b"0\r\n\r\n")
                        except OSError:
                            pass
                        return
                    while time.time() < deadline:
                        if rv and rv < sim.evicted_through:
                            # Slow CONNECTED watcher fell behind the
                            # cache window: real apiservers terminate it
                            # with the 410 Status line mid-stream.
                            emit({"type": "ERROR", "object": {
                                "kind": "Status", "apiVersion": "v1",
                                "status": "Failure", "reason": "Expired",
                                "code": 410,
                                "message": "too old resource version"}})
                            break
                        sent_any = False
                        for erv, etype, obj in sim.history:
                            if erv <= rv or obj["kind"] != kind:
                                continue
                            if not emit({"type": etype, "object": obj}):
                                return
                            rv = erv
                            sent_any = True
                        if not sent_any and \
                                time.time() - last_bookmark >= \
                                sim.bookmark_every:
                            # Real apiservers bookmark on an interval
                            # with the GLOBAL rv, not this kind's last.
                            if not emit({"type": "BOOKMARK", "object": {
                                    "kind": kind, "metadata": {
                                        "resourceVersion": str(sim._rv)}}}):
                                return
                            rv = max(rv, sim._rv)
                            last_bookmark = time.time()
                        sim.cond.wait(timeout=0.05)
                try:
                    self.wfile.write(b"0\r\n\r\n")
                except OSError:
                    pass

            def do_POST(self):
                r = self._route()
                if r is None:
                    return self._send(404, {"message": "unknown path"})
                kind, ns, _, _ = r
                body = json.loads(self.rfile.read(
                    int(self.headers.get("Content-Length", 0))))
                obj = sim.create(kind, ns or "default", body)
                if obj is None:
                    return self._send(409, {
                        "message": f"{kind} already exists"})
                return self._send(201, obj)

            def do_PUT(self):
                r = self._route()
                if r is None:
                    return self._send(404, {"message": "unknown path"})
                kind, ns, name, sub = r
                body = json.loads(self.rfile.read(
                    int(self.headers.get("Content-Length", 0))))
                obj, code = sim.update(kind, ns or "default", name, body,
                                       status_only=(sub == "status"))
                if code == 404:
                    return self._send(404, {"message": "not found"})
                if code == 409:
                    return self._send(409, {
                        "message": "Operation cannot be fulfilled: "
                                   "object has been modified"})
                return self._send(200, obj)

            def do_DELETE(self):
                r = self._route()
                if r is None:
                    return self._send(404, {"message": "unknown path"})
                kind, ns, name, _ = r
                if not sim.delete(kind, ns or "default", name):
                    return self._send(404, {"message": "not found"})
                return self._send(200, {"status": "Success"})

            def do_PATCH(self):
                r = self._route()
                if r is None:
                    return self._send(404, {"message": "unknown path"})
                kind, ns, name, _ = r
                ctype = (self.headers.get("Content-Type", "")
                         .split(";")[0].strip())
                if ctype not in ("application/merge-patch+json",
                                 "application/strategic-merge-patch+json"):
                    return self._send(415, {
                        "message": f"unsupported media type {ctype}"})
                body = json.loads(self.rfile.read(
                    int(self.headers.get("Content-Length", 0))))
                obj, code = sim.patch(
                    kind, ns or "default", name, body,
                    strategic=ctype.startswith("application/strategic"))
                if code == 404:
                    return self._send(404, {"message": "not found"})
                if code == 409:
                    return self._send(409, {
                        "message": "Operation cannot be fulfilled: "
                                   "object has been modified"})
                return self._send(200, obj)

        srv = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        return srv, f"http://127.0.0.1:{srv.server_port}"


def wait_for(fn, timeout=30.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if fn():
            return True
        time.sleep(0.05)
    return False


@pytest.fixture
def sim():
    s = SimKube()
    srv, url = s.make_server()
    yield s, url
    srv.shutdown()


# -- raw-protocol conformance -------------------------------------------


def test_chunked_list_followed_across_pages(sim):
    s, url = sim
    for i in range(7):
        s.create("Pod", "default", {"apiVersion": "v1",
                                    "metadata": {"name": f"p{i}"}})
    # Raw: one page of 3 carries a continue token + remaining count.
    page = json.load(urllib.request.urlopen(
        f"{url}/api/v1/namespaces/default/pods?limit=3"))
    assert len(page["items"]) == 3
    assert page["metadata"]["continue"]
    assert page["metadata"]["remainingItemCount"] == 4
    # Client: RestObjectStore.list transparently follows the chain.
    store = RestObjectStore(url)
    store.LIST_PAGE_LIMIT = 3
    names = sorted(p["metadata"]["name"] for p in store.list("Pod"))
    assert names == [f"p{i}" for i in range(7)]


def test_metadata_quirks_tolerated(sim):
    """String timestamps, managedFields, non-contiguous string rvs —
    the client must round-trip them untouched."""
    s, url = sim
    store = RestObjectStore(url)
    created = store.create(make_cluster(name="quirk").to_dict())
    md = created["metadata"]
    assert re.match(r"\d{4}-\d{2}-\d{2}T", md["creationTimestamp"])
    assert md["managedFields"][0]["manager"] == "simkube"
    rv1 = int(md["resourceVersion"])
    got = store.get(C.KIND_CLUSTER, "quirk")
    got["spec"]["suspend"] = True
    rv2 = int(store.update(got)["metadata"]["resourceVersion"])
    assert rv2 > rv1 + 1          # rvs jump; nothing may assume +1


def test_stale_rv_update_conflicts(sim):
    from kuberay_tpu.controlplane.store import Conflict
    s, url = sim
    store = RestObjectStore(url)
    store.create(make_cluster(name="cas").to_dict())
    a = store.get(C.KIND_CLUSTER, "cas")
    b = store.get(C.KIND_CLUSTER, "cas")
    a["spec"]["suspend"] = True
    store.update(a)
    b["spec"]["suspend"] = False
    with pytest.raises(Conflict):
        store.update(b)            # stale rv -> 409 rv-conflict


def test_watch_bookmarks_advance_resume_point(sim):
    """Interval bookmarks must advance the client's resume rv so a
    reconnect does not replay (or 410) — even with zero real events for
    the watched kind while OTHER kinds churn the global rv."""
    s, url = sim
    s.bookmark_every = 0.05
    store = RestObjectStore(url, watched_kinds=("TpuCluster",),
                            poll_interval=0.05)
    seen = []
    store.watch(seen.append)    # blocks until cache sync
    # Churn a DIFFERENT kind past the history window: without bookmark
    # handling the TpuCluster watcher's rv would fall behind and 410.
    for i in range(s.window + 20):
        s.create("Pod", "default", {"apiVersion": "v1",
                                    "metadata": {"name": f"churn{i}"}})
    time.sleep(0.6)                # several bookmark intervals
    s.create("TpuCluster", "default",
             make_cluster(name="after-churn").to_dict())
    assert wait_for(lambda: any(
        e.obj["metadata"]["name"] == "after-churn" for e in seen))
    store.close()


def test_watch_410_recovery_emits_missed_diff_once(sim):
    """An evicted resume rv must yield exactly one ADDED per missed
    object after the relist — no duplicates, no misses."""
    s, url = sim
    s.window = 4                   # tiny history: easy to evict
    s.bookmark_every = 3600        # no bookmarks: force the 410 path
    store = RestObjectStore(url, watched_kinds=("TpuCluster",),
                            poll_interval=0.05)
    seen = []
    store.watch(seen.append)    # blocks until cache sync
    s.create("TpuCluster", "default", make_cluster(name="pre").to_dict())
    assert wait_for(lambda: len(seen) >= 1)
    # Hold the watcher's rv behind while evicting: churn pods far past
    # the window, then add clusters the stream may or may not deliver
    # before expiry — the client must converge either way.
    for i in range(20):
        s.create("Pod", "default", {"apiVersion": "v1",
                                    "metadata": {"name": f"evict{i}"}})
    s.create("TpuCluster", "default", make_cluster(name="missed").to_dict())
    for i in range(20, 40):
        s.create("Pod", "default", {"apiVersion": "v1",
                                    "metadata": {"name": f"evict{i}"}})
    assert wait_for(lambda: sum(
        1 for e in seen if e.kind == "TpuCluster"
        and e.obj["metadata"]["name"] == "missed") >= 1, timeout=20)
    time.sleep(1.0)                # settle: catch any late duplicates
    adds = [e for e in seen if e.type == "ADDED"
            and e.obj["metadata"]["name"] == "missed"]
    assert len(adds) == 1, f"missed object delivered {len(adds)} times"
    store.close()


def test_patches_interleaved_with_relists(sim):
    """PATCHes landing between a watcher's 410 expiry and its relist
    must neither be lost nor double-applied: the final object state and
    the watcher's converged view agree (VERDICT r3 item 2)."""
    s, url = sim
    s.window = 4                    # tiny history: every churn evicts
    s.bookmark_every = 3600         # no bookmarks: force the 410 path
    store = RestObjectStore(url, watched_kinds=("TpuCluster",),
                            poll_interval=0.05)
    latest = {}
    store.watch(lambda ev: latest.__setitem__(
        ev.obj["metadata"]["name"], ev.obj))
    c = make_cluster(name="patched", accelerator="v5e", topology="2x2",
                     replicas=1).to_dict()
    c["spec"]["workerGroupSpecs"][0]["maxReplicas"] = 50
    store.create(c)
    # Interleave: merge + strategic patches with pod churn that keeps
    # expiring the TpuCluster watch mid-stream.
    for i in range(1, 11):
        store.patch(C.KIND_CLUSTER, "patched", "default",
                    {"spec": {"workerGroupSpecs": [
                        {"groupName": "workers", "replicas": i}]}},
                    patch_type="strategic")
        store.patch(C.KIND_CLUSTER, "patched", "default",
                    {"metadata": {"annotations": {"round": str(i)}}},
                    patch_type="merge")
        for j in range(6):
            s.create("Pod", "default", {
                "apiVersion": "v1",
                "metadata": {"name": f"churn-{i}-{j}"}})
    final = store.get(C.KIND_CLUSTER, "patched")
    g = final["spec"]["workerGroupSpecs"][0]
    assert g["replicas"] == 10
    assert g["topology"] == "2x2"                 # merged, never clobbered
    assert final["metadata"]["annotations"]["round"] == "10"
    # The watcher's converged view (through however many 410 relists)
    # must reach the same state.
    assert wait_for(lambda: latest.get("patched", {}).get(
        "spec", {}).get("workerGroupSpecs",
                        [{}])[0].get("replicas") == 10, timeout=20)
    store.close()


def test_autoscaler_scales_via_patch_under_410s(sim):
    """The done-criterion for VERDICT r3 item 2: the slice autoscaler
    scales a cluster via strategic PATCH against a kube-semantics server
    while watch history keeps expiring; the controller converges to the
    patched scale with no duplicate slice pods."""
    from kuberay_tpu.controlplane.autoscaler import (
        GroupDecision,
        apply_decisions,
    )
    from kuberay_tpu.controlplane.cluster_controller import (
        TpuClusterController,
    )
    from kuberay_tpu.controlplane.fake_kubelet import FakeKubelet
    from kuberay_tpu.controlplane.manager import Manager, owned_pod_mapper

    s, url = sim
    s.window = 6
    s.bookmark_every = 3600
    store = RestObjectStore(url, poll_interval=0.05)
    manager = Manager(store)
    ctrl = TpuClusterController(store, expectations=manager.expectations)
    manager.register(C.KIND_CLUSTER, ctrl.reconcile)
    manager.map_owned(owned_pod_mapper)
    kubelet = FakeKubelet(store)

    c = make_cluster(name="asc", accelerator="v5p", topology="2x2x2",
                     replicas=1)
    d = c.to_dict()
    d["spec"]["workerGroupSpecs"][0]["maxReplicas"] = 4
    d["metadata"]["annotations"] = {"keep": "me"}
    store.create(d)

    def settle(rounds=6):
        for _ in range(rounds):
            manager.flush_delayed()
            manager.run_until_idle()
            kubelet.step()
            for i in range(4):          # keep evicting watch history
                s.create("Event", "default", {
                    "apiVersion": "v1",
                    "metadata": {"name": f"churn-{time.time()}-{i}"},
                    "reason": "Noise"})

    deadline = time.time() + 30
    while time.time() < deadline:
        settle()
        obj = store.try_get(C.KIND_CLUSTER, "asc")
        if obj and obj.get("status", {}).get("state") == "ready":
            break
    # The autoscaler's write path: one strategic PATCH, no RMW loop.
    assert apply_decisions(store, "asc", "default",
                           [GroupDecision("workers", 2, [],
                                          "demand 2 > 1")])
    deadline = time.time() + 45
    while time.time() < deadline:
        settle()
        obj = store.try_get(C.KIND_CLUSTER, "asc")
        if obj and obj.get("status", {}).get("readySlices") == 2:
            break
    obj = store.get(C.KIND_CLUSTER, "asc")
    assert obj["spec"]["workerGroupSpecs"][0]["replicas"] == 2
    assert obj["metadata"]["annotations"]["keep"] == "me"
    workers = [p for p in store.list("Pod", "default")
               if p["metadata"].get("labels", {})
               .get(C.LABEL_NODE_TYPE) == "worker"]
    assert len(workers) == 4               # 2 slices x 2 hosts, no dups
    assert len({p["metadata"]["name"] for p in workers}) == 4
    assert obj["status"]["readySlices"] == 2
    store.close()


# -- the done-criterion: full controller over SimKube through a 410 ------


@pytest.mark.timeout(120)
def test_cluster_controller_survives_forced_relist(sim):
    """The REAL cluster controller reconciles a slice over SimKube; a
    mid-reconcile watch expiry (tiny history + churn) forces a relist.
    Slice pods must not be double-created (VERDICT r2 item 7)."""
    from kuberay_tpu.controlplane.cluster_controller import (
        TpuClusterController,
    )
    from kuberay_tpu.controlplane.fake_kubelet import FakeKubelet
    from kuberay_tpu.controlplane.manager import Manager, owned_pod_mapper

    s, url = sim
    s.window = 6                   # aggressive eviction
    s.bookmark_every = 3600
    store = RestObjectStore(url, poll_interval=0.05)
    manager = Manager(store)
    ctrl = TpuClusterController(store,
                                expectations=manager.expectations)
    manager.register(C.KIND_CLUSTER, ctrl.reconcile)
    manager.map_owned(owned_pod_mapper)
    kubelet = FakeKubelet(store)

    c = make_cluster(name="relist", accelerator="v5p", topology="2x2x2",
                     replicas=1)       # 8 chips / 4 per host = 2-host slice
    store.create(c.to_dict())

    def settle(rounds=6):
        for _ in range(rounds):
            manager.flush_delayed()
            manager.run_until_idle()
            kubelet.step()

    def worker_pods():
        return [p for p in store.list("Pod", "default")
                if p["metadata"].get("labels", {})
                .get(C.LABEL_CLUSTER) == "relist"
                and p["metadata"]["labels"]
                .get(C.LABEL_NODE_TYPE) == "worker"]

    deadline = time.time() + 60
    while time.time() < deadline:
        settle()
        # Churn: evict watch history WHILE the controller reconciles, so
        # its informer path has to relist mid-flight.
        for i in range(8):
            s.create("Event", "default", {
                "apiVersion": "v1",
                "metadata": {"name": f"churn-{time.time()}-{i}"},
                "reason": "Noise"})
        obj = store.try_get(C.KIND_CLUSTER, "relist")
        if obj and obj.get("status", {}).get("state") == "ready":
            break
    assert store.get(C.KIND_CLUSTER, "relist")["status"]["state"] == "ready"

    # Let relists + requeues settle, then assert the invariant.
    for _ in range(5):
        settle()
        time.sleep(0.2)
    pods = worker_pods()
    assert len(pods) == 2, [p["metadata"]["name"] for p in pods]
    names = [p["metadata"]["name"] for p in pods]
    assert len(set(names)) == 2
    store.close()
