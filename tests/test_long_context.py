"""Long-context training path: ring attention wired into the model +
sharded train step over an sp mesh (SURVEY §5.7: SP/CP as a first-class
framework feature)."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kuberay_tpu.models import llama
from kuberay_tpu.parallel.mesh import MeshSpec
from kuberay_tpu.train.train_step import TrainConfig, make_sharded_train_fns

BASE = llama.CONFIGS["llama_tiny"]
RING_CFG = dataclasses.replace(BASE, attn_impl="ring")


def make_batch(key, batch=2, seq=64):
    tokens = jax.random.randint(key, (batch, seq), 0, BASE.vocab_size)
    return {"tokens": tokens, "targets": jnp.roll(tokens, -1, axis=1)}


def test_ring_forward_matches_xla():
    mesh = MeshSpec(dp=1, fsdp=1, tp=1, sp=4).build(jax.devices()[:4])
    params = llama.init_params(BASE, jax.random.PRNGKey(0))
    tokens = make_batch(jax.random.PRNGKey(1))["tokens"]
    ref = llama.forward(BASE, params, tokens)
    got = llama.forward(RING_CFG, params, tokens, mesh=mesh)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=3e-3, atol=3e-3)


def test_ring_requires_mesh():
    params = llama.init_params(BASE, jax.random.PRNGKey(0))
    tokens = make_batch(jax.random.PRNGKey(1))["tokens"]
    with pytest.raises(ValueError):
        llama.forward(RING_CFG, params, tokens)


def test_sp_sharded_train_step():
    """Full train step with the sequence sharded over sp=4: loss matches
    the unsharded xla-attention baseline; batch arrays stay sp-sharded."""
    mesh = MeshSpec(dp=1, fsdp=2, tp=1, sp=4).build(jax.devices()[:8])
    tc = TrainConfig(warmup_steps=2, decay_steps=10)
    init, step, _ = make_sharded_train_fns(RING_CFG, tc, mesh)
    state = init(jax.random.PRNGKey(0))
    batch = make_batch(jax.random.PRNGKey(7), batch=2, seq=64)
    state2, metrics = step(state, batch)
    assert bool(jnp.isfinite(metrics["total_loss"]))

    # Baseline on a plain mesh with standard attention.
    mesh0 = MeshSpec(dp=1, fsdp=2, tp=1).build(jax.devices()[:2])
    init0, step0, _ = make_sharded_train_fns(BASE, tc, mesh0)
    _, m0 = step0(init0(jax.random.PRNGKey(0)), batch)
    np.testing.assert_allclose(float(metrics["loss"]), float(m0["loss"]),
                               rtol=2e-3)
    # Two more sp steps keep improving (optimizer + ring bwd are sane).
    state3, m2 = step(state2, batch)
    state4, m3 = step(state3, batch)
    assert float(m3["loss"]) < float(metrics["loss"])
