"""Cron parser + catch-up math (ref raycronjob_controller.go:93-135)."""

import time

import pytest

from kuberay_tpu.utils.cron import CronError, missed_runs, next_run_after, parse_cron


def test_parse_basic():
    s = parse_cron("*/15 3 * * 1-5")
    assert s.minute == {0, 15, 30, 45}
    assert s.hour == {3}
    assert s.weekday == {1, 2, 3, 4, 5}
    assert not s.day_restricted and s.weekday_restricted


def test_parse_errors():
    for bad in ("* * * *", "61 * * * *", "*/0 * * * *", "a * * * *",
                "1-60 * * * *", "1-5, * * * *", ",1 * * * *"):
        with pytest.raises(CronError):
            parse_cron(bad)


def test_sunday_as_7():
    assert parse_cron("0 0 * * 7").weekday == {0}
    # Ranges through 7 are valid and include Sunday (robfig compat).
    assert parse_cron("0 0 * * 1-7").weekday == {0, 1, 2, 3, 4, 5, 6}
    assert parse_cron("0 0 * * 5-7").weekday == {0, 5, 6}


def test_star_step_keeps_star_bit():
    from kuberay_tpu.utils.cron import matches
    # '*/2' in DOM keeps the star bit: AND semantics with the DOW field
    # (robfig compat) -> Thu Jan 1 2026 (odd day, not Monday) must NOT match.
    s = parse_cron("0 0 */2 * 1")
    thu = time.mktime((2026, 1, 1, 0, 0, 0, 0, 0, -1))
    assert not matches(s, thu)
    mon5 = time.mktime((2026, 1, 5, 0, 0, 0, 0, 0, -1))   # Monday, odd day
    assert matches(s, mon5)


def test_weekday_step_caps_at_six():
    # '1/2' in DOW: robfig expands to {1,3,5} (max 6), not through 7.
    assert parse_cron("0 0 * * 1/2").weekday == {1, 3, 5}


def test_dom_dow_or_rule():
    from kuberay_tpu.utils.cron import matches
    # '0 0 13 * 5': both restricted -> fires on the 13th OR any Friday.
    s = parse_cron("0 0 13 * 5")
    fri = time.mktime((2026, 1, 2, 0, 0, 0, 0, 0, -1))    # Fri Jan 2 2026
    thirteenth = time.mktime((2026, 1, 13, 0, 0, 0, 0, 0, -1))  # Tue Jan 13
    other = time.mktime((2026, 1, 5, 0, 0, 0, 0, 0, -1))  # Mon Jan 5
    assert matches(s, fri) and matches(s, thirteenth) and not matches(s, other)
    # Only DOM restricted -> AND semantics (weekday wildcard).
    s2 = parse_cron("0 0 13 * *")
    assert matches(s2, thirteenth) and not matches(s2, fri)


def test_next_run():
    # 2026-01-01 00:00:00 local.
    base = time.mktime((2026, 1, 1, 0, 0, 0, 0, 0, -1))
    nxt = next_run_after("30 2 * * *", base)
    st = time.localtime(nxt)
    assert (st.tm_hour, st.tm_min) == (2, 30)
    assert nxt > base


def test_missed_runs_catchup():
    base = time.mktime((2026, 1, 1, 0, 0, 30, 0, 0, -1))
    runs = missed_runs("*/10 * * * *", base, base + 3600)
    assert len(runs) == 6
    mins = [time.localtime(r).tm_min for r in runs]
    assert mins == [10, 20, 30, 40, 50, 0]


def test_missed_runs_limit():
    base = time.mktime((2026, 1, 1, 0, 0, 0, 0, 0, -1))
    runs = missed_runs("* * * * *", base, base + 86400, limit=10)
    assert len(runs) == 10
