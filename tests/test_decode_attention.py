"""Pallas decode-attention kernel vs XLA reference."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kuberay_tpu.ops.decode_attention import (
    decode_attention,
    decode_attention_pallas,
    decode_attention_xla,
)


def make(S=3, Hq=4, Hkv=2, D=16, M=64, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    q = jax.random.normal(ks[0], (S, Hq, D))
    ck = jax.random.normal(ks[1], (S, M, Hkv, D))
    cv = jax.random.normal(ks[2], (S, M, Hkv, D))
    return q, ck, cv


@pytest.mark.parametrize("gqa", [1, 2, 4])
def test_pallas_matches_xla(gqa):
    q, ck, cv = make(Hq=4, Hkv=4 // gqa)
    lens = jnp.array([5, 33, 64], jnp.int32)
    ref = decode_attention_xla(q, ck, cv, lens)
    got = decode_attention_pallas(q, ck, cv, lens, bkv=16, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-3, atol=2e-3)


def test_ragged_lengths_and_short_slots():
    """Per-slot lengths incl. len=1 and len=block-boundary cases."""
    q, ck, cv = make(S=4, M=48)
    lens = jnp.array([1, 16, 17, 48], jnp.int32)
    ref = decode_attention_xla(q, ck, cv, lens)
    got = decode_attention_pallas(q, ck, cv, lens, bkv=16, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-3, atol=2e-3)


def test_non_dividing_max_len_falls_back():
    q, ck, cv = make(M=50)    # 50 not divisible by any pow2 block >= 8
    lens = jnp.array([10, 20, 50], jnp.int32)
    got = decode_attention_pallas(q, ck, cv, lens, interpret=True)
    ref = decode_attention_xla(q, ck, cv, lens)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-3, atol=2e-3)


def test_engine_generation_unchanged_by_kernel_path():
    """The serving engine produces identical greedy generations whichever
    decode-attention path runs (XLA on CPU; the kernel via interpret)."""
    from kuberay_tpu.models import llama
    from kuberay_tpu.serve.engine import Request, ServeEngine
    from kuberay_tpu.ops import decode_attention as da

    cfg = llama.CONFIGS["llama_tiny"]
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    eng = ServeEngine(cfg, params, max_slots=2, max_len=64)
    eng.add_request(Request("r", [5, 6, 7], max_new_tokens=5))
    baseline = {r.request_id: r.tokens for r in eng.run()}["r"]

    orig = da.decode_attention
    da.decode_attention = lambda q, ck, cv, lens, scale=None, impl="auto": \
        orig(q, ck, cv, lens, scale, impl="pallas_interpret")
    try:
        eng2 = ServeEngine(cfg, params, max_slots=2, max_len=64)
        eng2.add_request(Request("r", [5, 6, 7], max_new_tokens=5))
        kernel_out = {r.request_id: r.tokens for r in eng2.run()}["r"]
    finally:
        da.decode_attention = orig
    assert kernel_out == baseline


def test_auto_impl_self_check_caches_and_falls_back(monkeypatch):
    """The auto path's first-use on-chip self-check: failures (wrong
    numerics OR lowering errors) permanently fall back to XLA for the
    process; the check runs exactly once per kernel kind."""
    from kuberay_tpu.ops import decode_attention as da

    da._AUTO_VERDICTS.clear()
    monkeypatch.setattr(da.jax, "default_backend", lambda: "tpu")
    try:
        calls = []

        def bad():
            calls.append(1)
            return False

        assert da._auto_impl("k-bad", bad) == "xla"
        assert da._auto_impl("k-bad", bad) == "xla"   # cached
        assert len(calls) == 1

        def boom():
            raise RuntimeError("Mosaic lowering failed")

        assert da._auto_impl("k-boom", boom) == "xla"

        assert da._auto_impl("k-good", lambda: True) == "pallas"
    finally:
        da._AUTO_VERDICTS.clear()


def test_auto_off_tpu_never_runs_checks(monkeypatch):
    from kuberay_tpu.ops import decode_attention as da

    da._AUTO_VERDICTS.clear()
    monkeypatch.setattr(da.jax, "default_backend", lambda: "cpu")

    def explode():
        raise AssertionError("check must not run off-TPU")

    assert da._auto_impl("k-cpu", explode) == "xla"
    assert not da._AUTO_VERDICTS      # nothing cached


def test_auto_self_check_executes_eagerly_inside_jit_trace(monkeypatch):
    """The dispatch runs at TRACE time (the serve engine jits the step
    that reaches it): the self-check must EXECUTE eagerly there — a
    staged check's float() would raise ConcretizationTypeError and
    masquerade as a kernel failure, permanently disabling Pallas."""
    import jax
    import jax.numpy as jnp

    from kuberay_tpu.ops import decode_attention as da

    da._AUTO_VERDICTS.clear()
    monkeypatch.setattr(da.jax, "default_backend", lambda: "tpu")
    try:
        def check():
            # Representative of the real checks: device compute + a
            # host float() comparison.
            return float(jnp.max(jnp.ones(4) * 2.0)) == 2.0

        def traced(x):
            impl = da._auto_impl("k-trace", check)
            return x + (1.0 if impl == "pallas" else 0.0)

        out = float(jax.jit(traced)(jnp.float32(0)))
        assert out == 1.0                       # check passed -> pallas
        assert da._AUTO_VERDICTS["k-trace"] is True
    finally:
        da._AUTO_VERDICTS.clear()


def test_auto_end_to_end_degrades_not_crashes(monkeypatch):
    """With the backend claiming to be TPU while actually CPU, the REAL
    self-checks either pass (pallas lowers on this backend) or fail —
    but decode_attention(auto) must return correct numbers either way."""
    import jax
    import jax.numpy as jnp

    from kuberay_tpu.ops import decode_attention as da

    da._AUTO_VERDICTS.clear()
    monkeypatch.setattr(da.jax, "default_backend", lambda: "tpu")
    try:
        S, M, Hq, Hkv, D = 2, 64, 4, 2, 64
        ks = jax.random.split(jax.random.PRNGKey(3), 3)
        q = jax.random.normal(ks[0], (S, Hq, D), jnp.float32)
        ck = jax.random.normal(ks[1], (S, M, Hkv, D), jnp.float32)
        cv = jax.random.normal(ks[2], (S, M, Hkv, D), jnp.float32)
        lens = jnp.array([10, 64], jnp.int32)
        got = da.decode_attention(q, ck, cv, lens, impl="auto")
        want = da.decode_attention_xla(q, ck, cv, lens)
        assert float(jnp.max(jnp.abs(got - want))) < 5e-2
        assert "decode" in da._AUTO_VERDICTS    # the check ran and cached
    finally:
        da._AUTO_VERDICTS.clear()
