"""Pallas decode-attention kernel vs XLA reference."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kuberay_tpu.ops.decode_attention import (
    decode_attention,
    decode_attention_pallas,
    decode_attention_xla,
)


def make(S=3, Hq=4, Hkv=2, D=16, M=64, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    q = jax.random.normal(ks[0], (S, Hq, D))
    ck = jax.random.normal(ks[1], (S, M, Hkv, D))
    cv = jax.random.normal(ks[2], (S, M, Hkv, D))
    return q, ck, cv


@pytest.mark.parametrize("gqa", [1, 2, 4])
def test_pallas_matches_xla(gqa):
    q, ck, cv = make(Hq=4, Hkv=4 // gqa)
    lens = jnp.array([5, 33, 64], jnp.int32)
    ref = decode_attention_xla(q, ck, cv, lens)
    got = decode_attention_pallas(q, ck, cv, lens, bkv=16, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-3, atol=2e-3)


def test_ragged_lengths_and_short_slots():
    """Per-slot lengths incl. len=1 and len=block-boundary cases."""
    q, ck, cv = make(S=4, M=48)
    lens = jnp.array([1, 16, 17, 48], jnp.int32)
    ref = decode_attention_xla(q, ck, cv, lens)
    got = decode_attention_pallas(q, ck, cv, lens, bkv=16, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-3, atol=2e-3)


def test_non_dividing_max_len_falls_back():
    q, ck, cv = make(M=50)    # 50 not divisible by any pow2 block >= 8
    lens = jnp.array([10, 20, 50], jnp.int32)
    got = decode_attention_pallas(q, ck, cv, lens, interpret=True)
    ref = decode_attention_xla(q, ck, cv, lens)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-3, atol=2e-3)


def test_engine_generation_unchanged_by_kernel_path():
    """The serving engine produces identical greedy generations whichever
    decode-attention path runs (XLA on CPU; the kernel via interpret)."""
    from kuberay_tpu.models import llama
    from kuberay_tpu.serve.engine import Request, ServeEngine
    from kuberay_tpu.ops import decode_attention as da

    cfg = llama.CONFIGS["llama_tiny"]
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    eng = ServeEngine(cfg, params, max_slots=2, max_len=64)
    eng.add_request(Request("r", [5, 6, 7], max_new_tokens=5))
    baseline = {r.request_id: r.tokens for r in eng.run()}["r"]

    orig = da.decode_attention
    da.decode_attention = lambda q, ck, cv, lens, scale=None, impl="auto": \
        orig(q, ck, cv, lens, scale, impl="pallas_interpret")
    try:
        eng2 = ServeEngine(cfg, params, max_slots=2, max_len=64)
        eng2.add_request(Request("r", [5, 6, 7], max_new_tokens=5))
        kernel_out = {r.request_id: r.tokens for r in eng2.run()}["r"]
    finally:
        da.decode_attention = orig
    assert kernel_out == baseline
