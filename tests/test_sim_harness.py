"""Chaos-simulation subsystem gate (kuberay_tpu.sim).

Mirrors test_static_analysis.py's two-half structure:

1. the machinery's own regression tests — virtual clock threading,
   fault-plan budgets, kubelet fault surface, journal determinism
   (same seed + scenario => byte-identical journal hash);
2. every invariant checker proven to FIRE on a hand-built violating
   store state, plus a seeded-regression drill (slice env injection
   sabotaged mid-run => a checker catches it with a replayable seed);
3. a small smoke corpus across all scenarios — the per-PR robustness
   gate (tools/sim_smoke.sh runs the bigger corpus).
"""

import pytest

from kuberay_tpu.controlplane.fake_kubelet import FakeKubelet
from kuberay_tpu.controlplane.manager import Manager
from kuberay_tpu.controlplane.store import Conflict, ObjectStore
from kuberay_tpu.sim.clock import VirtualClock
from kuberay_tpu.sim.faults import (
    STORE_CONFLICT,
    WATCH_DROP,
    FaultPlan,
)
from kuberay_tpu.sim.harness import SimHarness
from kuberay_tpu.sim.invariants import (
    CHECKERS,
    DESCRIPTIONS,
    CheckContext,
    run_checkers,
)
from kuberay_tpu.sim.scenarios import SCENARIOS, get_scenario, make_cluster_obj
from kuberay_tpu.utils import constants as C
from kuberay_tpu.utils.metrics import ControlPlaneMetrics


# ---------------------------------------------------------------------------
# virtual clock in the manager
# ---------------------------------------------------------------------------

def test_manager_timed_requeues_run_on_virtual_clock():
    clock = VirtualClock(start=1000.0)
    store = ObjectStore()
    manager = Manager(store, clock=clock)
    seen = []
    manager.register("Thing", lambda name, ns: seen.append(name) or None)
    manager.enqueue(("Thing", "default", "later"), after=30.0)
    assert manager.next_delayed_at() == pytest.approx(1030.0)
    # Virtual time has not reached the deadline: nothing runs.
    assert manager.run_until_idle() == 0
    assert seen == []
    clock.advance(29.0)
    assert manager.run_until_idle() == 0
    # Crossing the deadline promotes the key — no flush_delayed needed.
    clock.advance(1.5)
    assert manager.run_until_idle() == 1
    assert seen == ["later"]
    assert manager.next_delayed_at() is None


def test_manager_counts_conflicts_and_errors():
    store = ObjectStore()
    metrics = ControlPlaneMetrics()
    manager = Manager(store, metrics=metrics)

    calls = {"n": 0}

    def flaky(name, ns):
        calls["n"] += 1
        if calls["n"] == 1:
            raise Conflict("lost the rv race")
        if calls["n"] == 2:
            raise RuntimeError("boom")
        return None

    manager.register("Thing", flaky)
    manager.enqueue(("Thing", "default", "x"))
    manager.run_until_idle()            # -> Conflict, requeued
    manager.flush_delayed()
    manager.run_until_idle()            # -> RuntimeError, requeued
    manager.flush_delayed()
    manager.run_until_idle()            # -> clean
    text = metrics.render()
    assert 'tpu_reconcile_conflicts_total{kind="Thing"} 1' in text
    assert 'tpu_reconcile_errors_total{kind="Thing"} 1' in text


# ---------------------------------------------------------------------------
# fake kubelet fault surface
# ---------------------------------------------------------------------------

def _make_pod(store, name, labels=None, phase=None):
    pod = {"apiVersion": "v1", "kind": "Pod",
           "metadata": {"name": name, "labels": labels or {}},
           "spec": {"containers": [{"name": "w"}]}}
    store.create(pod)
    if phase:
        cur = store.get("Pod", name)
        cur["status"] = {"phase": phase}
        store.update_status(cur)


def test_fail_pod_merges_status_keeping_pod_ip():
    store = ObjectStore()
    kubelet = FakeKubelet(store)
    _make_pod(store, "w0")
    kubelet.step()
    running = store.get("Pod", "w0")
    ip = running["status"]["podIP"]
    assert running["status"]["phase"] == "Running"
    # Failure injection via the step() queue (the wholesale-overwrite
    # path this PR fixes), not the direct fail_pod shortcut.
    with kubelet._lock:
        kubelet._pending.add(("default", "w0"))
        kubelet._fail_next.add(("default", "w0"))
    kubelet.step()
    failed = store.get("Pod", "w0")
    assert failed["status"]["phase"] == "Failed"
    assert failed["status"]["podIP"] == ip            # last IP survives
    assert failed["status"]["conditions"]             # conditions survive
    kubelet.close()


def test_deferred_fail_injection_merges_status():
    store = ObjectStore()
    kubelet = FakeKubelet(store)
    # Injection BEFORE the pod exists: deferred through _fail_next.
    kubelet.fail_pod("w1")
    _make_pod(store, "w1")
    kubelet.step()      # consumes the queued failure
    failed = store.get("Pod", "w1")
    assert failed["status"]["phase"] == "Failed"
    kubelet.close()


def test_hold_pod_delays_start_until_virtual_release():
    clock = VirtualClock(start=0.0)
    store = ObjectStore()
    kubelet = FakeKubelet(store, now_fn=clock.now)
    _make_pod(store, "slow")
    kubelet.hold_pod("slow", until=50.0)
    assert kubelet.next_hold_at() == 50.0
    kubelet.step()
    assert store.get("Pod", "slow").get("status", {}).get(
        "phase", "Pending") == "Pending"
    clock.advance(51.0)
    kubelet.step()
    assert store.get("Pod", "slow")["status"]["phase"] == "Running"
    assert kubelet.next_hold_at() is None
    kubelet.close()


def test_fail_slice_takes_all_hosts_down():
    store = ObjectStore()
    kubelet = FakeKubelet(store)
    for h in range(2):
        _make_pod(store, f"s0-{h}",
                  labels={C.LABEL_SLICE_NAME: "grp-0"})
    kubelet.step()
    assert kubelet.fail_slice("grp-0") == 2
    phases = {p["status"]["phase"] for p in store.list("Pod")}
    assert phases == {"Failed"}
    kubelet.close()


# ---------------------------------------------------------------------------
# fault plan
# ---------------------------------------------------------------------------

def test_fault_plan_budgeted_conflict_injection():
    plan = FaultPlan(seed=1, profile={f: 0.0 for f in
                                      FaultPlan(0).profile})
    plan.profile[STORE_CONFLICT] = 2.0      # exactly two armed per step
    plan.arm()
    store = ObjectStore()
    store.set_interposer(plan)
    with pytest.raises(Conflict):
        store.create({"kind": "Pod", "metadata": {"name": "a"}})
    with pytest.raises(Conflict):
        store.create({"kind": "Pod", "metadata": {"name": "a"}})
    # Budget exhausted: the third write lands.
    store.create({"kind": "Pod", "metadata": {"name": "a"}})
    assert plan.injected[STORE_CONFLICT] == 2
    # Suspension shields harness-internal writes.
    plan.profile[STORE_CONFLICT] = 1.0
    plan.arm()
    with plan.suspended():
        store.create({"kind": "Pod", "metadata": {"name": "b"}})
    with pytest.raises(Conflict):
        store.create({"kind": "Pod", "metadata": {"name": "c"}})


def test_fault_plan_watch_drop_is_store_level():
    plan = FaultPlan(seed=3, profile={f: 0.0 for f in
                                      FaultPlan(0).profile})
    plan.profile[WATCH_DROP] = 1.0
    plan.arm()
    store = ObjectStore()
    seen = []
    store.watch(lambda ev: seen.append((ev.type, ev.kind)))
    store.set_interposer(plan)
    store.create({"kind": "Pod", "metadata": {"name": "a"}})   # dropped
    store.create({"kind": "Pod", "metadata": {"name": "b"}})   # delivered
    assert seen == [("ADDED", "Pod")]
    # The streaming backlog always has the truth.
    events, _, _ = store.events_since(0)
    assert len([e for _, e in events if e.kind == "Pod"]) == 2


# ---------------------------------------------------------------------------
# determinism: same seed + scenario => byte-identical journal hash
# ---------------------------------------------------------------------------

@pytest.mark.timeout(120)
def test_same_seed_same_scenario_identical_journal_hash():
    results = []
    for _ in range(2):
        with SimHarness(11, scenario=get_scenario("scale-up-storm")) as h:
            results.append(h.run(4))
    assert results[0].journal_hash == results[1].journal_hash
    assert results[0].journal_len == results[1].journal_len
    assert results[0].faults_injected == results[1].faults_injected
    assert results[0].ok, [str(v) for v in results[0].violations]


# ---------------------------------------------------------------------------
# every checker fires on a hand-built violating state
# ---------------------------------------------------------------------------

def _fired(store, journal=None):
    return {v.invariant
            for v in run_checkers(CheckContext(store, journal or []))}


def _worker_pod(name, slice_name, host_idx, cluster="demo",
                env=None, group="workers", extra_labels=None):
    labels = {
        C.LABEL_CLUSTER: cluster,
        C.LABEL_NODE_TYPE: C.NODE_TYPE_WORKER,
        C.LABEL_GROUP: group,
        C.LABEL_SLICE_NAME: slice_name,
        C.LABEL_SLICE_INDEX: slice_name.rsplit("-", 1)[-1],
        C.LABEL_HOST_INDEX: str(host_idx),
    }
    labels.update(extra_labels or {})
    default_env = {
        C.ENV_TPU_WORKER_ID: str(host_idx),
        C.ENV_TPU_WORKER_HOSTNAMES: "h0.svc,h1.svc",
        C.ENV_NUM_PROCESSES: "2",
    }
    if env is not None:
        default_env = env
    return {
        "apiVersion": "v1", "kind": "Pod",
        "metadata": {"name": name, "labels": labels},
        "spec": {"containers": [{
            "name": "w",
            "env": [{"name": k, "value": v}
                    for k, v in default_env.items()]}]},
        "status": {"phase": "Running"},
    }


def _seed_cluster(store, replicas=1):
    store.create(make_cluster_obj("demo", topology="2x2x2",
                                  replicas=replicas))


def test_registry_covers_the_issue_catalog():
    assert {"slice-identity", "slice-atomicity", "gang-admission",
            "warm-pool-accounting", "service-capacity",
            "no-resurrection", "drain-before-delete"} <= set(CHECKERS)
    for name in CHECKERS:
        assert DESCRIPTIONS[name]


def test_checker_sparse_worker_ids_fire():
    store = ObjectStore()
    _seed_cluster(store)
    # Two hosts claiming the same TPU_WORKER_ID (sparse set {0, 0}).
    store.create(_worker_pod("w0", "demo-workers-0", 0))
    bad = _worker_pod("w1", "demo-workers-0", 1)
    bad["spec"]["containers"][0]["env"] = [
        {"name": C.ENV_TPU_WORKER_ID, "value": "0"},
        {"name": C.ENV_TPU_WORKER_HOSTNAMES, "value": "h0.svc,h1.svc"},
        {"name": C.ENV_NUM_PROCESSES, "value": "2"},
    ]
    store.create(bad)
    fired = _fired(store)
    assert "slice-identity" in fired


def test_checker_inconsistent_hostnames_fire():
    store = ObjectStore()
    _seed_cluster(store)
    store.create(_worker_pod("w0", "demo-workers-0", 0))
    store.create(_worker_pod("w1", "demo-workers-0", 1, env={
        C.ENV_TPU_WORKER_ID: "1",
        C.ENV_TPU_WORKER_HOSTNAMES: "OTHER.svc,h1.svc",
        C.ENV_NUM_PROCESSES: "2",
    }))
    assert "slice-identity" in _fired(store)


def test_checker_missing_env_fire():
    store = ObjectStore()
    _seed_cluster(store)
    store.create(_worker_pod("w0", "demo-workers-0", 0))
    store.create(_worker_pod("w1", "demo-workers-0", 1, env={}))
    assert "slice-identity" in _fired(store)


def test_checker_partial_slice_fires():
    store = ObjectStore()
    _seed_cluster(store)
    # One host of a 2-host slice: atomicity violation AND a non-whole
    # slice count (gang).
    store.create(_worker_pod("w0", "demo-workers-0", 0))
    fired = _fired(store)
    assert "slice-atomicity" in fired
    assert "gang-admission" in fired


def test_checker_partially_running_slice_fires():
    store = ObjectStore()
    _seed_cluster(store)
    store.create(_worker_pod("w0", "demo-workers-0", 0))
    sick = _worker_pod("w1", "demo-workers-0", 1)
    sick["status"] = {"phase": "Pending"}
    store.create(sick)
    assert "slice-atomicity" in _fired(store)


def test_checker_drain_before_delete_fires():
    store = ObjectStore()
    journal = [{"type": "DELETED", "kind": "Pod", "ns": "default",
                "name": "w0", "rv": 7, "uid": "sim-uid-000001",
                "notice": "120.000"}]
    assert "drain-before-delete" in _fired(store, journal)


def test_checker_drain_before_delete_quiet_when_drained():
    store = ObjectStore()
    journal = [{"type": "DELETED", "kind": "Pod", "ns": "default",
                "name": "w0", "rv": 7, "uid": "sim-uid-000001",
                "notice": "120.000", "drained": "120.000"}]
    assert "drain-before-delete" not in _fired(store, journal)


def test_checker_warm_pool_accounting_fires():
    store = ObjectStore()
    store.create({
        "apiVersion": C.API_VERSION, "kind": "WarmSlicePool",
        "metadata": {"name": "standby"},
        "spec": {"accelerator": "v5e", "topology": "2x2", "poolSize": 1},
        "status": {"warmSlices": -1, "readySlices": 2,
                   "hostsPerSlice": 1},
    })
    fired = _fired(store)
    assert "warm-pool-accounting" in fired


def test_checker_double_assigned_warm_pod_fires():
    from kuberay_tpu.controlplane.warmpool_controller import LABEL_WARM_POOL
    store = ObjectStore()
    store.create({
        "apiVersion": C.API_VERSION, "kind": "WarmSlicePool",
        "metadata": {"name": "standby"},
        "spec": {"accelerator": "v5e", "topology": "2x2", "poolSize": 1},
        "status": {"warmSlices": 1, "readySlices": 1, "hostsPerSlice": 1},
    })
    # An unclaimed warm pod that ALSO carries a cluster label: assigned
    # to a consumer without going through claim().
    store.create(_worker_pod(
        "warm0", "warmpool-standby-warm-0", 0,
        extra_labels={LABEL_WARM_POOL: "standby"}))
    assert "warm-pool-accounting" in _fired(store)


def test_checker_service_capacity_fires():
    store = ObjectStore()
    store.create({
        "apiVersion": C.API_VERSION, "kind": C.KIND_SERVICE,
        "metadata": {"name": "inference"},
        "spec": {"clusterSpec":
                 make_cluster_obj("tmpl", replicas=1)["spec"]},
        "status": {},
    })
    svc = store.get(C.KIND_SERVICE, "inference")
    # Active cluster reference points at nothing: the upgrade deleted the
    # serving cluster before promotion.
    svc["status"] = {"serviceStatus": "Running",
                     "activeServiceStatus": {"clusterName": "gone"}}
    store.update_status(svc)
    assert "service-capacity" in _fired(store)


def test_checker_no_resurrection_fires():
    store = ObjectStore()
    journal = [
        {"type": "ADDED", "kind": "Pod", "ns": "default", "name": "w0",
         "rv": 1, "uid": "u1"},
        {"type": "DELETED", "kind": "Pod", "ns": "default", "name": "w0",
         "rv": 2, "uid": "u1"},
        # A status write re-materializing the deleted object's uid.
        {"type": "MODIFIED", "kind": "Pod", "ns": "default", "name": "w0",
         "rv": 3, "uid": "u1"},
    ]
    assert "no-resurrection" in _fired(store, journal)


def test_checkers_quiet_on_healthy_converged_state():
    with SimHarness(0, scenario=get_scenario("scale-up-storm"),
                    fault_profile={f: 0.0
                                   for f in FaultPlan(0).profile}) as h:
        violations = h.step()
    assert violations == [], [str(v) for v in violations]


# ---------------------------------------------------------------------------
# seeded regression drill: sabotage env injection mid-run, a checker
# catches it with a replayable seed in the report
# ---------------------------------------------------------------------------

@pytest.mark.timeout(120)
def test_seeded_regression_is_caught_with_replayable_seed(monkeypatch):
    from kuberay_tpu.builders import pod as pod_builder
    real = pod_builder.build_worker_pod

    def sabotaged(cluster, group, slice_idx, host_idx, **kw):
        out = real(cluster, group, slice_idx, host_idx, **kw)
        if host_idx == 1:       # one slice member loses its identity env
            env = out["spec"]["containers"][0]["env"]
            out["spec"]["containers"][0]["env"] = [
                e for e in env if e["name"] != C.ENV_TPU_WORKER_ID]
        return out

    monkeypatch.setattr(pod_builder, "build_worker_pod", sabotaged)
    with SimHarness(5, scenario=get_scenario("scale-up-storm")) as h:
        result = h.run(3)
    assert not result.ok
    assert any(v.invariant == "slice-identity" for v in result.violations)
    # The failure report names the seed so the run replays exactly.
    assert "--seed 5" in result.replay_command()


# ---------------------------------------------------------------------------
# smoke corpus: every scenario converges clean on a small fixed seed set
# ---------------------------------------------------------------------------

@pytest.mark.timeout(300)
@pytest.mark.parametrize("scenario_name", sorted(SCENARIOS))
def test_scenario_smoke_corpus(scenario_name):
    for seed in (0, 1):
        with SimHarness(seed, scenario=get_scenario(scenario_name)) as h:
            result = h.run(3)
        assert result.ok, (
            f"replay: {result.replay_command()}\n"
            + "\n".join(str(v) for v in result.violations))
        assert result.converged
        # The sim exports its injections as metrics.
        if sum(result.faults_injected.values()):
            assert "sim_faults_injected_total" in h.metrics.render()
