"""Timeline + device-profiling subsystem (SURVEY §5.1: the reference's
historyserver preserves Ray timeline/profile events; here the
orchestration timeline is Chrome-trace JSON from CR/event history and
device profiles are jax.profiler traces captured via the coordinator)."""

import json
import urllib.request

from kuberay_tpu.utils.timeline import cluster_timeline


def _cluster_doc():
    return {
        "kind": "TpuCluster",
        "metadata": {"name": "tl", "namespace": "default",
                     "creationTimestamp": 100.0,
                     "deletionTimestamp": 400.0},
        "status": {
            "state": "ready",
            "stateTransitionTimes": {"ready": 160.0, "suspended": 300.0},
            "conditions": [
                {"type": "HeadPodReady", "status": "True",
                 "reason": "HeadPodRunning", "lastTransitionTime": 150.0}],
        },
        "events": [
            {"involvedObject": {"name": "tl"}, "reason": "CreatedSlice",
             "type": "Normal", "eventTime": 155.0, "message": "slice up"}],
    }


def test_cluster_timeline_shape():
    doc = _cluster_doc()
    jobs = [{"metadata": {"name": "j1"},
             "status": {"startTime": 170.0, "endTime": 250.0,
                        "jobDeploymentStatus": "Complete",
                        "jobStatus": "SUCCEEDED"}}]
    trace = cluster_timeline(doc, jobs=jobs)
    evs = trace["traceEvents"]
    assert all(evs[i]["ts"] <= evs[i + 1]["ts"] for i in range(len(evs) - 1))
    names = [e["name"] for e in evs]
    # State spans: provisioning -> ready -> suspended (span to deletion).
    spans = [e for e in evs if e["ph"] == "X" and e["cat"] == "state"]
    assert [s["name"] for s in spans] == ["provisioning", "ready",
                                          "suspended"]
    assert spans[0]["ts"] == 100_000_000 and spans[0]["dur"] == 60_000_000
    assert spans[2]["dur"] == 100_000_000   # 300 -> 400 deletion
    assert "HeadPodReady=True" in names
    assert "CreatedSlice" in names
    j = next(e for e in evs if e["cat"] == "job")
    assert j["dur"] == 80_000_000 and j["args"]["job"] == "SUCCEEDED"


def test_timeline_from_history_archive(tmp_path):
    """Deleted cluster's timeline served by the history replay API."""
    from kuberay_tpu.history.server import HistoryServer
    from kuberay_tpu.history.storage import LocalStorage

    storage = LocalStorage(str(tmp_path))
    doc = _cluster_doc()
    doc["archivedAt"] = 400.0
    # Real archives store events pre-filtered with involvedObject
    # STRIPPED (HistoryCollector._archive) — the timeline must still
    # render them.
    doc["events"] = [{"reason": "CreatedSlice", "type": "Normal",
                      "eventTime": 155.0, "message": "slice up"}]
    storage.put_doc("TpuCluster/default/tl.json", doc)
    srv, url = HistoryServer(storage).serve_background()
    try:
        trace = json.load(urllib.request.urlopen(
            f"{url}/api/history/timeline/default/tl"))
        assert trace["traceEvents"], trace
        assert any(e["name"] == "CreatedSlice"
                   for e in trace["traceEvents"])
    finally:
        srv.shutdown()


def test_coordinator_profile_endpoints(tmp_path):
    """start -> appears in list -> stop; a second start while running is
    rejected.  On CPU the jax profiler trace is tiny but real."""
    from kuberay_tpu.runtime.coordinator_server import CoordinatorServer

    coord = CoordinatorServer(log_dir=str(tmp_path), spawn_jobs=False,
                              auth_token="")
    srv, url = coord.serve_background()
    try:
        req = urllib.request.Request(
            f"{url}/api/profile/start", data=b"{}",
            headers={"Content-Type": "application/json"}, method="POST")
        out = json.load(urllib.request.urlopen(req))
        assert "trace_dir" in out and "error" not in out
        # Second start rejected while running.
        try:
            urllib.request.urlopen(urllib.request.Request(
                f"{url}/api/profile/start", data=b"{}", method="POST"))
            raise AssertionError("double start should 400")
        except urllib.error.HTTPError as e:
            assert e.code == 400
        out = json.load(urllib.request.urlopen(urllib.request.Request(
            f"{url}/api/profile/stop", data=b"", method="POST")))
        assert "trace_dir" in out
        profiles = json.load(urllib.request.urlopen(
            f"{url}/api/profile/"))["profiles"]
        assert len(profiles) == 1 and profiles[0].startswith("trace-")
    finally:
        srv.shutdown()


def test_tpuctl_timeline(capsys):
    """tpuctl timeline renders a live cluster from the apiserver."""
    import threading
    from kuberay_tpu.api.config import OperatorConfiguration
    from kuberay_tpu.cli.__main__ import main as tpuctl
    from kuberay_tpu.operator import Operator
    from tests.test_api_types import make_cluster

    op = Operator(OperatorConfiguration(), fake_kubelet=True)
    op.start(leader_election=False)
    try:
        op.store.create(make_cluster(name="tlive").to_dict())
        for _ in range(10):
            op.run_until_idle()
        rc = tpuctl(["--server", op.api_url, "timeline", "tlive"])
        assert rc == 0
        trace = json.loads(capsys.readouterr().out)
        assert any(e["cat"] == "state" for e in trace["traceEvents"])
    finally:
        op.stop()
