"""History archive: storage backends (S3 SigV4 / GCS wire protocols),
log + coordinator collectors, and the full kill-a-cluster-then-replay
path (ref historyserver/pkg/storage + pkg/collector + test/e2e)."""

import json
import threading
import time
import urllib.error
import urllib.request
import xml.sax.saxutils
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from kuberay_tpu.history.collector import CoordinatorCollector, LogCollector
from kuberay_tpu.history.server import HistoryCollector, HistoryServer
from kuberay_tpu.history.storage import (
    GCSStorage,
    LocalStorage,
    S3Storage,
    backend_from_url,
    sigv4_headers,
)
from kuberay_tpu.utils import constants as C
from kuberay_tpu.utils.httpjson import serve_background
from tests.test_api_types import make_cluster


# ---------------------------------------------------------------------------
# Backends


def test_local_backend_roundtrip(tmp_path):
    b = LocalStorage(str(tmp_path / "arch"))
    b.put("logs/default/c1/head/raylet.log", b"line1\n")
    b.put("logs/default/c1/w0/out.log", b"w0\n")
    b.put_doc("TpuCluster/default/c1.json", {"kind": "TpuCluster"})
    assert b.get("logs/default/c1/head/raylet.log") == b"line1\n"
    assert b.get("missing") is None
    assert b.list("logs/default/c1/") == [
        "logs/default/c1/head/raylet.log", "logs/default/c1/w0/out.log"]
    b.delete("logs/default/c1/w0/out.log")
    assert b.list("logs/default/c1/") == ["logs/default/c1/head/raylet.log"]
    with pytest.raises(ValueError):
        b.put("../evil", b"x")


def test_backend_from_url(tmp_path):
    from kuberay_tpu.history.storage import CompressedBackend

    # Compression wraps by default (ref historyserver/pkg/compression).
    b = backend_from_url(str(tmp_path))
    assert isinstance(b, CompressedBackend)
    assert isinstance(b.inner, LocalStorage)
    # compress=none skips WRITE compression only — reads keep sniffing
    # so an existing compressed archive is never stranded.
    raw = backend_from_url(f"file://{tmp_path}?compress=none")
    assert isinstance(raw, CompressedBackend) and not raw.compress_writes
    s3 = backend_from_url("s3://bkt?endpoint=http://h:9000&region=eu-west-1")
    assert isinstance(s3, CompressedBackend)
    s3 = s3.inner
    assert isinstance(s3, S3Storage)
    assert (s3.bucket, s3.endpoint, s3.region) == \
        ("bkt", "http://h:9000", "eu-west-1")
    gs = backend_from_url("gs://bkt2?endpoint=http://h:8080").inner
    assert isinstance(gs, GCSStorage)
    assert (gs.bucket, gs.endpoint) == ("bkt2", "http://h:8080")
    with pytest.raises(ValueError):
        backend_from_url("azure://x")


class _FakeS3(BaseHTTPRequestHandler):
    """Minimal S3 endpoint that VERIFIES SigV4 signatures by re-deriving
    them with the shared secret — proves wire compatibility, not just
    that a header exists."""

    objects = {}
    access_key, secret_key, region = "AK", "SK", "us-east-1"

    def log_message(self, *a):
        pass

    def _verify(self, payload: bytes) -> bool:
        auth = self.headers.get("Authorization", "")
        if not auth.startswith("AWS4-HMAC-SHA256"):
            return False
        import datetime
        amz = self.headers["x-amz-date"]
        now = datetime.datetime.strptime(amz, "%Y%m%dT%H%M%SZ").replace(
            tzinfo=datetime.timezone.utc)
        url = f"http://{self.headers['Host']}{self.path}"
        expect = sigv4_headers(self.command, url, self.region, "s3",
                               self.access_key, self.secret_key, payload,
                               now=now)
        return expect["Authorization"] == auth

    def do_PUT(self):
        body = self.rfile.read(int(self.headers.get("Content-Length", 0)))
        if not self._verify(body):
            self.send_response(403), self.end_headers()
            return
        _FakeS3.objects[self.path] = body
        self.send_response(200), self.end_headers()

    def do_GET(self):
        if not self._verify(b""):
            self.send_response(403), self.end_headers()
            return
        if "?" in self.path:                       # ListObjectsV2
            q = dict(p.split("=", 1)
                     for p in self.path.split("?", 1)[1].split("&"))
            bucket = self.path.split("?")[0].strip("/")
            prefix = urllib.request.unquote(q.get("prefix", ""))
            keys = sorted(k[len(bucket) + 2:]
                          for k in _FakeS3.objects
                          if k.startswith(f"/{bucket}/")
                          and k[len(bucket) + 2:].startswith(prefix))
            xml = "".join(
                f"<Contents><Key>{xml_escape(k)}</Key></Contents>"
                for k in keys)
            body = (f"<ListBucketResult><IsTruncated>false</IsTruncated>"
                    f"{xml}</ListBucketResult>").encode()
            self.send_response(200)
            self.end_headers()
            self.wfile.write(body)
            return
        body = _FakeS3.objects.get(self.path)
        if body is None:
            self.send_response(404), self.end_headers()
            return
        self.send_response(200), self.end_headers()
        self.wfile.write(body)

    def do_DELETE(self):
        if not self._verify(b""):
            self.send_response(403), self.end_headers()
            return
        _FakeS3.objects.pop(self.path, None)
        self.send_response(204), self.end_headers()


def xml_escape(s):
    return xml.sax.saxutils.escape(s)


def test_s3_backend_wire_protocol():
    _FakeS3.objects = {}
    srv = ThreadingHTTPServer(("127.0.0.1", 0), _FakeS3)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        b = S3Storage(f"http://127.0.0.1:{srv.server_port}", "bkt",
                      access_key="AK", secret_key="SK")
        b.put("TpuCluster/default/c1.json", b'{"kind":"TpuCluster"}')
        b.put("logs/default/c1/head/a.log", b"aaa")
        assert b.get("TpuCluster/default/c1.json") == b'{"kind":"TpuCluster"}'
        assert b.get("nope") is None
        assert b.list("logs/") == ["logs/default/c1/head/a.log"]
        b.delete("logs/default/c1/head/a.log")
        assert b.list("logs/") == []
        # Wrong creds rejected by the fake's signature re-derivation.
        bad = S3Storage(f"http://127.0.0.1:{srv.server_port}", "bkt",
                        access_key="AK", secret_key="WRONG")
        with pytest.raises(urllib.error.HTTPError):
            bad.put("x", b"y")
    finally:
        srv.shutdown()


class _FakeGCS(BaseHTTPRequestHandler):
    objects = {}
    token = "tok123"

    def log_message(self, *a):
        pass

    def _authed(self):
        return self.headers.get("Authorization") == f"Bearer {self.token}"

    def do_POST(self):                             # upload
        if not self._authed():
            self.send_response(401), self.end_headers()
            return
        body = self.rfile.read(int(self.headers.get("Content-Length", 0)))
        q = dict(p.split("=", 1)
                 for p in self.path.split("?", 1)[1].split("&"))
        name = urllib.request.unquote(q["name"])
        _FakeGCS.objects[name] = body
        self._json({"name": name})

    def do_GET(self):
        if not self._authed():
            self.send_response(401), self.end_headers()
            return
        path, _, query = self.path.partition("?")
        if path.endswith("/o"):                    # list
            q = dict(p.split("=", 1) for p in query.split("&") if "=" in p)
            prefix = urllib.request.unquote(q.get("prefix", ""))
            items = [{"name": k} for k in sorted(_FakeGCS.objects)
                     if k.startswith(prefix)]
            return self._json({"items": items})
        name = urllib.request.unquote(path.rsplit("/o/", 1)[1])
        body = _FakeGCS.objects.get(name)
        if body is None:
            self.send_response(404), self.end_headers()
            return
        self.send_response(200), self.end_headers()
        self.wfile.write(body)

    def do_DELETE(self):
        if not self._authed():
            self.send_response(401), self.end_headers()
            return
        name = urllib.request.unquote(
            self.path.partition("?")[0].rsplit("/o/", 1)[1])
        _FakeGCS.objects.pop(name, None)
        self.send_response(204), self.end_headers()

    def _json(self, doc):
        body = json.dumps(doc).encode()
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.end_headers()
        self.wfile.write(body)


def test_gcs_backend_wire_protocol():
    _FakeGCS.objects = {}
    srv = ThreadingHTTPServer(("127.0.0.1", 0), _FakeGCS)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        b = GCSStorage("bkt", token="tok123",
                       endpoint=f"http://127.0.0.1:{srv.server_port}")
        b.put("meta/default/c1/metadata.json", b"{}")
        assert b.get("meta/default/c1/metadata.json") == b"{}"
        assert b.get("gone") is None
        assert b.list("meta/") == ["meta/default/c1/metadata.json"]
    finally:
        srv.shutdown()


# ---------------------------------------------------------------------------
# Collectors


def test_log_collector_uploads_changes(tmp_path):
    logd = tmp_path / "logs"
    (logd / "sub").mkdir(parents=True)
    (logd / "train.log").write_text("step 1\n")
    (logd / "sub" / "gc.log").write_text("gc\n")
    storage = LocalStorage(str(tmp_path / "arch"))
    col = LogCollector(storage, str(logd), cluster="c1", node="w0")
    assert col.poll_once() == 2
    assert storage.get("logs/default/c1/w0/train.log") == b"step 1\n"
    # Unchanged files skip; appended files re-upload whole.
    assert col.poll_once() == 0
    (logd / "train.log").write_text("step 1\nstep 2\n")
    assert col.poll_once() == 1
    assert storage.get("logs/default/c1/w0/train.log") == b"step 1\nstep 2\n"
    # stop() runs the final flush.
    (logd / "late.log").write_text("tail\n")
    col.stop()
    assert storage.get("logs/default/c1/w0/late.log") == b"tail\n"


def test_coordinator_collector_archives_jobs(tmp_path):
    from kuberay_tpu.utils.httpjson import JsonHandler

    class FakeCoord(JsonHandler):
        def do_GET(self):
            if self.path == "/api/cluster":
                return self._send(200, {"clusterName": "c1",
                                        "tpuVersion": "v5e"})
            if self.path == "/api/jobs/":
                return self._send(200, {"jobs": [
                    {"job_id": "j-1", "status": "SUCCEEDED"}]})
            if self.path == "/api/jobs/j-1/logs":
                return self._send(200, {"logs": "hello from job\n"})
            return self._send(404, {})

    srv = ThreadingHTTPServer(("127.0.0.1", 0), FakeCoord)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    storage = LocalStorage(str(tmp_path / "arch"))
    try:
        col = CoordinatorCollector(
            storage, f"http://127.0.0.1:{srv.server_port}", cluster="c1")
        assert col.collect_once() == 3
        meta = storage.get_doc("meta/default/c1/metadata.json")
        assert meta["tpuVersion"] == "v5e"
        jobs = storage.get_doc("meta/default/c1/jobs.json")
        assert jobs["jobs"][0]["job_id"] == "j-1"
        assert storage.get("logs/default/c1/head/jobs/j-1.log") == \
            b"hello from job\n"
    finally:
        srv.shutdown()


# ---------------------------------------------------------------------------
# End-to-end replay: kill a cluster, fetch logs+events+status from the API
# (VERDICT r1 item 4's done-criterion; ref test/e2e/historyserver_test.go).


def test_kill_cluster_then_replay_from_history(tmp_path):
    from kuberay_tpu.controlplane.store import ObjectStore

    store = ObjectStore()
    storage = LocalStorage(str(tmp_path / "arch"))
    cr_col = HistoryCollector(store, storage)

    # Live cluster with a worker log dir being collected.
    c = make_cluster(name="doomed")
    store.create(c.to_dict())
    obj = store.get(C.KIND_CLUSTER, "doomed")
    obj["status"] = {"state": "ready", "readySlices": 1}
    store.update_status(obj)
    store.create({
        "apiVersion": "v1", "kind": "Event",
        "metadata": {"name": "doomed.ev1", "namespace": "default"},
        "type": "Warning", "reason": "SliceUnhealthy", "message": "host died",
        "involvedObject": {"kind": C.KIND_CLUSTER, "name": "doomed",
                           "namespace": "default"},
        "eventTime": 2.0,
    })
    logd = tmp_path / "nodelogs"
    logd.mkdir()
    (logd / "train.log").write_text("loss=1.0\nloss=0.5\n")
    log_col = LogCollector(storage, str(logd), cluster="doomed", node="w0")
    log_col.poll_once()

    # Kill it.
    store.delete(C.KIND_CLUSTER, "doomed")
    log_col.stop()
    cr_col.close()

    # Everything remains fetchable over the replay API.
    srv, url = HistoryServer(storage).serve_background()
    try:
        rows = json.load(urllib.request.urlopen(
            f"{url}/api/history/clusters"))["items"]
        assert rows == [{"name": "doomed", "namespace": "default",
                         "state": "ready", "deleted": True,
                         "archivedAt": rows[0]["archivedAt"]}]
        doc = json.load(urllib.request.urlopen(
            f"{url}/api/history/TpuCluster/default/doomed"))
        assert doc["status"]["state"] == "ready"
        assert any(e["reason"] == "SliceUnhealthy" for e in doc["events"])
        files = json.load(urllib.request.urlopen(
            f"{url}/api/history/logs/default/doomed"))["files"]
        assert files == ["w0/train.log"]
        text = urllib.request.urlopen(
            f"{url}/api/history/logs/default/doomed/w0/train.log").read()
        assert b"loss=0.5" in text
    finally:
        srv.shutdown()


def test_tpuctl_download_logs(tmp_path, capsys):
    """tpuctl download-logs pulls a (possibly dead) cluster's per-node
    logs out of the archive (ref kubectl-plugin/pkg/cmd/log.go)."""
    from kuberay_tpu.cli.__main__ import main as tpuctl

    storage = LocalStorage(str(tmp_path / "arch"))
    storage.put("logs/default/gone/w0/train.log", b"w0 line\n")
    storage.put("logs/default/gone/w1/sub/gc.log", b"w1 gc\n")
    srv, url = HistoryServer(storage).serve_background()
    out = tmp_path / "dl"
    try:
        rc = tpuctl(["download-logs", "gone", "--history-url", url,
                     "--out-dir", str(out)])
        assert rc == 0
        assert (out / "w0" / "train.log").read_bytes() == b"w0 line\n"
        assert (out / "w1" / "sub" / "gc.log").read_bytes() == b"w1 gc\n"
        # Node filter.
        out2 = tmp_path / "dl2"
        rc = tpuctl(["download-logs", "gone", "--history-url", url,
                     "--out-dir", str(out2), "--node", "w1"])
        assert rc == 0
        assert not (out2 / "w0").exists()
        assert (out2 / "w1" / "sub" / "gc.log").exists()
        # Unknown cluster errors out.
        assert tpuctl(["download-logs", "nope",
                       "--history-url", url]) == 1
    finally:
        srv.shutdown()


def test_tpuctl_download_logs_rejects_traversal(tmp_path):
    """A hostile archive listing must not write outside --out-dir."""
    import json as _json
    from http.server import ThreadingHTTPServer
    from kuberay_tpu.cli.__main__ import main as tpuctl
    from kuberay_tpu.utils.httpjson import JsonHandler

    class EvilHistory(JsonHandler):
        def do_GET(self):
            if self.path.endswith("/evil"):
                return self._send(200, {"files": ["../../escape.txt",
                                                  "/abs.txt",
                                                  "ok/fine.log"]})
            if self.path.endswith("/ok/fine.log"):
                return self._send_text(200, "fine")
            return self._send_text(200, "pwned")

    srv = ThreadingHTTPServer(("127.0.0.1", 0), EvilHistory)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    out = tmp_path / "safe"
    try:
        rc = tpuctl(["download-logs", "evil",
                     "--history-url", f"http://127.0.0.1:{srv.server_port}",
                     "--out-dir", str(out)])
        assert rc == 0
        assert (out / "ok" / "fine.log").exists()
        assert not (tmp_path / "escape.txt").exists()
        assert sorted(p.name for p in out.rglob("*") if p.is_file()) == \
            ["fine.log"]
    finally:
        srv.shutdown()


# ---------------------------------------------------------------------------
# Event pipeline e2e (VERDICT r2 item 5; ref eventserver.go:838): run a
# real job on a live coordinator, ingest structured step events, archive,
# and replay them post-mortem through /api/history.


@pytest.mark.timeout(60)
def test_event_pipeline_run_archive_replay(tmp_path):
    import sys

    from kuberay_tpu.runtime.coordinator_client import CoordinatorClient
    from kuberay_tpu.runtime.coordinator_server import (
        CoordinatorServer,
        MemoryBackend,
    )

    coord = CoordinatorServer(state=MemoryBackend(),
                              log_dir=str(tmp_path / "logs"))
    srv, url = coord.serve_background()
    storage = LocalStorage(str(tmp_path / "arch"))
    try:
        client = CoordinatorClient(url)
        # A real job process runs to completion -> lifecycle task events.
        client.submit_job("j-ev", f"{sys.executable} -c 'print(42)'")
        deadline = time.time() + 30
        while time.time() < deadline:
            if client.get_job_info("j-ev").status == "SUCCEEDED":
                break
            time.sleep(0.1)
        assert client.get_job_info("j-ev").status == "SUCCEEDED"
        # The payload posts structured step events (what train/launcher.py
        # emits each log interval).
        assert client.post_events([
            {"type": "step", "name": "train_step", "job_id": "j-ev",
             "ts": 10.0, "dur": 0.5, "args": {"step": 1, "loss": 2.0}},
            {"type": "profile", "name": "trace_captured",
             "job_id": "j-ev"},
        ]) == 2
        evs = client.get_events(job_id="j-ev")
        names = [e["name"] for e in evs]
        assert "job_started" in names and "job_finished" in names
        assert "train_step" in names

        # Archive (the head-side collector scrape), then kill everything.
        col = CoordinatorCollector(storage, url, cluster="evc")
        assert col.collect_once() >= 3
    finally:
        srv.shutdown()

    # Post-mortem: the history server replays the events with the
    # coordinator long gone.
    hsrv, hurl = HistoryServer(storage).serve_background()
    try:
        evs = json.load(urllib.request.urlopen(
            f"{hurl}/api/history/events/default/evc"))["events"]
        names = [e["name"] for e in evs]
        assert "train_step" in names and "job_finished" in names
        step = next(e for e in evs if e["name"] == "train_step")
        assert step["args"]["loss"] == 2.0
    finally:
        hsrv.shutdown()


@pytest.mark.timeout(60)
def test_timeline_includes_task_events(tmp_path):
    """The archived timeline renders step events as spans alongside the
    control-plane state rows."""
    from kuberay_tpu.utils.timeline import cluster_timeline

    doc = {"metadata": {"name": "c", "creationTimestamp": 1.0},
           "status": {"stateTransitionTimes": {"ready": 2.0}},
           "archivedAt": 50.0}
    tl = cluster_timeline(doc, task_events=[
        {"type": "step", "name": "train_step", "job_id": "j1",
         "ts": 3.0, "dur": 0.5, "args": {"step": 10}}])
    rows = [e for e in tl["traceEvents"] if e["cat"] == "step"]
    assert len(rows) == 1
    assert rows[0]["ph"] == "X" and rows[0]["dur"] == 500000
    assert rows[0]["tid"] == "tasks/j1"


@pytest.mark.timeout(60)
def test_event_archive_merges_across_coordinator_restart(tmp_path):
    """The archive must be durable through coordinator restarts: a fresh
    (empty-ring) coordinator's scrape appends nothing but also must not
    clobber previously archived events."""
    from kuberay_tpu.runtime.coordinator_client import CoordinatorClient
    from kuberay_tpu.runtime.coordinator_server import (
        CoordinatorServer,
        MemoryBackend,
    )

    storage = LocalStorage(str(tmp_path / "arch"))

    def boot():
        coord = CoordinatorServer(state=MemoryBackend(),
                                  log_dir=str(tmp_path / "logs"))
        return coord.serve_background()

    srv, url = boot()
    try:
        CoordinatorClient(url).post_events(
            [{"type": "step", "name": "before-restart", "ts": 1.0}])
        col = CoordinatorCollector(storage, url, cluster="mrg")
        col.collect_once()
    finally:
        srv.shutdown()

    srv, url = boot()                   # restart: empty ring
    try:
        col = CoordinatorCollector(storage, url, cluster="mrg")
        col.collect_once()              # must NOT clobber
        CoordinatorClient(url).post_events(
            [{"type": "step", "name": "after-restart", "ts": 2.0}])
        col.collect_once()
    finally:
        srv.shutdown()

    doc = storage.get_doc("meta/default/mrg/events.json")
    names = [e["name"] for e in doc["events"]]
    assert "before-restart" in names and "after-restart" in names
    # Repeated scrapes of the same ring do not duplicate.
    assert names.count("after-restart") == 1


@pytest.mark.timeout(60)
def test_job_log_tail_param(tmp_path):
    """?tail=N reads only the last N bytes (live-tail consumers poll)."""
    import sys
    import urllib.request as rq

    from kuberay_tpu.runtime.coordinator_client import CoordinatorClient
    from kuberay_tpu.runtime.coordinator_server import (
        CoordinatorServer,
        MemoryBackend,
    )

    coord = CoordinatorServer(state=MemoryBackend(),
                              log_dir=str(tmp_path / "logs"))
    srv, url = coord.serve_background()
    try:
        client = CoordinatorClient(url)
        client.submit_job(
            "j-tail",
            f"{sys.executable} -c \"print('x' * 100); print('END')\"")
        deadline = time.time() + 20
        while time.time() < deadline and \
                client.get_job_info("j-tail").status != "SUCCEEDED":
            time.sleep(0.1)
        full = json.load(rq.urlopen(f"{url}/api/jobs/j-tail/logs"))["logs"]
        assert "x" * 100 in full
        tail = json.load(rq.urlopen(
            f"{url}/api/jobs/j-tail/logs?tail=8"))["logs"]
        assert len(tail) <= 8 and "END" in tail
    finally:
        srv.shutdown()


# ---------------------------------------------------------------------------
# Azure Blob + Aliyun OSS wire protocols (ref pkg/storage/{azureblob,
# aliyunoss}): fakes re-derive the signatures with the shared secret.


def _strict_parse_qs(rawq: str) -> dict:
    """Strict PERCENT-decoding, exactly like real Azure: unquote()
    leaves '+' as a literal plus, so a client that quote_plus-encodes a
    space fails this fake the way it fails real Azure."""
    query = {}
    for part in rawq.split("&") if rawq else []:
        k, _, v = part.partition("=")
        query[urllib.parse.unquote(k)] = urllib.parse.unquote(v)
    return query


class _FakeAzure(BaseHTTPRequestHandler):
    objects = {}
    account, key_b64 = "acct", "c2VjcmV0LWtleQ=="     # b64("secret-key")

    def log_message(self, *a):
        pass

    def _verify(self, payload: bytes) -> bool:
        import base64
        auth = self.headers.get("Authorization", "")
        if not auth.startswith(f"SharedKey {self.account}:"):
            return False
        path, _, rawq = self.path.partition("?")
        query = _strict_parse_qs(rawq)
        canon_headers = "".join(
            f"{k.lower()}:{v}\n" for k, v in sorted(
                (k, v) for k, v in self.headers.items()
                if k.lower().startswith("x-ms-")))
        canon_resource = (f"/{self.account}{urllib.parse.unquote(path)}"
                          + "".join(f"\n{k}:{v}"
                                    for k, v in sorted(query.items())))
        content_length = str(len(payload)) if payload else ""
        # Content-Type participates in the signature exactly as sent on
        # the wire — the bug class this guards: an unsigned header that
        # urllib injects makes real Azure 403 every upload.
        content_type = self.headers.get("Content-Type", "") or ""
        sts = "\n".join([self.command, "", "", content_length, "",
                         content_type, "",
                         "", "", "", "", "", canon_headers + canon_resource])
        import hashlib as _h
        import hmac as _hm
        sig = base64.b64encode(_hm.new(
            base64.b64decode(self.key_b64), sts.encode(),
            _h.sha256).digest()).decode()
        return auth == f"SharedKey {self.account}:{sig}"

    def do_PUT(self):
        body = self.rfile.read(int(self.headers.get("Content-Length", 0)))
        if not self._verify(body):
            self.send_response(403), self.end_headers()
            return
        _FakeAzure.objects[urllib.parse.unquote(self.path)] = body
        self.send_response(201), self.end_headers()

    def do_GET(self):
        if not self._verify(b""):
            self.send_response(403), self.end_headers()
            return
        path, _, rawq = self.path.partition("?")
        q = _strict_parse_qs(rawq)
        if q.get("comp") == "list":
            container = path.strip("/")
            prefix = q.get("prefix", "")
            keys = sorted(k[len(container) + 2:]
                          for k in _FakeAzure.objects
                          if k.startswith(f"/{container}/")
                          and k[len(container) + 2:].startswith(prefix))
            xml_body = "".join(
                f"<Blob><Name>{xml_escape(k)}</Name></Blob>" for k in keys)
            body = (f"<EnumerationResults><Blobs>{xml_body}</Blobs>"
                    f"<NextMarker/></EnumerationResults>").encode()
            self.send_response(200), self.end_headers()
            self.wfile.write(body)
            return
        body = _FakeAzure.objects.get(urllib.parse.unquote(path))
        if body is None:
            self.send_response(404), self.end_headers()
            return
        self.send_response(200), self.end_headers()
        self.wfile.write(body)

    def do_DELETE(self):
        if not self._verify(b""):
            self.send_response(403), self.end_headers()
            return
        _FakeAzure.objects.pop(urllib.parse.unquote(self.path), None)
        self.send_response(202), self.end_headers()


def test_azure_blob_backend_wire_protocol():
    from kuberay_tpu.history.storage import AzureBlobStorage

    _FakeAzure.objects = {}
    srv = ThreadingHTTPServer(("127.0.0.1", 0), _FakeAzure)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        st = AzureBlobStorage("acct", "arch", account_key=_FakeAzure.key_b64,
                              endpoint=f"http://127.0.0.1:{srv.server_port}")
        st.put("meta/default/c1/doc.json", b'{"a": 1}')
        st.put("logs/default/c1/w0/t.log", b"line\n")
        assert st.get("meta/default/c1/doc.json") == b'{"a": 1}'
        assert st.get("missing") is None
        assert st.list("meta/") == ["meta/default/c1/doc.json"]
        st.delete("meta/default/c1/doc.json")
        assert st.get("meta/default/c1/doc.json") is None
        # Prefixes whose urlencoding rewrites characters (space, '+',
        # '#', unicode) must still sign correctly: the fake percent-
        # decodes strictly, so a quote_plus space would 403 here.
        st.put("dir with space/a+b/doc#1.json", b"x")
        assert st.list("dir with space/") == ["dir with space/a+b/doc#1.json"]
        assert st.list("dir with space/a+b/") == \
            ["dir with space/a+b/doc#1.json"]
        # Bad key -> server rejects the signature.
        bad = AzureBlobStorage("acct", "arch", account_key="d3Jvbmc=",
                               endpoint=f"http://127.0.0.1:{srv.server_port}")
        with pytest.raises(urllib.error.HTTPError):
            bad.put("x", b"y")
    finally:
        srv.shutdown()


class _FakeOSS(BaseHTTPRequestHandler):
    objects = {}
    key_id, secret = "OSSKEY", "OSSSECRET"

    def log_message(self, *a):
        pass

    def _verify(self) -> bool:
        import base64
        import hashlib as _h
        import hmac as _hm
        auth = self.headers.get("Authorization", "")
        path = urllib.parse.unquote(self.path.partition("?")[0])
        sts = "\n".join([self.command, "",
                         self.headers.get("Content-Type", "") or "",
                         self.headers.get("Date", ""), path])
        sig = base64.b64encode(_hm.new(
            self.secret.encode(), sts.encode(), _h.sha1).digest()).decode()
        return auth == f"OSS {self.key_id}:{sig}"

    def do_PUT(self):
        body = self.rfile.read(int(self.headers.get("Content-Length", 0)))
        if not self._verify():
            self.send_response(403), self.end_headers()
            return
        _FakeOSS.objects[urllib.parse.unquote(self.path)] = body
        self.send_response(200), self.end_headers()

    def do_GET(self):
        if not self._verify():
            self.send_response(403), self.end_headers()
            return
        path, _, rawq = self.path.partition("?")
        if rawq:                                   # list
            q = dict(urllib.parse.parse_qsl(rawq))
            bucket = path.strip("/")
            prefix = q.get("prefix", "")
            keys = sorted(k[len(bucket) + 2:]
                          for k in _FakeOSS.objects
                          if k.startswith(f"/{bucket}/")
                          and k[len(bucket) + 2:].startswith(prefix))
            xml_body = "".join(
                f"<Contents><Key>{xml_escape(k)}</Key></Contents>"
                for k in keys)
            body = (f"<ListBucketResult><IsTruncated>false</IsTruncated>"
                    f"{xml_body}</ListBucketResult>").encode()
            self.send_response(200), self.end_headers()
            self.wfile.write(body)
            return
        body = _FakeOSS.objects.get(urllib.parse.unquote(path))
        if body is None:
            self.send_response(404), self.end_headers()
            return
        self.send_response(200), self.end_headers()
        self.wfile.write(body)

    def do_DELETE(self):
        if not self._verify():
            self.send_response(403), self.end_headers()
            return
        _FakeOSS.objects.pop(urllib.parse.unquote(self.path), None)
        self.send_response(204), self.end_headers()


def test_aliyun_oss_backend_wire_protocol():
    from kuberay_tpu.history.storage import AliyunOSSStorage

    _FakeOSS.objects = {}
    srv = ThreadingHTTPServer(("127.0.0.1", 0), _FakeOSS)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        st = AliyunOSSStorage("arch", access_key_id="OSSKEY",
                              access_key_secret="OSSSECRET",
                              endpoint=f"http://127.0.0.1:{srv.server_port}",
                              path_style=True)
        st.put("meta/default/c1/doc.json", b'{"b": 2}')
        assert st.get("meta/default/c1/doc.json") == b'{"b": 2}'
        assert st.get("nope") is None
        assert st.list("meta/") == ["meta/default/c1/doc.json"]
        st.delete("meta/default/c1/doc.json")
        assert st.get("meta/default/c1/doc.json") is None
        bad = AliyunOSSStorage("arch", access_key_id="OSSKEY",
                               access_key_secret="WRONG",
                               endpoint=f"http://127.0.0.1:{srv.server_port}",
                               path_style=True)
        with pytest.raises(urllib.error.HTTPError):
            bad.put("x", b"y")
    finally:
        srv.shutdown()


def test_backend_from_url_new_schemes(monkeypatch):
    from kuberay_tpu.history.storage import (
        AliyunOSSStorage,
        AzureBlobStorage,
        backend_from_url,
    )

    monkeypatch.setenv("AZURE_STORAGE_KEY", "c2VjcmV0LWtleQ==")
    az = backend_from_url("azblob://cont?account=acct&endpoint=http://x:1")
    az = az.inner
    assert isinstance(az, AzureBlobStorage)
    assert az.container == "cont" and az.account == "acct"
    oss = backend_from_url("oss://bkt?endpoint=http://y:2").inner
    assert isinstance(oss, AliyunOSSStorage)
    assert oss.bucket == "bkt" and oss.endpoint == "http://y:2"
    # Virtual-host addressing by default (real OSS rejects path-style).
    assert oss._object_url("k").startswith("http://bkt.y:2/")
    assert backend_from_url(
        "oss://bkt?endpoint=http://y:2&path_style=1").inner.path_style
    # Missing Azure key fails fast, not as per-request 403s.
    monkeypatch.delenv("AZURE_STORAGE_KEY")
    with pytest.raises(ValueError, match="account key"):
        backend_from_url("azblob://cont?account=acct")


# ---------------------------------------------------------------------------
# Compression layer (ref historyserver/pkg/compression/compression.go)


def _compression_roundtrip(backend):
    """Shared contract: gzip at rest, transparent replay, raw-payload
    pass-through (mixed archives), doc helpers inherit the codec."""
    import gzip as _gzip

    from kuberay_tpu.history.storage import CompressedBackend

    cb = CompressedBackend(backend)
    payload = b"log line one\nlog line two\n" * 64
    cb.put("logs/default/c1/head/a.log", payload)
    # At rest: smaller and gzip-framed.
    raw = backend.get("logs/default/c1/head/a.log")
    assert raw.startswith(b"\x1f\x8b") and len(raw) < len(payload)
    assert _gzip.decompress(raw) == payload
    # Replay: transparent.
    assert cb.get("logs/default/c1/head/a.log") == payload
    # Pre-compression objects (written raw) read through unchanged.
    backend.put("logs/default/c1/head/old.log", b"plain old log\n")
    assert cb.get("logs/default/c1/head/old.log") == b"plain old log\n"
    # Docs go through the same codec.
    cb.put_doc("TpuCluster/default/c1.json", {"kind": "TpuCluster"})
    assert cb.get_doc("TpuCluster/default/c1.json") == {
        "kind": "TpuCluster"}
    assert backend.get(
        "TpuCluster/default/c1.json").startswith(b"\x1f\x8b")
    # list/delete delegate.
    assert "logs/default/c1/head/a.log" in cb.list("logs/")
    cb.delete("logs/default/c1/head/a.log")
    assert cb.get("logs/default/c1/head/a.log") is None


def test_compression_roundtrip_local(tmp_path):
    _compression_roundtrip(LocalStorage(str(tmp_path / "arch")))


def test_compression_roundtrip_s3():
    _FakeS3.objects = {}
    srv = ThreadingHTTPServer(("127.0.0.1", 0), _FakeS3)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        _compression_roundtrip(
            S3Storage(f"http://127.0.0.1:{srv.server_port}", "bkt",
                      access_key="AK", secret_key="SK"))
    finally:
        srv.shutdown()


def test_compression_roundtrip_gcs():
    _FakeGCS.objects = {}
    srv = ThreadingHTTPServer(("127.0.0.1", 0), _FakeGCS)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        _compression_roundtrip(
            GCSStorage("bkt", token="tok123",
                       endpoint=f"http://127.0.0.1:{srv.server_port}"))
    finally:
        srv.shutdown()


def test_compression_roundtrip_azure():
    from kuberay_tpu.history.storage import AzureBlobStorage

    _FakeAzure.objects = {}
    srv = ThreadingHTTPServer(("127.0.0.1", 0), _FakeAzure)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        _compression_roundtrip(
            AzureBlobStorage("acct", "arch",
                             account_key=_FakeAzure.key_b64,
                             endpoint=f"http://127.0.0.1:{srv.server_port}"))
    finally:
        srv.shutdown()


def test_compression_roundtrip_oss():
    from kuberay_tpu.history.storage import AliyunOSSStorage

    _FakeOSS.objects = {}
    srv = ThreadingHTTPServer(("127.0.0.1", 0), _FakeOSS)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        _compression_roundtrip(
            AliyunOSSStorage("arch", access_key_id="OSSKEY",
                             access_key_secret="OSSSECRET",
                             endpoint=f"http://127.0.0.1:{srv.server_port}",
                             path_style=True))
    finally:
        srv.shutdown()


# ---------------------------------------------------------------------------
# Retention


def test_prune_archive_by_last_collection(tmp_path):
    import time as _time

    from kuberay_tpu.history.storage import prune_archive

    b = LocalStorage(str(tmp_path / "arch"))
    now = _time.time()
    # Stale cluster: everything under it ages out, incl. its CR snapshot.
    b.put_doc("meta/default/old/archived_at.json", {"ts": now - 40 * 86400})
    b.put("meta/default/old/metadata.json", b"{}")
    b.put("logs/default/old/head/a.log", b"x")
    b.put_doc("TpuCluster/default/old.json", {"kind": "TpuCluster"})
    # Fresh cluster: untouched.
    b.put_doc("meta/default/new/archived_at.json", {"ts": now - 86400})
    b.put("logs/default/new/head/a.log", b"y")
    # Unstamped (pre-retention archive): kept — never guess at age.
    b.put("meta/default/legacy/metadata.json", b"{}")
    removed = prune_archive(b, 30 * 86400, now=now)
    assert removed == ["default/old"]
    assert b.list("meta/default/old/") == []
    assert b.list("logs/default/old/") == []
    assert b.get("TpuCluster/default/old.json") is None
    assert b.get("logs/default/new/head/a.log") == b"y"
    assert b.get("meta/default/legacy/metadata.json") == b"{}"
    # Idempotent.
    assert prune_archive(b, 30 * 86400, now=now) == []


def test_prune_removes_referencing_cr_snapshots(tmp_path):
    import time as _time

    from kuberay_tpu.history.storage import prune_archive

    b = LocalStorage(str(tmp_path / "arch"))
    now = _time.time()
    b.put_doc("meta/default/gone/archived_at.json",
              {"ts": now - 60 * 86400})
    b.put_doc("TpuJob/default/train-j1.json",
              {"kind": "TpuJob", "status": {"clusterName": "gone"}})
    b.put_doc("TpuJob/default/other-j.json",
              {"kind": "TpuJob", "status": {"clusterName": "alive"}})
    b.put_doc("TpuService/default/svc1.json",
              {"kind": "TpuService", "status": {
                  "activeServiceStatus": {"clusterName": "gone"}}})
    b.put_doc("TpuCronJob/default/cron1.json", {"kind": "TpuCronJob"})
    assert prune_archive(b, 30 * 86400, now=now) == ["default/gone"]
    assert b.get("TpuJob/default/train-j1.json") is None
    assert b.get("TpuService/default/svc1.json") is None
    assert b.get("TpuJob/default/other-j.json") is not None
    assert b.get("TpuCronJob/default/cron1.json") is not None


def test_compress_none_still_reads_compressed_archive(tmp_path):
    """The knob can never strand data: write compressed, reopen with
    ?compress=none, replay still works; new writes land raw."""
    url = f"file://{tmp_path}/arch"
    backend_from_url(url).put("logs/default/c/x.log", b"payload " * 50)
    reopened = backend_from_url(url + "?compress=none")
    assert reopened.get("logs/default/c/x.log") == b"payload " * 50
    reopened.put("logs/default/c/raw.log", b"raw bytes")
    at_rest = LocalStorage(str(tmp_path / "arch")).get(
        "logs/default/c/raw.log")
    assert at_rest == b"raw bytes"          # not gzip-framed


def test_magic_collision_passthrough(tmp_path):
    """A raw object that BEGINS with the gzip magic but is not a valid
    stream (truncated .log.gz from before compression existed) must
    pass through, not 500."""
    from kuberay_tpu.history.storage import CompressedBackend

    inner = LocalStorage(str(tmp_path / "arch"))
    truncated = b"\x1f\x8b\x08\x00broken-not-really-gzip"
    inner.put("logs/default/c/old.log.gz", truncated)
    cb = CompressedBackend(inner)
    assert cb.get("logs/default/c/old.log.gz") == truncated


def test_log_only_collection_stamps_retention(tmp_path):
    """collect --log-dir without --coordinator must still stamp
    archived_at so retention can age the archive (main-loop stamp)."""
    import os as _os

    from kuberay_tpu.history.__main__ import main as history_main

    logdir = tmp_path / "logs"
    logdir.mkdir()
    (logdir / "a.log").write_bytes(b"x")
    rc = history_main(["collect", "--storage",
                       f"file://{tmp_path}/arch?compress=none",
                       "--cluster", "lonely", "--log-dir", str(logdir),
                       "--once"])
    assert rc == 0
    b = LocalStorage(str(tmp_path / "arch"))
    doc = b.get_doc("meta/default/lonely/archived_at.json")
    assert doc and doc["ts"] > 0


def test_collector_stamps_archived_at(tmp_path):
    """The coordinator collector writes the retention stamp every pass
    even when the coordinator is unreachable (stamp precedes scrape)."""
    from kuberay_tpu.history.collector import CoordinatorCollector

    b = LocalStorage(str(tmp_path / "arch"))
    col = CoordinatorCollector(b, "http://127.0.0.1:1", cluster="c1")
    col.collect_once()
    doc = b.get_doc("meta/default/c1/archived_at.json")
    assert doc and doc["ts"] > 0


def test_prune_cli(tmp_path):
    import time as _time

    from kuberay_tpu.history.__main__ import main as history_main

    b = LocalStorage(str(tmp_path / "arch"))
    b.put_doc("meta/default/dead/archived_at.json",
              {"ts": _time.time() - 90 * 86400})
    b.put("logs/default/dead/head/x.log", b"x")
    rc = history_main(["prune", "--storage",
                       f"file://{tmp_path}/arch?compress=none",
                       "--max-age-days", "30"])
    assert rc == 0
    assert b.list("logs/default/dead/") == []
