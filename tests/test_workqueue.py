"""WorkQueue contract tests: controller-runtime dedup/per-key-serialize
semantics plus the concurrency stress gate (ISSUE 5: no key on two
workers, nothing lost) and the deterministic single-thread ordering the
chaos-sim replay hash depends on."""

import random
import threading
import time
from collections import defaultdict

from kuberay_tpu.controlplane.workqueue import WorkQueue


def k(name):
    return ("TpuCluster", "default", name)


# ---------------------------------------------------------------------------
# semantics
# ---------------------------------------------------------------------------

def test_fifo_and_dedup():
    wq = WorkQueue()
    wq.add(k("a"))
    wq.add(k("b"))
    wq.add(k("a"))          # dedup: still one 'a', in first position
    assert wq.get(block=False) == k("a")
    wq.done(k("a"))
    assert wq.get(block=False) == k("b")
    wq.done(k("b"))
    assert wq.get(block=False) is None


def test_readd_while_queued_keeps_position():
    """Re-adding a waiting key neither duplicates nor moves it — the
    old dedup-queue ordering the sim replay hashes were recorded with."""
    wq = WorkQueue()
    wq.add(k("a"))
    wq.add(k("b"))
    wq.add(k("a"))
    order = []
    while True:
        key = wq.get(block=False)
        if key is None:
            break
        order.append(key)
        wq.done(key)
    assert order == [k("a"), k("b")]


def test_in_flight_key_never_handed_out_twice():
    """The per-key serialization core: a popped key still processing
    parks dirty and re-queues on done — it is never given to a second
    worker and never lost."""
    wq = WorkQueue()
    wq.add(k("hot"))
    assert wq.get(block=False) == k("hot")      # worker 1 holds it
    wq.add(k("hot"))                            # event during reconcile
    wq.add(k("other"))
    # Worker 2 asks: must get 'other', never the in-flight 'hot'.
    assert wq.get(block=False) == k("other")
    assert wq.get(block=False) is None
    wq.done(k("other"))
    wq.done(k("hot"))                           # worker 1 finishes
    # The coalesced re-add surfaces now.
    assert wq.get(block=False) == k("hot")
    wq.done(k("hot"))
    assert wq.get(block=False) is None


def test_add_after_promotes_on_clock():
    now = [100.0]
    wq = WorkQueue(now_fn=lambda: now[0])
    wq.add_after(k("later"), 5.0)
    assert wq.get(block=False) is None
    assert wq.next_delayed_at() == 105.0
    now[0] = 105.0
    assert wq.get(block=False) == k("later")
    wq.done(k("later"))


def test_add_after_equal_deadlines_pop_in_key_order():
    """(deadline, key) heap entries on purpose: same-instant requeues
    (ubiquitous under the sim's virtual clock) promote in key order —
    the deterministic tiebreak the replay contract was recorded with."""
    now = [0.0]
    wq = WorkQueue(now_fn=lambda: now[0])
    for name in ("zeta", "alpha", "mid"):
        wq.add_after(k(name), 1.0)
    now[0] = 1.0
    got = [wq.get(block=False) for _ in range(3)]
    assert got == [k("alpha"), k("mid"), k("zeta")]


def test_flush_delayed():
    now = [0.0]
    wq = WorkQueue(now_fn=lambda: now[0])
    wq.add_after(k("x"), 60.0)
    wq.add_after(k("y"), 90.0)
    assert wq.get(block=False) is None
    wq.flush_delayed()
    assert {wq.get(block=False), wq.get(block=False)} == {k("x"), k("y")}


def test_shutdown_unblocks_getters():
    wq = WorkQueue()
    results = []

    def getter():
        results.append(wq.get(block=True))

    t = threading.Thread(target=getter)
    t.start()
    time.sleep(0.05)
    wq.shutdown()
    t.join(timeout=2.0)
    assert not t.is_alive()
    assert results == [None]


def test_depth_and_latency_metrics():
    class FakeMetrics:
        def __init__(self):
            self.depths = []
            self.latencies = []

        def workqueue_depth(self, queue, depth):
            self.depths.append((queue, depth))

        def workqueue_latency(self, queue, seconds):
            self.latencies.append((queue, seconds))

    now = [10.0]
    m = FakeMetrics()
    wq = WorkQueue(now_fn=lambda: now[0], metrics=m, name="bench")
    wq.add(k("a"))
    now[0] = 10.25
    assert wq.get(block=False) == k("a")
    assert ("bench", 1) in m.depths and ("bench", 0) in m.depths
    assert m.latencies == [("bench", 0.25)]


# ---------------------------------------------------------------------------
# pause / drain (per-shard lease handoff, ISSUE 6)
# ---------------------------------------------------------------------------

def test_pause_parks_keys_and_resume_releases_them():
    wq = WorkQueue()
    wq.add(k("a"))
    wq.pause()
    wq.add(k("b"))                      # accumulates (and dedups) parked
    wq.add(k("b"))
    assert wq.get(block=False) is None  # nothing handed out while paused
    assert wq.depth() == 2              # nothing lost either
    wq.resume()
    assert wq.get(block=False) == k("a")
    wq.done(k("a"))
    assert wq.get(block=False) == k("b")
    wq.done(k("b"))
    assert wq.get(block=False) is None


def test_resume_wakes_blocked_getter():
    wq = WorkQueue()
    wq.pause()
    wq.add(k("x"))
    results = []
    t = threading.Thread(target=lambda: results.append(wq.get(block=True)))
    t.start()
    time.sleep(0.05)
    assert not results                  # parked behind the pause
    wq.resume()
    t.join(timeout=2.0)
    assert results == [k("x")]
    wq.done(k("x"))


def test_wait_idle_processing_is_the_drain_barrier():
    wq = WorkQueue()
    wq.add(k("inflight"))
    assert wq.get(block=False) == k("inflight")
    wq.pause()
    # In flight: the barrier must block (short timeout -> False).
    assert wq.wait_idle_processing(timeout=0.1) is False
    done = []
    t = threading.Thread(
        target=lambda: done.append(wq.wait_idle_processing(timeout=5.0)))
    t.start()
    time.sleep(0.05)
    wq.done(k("inflight"))              # worker finishes
    t.join(timeout=2.0)
    assert done == [True]
    # Paused + drained: a dirty re-add parked during flight stays parked.
    assert wq.get(block=False) is None


# ---------------------------------------------------------------------------
# concurrency stress (tier-1 gate: ISSUE 5 acceptance)
# ---------------------------------------------------------------------------

def test_stress_no_concurrent_same_key_and_nothing_lost():
    """N workers x hot-key churn: a per-key in-flight counter proves no
    key is ever reconciled on two workers at once, and a per-key add
    generation proves every key's LAST add is followed by a pass (no
    event is lost to the coalescing)."""
    wq = WorkQueue()
    hot = [k(f"hot-{i}") for i in range(6)]
    adds = defaultdict(int)
    seen = defaultdict(int)
    inflight = defaultdict(int)
    processed = defaultdict(int)
    violations = []
    state_lock = threading.Lock()
    producers_done = threading.Event()

    def producer(seed):
        rng = random.Random(seed)
        for _ in range(400):
            key = rng.choice(hot)
            with state_lock:
                adds[key] += 1
            wq.add(key)
            if rng.random() < 0.05:
                time.sleep(0.0005)

    def worker():
        while True:
            key = wq.get(block=True)
            if key is None:
                return
            with state_lock:
                inflight[key] += 1
                if inflight[key] > 1:
                    violations.append(key)
                gen = adds[key]
            time.sleep(0.0002)      # widen the race window
            with state_lock:
                seen[key] = max(seen[key], gen)
                processed[key] += 1
                inflight[key] -= 1
            wq.done(key)

    workers = [threading.Thread(target=worker) for _ in range(4)]
    producers = [threading.Thread(target=producer, args=(s,))
                 for s in range(4)]
    for t in workers + producers:
        t.start()
    for t in producers:
        t.join(timeout=30.0)
    producers_done.set()
    # Drain to quiescence, then release the workers.
    deadline = time.time() + 30.0
    while time.time() < deadline:
        with wq._lock:
            idle = not wq._queue and not wq._processing and not wq._dirty
        if idle:
            break
        time.sleep(0.005)
    wq.shutdown()
    for t in workers:
        t.join(timeout=10.0)

    assert not violations, f"keys reconciled concurrently: {set(violations)}"
    for key in hot:
        assert processed[key] >= 1, f"{key} never processed"
        # Nothing lost: a pass started at (or after) the final add.
        assert seen[key] == adds[key], \
            f"{key}: last pass saw generation {seen[key]} of {adds[key]}"
    # All coalesced passes accounted: far fewer passes than adds is the
    # point (dedup), but at least one per key per quiet period happened.
    assert sum(processed.values()) <= sum(adds.values())
