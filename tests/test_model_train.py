"""Llama forward/training: correctness on CPU, sharded step on 8-dev mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kuberay_tpu.models import llama
from kuberay_tpu.parallel.mesh import MeshSpec
from kuberay_tpu.train.train_step import (
    TrainConfig,
    init_train_state,
    make_optimizer,
    make_sharded_train_fns,
    make_train_step,
)

CFG = llama.CONFIGS["llama_tiny"]


def make_batch(key, batch=2, seq=16, vocab=CFG.vocab_size):
    tokens = jax.random.randint(key, (batch, seq), 0, vocab)
    targets = jnp.roll(tokens, -1, axis=1)
    return {"tokens": tokens, "targets": targets}


def test_param_count_formula():
    params = llama.init_params(CFG, jax.random.PRNGKey(0))
    actual = sum(x.size for x in jax.tree.leaves(params))
    assert actual == CFG.num_params()


def test_forward_shapes_and_finite():
    params = llama.init_params(CFG, jax.random.PRNGKey(0))
    batch = make_batch(jax.random.PRNGKey(1))
    logits = llama.forward(CFG, params, batch["tokens"])
    assert logits.shape == (2, 16, CFG.vocab_size)
    assert logits.dtype == jnp.float32
    assert bool(jnp.isfinite(logits).all())


def test_causality():
    """Changing a future token must not change past logits."""
    params = llama.init_params(CFG, jax.random.PRNGKey(0))
    batch = make_batch(jax.random.PRNGKey(1))
    logits1 = llama.forward(CFG, params, batch["tokens"])
    perturbed = batch["tokens"].at[:, -1].set(0)
    logits2 = llama.forward(CFG, params, perturbed)
    np.testing.assert_allclose(logits1[:, :-1], logits2[:, :-1],
                               rtol=1e-4, atol=1e-5)


def test_loss_decreases_on_overfit():
    tc = TrainConfig(learning_rate=1e-2, warmup_steps=2, decay_steps=50,
                     z_loss=0.0)
    optimizer = make_optimizer(tc)
    state = init_train_state(CFG, optimizer, jax.random.PRNGKey(0))
    step = make_train_step(CFG, tc, optimizer)
    batch = make_batch(jax.random.PRNGKey(1))
    first = None
    for _ in range(20):
        state, metrics = step(state, batch)
        if first is None:
            first = float(metrics["loss"])
    assert float(metrics["loss"]) < first * 0.7, (first, float(metrics["loss"]))
    assert int(state["step"]) == 20


def test_sharded_train_step_8dev():
    """Full sharded train step over a dp=2 x fsdp=2 x tp=2 mesh."""
    assert len(jax.devices()) >= 8, "conftest must provide 8 virtual devices"
    mesh = MeshSpec(dp=2, fsdp=2, tp=2, sp=1, ep=1).build(jax.devices()[:8])
    tc = TrainConfig(warmup_steps=2, decay_steps=10)
    init, step, sh = make_sharded_train_fns(CFG, tc, mesh)
    state = init(jax.random.PRNGKey(0))
    # Params actually sharded: wq [L, d, heads*hd] split over fsdp and tp.
    wq = state["params"]["layers"]["wq"]
    assert wq.sharding.spec == jax.sharding.PartitionSpec(None, "fsdp", "tp")
    batch = make_batch(jax.random.PRNGKey(1), batch=4)
    state, metrics = step(state, batch)
    assert bool(jnp.isfinite(metrics["total_loss"]))
    state, metrics2 = step(state, make_batch(jax.random.PRNGKey(2), batch=4))
    assert int(state["step"]) == 2


def test_sharded_matches_unsharded():
    """Same seed, same batch: sharded and single-device losses agree."""
    tc = TrainConfig(warmup_steps=2, decay_steps=10)
    optimizer = make_optimizer(tc)
    batch = make_batch(jax.random.PRNGKey(7), batch=4)

    state = init_train_state(CFG, optimizer, jax.random.PRNGKey(0))
    _, m_single = make_train_step(CFG, tc, optimizer)(state, batch)

    mesh = MeshSpec(dp=2, fsdp=2, tp=2).build(jax.devices()[:8])
    init, step, _ = make_sharded_train_fns(CFG, tc, mesh)
    _, m_sharded = step(init(jax.random.PRNGKey(0)), batch)
    np.testing.assert_allclose(float(m_single["loss"]),
                               float(m_sharded["loss"]), rtol=1e-4)


def test_mixed_precision_master_weights():
    """fp32 master weights + bf16 compute + bf16 Adam mu: dtypes land
    where the knobs say, and training still converges."""
    import dataclasses as dc
    import jax.numpy as jnp
    bf16_cfg = dc.replace(CFG, dtype=jnp.bfloat16)
    tc = TrainConfig(learning_rate=1e-2, warmup_steps=2, decay_steps=50,
                     z_loss=0.0, param_dtype="float32", mu_dtype="bfloat16")
    optimizer = make_optimizer(tc)
    state = init_train_state(bf16_cfg, optimizer, jax.random.PRNGKey(0),
                             param_dtype=tc.param_dtype)
    # Masters are fp32 even though the model computes in bf16.
    assert all(p.dtype == jnp.float32
               for p in jax.tree.leaves(state["params"]))
    mus = [l for l in jax.tree.leaves(state["opt_state"])
           if hasattr(l, "dtype") and l.dtype == jnp.bfloat16]
    assert mus, "adam mu should be bfloat16"
    step = make_train_step(bf16_cfg, tc, optimizer)
    batch = make_batch(jax.random.PRNGKey(1))
    first = None
    for _ in range(20):
        state, metrics = step(state, batch)
        if first is None:
            first = float(metrics["loss"])
    assert float(metrics["loss"]) < first * 0.8
    # Updated masters stay fp32 (grads came back in master dtype).
    assert all(p.dtype == jnp.float32
               for p in jax.tree.leaves(state["params"]))


def test_mixed_precision_sharded_8dev():
    """The sharded path honors param_dtype/mu_dtype too."""
    import dataclasses as dc
    import jax.numpy as jnp
    bf16_cfg = dc.replace(CFG, dtype=jnp.bfloat16)
    tc = TrainConfig(warmup_steps=2, decay_steps=50,
                     param_dtype="float32", mu_dtype="bfloat16")
    mesh = MeshSpec(dp=2, fsdp=2, tp=2, sp=1, ep=1).build(jax.devices()[:8])
    init, step, _ = make_sharded_train_fns(bf16_cfg, tc, mesh)
    state = init(jax.random.PRNGKey(0))
    assert all(p.dtype == jnp.float32
               for p in jax.tree.leaves(state["params"]))
    toks = jax.random.randint(jax.random.PRNGKey(1), (8, 16), 0,
                              CFG.vocab_size)
    batch = {"tokens": toks, "targets": jnp.roll(toks, -1, axis=1)}
    state, metrics = step(state, batch)
    assert np.isfinite(float(metrics["total_loss"]))


def test_grad_accumulation_matches_full_batch():
    """grad_accum=A (A microbatch fwd+bwd, one optimizer update) must
    reproduce the full-batch step numerically (mean-loss gradients;
    llama_tiny is f32, so tolerances are tight)."""
    import jax
    import jax.numpy as jnp

    from kuberay_tpu.models import llama
    from kuberay_tpu.train.train_step import (
        TrainConfig,
        init_train_state,
        make_optimizer,
        make_train_step,
    )

    cfg = llama.CONFIGS["llama_tiny"]
    toks = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0,
                              cfg.vocab_size)
    batch = {"tokens": toks, "targets": jnp.roll(toks, -1, axis=1)}

    outs = {}
    for accum in (1, 2, 4):
        tc = TrainConfig(warmup_steps=1, decay_steps=10, grad_accum=accum)
        opt = make_optimizer(tc)
        state = init_train_state(cfg, opt, jax.random.PRNGKey(0))
        state, m = make_train_step(cfg, tc, opt)(state, batch)
        outs[accum] = (float(m["total_loss"]), state["params"])

    for accum in (2, 4):
        assert abs(outs[accum][0] - outs[1][0]) < 1e-5
        diffs = jax.tree.map(
            lambda a, b: float(jnp.max(jnp.abs(a - b))),
            outs[accum][1], outs[1][1])
        assert max(jax.tree.leaves(diffs)) < 1e-4, diffs


def test_grad_accumulation_sharded(monkeypatch):
    """Accumulation under the sharded step on the virtual mesh."""
    import jax
    import jax.numpy as jnp

    from kuberay_tpu.models import llama
    from kuberay_tpu.parallel.mesh import MeshSpec
    from kuberay_tpu.train.train_step import (
        TrainConfig,
        make_sharded_train_fns,
    )

    cfg = llama.CONFIGS["llama_tiny"]
    mesh = MeshSpec(dp=2, fsdp=2, tp=2).build()
    tc = TrainConfig(warmup_steps=1, decay_steps=10, grad_accum=2)
    init, step, _ = make_sharded_train_fns(cfg, tc, mesh)
    state = init(jax.random.PRNGKey(0))
    toks = jax.random.randint(jax.random.PRNGKey(1), (8, 16), 0,
                              cfg.vocab_size)
    state, m = step(state, {"tokens": toks,
                            "targets": jnp.roll(toks, -1, axis=1)})
    assert bool(jnp.isfinite(jnp.asarray(m["total_loss"])))


def test_grad_accumulation_masked_matches_full_batch():
    """With a mask, accumulation must reproduce the full-batch MASKED
    mean — microbatches weight by their real-token counts, not equally."""
    import jax
    import jax.numpy as jnp

    from kuberay_tpu.models import llama
    from kuberay_tpu.train.train_step import (
        TrainConfig,
        init_train_state,
        make_optimizer,
        make_train_step,
    )

    cfg = llama.CONFIGS["llama_tiny"]
    toks = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0,
                              cfg.vocab_size)
    # Pathologically skewed: row 0 nearly empty, rows 2-3 full — equal
    # microbatch weighting would be ~8x off for row 0's tokens.
    mask = jnp.ones((4, 16)).at[0, 2:].set(0.0).at[1, 8:].set(0.0)
    batch = {"tokens": toks, "targets": jnp.roll(toks, -1, axis=1),
             "mask": mask}

    outs = {}
    for accum in (1, 2):
        tc = TrainConfig(warmup_steps=1, decay_steps=10, grad_accum=accum)
        opt = make_optimizer(tc)
        state = init_train_state(cfg, opt, jax.random.PRNGKey(0))
        state, m = make_train_step(cfg, tc, opt)(state, batch)
        outs[accum] = (float(m["total_loss"]), state["params"])

    assert abs(outs[2][0] - outs[1][0]) < 1e-5
    diffs = jax.tree.map(lambda a, b: float(jnp.max(jnp.abs(a - b))),
                         outs[2][1], outs[1][1])
    assert max(jax.tree.leaves(diffs)) < 1e-4, diffs
