"""Training-step telemetry unit tests (ISSUE 11 tentpole).

The ``StepTracker`` contract, exercised without the sim or the
coordinator: windowed per-host distributions, cross-host skew, the
K-consecutive straggler verdict (backdated to the first slow step,
cleared on recovery), MFU from the heartbeat model config, the
``tpu_train_*`` metric fan-out with exemplars, flight-ring straggler
records, goodput ``stalled-on-straggler`` sub-attribution, and the
bounded-everywhere guarantees (LRU jobs/hosts, malformed-beat guards,
the Noop surface the benchmark swaps in).
"""

from __future__ import annotations

import pytest

from kuberay_tpu.obs import (FlightRecorder, GoodputLedger, NOOP_STEPS,
                             NoopStepTracker, StepTracker)
from kuberay_tpu.obs.goodput import PHASE_PRODUCTIVE, PHASE_STALLED, PHASES
from kuberay_tpu.obs.steps import default_goodput_key
from kuberay_tpu.utils.metrics import ControlPlaneMetrics


class FakeClock:
    def __init__(self, t=100.0):
        self.t = t

    def now(self):
        return self.t

    def advance(self, dt):
        self.t += dt
        return self.t


def _feed(tr, clock, job, hosts, dur_by_host, step, **kw):
    """One synchronous step: every host reports, clock ticks once."""
    clock.advance(max(dur_by_host.values()))
    for h in hosts:
        tr.observe(job, h, step=step, dur_s=dur_by_host[h],
                   tokens=kw.get("tokens", 1000.0),
                   collective_wait_s=max(dur_by_host.values())
                   - dur_by_host[h],
                   ts=clock.now(), **{k: v for k, v in kw.items()
                                      if k != "tokens"})


# ---------------------------------------------------------------------------
# distributions + skew
# ---------------------------------------------------------------------------

def test_windowed_distributions_and_skew():
    clock = FakeClock()
    tr = StepTracker(clock=clock, window=8)
    hosts = ["s0w0", "s0w1"]
    for i in range(1, 13):
        _feed(tr, clock, "default/train", hosts,
              {"s0w0": 1.0, "s0w1": 2.0}, step=i)
    doc = tr.job_doc("default/train")
    assert doc is not None
    by = {h["host"]: h for h in doc["hosts"]}
    # Window is bounded at 8 even after 12 observations.
    assert by["s0w0"]["window"] == 8
    assert by["s0w0"]["steps_observed"] == 12
    assert by["s0w0"]["p50_s"] == pytest.approx(1.0)
    assert by["s0w1"]["p50_s"] == pytest.approx(2.0)
    assert by["s0w0"]["mean_s"] == pytest.approx(1.0)
    # Fleet median = median of per-host medians = median([1, 2]) = 1.5;
    # skew is each host's median over that.
    assert doc["fleet_median_s"] == pytest.approx(1.5)
    assert by["s0w1"]["skew_ratio"] == pytest.approx(2.0 / 1.5, abs=1e-3)
    # tokens/s = windowed-median tokens over windowed-median duration.
    assert by["s0w0"]["tokens_per_sec"] == pytest.approx(1000.0)
    assert by["s0w1"]["tokens_per_sec"] == pytest.approx(500.0)
    # The fast host waits for the slow one: collective wait == wall - dur.
    assert by["s0w0"]["collective_wait_p50_s"] == pytest.approx(1.0)
    assert by["s0w1"]["collective_wait_p50_s"] == pytest.approx(0.0)
    # Index doc rolls up the same story.
    row = tr.to_dict()["jobs"][0]
    assert row["job"] == "default/train"
    assert row["hosts"] == 2 and row["last_step"] == 12
    assert row["max_skew_ratio"] == pytest.approx(2.0 / 1.5, abs=1e-3)


# ---------------------------------------------------------------------------
# the straggler verdict
# ---------------------------------------------------------------------------

def test_k_consecutive_verdict_backdated_and_cleared():
    clock = FakeClock()
    tr = StepTracker(clock=clock, straggler_ratio=1.5, straggler_steps=5)
    hosts = ["a", "b", "c", "d"]
    even = {h: 1.0 for h in hosts}
    slow = dict(even, d=3.0)
    for i in range(1, 7):                       # warm up the windows
        _feed(tr, clock, "j", hosts, even, step=i)
    assert tr.stragglers() == []
    first_slow_ts = None
    for i in range(7, 12):                      # 5 consecutive slow steps
        _feed(tr, clock, "j", hosts, slow, step=i)
        if first_slow_ts is None:
            first_slow_ts = clock.now()
        if i < 11:
            assert tr.stragglers("j") == []     # K not yet reached
    vs = tr.stragglers("j")
    assert len(vs) == 1
    v = vs[0]
    assert v["host"] == "d" and v["job"] == "j"
    # Backdated: the verdict points at the FIRST slow step, not the
    # step where the evidence finished accumulating.
    assert v["first_slow_step"] == 7
    assert v["first_slow_ts"] == pytest.approx(first_slow_ts)
    assert v["detected_step"] == 11
    assert v["detected_step"] - v["first_slow_step"] + 1 == 5
    assert v["skew"] == pytest.approx(3.0, abs=0.1)
    assert v["cleared_step"] is None
    doc = tr.job_doc("j")
    d_row = next(h for h in doc["hosts"] if h["host"] == "d")
    assert d_row["straggler"] and d_row["consecutive_slow"] == 5
    # Recovery: first step back under the ratio clears the verdict.
    _feed(tr, clock, "j", hosts, even, step=12)
    v = tr.stragglers("j")[0]
    assert v["cleared_step"] == 12 and v["cleared_ts"] is not None
    assert not tr.job_doc("j")["hosts"][-1]["straggler"]


def test_blip_under_k_steps_never_flags():
    clock = FakeClock()
    tr = StepTracker(clock=clock, straggler_steps=5)
    hosts = ["a", "b"]
    for i in range(1, 5):
        _feed(tr, clock, "j", hosts, {"a": 1.0, "b": 1.0}, step=i)
    for i in range(5, 9):                       # 4 slow steps: one short
        _feed(tr, clock, "j", hosts, {"a": 1.0, "b": 4.0}, step=i)
    _feed(tr, clock, "j", hosts, {"a": 1.0, "b": 1.0}, step=9)
    for i in range(10, 14):                     # counter reset: 4 again
        _feed(tr, clock, "j", hosts, {"a": 1.0, "b": 4.0}, step=i)
    assert tr.stragglers("j") == []


def test_single_host_job_never_flags():
    clock = FakeClock()
    tr = StepTracker(clock=clock)
    for i in range(1, 30):
        # Wildly varying step times, but no fleet to skew against.
        tr.observe("solo", "s0w0", step=i, dur_s=1.0 + (i % 7),
                   ts=clock.advance(1.0))
    assert tr.stragglers("solo") == []
    assert tr.to_dict()["jobs"][0]["stragglers"] == []


# ---------------------------------------------------------------------------
# MFU
# ---------------------------------------------------------------------------

def test_mfu_formula_from_heartbeat_model_config():
    clock = FakeClock()
    tr = StepTracker(clock=clock)
    hosts = ["a", "b"]
    # No model config yet -> no MFU.
    _feed(tr, clock, "j", hosts, {"a": 1.0, "b": 1.0}, step=1,
          tokens=2048.0)
    assert tr.job_doc("j")["mfu"] is None
    for i in range(2, 6):
        _feed(tr, clock, "j", hosts, {"a": 1.0, "b": 1.0}, step=i,
              tokens=2048.0, n_params=1.0e9, device_count=8,
              peak_tflops=197.0)
    # fleet tokens/s = 2 hosts x 2048 tok / 1.0 s; MFU =
    # 6*N*tok_s / 1e12 / devices / peak.
    expected = 6.0 * 1.0e9 * (2 * 2048.0) / 1e12 / 8 / 197.0
    assert tr.job_doc("j")["mfu"] == pytest.approx(expected, rel=1e-6)
    assert tr.to_dict()["jobs"][0]["mfu"] == pytest.approx(expected,
                                                           rel=1e-6)


# ---------------------------------------------------------------------------
# fan-out: metrics + flight + goodput
# ---------------------------------------------------------------------------

def test_fanout_metrics_flight_and_goodput_stall_edges():
    clock = FakeClock()
    metrics = ControlPlaneMetrics()
    flight = FlightRecorder()
    goodput = GoodputLedger(clock=clock)
    tr = StepTracker(clock=clock, metrics=metrics, flight=flight,
                     goodput=goodput, straggler_steps=3)
    kind, ns, name = default_goodput_key("j1")
    assert (kind, ns, name) == ("CoordinatorJob", "head", "j1")
    goodput.transition(kind, ns, name, PHASE_PRODUCTIVE)

    hosts = ["a", "b"]
    for i in range(1, 4):
        _feed(tr, clock, "j1", hosts, {"a": 1.0, "b": 1.0}, step=i,
              exemplar=f"ev-{i}")
    t_slow_start = None
    for i in range(4, 7):                       # 3 slow -> flagged
        _feed(tr, clock, "j1", hosts, {"a": 1.0, "b": 3.0}, step=i)
        if t_slow_start is None:
            t_slow_start = clock.now()
    _feed(tr, clock, "j1", hosts, {"a": 1.0, "b": 1.0}, step=7)
    t_clear = clock.now()
    # The stalled interval spans [first slow heartbeat, clearing
    # heartbeat] — the recovery step's wall time still ran at the
    # fleet's pace, so it closes the window, not the last slow beat.
    stall_window = t_clear - t_slow_start
    clock.advance(5.0)

    # Metrics: histogram + skew gauge + straggler counter, with the
    # goodput-key labels the alert engine deep-links through.
    text = metrics.render()
    assert 'tpu_train_step_duration_seconds_bucket' in text
    # Exemplar survived (latest observation per bucket wins).
    assert 'trace_id="ev-3"' in text
    assert ('tpu_train_step_skew_ratio{host="b",job="j1",'
            'kind="CoordinatorJob",name="j1",namespace="head"}') in text
    assert 'tpu_train_stragglers_total{job="j1"} 1' in text

    # Flight ring: one flagged record, one recovered record.
    recs = [r for r in flight.timeline(kind, ns, name)
            if r["type"] == "straggler"]
    assert [r["edge"] for r in recs] == ["flagged", "cleared"]
    assert all(r["host"] == "b" for r in recs)
    assert "3 steps" in recs[0]["detail"]
    assert "recovered at step 7" in recs[1]["detail"]

    # Goodput: PRODUCTIVE split by a backdated stalled-on-straggler
    # interval covering exactly the slow window, partition intact.
    roll = goodput.rollup(kind, ns, name)
    assert set(roll["phases"]) == set(PHASES)
    assert sum(roll["phases"].values()) == pytest.approx(roll["total"],
                                                         abs=1e-6)
    assert roll["phases"][PHASE_STALLED] == pytest.approx(stall_window,
                                                          abs=1e-6)
    seq = [iv["phase"] for iv in goodput.intervals(kind, ns, name)]
    assert seq == [PHASE_PRODUCTIVE, PHASE_STALLED, PHASE_PRODUCTIVE]
    ivs = goodput.intervals(kind, ns, name)
    assert ivs[1]["start"] == pytest.approx(t_slow_start)
    assert ivs[1]["end"] == pytest.approx(t_clear)
    assert roll["current_phase"] == PHASE_PRODUCTIVE


# ---------------------------------------------------------------------------
# bounds + guards + the Noop surface
# ---------------------------------------------------------------------------

def test_malformed_beats_ignored():
    tr = StepTracker()
    tr.observe("", "h", step=1, dur_s=1.0)
    tr.observe("j", "", step=1, dur_s=1.0)
    tr.observe("j", "h", step=1, dur_s=-0.5)
    assert tr.jobs() == [] and tr.to_dict() == {"jobs": []}
    assert tr.job_doc("j") is None


def test_lru_bounds_jobs_and_hosts():
    clock = FakeClock()
    tr = StepTracker(clock=clock, max_jobs=4, max_hosts=8)
    for j in range(10):
        for h in range(20):
            tr.observe(f"job-{j}", f"h-{h}", step=1, dur_s=1.0,
                       ts=clock.now())
    jobs = tr.jobs()
    assert len(jobs) == 4
    assert jobs == [f"job-{j}" for j in range(6, 10)]   # oldest evicted
    assert tr.job_doc("job-9")["hosts"][0]["host"] == "h-12"
    assert len(tr.job_doc("job-9")["hosts"]) == 8


def test_noop_tracker_surface_compatible():
    noop = NoopStepTracker()
    noop.observe("j", "h", step=1, dur_s=1.0, tokens=5.0,
                 collective_wait_s=0.1, ts=1.0, exemplar="x")
    assert noop.jobs() == []
    assert noop.stragglers() == []
    assert noop.to_dict() == {"jobs": []}
    assert noop.job_doc("j") is None
    assert NOOP_STEPS.to_dict() == {"jobs": []}


def test_set_stalled_edge_cases():
    """The ledger side of the contract: no-op when not productive,
    when closed, or on a same-state repeat."""
    clock = FakeClock()
    g = GoodputLedger(clock=clock)
    # Unknown object: nothing created, nothing raised.
    g.set_stalled("CoordinatorJob", "head", "nope", True)
    assert g.keys() == []
    key = ("CoordinatorJob", "head", "j")
    g.transition(*key, "queued")
    clock.advance(3.0)
    # Not productive -> the flag latches but no interval swap.
    g.set_stalled(*key, True)
    assert [iv["phase"] for iv in g.intervals(*key)] == ["queued"]
    g.set_stalled(*key, False)
    clock.advance(2.0)
    g.transition(*key, PHASE_PRODUCTIVE)
    clock.advance(4.0)
    g.set_stalled(*key, True)
    g.set_stalled(*key, True)                   # same-state repeat: no-op
    clock.advance(6.0)
    g.set_stalled(*key, False)
    g.transition(*key, "teardown")
    g.close(*key) if hasattr(g, "close") else None
    roll = g.rollup(*key)
    assert roll["phases"][PHASE_STALLED] == pytest.approx(6.0, abs=1e-6)
    assert sum(roll["phases"].values()) == pytest.approx(roll["total"],
                                                         abs=1e-6)
    seq = [iv["phase"] for iv in g.intervals(*key)]
    assert seq == ["queued", PHASE_PRODUCTIVE, PHASE_STALLED,
                   PHASE_PRODUCTIVE, "teardown"]
