"""Chunked cross-entropy (ops/xent.py) vs the dense-logits reference."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from kuberay_tpu.models import llama
from kuberay_tpu.ops.xent import chunked_softmax_xent_loss, chunked_xent


def dense_reference(x, head, targets):
    logits = (x.astype(jnp.float32) @ head.astype(jnp.float32))
    logz = jax.nn.logsumexp(logits, axis=-1)
    tl = jnp.take_along_axis(logits, targets[:, None], axis=-1)[:, 0]
    return logz - tl, logz, logits.argmax(-1).astype(jnp.int32)


def rand(T=24, d=16, V=96, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    x = jax.random.normal(ks[0], (T, d), jnp.float32)
    head = jax.random.normal(ks[1], (d, V), jnp.float32) * 0.3
    targets = jax.random.randint(ks[2], (T,), 0, V)
    return x, head, targets


def test_forward_matches_dense():
    x, head, targets = rand()
    for chunk in (16, 32, 96, 1000):
        nll, logz, pred = chunked_xent(x, head, targets, chunk)
        rn, rz, rp = dense_reference(x, head, targets)
        np.testing.assert_allclose(np.asarray(nll), np.asarray(rn),
                                   rtol=1e-5, atol=1e-5)
        np.testing.assert_allclose(np.asarray(logz), np.asarray(rz),
                                   rtol=1e-5, atol=1e-5)
        assert np.array_equal(np.asarray(pred), np.asarray(rp)), chunk


def test_gradients_match_dense():
    x, head, targets = rand(seed=1)

    def chunked_loss(x, head):
        nll, logz, _ = chunked_xent(x, head, targets, 16)
        return jnp.mean(nll) + 1e-3 * jnp.mean(logz ** 2)

    def dense_loss(x, head):
        nll, logz, _ = dense_reference(x, head, targets)
        return jnp.mean(nll) + 1e-3 * jnp.mean(logz ** 2)

    gc = jax.grad(chunked_loss, argnums=(0, 1))(x, head)
    gd = jax.grad(dense_loss, argnums=(0, 1))(x, head)
    for a, b in zip(gc, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-5, atol=2e-5)


def test_loss_wrapper_masks():
    x, head, targets = rand(seed=2)
    mask = jnp.ones((x.shape[0],)).at[5:].set(0.0)
    loss_m, metrics = chunked_softmax_xent_loss(x, head, targets, mask=mask,
                                                chunk=16)
    loss_head, _ = chunked_softmax_xent_loss(x[:5], head, targets[:5],
                                             chunk=16)
    np.testing.assert_allclose(float(loss_m), float(loss_head), rtol=1e-5)
    assert 0.0 <= float(metrics["accuracy"]) <= 1.0


def test_model_level_loss_and_grads_match():
    """llama loss_fn with xent_chunk == dense loss_fn: same loss, same
    grads, same metrics (the real parity check the flag relies on)."""
    cfg_dense = llama.CONFIGS["llama_tiny"]
    cfg_chunk = dataclasses.replace(cfg_dense, xent_chunk=64)
    params = llama.init_params(cfg_dense, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0,
                                cfg_dense.vocab_size)
    targets = jnp.roll(tokens, -1, axis=1)
    mask = jnp.ones_like(tokens, jnp.float32).at[1, 10:].set(0.0)

    def run(cfg):
        def f(p):
            loss, metrics = llama.loss_fn(cfg, p, tokens, targets, mask)
            return loss, metrics
        (loss, metrics), grads = jax.value_and_grad(f, has_aux=True)(params)
        return loss, metrics, grads

    ld, md, gd = run(cfg_dense)
    lc, mc, gc = run(cfg_chunk)
    np.testing.assert_allclose(float(ld), float(lc), rtol=1e-5)
    for k in md:
        np.testing.assert_allclose(float(md[k]), float(mc[k]), rtol=1e-4,
                                   atol=1e-6, err_msg=k)
    from jax.flatten_util import ravel_pytree
    flat_d, _ = ravel_pytree(gd)
    flat_c, _ = ravel_pytree(gc)
    np.testing.assert_allclose(np.asarray(flat_c), np.asarray(flat_d),
                               rtol=5e-4, atol=5e-5)


def test_sharded_train_step_with_chunked_xent():
    """Chunked CE must compile and train under the real dp/fsdp/tp mesh
    (tp shards the vocab axis of lm_head — the dynamic_slice over vocab
    must still partition)."""
    from kuberay_tpu.parallel.mesh import MeshSpec
    from kuberay_tpu.train.train_step import (
        TrainConfig,
        make_sharded_train_fns,
    )
    mesh = MeshSpec(dp=2, fsdp=2, tp=2).build(jax.devices()[:8][:8])
    cfg = dataclasses.replace(llama.CONFIGS["llama_tiny"], xent_chunk=64)
    init, step, _ = make_sharded_train_fns(
        cfg, TrainConfig(warmup_steps=2, decay_steps=10), mesh)
    state = init(jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 16), 0,
                                cfg.vocab_size)
    batch = {"tokens": tokens, "targets": jnp.roll(tokens, -1, axis=1)}
    state, m1 = step(state, batch)
    loss_chunked = float(m1["total_loss"])

    cfg_d = llama.CONFIGS["llama_tiny"]
    init_d, step_d, _ = make_sharded_train_fns(
        cfg_d, TrainConfig(warmup_steps=2, decay_steps=10), mesh)
    state_d = init_d(jax.random.PRNGKey(0))
    _, m2 = step_d(state_d, batch)
    np.testing.assert_allclose(loss_chunked, float(m2["total_loss"]),
                               rtol=1e-4)


def test_mixtral_chunked_loss_matches_dense():
    from kuberay_tpu.models import mixtral
    cfg_d = mixtral.CONFIGS["mixtral_tiny"]
    cfg_c = dataclasses.replace(cfg_d, xent_chunk=64)
    params = mixtral.init_params(cfg_d, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 12), 0,
                                cfg_d.vocab_size)
    targets = jnp.roll(tokens, -1, axis=1)
    ld, md = mixtral.loss_fn(cfg_d, params, tokens, targets)
    lc, mc = mixtral.loss_fn(cfg_c, params, tokens, targets)
    np.testing.assert_allclose(float(ld), float(lc), rtol=1e-5)
    for k in md:
        np.testing.assert_allclose(float(md[k]), float(mc[k]), rtol=1e-4,
                                   atol=1e-6, err_msg=k)


def test_odd_vocab_uses_tail_segment():
    """V not divisible by the chunk runs full chunks + one remainder
    segment (no silent chunk collapse) — e.g. llama3's 128256 % 16384."""
    x, head, targets = rand(V=100)

    def f(x, head):
        nll, logz, _ = chunked_xent(x, head, targets, 48)  # 2 full + 4 tail
        return jnp.mean(nll) + 1e-3 * jnp.mean(logz ** 2)

    def fd(x, head):
        nll, logz, _ = dense_reference(x, head, targets)
        return jnp.mean(nll) + 1e-3 * jnp.mean(logz ** 2)

    np.testing.assert_allclose(float(f(x, head)), float(fd(x, head)),
                               rtol=1e-5)
    gc = jax.grad(f, argnums=(0, 1))(x, head)
    gd = jax.grad(fd, argnums=(0, 1))(x, head)
    for a, b in zip(gc, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-5, atol=2e-5)
    # Targets landing IN the tail segment contribute correctly.
    t_tail = jnp.full_like(targets, 98)
    n1, _, _ = chunked_xent(x, head, t_tail, 48)
    n2, _, _ = dense_reference(x, head, t_tail)
    np.testing.assert_allclose(np.asarray(n1), np.asarray(n2),
                               rtol=1e-5, atol=1e-5)
