"""TpuCluster controller integration tests (envtest-style: real store +
manager + fake kubelet; modeled on raycluster_controller_test.go incl.
"multi-host worker group" :928 and suspend :736 specs)."""

import pytest

from kuberay_tpu.api.common import ObjectMeta
from kuberay_tpu.api.tpucluster import TpuCluster, ClusterState
from kuberay_tpu.controlplane.cluster_controller import TpuClusterController
from kuberay_tpu.controlplane.fake_kubelet import FakeKubelet
from kuberay_tpu.controlplane.manager import Manager, owned_pod_mapper
from kuberay_tpu.controlplane.store import ObjectStore
from kuberay_tpu.utils import constants as C
from tests.test_api_types import make_cluster


class Harness:
    def __init__(self):
        self.store = ObjectStore()
        self.manager = Manager(self.store)
        self.controller = TpuClusterController(
            self.store, expectations=self.manager.expectations)
        self.manager.register(C.KIND_CLUSTER, self.controller.reconcile)
        self.manager.map_owned(owned_pod_mapper)
        self.kubelet = FakeKubelet(self.store)

    def settle(self, rounds: int = 6):
        """Alternate reconcile-drain and kubelet steps until stable."""
        for _ in range(rounds):
            self.manager.flush_delayed()
            self.manager.run_until_idle()
            self.kubelet.step()
        self.manager.flush_delayed()
        self.manager.run_until_idle()

    def pods(self, **labels):
        return self.store.list("Pod", labels=labels or None)

    def cluster(self, name="demo"):
        return TpuCluster.from_dict(self.store.get(C.KIND_CLUSTER, name))


@pytest.fixture
def h():
    return Harness()


def test_single_host_cluster_provisions(h):
    c = make_cluster(accelerator="v5e", topology="2x2", replicas=2)
    h.store.create(c.to_dict())
    h.settle()
    # 1 head + 2 single-host slices.
    assert len(h.pods()) == 3
    got = h.cluster()
    assert got.status.state == ClusterState.READY
    assert got.status.readySlices == 2
    assert got.status.desiredTpuChips == 8
    # Head service exists.
    assert h.store.try_get("Service", "demo-head-svc") is not None


def test_multi_host_slice_atomic_create(h):
    c = make_cluster(accelerator="v5p", topology="2x2x2", replicas=2)
    h.store.create(c.to_dict())
    h.settle()
    workers = h.pods(**{C.LABEL_NODE_TYPE: C.NODE_TYPE_WORKER})
    assert len(workers) == 4  # 2 slices x 2 hosts
    # Host/slice identity labels + env:
    by_slice = {}
    for p in workers:
        lab = p["metadata"]["labels"]
        by_slice.setdefault(lab[C.LABEL_SLICE_INDEX], []).append(p)
        env = {e["name"]: e.get("value", "") for e in p["spec"]["containers"][0]["env"]}
        assert env[C.ENV_TPU_WORKER_ID] == lab[C.LABEL_HOST_INDEX]
        assert env[C.ENV_TPU_TOPOLOGY] == "2x2x2"
        assert len(env[C.ENV_TPU_WORKER_HOSTNAMES].split(",")) == 2
        assert env[C.ENV_NUM_PROCESSES] == "2"
    assert sorted(by_slice) == ["0", "1"]
    # Headless service created for multi-host.
    assert h.store.try_get("Service", "demo-headless") is not None
    # TPU resources requested per host.
    res = workers[0]["spec"]["containers"][0]["resources"]["requests"]
    assert res[C.RESOURCE_TPU] == "4"
    # Node selectors stamp generation + topology.
    sel = workers[0]["spec"]["nodeSelector"]
    assert sel[C.NODE_SELECTOR_GKE_ACCELERATOR] == "tpu-v5p-slice"
    assert sel[C.NODE_SELECTOR_GKE_TOPOLOGY] == "2x2x2"


def test_unhealthy_slice_repaired_whole(h):
    c = make_cluster(accelerator="v5p", topology="2x2x2", replicas=1)
    h.store.create(c.to_dict())
    h.settle()
    workers = h.pods(**{C.LABEL_NODE_TYPE: C.NODE_TYPE_WORKER})
    assert len(workers) == 2
    # Kill ONE host of the slice -> the WHOLE slice is replaced.
    victim = workers[0]["metadata"]["name"]
    h.kubelet.fail_pod(victim)
    h.settle()
    new_workers = h.pods(**{C.LABEL_NODE_TYPE: C.NODE_TYPE_WORKER})
    assert len(new_workers) == 2
    assert all(p["status"]["phase"] == "Running" for p in new_workers)
    # Replacement pods are new objects (uids differ from the killed set).
    assert {p["metadata"]["name"] for p in new_workers} == \
        {p["metadata"]["name"] for p in workers}  # same stable names
    got = h.cluster()
    assert got.status.readySlices == 1


def test_incomplete_slice_cleaned(h):
    c = make_cluster(accelerator="v5p", topology="2x2x2", replicas=1)
    h.store.create(c.to_dict())
    h.settle()
    # Delete one host pod directly (simulating eviction mid-creation).
    workers = h.pods(**{C.LABEL_NODE_TYPE: C.NODE_TYPE_WORKER})
    h.store.delete("Pod", workers[0]["metadata"]["name"])
    h.settle()
    # Slice was rebuilt complete.
    new_workers = h.pods(**{C.LABEL_NODE_TYPE: C.NODE_TYPE_WORKER})
    assert len(new_workers) == 2
    assert h.cluster().status.readySlices == 1


def test_scale_down_whole_slices(h):
    c = make_cluster(accelerator="v5p", topology="2x2x2", replicas=3)
    c.spec.workerGroupSpecs[0].maxReplicas = 3
    h.store.create(c.to_dict())
    h.settle()
    assert len(h.pods(**{C.LABEL_NODE_TYPE: C.NODE_TYPE_WORKER})) == 6
    # Scale to 1 slice.
    obj = h.store.get(C.KIND_CLUSTER, "demo")
    obj["spec"]["workerGroupSpecs"][0]["replicas"] = 1
    h.store.update(obj)
    h.settle()
    workers = h.pods(**{C.LABEL_NODE_TYPE: C.NODE_TYPE_WORKER})
    assert len(workers) == 2
    # Remaining pods form one complete slice (lowest index kept).
    assert {p["metadata"]["labels"][C.LABEL_SLICE_INDEX] for p in workers} == {"0"}


def test_autoscaler_slices_to_delete(h):
    c = make_cluster(accelerator="v5p", topology="2x2x2", replicas=2)
    c.spec.enableInTreeAutoscaling = True
    c.spec.workerGroupSpecs[0].maxReplicas = 4
    h.store.create(c.to_dict())
    h.settle()
    assert len(h.pods(**{C.LABEL_NODE_TYPE: C.NODE_TYPE_WORKER})) == 4
    # Autoscaler decides: drop slice demo-workers-1, replicas -> 1.
    obj = h.store.get(C.KIND_CLUSTER, "demo")
    obj["spec"]["workerGroupSpecs"][0]["replicas"] = 1
    obj["spec"]["workerGroupSpecs"][0]["scaleStrategy"] = {
        "slicesToDelete": ["demo-workers-1"]}
    h.store.update(obj)
    h.settle()
    workers = h.pods(**{C.LABEL_NODE_TYPE: C.NODE_TYPE_WORKER})
    assert {p["metadata"]["labels"][C.LABEL_SLICE_NAME] for p in workers} == \
        {"demo-workers-0"}


def test_suspend_resume(h):
    c = make_cluster(accelerator="v5e", topology="2x2", replicas=1)
    h.store.create(c.to_dict())
    h.settle()
    assert len(h.pods()) == 2
    obj = h.store.get(C.KIND_CLUSTER, "demo")
    obj["spec"]["suspend"] = True
    h.store.update(obj)
    h.settle()
    assert len(h.pods()) == 0
    assert h.cluster().status.state == ClusterState.SUSPENDED
    obj = h.store.get(C.KIND_CLUSTER, "demo")
    obj["spec"]["suspend"] = False
    h.store.update(obj)
    h.settle()
    assert len(h.pods()) == 2
    assert h.cluster().status.state == ClusterState.READY


def test_head_pod_restart_on_failure(h):
    c = make_cluster(accelerator="v5e", topology="2x2", replicas=0)
    h.store.create(c.to_dict())
    h.settle()
    head = h.pods(**{C.LABEL_NODE_TYPE: C.NODE_TYPE_HEAD})[0]
    h.kubelet.fail_pod(head["metadata"]["name"])
    h.settle()
    new_head = h.pods(**{C.LABEL_NODE_TYPE: C.NODE_TYPE_HEAD})[0]
    assert new_head["status"]["phase"] == "Running"


def test_invalid_spec_sets_failed_state(h):
    c = make_cluster(accelerator="v5e", topology="3x9", replicas=1)
    h.store.create(c.to_dict())
    h.manager.run_until_idle()
    got = h.cluster()
    assert got.status.state == ClusterState.FAILED
    assert "not divisible" in got.status.reason or "node pool" in got.status.reason
    assert len(h.pods()) == 0


def test_recreate_upgrade_on_template_change(h):
    c = make_cluster(accelerator="v5e", topology="2x2", replicas=1)
    c.spec.upgradeStrategy = "Recreate"
    h.store.create(c.to_dict())
    h.settle()
    old_pods = {p["metadata"]["name"]: p["metadata"]["uid"] for p in h.pods()}
    obj = h.store.get(C.KIND_CLUSTER, "demo")
    obj["spec"]["workerGroupSpecs"][0]["template"]["spec"]["containers"][0][
        "image"] = "new-image:v2"
    h.store.update(obj)
    h.settle(rounds=10)
    new_pods = {p["metadata"]["name"]: p["metadata"]["uid"] for p in h.pods()}
    assert len(new_pods) == 2
    # All pods were recreated (fresh uids).
    assert all(old_pods.get(n) != u for n, u in new_pods.items())


def test_deletion_cascades_to_pods(h):
    c = make_cluster(accelerator="v5p", topology="2x2x2", replicas=1)
    h.store.create(c.to_dict())
    h.settle()
    assert len(h.pods()) == 3
    h.store.delete(C.KIND_CLUSTER, "demo")
    h.manager.run_until_idle()
    assert h.store.try_get(C.KIND_CLUSTER, "demo") is None
    assert len(h.pods()) == 0  # ownerReference GC


def test_per_group_suspend(h):
    c = make_cluster(accelerator="v5e", topology="2x2", replicas=2)
    h.store.create(c.to_dict())
    h.settle()
    obj = h.store.get(C.KIND_CLUSTER, "demo")
    obj["spec"]["workerGroupSpecs"][0]["suspend"] = True
    h.store.update(obj)
    h.settle()
    assert len(h.pods(**{C.LABEL_NODE_TYPE: C.NODE_TYPE_WORKER})) == 0
    assert len(h.pods(**{C.LABEL_NODE_TYPE: C.NODE_TYPE_HEAD})) == 1
