#!/usr/bin/env sh
# Control-plane hot-path benchmark smoke: a small scale-up storm must
# converge and emit a parseable JSON result with nonzero reconcile
# throughput.  This is the standing guard for the store/workqueue fast
# path (docs/performance.md) — the full before/after numbers there were
# produced by the same harness at --clusters 300:
#
#   tools/bench_controlplane.sh                   # smoke (8 clusters)
#   BENCH_CLUSTERS=300 BENCH_WORKERS=4 tools/bench_controlplane.sh
#
# Part of the smoke-script family (tools/sim_smoke.sh, tools/obs_smoke.sh).
set -eu
cd "$(dirname "$0")/.."
out=$(timeout -k 10 300 env JAX_PLATFORMS=cpu python benchmark/controlplane_bench.py \
    --clusters "${BENCH_CLUSTERS:-8}" \
    --slices "${BENCH_SLICES:-2}" \
    --workers "${BENCH_WORKERS:-4}" \
    --dispatch "${BENCH_DISPATCH:-async}" \
    --timeout "${BENCH_TIMEOUT:-120}")
echo "$out"
BENCH_JSON="$out" python - <<'EOF'
import json, os
r = json.loads(os.environ["BENCH_JSON"])
assert r["converged"], f"storm did not converge: {r}"
assert r["reconciles_per_sec"] > 0, f"no reconcile throughput: {r}"
assert r["store_writes"] > 0 and r["events"] > 0, f"no store traffic: {r}"
print(f"bench smoke ok: {r['reconciles_per_sec']} reconciles/s, "
      f"{r['events_per_sec']} events/s, "
      f"store write p99 {r['store_write_p99_ms']} ms")
EOF
