#!/bin/bash
# Poll TPU tunnel liveness; append one status line per probe to
# /tmp/tpu_status.log.  On the FIRST probe that comes back UP, launch
# tools/tpu_capture.py (once — marker file) so a short tunnel window is
# never wasted waiting for a human.  Usage: tools/tpu_watch.sh [interval]
INTERVAL=${1:-120}
REPO="$(cd "$(dirname "$0")/.." && pwd)"
MARKER=/tmp/tpu_capture.started
# One capture per WATCHER SESSION: a stale marker from a crashed capture
# or an earlier session must not suppress this session's launch.
rm -f "$MARKER"
while true; do
  if timeout 60 python -c "
import os
os.environ['JAX_PLATFORMS'] = 'tpu'
import jax, jax.numpy as jnp
assert jax.devices()[0].platform == 'tpu', jax.devices()
x = jnp.ones((128,128), jnp.bfloat16)
assert float((x@x).sum()) > 0
" >/dev/null 2>&1; then
    echo "$(date -u +%H:%M:%S) UP" >> /tmp/tpu_status.log
    if [ ! -f "$MARKER" ]; then
      touch "$MARKER"
      echo "$(date -u +%H:%M:%S) capture launched" >> /tmp/tpu_status.log
      (cd "$REPO" && nohup python tools/tpu_capture.py \
          > /tmp/tpu_capture.log 2>&1 &)
    fi
  else
    echo "$(date -u +%H:%M:%S) down" >> /tmp/tpu_status.log
  fi
  sleep "$INTERVAL"
done
