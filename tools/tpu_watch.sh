#!/bin/bash
# Poll TPU tunnel liveness; append one status line per probe to
# /tmp/tpu_status.log so a build session can grab the chip the moment
# the tunnel returns.  Usage: tools/tpu_watch.sh [interval_seconds]
INTERVAL=${1:-120}
while true; do
  if timeout 60 python -c "
import jax, jax.numpy as jnp
x = jnp.ones((128,128), jnp.bfloat16)
assert float((x@x).sum()) > 0
" >/dev/null 2>&1; then
    echo "$(date -u +%H:%M:%S) UP" >> /tmp/tpu_status.log
  else
    echo "$(date -u +%H:%M:%S) down" >> /tmp/tpu_status.log
  fi
  sleep "$INTERVAL"
done
