#!/usr/bin/env sh
# Serve traffic-generator smoke: a tiny seeded hot-prefix run through the
# prefix-aware gateway must complete and emit a tpu-bench-serve/v1
# artifact with the full per-leg schema.  This is the standing guard for
# the fleet-serving data plane (docs/serving.md) — the published numbers
# in benchmark/results/serve_r07.json come from the same harness at
# full scale:
#
#   tools/bench_serve.sh                                   # smoke
#   python benchmark/serve_bench.py --traffic all --seeds 0..2 \
#       --duration 20 --json-out benchmark/results/serve_r07.json
#
# Part of the smoke-script family (tools/bench_controlplane.sh,
# tools/bench_scale.sh, tools/sim_smoke.sh, tools/obs_smoke.sh).
set -eu
cd "$(dirname "$0")/.."
out="${BENCH_OUT:-/tmp/tpu_bench_serve_smoke.json}"
timeout -k 10 600 env JAX_PLATFORMS=cpu python benchmark/serve_bench.py \
    --traffic "${BENCH_TRAFFIC:-hot-prefix}" \
    --seeds "${BENCH_SEEDS:-0}" \
    --duration "${BENCH_DURATION:-5}" \
    --rate-scale "${BENCH_RATE_SCALE:-0.5}" \
    --json-out "$out"
BENCH_JSON_PATH="$out" python - <<'EOF'
import json, os, sys
sys.path.insert(0, os.getcwd())
from benchmark.serve_bench import TRAFFIC_LEG_KEYS, TRAFFIC_SCHEMA
doc = json.load(open(os.environ["BENCH_JSON_PATH"]))
assert doc["schema"] == TRAFFIC_SCHEMA, doc.get("schema")
assert doc["legs"], "traffic run produced no legs"
for leg in doc["legs"]:
    missing = [k for k in TRAFFIC_LEG_KEYS if k not in leg]
    assert not missing, f"leg missing keys {missing}: {leg}"
    assert leg["errors"] == 0, f"transport errors in leg: {leg}"
    assert leg["completed"] + leg["shed"] == leg["requests"], leg
    assert leg["completed"] > 0 and leg["tokens_per_sec"] > 0, leg
print(f"bench serve smoke ok: {len(doc['legs'])} legs, "
      f"{sum(l['requests'] for l in doc['legs'])} requests, "
      f"schema {doc['schema']}")
EOF

# Disaggregated-serving smoke: the colocated-vs-disagg comparison legs
# (docs/serving.md, "Disaggregated prefill/decode") must complete with
# the two-hop scheduler live — the disagg leg has to show actual KV
# handoffs (sent + resident-skipped blocks from
# tpu_serve_kv_transfer_blocks_total) and at least one kv-transfer span
# under the gateway trace root.  Full-scale published numbers:
# benchmark/results/serve_r12.json (seeds 0..2, duration 30).
disagg_out="${BENCH_DISAGG_OUT:-/tmp/tpu_bench_serve_disagg.json}"
timeout -k 10 600 env JAX_PLATFORMS=cpu python benchmark/serve_bench.py \
    --traffic long-prompt \
    --seeds "${BENCH_SEEDS:-0}" \
    --duration "${BENCH_DURATION:-5}" \
    --rate-scale "${BENCH_RATE_SCALE:-0.5}" \
    --json-out "$disagg_out"
BENCH_JSON_PATH="$disagg_out" python - <<'EOF'
import json, os, sys
sys.path.insert(0, os.getcwd())
from benchmark.serve_bench import TRAFFIC_LEG_KEYS, TRAFFIC_SCHEMA
doc = json.load(open(os.environ["BENCH_JSON_PATH"]))
assert doc["schema"] == TRAFFIC_SCHEMA, doc.get("schema")
modes = sorted(leg["mode"] for leg in doc["legs"])
assert modes == ["colocated", "disagg"], modes
for leg in doc["legs"]:
    missing = [k for k in TRAFFIC_LEG_KEYS if k not in leg]
    assert not missing, f"leg missing keys {missing}: {leg}"
    assert leg["errors"] == 0, f"transport errors in leg: {leg}"
    assert leg["completed"] > 0 and leg["tokens_per_sec"] > 0, leg
dis = next(leg for leg in doc["legs"] if leg["mode"] == "disagg")
assert dis["kv_sent_blocks"] > 0, f"no KV blocks shipped: {dis}"
assert dis["kv_skipped_blocks"] > 0, \
    f"delta-only transfer never skipped a resident block: {dis}"
assert dis["kv_transfer_spans"] > 0, f"no kv-transfer spans traced: {dis}"
print(f"bench serve disagg ok: {dis['completed']} requests, "
      f"{dis['kv_sent_blocks']} blocks sent / "
      f"{dis['kv_skipped_blocks']} resident-skipped, "
      f"{dis['kv_transfer_spans']} kv-transfer spans")
EOF

# Tracing-overhead gate: same fleet + arrival schedule with end-to-end
# request tracing off vs on; the throughput cost of spans + exemplars
# must stay inside the budget (docs/observability.md, serve span model).
trace_out="${BENCH_TRACE_OUT:-/tmp/tpu_bench_serve_trace.json}"
timeout -k 10 600 env JAX_PLATFORMS=cpu python benchmark/serve_bench.py \
    --trace \
    --seeds "${BENCH_SEEDS:-0}" \
    --duration "${BENCH_DURATION:-5}" \
    --rate-scale "${BENCH_RATE_SCALE:-0.5}" \
    --json-out "$trace_out"
BENCH_JSON_PATH="$trace_out" \
BENCH_TRACE_MAX_PCT="${BENCH_TRACE_MAX_PCT:-5}" python - <<'EOF'
import json, os, sys
sys.path.insert(0, os.getcwd())
from benchmark.serve_bench import TRAFFIC_SCHEMA
doc = json.load(open(os.environ["BENCH_JSON_PATH"]))
assert doc["schema"] == TRAFFIC_SCHEMA, doc.get("schema")
assert len(doc["legs"]) == 2, f"expected off+on legs: {doc['legs']}"
off, on = doc["legs"]
assert off["tracing"] is False and on["tracing"] is True, doc["legs"]
for leg in doc["legs"]:
    assert leg["errors"] == 0, f"transport errors in leg: {leg}"
    assert leg["completed"] > 0 and leg["tokens_per_sec"] > 0, leg
ov = doc["trace_overhead"]
assert ov["spans_recorded"] > 0, "tracing-on leg recorded no spans"
limit = float(os.environ["BENCH_TRACE_MAX_PCT"])
assert ov["overhead_pct"] < limit, (
    f"tracing overhead {ov['overhead_pct']}% exceeds {limit}% budget: {ov}")
print(f"bench serve trace ok: overhead {ov['overhead_pct']}% "
      f"({ov['tokens_per_sec_off']} -> {ov['tokens_per_sec_on']} tok/s), "
      f"ttft p99 delta {ov['ttft_p99_delta_ms']} ms, "
      f"{ov['spans_recorded']} spans")
EOF

# Critical-path profile gate (docs/observability.md, "Critical-path
# profiles & trace diff"): tracer off vs on per seed over the identical
# schedule; the on legs fold into ONE tpu-profile/v1 serve profile whose
# self-diff must report zero regressions (the determinism canary) and
# whose requests/sec overhead stays inside the tracing budget.  The
# committed benchmark/results/profile_r18.json is the full-scale
# baseline; the candidate-vs-baseline diff is printed informationally
# only (absolute timings vary across machines — the diff names WHERE
# they moved, it is not a smoke failure).
profile_out="${BENCH_PROFILE_OUT:-/tmp/tpu_bench_serve_profile.json}"
timeout -k 10 600 env JAX_PLATFORMS=cpu python benchmark/serve_bench.py \
    --profile \
    --seeds "${BENCH_SEEDS:-0}" \
    --duration "${BENCH_DURATION:-5}" \
    --rate-scale "${BENCH_RATE_SCALE:-0.5}" \
    --json-out "$profile_out"
BENCH_JSON_PATH="$profile_out" \
BENCH_TRACE_MAX_PCT="${BENCH_TRACE_MAX_PCT:-5}" python - <<'EOF'
import json, os, sys
sys.path.insert(0, os.getcwd())
from benchmark.serve_bench import PROFILE_BENCH_SCHEMA, PROFILE_LEG_KEYS
doc = json.load(open(os.environ["BENCH_JSON_PATH"]))
assert doc["schema"] == PROFILE_BENCH_SCHEMA, doc.get("schema")
assert doc["legs"], "profile run produced no legs"
for leg in doc["legs"]:
    missing = [k for k in PROFILE_LEG_KEYS if k not in leg]
    assert not missing, f"leg missing keys {missing}: {leg}"
    assert leg["errors"] == 0, f"transport errors in leg: {leg}"
    assert leg["completed"] > 0, leg
prof = doc["profile"]
assert prof["schema"] == "tpu-profile/v1", prof.get("schema")
serve = prof["shapes"]["serve"]
assert serve["traces"] > 0, "no serve windows profiled"
frac = sum(k["fraction"] for k in serve["kinds"].values())
assert abs(frac - 1.0) < 1e-6, f"self-time fractions sum to {frac}"
assert doc["self_diff"]["regressions"] == [], (
    f"self-diff found regressions: {doc['self_diff']}")
ov = doc["overhead"]
limit = float(os.environ["BENCH_TRACE_MAX_PCT"])
assert ov["overhead_pct"] < limit, (
    f"profiling overhead {ov['overhead_pct']}% exceeds {limit}%: {ov}")
print(f"bench serve profile ok: {serve['traces']} windows, "
      f"kinds {sorted(serve['kinds'])}, overhead {ov['overhead_pct']}% "
      f"({ov['requests_per_sec_off']} -> {ov['requests_per_sec_on']} req/s)")
EOF
if [ -f benchmark/results/profile_r18.json ]; then
    python -m kuberay_tpu.cli profile diff \
        benchmark/results/profile_r18.json "$profile_out" || true
fi

# Zero-downtime upgrade gate (docs/upgrades.md): per seed, a blue-only
# baseline, the burn-rate-gated orchestrator ramp, and the legacy naive
# timer ramp — both ramps hit a connection-refused fault on the green
# endpoint mid-upgrade.  The gated ramp must roll back with ZERO
# client-visible failures and bounded TTFT inflation; the naive ramp
# demonstrates the failure mode it replaced (promotes the dead build
# and fails requests).  Full-scale published numbers:
# benchmark/results/upgrade_r13.json (seeds 0..2, duration 12).
upgrade_out="${BENCH_UPGRADE_OUT:-/tmp/tpu_bench_serve_upgrade.json}"
timeout -k 10 600 env JAX_PLATFORMS=cpu python benchmark/serve_bench.py \
    --upgrade \
    --seeds "${BENCH_SEEDS:-0}" \
    --duration "${BENCH_UPGRADE_DURATION:-6}" \
    --rate-scale "${BENCH_RATE_SCALE:-0.5}" \
    --json-out "$upgrade_out"
BENCH_JSON_PATH="$upgrade_out" \
BENCH_UPGRADE_TTFT_LIMIT="${BENCH_UPGRADE_TTFT_LIMIT:-5}" python - <<'EOF'
import json, os, sys
sys.path.insert(0, os.getcwd())
from benchmark.serve_bench import UPGRADE_LEG_KEYS, UPGRADE_SCHEMA
doc = json.load(open(os.environ["BENCH_JSON_PATH"]))
assert doc["schema"] == UPGRADE_SCHEMA, doc.get("schema")
assert doc["legs"] and doc["comparisons"], "upgrade run produced no legs"
for leg in doc["legs"]:
    missing = [k for k in UPGRADE_LEG_KEYS if k not in leg]
    assert not missing, f"leg missing keys {missing}: {leg}"
    assert leg["completed"] > 0, f"leg completed nothing: {leg}"
limit = float(os.environ["BENCH_UPGRADE_TTFT_LIMIT"])
for cmp in doc["comparisons"]:
    # The tentpole's gate: the burn-rate-gated ramp survives the
    # mid-upgrade fault with zero failed requests and bounded TTFT...
    assert cmp["gated_errors"] == 0, f"gated ramp failed requests: {cmp}"
    assert cmp["gated_rolled_back"], f"gated ramp never rolled back: {cmp}"
    assert cmp["ttft_inflation"] is not None and \
        cmp["ttft_inflation"] < limit, (
        f"gated TTFT inflation {cmp['ttft_inflation']}x over {limit}x: {cmp}")
    # ...while the naive timer ramp under the identical fault either
    # fails requests or serves the bad build (it does both: promotes
    # the dead green fleet, then every request errors).
    assert cmp["naive_errors"] > 0 or cmp["naive_promoted_bad_build"], (
        f"naive ramp showed no failure mode: {cmp}")
gated = [l for l in doc["legs"] if l["mode"] == "gated"]
assert all(l["prewarm_replayed"] > 0 for l in gated), \
    "gated legs never pre-warmed the green fleet"
print(f"bench serve upgrade ok: {len(doc['comparisons'])} seeds, "
      f"gated errors 0, "
      f"naive errors {sum(c['naive_errors'] for c in doc['comparisons'])}, "
      f"ttft inflation "
      f"{max(c['ttft_inflation'] for c in doc['comparisons'])}x")
EOF

# Stateful-session KV gate (docs/kv-tiers.md): the closed-loop
# multi-turn schedule runs twice per seed — resume-with-tiers vs
# full-recompute — with zero wall-clock in the artifact, so a re-run of
# the same seed must be BYTE-identical (the determinism contract the
# published benchmark/results/kv_r17.json pins, seeds 0..2).  Resume's
# prefill-token p99 (the TTFT proxy the hierarchy exists to shrink)
# must beat recompute's, with session context far exceeding the device
# pool and zero failures.
kv_out="${BENCH_KV_OUT:-/tmp/tpu_bench_serve_kv.json}"
timeout -k 10 600 env JAX_PLATFORMS=cpu python benchmark/serve_bench.py \
    --traffic multi-turn \
    --seeds "${BENCH_SEEDS:-0}" \
    --json-out "$kv_out"
timeout -k 10 600 env JAX_PLATFORMS=cpu python benchmark/serve_bench.py \
    --traffic multi-turn \
    --seeds "${BENCH_SEEDS:-0}" \
    --json-out "${kv_out}.rerun"
BENCH_JSON_PATH="$kv_out" python - <<'EOF'
import json, os, sys
sys.path.insert(0, os.getcwd())
from benchmark.serve_bench import KV_LEG_KEYS, KV_SCHEMA
path = os.environ["BENCH_JSON_PATH"]
assert open(path, "rb").read() == open(path + ".rerun", "rb").read(), \
    "multi-turn artifact is not byte-identical across re-runs"
doc = json.load(open(path))
assert doc["schema"] == KV_SCHEMA, doc.get("schema")
assert doc["legs"] and doc["comparisons"], "kv run produced no legs"
for leg in doc["legs"]:
    missing = [k for k in KV_LEG_KEYS if k not in leg]
    assert not missing, f"leg missing keys {missing}: {leg}"
    assert leg["errors"] == 0, f"failed requests in leg: {leg}"
    assert leg["completed"] == leg["requests"], leg
    assert leg["context_tokens_total"] > 2 * leg["device_token_capacity"], (
        f"session state does not exceed device capacity: {leg}")
for cmp in doc["comparisons"]:
    assert cmp["resume_beats_recompute"], (
        f"resume prefill p99 did not beat recompute: {cmp}")
resume = [l for l in doc["legs"] if l["mode"] == "resume"]
assert all(l["session_resumes"] > 0 for l in resume), \
    "resume legs recorded no session resumes"
assert all(l["tier_fetch_blocks"] > 0 for l in resume), \
    "resume legs never promoted a block from the host tier"
print(f"bench serve kv ok: {len(doc['comparisons'])} seeds byte-stable, "
      f"prefill p99 resume vs recompute "
      + ", ".join(f"{c['resume_prefill_p99']}/{c['recompute_prefill_p99']}"
                  for c in doc["comparisons"]))
EOF
