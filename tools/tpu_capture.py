#!/usr/bin/env python
"""One-shot TPU measurement capture: run the full on-chip checklist the
moment the tunnel is up (windows have been ~11 min — tools/tpu_watch.sh
triggers this automatically on the first UP probe).

Steps, in priority order (each its own subprocess with a timeout so one
hang can't burn the window; partial results are still written):
 1. bench.py            — train tokens/s/chip + MFU (the BENCH_r02 line)
 2. bench.py --op       — flash fwd kernel vs XLA
 3. decode kernel       — pallas vs XLA, full + short lens
 4. paged kernel        — rewritten grid, vs gather-XLA
 5. flash block sweep   — TPU_FLASH_BQ/BKV targets on the 1b fwd+bwd shape
 6. flash bwd check     — fwd/bwd numerics vs XLA on-chip

Results land in tpu_results/capture-<unix>.json (repo-tracked), one dict
per step with rc/seconds/stdout-tail.
"""

from __future__ import annotations

import json
import os
import pathlib
import subprocess
import sys
import time

REPO = pathlib.Path(__file__).resolve().parent.parent
OUT_DIR = REPO / "tpu_results"

DECODE_SNIPPET = r"""
import time, jax, jax.numpy as jnp
from kuberay_tpu.ops.decode_attention import decode_attention
def bench(f, *a, n=30):
    f(*a).block_until_ready()
    t0=time.perf_counter()
    for _ in range(n): o = f(*a)
    o.block_until_ready(); float(jnp.max(o))
    return (time.perf_counter()-t0)/n*1e3
B,K,Hq,Hkv,D = 64, 2048, 8, 4, 128
ks = jax.random.split(jax.random.PRNGKey(0), 4)
q  = jax.random.normal(ks[0],(B,Hq,D),jnp.bfloat16)
ck = jax.random.normal(ks[1],(B,K,Hkv,D),jnp.bfloat16)
cv = jax.random.normal(ks[2],(B,K,Hkv,D),jnp.bfloat16)
fp = jax.jit(lambda *a: decode_attention(*a, impl='pallas'))
fx = jax.jit(lambda *a: decode_attention(*a, impl='xla'))
full = jnp.full((B,), K, jnp.int32); short = jnp.full((B,), 128, jnp.int32)
d = float(jnp.max(jnp.abs(fp(q,ck,cv,full).astype(jnp.float32)-fx(q,ck,cv,full).astype(jnp.float32))))
import json
print(json.dumps({"diff": d,
  "pallas_full_ms": bench(fp,q,ck,cv,full), "xla_full_ms": bench(fx,q,ck,cv,full),
  "pallas_short_ms": bench(fp,q,ck,cv,short), "xla_short_ms": bench(fx,q,ck,cv,short)}))
"""

PAGED_SNIPPET = r"""
import time, jax, jax.numpy as jnp, json
from kuberay_tpu.ops.paged_attention import paged_decode_attention_pallas, paged_decode_attention_xla
def bench(f, *a, n=30):
    f(*a).block_until_ready()
    t0=time.perf_counter()
    for _ in range(n): o = f(*a)
    o.block_until_ready(); float(jnp.max(o))
    return (time.perf_counter()-t0)/n*1e3
out = {}
S,Hq,Hkv,D = 16, 8, 4, 128
for bs, nblk in ((64, 16), (128, 8), (256, 4)):
    P = 256
    ks = jax.random.split(jax.random.PRNGKey(0), 5)
    q  = jax.random.normal(ks[0],(S,Hq,D),jnp.bfloat16)
    pk = jax.random.normal(ks[1],(Hkv,P*bs,D),jnp.bfloat16)
    pv = jax.random.normal(ks[2],(Hkv,P*bs,D),jnp.bfloat16)
    tb = jax.random.randint(ks[3],(S,nblk),0,P)
    ln = jnp.full((S,), nblk*bs, jnp.int32)
    p = jax.jit(lambda *a, bs=bs: paged_decode_attention_pallas(*a, block_size=bs))
    x = jax.jit(lambda *a, bs=bs: paged_decode_attention_xla(*a, block_size=bs))
    d = float(jnp.max(jnp.abs(p(q,pk,pv,ln,tb).astype(jnp.float32)-x(q,pk,pv,ln,tb).astype(jnp.float32))))
    out[f"bs{bs}"] = {"diff": d, "pallas_ms": bench(p,q,pk,pv,ln,tb), "xla_ms": bench(x,q,pk,pv,ln,tb)}
print(json.dumps(out))
"""

FLASH_CHECK_SNIPPET = r"""
import jax, jax.numpy as jnp, json
from kuberay_tpu.ops.attention import flash_attention
B,S,Hq,Hkv,D = 2,2048,8,4,128
ks = jax.random.split(jax.random.PRNGKey(0), 3)
q = jax.random.normal(ks[0],(B,S,Hq,D),jnp.bfloat16)
k = jax.random.normal(ks[1],(B,S,Hkv,D),jnp.bfloat16)
v = jax.random.normal(ks[2],(B,S,Hkv,D),jnp.bfloat16)
p = jax.jit(lambda q,k,v: flash_attention(q,k,v,causal=True,impl='pallas'))(q,k,v)
x = jax.jit(lambda q,k,v: flash_attention(q,k,v,causal=True,impl='xla'))(q,k,v)
fwd = float(jnp.max(jnp.abs(p.astype(jnp.float32)-x.astype(jnp.float32))))
def lp(q,k,v,impl): return jnp.sum(flash_attention(q,k,v,causal=True,impl=impl).astype(jnp.float32)*0.01)
gp = jax.jit(jax.grad(lambda *a: lp(*a,'pallas'), argnums=(0,1,2)))(q,k,v)
gx = jax.jit(jax.grad(lambda *a: lp(*a,'xla'), argnums=(0,1,2)))(q,k,v)
bwd = {n: float(jnp.max(jnp.abs(a.astype(jnp.float32)-b.astype(jnp.float32))))
       for n,a,b in zip('qkv', gp, gx)}
print(json.dumps({"fwd_maxdiff": fwd, "bwd_maxdiff": bwd}))
"""

MOE_SNIPPET = r"""
import time, jax, jax.numpy as jnp, json
from kuberay_tpu.ops.moe_matmul import grouped_moe_ffn, dropless_reference
T,d,f,E,K = 64, 4096, 14336, 8, 2
ks = jax.random.split(jax.random.PRNGKey(0), 5)
xt = jax.random.normal(ks[0],(T,d),jnp.bfloat16)
wg = jax.random.normal(ks[1],(E,d,f),jnp.bfloat16)*0.05
wu = jax.random.normal(ks[2],(E,d,f),jnp.bfloat16)*0.05
wd = jax.random.normal(ks[3],(E,f,d),jnp.bfloat16)*0.05
topw, topi = jax.lax.top_k(jax.nn.softmax(jax.random.normal(ks[4],(T,E)),-1), K)
topw = topw / topw.sum(-1, keepdims=True)
g = jax.jit(grouped_moe_ffn); r = jax.jit(dropless_reference)
def bench(fn, n=30):
    fn(xt,wg,wu,wd,topi,topw).block_until_ready()
    t0=time.perf_counter()
    for _ in range(n): o = fn(xt,wg,wu,wd,topi,topw)
    float(jnp.max(jnp.abs(o)))
    return (time.perf_counter()-t0)/n*1e3
diff = float(jnp.max(jnp.abs(g(xt,wg,wu,wd,topi,topw).astype(jnp.float32)
                             - r(xt,wg,wu,wd,topi,topw).astype(jnp.float32))))
print(json.dumps({"diff": diff, "grouped_ms": bench(g), "dense_ms": bench(r)}))
"""

QUANT_DECODE_SNIPPET = r"""
import time, jax, jax.numpy as jnp, json
from kuberay_tpu.ops.decode_attention import (
    decode_attention, decode_attention_quant)
from kuberay_tpu.serve.kv_cache import quantize_kv
B,K,Hq,Hkv,D = 64, 2048, 8, 4, 128
ks_ = jax.random.split(jax.random.PRNGKey(0), 3)
q  = jax.random.normal(ks_[0],(B,Hq,D),jnp.bfloat16)
ck = jax.random.normal(ks_[1],(B,K,Hkv,D),jnp.bfloat16)
cv = jax.random.normal(ks_[2],(B,K,Hkv,D),jnp.bfloat16)
kq, ksc = quantize_kv(ck); vq, vsc = quantize_kv(cv)
ksc = jnp.moveaxis(ksc[...,0], -1, 1); vsc = jnp.moveaxis(vsc[...,0], -1, 1)
lens = jnp.full((B,), K, jnp.int32)
fq = jax.jit(lambda: decode_attention_quant(q,kq,ksc,vq,vsc,lens,impl='pallas'))
fb = jax.jit(lambda: decode_attention(q,ck,cv,lens,impl='pallas'))
def bench(f, n=30):
    f().block_until_ready()
    t0=time.perf_counter()
    for _ in range(n): o=f()
    float(jnp.max(jnp.abs(o)))
    return (time.perf_counter()-t0)/n*1e3
d = float(jnp.max(jnp.abs(fq().astype(jnp.float32)-fb().astype(jnp.float32))))
print(json.dumps({"diff_vs_bf16": d, "int8_ms": bench(fq),
                  "bf16_ms": bench(fb)}))
"""

XENT_SNIPPET = r"""
import time, dataclasses, jax, jax.numpy as jnp, json
from kuberay_tpu.models import llama
base = llama.CONFIGS["llama_1b"]
out = {}
for label, cfg in (("dense", base),
                   ("chunked", dataclasses.replace(base, xent_chunk=8192))):
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    toks = jax.random.randint(jax.random.PRNGKey(1), (4, 2048), 0,
                              cfg.vocab_size)
    tgt = jnp.roll(toks, -1, axis=1)
    f = jax.jit(jax.grad(lambda p: llama.loss_fn(cfg, p, toks, tgt)[0]))
    g = f(params); jax.block_until_ready(g)
    t0 = time.perf_counter()
    for _ in range(5): g = f(params)
    float(jnp.max(jnp.abs(g["lm_head"])))
    out[label + "_ms"] = (time.perf_counter() - t0) / 5 * 1e3
print(json.dumps(out))
"""

BLOCK_SWEEP_SNIPPET = r"""
import time, jax, jax.numpy as jnp, json
from kuberay_tpu.ops.attention import flash_attention
B,S,Hq,Hkv,D = 4,2048,16,8,128
ks = jax.random.split(jax.random.PRNGKey(0), 3)
q = jax.random.normal(ks[0],(B,S,Hq,D),jnp.bfloat16)
k = jax.random.normal(ks[1],(B,S,Hkv,D),jnp.bfloat16)
v = jax.random.normal(ks[2],(B,S,Hkv,D),jnp.bfloat16)
fn = jax.jit(lambda q,k,v: flash_attention(q,k,v,causal=True,impl='pallas'))
float(jnp.max(fn(q,k,v)))
t0=time.perf_counter()
out = q
for _ in range(20): out = fn(out,k,v)
float(jnp.max(out))
print(json.dumps({"fwd_ms": (time.perf_counter()-t0)/20*1e3}))
"""


def run_step(name, argv, timeout, env=None):
    t0 = time.time()
    try:
        out = subprocess.run(argv, capture_output=True, text=True,
                             timeout=timeout, cwd=str(REPO),
                             env={**os.environ, **(env or {})})
        rc, text = out.returncode, (out.stdout + out.stderr)
    except subprocess.TimeoutExpired as e:
        rc, text = -99, (e.stdout or b"").decode(errors="replace") \
            if isinstance(e.stdout, bytes) else (e.stdout or "")
    rec = {"step": name, "rc": rc, "seconds": round(time.time() - t0, 1),
           "tail": text.strip().splitlines()[-8:]}
    print(json.dumps(rec), flush=True)
    return rec


def main() -> int:
    OUT_DIR.mkdir(exist_ok=True)
    out_path = OUT_DIR / f"capture-{int(time.time())}.json"
    results = []

    def save():
        out_path.write_text(json.dumps(results, indent=1) + "\n")

    py = sys.executable
    steps = [
        ("bench_train", [py, "bench.py"], 560, None),
        # The two tuning levers from docs/roofline_llama1b.md, right
        # after the baseline so a short window still compares them:
        ("bench_train_remat_dots", [py, "bench.py"], 560,
         {"BENCH_REMAT_POLICY": "dots"}),
        ("bench_train_bkv1024", [py, "bench.py"], 560,
         {"TPU_FLASH_BKV": "1024"}),
        ("bench_op", [py, "bench.py", "--op"], 400, None),
        ("decode_kernel", [py, "-c", DECODE_SNIPPET], 400, None),
        ("paged_kernel", [py, "-c", PAGED_SNIPPET], 500, None),
        ("flash_check", [py, "-c", FLASH_CHECK_SNIPPET], 400, None),
        ("moe_grouped", [py, "-c", MOE_SNIPPET], 400, None),
        ("xent_chunked", [py, "-c", XENT_SNIPPET], 500, None),
        ("quant_decode", [py, "-c", QUANT_DECODE_SNIPPET], 400, None),
        # Serve engine matrix on-chip: same harness that published the
        # CPU-relative numbers (benchmark/results/serve_r05.json) —
        # a tunnel window upgrades them to real tokens/s + TTFT.
        ("serve_matrix", [py, "benchmark/serve_bench.py", "--matrix",
                          "--model", "llama_tiny", "--requests", "32",
                          "--json-out",
                          "tpu_results/serve_matrix_onchip.json"],
         560, None),
    ]
    for bq, bkv in ((512, 512), (1024, 512), (512, 1024), (1024, 1024),
                    (256, 512), (1024, 256)):
        steps.append((f"block_sweep_bq{bq}_bkv{bkv}",
                      [py, "-c", BLOCK_SWEEP_SNIPPET], 300,
                      {"TPU_FLASH_BQ": str(bq), "TPU_FLASH_BKV": str(bkv)}))

    for name, argv, timeout, env in steps:
        results.append(run_step(name, argv, timeout, env))
        save()
        # If the tunnel died mid-capture (hang/timeout), keep trying the
        # remaining cheap steps only if something has succeeded already.
        if results[-1]["rc"] == -99 and \
                not any(r["rc"] == 0 for r in results):
            break
    save()
    print(f"capture written: {out_path}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
