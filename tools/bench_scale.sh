#!/usr/bin/env sh
# Scale-ladder smoke: the 300-cluster rung only (CI-shaped; the full
# published ladder is 300/1k/3k/10k x shards 1,4 — docs/performance.md):
#
#   tools/bench_scale.sh                                # 300-rung smoke
#   BENCH_RUNGS=300,1000 BENCH_SHARDS=1,4 tools/bench_scale.sh
#
# Asserts the tpu-bench-ladder/v1 artifact schema: every leg converged
# and carries the full tpu-bench/v1 key set (ARTIFACT_KEYS), so a
# refactor can't silently drop a ladder column.  Part of the smoke
# family (tools/bench_controlplane.sh, tools/sim_smoke.sh).
set -eu
cd "$(dirname "$0")/.."
out="${BENCH_OUT:-/tmp/tpu_bench_ladder_smoke.json}"
timeout -k 10 900 env JAX_PLATFORMS=cpu python benchmark/scale_bench.py \
    --ladder "${BENCH_RUNGS:-300}" \
    --ladder-shards "${BENCH_SHARDS:-1,4}" \
    --ladder-workers "${BENCH_WORKERS:-1}" \
    --timeout "${BENCH_TIMEOUT:-600}" \
    --out "$out" > /dev/null
BENCH_ARTIFACT="$out" python - <<'EOF'
import json, os, sys
sys.path.insert(0, ".")
from benchmark.controlplane_bench import ARTIFACT_KEYS
doc = json.load(open(os.environ["BENCH_ARTIFACT"]))
assert doc.get("schema") == "tpu-bench-ladder/v1", doc.get("schema")
assert doc["legs"], "ladder produced no legs"
for leg in doc["legs"]:
    missing = [k for k in ARTIFACT_KEYS if k not in leg]
    assert not missing, f"leg missing artifact keys {missing}: {leg}"
    assert leg["schema"] == "tpu-bench/v1"
    assert leg["converged"], f"leg did not converge: {leg['workload']}"
    assert leg["reconciles_per_sec"] > 0
print("bench_scale smoke ok:", ", ".join(
    "%(clusters)dx s=%(shards)d" % leg["workload"] +
    " %.1fs" % leg["elapsed_s"] for leg in doc["legs"]))
EOF
