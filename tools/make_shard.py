#!/usr/bin/env python3
"""Token-shard converter: text -> the framework's uint32 shard format
(see kuberay_tpu/train/data.py).

    python tools/make_shard.py --input corpus.txt --output shard.bin \
        [--tokenizer gpt2 | --byte-level]

--byte-level needs no model downloads (offset-256 bytes, vocab 512) and is
the zero-dependency default; --tokenizer uses a HuggingFace tokenizer when
the transformers cache has one.
"""

from __future__ import annotations

import argparse
import pathlib
import sys

import numpy as np

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

from kuberay_tpu.train.data import write_token_shard  # noqa: E402


def byte_level_tokens(text: bytes) -> np.ndarray:
    # Offset so 0..255 stay free for special tokens.
    return np.frombuffer(text, dtype=np.uint8).astype(np.uint32) + 256


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--input", required=True)
    ap.add_argument("--output", required=True)
    ap.add_argument("--tokenizer", default="",
                    help="HuggingFace tokenizer name (needs cached model)")
    ap.add_argument("--byte-level", action="store_true")
    args = ap.parse_args(argv)

    raw = pathlib.Path(args.input).read_bytes()
    if args.tokenizer and not args.byte_level:
        from transformers import AutoTokenizer
        tok = AutoTokenizer.from_pretrained(args.tokenizer)
        ids = tok(raw.decode(errors="replace"))["input_ids"]
        tokens = np.asarray(ids, dtype=np.uint32)
    else:
        tokens = byte_level_tokens(raw)
    write_token_shard(args.output, tokens)
    print(f"wrote {len(tokens)} tokens -> {args.output}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
