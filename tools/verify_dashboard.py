"""Verify drive: live apiserver + seeded CRs + coordinator for the
dashboard drill-down views.  Prints the URL and blocks."""
import json
import sys
import time

from kuberay_tpu.apiserver.server import serve_background
from kuberay_tpu.controlplane.store import ObjectStore
from kuberay_tpu.runtime.coordinator_client import CoordinatorClient
from kuberay_tpu.runtime.coordinator_server import CoordinatorServer, MemoryBackend
from kuberay_tpu.utils import constants as C

sys.path.insert(0, "tests")
from test_api_types import make_cluster  # noqa: E402


def main():
    coord = CoordinatorServer(state=MemoryBackend(),
                              log_dir="/tmp/verify-dash-logs")
    csrv, curl = coord.serve_background()
    host, port = curl.rsplit("//", 1)[1].rsplit(":", 1)
    C.PORT_DASHBOARD = int(port)
    client = CoordinatorClient(curl)
    client.submit_job("j-dash", f"{sys.executable} -c 'print(\"hello from job\")'")
    client.post_events([{"type": "step", "name": "train_step", "job_id": "j-dash",
                         "ts": time.time(), "dur": 0.6,
                         "args": {"step": 100, "loss": 1.23}}])

    store = ObjectStore()
    store.create(make_cluster(name="democ").to_dict())
    obj = store.get(C.KIND_CLUSTER, "democ")
    obj["status"] = {"state": "ready", "readySlices": 1, "desiredSlices": 1,
                     "coordinatorAddress": f"{host}:{port}"}
    store.update_status(obj)
    store.create({
        "apiVersion": C.API_VERSION, "kind": C.KIND_JOB,
        "metadata": {"name": "demoj", "namespace": "default"},
        "spec": {"entrypoint": "python x.py", "submissionMode": "HTTPMode",
                 "clusterSpec": obj["spec"]},
        "status": {"jobId": "j-dash", "clusterName": "democ",
                   "jobDeploymentStatus": "Running", "jobStatus": "RUNNING",
                   "startTime": time.time() - 60,
                   "conditions": [{"type": "Initialized", "status": "True",
                                   "lastTransitionTime": time.time() - 50}]},
    })
    store.create({
        "apiVersion": C.API_VERSION, "kind": C.KIND_SERVICE,
        "metadata": {"name": "demos", "namespace": "default"},
        "spec": {"serveConfig": {"applications": []},
                 "clusterSpec": obj["spec"]},
        "status": {"serviceStatus": "Running",
                   "activeServiceStatus": {"clusterName": "democ",
                                           "trafficWeightPercent": 80,
                                           "targetCapacityPercent": 100,
                                           "specHash": "abcdef123456",
                                           "applications": [{"name": "llm", "status": "RUNNING"}]},
                   "pendingServiceStatus": {"clusterName": "democ2",
                                            "trafficWeightPercent": 20,
                                            "targetCapacityPercent": 40,
                                            "specHash": "fedcba654321"}},
    })
    srv, url = serve_background(store)
    print(f"DASHBOARD_URL {url}/dashboard", flush=True)
    while True:
        time.sleep(5)


if __name__ == "__main__":
    main()
