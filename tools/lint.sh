#!/usr/bin/env sh
# Standalone invariant-lint entry point (the same gate tier-1 tests run
# via tests/test_static_analysis.py).  Exits nonzero on findings, so it
# drops straight into CI:
#
#   tools/lint.sh                      # human output, whole package
#   tools/lint.sh --format json        # machine-readable (CI annotations)
#   tools/lint.sh kuberay_tpu/serve    # a subtree
#   tools/lint.sh --changed-only       # git-diff file set (pre-commit;
#                                      # auto-widens to whole repo when
#                                      # unchanged callers are affected)
#   tools/lint.sh --list-rules         # what is enforced, and why
#
# See docs/static-analysis.md for the rules and the suppression syntax.
set -eu
cd "$(dirname "$0")/.."
exec python -m kuberay_tpu.analysis "$@"
