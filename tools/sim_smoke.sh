#!/usr/bin/env sh
# Chaos-simulation smoke corpus: every scenario across a small fixed
# seed set must converge with zero invariant violations.  This is the
# standing robustness gate for controller changes — a violation prints
# the exact replay command (scenario + seed), so failures reproduce
# deterministically on any machine:
#
#   tools/sim_smoke.sh                 # default corpus (seeds 0..4)
#   SIM_SEEDS=0..9 tools/sim_smoke.sh  # wider sweep
#   SIM_STEPS=20   tools/sim_smoke.sh  # deeper runs
#
# The tier-1 pytest gate (tests/test_sim_harness.py) runs a 2-seed
# subset of this corpus on every PR; see docs/chaos-sim.md.
set -eu
cd "$(dirname "$0")/.."
timeout -k 10 600 env JAX_PLATFORMS=cpu python -m kuberay_tpu.sim \
    --scenario all \
    --seed "${SIM_SEEDS:-0..4}" \
    --steps "${SIM_STEPS:-8}"
# The contention storm again, deeper: the corpus above runs every
# scenario (including the three quota scenarios) at the default step
# budget, but the storm's interesting failure modes — reclaim racing a
# voluntary release, escalation past the starvation bound, pending GC —
# need enough virtual minutes of backlog churn to surface.  The quota-*
# invariants are armed (the scenario mounts the quota seam), so a
# partially-admitted gang, a conservation breach, or an unescalated
# starving gang fails the smoke here.
timeout -k 10 600 env JAX_PLATFORMS=cpu python -m kuberay_tpu.sim \
    --scenario contention-storm \
    --seed "${SIM_SEEDS:-0..4}" \
    --steps "${SIM_STEPS:-16}"
# Session churn, wider and deeper: the corpus above already runs the
# scenario at the default budget, but the no-stale-block invariant's
# interesting regimes — spill-tier pressure eviction racing a resume,
# a stale re-admit offered just before the true block's checkout —
# need more ticks of chain growth and the full 0..9 seed sweep the
# KV-tier acceptance gate pins (docs/kv-tiers.md).
timeout -k 10 600 env JAX_PLATFORMS=cpu python -m kuberay_tpu.sim \
    --scenario session-churn \
    --seed "${SIM_SEEDS:-0..9}" \
    --steps "${SIM_STEPS:-16}"
# Incident forensics drill leg: the dead-green-upgrade scenario ramps
# onto a green build whose serve endpoint is dead on arrival, the
# burn-rate gate rolls the ramp back, and the incident engine must
# (a) open a bundle whose TOP-ranked suspect names the dead green
# backend's error series — not the ramp's own audit trail — and
# (b) export a BYTE-identical tpu-incident-export/v1 artifact across
# two runs of the same (scenario, seed), and (c) leave the journal
# hash untouched when the engine is mounted (observation must never
# perturb the timeline).
inc_a="${SIM_INC_A:-/tmp/sim_smoke_incidents_a.json}"
inc_b="${SIM_INC_B:-/tmp/sim_smoke_incidents_b.json}"
timeout -k 10 300 env JAX_PLATFORMS=cpu python -m kuberay_tpu.sim \
    --scenario dead-green-upgrade --seed 3 \
    --incidents-out "$inc_a" >/dev/null
timeout -k 10 300 env JAX_PLATFORMS=cpu python -m kuberay_tpu.sim \
    --scenario dead-green-upgrade --seed 3 \
    --incidents-out "$inc_b" >/dev/null
cmp "$inc_a" "$inc_b" || {
    echo "incident bundles not byte-identical across re-runs" >&2
    exit 1
}
timeout -k 10 60 env JAX_PLATFORMS=cpu python - "$inc_a" <<'EOF'
import json
import sys

doc = json.load(open(sys.argv[1]))
assert doc["schema"] == "tpu-incident-export/v1", doc.get("schema")
bundles = doc["incidents"]
assert bundles, "dead-green-upgrade drill produced no incident bundles"
tops = [b["suspects"][0] for b in bundles if b.get("suspects")]
assert any(t["kind"] == "backend-errors" and "serve-svc" in t["key"]
           for t in tops), (
    "no bundle's top suspect names the dead green backend's error "
    f"series: {[(t['kind'], t['key']) for t in tops]}")
print(f"incident drill ok: {len(bundles)} bundles, "
      f"tops={[(t['kind'], t['key']) for t in tops]}")
EOF
hash_on=$(timeout -k 10 300 env JAX_PLATFORMS=cpu python -m kuberay_tpu.sim \
    --scenario dead-green-upgrade --seed 3 --incidents --json \
    | timeout -k 10 30 python -c \
      'import json,sys; print(json.loads(sys.stdin.read())["journal_hash"])')
hash_off=$(timeout -k 10 300 env JAX_PLATFORMS=cpu python -m kuberay_tpu.sim \
    --scenario dead-green-upgrade --seed 3 --json \
    | timeout -k 10 30 python -c \
      'import json,sys; print(json.loads(sys.stdin.read())["journal_hash"])')
[ "$hash_on" = "$hash_off" ] || {
    echo "journal hash differs with incidents on ($hash_on) vs" \
         "off ($hash_off): the engine perturbed the timeline" >&2
    exit 1
}
echo "incident hash invariance ok: $hash_on"
# The straggler drill again WITH the step tracker mounted: the corpus
# above runs every scenario telemetry-off (where the straggler
# invariant is vacuous); this leg arms the detection checker — a slow
# host the microscope misses, mis-attributes, or detects late now
# fails the smoke.
exec timeout -k 10 600 env JAX_PLATFORMS=cpu python -m kuberay_tpu.sim \
    --scenario straggler-drill \
    --seed "${SIM_SEEDS:-0..4}" \
    --steps "${SIM_STEPS:-12}" \
    --step-telemetry
